"""Fleet observability plane (ISSUE 15): cross-process trace stitching,
fleet metrics aggregation, the SLO engine, and the per-request cost ledger.

The load-bearing claims:

- a DISAGGREGATED request (prefill replica -> page ship -> decode replica
  -> attach) and a LIVE MIGRATION each produce ONE merged Perfetto trace
  with per-process tracks — >= 95% of the client-observed wall latency
  covered, zero orphan spans, hop ordering consistent after clock-offset
  correction;
- the router's fleet_* rollups are pin-equal to the per-replica scrapes
  they fold (counters summed, histograms bucket-merged, MAX_GAUGES maxed);
- an induced fast burn fires the existing machinery within one evaluation:
  a FlightRecorder dump carrying the fleet snapshot and an autoscaler
  up-signal — with dropped_streams == 0 throughout;
- every terminated stream carries a complete cost ledger whose counters
  cross-check against the engine's stats;
- the satellites: span-ring overflow warns once and exports
  ``obs_spans_dropped``; FlightRecorder rotates its dump directory.
"""
import http.client
import json
import logging
import threading
import time
from pathlib import Path

import pytest

import jax
import jax.numpy as jnp

from zero_transformer_tpu import obs
from zero_transformer_tpu.config import model_config
from zero_transformer_tpu.inference.generate import decode_model, generate
from zero_transformer_tpu.inference.sampling import SamplingConfig
from zero_transformer_tpu.models import Transformer
from zero_transformer_tpu.obs.fleet import (
    ENGINE_LEDGER_KEYS,
    FLEET_OBS_REQUIRED_KEYS,
    LEDGER_KEYS,
    FleetAggregator,
    estimate_clock_offset,
    parse_exposition,
)
from zero_transformer_tpu.obs.slo import Objective, parse_slo_config
from zero_transformer_tpu.serving import (
    RouterServer,
    ServingEngine,
    ServingServer,
)

CACHE_LEN = 48
SAMPLING = SamplingConfig(temperature=0.9, top_k=20)


@pytest.fixture(scope="module")
def cfg():
    return model_config("test", dropout=0.0, compute_dtype="float32")


@pytest.fixture(scope="module")
def params(cfg):
    return Transformer(cfg).init(
        jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]


@pytest.fixture(scope="module")
def reference(cfg, params):
    model = decode_model(cfg, CACHE_LEN)

    def run(prompt, seed, max_new=8):
        toks = generate(
            model, params, jnp.asarray([prompt], jnp.int32), max_new,
            jax.random.PRNGKey(seed), SAMPLING,
        )
        return jax.device_get(toks)[0].tolist()

    return run


def make_engine(cfg, params, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("cache_len", CACHE_LEN)
    kw.setdefault("sampling", SAMPLING)
    kw.setdefault("prefill_chunk", 8)
    kw.setdefault("kv_layout", "paged")
    kw.setdefault("page_size", 4)
    return ServingEngine(cfg, params, **kw)


class _Tok:
    eos_token_id = None

    def encode(self, text):
        return [1 + (b % 250) for b in text.encode()]

    def decode(self, ids, **kw):
        return "".join(f"<{t}>" for t in ids)

    def convert_ids_to_tokens(self, ids):
        return [f"<{t}>" for t in ids]

    def convert_tokens_to_string(self, toks):
        return "".join(toks)


def _server(cfg, params, role, **kw):
    engine = make_engine(cfg, params, role=role, **kw)
    server = ServingServer(engine, _Tok(), port=0)
    server.start()
    return engine, server


def _sse(port, path, body, timeout=240.0):
    conn = http.client.HTTPConnection("127.0.0.1", port, timeout=timeout)
    try:
        conn.request("POST", path, json.dumps(body),
                     {"Content-Type": "application/json"})
        resp = conn.getresponse()
        if "text/event-stream" not in (resp.getheader("Content-Type") or ""):
            return resp.status, [], json.loads(resp.read() or b"{}")
        ids, done = [], None
        while True:
            line = resp.readline()
            if not line:
                break
            if not line.startswith(b"data: "):
                continue
            event = json.loads(line[6:])
            if event.get("done"):
                done = event
                break
            if "token" in event:
                ids.append(int(event["token"]))
        return resp.status, ids, done
    finally:
        conn.close()


def _wait(pred, timeout=120.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def _prompt(length, offset=0):
    return [(3 + offset + i) % 250 + 1 for i in range(length)]


def _assert_stitched(router, rid, want_processes):
    """The acceptance bar, executable: ONE merged doc, >=95% coverage,
    zero orphans, hop ordering consistent after clock correction, and the
    expected process tracks present."""
    doc = router.merged_trace(rid)
    check = doc["otherData"]["stitch"]
    assert check["coverage"] >= 0.95, check
    assert check["orphans"] == 0, check
    assert check["hops_ordered"], check
    procs = {
        e["args"]["name"]
        for e in doc["traceEvents"]
        if e.get("ph") == "M" and e.get("name") == "process_name"
    }
    for want in want_processes:
        assert any(want in p for p in procs), (want, procs)
    # the request's spans really span processes (per-process pids)
    pids = {
        e["pid"] for e in doc["traceEvents"]
        if e.get("ph") == "X" and e.get("cat") == rid
    }
    assert len(pids) >= len(want_processes), (pids, procs)
    return doc, check


# ------------------------------------------------- stitching: disagg + migrate


def test_disagg_request_produces_one_merged_trace(cfg, params, reference):
    """Prefill replica -> page ship -> decode replica -> attach: ONE merged
    Perfetto trace with router/prefill/decode tracks (satellite + tentpole
    acceptance: the trace nobody could read before)."""
    ed, sd = _server(cfg, params, "decode")
    ep, sp = _server(cfg, params, "prefill")
    router = RouterServer(
        [f"127.0.0.1:{sp.port}", f"127.0.0.1:{sd.port}"],
        probe_interval=0.05, chunk_tokens=8, stream_timeout=240.0,
        metrics_scrape_interval=0.0,
    )
    try:
        router.start()
        assert router.wait_ready(30)
        _wait(
            lambda: any(
                r.role == "prefill" for r in router.registry.routable()
            ),
            msg="role scrape",
        )
        prompt = _prompt(13)
        status, ids, done = _sse(
            router.port, "/generate",
            {"tokens": prompt, "max_new_tokens": 8, "seed": 3,
             "request_id": "disagg-trace-1"},
        )
        assert done and done.get("status") == "done", done
        assert ids == reference(prompt, seed=3, max_new=8)
        assert router.stats["disagg_dispatches"] == 1
        doc, check = _assert_stitched(
            router, "disagg-trace-1", ("router", "prefill", "decode")
        )
        # the phase split is readable: the prefill replica's tree has a
        # prefill span, the decode replica's tree decodes, hop attrs order
        # prefill (0) before attach (1)
        names = {
            (e["args"].get("hop") if e.get("args") else None, e["name"])
            for e in doc["traceEvents"]
            if e.get("ph") == "X" and e.get("cat") == "disagg-trace-1"
        }
        hops = {h for h, _ in names if h is not None}
        assert {0, 1} <= hops, names
        assert router.stats["dropped_streams"] == 0
        # the complete ledger: engine counters + fleet fields, migrations
        # == 1 (the page ship), 2 replicas crossed, zero replayed tokens
        ledger = done["ledger"]
        assert set(LEDGER_KEYS) <= set(ledger)
        assert ledger["migrations"] == 1
        assert ledger["replicas_crossed"] == 2
        assert ledger["attach_hops"] == 1
        assert ledger["resume_replayed_tokens"] == 0
        assert ledger["tokens_out"] == len(ids)
        assert ledger["prefill_chunks"] >= 1  # paid on the prefill replica
    finally:
        router.stop()
        sd.stop()
        sp.stop()


def test_migrated_stream_produces_one_merged_trace(cfg, params, reference):
    """/admin/migrate mid-stream: the merged trace covers both replicas'
    span trees plus the router's relay/attach hops — no inter-hop gap
    unaccounted past the 5% bar, zero orphans."""
    e1, s1 = _server(cfg, params, "mixed")
    e2, s2 = _server(cfg, params, "mixed")
    router = RouterServer(
        [f"127.0.0.1:{s1.port}", f"127.0.0.1:{s2.port}"],
        probe_interval=0.05, chunk_tokens=8, stream_timeout=240.0,
        metrics_scrape_interval=0.0,
    )
    try:
        router.start()
        assert router.wait_ready(30)
        prompt = _prompt(13)
        expect = reference(prompt, seed=7, max_new=24)
        got = {}

        def client():
            got["r"] = _sse(
                router.port, "/generate",
                {"tokens": prompt, "max_new_tokens": 24, "seed": 7,
                 "request_id": "mig-trace-1"},
            )

        t = threading.Thread(target=client, daemon=True)
        t.start()
        src = {}

        def find_src():
            for e, s, other in ((e1, s1, s2), (e2, s2, s1)):
                for act in e._active:
                    if (
                        act is not None
                        and act.handle.rid == "mig-trace-1"
                        and len(act.handle.tokens) >= 3
                    ):
                        src["server"], src["target"] = s, other
                        return True
            return False

        _wait(find_src, msg="stream decoding on a replica")
        conn = http.client.HTTPConnection(
            "127.0.0.1", src["server"].port, timeout=30
        )
        conn.request(
            "POST", "/admin/migrate",
            json.dumps({"request_id": "mig-trace-1",
                        "target": f"http://127.0.0.1:{src['target'].port}"}),
            {"Content-Type": "application/json"},
        )
        assert conn.getresponse().status == 202
        conn.close()
        t.join(timeout=240)
        assert not t.is_alive(), "migrated stream hung"
        _, ids, done = got["r"]
        assert done and done.get("status") == "done", done
        assert ids == expect
        assert router.stats["migration_resumes"] == 1
        assert router.stats["dropped_streams"] == 0
        _assert_stitched(router, "mig-trace-1", ("router", "mixed"))
        # the cumulative ledger crossed the migration: one page crossing,
        # both replicas, zero replay, every token accounted
        ledger = done["ledger"]
        assert ledger["migrations"] == 1
        assert ledger["replicas_crossed"] == 2
        assert ledger["resume_replayed_tokens"] == 0
        assert ledger["tokens_out"] == len(ids)
        # the per-request /admin/trace endpoint serves the same doc
        conn = http.client.HTTPConnection("127.0.0.1", router.port, timeout=30)
        conn.request("GET", "/admin/trace?request_id=mig-trace-1")
        resp = conn.getresponse()
        assert resp.status == 200
        doc = json.loads(resp.read())
        conn.close()
        assert doc["otherData"]["stitch"]["coverage"] >= 0.95
    finally:
        router.stop()
        s1.stop()
        s2.stop()


# ------------------------------------------------------- metrics aggregation


def test_fleet_rollups_pin_equal_to_per_replica_scrapes(cfg, params):
    """The aggregation semantics, pinned: per-role sums of fleet_* equal
    the per-replica scrapes they fold (counters summed, histogram
    bucket/count merged, MAX_GAUGES maxed)."""
    e1 = make_engine(cfg, params)
    e2 = make_engine(cfg, params)
    for i in range(3):
        e1.submit(_prompt(5, i), max_new_tokens=4, seed=i)
    e1.run_until_idle()
    for i in range(2):
        e2.submit(_prompt(5, 10 + i), max_new_tokens=4, seed=i)
    e2.run_until_idle()
    agg = FleetAggregator()
    agg.update("r1", "mixed", e1.prometheus_text())
    agg.update("r2", "decode", e2.prometheus_text())

    text = agg.render()
    fams = parse_exposition(text)
    # counters: per-role series sum to the engines' own stats
    samples = fams["fleet_serve_completed_total"]["samples"]
    role_sum = sum(v for labels, v in samples if "replica" not in labels)
    assert role_sum == e1.stats["completed"] + e2.stats["completed"] == 5
    per_replica = {
        labels["replica"]: v for labels, v in samples if "replica" in labels
    }
    assert per_replica == {"r1": 3.0, "r2": 2.0}
    # role labels are carried (one series per role)
    roles = {
        labels["role"] for labels, _ in samples if "replica" not in labels
    }
    assert roles == {"mixed", "decode"}
    # histograms: bucket-merged count equals the sum of observations
    hist = agg.merged_histogram("serve_ttft_seconds")
    assert hist["count"] == len(e1._h_ttft) + len(e2._h_ttft) == 5
    assert hist["buckets"][-1][1] == 5  # +Inf cumulative == count
    # MAX_GAUGES: uptime is the max, not the sum
    up = [
        v for labels, v in fams["fleet_serve_uptime_seconds"]["samples"]
        if "replica" not in labels and labels.get("role") == "mixed"
    ]
    assert up and up[0] <= max(
        e1.lifecycle.uptime_s, e2.lifecycle.uptime_s
    ) + 1.0
    # dropping a replica removes its contribution
    agg.drop("r2")
    fams2 = parse_exposition(agg.render())
    total = sum(
        v for labels, v in fams2["fleet_serve_completed_total"]["samples"]
        if "replica" not in labels
    )
    assert total == 3


def test_good_total_below_reads_cumulative_buckets():
    agg = FleetAggregator()
    text = (
        "# TYPE serve_ttft_seconds histogram\n"
        'serve_ttft_seconds_bucket{le="0.1"} 7\n'
        'serve_ttft_seconds_bucket{le="1"} 9\n'
        'serve_ttft_seconds_bucket{le="+Inf"} 10\n'
        "serve_ttft_seconds_sum 4.2\n"
        "serve_ttft_seconds_count 10\n"
    )
    agg.update("r1", "mixed", text)
    agg.update("r2", "mixed", text)
    assert agg.good_total_below("serve_ttft_seconds", 0.1) == (14.0, 20.0)
    assert agg.good_total_below("serve_ttft_seconds", 1.0) == (18.0, 20.0)
    # a threshold BETWEEN bounds rounds UP to the covering bound (the
    # histogram cannot split a bucket; rounding down would damn good
    # observations inside the straddling bucket)
    assert agg.good_total_below("serve_ttft_seconds", 0.5) == (18.0, 20.0)
    # past the top finite bound: everything in +Inf stays bad
    assert agg.good_total_below("serve_ttft_seconds", 5.0) == (18.0, 20.0)
    assert agg.good_total_below("serve_nonexistent", 0.1) is None


def test_clock_offset_estimation_prefers_tight_round_trips():
    # remote clock 100s ahead, measured through a 10ms round trip
    off, rtt, at = estimate_clock_offset(100.105, t0=0.1, t1=0.11)
    assert off == pytest.approx(100.0)
    assert rtt == pytest.approx(0.01)
    # a looser round trip does NOT displace the tight estimate...
    off2, rtt2, _ = estimate_clock_offset(
        107.0, t0=5.0, t1=6.0, prev=(off, rtt, at), now=6.0
    )
    assert (off2, rtt2) == (off, rtt)
    # ...until the tight one ages out (clock drift wins eventually)
    off3, rtt3, _ = estimate_clock_offset(
        107.5, t0=50.0, t1=51.0, prev=(off, rtt, at), now=51.0,
        max_age_s=30.0,
    )
    assert off3 == pytest.approx(107.5 - 50.5)


# ------------------------------------------------------------------ SLO engine


class _SpyScaler:
    def __init__(self):
        self.spawned = 0

    def spawn(self):
        self.spawned += 1
        return f"127.0.0.1:{9000 + self.spawned}"

    def retire(self, url):
        pass


def _ttft_text(good, bad):
    total = good + bad
    return (
        "# TYPE serve_ttft_seconds histogram\n"
        f'serve_ttft_seconds_bucket{{le="0.1"}} {good}\n'
        f'serve_ttft_seconds_bucket{{le="+Inf"}} {total}\n'
        f"serve_ttft_seconds_sum 1.0\n"
        f"serve_ttft_seconds_count {total}\n"
    )


def test_slo_fast_burn_fires_dump_and_autoscaler_up_signal(tmp_path):
    """Induced fast burn (chaos latency injection shape: TTFT samples past
    the threshold flood the aggregated histogram) -> within ONE evaluation
    the flight recorder dumps the fleet snapshot and the autoscaler gets
    an up-signal. dropped_streams stays 0 throughout."""
    t = [0.0]
    router = RouterServer(
        ["127.0.0.1:9"],
        clock=lambda: t[0],
        obs_dir=str(tmp_path),
        scaler=_SpyScaler(),
        autoscale_interval=0.0,  # loop off; ticks driven by hand
        scale_patience=1,
        max_replicas=4,
        slo=[Objective(
            name="ttft_p99", metric="ttft_p99", target=0.99,
            threshold_s=0.1, short_window_s=5.0, long_window_s=30.0,
            fast_burn=4.0,
        )],
    )
    try:
        router.start(probe=False)  # HTTP only; probes/evals driven by hand
        # a routable replica (hand-fed probe; no threads started)
        router.registry.observe_probe(
            "127.0.0.1:9", ok=True, body={"state": "ready"},
        )
        # healthy traffic: all TTFTs under the threshold
        for _ in range(6):
            t[0] += 1.0
            router.aggregator.update(
                "127.0.0.1:9", "mixed", _ttft_text(good=10 * int(t[0]), bad=0)
            )
            snap = router.evaluate_slo()
        assert snap["verdict"] == "ok"
        assert router.consume_slo_hot() is False
        # chaos latency injection: every new request blows the threshold
        good = 10 * int(t[0])
        for i in range(2):
            t[0] += 1.0
            router.aggregator.update(
                "127.0.0.1:9", "mixed",
                _ttft_text(good=good, bad=10 * (i + 1)),
            )
            snap = router.evaluate_slo()
        assert snap["verdict"] == "violated"
        assert snap["objectives"]["ttft_p99"]["state"] == "fast_burn"
        assert router.stats["slo_fast_burns"] == 1
        # the existing machinery fired: a flight dump with the fleet inside
        dumps = list((tmp_path / "flightrec").glob("*slo_fast_burn*"))
        assert dumps, "fast burn must dump the flight recorder"
        doc = json.loads(dumps[0].read_text())
        assert doc["extra"]["objective"] == "ttft_p99"
        assert "registry" in doc["extra"] and "slo" in doc["extra"]
        # ...and the autoscaler consumes the up-signal on its next tick
        router._autoscale_tick()
        assert router.scaler.spawned == 1
        assert router.consume_slo_hot() is False  # consumed, not sticky
        assert router.stats["dropped_streams"] == 0
        # /metrics carries the slo_* families
        text = router.metrics.render()
        assert 'slo_budget_remaining{objective="ttft_p99"}' in text
        assert "slo_violated 1" in text
    finally:
        router.stop()


def test_slo_zero_kind_and_config_parsing():
    objs = parse_slo_config(json.loads(
        (Path(__file__).resolve().parent.parent / "configs"
         / "slo_default.json").read_text()
    ))
    assert {o.name for o in objs} == {
        "ttft_p99", "itl_p99", "availability", "dropped_streams",
        "ttft_p99_gold", "itl_p99_gold",
    }
    assert next(o for o in objs if o.name == "dropped_streams").kind == "zero"
    # per-class objectives (PR 18) bind to one class's histogram stream
    assert next(o for o in objs if o.name == "ttft_p99_gold").qos_class == "gold"
    assert next(o for o in objs if o.name == "ttft_p99").qos_class is None
    with pytest.raises(ValueError, match="unknown keys"):
        parse_slo_config([{"name": "x", "metric": "ttft_p99", "oops": 1}])
    with pytest.raises(ValueError, match="unknown metric"):
        parse_slo_config([{"name": "x", "metric": "nope"}])


def test_slo_dropped_streams_zero_objective():
    t = [0.0]
    router = RouterServer(
        ["127.0.0.1:9"], clock=lambda: t[0],
        slo=[Objective(
            name="dropped_streams", metric="dropped_streams", kind="zero",
            target=0.999999, short_window_s=5.0, long_window_s=30.0,
            fast_burn=1.0,
        )],
    )
    try:
        router.start(probe=False)  # HTTP only; evaluations driven by hand
        for _ in range(3):
            t[0] += 1.0
            router.stats["streams"] += 5
            snap = router.evaluate_slo()
        assert snap["verdict"] == "ok"
        t[0] += 1.0
        router.stats["dropped_streams"] += 1  # the unforgivable event
        snap = router.evaluate_slo()
        assert snap["verdict"] == "violated"
        assert snap["objectives"]["dropped_streams"]["budget_remaining"] == 0.0
    finally:
        router.stop()


# ------------------------------------------------------------- cost ledger


def test_engine_ledger_cross_checks_against_stats(cfg, params):
    """Ledger counters summed over requests equal the engine's own stats —
    the ledger is an attribution of the stats, not a second opinion."""
    engine = make_engine(cfg, params)
    handles = [
        engine.submit(_prompt(9, i), max_new_tokens=6, seed=i)
        for i in range(3)
    ]
    engine.run_until_idle()
    assert all(h.status == "done" for h in handles)
    led = [h.ledger_snapshot() for h in handles]
    for snap in led:
        assert set(ENGINE_LEDGER_KEYS) <= set(snap)
        assert snap["queue_ms"] >= 0 and snap["decode_ms"] >= 0
        assert snap["pages_held_ticks"] > 0  # paged engine holds pages
        assert snap["migrations"] == 0
    assert sum(s["tokens_out"] for s in led) == engine.stats["tokens_out"]
    assert sum(s["prefill_chunks"] for s in led) == engine.stats["prefill_chunks"]
    # decode ticks: every emitted token cost at least one held tick
    for s in led:
        assert s["decode_ticks"] >= s["tokens_out"] > 0


def test_migration_export_carries_live_wall_time(cfg, params):
    """A mid-decode export ships the SOURCE hop's decode_ms (the handle is
    live, so the snapshot must account wall time to now — regression: it
    shipped decode_ms=0 and the cumulative split lost the source hop)."""
    engine = make_engine(cfg, params)
    shipped = []
    engine.page_shipper = lambda payload, target, on_done: (
        shipped.append(payload), on_done("sink")  # fail it; payload captured
    )
    handle = engine.submit(_prompt(9), max_new_tokens=16, seed=0)
    while len(handle.tokens) < 3:
        engine.step()
    assert engine.request_migration(handle.rid, "http://sink")
    engine.step()
    assert shipped, "export never reached the shipper"
    led = shipped[0]["ledger"]
    assert led["decode_ms"] > 0.0, led  # source decode time carried
    assert led["tokens_out"] >= 3


def test_speculative_ledger_attributes_drafts(cfg, params):
    engine = make_engine(
        cfg, params, draft_k=4, sampling=SamplingConfig(greedy=True),
    )
    handles = [
        engine.submit(_prompt(9, i), max_new_tokens=8, seed=i)
        for i in range(2)
    ]
    engine.run_until_idle()
    assert all(h.status == "done" for h in handles)
    drafted = sum(h.ledger["draft_tokens"] for h in handles)
    accepted = sum(h.ledger["accepted_tokens"] for h in handles)
    assert drafted == engine.stats["draft_tokens"] > 0
    assert accepted == engine.stats["accepted_tokens"]


def test_http_done_event_carries_ledger_and_tenant_rollup(cfg, params):
    """Every terminated stream carries the schema-pinned ledger; the
    router rolls it up under the tenant key."""
    from zero_transformer_tpu.serving import run_server

    engine = make_engine(cfg, params)
    server = run_server(engine, _Tok(), port=0, background=True)
    router = RouterServer(
        [f"127.0.0.1:{server.port}"], probe_interval=0.05,
        chunk_tokens=8, stream_timeout=240.0, metrics_scrape_interval=0.0,
    )
    try:
        router.start()
        assert router.wait_ready(30)
        conn = http.client.HTTPConnection("127.0.0.1", router.port, timeout=240)
        conn.request(
            "POST", "/generate",
            json.dumps({"tokens": _prompt(9), "max_new_tokens": 4,
                        "stream": False}),
            {"Content-Type": "application/json", "X-Tenant-Key": "acme"},
        )
        doc = json.loads(conn.getresponse().read())
        conn.close()
        assert doc["status"] == "done"
        missing = FLEET_OBS_REQUIRED_KEYS["ledger"] - set(doc["ledger"])
        assert not missing, sorted(missing)
        assert doc["ledger"]["tokens_out"] == len(doc["tokens"])
        assert doc["ledger"]["replicas_crossed"] == 1
        # SSE path, tenant via body field
        status, ids, done = _sse(
            router.port, "/generate",
            {"tokens": _prompt(9, 3), "max_new_tokens": 4, "tenant": "acme"},
        )
        assert done["status"] == "done"
        assert set(LEDGER_KEYS) <= set(done["ledger"])
        tenants = router.tenants.snapshot()
        assert "acme" in tenants and tenants["acme"]["requests"] == 2
        assert tenants["acme"]["tokens_out"] == doc["ledger"]["tokens_out"] + len(ids)
        # per-tenant families render on /metrics
        text = router.metrics.render()
        assert 'router_tenant_requests_total{tenant="acme"} 2' in text
    finally:
        router.stop()
        server.stop()


def test_tenant_ledger_is_bounded_lru():
    tl = obs.TenantLedger(capacity=3)
    for i in range(5):
        tl.record(f"t{i}", {"tokens_out": 1})
    snap = tl.snapshot()
    assert len(snap) == 3
    assert "t4" in snap and "t0" not in snap  # least-recent evicted
    assert tl.totals()["tokens_out"] == 3.0
    # true LRU: an ACTIVE tenant survives a key-churn flood (recording
    # refreshes recency; a one-off key is what gets evicted)
    tl = obs.TenantLedger(capacity=3)
    tl.record("prod", {"tokens_out": 10})
    for i in range(10):
        tl.record(f"oneoff{i}", {"tokens_out": 1})
        tl.record("prod", {"tokens_out": 10})
    snap = tl.snapshot()
    assert "prod" in snap
    assert snap["prod"]["tokens_out"] == 110.0  # never evicted/reset


# --------------------------------------------------------------- satellites


def test_tracer_overflow_warns_once_and_counts(caplog):
    tr = obs.Tracer(capacity=4)
    with caplog.at_level(logging.WARNING, logger="zero_transformer_tpu"):
        for i in range(10):
            tr.add("s", "t", float(i), float(i) + 0.5)
    warnings = [r for r in caplog.records if "span ring overflowed" in r.message]
    assert len(warnings) == 1, "overflow must warn exactly once"
    assert tr.dropped == 6


def test_engine_exports_obs_spans_dropped(cfg, params):
    engine = make_engine(cfg, params, trace_capacity=4)
    for i in range(3):
        engine.submit(_prompt(5, i), max_new_tokens=4, seed=i)
    engine.run_until_idle()
    text = engine.prometheus_text()
    assert "obs_spans_dropped" in text
    assert engine.tracer.dropped > 0  # 3 request trees overflow capacity 4
    assert f"obs_spans_dropped {engine.tracer.dropped}" in text


def test_flight_recorder_rotates_dumps_newest_survives(tmp_path):
    fr = obs.FlightRecorder(directory=str(tmp_path), max_dumps=3)
    fr.tick({"tick": 1})
    paths = [fr.dump(f"reason{i}") for i in range(7)]
    assert all(p is not None for p in paths)
    remaining = sorted(Path(p).name for p in paths if Path(p).exists())
    assert len(remaining) == 3
    # the NEWEST dump always survives; the oldest were deleted
    assert Path(paths[-1]).exists()
    assert not Path(paths[0]).exists()
    assert [Path(p).name for p in fr.dumps] == remaining


def test_flight_recorder_default_rotation_bound():
    fr = obs.FlightRecorder(directory=None)
    assert fr.max_dumps == 64
