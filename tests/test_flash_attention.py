"""Pallas flash attention vs the XLA reference path (interpret mode on CPU).

The reference has no kernel tier at all — its attention materializes the full
[T, T] score matrix (reference ``src/models/layers.py:159-173``); these tests
pin the blockwise kernel to that math, forward and backward.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.ops.attention import xla_attention
from zero_transformer_tpu.ops.pallas.flash import flash_attention


def _make_qkv(B, T, H, KVH, D, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), dtype)
    k = jax.random.normal(ks[1], (B, T, KVH, D), dtype)
    v = jax.random.normal(ks[2], (B, T, KVH, D), dtype)
    return q, k, v


@pytest.mark.parametrize(
    "B,T,H,KVH,D,alibi",
    [
        (2, 256, 4, 4, 64, False),
        (2, 256, 4, 4, 64, True),
        (1, 128, 8, 2, 64, False),  # GQA
        (1, 128, 6, 6, 64, True),  # non-power-of-2 heads → interpolated slopes
    ],
)
def test_forward_matches_xla(B, T, H, KVH, D, alibi):
    q, k, v = _make_qkv(B, T, H, KVH, D)
    ref = xla_attention(q, k, v, causal=True, alibi=alibi)
    out = flash_attention(q, k, v, causal=True, alibi=alibi, block=64, interpret=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_forward_non_causal():
    q, k, v = _make_qkv(1, 128, 4, 4, 64)
    ref = xla_attention(q, k, v, causal=False)
    out = flash_attention(q, k, v, causal=False, block=64, interpret=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("alibi,KVH", [(False, 4), (True, 4), (False, 2)])
def test_gradients_match_xla(alibi, KVH):
    B, T, H, D = 1, 128, 4, 64
    q, k, v = _make_qkv(B, T, H, KVH, D)
    g = jax.random.normal(jax.random.PRNGKey(9), (B, T, H, D))

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True, alibi=alibi) * g)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, alibi=alibi, block=64, interpret=True) * g
        )

    ref_grads = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    out_grads = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for name, r, o in zip("qkv", ref_grads, out_grads):
        np.testing.assert_allclose(o, r, atol=5e-5, rtol=5e-4, err_msg=f"d{name}")


def test_uneven_blocks_rejected():
    q, k, v = _make_qkv(1, 96, 4, 4, 64)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block=64, interpret=True)


def test_bf16_forward_close():
    q, k, v = _make_qkv(1, 128, 4, 4, 64, dtype=jnp.bfloat16)
    ref = xla_attention(q, k, v, causal=True, alibi=True)
    out = flash_attention(q, k, v, causal=True, alibi=True, block=64, interpret=True)
    np.testing.assert_allclose(
        out.astype(jnp.float32), ref.astype(jnp.float32), atol=2e-2, rtol=2e-2
    )


# -------------------------------------------------- serving shapes (PR 11)


def _serving_case(B, C, L, H, KVH, D, seed=0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 4)
    q = jax.random.normal(ks[0], (B, C, H, D), dtype)
    k = jax.random.normal(ks[1], (B, L, KVH, D), dtype)
    v = jax.random.normal(ks[2], (B, L, KVH, D), dtype)
    offs = jax.random.randint(ks[3], (B,), 0, L - C + 1, jnp.int32)
    seg = (jnp.arange(L)[None, :] < (offs[:, None] + C)).astype(jnp.int32)
    return q, k, v, offs, seg


@pytest.mark.parametrize("alibi,B,C,L,H,KVH,D", [
    (True, 3, 8, 48, 4, 2, 64),    # GQA + ALiBi, chunked-prefill window
    (False, 2, 16, 64, 6, 6, 64),  # MHA, non-pow2 heads, causal only
])
def test_serving_per_row_offsets_and_validity(alibi, B, C, L, H, KVH, D):
    """The engine's cache shapes: every row's query window at its OWN
    offset (vector cache index) with a kv-validity mask — the calls the
    gate used to decline, now pinned few-ulp against the XLA path."""
    from zero_transformer_tpu.ops.pallas.flash import flash_serving

    q, k, v, offs, seg = _serving_case(B, C, L, H, KVH, D)
    ref = xla_attention(q, k, v, causal=True, alibi=alibi, q_offset=offs,
                        segment_ids=seg)
    out = flash_serving(q, k, v, causal=True, alibi=alibi, q_offset=offs,
                        segment_ids=seg, interpret=True)
    np.testing.assert_allclose(out, ref, atol=3e-6, rtol=3e-6)


def test_serving_scalar_traced_offset():
    from zero_transformer_tpu.ops.pallas.flash import flash_serving

    q, k, v, _, _ = _serving_case(2, 8, 48, 4, 4, 64, seed=3)
    off = jnp.int32(5)
    seg = jnp.broadcast_to(
        (jnp.arange(48)[None, :] < off + 8).astype(jnp.int32), (2, 48)
    )
    ref = xla_attention(q, k, v, causal=True, alibi=True, q_offset=off,
                        segment_ids=seg)
    out = flash_serving(q, k, v, causal=True, alibi=True, q_offset=off,
                        segment_ids=seg, interpret=True)
    np.testing.assert_allclose(out, ref, atol=3e-6, rtol=3e-6)


def test_serving_rope_rotated_inputs():
    """RoPE rides OUTSIDE the kernel (the model rotates q/k before the
    call); the kernel must stay exact on rotated inputs at per-row
    positions — the serving RoPE-decode shape."""
    from zero_transformer_tpu.ops.pallas.flash import flash_serving
    from zero_transformer_tpu.ops.positions import apply_rope

    B, C, L, H, D = 2, 8, 48, 4, 64
    q, k, v, offs, seg = _serving_case(B, C, L, H, H, D, seed=5)
    pos_q = offs[:, None] + jnp.arange(C, dtype=jnp.int32)[None, :]
    q = apply_rope(q, pos_q, 10000.0)
    k = apply_rope(k, jnp.arange(L, dtype=jnp.int32), 10000.0)
    ref = xla_attention(q, k, v, causal=True, alibi=False, q_offset=offs,
                        segment_ids=seg)
    out = flash_serving(q, k, v, causal=True, alibi=False, q_offset=offs,
                        segment_ids=seg, interpret=True)
    np.testing.assert_allclose(out, ref, atol=3e-6, rtol=3e-6)


# ------------------------------------------------------- gate honesty (PR 11)


def test_gate_and_wrapper_signatures_match():
    """The small-fix contract: every kwarg ``supported`` inspects, the
    wrapper accepts and THREADS — the gate may never advertise a
    distinction (alibi, q_offset, segment_ids, doc_ids) it then drops."""
    import inspect

    from zero_transformer_tpu.ops import flash_attention as fa

    gate = set(inspect.signature(fa.supported).parameters) - {"q", "k", "v"}
    wrapper = set(inspect.signature(fa.flash_attention).parameters) - {
        "q", "k", "v"
    }
    assert gate == wrapper, (gate, wrapper)


def test_gate_alibi_is_threaded(monkeypatch):
    """alibi=True through the DISPATCHING wrapper must change the output
    (the pre-fix gate accepted the kwarg and the wrapper dropped no
    distinction — pin that it stays that way through the serving path
    too)."""
    from zero_transformer_tpu.ops import flash_attention as fa

    monkeypatch.setenv("ZT_PALLAS_INTERPRET", "1")
    q, k, v = _make_qkv(1, 128, 4, 4, 64)
    assert fa.supported(q, k, v, causal=True, alibi=True)
    on = fa.flash_attention(q, k, v, causal=True, alibi=True)
    off = fa.flash_attention(q, k, v, causal=True, alibi=False)
    assert not np.allclose(np.asarray(on), np.asarray(off))
    # serving path threads it too
    q2, k2, v2, offs, seg = _serving_case(2, 8, 48, 4, 4, 64)
    on = fa.flash_attention(q2, k2, v2, causal=True, alibi=True,
                            q_offset=offs, segment_ids=seg)
    off = fa.flash_attention(q2, k2, v2, causal=True, alibi=False,
                             q_offset=offs, segment_ids=seg)
    assert not np.allclose(np.asarray(on), np.asarray(off))


def test_forced_flash_decodes_without_raising(monkeypatch):
    """attention_impl='flash' must not crash the decode loop: flash-or-raise
    guards the O(T^2) training shapes, but the cache branch's T=1 fallback
    is an O(S) read that is XLA/paged by design — the model downgrades
    'flash' to 'auto' there (regression: PR 11 review finding)."""
    from zero_transformer_tpu.config import model_config
    from zero_transformer_tpu.inference.generate import decode_model, generate
    from zero_transformer_tpu.inference.sampling import SamplingConfig

    monkeypatch.setenv("ZT_PALLAS_INTERPRET", "1")
    cfg = model_config(
        "test", dropout=0.0, compute_dtype="float32", attention_impl="flash"
    )
    model = decode_model(cfg, 32)
    params = model.init(jax.random.PRNGKey(0), jnp.zeros((1, 8), jnp.int32))["params"]
    out = generate(
        model, params, jnp.asarray([[1, 5, 9, 2, 7, 3, 4, 8]], jnp.int32), 4,
        jax.random.PRNGKey(1), SamplingConfig(greedy=True),
    )
    assert out.shape == (1, 4)


def test_gate_serving_decisions(monkeypatch):
    from zero_transformer_tpu.ops import flash_attention as fa

    monkeypatch.setenv("ZT_PALLAS_INTERPRET", "1")
    q, k, v, offs, seg = _serving_case(2, 8, 48, 4, 2, 64)
    # serving shapes now accepted (traced vector offset + validity mask)
    assert fa.supported(q, k, v, causal=True, q_offset=offs, segment_ids=seg)
    # single-token decode stays declined: the paged kernel owns it
    q1 = q[:, :1]
    assert not fa.supported(q1, k, v, causal=False, q_offset=offs,
                            segment_ids=seg)
    # packed-doc masks never combine with cache shapes
    assert not fa.supported(
        q, k, v, causal=True, q_offset=offs, segment_ids=seg,
        doc_ids=jnp.zeros((2, 8), jnp.int32),
    )
    # off-TPU without interpret mode: decline everything
    monkeypatch.delenv("ZT_PALLAS_INTERPRET")
    if jax.default_backend() != "tpu":
        assert not fa.supported(q, k, v, causal=True, q_offset=offs,
                                segment_ids=seg)
