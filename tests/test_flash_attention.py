"""Pallas flash attention vs the XLA reference path (interpret mode on CPU).

The reference has no kernel tier at all — its attention materializes the full
[T, T] score matrix (reference ``src/models/layers.py:159-173``); these tests
pin the blockwise kernel to that math, forward and backward.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from zero_transformer_tpu.ops.attention import xla_attention
from zero_transformer_tpu.ops.pallas.flash import flash_attention


def _make_qkv(B, T, H, KVH, D, dtype=jnp.float32, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(ks[0], (B, T, H, D), dtype)
    k = jax.random.normal(ks[1], (B, T, KVH, D), dtype)
    v = jax.random.normal(ks[2], (B, T, KVH, D), dtype)
    return q, k, v


@pytest.mark.parametrize(
    "B,T,H,KVH,D,alibi",
    [
        (2, 256, 4, 4, 64, False),
        (2, 256, 4, 4, 64, True),
        (1, 128, 8, 2, 64, False),  # GQA
        (1, 128, 6, 6, 64, True),  # non-power-of-2 heads → interpolated slopes
    ],
)
def test_forward_matches_xla(B, T, H, KVH, D, alibi):
    q, k, v = _make_qkv(B, T, H, KVH, D)
    ref = xla_attention(q, k, v, causal=True, alibi=alibi)
    out = flash_attention(q, k, v, causal=True, alibi=alibi, block=64, interpret=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_forward_non_causal():
    q, k, v = _make_qkv(1, 128, 4, 4, 64)
    ref = xla_attention(q, k, v, causal=False)
    out = flash_attention(q, k, v, causal=False, block=64, interpret=True)
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("alibi,KVH", [(False, 4), (True, 4), (False, 2)])
def test_gradients_match_xla(alibi, KVH):
    B, T, H, D = 1, 128, 4, 64
    q, k, v = _make_qkv(B, T, H, KVH, D)
    g = jax.random.normal(jax.random.PRNGKey(9), (B, T, H, D))

    def loss_ref(q, k, v):
        return jnp.sum(xla_attention(q, k, v, causal=True, alibi=alibi) * g)

    def loss_flash(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=True, alibi=alibi, block=64, interpret=True) * g
        )

    ref_grads = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    out_grads = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    for name, r, o in zip("qkv", ref_grads, out_grads):
        np.testing.assert_allclose(o, r, atol=5e-5, rtol=5e-4, err_msg=f"d{name}")


def test_uneven_blocks_rejected():
    q, k, v = _make_qkv(1, 96, 4, 4, 64)
    with pytest.raises(ValueError):
        flash_attention(q, k, v, block=64, interpret=True)


def test_bf16_forward_close():
    q, k, v = _make_qkv(1, 128, 4, 4, 64, dtype=jnp.bfloat16)
    ref = xla_attention(q, k, v, causal=True, alibi=True)
    out = flash_attention(q, k, v, causal=True, alibi=True, block=64, interpret=True)
    np.testing.assert_allclose(
        out.astype(jnp.float32), ref.astype(jnp.float32), atol=2e-2, rtol=2e-2
    )
