"""bench.py parent-side logic: cached-artifact selection for wedged-tunnel
rounds, and the string-sanitization contract that keeps the one-line JSON
artifact parseable. No jax — these are host-side unit tests of the round
evidence chain (round-3 VERDICT weak #1: a wedged tunnel zeroed the round's
official record)."""
import importlib.util
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def _load_bench():
    spec = importlib.util.spec_from_file_location("bench", REPO / "bench.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


bench = _load_bench()


def _write(path: Path, obj: dict):
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(obj))


def test_cached_artifact_prefers_canonical(tmp_path):
    _write(tmp_path / "BENCH_measured.json", {
        "metric": "train_tokens_per_sec_per_chip_580m", "value": 30429.5,
        "unit": "tokens/s/chip", "vs_baseline": 7.077, "mfu": 0.5964,
        "measured_at_utc": "2026-07-30T05:48:00Z",
    })
    _write(tmp_path / "docs" / "bench" / "2026-07-29_old.json", {
        "metric": "train_tokens_per_sec_per_chip_580m", "value": 11111.0,
        "unit": "tokens/s/chip", "vs_baseline": 2.0,
    })
    art = bench._cached_tpu_artifact(root=str(tmp_path))
    assert art["source"] == "BENCH_measured.json"
    assert art["value"] == 30429.5
    assert art["provenance"] == "cached"
    assert art["measured_at"] == "2026-07-30T05:48:00Z"


def test_cached_artifact_never_recycles_cached_or_cpu(tmp_path):
    """A prior wedged round's own output (metric *_cached) and CPU-fallback
    artifacts must never resurface as the cached on-chip number."""
    _write(tmp_path / "BENCH_measured.json", {
        "metric": "train_tokens_per_sec_per_chip_580m_cached", "value": 1.0,
        "unit": "tokens/s/chip", "vs_baseline": 0.0,
    })
    _write(tmp_path / "docs" / "bench" / "a.json", {
        "metric": "train_tokens_per_sec_per_chip_cpu_fallback", "value": 2.0,
        "unit": "tokens/s/chip", "vs_baseline": 0.0,
    })
    assert bench._cached_tpu_artifact(root=str(tmp_path)) is None
    # a real measurement behind them is still found
    _write(tmp_path / "docs" / "bench" / "b_real.json", {
        "metric": "train_tokens_per_sec_per_chip_580m", "value": 30000.0,
        "unit": "tokens/s/chip", "vs_baseline": 7.0,
    })
    art = bench._cached_tpu_artifact(root=str(tmp_path))
    assert art is not None and art["value"] == 30000.0


def test_cached_artifact_none_when_nothing_exists(tmp_path):
    assert bench._cached_tpu_artifact(root=str(tmp_path)) is None


def test_truncate_keeps_head_and_tail():
    s = "A" * 5000 + "TAIL"
    out = bench._truncate(s, 1000)
    assert len(out) < 1200
    assert out.startswith("A") and out.endswith("TAIL")
    assert "truncated" in out


def test_sanitize_recurses_and_line_parses():
    obj = {"a": "x" * 10_000, "b": [{"c": "y" * 10_000}], "n": 3}
    out = bench._sanitize(obj)
    line = json.dumps(out)
    assert len(line) < 10_000
    assert json.loads(line)["n"] == 3


def _load_watch():
    spec = importlib.util.spec_from_file_location(
        "tpu_watch", REPO / "scripts" / "tpu_watch.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_tpu_watch_live_detection_and_promotion(tmp_path, monkeypatch):
    """The watcher promotes ONLY artifacts with a genuinely-live TPU
    scenario — cached replays and CPU fallbacks must never overwrite
    BENCH_measured.json (that file is the cached-fallback SOURCE; recycling
    a stale value into it would degrade provenance every wedged round)."""
    watch = _load_watch()
    live = {"metric": "train_tokens_per_sec_per_chip_580m", "value": 30000.0,
            "unit": "tokens/s/chip",
            "extra": {"scenarios": {"remat_on": {"ok": True, "platform": "tpu"}}}}
    assert watch.is_live_tpu(live)
    cached = {"metric": "train_tokens_per_sec_per_chip_580m_cached",
              "value": 30429.5,
              "extra": {"scenarios": {"remat_on": {"ok": False,
                                                   "backend_init_hung": True}}}}
    assert not watch.is_live_tpu(cached)
    cpu = {"metric": "train_tokens_per_sec_per_chip_cpu_fallback", "value": 2.0,
           "extra": {"scenarios": {"remat_on": {"ok": True, "platform": "cpu"}}}}
    assert not watch.is_live_tpu(cpu)

    monkeypatch.setattr(watch, "ROOT", str(tmp_path))
    watch.promote(live)
    promoted = json.loads((tmp_path / "BENCH_measured.json").read_text())
    assert promoted["value"] == 30000.0
    assert "measured_at_utc" in promoted
    # the promoted artifact must satisfy bench.py's own cached-artifact
    # acceptance rules (the whole point of promotion)
    art = bench._cached_tpu_artifact(root=str(tmp_path))
    assert art is not None and art["value"] == 30000.0


def test_baselines_table_covers_north_star():
    """The 1.3B north-star scenario must resolve a per-model baseline (a
    falls-through-to-580m default would overstate vs_baseline)."""
    assert "1_3b" in bench.BASELINES
    assert bench.BASELINES["1_3b"] <= bench.BASELINES["580m"]


# ---------------------------------------------------------------- ladder order


def _drive_ladder(monkeypatch, capsys, fake):
    """Run bench.main() (parent mode) with _run_child stubbed; returns the
    ordered child calls and the parsed one-line artifact."""
    calls = []

    def wrapper(scenario, env_extra, timeout):
        calls.append((scenario, dict(env_extra)))
        return fake(scenario, env_extra)

    monkeypatch.delenv("BENCH_CHILD", raising=False)
    monkeypatch.delenv("BENCH_SIMULATE_HUNG", raising=False)
    monkeypatch.setattr(bench, "_run_child", wrapper)
    bench.main()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    return calls, json.loads(line)


def test_ladder_micros_before_upsides_and_b2_skip(monkeypatch, capsys):
    """The 2026-07-31 live window lost the decode/flash datapoints to a
    mid-ladder re-wedge because the micros ran last. Contract now: micros
    run right after the headline scenarios and before any upside
    experiment; the batch-2 1.3B fallback is skipped once a batch-4 1.3B
    datapoint landed; a landed north star headlines over a faster 580m."""
    def fake(scenario, env):
        if scenario in ("flash", "decode", "loader"):
            return {"ok": True, "platform": "tpu"}
        m = env.get("BENCH_MODEL", "580m")
        return {"ok": True, "platform": "tpu", "model": m, "mfu": 0.5,
                "tok_s_chip": 30000.0 if m == "580m" else 9000.0}

    calls, art = _drive_ladder(monkeypatch, capsys, fake)
    order = [s for s, _ in calls]
    i_flash = order.index("flash")
    # anchor on the FIRST upside call (the third train scenario), not a
    # specific one deep in the block: micros sneaking in after one or two
    # upsides is exactly the re-wedge exposure this test pins
    i_first_upside = [i for i, s in enumerate(order) if s == "train"][2]
    assert i_flash < i_first_upside, "micros must precede ALL upside scenarios"
    # the batch-2 INSURANCE scenario (north_star_b2: batch 2, default remat
    # policy) must be skipped; the batch-2 qkv_mlp POLICY upside still runs —
    # it exists to move the landed datapoint, not to replace a missing one
    assert not any(
        e.get("BENCH_BATCH") == "2" and "BENCH_REMAT_POLICY" not in e
        for _, e in calls
    )
    assert any(
        e.get("BENCH_BATCH") == "2" and e.get("BENCH_REMAT_POLICY") == "qkv_mlp"
        for _, e in calls
    ), "the batch-2 qkv_mlp POLICY upside must not be caught by the skip"
    assert art["metric"] == "train_tokens_per_sec_per_chip_1_3b"
    assert art["value"] == 9000.0


def test_ladder_micros_at_first_mid_upside_success(monkeypatch, capsys):
    """Edge: both headline configs fail without hanging, the batch-2
    fallback lands the FIRST TPU success inside the upside block, and the
    tunnel wedges right after — the micros must already have fired (once),
    and the 1.3B fallback headlines."""
    def fake(scenario, env):
        if scenario in ("flash", "decode"):
            return {"ok": True, "platform": "tpu"}
        if scenario == "loader":
            return {"ok": True}
        m = env.get("BENCH_MODEL", "580m")
        if m == "1_3b" and env.get("BENCH_BATCH") == "2":
            return {"ok": True, "platform": "tpu", "model": m,
                    "tok_s_chip": 6000.0, "mfu": 0.4}
        if m == "1_3b":
            return {"ok": False, "error": "RESOURCE_EXHAUSTED"}
        if env.get("BENCH_REMAT_POLICY") == "dots" or env.get("BENCH_REMAT") == "0":
            return {"ok": False, "error": "hung", "backend_init_hung": True}
        return {"ok": False, "error": "RESOURCE_EXHAUSTED"}

    calls, art = _drive_ladder(monkeypatch, capsys, fake)
    order = [s for s, _ in calls]
    i_b2 = next(
        i for i, (s, e) in enumerate(calls) if e.get("BENCH_BATCH") == "2"
    )
    assert i_b2 < order.index("flash")
    assert order.count("flash") == 1
    assert art["metric"] == "train_tokens_per_sec_per_chip_1_3b"
    assert art["value"] == 6000.0


def test_ckpt_integrity_artifact_budget():
    """The committed BENCH_ckpt_integrity.json (scripts/ckpt_overhead_bench.py)
    pins the save-tick cost of checkpoint integrity manifests. On
    accelerator-measured artifacts the <5% budget is asserted directly. On
    this image's CPU container (2 shared cores, page-cache-speed storage)
    the measured ratio is an upper bound that cannot transfer — digesting is
    compute-bound and maximally penalized while the write is storage-bound
    and maximally flattered — so the CPU branch pins schema, digest-
    bandwidth sanity, a coarse regression backstop, and the <5% PROJECTION
    at deployment bandwidths (on-device digest >= 20 GB/s vs the artifact's
    own measured save time; TPU HBM reads run at hundreds of GB/s)."""
    art = json.loads((REPO / "BENCH_ckpt_integrity.json").read_text())
    for key in ("digest_ms", "save_ms", "save_block_ms", "overhead_frac",
                "digest_gbps", "state_mb", "leaves", "platform",
                "measured_at_utc"):
        assert key in art, key
    assert art["digest_ms"] > 0
    assert art["save_ms"] >= art["save_block_ms"] > 0
    assert abs(art["overhead_frac"] - art["digest_ms"] / art["save_ms"]) < 1e-3
    if art["platform"] in ("tpu", "gpu"):
        assert art["overhead_frac"] < 0.05
    else:
        assert art["digest_gbps"] > 0.2  # the digest is bandwidth-bound, not broken
        assert art["overhead_frac"] < 0.5  # regression backstop for the CPU box
        digest_s_at_20gbps = (art["state_mb"] / 1e3) / 20.0
        assert digest_s_at_20gbps / (art["save_ms"] / 1e3) < 0.05


def test_ladder_wedge_no_micro_attempts(monkeypatch, capsys):
    """A fully wedged tunnel must not burn timeouts on micro attempts (3 x
    600 s against a dead backend), and the cached replay must carry the
    _cached suffix. Hermetic: the cached-artifact lookup is pinned so the
    test never reads the real repo's BENCH_measured.json."""
    def fake(scenario, env):
        if scenario == "loader":
            return {"ok": True}
        return {"ok": False, "error": "timeout (backend init hung)",
                "backend_init_hung": True}

    monkeypatch.setattr(
        bench, "_cached_tpu_artifact",
        lambda root=None: {
            "metric": "train_tokens_per_sec_per_chip_580m", "value": 30000.0,
            "unit": "tokens/s/chip", "vs_baseline": 7.0, "mfu": 0.59,
            "source": "BENCH_measured.json", "provenance": "cached",
            "measured_at": "2026-07-31T04:15:00Z",
        },
    )
    calls, art = _drive_ladder(monkeypatch, capsys, fake)
    assert not any(s in ("flash", "decode") for s, _ in calls)
    assert art["metric"] == "train_tokens_per_sec_per_chip_580m_cached"
    assert art["value"] == 30000.0
