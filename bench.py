"""Benchmark: training throughput (tokens/sec/chip) on the reference's 580M config.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference trained its 580M model at ~4.3k tokens/sec/chip on
TPU v3-32 (derived in BASELINE.md from ``logs/580.md:34,49`` — 97k steps /
48B tokens / ~4 days / 32 chips). ``vs_baseline`` is the speedup over that
per-chip figure.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp


BASELINE_TOK_S_CHIP = 4300.0  # reference 580M on TPU v3 (BASELINE.md, derived)


def main():
    from zero_transformer_tpu.config import MeshConfig, OptimizerConfig, model_config
    from zero_transformer_tpu.models.gpt import Transformer
    from zero_transformer_tpu.parallel.mesh import make_mesh
    from zero_transformer_tpu.parallel.zero import (
        init_train_state,
        make_plan,
        make_train_step,
    )
    from zero_transformer_tpu.training.optimizer import make_optimizer

    on_accel = jax.default_backend() not in ("cpu",)
    if on_accel:
        model_name, batch_size, seq, timed_steps = "580m", 8, 1024, 10
    else:  # keep the CPU smoke path fast
        model_name, batch_size, seq, timed_steps = "test", 8, 32, 3

    cfg = model_config(model_name, dropout=0.0, remat=True)
    n_chips = jax.device_count()
    mesh = make_mesh(MeshConfig(zero_stage=1))
    model = Transformer(cfg)
    tx = make_optimizer(OptimizerConfig(warmup_steps=10, total_steps=1000))

    sample_shape = (batch_size, seq)
    plan = make_plan(model, tx, mesh, sample_shape, zero_stage=1)
    state = init_train_state(model, tx, jax.random.PRNGKey(0), mesh, sample_shape, plan)
    step = make_train_step(model, tx, mesh, plan, zero_stage=1)

    batch = jax.random.randint(
        jax.random.PRNGKey(1), (1, batch_size, seq), 0, cfg.vocab_size, jnp.int32
    )
    rng = jax.random.PRNGKey(2)

    # warmup / compile. NOTE: sync via a scalar fetch, not block_until_ready —
    # on the tunneled TPU platform in this image block_until_ready returns
    # before execution finishes; fetching an output of the step executable is
    # the reliable barrier (all steps chain through the donated state).
    state, metrics = step(state, batch, rng)
    float(metrics["loss"])

    t0 = time.perf_counter()
    for _ in range(timed_steps):
        state, metrics = step(state, batch, rng)
    float(metrics["loss"])
    dt = time.perf_counter() - t0

    tokens = batch_size * seq * timed_steps
    tok_s_chip = tokens / dt / n_chips
    print(
        json.dumps(
            {
                "metric": f"train_tokens_per_sec_per_chip_{model_name}",
                "value": round(tok_s_chip, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(tok_s_chip / BASELINE_TOK_S_CHIP, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
