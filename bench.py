"""Benchmark: training throughput (tokens/sec/chip) + MFU on the reference's
580M config, at an honest step size (>=64k tokens/step via grad accumulation).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Baseline: the reference trained its 580M model at ~4.3k tokens/sec/chip on
TPU v3-32 (derived in BASELINE.md from ``logs/580.md:34,49`` — 97k steps /
48B tokens / ~4 days / 32 chips). ``vs_baseline`` is the speedup over that
per-chip figure.

Architecture (failure-proof by construction): the parent process imports NO
jax — each measurement runs in a child subprocess with a wall-clock timeout,
so a hung TPU backend init (observed in this image: ``jax.devices()`` can
block >300s) is killed and recorded instead of taking the whole capture down
(round-1 failure mode: rc=1, no JSON). Scenario ladder:

  1. TPU, 580M, remat on    (the memory-safe configuration — runs FIRST so a
     good number always lands before risky upside experiments; round-2 ran
     the OOM-prone remat-off config first and lost the artifact)
  2. TPU, 1.3B, remat on, adafactor — THE north-star scenario
     (BASELINE.json metric is "GPT-1.3B tokens/sec/chip"); if it lands it
     becomes the headline metric/value even though the smaller 580M posts
     higher raw tok/s, with vs_baseline computed against the per-model
     baseline table below.
  3. TPU, 580M, remat with the "dots" policy (saves matmul outputs,
     recomputes only elementwise — faster bwd if it fits)
  4. TPU, 580M, remat off   (upside experiment; smaller per-step batch so it
     has a chance of fitting 16 GB v5e HBM, same 64k tokens/step via accum)
  5. TPU flash-attention microbenchmark sweep T in {1k,4k,8k,16k}
     (extra; only after a TPU success)
  6. TPU KV-cache decode throughput (extra; only after a TPU success)
  7. CPU smoke fallback     (only if every TPU scenario failed); if every TPU
     failure was a BACKEND-INIT hang (environment outage, not code), the
     latest committed measured artifact rides in extra.cached_tpu and the
     headline carries it, suffixed "_cached".

The parent always exits 0 with exactly ONE parseable JSON line; errors ride
in ``extra.errors``. Every string embedded in the output is truncated to
<=2 KB (round-2 failure mode: a multi-hundred-KB XLA OOM dump stringified
into the line made it unparseable to the driver's tail capture), and the
final line is verified with ``json.loads`` and size-capped before printing.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys

BASELINE_TOK_S_CHIP = 4300.0  # reference 580M on TPU v3 (BASELINE.md, derived)

# Per-model reference baselines (tokens/sec/chip, TPU v3-32, derived in
# BASELINE.md from the reference's training logs). The reference published no
# 1.3B throughput; its 760M-derived 4.1k/chip is an UPPER bound on what its
# stack could do at 1.3B (a ~2x larger model is strictly slower per chip at
# equal efficiency), so vs_baseline for 1_3b is a LOWER bound on the true
# speedup — conservative, never flattering.
BASELINES = {"580m": 4300.0, "760m": 4100.0, "1_3b": 4100.0}

MAX_ERR_CHARS = 2048  # hard cap on any string embedded in the output JSON
MAX_LINE_CHARS = 24_000  # hard cap on the final JSON line itself


def _truncate(s: str, limit: int = MAX_ERR_CHARS) -> str:
    """Keep the head and tail of an oversized string (XLA dumps bury the
    actual error at both ends: the message up top, the allocation table at
    the bottom)."""
    if len(s) <= limit:
        return s
    head, tail = limit * 2 // 3, limit // 3
    return s[:head] + f" ...[{len(s) - head - tail} chars truncated]... " + s[-tail:]


def _sanitize(obj):
    """Recursively truncate every string in a JSON-able structure."""
    if isinstance(obj, str):
        return _truncate(obj)
    if isinstance(obj, dict):
        return {k: _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize(v) for v in obj]
    return obj


# ----------------------------------------------------------------- children


def _force_platform():
    """Apply BENCH_PLATFORM before backend init. In this image jax is
    pre-imported at interpreter startup with platforms already baked into
    jax.config (the JAX_PLATFORMS env var is read then and ignored later), so
    env vars don't work — only jax.config.update does."""
    import jax

    plat = os.environ.get("BENCH_PLATFORM")
    if plat:
        jax.config.update("jax_platforms", plat)


def child_train() -> dict:
    """Timed fused train steps; returns the result dict (runs inside a child)."""
    import time

    import jax

    _force_platform()
    import jax.numpy as jnp

    from zero_transformer_tpu.config import MeshConfig, OptimizerConfig, model_config
    from zero_transformer_tpu.models.gpt import Transformer
    from zero_transformer_tpu.parallel.mesh import make_mesh
    from zero_transformer_tpu.parallel.zero import (
        init_train_state,
        make_plan,
        make_train_step,
    )
    from zero_transformer_tpu.training.optimizer import make_optimizer
    from zero_transformer_tpu.utils import monitoring

    model_name = os.environ.get("BENCH_MODEL", "580m")
    batch_size = int(os.environ.get("BENCH_BATCH", "8"))
    seq = int(os.environ.get("BENCH_SEQ", "1024"))
    accum = int(os.environ.get("BENCH_ACCUM", "8"))
    remat = os.environ.get("BENCH_REMAT", "0") == "1"
    remat_policy = os.environ.get("BENCH_REMAT_POLICY", "none")
    max_steps = int(os.environ.get("BENCH_STEPS", "10"))
    min_seconds = float(os.environ.get("BENCH_MIN_SECONDS", "45"))
    # "adamw" needs 12 bytes/param of optimizer+master state — too much for
    # 1.3B on one 16 GB v5e chip. "adafactor" (factored second moment) is how
    # the 1.3B north-star scenario fits; see training/optimizer.py.
    optimizer = os.environ.get("BENCH_OPT", "adamw")

    platform = jax.default_backend()
    print(f"devices_ok platform={platform} n={jax.device_count()}", file=sys.stderr)

    loss_chunk = int(os.environ.get("BENCH_LOSS_CHUNK", "0")) or None
    # attention_impl A/B (ISSUE 8 satellite): "auto" (default) dispatches to
    # the Pallas flash kernel on TPU; BENCH_ATTN_IMPL=xla pins the O(T^2)
    # path so the pair of end-to-end runs prices the kernel in context
    attn_impl = os.environ.get("BENCH_ATTN_IMPL", "auto")
    cfg = model_config(
        model_name, dropout=0.0, remat=remat, remat_policy=remat_policy,
        loss_chunk=loss_chunk, attention_impl=attn_impl,
    )
    n_chips = jax.device_count()
    zero_stage = int(os.environ.get("BENCH_ZERO_STAGE", "1"))
    # BENCH_OVERLAP=1: bucketed ZeRO comm overlap (parallel/overlap.py) —
    # per-layer gathers/scatters inside the layer scan instead of the
    # serial bracket; gradients bitwise-identical, only placement moves
    overlap = os.environ.get("BENCH_OVERLAP", "0") == "1"
    mesh = make_mesh(MeshConfig(zero_stage=zero_stage))
    model = Transformer(cfg)
    tx = make_optimizer(
        OptimizerConfig(warmup_steps=10, total_steps=1000, optimizer=optimizer)
    )

    sample_shape = (batch_size, seq)
    plan = make_plan(model, tx, mesh, sample_shape, zero_stage=zero_stage)
    state = init_train_state(model, tx, jax.random.PRNGKey(0), mesh, sample_shape, plan)
    accum_dtype = os.environ.get("BENCH_ACCUM_DTYPE", "float32")
    step = make_train_step(
        model, tx, mesh, plan, zero_stage=zero_stage,
        grad_accum_dtype=accum_dtype, overlap_comm=overlap,
    )

    batch = jax.random.randint(
        jax.random.PRNGKey(1), (accum, batch_size, seq), 0, cfg.vocab_size, jnp.int32
    )
    rng = jax.random.PRNGKey(2)

    # warmup / compile. NOTE: sync via a scalar fetch, not block_until_ready —
    # on the tunneled TPU platform in this image block_until_ready can return
    # before execution finishes; fetching an output of the step executable is
    # the reliable barrier (all steps chain through the donated state).
    t_compile = time.perf_counter()
    state, metrics = step(state, batch, rng)
    loss0 = float(metrics["loss"])
    t_compile = time.perf_counter() - t_compile
    print(f"compiled+step0 in {t_compile:.1f}s loss={loss0:.3f}", file=sys.stderr)

    # timed: run until min_seconds elapsed or max_steps, whichever first
    n_steps = 0
    t0 = time.perf_counter()
    while n_steps < max_steps:
        state, metrics = step(state, batch, rng)
        n_steps += 1
        if n_steps >= 2 and time.perf_counter() - t0 > min_seconds:
            break
    loss = float(metrics["loss"])  # sync barrier
    dt = time.perf_counter() - t0

    tokens_per_step = batch_size * seq * accum
    tok_s_chip = tokens_per_step * n_steps / dt / n_chips
    fpt = monitoring.model_flops_per_token(
        cfg.num_params, cfg.n_layers, cfg.d_model, seq
    )
    mfu_val = monitoring.mfu(tok_s_chip, fpt)
    return {
        "ok": True,
        "platform": platform,
        "model": model_name,
        "tok_s_chip": round(tok_s_chip, 1),
        "mfu": round(mfu_val, 4) if mfu_val is not None else None,
        "tokens_per_step": tokens_per_step,
        "steps_timed": n_steps,
        "step_seconds": round(dt / n_steps, 3),
        "compile_seconds": round(t_compile, 1),
        "remat": remat,
        "remat_policy": remat_policy,
        "loss_chunk": loss_chunk,
        "grad_accum_dtype": accum_dtype,
        "optimizer": optimizer,
        "attention_impl": attn_impl,
        "zero_stage": zero_stage,
        "overlap_comm": overlap,
        "n_chips": n_chips,
        "loss_finite": bool(loss == loss),
        "device_kind": jax.devices()[0].device_kind,
    }


def child_decode() -> dict:
    """KV-cache decode throughput on the flagship config: one compiled
    prefill + one compiled while_loop decode (the in-tree replacement for the
    reference's CUDA inference side-car, ``torch_compatability/GPT2.py`` /
    ``app.py``). bf16 params — decode is HBM-bandwidth-bound, so weight bytes
    are the denominator that matters."""
    import time

    import jax

    _force_platform()
    import jax.numpy as jnp

    from zero_transformer_tpu.config import model_config
    from zero_transformer_tpu.inference.generate import decode_model, generate
    from zero_transformer_tpu.inference.sampling import SamplingConfig

    model_name = os.environ.get("BENCH_MODEL", "580m")
    B = int(os.environ.get("BENCH_DECODE_BATCH", "8"))
    prompt_len = int(os.environ.get("BENCH_DECODE_PROMPT", "128"))
    new = int(os.environ.get("BENCH_DECODE_NEW", "256"))
    kv_dtype = os.environ.get("BENCH_DECODE_KV", "auto")

    platform = jax.default_backend()
    print(f"devices_ok platform={platform}", file=sys.stderr)
    # BENCH_DECODE_QUANT=int8: weight-only int8 serving path (random int8
    # init — decode throughput is weight-bandwidth-bound, values don't
    # matter). Paired with the bf16 row it measures what halving the weight
    # reads buys.
    quant = os.environ.get("BENCH_DECODE_QUANT", "none")
    cfg = model_config(
        model_name, dropout=0.0, param_dtype="bfloat16",
        compute_dtype="bfloat16", kv_cache_dtype=kv_dtype, param_quant=quant,
    )
    model = decode_model(cfg, prompt_len + new)
    prompt = jax.random.randint(
        jax.random.PRNGKey(0), (B, prompt_len), 0, cfg.vocab_size, jnp.int32
    )
    params = model.init(jax.random.PRNGKey(1), prompt[:, :8])["params"]
    # BENCH_DECODE_SAMPLING=greedy isolates the sampler's cost from the
    # forward's: top-k over the [B, 50304] f32 logits runs a TPU sort each
    # step, and the A/B against argmax says whether the decode gap to the
    # HBM-bandwidth ceiling lives in the model or in the sampler.
    # =topk_approx runs the same top-k through lax.approx_max_k (the TPU
    # partial-reduce) — the third arm that says how much of the sort cost
    # the approximate cutoff recovers.
    arm = os.environ.get("BENCH_DECODE_SAMPLING", "topk")
    if arm == "greedy":
        sampling = SamplingConfig(greedy=True)
    elif arm == "topk_approx":
        sampling = SamplingConfig(top_k=40, temperature=0.9, top_k_impl="approx")
    elif arm == "topk":
        sampling = SamplingConfig(top_k=40, temperature=0.9)
    else:  # a typo'd arm must not silently benchmark the wrong thing
        raise ValueError(f"BENCH_DECODE_SAMPLING={arm!r} (topk|topk_approx|greedy)")

    t_compile = time.perf_counter()
    out = generate(model, params, prompt, new, jax.random.PRNGKey(2), sampling)
    out.block_until_ready()
    import numpy as np  # sync barrier that survives the tunneled platform

    np.asarray(out)
    t_compile = time.perf_counter() - t_compile
    print(f"compiled+decode0 in {t_compile:.1f}s", file=sys.stderr)

    reps = int(os.environ.get("BENCH_DECODE_REPS", "3"))
    t0 = time.perf_counter()
    for i in range(reps):
        out = generate(model, params, prompt, new, jax.random.PRNGKey(3 + i), sampling)
    np.asarray(out)
    dt = (time.perf_counter() - t0) / reps

    # optional on-chip trace of one rep (view with xprof/tensorboard):
    # BENCH_DECODE_PROFILE=/path/dir — for chasing the gap between measured
    # ms/step and the weight-streaming lower bound
    prof_dir = os.environ.get("BENCH_DECODE_PROFILE")
    if prof_dir:
        with jax.profiler.trace(prof_dir):
            out = generate(model, params, prompt, new, jax.random.PRNGKey(99), sampling)
            np.asarray(out)

    result = {
        "ok": True,
        "platform": platform,
        "model": model_name,
        "decode_tok_s": round(B * new / dt, 1),
        "ms_per_token": round(dt / new * 1e3, 3),
        "batch": B,
        "prompt_len": prompt_len,
        "new_tokens": new,
        "kv_cache_dtype": kv_dtype,
        "param_quant": quant,
        "sampling": ("greedy" if sampling.greedy
                     else f"top_k={sampling.top_k}:{sampling.top_k_impl}"),
        "compile_seconds": round(t_compile, 1),
        "note": "wall time includes one prefill per rep",
    }

    # batch-1 latency path: prompt-lookup speculative vs plain greedy on a
    # self-similar prompt (the regime speculation exists for)
    spec_k = int(os.environ.get("BENCH_DECODE_SPEC", "8"))
    if spec_k > 0:
        from zero_transformer_tpu.inference.generate import (
            decode_model as build_decode_model,
            generate as gen,
        )
        from zero_transformer_tpu.inference.speculative import generate_speculative

        piece = jax.random.randint(jax.random.PRNGKey(7), (32,), 0, cfg.vocab_size, jnp.int32)
        rep_prompt = jnp.tile(piece, 4)[None, :]  # [1, 128] periodic
        # the speculative scratch needs prompt + new + K cache slots — the
        # batch model above was sized without the K slack
        model = build_decode_model(cfg, rep_prompt.shape[1] + new + spec_k)
        greedy = SamplingConfig(greedy=True)

        def timed(fn, reps=3):
            out = fn()
            np.asarray(out)
            t0 = time.perf_counter()
            for _ in range(reps):
                out = fn()
            np.asarray(out)
            return (time.perf_counter() - t0) / reps

        t_plain = timed(lambda: gen(model, params, rep_prompt, new,
                                    jax.random.PRNGKey(0), greedy))
        spec_out, stats = generate_speculative(
            model, params, rep_prompt, new, draft_len=spec_k, return_stats=True
        )
        t_spec = timed(lambda: generate_speculative(
            model, params, rep_prompt, new, draft_len=spec_k))
        result["speculative"] = {
            "draft_len": spec_k,
            "plain_tok_s": round(new / t_plain, 1),
            "spec_tok_s": round(new / t_spec, 1),
            "speedup": round(t_plain / t_spec, 2),
            "tokens_per_forward": round(stats["tokens_per_forward"], 2),
        }
    return result


def child_loader() -> dict:
    """Tar-gzip loader throughput + prefetch-overlap microbench (CPU-only;
    no jax). See ``zero_transformer_tpu.data.loader_bench``."""
    from zero_transformer_tpu.data.loader_bench import run

    out = run()
    out["ok"] = True
    return out


def child_flash() -> dict:
    """Flash-vs-XLA attention microbenchmark, fwd+bwd, swept over sequence
    lengths (the kernel exists to make 8k-32k context viable — one 1k
    datapoint says nothing about that regime). Batch shrinks as T grows to
    hold tokens (B*T) constant, the way a real long-context run would.

    Off-TPU, timed numbers would be meaningless (Pallas interpret mode runs
    the kernel as jax ops) — so the CPU branch runs the PARITY half of the
    per-op A/B instead: flash fwd+bwd and the paged decode kernel pinned
    against the XLA reference in interpret mode, with provenance labels
    that keep parity evidence and on-chip timings from being conflated."""
    import time

    import jax

    _force_platform()
    import jax.numpy as jnp

    from zero_transformer_tpu.ops.attention import xla_attention
    from zero_transformer_tpu.ops.pallas.flash import flash_attention

    print(f"devices_ok platform={jax.default_backend()}", file=sys.stderr)
    if jax.default_backend() != "tpu":
        # ONE shared parity implementation with train_step_bench's
        # interpret_parity block (zero_transformer_tpu.ops.pallas.parity):
        # the two artifacts must never assert different parity contracts
        from zero_transformer_tpu.ops.pallas.parity import (
            interpret_parity_report,
        )

        report = interpret_parity_report()
        return {
            "ok": report["ok"],
            "provenance": "interpret_mode_parity_cpu",
            "note": (
                "off-TPU: Pallas interpret-mode PARITY only — timed "
                "flash-vs-XLA numbers require the chip and are absent by "
                "design"
            ),
            "points": report["cases"],
        }
    seqs = [int(s) for s in os.environ.get("BENCH_FLASH_SEQS", "1024,4096,8192,16384").split(",")]
    H, D = 12, 128
    tokens = 8 * 1024  # B*T held constant across the sweep

    def bench(fn, q, k, v, reps=10):
        lossf = lambda q, k, v: jnp.sum(fn(q, k, v).astype(jnp.float32))
        step = jax.jit(jax.grad(lossf, argnums=(0, 1, 2)))
        out = step(q, k, v)  # compile
        float(jnp.sum(out[0].astype(jnp.float32)))
        t0 = time.perf_counter()
        for _ in range(reps):
            out = step(q, k, v)
        float(jnp.sum(out[0].astype(jnp.float32)))
        return (time.perf_counter() - t0) / reps * 1e3  # ms

    points = []
    for T in seqs:
        B = max(1, tokens // T)
        try:
            q, k, v = (
                jax.random.normal(jax.random.PRNGKey(i), (B, T, H, D), jnp.bfloat16)
                for i in range(3)
            )
            flash_ms = bench(
                lambda q, k, v: flash_attention(q, k, v, causal=True, alibi=True), q, k, v
            )
            # XLA full-matrix attention at 16k materializes B*H*T*T scores;
            # guard it separately so a flash datapoint still lands if XLA OOMs.
            try:
                xla_ms = bench(
                    lambda q, k, v: xla_attention(q, k, v, causal=True, alibi=True), q, k, v
                )
            except Exception as e:
                xla_ms = None
            # fwd+bwd attention FLOPs: ~4*B*T^2*H*D fwd, x2.5 with bwd, causal halves
            flops = 4 * B * T * T * H * D * 2.5 / 2
            points.append(
                {
                    "shape": [B, T, H, D],
                    "xla_ms": round(xla_ms, 3) if xla_ms else None,
                    "flash_ms": round(flash_ms, 3),
                    "speedup": round(xla_ms / flash_ms, 2) if xla_ms else None,
                    "flash_tflops": round(flops / (flash_ms * 1e-3) / 1e12, 1),
                }
            )
        except Exception as e:
            points.append({"shape": [B, T, H, D], "error": _truncate(f"{type(e).__name__}: {e}", 512)})
    return {"ok": any("flash_ms" in p for p in points), "points": points}


# ------------------------------------------------------------------- parent


def _cached_tpu_artifact(root: str | None = None) -> dict | None:
    """Most recent committed on-chip measurement, for the wedged-tunnel case.

    The axon TPU tunnel can hang at backend init for hours (observed rounds
    1-3); when that happens the round's official artifact must not read as
    zero when a committed measured number exists. Looks for, in order:
    ``BENCH_measured.json`` (canonical latest), newest ``docs/bench/*.json``,
    newest ``BENCH_r*_measured.json`` (legacy round files). Returns the parsed
    artifact plus provenance (source path + commit/file timestamp), or None.
    """
    import glob

    if root is None:
        root = os.path.dirname(os.path.abspath(__file__))
    candidates = [os.path.join(root, "BENCH_measured.json")]
    candidates += sorted(glob.glob(os.path.join(root, "docs", "bench", "*.json")), reverse=True)
    candidates += sorted(glob.glob(os.path.join(root, "BENCH_r*_measured.json")), reverse=True)
    for path in candidates:
        if not os.path.exists(path):
            continue
        try:
            with open(path) as f:
                art = json.load(f)
        except (OSError, json.JSONDecodeError):
            continue
        if not isinstance(art, dict) or "value" not in art:
            continue
        # never recycle a previous wedged-round output (provenance would
        # degrade silently with each hop) nor a CPU-fallback artifact (not an
        # on-chip measurement) back as the cached TPU number
        metric = str(art.get("metric", ""))
        if (metric.endswith("_cached") or "cpu_fallback" in metric
                or art.get("provenance") == "cached"):
            continue
        ts = art.get("measured_at_utc")
        if not ts:  # fall back to the commit date of the artifact file
            try:
                ts = subprocess.run(
                    ["git", "log", "-1", "--format=%cI", "--", path],
                    cwd=root, capture_output=True, text=True, timeout=15,
                ).stdout.strip() or None
            except Exception:
                ts = None
        return {
            "provenance": "cached",
            "source": os.path.relpath(path, root),
            "measured_at": ts,
            "metric": art.get("metric"),
            "value": art.get("value"),
            "unit": art.get("unit"),
            "vs_baseline": art.get("vs_baseline"),
            "mfu": art.get("mfu"),
        }
    return None


def _run_child(scenario: str, env_extra: dict, timeout: float) -> dict:
    """Run one scenario in a subprocess; parse its final JSON stdout line."""
    env = dict(os.environ)
    env["BENCH_CHILD"] = scenario
    env.update(env_extra)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__)],
            env=env,
            capture_output=True,
            text=True,
            timeout=timeout,
        )
    except subprocess.TimeoutExpired as e:
        stderr = (e.stderr or b"")
        stderr = stderr.decode(errors="replace") if isinstance(stderr, bytes) else stderr
        backend_up = "devices_ok" in stderr
        return {
            "ok": False,
            "error": f"timeout after {timeout:.0f}s "
            + ("(backend was up; run too slow)" if backend_up else "(backend init hung)"),
            "backend_init_hung": not backend_up,
        }
    except Exception as e:  # spawn failure — still record, never raise
        return {"ok": False, "error": f"spawn failed: {e!r}"}
    for line in reversed(proc.stdout.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                break
    tail = (proc.stderr or "").strip().splitlines()[-8:]
    return {
        "ok": False,
        "error": _truncate(f"rc={proc.returncode}: " + " | ".join(tail)),
    }


def main() -> None:
    scenario = os.environ.get("BENCH_CHILD")
    if scenario:  # ---- child mode: run one measurement, print its JSON
        try:
            result = {
                "flash": child_flash,
                "loader": child_loader,
                "decode": child_decode,
            }.get(scenario, child_train)()
        except Exception as e:
            # XLA OOMs stringify to hundreds of KB — truncate HERE, at the
            # source, so no oversized string ever enters the artifact path.
            result = {"ok": False, "error": _truncate(f"{type(e).__name__}: {e}")}
        print(json.dumps(_sanitize(result)), flush=True)
        return

    # ---- parent mode: scenario ladder, one final JSON line, always rc=0
    errors: list = []
    results: dict = {}
    tpu_timeout = float(os.environ.get("BENCH_TPU_TIMEOUT", "900"))

    # remat_on runs FIRST: it is the memory-safe configuration, so a good
    # number always lands before upside experiments (round-2 lesson). The
    # remat_off upside run uses half the per-step batch (same 64k tokens/step
    # via doubled accum) so its activation temporaries have a chance of
    # fitting 16 GB v5e HBM. Upside scenarios get a SHORTER timeout (except
    # long_ctx_8k, whose compile alone is known to outlast it — see the
    # scenario comment): the
    # known-good config compiles in ~2 min, so a config that can't compile
    # in `upside_timeout` isn't going to win and must not eat the driver's
    # budget (observed: the dots-policy compile can hang >30 min on the
    # tunneled compile helper).
    upside_timeout = float(os.environ.get("BENCH_UPSIDE_TIMEOUT", "420"))

    # the two scenarios the whole capture exists for: the memory-safe 580M
    # number and the BASELINE.json 1.3B north star.
    HEADLINE = (
        ("remat_on", {"BENCH_REMAT": "1"}, tpu_timeout),
        # THE north-star scenario (BASELINE.json metric: "GPT-1.3B
        # tokens/sec/chip"): 1.3B on one 16 GB v5e chip needs remat +
        # adafactor (adamw's 12 bytes/param of state would never fit) AND a
        # bfloat16 grad-accumulation buffer: the 2026-07-31 live window
        # proved (AOT-compile HBM rejection, recorded in BENCH_measured.json's
        # north_star_f32acc scenario) that
        # three param-sized f32 trees — master params, accumulator,
        # micro-grads — are 15.6 GB before activations. bf16 accumulator +
        # chunked CE + batch 4 brings the static picture to ~13 GB.
        # 64k tokens/step via accumulation, same as the 580m scenario.
        ("north_star_1_3b",
         {"BENCH_REMAT": "1", "BENCH_MODEL": "1_3b", "BENCH_OPT": "adafactor",
          "BENCH_BATCH": "4", "BENCH_ACCUM": "16", "BENCH_LOSS_CHUNK": "256",
          "BENCH_ACCUM_DTYPE": "bfloat16"}, tpu_timeout),
    )
    # upside experiments, in decreasing fit-probability order. These run
    # AFTER the flash/decode microbenches: a mid-window re-wedge must not
    # cost the high-value micro datapoints (it did on 2026-07-31, when the
    # tunnel died somewhere in the upside block).
    UPSIDE = (
        # north_star_f32acc: the same config with the default f32 accumulator
        # — marginal on paper (~15.9 GB static); if the AOT compiler accepts
        # it, full-precision accumulation becomes the headline instead.
        ("north_star_f32acc",
         {"BENCH_REMAT": "1", "BENCH_MODEL": "1_3b", "BENCH_OPT": "adafactor",
          "BENCH_BATCH": "4", "BENCH_ACCUM": "16", "BENCH_LOSS_CHUNK": "256"},
         upside_timeout),
        # north_star_b2: half the microbatch again — fallback insurance so a
        # 1.3B datapoint lands even if the batch-4 activation/temp picture
        # is tighter than the static estimate (an OOM rejection costs only
        # the AOT compile, ~3-5 min)
        ("north_star_b2",
         {"BENCH_REMAT": "1", "BENCH_MODEL": "1_3b", "BENCH_OPT": "adafactor",
          "BENCH_BATCH": "2", "BENCH_ACCUM": "32", "BENCH_LOSS_CHUNK": "256",
          "BENCH_ACCUM_DTYPE": "bfloat16"}, upside_timeout),
        # remat_qkv_mlp: the named-checkpoint middle ground — saves only
        # q/k/v + MLP pre-activations (~1.6 GB at batch 4 for 580M), which
        # skips ~85% of the re-forward matmul FLOPs the full-remat headline
        # pays. The dots policy was AOT-rejected at batch 8 AND its batch-4
        # retry is unproven, so this smaller-footprint policy is the most
        # likely to actually move the 59.7% MFU headline.
        ("remat_qkv_mlp",
         {"BENCH_REMAT": "1", "BENCH_REMAT_POLICY": "qkv_mlp",
          "BENCH_BATCH": "4", "BENCH_ACCUM": "16"}, upside_timeout),
        # the same lever pointed at the north star: 1.3B at batch 2 keeps
        # the saved-tensor set to ~1.4 GB (d2048, 24 layers) next to the
        # ~13 GB static picture — if the AOT compiler takes it, the
        # BASELINE.json metric itself moves up from 52.8% MFU
        ("north_star_qkv_mlp_b2",
         {"BENCH_REMAT": "1", "BENCH_REMAT_POLICY": "qkv_mlp",
          "BENCH_MODEL": "1_3b", "BENCH_OPT": "adafactor",
          "BENCH_BATCH": "2", "BENCH_ACCUM": "32", "BENCH_LOSS_CHUNK": "256",
          "BENCH_ACCUM_DTYPE": "bfloat16"}, upside_timeout),
        # remat_dots at HALF the per-step batch (same 64k tokens/step): the
        # dots policy saves every matmul output, trading ~33% backward FLOPs
        # (the full-remat re-forward) for ~250 MB/layer of saved activations
        # at batch 8 — the batch-8 attempt was rejected by the AOT compiler
        # on 2026-07-31; batch 4 halves the saved set to ~2.3 GB, which fits
        # next to the 580M adamw state. If it lands, the MFU ceiling moves
        # from ~60% (full remat, 8 FLOPs/param/token) toward ~75%.
        ("remat_dots",
         {"BENCH_REMAT": "1", "BENCH_REMAT_POLICY": "dots",
          "BENCH_BATCH": "4", "BENCH_ACCUM": "16"}, upside_timeout),
        # overlapped ZeRO comm (ISSUE 8): the same 580M headline config with
        # zero_stage=2 serial vs overlapped collective placement — the pair
        # prices the exposed-comm reduction end-to-end on real ICI (grads
        # bitwise-identical between the arms, only placement moves). Run as
        # a pair so neither number is orphaned by a mid-window wedge.
        ("zero2_serial",
         {"BENCH_REMAT": "1", "BENCH_ZERO_STAGE": "2"}, upside_timeout),
        ("zero2_overlap",
         {"BENCH_REMAT": "1", "BENCH_ZERO_STAGE": "2", "BENCH_OVERLAP": "1"},
         upside_timeout),
        # attention_impl A/B: same headline config pinned to the XLA O(T^2)
        # attention — the flash kernel's end-to-end value at training shapes
        # (the per-op sweep in child_flash prices it in isolation)
        ("attn_xla",
         {"BENCH_REMAT": "1", "BENCH_ATTN_IMPL": "xla"}, upside_timeout),
        ("remat_off", {"BENCH_REMAT": "0", "BENCH_BATCH": "4", "BENCH_ACCUM": "16"}, upside_timeout),
        # long-context training point: 580M at 8k tokens/row (the regime the
        # Pallas flash kernel + chunked CE exist for; same 64k tokens/step).
        # Full tpu_timeout, not the upside one: the 2026-07-31 window showed
        # the backend UP but the 8k flash fwd+bwd compile alone outlasting
        # 420s through the tunneled AOT helper — this datapoint is the
        # long-context headline, so it gets the same budget as the headline
        # scenarios rather than being dropped as a non-fit.
        ("long_ctx_8k",
         {"BENCH_REMAT": "1", "BENCH_SEQ": "8192", "BENCH_BATCH": "1",
          "BENCH_ACCUM": "8", "BENCH_LOSS_CHUNK": "1024"}, tpu_timeout),
    )

    micros = None

    def run_micros() -> dict:
        """Flash/decode microbenches — once, at the earliest point a live
        TPU is proven."""
        flash = _run_child("flash", {}, 600.0)
        if not flash.get("ok"):
            errors.append(_truncate(f"flash: {flash.get('error')}"))
        decode = _run_child("decode", {}, 600.0)
        if not decode.get("ok"):
            errors.append(_truncate(f"decode: {decode.get('error')}"))
        # int8-KV guard (ADVICE r3): the int8 cache's HBM win rests on XLA
        # fusing the dequant into the attention reads; if that fusion ever
        # regresses, int8 decode tok/s falls BELOW the auto (bf16) number
        # measured above — so the pair of datapoints is the regression alarm.
        decode_int8 = _run_child(
            "decode", {"BENCH_DECODE_KV": "int8", "BENCH_DECODE_SPEC": "0"}, 600.0
        )
        if not decode_int8.get("ok"):
            errors.append(_truncate(f"decode_int8: {decode_int8.get('error')}"))
        # the fully bandwidth-optimized decode: int8 weights AND int8 KV —
        # what `serve --quantize int8 --kv-cache-dtype int8` runs
        decode_w8 = _run_child(
            "decode",
            {"BENCH_DECODE_QUANT": "int8", "BENCH_DECODE_KV": "int8",
             "BENCH_DECODE_SPEC": "0"}, 600.0,
        )
        if not decode_w8.get("ok"):
            errors.append(_truncate(f"decode_w8: {decode_w8.get('error')}"))
        return {
            "flash": flash, "decode": decode, "decode_int8": decode_int8,
            "decode_w8": decode_w8,
        }

    def run_block(scenarios, micros_at_first_tpu_ok=False) -> bool:
        """Run train scenarios in order; False = stop the ladder (tunnel
        hung, or a child landed on CPU — no TPU exists here). With
        ``micros_at_first_tpu_ok`` the microbenches fire the moment a
        scenario proves the TPU live (the upside block's edge case: both
        headline configs failed, so the micros haven't run, and waiting for
        the block's end risks a re-wedge eating them)."""
        nonlocal micros
        for name, env_extra, timeout in scenarios:
            if name == "north_star_b2" and any(
                results.get(n, {}).get("ok")
                for n in ("north_star_1_3b", "north_star_f32acc")
            ):
                continue  # fallback not needed: a batch-4 1.3B datapoint landed
            if os.environ.get("BENCH_SIMULATE_HUNG") == "1":
                res = {"ok": False, "error": "simulated: backend init hung",
                       "backend_init_hung": True}
            else:
                res = _run_child("train", env_extra, timeout)
            results[name] = res
            if not res.get("ok"):
                errors.append(_truncate(f"{name}: {res.get('error')}"))
                if res.get("backend_init_hung"):
                    errors.append(
                        "skipping further TPU scenarios: backend init hung"
                    )
                    return False
            elif res.get("platform") == "cpu":
                # no TPU visible in this environment: one datapoint is enough
                return False
            elif micros_at_first_tpu_ok and micros is None:
                micros = run_micros()
        return True

    def any_tpu_ok() -> bool:
        return any(
            r.get("ok") and r.get("platform") == "tpu"
            for r in results.values()
        )

    alive = run_block(HEADLINE)
    if any_tpu_ok():
        micros = run_micros()
    if alive:
        # if the first TPU success arrives only inside this block (both
        # headline configs failed without hanging), the micros fire right
        # there — never after a block that ended in a backend hang
        run_block(UPSIDE, micros_at_first_tpu_ok=True)

    good = [r for r in results.values() if r.get("ok")]
    tpu_good = [r for r in good if r.get("platform") == "tpu"]

    if tpu_good:
        # headline preference: the best 1.3B north-star variant if any landed
        # (it is the BASELINE.json metric, even though the smaller 580m
        # config posts higher raw tok/s); otherwise the best throughput.
        # platform check matters: a wedged tunnel can silently drop a child
        # onto CPU mid-ladder, and a CPU 1.3B number must never headline
        ns_good = [
            r for name, r in results.items()
            if name.startswith("north_star") and r.get("ok")
            and r.get("platform") == "tpu"
        ]
        best = (max(ns_good, key=lambda r: r["tok_s_chip"]) if ns_good
                else max(tpu_good, key=lambda r: r["tok_s_chip"]))
        flash, decode, decode_int8 = (
            micros["flash"], micros["decode"], micros["decode_int8"]
        )
        decode_w8 = micros.get("decode_w8", {"ok": False, "error": "not run"})
        loader = _run_child("loader", {"BENCH_PLATFORM": "cpu"}, 300.0)
        if not loader.get("ok"):
            errors.append(_truncate(f"loader: {loader.get('error')}"))
        baseline = BASELINES.get(best["model"], BASELINE_TOK_S_CHIP)
        out = {
            "metric": f"train_tokens_per_sec_per_chip_{best['model']}",
            "value": best["tok_s_chip"],
            "unit": "tokens/s/chip",
            "vs_baseline": round(best["tok_s_chip"] / baseline, 3),
            "mfu": best.get("mfu"),
            "extra": {
                "scenarios": results,
                "flash_microbench": flash,
                "decode_microbench": decode,
                "decode_int8_microbench": decode_int8,
                "decode_w8_microbench": decode_w8,
                "loader_microbench": loader,
                "errors": errors,
            },
        }
    else:
        # CPU fallback: tiny model, a real number from whatever backend exists
        res = _run_child(
            "train",
            {
                "BENCH_PLATFORM": "cpu",
                "BENCH_MODEL": "test",
                "BENCH_BATCH": "8",
                "BENCH_SEQ": "32",
                "BENCH_ACCUM": "1",
                "BENCH_STEPS": "3",
                "BENCH_MIN_SECONDS": "0",
            },
            300.0,
        )
        if not res.get("ok"):
            errors.append(_truncate(f"cpu: {res.get('error')}"))
        # the loader path is host-side: measurable even with the TPU down
        loader = _run_child("loader", {"BENCH_PLATFORM": "cpu"}, 300.0)
        if not loader.get("ok"):
            errors.append(_truncate(f"loader: {loader.get('error')}"))
        out = {
            "metric": "train_tokens_per_sec_per_chip_cpu_fallback",
            "value": res.get("tok_s_chip", 0.0),
            "unit": "tokens/s/chip",
            "vs_baseline": 0.0,  # no TPU datapoint: honest zero, see errors
            "extra": {
                "scenarios": results,
                "cpu_fallback": res,
                "loader_microbench": loader,
                "errors": errors,
            },
        }
        # Wedged-tunnel mitigation: ONLY when every failed TPU scenario died
        # at BACKEND INIT (an environment outage, not a code failure) —
        # surface the latest committed on-chip measurement so the round's
        # record carries the real number, clearly labeled as cached, instead
        # of a zero. A single genuine failure (OOM, compile error) among the
        # results disables this, so a cached number can never mask a real
        # regression.
        failed = [r for r in results.values() if not r.get("ok")]
        hung = bool(failed) and all(r.get("backend_init_hung") for r in failed)
        cached = _cached_tpu_artifact() if hung else None
        if cached is not None:
            out["metric"] = str(cached.get("metric") or "train_tokens_per_sec_per_chip") + "_cached"
            out["value"] = cached["value"]
            out["unit"] = cached.get("unit") or "tokens/s/chip"
            out["vs_baseline"] = cached.get("vs_baseline") or 0.0
            if cached.get("mfu") is not None:
                out["mfu"] = cached["mfu"]
            out["extra"]["cached_tpu"] = cached

    # Artifact contract: exactly one JSON line, parseable, bounded size.
    line = json.dumps(_sanitize(out))
    if len(line) > MAX_LINE_CHARS:  # drop detail until it fits
        out["extra"] = {"errors": [_truncate(e, 512) for e in errors[:8]],
                        "detail_dropped": "output exceeded size cap"}
        line = json.dumps(_sanitize(out))
    json.loads(line)  # hard assert: never print an unparseable artifact
    print(line, flush=True)


if __name__ == "__main__":
    main()
