"""Chaos injection: deterministic faults to prove the resilience layer.

A fault-tolerance subsystem that has only ever seen healthy runs is a guess.
``ChaosMonkey`` injects the failure modes a preemptible pod run actually
hits — divergent steps, loader IO errors, SIGTERM preemption, failing or
slow checkpoint writes, hung steps — at exact step boundaries, so
``tests/test_resilience.py`` can assert end-state parity between a faulted
supervised run and an undisturbed one.

Injection points mirror where real faults enter:

- ``wrap_train_step``: an IN-GRAPH poison — at ``state.step`` inside the
  fault window, loss/grad-norm/params all go NaN, exactly what a divergent
  update looks like from outside the step. Traced into the jitted step, so
  the anomaly guard sees it through the same metrics path as a real NaN
  (a host-side monkeypatch would bypass the compiled guard entirely).
- ``wrap_loader`` / ``wrap_checkpoint``: proxy objects raising (or delaying)
  at a chosen batch/step — storage faults at the exact API surface the
  trainer calls.
- ``on_step``: host-side faults the trainer invokes once per completed step
  (SIGTERM to this process; an interruptible busy-hang for the watchdog).

One-shot semantics are host-side: a ``Fault`` records having fired and stays
fired across supervisor restarts when the same monkey is reused — so "fault
once, recover, complete" is expressible. The in-graph poison is windowed on
``state.step`` instead (it cannot observe host state from inside the trace);
rollback never replays it because rollback keeps the step counter moving
forward (see docs/RESILIENCE.md).
"""
from __future__ import annotations

import dataclasses
import logging
import os
import signal
import time
from typing import Callable, List

import jax
import jax.numpy as jnp

log = logging.getLogger("zero_transformer_tpu")


@dataclasses.dataclass
class Fault:
    """One injectable fault.

    kind: "nan_step" | "loader_error" | "sigterm" | "ckpt_fail" |
          "ckpt_slow" | "ckpt_truncate" | "ckpt_bitflip" | "hang" |
          "replica_perturb" | "sigkill" | "sigstop" | "hb_blackhole" |
          "slow_worker"

    The last four are PROCESS-level faults for the training fleet
    (training/fleet.py): "sigkill" is unmaskable death (no handler, no
    force-save — the coordinator must notice via missed heartbeats);
    "sigstop" freezes the process without killing it (the
    indistinguishable-from-hung case: heartbeats stop but the PID lives);
    "hb_blackhole" drops outgoing heartbeats for ``duration`` seconds while
    the worker keeps computing (a partitioned-but-alive worker — the
    coordinator declares it dead and it must re-register); "slow_worker"
    delays every shard compute by ``duration`` seconds from ``step`` on
    (persistent straggler, for the obs-plane detection path).
    step: step at which to fire. For "nan_step" this is matched against the
      in-graph ``state.step`` (0-based step being computed); for host faults
      it is the 1-based count of completed steps; for "loader_error" the
      batch index (0-based) whose fetch raises; for "ckpt_fail"/"ckpt_slow"
      the first save call with ``step >= fault.step`` fires; for
      "ckpt_truncate"/"ckpt_bitflip" the first save that actually WRITES at
      ``step >= fault.step`` has its just-committed step dir corrupted (a
      torn write / storage bit rot, after the fact); for "replica_perturb"
      the first completed step ``>= fault.step`` desyncs one device's copy
      of a replicated param leaf (silent data corruption on one replica).
    duration: consecutive steps poisoned ("nan_step") or seconds
      ("ckpt_slow"/"hang" cap).
    exc: exception type for "loader_error"/"ckpt_fail".
    message: exception text.
    """

    kind: str
    step: int
    duration: float = 1
    exc: type = OSError
    message: str = "chaos: injected fault"
    fired: bool = False


class _ChaosLoader:
    """DataLoader proxy that raises ``fault.exc`` before yielding batch N."""

    def __init__(self, inner, fault: Fault, monkey: "ChaosMonkey"):
        self._inner = inner
        self._fault = fault
        self._monkey = monkey

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def __iter__(self):
        for i, batch in enumerate(iter(self._inner)):
            f = self._fault
            if not f.fired and i >= f.step:
                self._monkey.record(f)
                raise f.exc(f"{f.message} (loader batch {i})")
            yield batch


def corrupt_step_dir(step_dir, kind: str) -> List[str]:
    """Storage-level corruption of a COMMITTED step directory.

    ``ckpt_truncate``: halve every file under the ``state`` item — a torn
    write / partial upload (restore raises mid-read). ``ckpt_bitflip``: flip
    one bit every 64 bytes across the back half of EVERY ocdbt data blob
    (``d/`` dirs) — every copy, because ocdbt stores small arrays
    redundantly and a flip in only the unread duplicate is absorbed; a
    single flip can also land in dead padding and legitimately change
    nothing, hence the sparse burst. Where the burst hits array bytes the
    restore comes back *silently wrong* (the case only the digest manifest
    catches); where it hits ocdbt framing the read raises. Both routes land
    in ``restore_verified``'s quarantine path. Returns the files touched."""
    from pathlib import Path

    state_dir = Path(str(step_dir)) / "state"
    files = sorted(
        (f for f in state_dir.rglob("*") if f.is_file()),
        key=lambda f: -f.stat().st_size,
    )
    touched = []
    if kind == "ckpt_truncate":
        for f in files:
            data = f.read_bytes()
            f.write_bytes(data[: len(data) // 2])
            touched.append(str(f))
    else:  # ckpt_bitflip
        for f in files:
            if f.parent.name != "d":
                continue  # only data blobs: keep the corruption "silent"
            data = bytearray(f.read_bytes())
            for off in range(len(data) // 2, len(data), 64):
                data[off] ^= 0x01
            f.write_bytes(bytes(data))
            touched.append(str(f))
    return touched


class _ChaosCheckpoint:
    """CheckpointManager proxy: failing, slow, or corrupting ``save`` at a
    chosen step."""

    def __init__(self, inner, faults: List[Fault], monkey: "ChaosMonkey"):
        self._inner = inner
        self._faults = faults
        self._monkey = monkey

    def __getattr__(self, name):
        return getattr(self._inner, name)

    def save(self, step: int, state, meta=None, force: bool = False):
        for f in self._faults:
            if f.fired or step < f.step or f.kind in ("ckpt_truncate",
                                                      "ckpt_bitflip"):
                continue
            self._monkey.record(f)
            if f.kind == "ckpt_fail":
                raise f.exc(f"{f.message} (checkpoint save at step {step})")
            log.warning("chaos: delaying checkpoint save %.1fs", f.duration)
            time.sleep(f.duration)
        saved = self._inner.save(step, state, meta=meta, force=force)
        if saved:
            for f in self._faults:
                if f.fired or step < f.step or f.kind not in (
                    "ckpt_truncate", "ckpt_bitflip"
                ):
                    continue
                # corrupt AFTER the commit: the fault models storage rot /
                # a torn write on an already-"successful" checkpoint
                self._inner.wait()
                self._monkey.record(f)
                touched = corrupt_step_dir(self._inner.step_path(step), f.kind)
                log.warning(
                    "chaos: %s corrupted step %d (%d file(s))",
                    f.kind, step, len(touched),
                )
        return saved


class ChaosMonkey:
    """Holds the fault plan and wires it into a Trainer's seams.

    Reuse ONE monkey across supervisor restarts (pass it to every Trainer the
    factory builds): fired faults stay fired, which is what lets a
    fault-recover-complete scenario terminate.
    """

    def __init__(self, faults: List[Fault]):
        self.faults = list(faults)
        self.fired_log: List[str] = []

    def record(self, fault: Fault) -> None:
        fault.fired = True
        entry = f"{fault.kind}@{fault.step}"
        self.fired_log.append(entry)
        log.warning("chaos: fired %s", entry)

    def _of_kind(self, *kinds: str) -> List[Fault]:
        return [f for f in self.faults if f.kind in kinds]

    # -- trainer seams ------------------------------------------------------

    def wrap_train_step(self, step_fn: Callable) -> Callable:
        """In-graph NaN poison over ``state.step`` ∈ [step, step+duration)."""
        windows = self._of_kind("nan_step")
        if not windows:
            return step_fn

        def poisoned(state, batch, rng):
            new_state, metrics = step_fn(state, batch, rng)
            s = state.step
            inside = jnp.zeros((), jnp.bool_)
            for f in windows:
                inside |= (s >= f.step) & (s < f.step + int(f.duration))
            nanify = jnp.where(inside, jnp.float32(jnp.nan), jnp.float32(0.0))
            metrics = dict(metrics)
            metrics["loss"] = metrics["loss"] + nanify
            metrics["grad_norm"] = metrics["grad_norm"] + nanify
            # the update itself diverges too: without the guard these NaNs
            # would land in params exactly like a real blow-up
            new_params = jax.tree.map(
                lambda p: p + nanify.astype(p.dtype), new_state.params
            )
            from zero_transformer_tpu.parallel.zero import TrainState

            return (
                TrainState(
                    step=new_state.step,
                    params=new_params,
                    opt_state=new_state.opt_state,
                ),
                metrics,
            )

        return poisoned

    def wrap_loader(self, loader):
        faults = self._of_kind("loader_error")
        if not faults:
            return loader
        if len(faults) > 1:
            raise ValueError("one loader_error fault at a time")
        return _ChaosLoader(loader, faults[0], self)

    def wrap_checkpoint(self, ckpt):
        faults = self._of_kind(
            "ckpt_fail", "ckpt_slow", "ckpt_truncate", "ckpt_bitflip"
        )
        if not faults:
            return ckpt
        return _ChaosCheckpoint(ckpt, faults, self)

    def perturb_state(self, step: int, state):
        """``replica_perturb``: desync ONE device's copy of a replicated
        param leaf — bit-level silent data corruption on one DP replica.
        Called by the trainer after each completed step; returns the state
        unchanged unless a pending fault fires."""
        for f in self._of_kind("replica_perturb"):
            if f.fired or step < f.step:
                continue
            self.record(f)
            state = perturb_one_replica(state)
        return state

    def on_step(self, step: int) -> None:
        """Host-side faults, called by the trainer (and the fleet worker)
        after each completed step."""
        for f in self._of_kind("sigterm", "hang", "sigkill", "sigstop"):
            if f.fired or step < f.step:
                continue
            self.record(f)
            if f.kind == "sigterm":
                os.kill(os.getpid(), signal.SIGTERM)
            elif f.kind == "sigkill":
                # unmaskable: no force-save, no atexit — the process is
                # simply gone, which is exactly what the fleet's
                # missed-heartbeat path must absorb
                os.kill(os.getpid(), signal.SIGKILL)
            elif f.kind == "sigstop":
                # frozen, not dead: the PID persists but nothing runs until
                # an external SIGCONT/SIGKILL (the test harness owns that)
                os.kill(os.getpid(), signal.SIGSTOP)
            else:
                # interruptible busy-hang: short sleeps keep bytecode
                # boundaries frequent so the watchdog's interrupt_main can
                # land; the cap keeps a broken watchdog from deadlocking CI
                deadline = time.monotonic() + float(f.duration)
                while time.monotonic() < deadline:
                    time.sleep(0.01)
                log.error(
                    "chaos: hang cap %.0fs elapsed without watchdog abort",
                    float(f.duration),
                )

    # -- fleet-worker seams -------------------------------------------------

    def compute_delay(self, step: int) -> float:
        """Per-shard compute delay in seconds ("slow_worker"): persistent
        from ``fault.step`` on — a straggler is a condition, not an event,
        so firing once does NOT clear it."""
        delay = 0.0
        for f in self._of_kind("slow_worker"):
            if step < f.step:
                continue
            if not f.fired:
                self.record(f)
            delay += float(f.duration)
        return delay

    def drop_heartbeat(self, step: int) -> bool:
        """True while an "hb_blackhole" fault wants outgoing heartbeats
        dropped: from the first step >= ``fault.step``, for ``duration``
        seconds of wall time. The worker stays alive and computing — only
        its health signal is partitioned away."""
        for f in self._of_kind("hb_blackhole"):
            if step < f.step:
                continue
            if not f.fired:
                self.record(f)
                f.until = time.monotonic() + float(f.duration)
            if time.monotonic() < getattr(f, "until", 0.0):
                return True
        return False


def perturb_one_replica(state):
    """Flip one element of ONE device's physical copy of the first
    replicated, multi-device param leaf (everything else — and every other
    device's copy — is byte-identical). This is what SDC on a single
    host/device does to a "replicated" array: XLA assumes the copies are
    identical, so nothing notices until the cross-replica audit compares
    them (or the loss curves fork). Rebuilds the leaf with
    ``jax.make_array_from_single_device_arrays`` and routes the result
    through ``ensure_donatable`` (the per-device ``device_put`` buffers may
    be zero-copy host views, and the train step donates this state)."""
    import numpy as np

    from zero_transformer_tpu.parallel.zero import TrainState
    from zero_transformer_tpu.utils.jax_compat import ensure_donatable

    leaves, treedef = jax.tree_util.tree_flatten(state.params)
    target = None
    for idx, leaf in enumerate(leaves):
        if (
            getattr(leaf, "sharding", None) is not None
            and leaf.sharding.is_fully_replicated
            and len(leaf.sharding.device_set) > 1
            and leaf.size > 0
        ):
            target = idx
            break
    if target is None:
        raise ValueError(
            "replica_perturb: no replicated multi-device param leaf to "
            "desync (single-device mesh, or fully sharded params)"
        )
    leaf = leaves[target]
    bufs = []
    for i, shard in enumerate(leaf.addressable_shards):
        arr = np.array(shard.data, copy=True)
        if i == 0:
            flat = arr.reshape(-1)
            flat[0] = flat[0] + np.asarray(1.0, arr.dtype)
        bufs.append(jax.device_put(arr, shard.device))
    leaves[target] = jax.make_array_from_single_device_arrays(
        leaf.shape, leaf.sharding, bufs
    )
    perturbed = jax.tree_util.tree_unflatten(treedef, leaves)
    return ensure_donatable(
        TrainState(step=state.step, params=perturbed, opt_state=state.opt_state)
    )
