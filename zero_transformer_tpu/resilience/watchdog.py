"""Hang watchdog: a heartbeat deadline on the train loop.

A wedged collective, a deadlocked host thread, or a storage stall presents as
the same symptom — the loop stops completing steps — and on a pod it burns
reserved chips silently until a human notices. The watchdog turns that into
a bounded, diagnosable, *retryable* failure:

- the train loop touches ``beat()`` once per step (a monotonic-clock store,
  no locks, no device work);
- a daemon thread checks the deadline; on expiry it (1) dumps every Python
  thread's stack plus live-device-array stats to the log — the forensic
  snapshot a post-mortem needs, (2) runs the caller's ``on_hang`` hook
  (the trainer force-saves a checkpoint there, best-effort), and
  (3) aborts the main thread via ``_thread.interrupt_main()``;
- the trainer translates the resulting ``KeyboardInterrupt`` into
  ``HangError`` — a ``RetryableError`` — so ``--supervise`` restarts the run
  from the checkpoint the hook just wrote.

``interrupt_main`` only lands between Python bytecodes: it reliably breaks
host-side stalls (loader deadlock, storage retry loop, a stuck ``sleep``
loop) but cannot preempt a single blocking C call such as a wedged XLA
execute — there the stack dump still fires and an external supervisor (the
pod scheduler's own liveness probe) must kill the process. That split is
exactly the design: everything recoverable in-process is recovered
in-process, and everything else at least dies loudly with stacks on disk.
"""
from __future__ import annotations

import _thread
import logging
import sys
import threading
import time
import traceback
from typing import Callable, Optional

log = logging.getLogger("zero_transformer_tpu")


def dump_stacks(reason: str = "watchdog") -> str:
    """Format every live thread's Python stack + live-array stats, and log it."""
    lines = [f"=== {reason}: thread stacks ==="]
    names = {t.ident: t.name for t in threading.enumerate()}
    for ident, frame in sys._current_frames().items():
        lines.append(f"--- thread {names.get(ident, '?')} ({ident}) ---")
        lines.extend(l.rstrip() for l in traceback.format_stack(frame))
    try:
        import jax

        arrays = jax.live_arrays()
        total = sum(a.size * a.dtype.itemsize for a in arrays)
        lines.append(
            f"--- live device arrays: {len(arrays)}, "
            f"{total / 1e9:.3f} GB (logical) ---"
        )
    except Exception as e:  # diagnostics must never mask the hang itself
        lines.append(f"--- live-array stats unavailable: {e!r} ---")
    text = "\n".join(lines)
    log.error("%s", text)
    return text


class Watchdog:
    """Deadline thread over a heartbeat the owner touches each step."""

    def __init__(
        self,
        timeout_s: float,
        on_hang: Optional[Callable[[], None]] = None,
        poll_s: Optional[float] = None,
    ):
        if timeout_s <= 0:
            raise ValueError("watchdog timeout must be > 0 (0 disables upstream)")
        self.timeout_s = timeout_s
        self.on_hang = on_hang
        self.poll_s = poll_s if poll_s is not None else min(timeout_s / 4.0, 1.0)
        self.fired = False
        self._last_beat = time.monotonic()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        self._last_beat = time.monotonic()

    def start(self) -> "Watchdog":
        self.beat()
        self._thread = threading.Thread(
            target=self._watch, daemon=True, name="zt-watchdog"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=self.poll_s * 4 + 1.0)
            self._thread = None

    def _watch(self) -> None:
        while not self._stop.wait(self.poll_s):
            stalled = time.monotonic() - self._last_beat
            if stalled <= self.timeout_s:
                continue
            self.fired = True
            log.error(
                "watchdog: no heartbeat for %.1fs (deadline %.1fs) — "
                "dumping stacks, force-saving, aborting retryably",
                stalled,
                self.timeout_s,
            )
            dump_stacks("watchdog deadline expired")
            if self.on_hang is not None:
                # side thread with a bounded join: the hook (a checkpoint
                # force-save) may itself hang on the very storage stall that
                # triggered the watchdog, and the ABORT must never depend on
                # the hook finishing
                hook = threading.Thread(
                    target=self._run_hook, daemon=True, name="zt-watchdog-hook"
                )
                hook.start()
                hook.join(timeout=self.timeout_s)
                if hook.is_alive():
                    log.error(
                        "watchdog: on_hang hook still running after %.1fs — "
                        "aborting without it", self.timeout_s,
                    )
            # lands as KeyboardInterrupt in the main thread at the next
            # bytecode boundary; the trainer re-raises it as HangError
            _thread.interrupt_main()
            return

    def _run_hook(self) -> None:
        try:
            self.on_hang()
        except Exception:
            log.exception("watchdog on_hang hook failed (continuing abort)")
