"""In-graph anomaly guard: detect-and-drop divergent updates, no host sync.

The trainer's historical defense was ``halt_on_nan``, which inspects the loss
only on ``step % log_frequency == 0`` steps — divergence at any other step
poisoned up to ``log_frequency - 1`` further updates before detection
(the blind spot this module closes). Syncing the loss to host EVERY step
would fix that but serializes dispatch against compute and stalls the pipeline
the whole hot loop is built around.

The guard instead moves detection *into the compiled step*:

- ``bad`` = non-finite loss/grad-norm, or (optionally) a spike against a
  running EMA of either — all computed on device from metrics the step
  already produces;
- the state update is SELECTED, not applied: ``where(bad, old, new)`` over
  params and optimizer state, so a flagged update never lands. The step
  counter still advances — the batch is consumed (skipped), not retried;
- a tiny replicated carry (anomaly count, current streak, the EMAs) threads
  through the loop as device arrays. The host fetches it only at log points,
  so the non-logging path has ZERO additional device→host transfers
  (asserted under ``jax.transfer_guard`` in tests/test_resilience.py).

Escalation beyond skipping — rollback to the host-RAM snapshot, or halt — is
a host-side decision made from the carry at log points (resilience config
``anomaly_response``); see ``training/trainer.py``.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from zero_transformer_tpu.config import ResilienceConfig
from zero_transformer_tpu.parallel.zero import TrainState, _with_ambient_mesh


# one definition of "anomalous" across training and serving — the serving
# tick guard imports the jax-only leaf directly (no training-stack deps)
from zero_transformer_tpu.resilience.detect import nonfinite_rows  # noqa: F401


@dataclasses.dataclass(frozen=True)
class AnomalyStats:
    """Host-side view of the guard carry (one fetch per log point)."""

    count: int  # total flagged (dropped) steps this run
    streak: int  # consecutive flagged steps ending at the current step
    loss_ema: float
    grad_ema: float
    # cross-replica divergence audits that failed (0 when the audit is off
    # or the mesh has no ZeRO-axis redundancy); see zero.make_replica_audit
    audit_failures: int = 0


class AnomalyGuard:
    """Wraps a jitted train step with the in-graph detect-and-drop guard.

    The wrapped step has signature ``(state, batch, rng, carry) ->
    (state, metrics, carry)``; both state and carry are donated. The inner
    step may be any of the trainer's step variants (GSPMD hint, explicit
    ZeRO shard_map core, pipeline wavefront) — the guard only needs the
    ``loss``/``grad_norm`` metrics every variant already returns, and the
    select respects whatever sharding the plan dictates.
    """

    def __init__(self, cfg: ResilienceConfig, mesh, plan, batch_sharding):
        self.cfg = cfg
        self.mesh = mesh
        self.plan = plan
        self.batch_sharding = batch_sharding
        self._replicated = NamedSharding(mesh, P())
        # periodic cross-replica agreement check (None: off, or no DP
        # redundancy on this mesh) — see parallel.zero.make_replica_audit
        self._audit = None
        if cfg.audit_frequency > 0:
            from zero_transformer_tpu.parallel.zero import make_replica_audit

            self._audit = make_replica_audit(mesh, plan)
            if self._audit is None:
                import logging

                logging.getLogger("zero_transformer_tpu").warning(
                    "audit_frequency=%d requested but this mesh has no "
                    "ZeRO-axis redundancy (zero world of 1) — there are no "
                    "replicated copies to cross-check, so the replica audit "
                    "is INACTIVE", cfg.audit_frequency,
                )

    def init_carry(self) -> dict:
        zero = lambda dt: jnp.zeros((), dt)  # noqa: E731
        carry = {
            "count": zero(jnp.int32),
            "streak": zero(jnp.int32),
            "loss_ema": zero(jnp.float32),
            "grad_ema": zero(jnp.float32),
            # clean steps absorbed into the EMAs (spike checks arm at
            # spike_warmup_steps)
            "seen": zero(jnp.int32),
            # failed cross-replica agreement checks (audit_frequency > 0)
            "audit_failures": zero(jnp.int32),
        }
        from zero_transformer_tpu.utils.jax_compat import ensure_donatable

        # the guarded step DONATES the carry (wrap(): donate_argnums=(0, 3));
        # device_put output must be forced runtime-owned before the first
        # dispatch, same seam as every restore path (graftlint found this
        # one — the leaves happen to be device-born jnp.zeros today, but
        # the invariant is about the seam, not today's provenance)
        return ensure_donatable(jax.device_put(carry, self._replicated))

    def _flag(self, loss, grad_norm, carry):
        cfg = self.cfg
        loss = loss.astype(jnp.float32)
        grad_norm = grad_norm.astype(jnp.float32)
        bad = ~(jnp.isfinite(loss) & jnp.isfinite(grad_norm))
        warm = carry["seen"] >= cfg.spike_warmup_steps
        if cfg.loss_spike_factor > 0:
            bad |= warm & (loss > cfg.loss_spike_factor * carry["loss_ema"])
        if cfg.grad_spike_factor > 0:
            bad |= warm & (grad_norm > cfg.grad_spike_factor * carry["grad_ema"])
        return bad

    def _advance_carry(self, bad, loss, grad_norm, carry):
        d = self.cfg.ema_decay
        loss = loss.astype(jnp.float32)
        grad_norm = grad_norm.astype(jnp.float32)

        def ema(prev, x):
            # first clean sample seeds the EMA; flagged samples never enter it
            seeded = jnp.where(carry["seen"] == 0, x, d * prev + (1.0 - d) * x)
            return jnp.where(bad, prev, seeded)

        return {
            "count": carry["count"] + bad.astype(jnp.int32),
            "streak": jnp.where(bad, carry["streak"] + 1, 0).astype(jnp.int32),
            "loss_ema": ema(carry["loss_ema"], loss),
            "grad_ema": ema(carry["grad_ema"], grad_norm),
            "seen": carry["seen"] + (~bad).astype(jnp.int32),
            # passed through; the audit increment happens in wrap()
            "audit_failures": carry["audit_failures"],
        }

    def wrap(self, train_step: Callable) -> Callable:
        def guarded(state: TrainState, batch, rng, carry):
            new_state, metrics = train_step(state, batch, rng)
            bad = self._flag(metrics["loss"], metrics["grad_norm"], carry)
            keep = lambda old, new: jnp.where(bad, old, new)  # noqa: E731
            # the step counter always advances (the batch is consumed either
            # way); only the learned state is protected
            guarded_state = TrainState(
                step=new_state.step,
                params=jax.tree.map(keep, state.params, new_state.params),
                opt_state=jax.tree.map(keep, state.opt_state, new_state.opt_state),
            )
            metrics = dict(metrics)
            metrics["anomaly"] = bad.astype(jnp.float32)
            new_carry = self._advance_carry(
                bad, metrics["loss"], metrics["grad_norm"], carry
            )
            if self._audit is not None:
                # periodic bit-exact cross-replica agreement check on the
                # state that PERSISTS (post-select), gated in-graph so the
                # replicated-leaf read only happens on audit steps
                do = (guarded_state.step % self.cfg.audit_frequency) == 0
                diverged = jax.lax.cond(
                    do,
                    self._audit,
                    lambda s: jnp.zeros((), jnp.bool_),
                    guarded_state,
                )
                metrics["replica_diverged"] = diverged.astype(jnp.float32)
                new_carry["audit_failures"] = (
                    new_carry["audit_failures"] + diverged.astype(jnp.int32)
                )
            return guarded_state, metrics, new_carry

        rep = self._replicated
        return _with_ambient_mesh(
            jax.jit(
                guarded,
                in_shardings=(self.plan.state, self.batch_sharding, rep, rep),
                out_shardings=(self.plan.state, rep, rep),
                donate_argnums=(0, 3),
            ),
            self.mesh,
        )

    def read(self, carry) -> AnomalyStats:
        """Fetch the carry to host — call ONLY at log/check points (this is
        the device sync the per-step path deliberately avoids)."""
        host = jax.device_get(carry)
        return AnomalyStats(
            count=int(host["count"]),
            streak=int(host["streak"]),
            loss_ema=float(host["loss_ema"]),
            grad_ema=float(host["grad_ema"]),
            audit_failures=int(host.get("audit_failures", 0)),
        )


class HostSnapshot:
    """Cheap host-RAM mirror of a known-good TrainState for rollback.

    ``capture`` copies the (sharded) device state to host numpy; ``restore``
    places it back into each leaf's original sharding. No disk involved —
    rollback latency is one device_put of the state, vs a checkpoint restore
    that would also be limited to ``save_frequency`` granularity and storage
    bandwidth. The loader is deliberately NOT part of the snapshot: after a
    rollback the stream continues forward, past the offending window
    (replaying the same batches into the same state would diverge again).
    """

    def __init__(self):
        self.step: Optional[int] = None
        self._state: Optional[TrainState] = None
        self._shardings: Any = None

    @property
    def captured(self) -> bool:
        return self._state is not None

    def capture(self, state: TrainState) -> None:
        self._shardings = jax.tree.map(lambda leaf: leaf.sharding, state)
        # COPY, never view: on the CPU backend device_get can return a
        # zero-copy view of the XLA buffer, and the train step will donate
        # (and reuse) that buffer on the very next call — a viewing snapshot
        # is silently corrupted, then rollback restores garbage
        self._state = jax.tree.map(
            lambda leaf: np.array(jax.device_get(leaf), copy=True), state
        )
        self.step = int(self._state.step)

    def restore(self) -> TrainState:
        if self._state is None:
            raise RuntimeError("no snapshot captured")
        from zero_transformer_tpu.utils.jax_compat import ensure_donatable

        placed = jax.tree.map(jax.device_put, self._state, self._shardings)
        # device_put from host numpy can be ZERO-COPY (the jax array shares
        # the numpy heap buffer), and the train step DONATES its input state
        # — XLA would then recycle a buffer it does not own and corrupt the
        # host heap (observed as a glibc abort on the CPU backend); see
        # jax_compat.ensure_donatable
        return ensure_donatable(placed)
