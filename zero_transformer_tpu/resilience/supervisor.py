"""Run supervisor: bounded in-process restarts with exponential backoff.

The reference's recovery loop was a human rerunning ``python main_zero.py
--resume`` (reference ``main_zero.py:48-52``). The supervisor is that loop as
code: build a Trainer, run it, and on a *retryable* failure — loader/storage
IO, transient XLA runtime errors, watchdog hangs, preemption — resume from
the last good checkpoint after a backoff, up to a restart budget. Fatal
errors (config/shape mistakes, anomaly-policy halts) propagate immediately:
restarting cannot fix a wrong config, and retrying a deterministic divergence
just burns the budget.

Restartability leans on what the rest of the stack already guarantees:
checkpoints are atomic step directories carrying loader position, resume
fast-forwards the data stream, and the partitioned program is deterministic
(GSPMD, arXiv:2105.04663) — so a restart lands exactly where the run left
off. Each retry constructs a FRESH Trainer (fresh loader threads, fresh
orbax manager): a failed run's half-broken host state is never reused.
"""
from __future__ import annotations

import dataclasses
import logging
import random
import time
from typing import Callable, List, Optional

from zero_transformer_tpu.config import Config
from zero_transformer_tpu.resilience import AnomalyHalt, RetryableError

log = logging.getLogger("zero_transformer_tpu")

# Exception types that restarting can never fix. FileNotFoundError (an
# OSError subclass) is fatal by position in this tuple: a missing config /
# dataset / checkpoint root stays missing on retry.
_FATAL_TYPES = (
    AnomalyHalt,
    ValueError,
    TypeError,
    KeyError,
    AttributeError,
    NotImplementedError,
    FileNotFoundError,
    IsADirectoryError,
    PermissionError,
)

# Transient-failure fingerprints in foreign exception messages (XLA runtime
# status codes, storage/network strings). Matched case-insensitively against
# any exception not already classified by type.
_RETRYABLE_PATTERNS = (
    "resource_exhausted",
    "deadline_exceeded",
    "unavailable",
    "data_loss",
    "aborted",
    "cancelled",
    "connection",
    "socket",
    "timed out",
    "timeout",
    "preempt",
    "temporarily",
    "transient",
    "too many requests",
    "service unavailable",
)


def _cause_chain(exc: BaseException, limit: int = 50):
    """``exc`` followed by its ``__cause__``/``__context__`` chain,
    innermost last. Cycle-safe and depth-bounded (exception chains built by
    retry wrappers can self-reference)."""
    seen = set()
    cur: Optional[BaseException] = exc
    while cur is not None and id(cur) not in seen and limit > 0:
        yield cur
        seen.add(id(cur))
        cur = cur.__cause__ if cur.__cause__ is not None else cur.__context__
        limit -= 1


def classify(exc: BaseException) -> str:
    """``"retryable"`` | ``"fatal"`` for a train-loop exception.

    Order matters: explicit ``RetryableError`` marks win over everything
    (``HangError`` is a RuntimeError by ancestry but retryable by intent),
    then the fatal type list, then OSError (storage/loader IO) and
    message-fingerprint matching; anything unrecognized defaults to fatal —
    blindly restarting an unknown bug risks an infinite crash loop that
    *looks* like progress.

    The ``RetryableError`` mark is honored through the whole
    ``__cause__``/``__context__`` chain, not just the outermost type: a
    retryable storage fault re-raised through (or merely re-wrapped inside)
    a ``ValueError``-raising seam is still the SAME transient fault, and
    classifying it by the accidental outer wrapper would burn a restartable
    run. User interrupts stay fatal regardless of what they interrupted —
    a Ctrl-C that lands mid-retry must not be "classified away".
    """
    if isinstance(exc, (KeyboardInterrupt, SystemExit)):
        return "fatal"
    if any(isinstance(e, RetryableError) for e in _cause_chain(exc)):
        return "retryable"
    if isinstance(exc, _FATAL_TYPES):
        return "fatal"
    if isinstance(exc, (OSError, ConnectionError, TimeoutError)):
        return "retryable"
    msg = f"{type(exc).__name__}: {exc}".lower()
    if any(pat in msg for pat in _RETRYABLE_PATTERNS):
        return "retryable"
    return "fatal"


def backoff_delay(
    base_s: float,
    max_s: float,
    attempt: int,
    jitter: float = 0.0,
    rng: Callable[[], float] = random.random,
) -> float:
    """Exponential backoff with multiplicative jitter.

    ``min(base * 2^(attempt-1), max)`` spread uniformly over
    ``[1 - jitter, 1 + jitter]``: after a shared-cause failure (storage
    blip, preemption wave) N workers restart with DIFFERENT delays instead
    of thundering-herd-ing the checkpoint store at the exact same instant —
    the same reason the serving registry staggers its re-probes. Shared by
    the in-process Supervisor and the fleet coordinator's worker respawn
    path; the jitter window is pinned by tests/test_resilience.py."""
    delay = min(base_s * (2.0 ** (attempt - 1)), max_s)
    if jitter <= 0.0:
        return delay
    return delay * (1.0 + jitter * (2.0 * rng() - 1.0))


@dataclasses.dataclass
class RestartRecord:
    attempt: int
    step: Optional[int]  # last known step when the attempt ended
    reason: str
    backoff_s: float


class Supervisor:
    """Run ``Trainer.train`` under bounded restarts (``train.py --supervise``).

    Args:
      cfg: run config; ``cfg.resilience`` supplies the restart budget and
        backoff. After the first attempt, retries force ``checkpoint.resume``
        so each restart picks up from the last good checkpoint.
      trainer_factory: ``cfg -> Trainer``; defaults to the real Trainer.
        Tests inject chaos-wrapped trainers here, keeping one ChaosMonkey
        alive across restarts (a fault that fired stays fired).
      use_wandb: forwarded to the default factory.
      sleep_fn: injectable backoff sleep (tests pass a recorder).
      rng: uniform [0,1) source for backoff jitter (tests pin it).
    """

    def __init__(
        self,
        cfg: Config,
        trainer_factory: Optional[Callable[[Config], "object"]] = None,
        use_wandb: bool = False,
        sleep_fn: Callable[[float], None] = time.sleep,
        rng: Callable[[], float] = random.random,
    ):
        self.cfg = cfg
        self.res = cfg.resilience
        self.use_wandb = use_wandb
        self.sleep_fn = sleep_fn
        self.rng = rng
        self.history: List[RestartRecord] = []
        if trainer_factory is None:

            def trainer_factory(run_cfg: Config):
                from zero_transformer_tpu.training.trainer import Trainer

                return Trainer(run_cfg, use_wandb=self.use_wandb)

        self.trainer_factory = trainer_factory

    def _backoff(self, attempt: int) -> float:
        return backoff_delay(
            self.res.backoff_base_s,
            self.res.backoff_max_s,
            attempt,
            jitter=getattr(self.res, "backoff_jitter", 0.0),
            rng=self.rng,
        )

    def _resumed_cfg(self, attempt: int) -> Config:
        if attempt == 0 or self.cfg.checkpoint.resume:
            return self.cfg
        return dataclasses.replace(
            self.cfg,
            checkpoint=dataclasses.replace(self.cfg.checkpoint, resume=True),
        )

    def run(self, max_steps: Optional[int] = None):
        """Train to completion or exhaust the restart budget.

        Returns the final TrainState. A clean-but-early exit (SIGTERM
        preemption breaks the loop after a force-save) is resumed like a
        retryable failure: in-process the distinction does not matter, and
        if the platform really is about to kill the process the checkpoint
        is already on disk either way.
        """
        attempt = 0
        target: Optional[int] = None  # fixed step target once max_steps known
        while True:
            trainer = None
            step: Optional[int] = None
            try:
                # construction is inside the try: it touches storage
                # (checkpoint ensure_ready, loader opens), which fails
                # transiently on pods just like the loop does
                trainer = self.trainer_factory(self._resumed_cfg(attempt))
                run_max = max_steps
                if max_steps is not None:
                    # max_steps is a budget for the WHOLE supervised run, not
                    # per attempt: pin the absolute target on the first
                    # attempt and hand each retry only the remainder (a
                    # restart resumes from the latest checkpoint, which is
                    # where Trainer.train will restart counting from)
                    resumed_at = (
                        trainer.ckpt.latest_step() or 0
                        if self._resumed_cfg(attempt).checkpoint.resume
                        else 0
                    )
                    if target is None:
                        target = resumed_at + max_steps
                    run_max = target - resumed_at
                    if run_max <= 0:
                        # preempted exactly at the target: budget spent
                        # (0 is falsy to Trainer.train and would mean
                        # "run to total_steps")
                        log.info(
                            "supervisor: step target %d already reached", target
                        )
                        return trainer.init_state()
                state = trainer.train(max_steps=run_max)
                step = int(state.step)
                if not getattr(trainer, "preempted", False):
                    if attempt:
                        log.info(
                            "supervisor: run completed at step %d after %d "
                            "restart(s)", step, attempt,
                        )
                    return state
                reason = f"preempted at step {step}"
            except BaseException as e:
                kind = classify(e)
                if trainer is not None:
                    step = getattr(trainer, "last_step", None)
                if kind == "fatal":
                    log.error(
                        "supervisor: fatal %s at step %s — not restarting: %s",
                        type(e).__name__, step, e,
                    )
                    raise
                reason = f"{type(e).__name__}: {e}"
            finally:
                if trainer is not None:
                    try:
                        trainer.close()
                    except Exception:
                        log.exception(
                            "supervisor: trainer.close() failed (ignored)"
                        )

            attempt += 1
            if attempt > self.res.max_restarts:
                raise RetryableError(
                    f"restart budget exhausted ({self.res.max_restarts}); "
                    f"last failure: {reason}"
                )
            delay = self._backoff(attempt)
            self.history.append(
                RestartRecord(attempt=attempt, step=step, reason=reason, backoff_s=delay)
            )
            log.warning(
                "supervisor: restart %d/%d in %.1fs (%s)",
                attempt, self.res.max_restarts, delay, reason,
            )
            self.sleep_fn(delay)
