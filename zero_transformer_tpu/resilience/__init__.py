"""Fault tolerance for long preemptible pod runs.

The paper's premise is multi-day pretraining on preemptible TPU pods, yet the
reference's whole recovery story was "rerun with ``--resume``" (reference
``main_zero.py:48-52``). GSPMD-era stacks make the *partitioned program*
deterministic and restartable (GSPMD, arXiv:2105.04663); what is missing is
the host-side machinery that notices failure and restarts without a human.
This package is that machinery, in four layers (docs/RESILIENCE.md has the
full fault → detection → response → recovery matrix):

- ``anomaly``   — in-graph per-step loss/grad guard: a flagged update is
                  dropped inside the compiled step (params can never be
                  poisoned by one bad batch), with skip → rollback → halt
                  escalation handled host-side at log points only;
- ``watchdog``  — heartbeat deadline on the train loop: dump stacks,
                  force-save, abort retryably so the supervisor restarts;
- ``supervisor``— in-process bounded-restart loop with exponential backoff
                  and retryable-vs-fatal exception classification
                  (``train.py --supervise``);
- ``chaos``     — fault injection (NaN step, loader error, SIGTERM, failed
                  or slow checkpoint write, hung step) proving the above in
                  ``tests/test_resilience.py``.
"""
from __future__ import annotations


class RetryableError(RuntimeError):
    """An error worth restarting from the last good checkpoint: transient
    storage/loader/XLA failures, hangs, preemptions. The supervisor's
    classifier treats subclasses (and a pattern-matched set of foreign
    exceptions — see ``supervisor.classify``) as restart candidates."""


class HangError(RetryableError):
    """The watchdog found the train loop stalled past its deadline."""


class AnomalyHalt(RuntimeError):
    """The anomaly policy escalated to halt (non-finite loss / spike streak /
    rollback budget exhausted). Deliberately FATAL to the supervisor: a run
    that diverges identically from its last good checkpoint would loop
    restarts forever — this needs a human (lower LR, inspect data window)."""


from zero_transformer_tpu.resilience.anomaly import (  # noqa: E402,F401
    AnomalyGuard,
    HostSnapshot,
)
from zero_transformer_tpu.resilience.chaos import ChaosMonkey, Fault  # noqa: E402,F401
from zero_transformer_tpu.resilience.supervisor import (  # noqa: E402,F401
    Supervisor,
    classify,
)
from zero_transformer_tpu.resilience.watchdog import Watchdog  # noqa: E402,F401
