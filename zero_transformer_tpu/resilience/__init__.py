"""Fault tolerance for long preemptible pod runs.

The paper's premise is multi-day pretraining on preemptible TPU pods, yet the
reference's whole recovery story was "rerun with ``--resume``" (reference
``main_zero.py:48-52``). GSPMD-era stacks make the *partitioned program*
deterministic and restartable (GSPMD, arXiv:2105.04663); what is missing is
the host-side machinery that notices failure and restarts without a human.
This package is that machinery, in four layers (docs/RESILIENCE.md has the
full fault → detection → response → recovery matrix):

- ``anomaly``   — in-graph per-step loss/grad guard: a flagged update is
                  dropped inside the compiled step (params can never be
                  poisoned by one bad batch), with skip → rollback → halt
                  escalation handled host-side at log points only;
- ``watchdog``  — heartbeat deadline on the train loop: dump stacks,
                  force-save, abort retryably so the supervisor restarts;
- ``supervisor``— in-process bounded-restart loop with exponential backoff
                  and retryable-vs-fatal exception classification
                  (``train.py --supervise``);
- ``chaos``     — fault injection (NaN step, loader error, SIGTERM, failed
                  or slow checkpoint write, hung step, truncated/bit-flipped
                  checkpoint dirs, single-replica state desync) proving the
                  above in ``tests/test_resilience.py``.

The TRUSTWORTHY-RESTORE layer (integrity manifests + quarantine/fallback in
``checkpoint.py``, elastic topology validation in ``parallel.sharding``, the
cross-replica divergence audit in ``parallel.zero.make_replica_audit``)
builds on these: a corrupt step dir is quarantined at restore instead of
crash-looping the supervisor on the same artifact, and an SDC-desynced
replica trips the audit within ``audit_frequency`` steps instead of never.

The SERVING counterpart — engine lifecycle, decode-tick supervision,
graceful drain, hot weight reload, deadline-aware shedding — lives in
``zero_transformer_tpu.serving.resilience`` and reuses these primitives
(the anomaly predicate ``anomaly.nonfinite_rows``, the ``ChaosMonkey``
bookkeeping, the bounded-recovery shape of the supervisor).
"""
from __future__ import annotations


class RetryableError(RuntimeError):
    """An error worth restarting from the last good checkpoint: transient
    storage/loader/XLA failures, hangs, preemptions. The supervisor's
    classifier treats subclasses (and a pattern-matched set of foreign
    exceptions — see ``supervisor.classify``) as restart candidates."""


class HangError(RetryableError):
    """The watchdog found the train loop stalled past its deadline."""


class AnomalyHalt(RuntimeError):
    """The anomaly policy escalated to halt (non-finite loss / spike streak /
    rollback budget exhausted). Deliberately FATAL to the supervisor: a run
    that diverges identically from its last good checkpoint would loop
    restarts forever — this needs a human (lower LR, inspect data window)."""


# Lazy re-exports (PEP 562): importing the package must stay light — the
# serving process reaches through here for the jax-only ``detect``
# predicates and the chaos bookkeeping, and must not pay for (or couple
# itself to) the training stack that ``anomaly``/``supervisor`` pull in
# (optax opt-state, parallel.zero.TrainState) just to resolve the package.
_LAZY = {
    "AnomalyGuard": "anomaly",
    "HostSnapshot": "anomaly",
    "nonfinite_rows": "detect",
    "leaf_checksum": "detect",
    "ChaosMonkey": "chaos",
    "Fault": "chaos",
    "perturb_one_replica": "chaos",
    "Supervisor": "supervisor",
    "classify": "supervisor",
    "backoff_delay": "supervisor",
    "Watchdog": "watchdog",
}

__all__ = ["RetryableError", "HangError", "AnomalyHalt", *_LAZY]


def __getattr__(name: str):
    module = _LAZY.get(name)
    if module is None:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(f"{__name__}.{module}"), name)
