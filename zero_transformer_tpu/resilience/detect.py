"""Dependency-light anomaly predicates shared by training and serving.

``anomaly.py`` (the training guard) pulls in the full training stack
(optax optimizer state, ``parallel.zero.TrainState``) — far too heavy a
dependency for a pure-inference serving process that only needs the
detection CRITERION. The predicates live here, in a jax-only leaf module;
``anomaly.py`` re-exports them so training-side callers see one surface,
and ``serving/`` imports this module directly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def nonfinite_rows(x: jax.Array) -> jax.Array:
    """Per-row non-finite flag: ``[B, ...] -> [B]`` bool, True where any
    element of the row is NaN/Inf. The same criterion the training guard's
    ``AnomalyGuard._flag`` applies to loss/grad-norm, so training and
    serving judge "anomalous" by one definition. Cheap enough to run every
    serving tick: a [S, V] -> [S] reduction computed inside the fused step
    and fetched alongside the sampled tokens in the same device_get."""
    return ~jnp.isfinite(x).reshape(x.shape[0], -1).all(axis=1)


def leaf_checksum(x: jax.Array) -> jax.Array:
    """Exact uint32 wrap-sum of the raw BITS of ``x`` (trace-time helper).

    The one content-fingerprint definition shared by checkpoint integrity
    manifests (``checkpoint.tree_digests``) and the cross-replica divergence
    audit (``parallel.zero.make_replica_audit``). Properties that both rely
    on:

    - **bit-exact**: integer wrap-around addition, no float rounding — a
      single flipped bit changes the sum by ±2^k mod 2^32, never by "less
      than an ulp";
    - **layout/topology invariant**: addition is commutative and exact, so
      the digest of a logical array is identical whether it is computed on
      1 device or 64, sharded or replicated — which is what lets an
      8-device-saved manifest verify a restore onto a 4-device mesh;
    - **cheap**: one bandwidth-bound read of the tensor.

    Two flips that exactly cancel (same bit position, opposite direction)
    collide — acceptable for SDC detection, where the failure mode is a
    single flipped bit or a torn write, not an adversary.

    64-bit dtypes are bitcast to uint32 PAIRS before summing (a single
    uint64 -> uint32 narrowing would drop bits 32-63 entirely, making
    high-word flips invisible); ``checkpoint._np_checksum`` mirrors the
    same word split so both digest paths agree bit-for-bit.
    """
    if x.dtype == jnp.bool_:
        x = x.astype(jnp.uint8)
    nbits = jnp.dtype(x.dtype).itemsize * 8
    if nbits >= 64:
        # bitcast to a SMALLER width appends a trailing dim: every 32-bit
        # word of the 64-bit value participates in the sum
        u = jax.lax.bitcast_convert_type(x, jnp.uint32)
    else:
        u = jax.lax.bitcast_convert_type(x, jnp.dtype(f"uint{nbits}"))
    return jnp.sum(u.astype(jnp.uint32), dtype=jnp.uint32)
