"""Dependency-light anomaly predicates shared by training and serving.

``anomaly.py`` (the training guard) pulls in the full training stack
(optax optimizer state, ``parallel.zero.TrainState``) — far too heavy a
dependency for a pure-inference serving process that only needs the
detection CRITERION. The predicates live here, in a jax-only leaf module;
``anomaly.py`` re-exports them so training-side callers see one surface,
and ``serving/`` imports this module directly.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp


@jax.jit
def nonfinite_rows(x: jax.Array) -> jax.Array:
    """Per-row non-finite flag: ``[B, ...] -> [B]`` bool, True where any
    element of the row is NaN/Inf. The same criterion the training guard's
    ``AnomalyGuard._flag`` applies to loss/grad-norm, so training and
    serving judge "anomalous" by one definition. Cheap enough to run every
    serving tick: a [S, V] -> [S] reduction computed inside the fused step
    and fetched alongside the sampled tokens in the same device_get."""
    return ~jnp.isfinite(x).reshape(x.shape[0], -1).all(axis=1)
