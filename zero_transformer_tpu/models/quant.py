"""Weight-only int8 quantization for the inference path.

TPU-native rationale: decode is HBM-bandwidth-bound (weight bytes stream
per token), so halving the bytes ~doubles the decode ceiling — and it is
what fits Llama-3-8B-class models on ONE 16 GB v5e chip. The kernel stays
int8 in HBM and upcasts in-register inside the matmul fusion — the same
fusion contract the int8 KV cache rides (measured faster than bf16 on
chip, ``BENCHMARKS.md``); the f32 per-channel scale applies AFTER the
matmul, which is exact for per-output-channel quantization:

    x @ (q * s)  ==  (x @ q) * s          (s broadcast over columns)

Capability extension of the reference's inference side-car
(``torch_compatability/GPT2.py`` runs fp16 CUDA; no quantization exists
anywhere in the reference). Serving surface: ``serve --quantize int8``.

Layout contract (mirrors the bf16 modules 1:1 so sharding rules apply
unchanged): ``kernel`` [*, in, out] -> ``kernel_q`` int8 same shape +
``scale`` f32 [*, out]; ``wte/embedding`` [V, d] -> ``embedding_q`` int8 +
``scale`` f32 [V] (per-row, exact through both the lookup and the tied
``attend`` logits matmul); MoE expert tensors ``wi``/``wo``/``gate``
[*, E, in, out] -> ``<name>_q`` int8 + ``<name>_scale`` f32 [*, E, out]
(distinct keys — three weights share one module dict; models/moe.py
applies the scale after each expert einsum).
"""
from __future__ import annotations

from typing import Any, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.nn import initializers


def quantize_array(w, axis: int):
    """Symmetric per-channel int8: reduce |max| over ``axis``; returns
    (q int8 with ``w``'s shape, scale f32 with ``axis`` removed).

    Deliberately numpy, NOT jnp: conversion must stay on the host so an
    8B-class checkpoint is never materialized at full precision on the
    device mid-conversion (serve/evalharness quantize before placement)."""
    import numpy as np

    w = np.asarray(w, np.float32)
    amax = np.max(np.abs(w), axis=axis)
    scale = np.maximum(amax / 127.0, 1e-12).astype(np.float32)
    q = np.round(w / np.expand_dims(scale, axis)).astype(np.int8)
    return q, scale


def _int8_normal(std: float):
    """Init for an untrained quantized kernel: int8 draws whose dequantized
    distribution (with ``_q_scale(std)``) approximates normal(0, std)."""

    def init(key, shape, dtype=jnp.int8):
        return jnp.clip(
            jnp.round(jax.random.normal(key, shape) * (127.0 / 3.0)),
            -127, 127,
        ).astype(dtype)

    return init


def _q_scale(std: float):
    def init(key, shape, dtype=jnp.float32):
        return jnp.full(shape, std * 3.0 / 127.0, dtype)

    return init


class QuantDense(nn.Module):
    """Bias-free Dense with an int8 kernel + f32 per-output-channel scale.

    Same param path prefix, logical axes, and call contract as the
    ``nn.Dense`` built by ``models/gpt.py::_dense``, so the sharding rules
    and scan stacking apply unchanged."""

    features: int
    axes: Tuple
    std: float
    dtype: Any

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        q = self.param(
            "kernel_q",
            nn.with_partitioning(_int8_normal(self.std), self.axes),
            (x.shape[-1], self.features),
            jnp.int8,
        )
        scale = self.param(
            "scale",
            nn.with_partitioning(_q_scale(self.std), (self.axes[-1],)),
            (self.features,),
            jnp.float32,
        )
        # int8 HBM read; the astype upcast fuses into the dot
        y = x.astype(self.dtype) @ jnp.asarray(q).astype(self.dtype)
        return y * jnp.asarray(scale).astype(self.dtype)


class QuantEmbed(nn.Module):
    """Token table as int8 rows + f32 per-row scales; exact per-row dequant
    through BOTH consumers: the lookup (gather rows, scale) and the tied
    head's ``attend`` (matmul against the int8 table, scale the logits)."""

    num_embeddings: int
    features: int
    dtype: Any

    def setup(self):
        self.embedding_q = self.param(
            "embedding_q",
            nn.with_partitioning(_int8_normal(0.02), ("vocab", "embed")),
            (self.num_embeddings, self.features),
            jnp.int8,
        )
        self.scale = self.param(
            "scale",
            nn.with_partitioning(_q_scale(0.02), ("vocab",)),
            (self.num_embeddings,),
            jnp.float32,
        )

    def __call__(self, ids: jax.Array) -> jax.Array:
        rows = jnp.take(jnp.asarray(self.embedding_q), ids, axis=0)
        s = jnp.take(jnp.asarray(self.scale), ids, axis=0)
        return rows.astype(self.dtype) * s[..., None].astype(self.dtype)

    def attend(self, h: jax.Array) -> jax.Array:
        logits = h.astype(self.dtype) @ jnp.asarray(self.embedding_q).T.astype(
            self.dtype
        )
        return logits * jnp.asarray(self.scale).astype(self.dtype)


def _tree_paths(tree: dict, prefix: tuple = ()) -> dict:
    """Flatten a param tree to {('a','b','kernel'): shape}. Unwraps flax
    Partitioned boxes (``.value``) so boxed and plain trees compare equal."""
    out = {}
    for k, v in tree.items():
        if isinstance(v, dict):
            out.update(_tree_paths(v, prefix + (k,)))
        else:
            leaf = getattr(v, "value", v)
            out[prefix + (k,)] = tuple(getattr(leaf, "shape", ()))
    return out


def validate_quantized_tree(converted: dict, cfg) -> None:
    """Check a converted tree against the quant model's ``eval_shape``
    param structure; raise with the exact path diff on mismatch.

    A by-name conversion (``quantize_params`` walks leaf names) silently
    produces a tree the quant model cannot consume when a checkpoint uses
    unexpected names — flax then fails deep inside ``apply`` with an opaque
    structure error. Failing AT CONVERSION names the offending paths."""
    import dataclasses as _dc

    import jax as _jax

    from zero_transformer_tpu.models.gpt import Transformer

    qcfg = _dc.replace(cfg, param_quant="int8")
    model = Transformer(qcfg)
    expected = _jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((1, 1), jnp.int32)),
        _jax.random.PRNGKey(0),
    )["params"]
    want, got = _tree_paths(expected), _tree_paths(converted)
    missing = sorted(set(want) - set(got))
    unexpected = sorted(set(got) - set(want))
    shapes = sorted(
        p for p in set(want) & set(got) if want[p] != got[p]
    )
    if missing or unexpected or shapes:
        fmt = lambda ps: ", ".join("/".join(p) for p in ps[:6]) + (
            " …" if len(ps) > 6 else ""
        )
        parts = []
        if missing:
            parts.append(f"missing from conversion: {fmt(missing)}")
        if unexpected:
            parts.append(f"unexpected after conversion: {fmt(unexpected)}")
        if shapes:
            parts.append(
                "shape mismatch: "
                + ", ".join(
                    f"/{'/'.join(p)} {got[p]} != {want[p]}" for p in shapes[:4]
                )
            )
        raise ValueError(
            f"quantize_params produced a tree the int8 {cfg.name!r} model "
            "cannot consume — the checkpoint's leaf names/shapes do not "
            "match the conversion's by-name walk. " + "; ".join(parts)
        )


def quantize_params(params: dict, cfg=None) -> dict:
    """Trained bf16/f32 params -> the quantized model's param tree.

    Walks the tree by leaf path: every ``kernel`` (2-D, or scan-stacked
    [L, in, out]) becomes ``kernel_q`` + per-output-channel ``scale``;
    ``wte``'s ``embedding`` becomes ``embedding_q`` + per-row ``scale``;
    MoE expert tensors (``wi``/``wo``/``gate``, [*, E, in, out]) become
    ``<name>_q`` + per-(expert, out-channel) ``<name>_scale``. Norm
    scales, biases, the router, and ``wpe`` stay full precision (tiny).

    With ``cfg``, the converted tree is validated against the quant model's
    ``eval_shape`` structure so a by-name mis-quantization fails HERE with
    the offending paths, not as an opaque flax mismatch inside ``apply``
    (an already-quantized tree passes through unchanged and validates)."""

    def convert(tree: dict, path: tuple) -> dict:
        out: dict = {}
        for k, v in tree.items():
            if isinstance(v, dict):
                out[k] = convert(v, path + (k,))
            elif k == "kernel" and getattr(v, "ndim", 0) >= 2:
                q, scale = quantize_array(v, axis=-2)
                out["kernel_q"] = q
                out["scale"] = scale
            elif k == "embedding" and path and path[-1] == "wte":
                q, scale = quantize_array(v, axis=-1)
                out["embedding_q"] = q
                out["scale"] = scale
            elif k in ("wi", "wo", "gate") and getattr(v, "ndim", 0) >= 3:
                # stacked MoE expert tensors ([L,] E, in, out): per-(expert,
                # out-channel) scales under distinct keys (three weights
                # share one module dict)
                q, scale = quantize_array(v, axis=-2)
                out[f"{k}_q"] = q
                out[f"{k}_scale"] = scale
            else:
                out[k] = v
        return out

    converted = convert(params, ())
    if cfg is not None:
        validate_quantized_tree(converted, cfg)
    return converted
