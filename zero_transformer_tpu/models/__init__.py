from zero_transformer_tpu.models.gpt import Attention, Block, MLP, Transformer  # noqa: F401
from zero_transformer_tpu.models.registry import model_getter  # noqa: F401
