"""Model factory (reference ``src/models/GPT.py:116-137`` ``model_getter``)."""
from __future__ import annotations

import dataclasses
from typing import Optional, Tuple, Union

import jax.numpy as jnp

from zero_transformer_tpu.config import _DTYPES, ModelConfig, model_config
from zero_transformer_tpu.models.gpt import Transformer

_DTYPE_NAMES = {v: k for k, v in _DTYPES.items()}


def model_getter(
    model_size: str,
    config_path: Optional[str] = None,
    return_cfg: bool = False,
    dtype=jnp.float32,
    decode: bool = False,
    **overrides,
) -> Union[Transformer, Tuple[Transformer, ModelConfig]]:
    """Build a Transformer from the zoo by name.

    ``dtype`` sets the compute dtype (params are always kept in
    ``param_dtype``, float32 by default — the master-weight discipline the
    reference implements with an explicit bf16 cast, reference
    ``src/partitioning/xmap_train_functions.py:13-16``).
    """
    if dtype not in _DTYPE_NAMES:
        raise ValueError(f"Invalid dtype provided: {dtype}")
    kwargs = {"path": config_path} if config_path else {}
    cfg = model_config(model_size, **kwargs)
    cfg = dataclasses.replace(cfg, compute_dtype=_DTYPE_NAMES[dtype], **overrides)
    model = Transformer(cfg, decode=decode)
    return (model, cfg) if return_cfg else model
