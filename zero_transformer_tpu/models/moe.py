"""Mixture-of-Experts MLP with top-k routing and expert parallelism.

Beyond the reference (which is dense-only; SURVEY §2 checklist: EP/MoE =
none). TPU-first design choices:

- **einsum dispatch** (GShard/Switch formulation): routing builds one-hot
  dispatch/combine tensors ``[B, T, E, C]`` and moves tokens with two
  einsums. Static shapes, no gather/scatter, MXU-friendly — XLA lowers the
  expert-dim resharding to an all-to-all when the ``expert`` mesh axis is
  active (capacity C bounds the per-expert buffer, so the communication
  volume is fixed at trace time).
- **capacity-based top-k** (k ∈ {1, 2}): per-expert queue positions come
  from a cumulative sum over the token axis; overflowing tokens are dropped
  (their residual path passes through unchanged) — the standard
  fixed-capacity contract that keeps every shape static under jit.
- **router in float32** with a load-balance auxiliary loss (Switch: E ·
  Σ_e fraction_e · prob_e over first-choice assignments) and a router
  z-loss; both are returned to the caller and added to the training loss
  only (never to eval perplexity).
- expert weights are stacked ``[E, d, f]`` with the ``expert`` logical axis
  → sharded over the mesh's ``expert`` axis (EP) and composable with
  Megatron TP on the ``mlp`` axis within each expert.
"""
from __future__ import annotations

from typing import Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.nn import initializers

from zero_transformer_tpu.config import ModelConfig, resolve_dtype


def _routing(
    logits: jax.Array, top_k: int, capacity: int
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Top-k capacity-limited assignment.

    Args:
      logits: [B, T, E] float32 router scores.
      top_k: 1 (Switch: output scaled by raw router prob) or 2 (GShard:
        weights renormalized over the chosen pair).
      capacity: per-expert queue length C.

    Returns (dispatch [B,T,E,C] 0/1, combine [B,T,E,C], aux) where aux is
    the Switch load-balance loss (coefficient-free; caller scales).
    """
    B, T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)

    p = probs
    masks, gates = [], []
    for _ in range(top_k):
        idx = jnp.argmax(p, axis=-1)
        m = jax.nn.one_hot(idx, E, dtype=jnp.float32)  # [B, T, E]
        gates.append(jnp.sum(p * m, axis=-1))  # [B, T]
        masks.append(m)
        p = p * (1.0 - m)

    if top_k == 1:
        weights = gates  # Switch: scale by the raw router probability
    else:
        denom = sum(gates) + 1e-9
        weights = [g / denom for g in gates]

    dispatch = jnp.zeros((B, T, E, capacity), jnp.float32)
    combine = jnp.zeros((B, T, E, capacity), jnp.float32)
    queued = jnp.zeros((B, 1, E), jnp.float32)  # tokens enqueued per expert
    for m, w in zip(masks, weights):
        pos = jnp.cumsum(m, axis=1) - m + queued  # queue slot per token
        keep = m * (pos < capacity)
        queued = queued + jnp.cumsum(m, axis=1)[:, -1:, :]
        slot = jax.nn.one_hot(pos.astype(jnp.int32), capacity, dtype=jnp.float32)
        slot = slot * keep[..., None]  # [B, T, E, C]
        dispatch = dispatch + slot
        combine = combine + slot * w[:, :, None, None]

    # load balance over FIRST choices (Switch §2.2): E * Σ_e f_e * P_e
    f = jnp.mean(masks[0], axis=(0, 1))  # fraction routed to e
    pmean = jnp.mean(probs, axis=(0, 1))  # mean router prob for e
    aux = E * jnp.sum(f * pmean)
    return dispatch, combine, aux


class MoEMLP(nn.Module):
    """Drop-in MLP replacement: returns (output, aux_loss)."""

    cfg: ModelConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> Tuple[jax.Array, jax.Array]:
        cfg = self.cfg
        dtype = x.dtype
        param_dtype = resolve_dtype(cfg.param_dtype)
        B, T, d = x.shape
        E, k, f = cfg.n_experts, cfg.moe_top_k, cfg.ff_dim
        C = max(1, int(cfg.capacity_factor * k * T / E))
        resid_std = 0.02 / (2 * cfg.n_layers) ** 0.5

        router = self.param(
            "router",
            nn.with_partitioning(initializers.normal(stddev=0.02), ("embed", None)),
            (d, E),
            param_dtype,
        )
        # router math in f32: routing decisions are precision-sensitive (the
        # same discipline as the f32 softmax, reference ``layers.py:167-173``)
        logits = jnp.einsum(
            "btd,de->bte", x, router, preferred_element_type=jnp.float32
        )
        dispatch, combine, balance = _routing(logits, k, C)
        zloss = jnp.mean(jax.nn.logsumexp(logits, axis=-1) ** 2)
        aux = (
            jnp.float32(cfg.router_aux_coef) * balance
            + jnp.float32(cfg.router_z_coef) * zloss
        )

        # stacked expert weights; `expert` logical axis → EP mesh axis.
        # param_quant="int8" (inference only): int8 expert tensors +
        # per-(expert, out-channel) f32 scales, applied AFTER each einsum —
        # exact for this quantization granularity, same contract as
        # models/quant.py::QuantDense
        quant = cfg.param_quant == "int8"

        def expert_weight(name, shape, axes, std):
            if quant:
                from zero_transformer_tpu.models.quant import (
                    _int8_normal,
                    _q_scale,
                )

                q = self.param(
                    f"{name}_q",
                    nn.with_partitioning(_int8_normal(std), axes),
                    shape,
                    jnp.int8,
                )
                scale = self.param(
                    f"{name}_scale",
                    nn.with_partitioning(_q_scale(std), (axes[0], axes[-1])),
                    (shape[0], shape[-1]),
                    jnp.float32,
                )
                return q, scale
            w = self.param(
                name,
                nn.with_partitioning(initializers.normal(stddev=std), axes),
                shape,
                param_dtype,
            )
            return w, None

        def expert_einsum(lhs, w, scale, spec="ebcd,edf->ebcf"):
            y = jnp.einsum(spec, lhs, w.astype(dtype))
            if scale is not None:
                y = y * scale[:, None, None, :].astype(dtype)
            return y

        wi, wi_scale = expert_weight(
            "wi", (E, d, f), ("expert", "embed", "mlp"), 0.02
        )
        wo, wo_scale = expert_weight(
            "wo", (E, f, d), ("expert", "mlp", "embed"), resid_std
        )

        # dispatch: [B,T,d] tokens -> [E,B,C,d] expert buffers (all-to-all
        # over the expert axis when sharded)
        xin = jnp.einsum("btec,btd->ebcd", dispatch.astype(dtype), x)
        # named for remat_policy="qkv_mlp" (models/gpt.py
        # resolve_remat_policy): saving the expert pre-activations skips the
        # dispatch + wi einsum recompute — the dominant MoE re-forward cost —
        # exactly as saving mlp_wi does in the dense MLP
        h = checkpoint_name(expert_einsum(xin, wi, wi_scale), "mlp_wi")
        if cfg.activation == "swiglu":
            wg, wg_scale = expert_weight(
                "gate", (E, d, f), ("expert", "embed", "mlp"), 0.02
            )
            g = checkpoint_name(expert_einsum(xin, wg, wg_scale), "mlp_gate")
            h = nn.silu(g) * h
        else:
            h = nn.gelu(h)
        out_e = expert_einsum(h, wo, wo_scale, "ebcf,efd->ebcd")
        out = jnp.einsum("btec,ebcd->btd", combine.astype(dtype), out_e)
        out = nn.Dropout(cfg.dropout, deterministic=self.deterministic)(out)
        return out, aux
