"""Decoder-only transformer LM, TPU-first.

Covers the reference's GPT-2+ALiBi family (reference ``src/models/GPT.py``,
``src/models/layers.py``) and the Llama family (RoPE/RMSNorm/SwiGLU/GQA) from
one module tree, with:

- logical-axis sharding metadata on every parameter (``nn.with_partitioning``),
  which the reference only gestured at (reference ``layers.py:13-14``, unused);
- optional ``nn.scan`` over layers → O(1) compile time in depth and stacked
  [n_layers, ...] params that ZeRO shards cleanly;
- optional ``nn.remat`` per block (rematerialization: FLOPs for HBM);
- a fixed-shape jit-able KV-cache decode path — the capability the reference
  only has on its CUDA side (reference ``torch_compatability/GPT2.py:175-245``);
- float32 softmax and residual-projection init std 0.02/sqrt(2N) preserved
  (reference ``layers.py:72,184,167-173``).

API kept reference-compatible: ``Transformer.__call__(x, labels=None, train=False)``
returns logits or (logits, loss) (reference ``GPT.py:67-113``).
"""
from __future__ import annotations

from typing import Any, Optional, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax.ad_checkpoint import checkpoint_name
from jax.nn import initializers

from zero_transformer_tpu.config import ModelConfig, resolve_dtype
from zero_transformer_tpu.models.moe import MoEMLP
from zero_transformer_tpu.parallel.sharding import (
    constrain_activation,
    replicate_activation,
)
from zero_transformer_tpu.ops.attention import dot_product_attention
from zero_transformer_tpu.ops.losses import chunked_next_token_loss, next_token_loss
from zero_transformer_tpu.ops.positions import apply_rope

Dtype = Any


def _dense(
    features: int, axes: Tuple, std: float, dtype, param_dtype, name: str,
    quant: bool = False,
):
    if quant:  # weight-only int8 inference path (models/quant.py)
        from zero_transformer_tpu.models.quant import QuantDense

        return QuantDense(
            features=features, axes=axes, std=std, dtype=dtype, name=name
        )
    return nn.Dense(
        features,
        use_bias=False,
        dtype=dtype,
        param_dtype=param_dtype,
        kernel_init=nn.with_partitioning(initializers.normal(stddev=std), axes),
        name=name,
    )


def doc_ids_from_tokens(x: jax.Array, sep_token: int) -> jax.Array:
    """[B, T] tokens -> [B, T] document ids for packed-sequence masking.

    The separator closes its own document (exclusive cumsum): the sep token
    attends within the doc it terminates, the token after it starts a fresh
    segment. ONE rule shared by the fused model and the pipeline engine —
    they must never diverge (the pipeline trajectory test pins this)."""
    is_sep = (x == sep_token).astype(jnp.int32)
    return jnp.cumsum(is_sep, axis=1) - is_sep


def mask_boundary_labels(labels: jax.Array, doc_ids: jax.Array) -> jax.Array:
    """Set labels to -1 (the loss ignore_index) where the document changes:
    never predict the first token of the NEXT document from the previous
    one. Shared by the fused model and the pipeline engine."""
    boundary = doc_ids[:, 1:] != doc_ids[:, :-1]
    return jnp.concatenate(
        [labels[:, :1], jnp.where(boundary, -1, labels[:, 1:])], axis=1
    )


def _quantize_kv(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric int8 quantization over the head dim: [B, T, KVH, D] ->
    (int8 values, f32 scale [B, T, KVH, 1]). Round-to-nearest; scale floored
    so all-zero rows stay exactly zero after dequant."""
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(xf / scale), -127, 127).astype(jnp.int8)
    return q, scale


def resolve_remat_policy(cfg: ModelConfig):
    """cfg.remat_policy → jax.checkpoint saveable-policy.

    The ONE mapping, shared by the plain Transformer and the pipeline stage
    builder (parallel/pipeline.py) so the two step paths cannot diverge.

    - "none": save nothing — max HBM savings, the whole block re-forwards in
      the backward (minus dead code: the out/wo projection OUTPUTS are never
      needed, so they are not recomputed even here).
    - "dots": save every no-batch-dim matmul output
      (``dots_with_no_batch_dims_saveable``).
    - "qkv_mlp": save only the named q/k/v and MLP pre-activation tensors
      (``checkpoint_name`` sites in Attention/MLP/MoEMLP) — roughly a third
      of the dots footprint while still skipping ~85% of the re-forward
      matmul FLOPs, which are dominated by the qkv and wi projections.
    """
    if cfg.remat_policy == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    if cfg.remat_policy == "qkv_mlp":
        return jax.checkpoint_policies.save_only_these_names(
            "attn_q", "attn_k", "attn_v", "mlp_wi", "mlp_gate"
        )
    return None


def _norm(cfg: ModelConfig, dtype, name: str):
    kwargs = dict(
        dtype=dtype,
        param_dtype=resolve_dtype(cfg.param_dtype),
        scale_init=nn.with_partitioning(initializers.ones, ("embed",)),
        name=name,
    )
    if cfg.norm == "rmsnorm":
        return nn.RMSNorm(**kwargs)
    return nn.LayerNorm(use_bias=False, **kwargs)


class LMHead(nn.Module):
    """Untied output projection: a bias-free Dense whose kernel is ALSO
    directly readable (``head.kernel`` — the chunked-loss path projects the
    hidden states tile-by-tile and must not call the full-width matmul).
    Same param path (``lm_head/kernel``), shape, init, and dtype semantics
    as the ``nn.Dense`` it replaces, so existing checkpoints load
    unchanged."""

    d_in: int
    features: int
    dtype: Dtype
    param_dtype: Dtype

    def setup(self):
        self.kernel = self.param(
            "kernel",
            nn.with_partitioning(initializers.normal(stddev=0.02), ("embed", "vocab")),
            (self.d_in, self.features),
            self.param_dtype,
        )

    def __call__(self, x: jax.Array) -> jax.Array:
        return x.astype(self.dtype) @ jnp.asarray(self.kernel, self.dtype)


class Attention(nn.Module):
    """Causal MHA/GQA with ALiBi or RoPE and a fixed-shape KV cache.

    ``kv_pages=(n_pages, page_size)`` switches the decode cache to a PAGED
    layout (vLLM-style, Kwon et al. 2309.06180): K/V live in a global page
    pool ``[n_pages, page_size, KVH, D]`` shared by every row, and each row
    owns an int32 ``block_table`` ``[B, cache_len // page_size]`` mapping
    its logical sequence blocks to pool pages. Reads gather the row's pages
    back into the same ``[B, cache_len, KVH, D]`` view the slab path
    attends over; writes scatter each token's K/V to
    ``pool[table[b, pos // P], pos % P]``. Position math, validity masks,
    the int8 path, and the overflow poison guard are IDENTICAL to the slab
    cache — paging only changes where the bytes live, so paged decode is
    bit-exact vs slab decode (tested). Page 0 is the serving layer's trash
    page: a zeroed block table routes writes somewhere harmless, which is
    how parked rows ride along in fixed-shape dispatches."""

    cfg: ModelConfig
    deterministic: bool = True
    decode: bool = False
    cache_len: Optional[int] = None  # KV cache capacity; defaults to cfg.max_seq_len
    # mesh with an active `sequence` axis → ring attention (context parallel)
    mesh: Optional[Any] = None
    kv_pages: Optional[Tuple[int, int]] = None  # (n_pages, page_size)

    @nn.compact
    def __call__(self, x: jax.Array, doc_ids: Optional[jax.Array] = None) -> jax.Array:
        cfg = self.cfg
        dtype = x.dtype
        param_dtype = resolve_dtype(cfg.param_dtype)
        H, KVH, D = cfg.n_heads, cfg.kv_heads, cfg.head_width
        B, T, _ = x.shape
        resid_std = 0.02 / (2 * cfg.n_layers) ** 0.5
        quant = cfg.param_quant == "int8"

        q = _dense(H * D, ("embed", "qheads"), 0.02, dtype, param_dtype, "query", quant)(x)
        k = _dense(KVH * D, ("embed", "kvheads"), 0.02, dtype, param_dtype, "key", quant)(x)
        v = _dense(KVH * D, ("embed", "kvheads"), 0.02, dtype, param_dtype, "value", quant)(x)
        q = constrain_activation(q.reshape(B, T, H, D), "batch", "seq", "heads", "head_dim")
        k = constrain_activation(k.reshape(B, T, KVH, D), "batch", "seq", "kvheads", "head_dim")
        v = constrain_activation(v.reshape(B, T, KVH, D), "batch", "seq", "kvheads", "head_dim")
        # remat_policy="qkv_mlp" saves these three (plus the MLP
        # pre-activations) across the forward: the flash kernel's backward
        # needs q/k/v as residuals anyway, so saving them skips the qkv
        # projections' recompute — the bulk of the attention-side re-forward
        # — for ~38 MB/layer (bf16, batch 4 x 1024 x d1536). Outside remat
        # checkpoint_name is a no-op.
        q = checkpoint_name(q, "attn_q")
        k = checkpoint_name(k, "attn_k")
        v = checkpoint_name(v, "attn_v")

        use_cache = False
        offset = 0
        int8_cache = cfg.kv_cache_dtype == "int8"
        paged = self.decode and self.kv_pages is not None
        # impl="flash" downgrades to "auto" for the DECODE variant only:
        # flash-or-raise guards against silently taking the O(T^2) path on
        # training shapes, but the decode model's fallbacks — the T=1
        # cache-init trace, single-token slab decode, paged-gate declines —
        # are O(S) reads that are XLA/paged by design, and raising would
        # crash cache allocation and every decode tick of a
        # flash-configured model.
        impl = "auto" if (self.decode and cfg.attention_impl == "flash") else cfg.attention_impl
        bt = None
        if self.decode:
            max_len = self.cache_len or cfg.max_seq_len
            is_init = not self.has_variable("cache", "cached_key")
            cache_dtype = jnp.int8 if int8_cache else dtype
            if paged:
                n_pages, page = self.kv_pages
                if max_len % page:
                    raise ValueError(
                        f"cache_len ({max_len}) must be a multiple of "
                        f"page_size ({page}) for the paged KV cache"
                    )
                n_blocks = max_len // page
                ck = self.variable("cache", "cached_key", jnp.zeros, (n_pages, page, KVH, D), cache_dtype)
                cv = self.variable("cache", "cached_value", jnp.zeros, (n_pages, page, KVH, D), cache_dtype)
                if int8_cache:
                    ksc = self.variable("cache", "key_scale", jnp.zeros, (n_pages, page, KVH, 1), jnp.float32)
                    vsc = self.variable("cache", "value_scale", jnp.zeros, (n_pages, page, KVH, 1), jnp.float32)
                bt = self.variable(
                    "cache", "block_table", jnp.zeros, (B, n_blocks), jnp.int32
                )
            else:
                ck = self.variable("cache", "cached_key", jnp.zeros, (B, max_len, KVH, D), cache_dtype)
                cv = self.variable("cache", "cached_value", jnp.zeros, (B, max_len, KVH, D), cache_dtype)
                if int8_cache:
                    # per-(token, head) symmetric scales; f32 so tiny magnitudes
                    # don't underflow the dequant product
                    ksc = self.variable("cache", "key_scale", jnp.zeros, (B, max_len, KVH, 1), jnp.float32)
                    vsc = self.variable("cache", "value_scale", jnp.zeros, (B, max_len, KVH, 1), jnp.float32)
            idx = self.variable("cache", "cache_index", lambda: jnp.zeros((), jnp.int32))
            use_cache = not is_init
            if use_cache:
                offset = idx.value

        # A [B]-vector cache_index (installed by serving.slots for the
        # continuous-batching engine) means every row sits at its OWN
        # position: writes, masks, and position-dependent biases all go
        # per-row. The scalar path is untouched — a fresh init_cache gives
        # scalar indices and generate()/prefill() keep compiling the same
        # programs.
        per_slot = getattr(offset, "ndim", 0) == 1

        if cfg.position == "rope":
            if per_slot:
                pos = offset[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
            else:
                pos = offset + jnp.arange(T, dtype=jnp.int32)
            q = apply_rope(q, pos, cfg.rope_theta)
            k = apply_rope(k, pos, cfg.rope_theta)  # cache stores rotated keys

        if use_cache:
            if paged:
                n_pages, page = self.kv_pages
                n_blocks = (self.cache_len or cfg.max_seq_len) // page
                # global positions per (row, token) -> (pool page, in-page
                # slot) through each row's block table. Out-of-range blocks
                # clip to the last table entry: overflow is already made
                # loud by the NaN poison guard below, and a parked row's
                # zeroed table routes the write to the trash page.
                if per_slot:
                    pos = offset[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
                else:
                    pos = jnp.broadcast_to(
                        offset + jnp.arange(T, dtype=jnp.int32), (B, T)
                    )
                page_ids = jnp.take_along_axis(
                    bt.value, jnp.clip(pos // page, 0, n_blocks - 1), axis=1
                )  # [B, T]
                in_page = pos % page

                def write(buf, upd):
                    return buf.at[page_ids, in_page].set(upd.astype(buf.dtype))

                def gather(buf):
                    # [n_pages, page, ...] -> the row-major [B, cache_len,
                    # ...] view the slab path attends over
                    g = jnp.take(buf, bt.value, axis=0)  # [B, n_blocks, page, ...]
                    return g.reshape((B, n_blocks * page) + buf.shape[2:])

            else:
                if per_slot:
                    # per-row dynamic_update_slice at each slot's own offset
                    def write(buf, upd):
                        return jax.vmap(
                            lambda c, u, o: jax.lax.dynamic_update_slice(
                                c, u, (o,) + (0,) * (c.ndim - 1)
                            )
                        )(buf, upd, offset)

                else:
                    def write(buf, upd):
                        return jax.lax.dynamic_update_slice(
                            buf, upd, (0, offset) + (0,) * (buf.ndim - 2)
                        )

                def gather(buf):
                    return buf

            if int8_cache:
                kq, k_scale = _quantize_kv(k)
                vq, v_scale = _quantize_kv(v)
                ck.value = write(ck.value, kq)
                cv.value = write(cv.value, vq)
                ksc.value = write(ksc.value, k_scale)
                vsc.value = write(vsc.value, v_scale)
            else:
                ck.value = write(ck.value, k)
                cv.value = write(cv.value, v)
            idx.value = offset + T
            max_len_b = self.cache_len or cfg.max_seq_len
            if per_slot:
                kv_valid = (
                    jnp.arange(max_len_b)[None, :] < (offset[:, None] + T)
                ).astype(jnp.int32)
            else:
                kv_valid = jnp.broadcast_to(
                    (jnp.arange(max_len_b) < offset + T).astype(jnp.int32)[None, :],
                    (B, max_len_b),
                )
            # Writing past capacity would silently clamp onto the last slot
            # (dynamic_update_slice semantics). Poison the output with NaN
            # instead so overflow is loud even under jit; generate() also
            # guards statically. Per-slot, only the overflowing ROW is
            # poisoned — a parked slot must not corrupt its neighbors.
            overflow = offset + T > max_len_b
            if per_slot:
                overflow = overflow[:, None, None, None]
            q = jnp.where(overflow, jnp.nan, 1.0).astype(q.dtype) * q
            from zero_transformer_tpu.ops.pallas import paged_attention as pa

            if paged and pa.supported(
                impl, T=T, D=D,
                page_size=self.kv_pages[1], dtype=dtype,
            ):
                # paged-attention kernel: the block table is walked INSIDE
                # the kernel grid (page fetch per grid step), so the
                # gather-pages-to-slab view below never materializes —
                # bit-exact vs that gather path by construction and by test
                out = pa.paged_attention(
                    q, ck.value, cv.value, bt.value, offset,
                    causal=T > 1,
                    alibi=cfg.position == "alibi",
                    k_scale=ksc.value if int8_cache else None,
                    v_scale=vsc.value if int8_cache else None,
                )
            else:
                if int8_cache:
                    # dequant fuses into the attention reads; the cache is
                    # a loop carry of the decode while_loop, so XLA cannot
                    # hoist this out — HBM traffic stays at int8 + one f32
                    # scale per (token, head) instead of bf16 K/V (paged:
                    # the gather moves int8 bytes + scales, dequant happens
                    # on the gathered view) multiply in f32 (scales are
                    # stored f32 for exactly this), round once at the end
                    k_all = (gather(ck.value).astype(jnp.float32) * gather(ksc.value)).astype(dtype)
                    v_all = (gather(cv.value).astype(jnp.float32) * gather(vsc.value)).astype(dtype)
                else:
                    k_all, v_all = gather(ck.value), gather(cv.value)
                # dispatching entry point: chunked-prefill / spec-verify
                # windows route to the flash kernel where the gate accepts
                # them (TPU or interpret mode); single-token decode and CPU
                # keep the XLA path (impl downgrade above)
                out = dot_product_attention(
                    q,
                    k_all,
                    v_all,
                    causal=T > 1,
                    alibi=cfg.position == "alibi",
                    q_offset=offset,
                    segment_ids=kv_valid,
                    impl=impl,
                )
        elif self.mesh is not None:
            if cfg.cp_impl == "ulysses":
                from zero_transformer_tpu.ops.ulysses import ulysses_attention as cp_attn
            else:
                from zero_transformer_tpu.ops.ring_attention import ring_attention as cp_attn

            out = cp_attn(
                q, k, v, self.mesh, causal=True,
                alibi=cfg.position == "alibi", doc_ids=doc_ids,
            )
        else:
            # `impl` (not cfg.attention_impl): identical for training
            # models; for the decode variant this branch is the T=1
            # cache-init trace, which must not flash-or-raise
            out = dot_product_attention(
                q, k, v, causal=True, alibi=cfg.position == "alibi",
                doc_ids=doc_ids, impl=impl,
            )

        out = out.reshape(B, T, H * D)
        out = _dense(cfg.d_model, ("qheads", "embed"), resid_std, dtype, param_dtype, "out", quant)(out)
        return nn.Dropout(cfg.dropout, deterministic=self.deterministic)(out)


class MLP(nn.Module):
    cfg: ModelConfig
    deterministic: bool = True

    @nn.compact
    def __call__(self, x: jax.Array) -> jax.Array:
        cfg = self.cfg
        dtype = x.dtype
        param_dtype = resolve_dtype(cfg.param_dtype)
        resid_std = 0.02 / (2 * cfg.n_layers) ** 0.5
        f = cfg.ff_dim
        quant = cfg.param_quant == "int8"
        h = constrain_activation(
            _dense(f, ("embed", "mlp"), 0.02, dtype, param_dtype, "wi", quant)(x),
            "batch", "seq", "mlp",
        )
        # saved under remat_policy="qkv_mlp": wo's weight gradient needs
        # act(h) — saving the pre-activation skips the wi (and gate) matmul
        # recompute, the largest single matmul in the block's re-forward
        h = checkpoint_name(h, "mlp_wi")
        if cfg.activation == "swiglu":
            g = checkpoint_name(
                _dense(f, ("embed", "mlp"), 0.02, dtype, param_dtype, "gate", quant)(x),
                "mlp_gate",
            )
            h = nn.silu(g) * h
        else:
            h = nn.gelu(h)
        out = _dense(cfg.d_model, ("mlp", "embed"), resid_std, dtype, param_dtype, "wo", quant)(h)
        return nn.Dropout(cfg.dropout, deterministic=self.deterministic)(out)


class Block(nn.Module):
    """Pre-norm transformer block (reference ``GPT.py:16-50``).

    Carry is ``(x, aux)``: MoE blocks add their router auxiliary loss to
    ``aux`` as it threads through the layer scan; dense blocks pass it
    through unchanged."""

    cfg: ModelConfig
    deterministic: bool = True
    decode: bool = False
    cache_len: Optional[int] = None
    mesh: Optional[Any] = None
    kv_pages: Optional[Tuple[int, int]] = None

    @nn.compact
    def __call__(self, carry, _=None):
        cfg = self.cfg
        # packed-sequence models thread the document ids as a third carry
        # element (constant through the layer scan); the decode path never
        # packs, so its carry stays (x, aux)
        packed = cfg.doc_sep_token is not None and not self.decode
        if packed:
            x, aux, doc_ids = carry
        else:
            x, aux = carry
            doc_ids = None
        x = x + Attention(
            cfg, self.deterministic, self.decode, self.cache_len, self.mesh,
            self.kv_pages, name="attn"
        )(
            _norm(cfg, x.dtype, "ln_attn")(x), doc_ids
        )
        # pin the residual stream: batch/seq sharded, replicated over tensor
        # (Megatron layout) — GSPMD must not invent another layout for it
        x = constrain_activation(x, "batch", "seq", "embed")
        if cfg.n_experts > 0:
            mo, layer_aux = MoEMLP(cfg, self.deterministic, name="moe")(
                _norm(cfg, x.dtype, "ln_mlp")(x)
            )
            x = x + mo
            aux = aux + layer_aux
        else:
            x = x + MLP(cfg, self.deterministic, name="mlp")(
                _norm(cfg, x.dtype, "ln_mlp")(x)
            )
        x = constrain_activation(x, "batch", "seq", "embed")
        return ((x, aux, doc_ids) if packed else (x, aux)), None


class Transformer(nn.Module):
    """Full decoder LM. ``decode=True`` builds the KV-cache variant."""

    cfg: ModelConfig
    decode: bool = False
    cache_len: Optional[int] = None
    # mesh with sequence axis > 1 routes attention through ring attention
    # (context parallelism); None = single-chip / GSPMD-only layouts
    mesh: Optional[Any] = None
    # (n_pages, page_size): paged KV cache for the serving engine — K/V in
    # a global page pool addressed through per-row block tables (see
    # Attention). None = the classic [B, cache_len] slab.
    kv_pages: Optional[Tuple[int, int]] = None

    @nn.compact
    def __call__(
        self,
        x: jax.Array,
        labels: Optional[jax.Array] = None,
        train: bool = False,
    ) -> Union[jax.Array, Tuple[jax.Array, jax.Array]]:
        cfg = self.cfg
        dtype = resolve_dtype(cfg.compute_dtype)
        param_dtype = resolve_dtype(cfg.param_dtype)
        B, T = x.shape
        quant = cfg.param_quant == "int8"

        if quant:
            # weight-only int8 (inference only — the trainer rejects it):
            # int8 rows + per-row scales through both the lookup and the
            # tied head's attend (models/quant.py)
            if labels is not None:
                raise NotImplementedError(
                    "param_quant='int8' is an inference configuration; the "
                    "loss paths (incl. chunked CE's direct kernel reads) "
                    "run on full-precision params"
                )
            from zero_transformer_tpu.models.quant import QuantEmbed

            embed = QuantEmbed(
                num_embeddings=cfg.vocab_size,
                features=cfg.d_model,
                dtype=dtype,
                name="wte",
            )
        else:
            embed = nn.Embed(
                num_embeddings=cfg.vocab_size,
                features=cfg.d_model,
                embedding_init=nn.with_partitioning(
                    initializers.normal(stddev=0.02), ("vocab", "embed")
                ),
                dtype=dtype,
                param_dtype=param_dtype,
                name="wte",
            )
        if self.decode or quant:
            # decode gathers [B, <=few] ids per step; replicating the table
            # inside the decode while_loop would all-gather it every token.
            # (The quant prefill/eval path also gathers directly: its table
            # reads are int8, and quant serving meshes are pure-TP where
            # the replicated-view rewrite below is not needed.)
            h = embed(x)
        else:
            # Token lookup runs on an explicitly REPLICATED view of the
            # table: with wte sharded over vocab (tensor) and/or embed
            # (ZeRO-3), the gather output inherits an embed-sharded layout
            # that GSPMD can only reshard to the batch/seq activation layout
            # via "[SPMD] Involuntary full rematerialization" (round-4
            # MULTICHIP finding). One up-front all-gather is the efficient
            # form of the same data movement — and matches the reference's
            # trivially-replicated wte (reference ``src/models/GPT.py:75-83``).
            # The tied head (``embed.attend``) still consumes the sharded
            # table, so the vocab-parallel logits matmul is unaffected.
            table = replicate_activation(jnp.asarray(embed.embedding, dtype))
            h = jnp.take(table, x, axis=0)
        h = constrain_activation(h, "batch", "seq", "embed")

        if cfg.position == "learned":
            if T > cfg.max_seq_len:
                raise ValueError(
                    f"sequence length {T} > max_seq_len {cfg.max_seq_len}: learned "
                    "positions cannot extrapolate (use position='alibi' for that)"
                )
            wpe = nn.Embed(
                num_embeddings=cfg.max_seq_len,
                features=cfg.d_model,
                embedding_init=nn.with_partitioning(
                    initializers.normal(stddev=0.02), (None, "embed")
                ),
                dtype=dtype,
                param_dtype=param_dtype,
                name="wpe",
            )
            offset = 0
            if self.decode:
                is_init = not self.has_variable("cache", "decode_pos")
                pos_var = self.variable("cache", "decode_pos", lambda: jnp.zeros((), jnp.int32))
                if not is_init:
                    offset = pos_var.value
                    pos_var.value = offset + T
            if getattr(offset, "ndim", 0) == 1:
                # [B]-vector decode positions (continuous-batching slots)
                positions = offset[:, None] + jnp.arange(T, dtype=jnp.int32)[None, :]
            else:
                positions = offset + jnp.arange(T, dtype=jnp.int32)
            h = h + wpe(positions)

        h = nn.Dropout(cfg.dropout, deterministic=not train)(h)

        block_cls = Block
        if cfg.remat:
            block_cls = nn.remat(
                Block, prevent_cse=not cfg.scan_layers,
                policy=resolve_remat_policy(cfg),
            )
        aux = jnp.zeros((), jnp.float32)  # MoE router losses, summed over layers
        packed = cfg.doc_sep_token is not None and not self.decode
        doc_ids = None
        if packed:
            # composes with ring attention too (the kv doc ids ride the
            # ppermute ring)
            doc_ids = doc_ids_from_tokens(x, cfg.doc_sep_token)
        carry = (h, aux, doc_ids) if packed else (h, aux)
        if cfg.scan_layers:
            stack = nn.scan(
                block_cls,
                variable_axes={"params": 0, "cache": 0},
                split_rngs={"params": True, "dropout": True},
                length=cfg.n_layers,
                metadata_params={nn.PARTITION_NAME: "layers"},
            )(cfg, not train, self.decode, self.cache_len, self.mesh,
              self.kv_pages, name="blocks")
            carry, _ = stack(carry, None)
        else:
            for i in range(cfg.n_layers):
                carry, _ = block_cls(
                    cfg, not train, self.decode, self.cache_len, self.mesh,
                    self.kv_pages, name=f"block_{i}",
                )(carry, None)
        h, aux = carry[0], carry[1]

        h = _norm(cfg, h.dtype, "ln_f")(h)

        if cfg.tie_embeddings:
            head = None
        elif quant:
            head = _dense(
                cfg.vocab_size, ("embed", "vocab"), 0.02, dtype, param_dtype,
                "lm_head", quant=True,
            )
        else:
            head = LMHead(cfg.d_model, cfg.vocab_size, dtype, param_dtype, name="lm_head")

        if labels is not None and cfg.loss_chunk and not self.decode:
            # chunked CE: the [B, T, vocab] logits never materialize —
            # the loss-bearing return is (None, loss); labels-free calls
            # below still produce full logits (eval scoring needs them)
            ignore = None
            if packed:
                labels = mask_boundary_labels(labels, doc_ids)
                ignore = -1
            w_dv = (
                jnp.asarray(embed.embedding, dtype).T
                if cfg.tie_embeddings
                else jnp.asarray(head.kernel, dtype)
            )
            loss = chunked_next_token_loss(
                h, w_dv, labels, cfg.loss_chunk, ignore_index=ignore
            )
            if train and cfg.n_experts > 0:
                loss = loss + aux
            return None, loss

        logits = embed.attend(h) if cfg.tie_embeddings else head(h)

        if labels is None:
            return logits
        if packed:
            labels = mask_boundary_labels(labels, doc_ids)
            loss = next_token_loss(logits, labels, ignore_index=-1)
        else:
            loss = next_token_loss(logits, labels)
        if train and cfg.n_experts > 0:
            # router losses steer TRAINING only; eval loss stays pure CE so
            # perplexities remain comparable to dense models
            loss = loss + aux
        return logits, loss
