"""Typed configuration system.

Replaces the reference's dual OmegaConf YAML zoos (reference:
``conf/model_config.yaml`` + ``torch_compatability/model_config.yaml`` —
duplicated per SURVEY.md §2) with a single typed dataclass hierarchy loaded
from one YAML file. Everything the reference hardcoded in ``main_zero.py``
(decay_steps at :211, shuffle seed :393, PRNGKey(0) :215, adam b2 :166,
keep=5 :70) is a field here.
"""
from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Optional

import jax.numpy as jnp
import yaml

_DTYPES = {
    "float32": jnp.float32,
    "bfloat16": jnp.bfloat16,
    "float16": jnp.float16,
}


def resolve_dtype(name: str):
    if name not in _DTYPES:
        raise ValueError(f"Invalid dtype {name!r}; expected one of {sorted(_DTYPES)}")
    return _DTYPES[name]


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Architecture hyperparameters.

    Covers the reference's GPT-2+ALiBi family (reference ``src/models/GPT.py:53-113``,
    ``conf/model_config.yaml``) and extends it to the Llama family (RoPE, RMSNorm,
    SwiGLU, GQA) via the ``position``, ``norm``, ``activation``, ``n_kv_heads`` axes.
    """

    name: str = "test"
    vocab_size: int = 256
    d_model: int = 64
    n_heads: int = 4
    n_layers: int = 2
    max_seq_len: int = 32
    dropout: float = 0.0
    # "alibi" (train-short/test-long extrapolation, reference layers.py:17-44),
    # "rope" (llama family), or "learned" (plain GPT-2).
    position: str = "alibi"
    rope_theta: float = 10000.0
    n_kv_heads: Optional[int] = None  # GQA; None -> MHA
    head_dim: Optional[int] = None  # None -> d_model // n_heads
    d_ff: Optional[int] = None  # None -> 4*d_model (gelu) or 8/3*d_model (swiglu)
    activation: str = "gelu"  # "gelu" | "swiglu"
    norm: str = "layernorm"  # "layernorm" | "rmsnorm"
    tie_embeddings: bool = True
    # Compilation shape: scan over layers gives O(1) compile time in depth and a
    # stacked [n_layers, ...] param layout that ZeRO shards cleanly.
    scan_layers: bool = True
    remat: bool = False  # jax.checkpoint each block: trade FLOPs for HBM
    # what the per-block checkpoint SAVES: "none" = save nothing (max HBM
    # savings, recomputes the whole block in bwd); "dots" = save matmul
    # outputs, recompute only elementwise/norm/softmax (jax
    # dots_with_no_batch_dims_saveable — cheaper bwd for ~1 extra
    # activations-worth of HBM per block); "qkv_mlp" = save only the named
    # q/k/v + MLP pre-activation tensors (models/gpt.py checkpoint_name) —
    # ~1/3 the dots footprint, still skips most of the re-forward matmuls
    remat_policy: str = "none"
    attention_impl: str = "auto"  # "auto" | "xla" | "flash" (pallas)
    # Context-parallel engine when the mesh's `sequence` axis is active:
    # "ring" (ppermute KV rotation, ops/ring_attention.py — any head count,
    # best at very long T) or "ulysses" (two all-to-all reshards + one local
    # flash call at full T, ops/ulysses.py — needs the sequence axis to
    # divide the per-tensor-shard head counts).
    cp_impl: str = "ring"
    param_dtype: str = "float32"
    compute_dtype: str = "bfloat16"
    # Decode KV cache storage: "auto" stores at compute dtype; "int8" stores
    # symmetric per-(token, head) int8 + f32 scales — halves cache HBM
    # traffic and doubles servable context; dequant fuses into the attention
    # reads inside the decode loop. Training paths ignore this.
    kv_cache_dtype: str = "auto"
    # Weight-only int8 for the INFERENCE path (serve --quantize int8):
    # Dense kernels and the token table become int8 + per-output-channel /
    # per-vocab-row f32 scales (models/quant.py); HBM weight reads halve —
    # decode is bandwidth-bound, and this is what fits 8B-class models on
    # one 16 GB chip. Training rejects it (build_training); loss paths
    # raise.
    param_quant: str = "none"  # "none" | "int8"
    # Packed-sequence training: rows hold multiple documents separated by
    # this token id. Attention is masked so documents cannot see each other
    # (segments derived in-graph from the separator — no loader changes) and
    # the loss never predicts across a boundary. None = rows are single
    # documents (the reference's setup).
    doc_sep_token: Optional[int] = None
    # Mixture-of-Experts (0 = dense MLP everywhere). With n_experts > 0 every
    # block's MLP becomes a top-k routed expert mixture with capacity-based
    # dispatch; expert weights shard over the mesh's `expert` axis (EP).
    n_experts: int = 0
    moe_top_k: int = 2
    # Chunked cross entropy: compute the LM loss `loss_chunk` sequence
    # positions at a time so the [B, T, vocab] logits — the step's single
    # largest activation at real scale (1.6 GB f32 for 1.3B/50k-vocab at
    # 8x1024 tokens, paid again in backward) — are never materialized; the
    # loss-bearing forward then returns (None, loss). None = full logits
    # (needed whenever the caller wants logits, e.g. eval scoring; labels-
    # free calls always produce logits regardless).
    loss_chunk: Optional[int] = None
    # per-expert buffer = capacity_factor * top_k * tokens / n_experts
    capacity_factor: float = 1.25
    router_aux_coef: float = 0.01  # load-balance aux loss weight
    router_z_coef: float = 1e-3  # router z-loss weight

    @property
    def kv_heads(self) -> int:
        return self.n_kv_heads if self.n_kv_heads is not None else self.n_heads

    @property
    def head_width(self) -> int:
        return self.head_dim if self.head_dim is not None else self.d_model // self.n_heads

    @property
    def ff_dim(self) -> int:
        if self.d_ff is not None:
            return self.d_ff
        if self.activation == "swiglu":
            # keep ~same params as 4x gelu: 2/3 * 4 * d, rounded to 128
            return ((8 * self.d_model // 3) + 127) // 128 * 128
        return 4 * self.d_model

    @property
    def num_params(self) -> int:
        """Approximate parameter count (embedding included once when tied)."""
        d, f, L, v = self.d_model, self.ff_dim, self.n_layers, self.vocab_size
        h, kv, hd = self.n_heads, self.kv_heads, self.head_width
        attn = d * h * hd + 2 * d * kv * hd + h * hd * d
        mlp = (3 if self.activation == "swiglu" else 2) * d * f
        if self.n_experts > 0:
            mlp = self.n_experts * mlp + d * self.n_experts  # experts + router
        norms = 2 * d
        per_layer = attn + mlp + norms
        embed = v * d * (1 if self.tie_embeddings else 2)
        return L * per_layer + embed + d

    def __post_init__(self):
        if self.d_model % self.n_heads and self.head_dim is None:
            raise ValueError("d_model must be divisible by n_heads")
        if self.n_kv_heads is not None and self.n_heads % self.n_kv_heads:
            raise ValueError("n_heads must be divisible by n_kv_heads")
        if self.position not in ("alibi", "rope", "learned"):
            raise ValueError(f"invalid position {self.position!r}")
        if self.activation not in ("gelu", "swiglu"):
            raise ValueError(f"invalid activation {self.activation!r}")
        if self.norm not in ("layernorm", "rmsnorm"):
            raise ValueError(f"invalid norm {self.norm!r}")
        if self.remat_policy not in ("none", "dots", "qkv_mlp"):
            raise ValueError(f"invalid remat_policy {self.remat_policy!r}")
        if self.doc_sep_token is not None and self.position == "learned":
            raise ValueError(
                "doc_sep_token packing requires a relative position scheme "
                "(alibi/rope): learned absolute positions break the "
                "packed==standalone logits contract"
            )
        if self.doc_sep_token is not None and not (
            0 <= self.doc_sep_token < self.vocab_size
        ):
            raise ValueError(
                f"doc_sep_token {self.doc_sep_token} outside vocab "
                f"[0, {self.vocab_size}): the separator could never appear, "
                "silently disabling document masking"
            )
        if self.loss_chunk is not None and self.loss_chunk <= 0:
            raise ValueError("loss_chunk must be a positive chunk size or None")
        if self.n_experts < 0:
            raise ValueError("n_experts must be >= 0")
        if self.n_experts > 0 and self.moe_top_k not in (1, 2):
            raise ValueError("moe_top_k must be 1 or 2")
        if self.n_experts > 0 and self.moe_top_k > self.n_experts:
            raise ValueError("moe_top_k cannot exceed n_experts")
        if self.attention_impl not in ("auto", "xla", "flash"):
            raise ValueError(f"invalid attention_impl {self.attention_impl!r}")
        if self.cp_impl not in ("ring", "ulysses"):
            raise ValueError(f"invalid cp_impl {self.cp_impl!r}")
        if self.kv_cache_dtype not in ("auto", "int8"):
            raise ValueError(f"invalid kv_cache_dtype {self.kv_cache_dtype!r}")
        if self.param_quant not in ("none", "int8"):
            raise ValueError(f"invalid param_quant {self.param_quant!r}")
        resolve_dtype(self.param_dtype)
        resolve_dtype(self.compute_dtype)


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device-mesh layout. Axes: data (DP+ZeRO), fsdp (param shard for ZeRO-3),
    expert (MoE expert parallelism), tensor (Megatron TP), sequence
    (ring-attention context parallelism).

    The reference uses a 1-D ``("dp",)`` mesh only (reference ``main_zero.py:227-228``).
    """

    data: int = -1  # -1: use all remaining devices
    fsdp: int = 1
    expert: int = 1
    tensor: int = 1
    pipe: int = 1  # GPipe pipeline stages (layer sharding + ppermute wavefront)
    sequence: int = 1
    # Multi-slice / multi-pod placement: number of DCN-connected device
    # groups (TPU slices, or processes on platforms without slice_index)
    # that the GLOBAL data axis spans. The per-step gradient all-reduce is
    # the only collective that crosses groups; every model axis (fsdp,
    # expert, tensor, sequence, pipe) stays inside one ICI domain — the
    # scaling-book layout (DCN outermost, ICI inner). 1 = single slice
    # (plain topology-aware mesh); must divide `data`.
    dcn_data: int = 1
    # ZeRO stage: 0 = plain DP, 1 = opt-state sharded, 2 = +grad reduce-scatter,
    # 3 = +param sharded (FSDP). Reference implements stage 1 only (SURVEY §2).
    zero_stage: int = 1
    # pipeline schedule (pipe > 1): "gpipe" = fill-drain wavefront, activation
    # stash O(M) microbatches; "1f1b" = one-forward-one-backward ticks with
    # stash-and-recompute, activation stash O(P) — use when M (accumulation
    # depth) at the target context no longer fits HBM; "interleaved" = V
    # virtual stages per rank (`pp_interleave`) shrinking the bubble from
    # (P-1)/(M+P-1) toward (P-1)/(V*M+P-1) — use when the bubble, not HBM,
    # dominates step time. See docs/TRAINING.md.
    pp_schedule: str = "gpipe"
    # virtual pipeline stages per rank for pp_schedule="interleaved": each
    # microbatch makes V laps around the pipe ring, each lap running
    # n_layers/(pipe*V) layers per rank. Requires n_layers % (pipe*V) == 0
    # and accumulation depth M % pipe == 0 (microbatches flow in groups of
    # P so the wrap-around hop arrives exactly when needed — no stash).
    pp_interleave: int = 1
    # Overlapped ZeRO communication (parallel/overlap.py): the train step is
    # built around layer-granular comm buckets derived from the sharding
    # plan — the per-layer param all_gather and gradient psum_scatter are
    # issued INSIDE the blocks' layer scan (gather for layer l as its
    # iteration starts, scatter for layer l as its backward retires), so
    # XLA's latency-hiding scheduler can hide the collectives behind
    # adjacent layers' compute instead of exposing one monolithic
    # gather/scatter bracket around the whole step. Gradients are
    # bit-identical to the serial placement (tests/test_overlap.py).
    # Requires zero_stage >= 1, scan_layers, and no pipe axis (the pipeline
    # engine owns its own collective schedule).
    overlap_comm: bool = False

    def __post_init__(self):
        if self.dcn_data < 1:
            raise ValueError(f"dcn_data must be >= 1, got {self.dcn_data}")
        if self.pp_schedule not in ("gpipe", "1f1b", "interleaved"):
            raise ValueError(
                f"pp_schedule must be 'gpipe', '1f1b', or 'interleaved', "
                f"got {self.pp_schedule!r}"
            )
        if self.pp_schedule != "gpipe" and self.pipe == 1:
            # loud, not silent: without a pipe axis the schedule choice
            # would be ignored while the user expects 1F1B's O(P) memory
            # or interleaved's smaller bubble
            raise ValueError(
                f"pp_schedule={self.pp_schedule!r} requires pipe > 1 "
                f"(got pipe={self.pipe})"
            )
        if self.pp_interleave < 1:
            raise ValueError(
                f"pp_interleave must be >= 1, got {self.pp_interleave}"
            )
        if self.pp_interleave > 1 and self.pp_schedule != "interleaved":
            raise ValueError(
                f"pp_interleave={self.pp_interleave} only applies to "
                f"pp_schedule='interleaved' (got {self.pp_schedule!r})"
            )
        if self.pp_schedule == "interleaved" and self.pp_interleave < 2:
            raise ValueError(
                "pp_schedule='interleaved' needs pp_interleave >= 2 virtual "
                "stages per rank (pp_interleave=1 is exactly gpipe — ask "
                "for that by name)"
            )
        if self.overlap_comm and self.pipe > 1:
            raise ValueError(
                "overlap_comm applies to the non-pipeline ZeRO step; the "
                "pipeline engine owns its own collective schedule "
                "(pp_schedule) — drop one of overlap_comm / pipe > 1"
            )
        if self.overlap_comm and self.zero_stage < 1:
            raise ValueError(
                "overlap_comm requires zero_stage >= 1: at stage 0 there "
                "is no ZeRO collective schedule to overlap"
            )


@dataclasses.dataclass(frozen=True)
class OptimizerConfig:
    peak_learning_rate: float = 3e-4
    end_learning_rate: float = 3e-5
    warmup_steps: int = 2000
    decay_steps: Optional[int] = None  # None -> total_steps - warmup_steps
    total_steps: int = 163000
    weight_decay: float = 0.1
    b1: float = 0.9
    b2: float = 0.95
    eps: float = 1e-8
    grad_clip: float = 1.0
    schedule: str = "warmup_cosine"  # "warmup_cosine" | "warmup_linear" | "constant"
    # "adamw" (reference, main_zero.py:160-168) | "adafactor" (factored
    # second moments — classic TPU memory saver for the largest models) |
    # "lion" (momentum-only: one f32 buffer per param)
    optimizer: str = "adamw"

    def __post_init__(self):
        if self.optimizer not in ("adamw", "adafactor", "lion"):
            raise ValueError(f"invalid optimizer {self.optimizer!r}")


@dataclasses.dataclass(frozen=True)
class TrainingConfig:
    batch_size: int = 256  # global batch, in sequences
    gradient_accumulation_steps: int = 1
    train_context: int = 1024
    evaluation_frequency: int = 1000
    maximum_evaluation_steps: int = 250
    total_steps: int = 163000
    seed: int = 0
    log_frequency: int = 10
    # capture a jax.profiler trace of this many consecutive steps (0 = off),
    # starting after the first (compile) step; viewable in TensorBoard/XProf
    profile_steps: int = 0
    # absolute step at which the capture window opens (train.py
    # --profile-window START:LEN sets both fields); 0 keeps the legacy
    # "after the first step of this run" behavior
    profile_start: int = 0
    profile_dir: str = ""  # default: <checkpoint.directory>/profile
    # stop (after force-saving a checkpoint) when the loss goes NaN/inf —
    # checked at each log sync point, so it costs nothing extra. The
    # reference could burn days of pod time past a divergence.
    halt_on_nan: bool = True
    # dtype of the gradient-accumulation buffer ("float32" | "bfloat16").
    # bfloat16 halves the param-sized accumulator — the knob that lets the
    # 1.3B single-chip config fit 16 GB HBM (three f32 param-sized trees —
    # master params, accumulator, micro-grads — are 15.6 GB before
    # activations). Micro-step gradients are still computed in f32; only the
    # running sum rounds (once per add, upcast-add-round), and adafactor's
    # per-tensor normalization makes it insensitive to that scale of noise.
    # float32 is the default and is bit-identical to the pre-knob behavior.
    grad_accum_dtype: str = "float32"
    # path to a BENCH_step.json step-time decomposition artifact
    # (scripts/train_step_bench.py) measured for this config's platform.
    # When set, the trainer's obs track reports train/exposed_comm_frac
    # from the artifact's measured overlap A/B alongside the analytic
    # train/bubble_frac gauge, and emits per-window grads_compute /
    # comm_exposed / bubble_wait estimate spans. "" = bubble_frac only
    # (it is analytic — exact for the configured schedule).
    step_bench_artifact: str = ""

    def __post_init__(self):
        if self.grad_accum_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                "training.grad_accum_dtype must be 'float32' or 'bfloat16', "
                f"got {self.grad_accum_dtype!r}"
            )


@dataclasses.dataclass(frozen=True)
class DataConfig:
    # "synthetic" | "memmap" | "hf" (datasets streaming) | "tar" (webdataset-
    # style tar shards / *.index files — the reference's actual data path,
    # main_zero.py:389-421)
    source: str = "synthetic"
    train_path: str = ""
    validation_path: str = ""
    max_context: int = 2048
    shuffle_buffer: int = 10_000
    shuffle_seed: int = 23
    # batches decoded ahead of the train step by a background thread
    # (DataLoader.prefetch); 0 = fully synchronous. The reference used torch
    # DataLoader workers for the same overlap (main_zero.py:407-421).
    num_workers: int = 2
    # tar source: True crashes on any undecodable member / unreadable shard
    # (data validation); False warns, retries opens once, and skips
    strict: bool = False


@dataclasses.dataclass(frozen=True)
class ResilienceConfig:
    """Fault-tolerance policy (``zero_transformer_tpu/resilience/``).

    Three layers, all host-side except the anomaly guard:

    - **anomaly guard**: every train step is checked IN-GRAPH for non-finite
      loss/grad-norm (and, optionally, spikes against a running EMA); a
      flagged step's update is dropped inside the compiled step, so a
      divergent batch can never poison params — and detection costs no extra
      device→host sync on non-logging steps (the carry is a device array the
      host only reads at log points). This closes the ``halt_on_nan``
      blind spot where divergence between log points poisoned up to
      ``log_frequency - 1`` further updates.
    - **rollback snapshot**: state mirrored to host RAM every
      ``snapshot_frequency`` steps; on a sustained anomaly streak the last
      good snapshot is restored (no disk read) and the loader continues
      forward — the offending data window is never replayed.
    - **watchdog / supervisor**: hang detection and bounded-restart
      supervision of the whole run (``train.py --supervise``).
    """

    # in-graph per-step anomaly guard (non-finite loss/grad always flags)
    anomaly_detection: bool = True
    # escalation ceiling when an anomaly is detected: "skip_batch" only ever
    # drops flagged updates; "rollback" additionally restores the host-RAM
    # snapshot after `rollback_after` consecutive anomalies; "halt" raises at
    # the first detection (the historical halt_on_nan semantics).
    anomaly_response: str = "halt"
    # >0: flag loss > factor * EMA(loss) as an anomaly (0 = non-finite only)
    loss_spike_factor: float = 0.0
    # >0: flag grad_norm > factor * EMA(grad_norm)
    grad_spike_factor: float = 0.0
    ema_decay: float = 0.98
    # clean steps absorbed into the EMAs before spike checks arm
    spike_warmup_steps: int = 50
    # consecutive flagged steps before skip_batch escalates to halt (the
    # guard keeps params clean, but zero progress forever is its own failure)
    max_consecutive_anomalies: int = 25
    # rollback policy: restore the snapshot once a streak reaches this length
    rollback_after: int = 3
    snapshot_frequency: int = 200  # steps between host-RAM state mirrors
    max_rollbacks: int = 3  # budget per train() call; exceeding it halts
    # cross-replica divergence audit: every N steps the anomaly guard
    # checksums the state leaves that are REPLICATED over the ZeRO axes on
    # every DP replica (in-graph shard_map + scalar all_gather — no host
    # sync) and flags any bit-level disagreement. Catches silent data
    # corruption that desynced one replica within N steps instead of never
    # (XLA assumes replicated copies identical; a desync otherwise only
    # shows up when the loss curves fork). 0 disables. Escalation on a trip:
    # anomaly_response 'rollback' re-places the host snapshot (which
    # re-replicates identical copies — the desync is HEALED); anything else
    # halts (a desynced replica cannot be skipped past).
    audit_frequency: int = 0
    # hang watchdog: abort (retryably) when no step completes for this many
    # seconds; 0 disables. Must comfortably exceed worst-case compile +
    # checkpoint-write time.
    watchdog_timeout_s: float = 0.0
    # supervisor (train.py --supervise): restart budget + exponential backoff
    max_restarts: int = 3
    backoff_base_s: float = 2.0
    backoff_max_s: float = 300.0
    # multiplicative backoff jitter: each delay is spread uniformly over
    # [1-j, 1+j] so N workers restarting after a SHARED-cause failure (a
    # storage blip, a preemption wave) don't thundering-herd the checkpoint
    # store at the same instant. 0 disables (deterministic delays).
    backoff_jitter: float = 0.1

    def __post_init__(self):
        if self.anomaly_response not in ("skip_batch", "rollback", "halt"):
            raise ValueError(
                f"invalid anomaly_response {self.anomaly_response!r}; expected "
                "'skip_batch', 'rollback', or 'halt'"
            )
        if not 0.0 < self.ema_decay < 1.0:
            raise ValueError("ema_decay must be in (0, 1)")
        for name in ("loss_spike_factor", "grad_spike_factor"):
            if getattr(self, name) < 0:
                raise ValueError(f"{name} must be >= 0 (0 disables)")
        for name in (
            "rollback_after",
            "max_consecutive_anomalies",
        ):
            if getattr(self, name) < 1:
                raise ValueError(f"{name} must be >= 1")
        if self.snapshot_frequency < 0 or self.max_rollbacks < 0:
            raise ValueError("snapshot_frequency/max_rollbacks must be >= 0")
        if self.audit_frequency < 0:
            raise ValueError("audit_frequency must be >= 0 (0 disables)")
        if self.audit_frequency > 0 and not self.anomaly_detection:
            raise ValueError(
                "audit_frequency requires anomaly_detection: the replica "
                "audit rides the in-graph anomaly-guard carry (it would be "
                "silently inert with the guard disabled)"
            )
        if self.watchdog_timeout_s < 0 or self.max_restarts < 0:
            raise ValueError("watchdog_timeout_s/max_restarts must be >= 0")
        if self.backoff_base_s <= 0 or self.backoff_max_s < self.backoff_base_s:
            raise ValueError(
                "backoff_base_s must be > 0 and backoff_max_s >= backoff_base_s"
            )
        if not 0.0 <= self.backoff_jitter < 1.0:
            raise ValueError(
                "backoff_jitter must be in [0, 1): at 1.0 the jitter window "
                "touches a zero delay, which defeats the backoff entirely"
            )


@dataclasses.dataclass(frozen=True)
class ServingConfig:
    """Continuous-batching serving policy (``zero_transformer_tpu/serving/``).

    The hot-path knobs ``serve --server`` exposes as flags, with the
    defaults defined ONCE here (the CLI reads them from this dataclass so a
    YAML deployment config and the flag surface can never drift):

    - **prefill_chunk**: prompts prefill ``prefill_chunk`` tokens per
      scheduler tick, written DIRECTLY into the slot's rows of the shared
      KV cache and interleaved with the fused decode step — one long prompt
      can no longer stall every active stream for its full prefill
      (Sarathi-style chunked prefill). 0 = legacy one-shot bucketed
      prefill (the whole prompt in one padded [1, bucket] dispatch, then a
      cache insert).
    - **prefix_cache_chunks**: capacity (in chunk-sized K/V spans) of the
      chunk-aligned token-prefix LRU; repeated system prompts skip straight
      to the first novel chunk (vLLM-style block hashing). 0 disables.
      Requires ``prefill_chunk > 0``. Flushed on hot weight reload — cached
      K/V is only valid for the weights that produced it.
    - **max_prefill_buckets**: cap on DISTINCT compiled one-shot prefill
      buckets (legacy path): past it, new prompt lengths round up to an
      already-compiled bucket instead of compiling another program, so
      diverse prompt lengths cannot compile-storm a serving replica.
    - **kv_layout / page_size / page_pool_tokens**: ``paged`` replaces the
      fixed [slots, cache_len] KV slab with a block-table paged pool
      (PagedAttention): HBM is ``page_pool_tokens`` positions regardless of
      slot count, so concurrency scales with ACTUAL sequence lengths
      instead of the worst case, and prefix-cache hits become page-refcount
      bumps instead of span copies. ``page_pool_tokens = 0`` sizes the pool
      to the exact slab equivalent (slots x cache_len).
    - **draft_k**: per-tick self-speculative decoding — every decode tick
      proposes ``draft_k`` tokens per slot (prompt-lookup n-grams) and
      verifies them in ONE batched forward; greedy output is bit-identical
      to plain decode, sampling follows the standard rejection rule.
      Requires repetition_penalty == 1.0. 0 disables.
    """

    slots: int = 4
    max_queue: int = 64
    prefill_chunk: int = 64
    prefix_cache_chunks: int = 256
    max_prefill_buckets: int = 8
    drain_deadline_s: float = 30.0
    kv_layout: str = "paged"
    page_size: int = 16
    page_pool_tokens: int = 0
    draft_k: int = 0
    # fused decode tail (PR 11): sampling (temperature/top-k/veto/rejection)
    # runs INSIDE the single jitted decode/spec-verify program. False is the
    # A/B CONTROL — sampling as its own dispatch after the forward — kept
    # only so the bench can price the fusion (BENCH_serve.json's
    # no_fused_tail arm); byte-identical trajectories either way.
    fused_tail: bool = True
    # disaggregated fleets (PR 12): a "prefill" replica runs only chunked
    # prefill at max batch and ships every finished stream's KV pages to
    # the decode replica the request names; a "decode" replica serves
    # imported streams (and plain requests, as the recompute fallback);
    # "mixed" is the classic single-replica behavior. Non-mixed roles
    # require the paged KV layout — pages are the unit that ships.
    role: str = "mixed"

    def __post_init__(self):
        if self.slots < 1:
            raise ValueError("serving.slots must be >= 1")
        if self.max_queue < 1:
            raise ValueError("serving.max_queue must be >= 1")
        if self.prefill_chunk < 0:
            raise ValueError("serving.prefill_chunk must be >= 0 (0 disables)")
        if self.prefix_cache_chunks < 0:
            raise ValueError(
                "serving.prefix_cache_chunks must be >= 0 (0 disables)"
            )
        if self.prefix_cache_chunks > 0 and self.prefill_chunk == 0:
            raise ValueError(
                "serving.prefix_cache_chunks requires prefill_chunk > 0: the "
                "prefix cache is keyed on chunk-aligned token spans"
            )
        if self.max_prefill_buckets < 1:
            raise ValueError("serving.max_prefill_buckets must be >= 1")
        if self.drain_deadline_s < 0:
            raise ValueError("serving.drain_deadline_s must be >= 0")
        if self.kv_layout not in ("slab", "paged"):
            raise ValueError(
                f"serving.kv_layout must be 'slab' or 'paged', got "
                f"{self.kv_layout!r}"
            )
        if self.kv_layout == "paged" and self.prefill_chunk == 0:
            raise ValueError(
                "serving.kv_layout='paged' requires prefill_chunk > 0 (the "
                "legacy one-shot prefill has no block-table path); set "
                "kv_layout='slab' to keep prefill_chunk=0 (serve --server "
                "falls back to slab automatically for this combination)"
            )
        if self.page_size < 1:
            raise ValueError("serving.page_size must be >= 1")
        if (
            self.kv_layout == "paged"
            and self.prefill_chunk
            and self.prefill_chunk % self.page_size
        ):
            raise ValueError(
                "serving.page_size must divide prefill_chunk (page-aligned "
                "chunk sharing)"
            )
        if self.page_pool_tokens < 0:
            raise ValueError(
                "serving.page_pool_tokens must be >= 0 (0 = slots x cache_len)"
            )
        if self.draft_k < 0:
            raise ValueError("serving.draft_k must be >= 0 (0 disables)")
        if not self.fused_tail and self.draft_k:
            raise ValueError(
                "serving.fused_tail=False (the A/B control) covers the "
                "plain decode path only; speculative verify (draft_k > 0) "
                "is inseparable from its in-program sampling"
            )
        if self.role not in ("mixed", "prefill", "decode"):
            raise ValueError(
                f"serving.role must be mixed|prefill|decode, got {self.role!r}"
            )
        if self.role != "mixed" and self.kv_layout != "paged":
            raise ValueError(
                f"serving.role={self.role!r} requires kv_layout='paged': "
                "KV pages are the unit that ships between replicas"
            )
        if self.role == "prefill" and self.draft_k:
            raise ValueError(
                "serving.role='prefill' replicas never decode; draft_k "
                "must be 0"
            )


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    directory: str = "checkpoints"
    keep: int = 5
    save_frequency: int = 1000
    async_save: bool = True
    resume: bool = False
    # integrity manifests: every save writes a per-leaf content-digest item
    # (exact uint32 bit-sums, computed on device in one jit call — the
    # save-tick overhead is measured and reported as train/ckpt_verify_ms);
    # restore re-digests the restored leaves and QUARANTINES a
    # corrupt/truncated/mismatched step dir (renamed to *.quarantined),
    # falling back to the newest verified older step instead of crash-
    # looping on the same bad artifact. False = trust storage blindly
    # (the pre-manifest behavior).
    integrity: bool = True
    warm_init: bool = False
    warm_init_dir: str = ""
    # warm start from an exported params msgpack instead of a checkpoint dir;
    # depth is auto-extended (Gopher G.3.3) and the layer layout auto-converted
    warm_init_msgpack: str = ""


@dataclasses.dataclass(frozen=True)
class Config:
    model: ModelConfig = dataclasses.field(default_factory=ModelConfig)
    mesh: MeshConfig = dataclasses.field(default_factory=MeshConfig)
    optimizer: OptimizerConfig = dataclasses.field(default_factory=OptimizerConfig)
    training: TrainingConfig = dataclasses.field(default_factory=TrainingConfig)
    data: DataConfig = dataclasses.field(default_factory=DataConfig)
    checkpoint: CheckpointConfig = dataclasses.field(default_factory=CheckpointConfig)
    resilience: ResilienceConfig = dataclasses.field(default_factory=ResilienceConfig)
    serving: ServingConfig = dataclasses.field(default_factory=ServingConfig)


def _build(cls, raw: dict) -> Any:
    fields = {f.name: f for f in dataclasses.fields(cls)}
    unknown = set(raw) - set(fields)
    if unknown:
        raise ValueError(f"Unknown keys for {cls.__name__}: {sorted(unknown)}")
    return cls(**raw)


_MODEL_ZOO_PATH = Path(__file__).resolve().parent.parent / "configs" / "models.yaml"


def load_model_zoo(path: str | Path = _MODEL_ZOO_PATH) -> dict[str, ModelConfig]:
    with open(path) as f:
        raw = yaml.safe_load(f)
    return {name: _build(ModelConfig, {"name": name, **(body or {})}) for name, body in raw.items()}


def model_config(name: str, path: str | Path = _MODEL_ZOO_PATH, **overrides) -> ModelConfig:
    """Look up a model by zoo name (reference ``model_getter``, GPT.py:116-137)."""
    zoo = load_model_zoo(path)
    if name not in zoo:
        raise ValueError(f"Invalid model name {name!r}; expected one of {sorted(zoo)}")
    cfg = zoo[name]
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def load_config(path: str | Path, **overrides) -> Config:
    """Load a full training Config from YAML.

    The ``model`` section may be either an inline mapping or ``{"size": <zoo name>}``
    (mirroring the reference's ``model.size`` lookup, ``conf/config.yaml:14``).
    """
    with open(path) as f:
        raw = yaml.safe_load(f) or {}
    raw.update(overrides)
    sections = {}
    model_raw = dict(raw.pop("model", {}) or {})
    if "size" in model_raw:
        size = model_raw.pop("size")
        base = model_config(size)
        valid = {f.name for f in dataclasses.fields(ModelConfig)}
        unknown = set(model_raw) - valid
        if unknown:
            raise ValueError(f"Unknown keys for ModelConfig: {sorted(unknown)}")
        sections["model"] = dataclasses.replace(base, **model_raw)
    elif model_raw:
        sections["model"] = _build(ModelConfig, model_raw)
    for key, cls in (
        ("mesh", MeshConfig),
        ("optimizer", OptimizerConfig),
        ("training", TrainingConfig),
        ("data", DataConfig),
        ("checkpoint", CheckpointConfig),
        ("resilience", ResilienceConfig),
        ("serving", ServingConfig),
    ):
        if key in raw:
            sections[key] = _build(cls, raw.pop(key) or {})
    if raw:
        raise ValueError(f"Unknown top-level config keys: {sorted(raw)}")
    return Config(**sections)


def apply_dotted_overrides(cfg: Config, overrides: dict[str, Any]) -> Config:
    """Apply ``{"section.field": value}`` overrides to a Config, revalidating
    every touched section (each ``dataclasses.replace`` re-runs the frozen
    dataclass' ``__post_init__``). One implementation for ``train.py --set``
    AND the autotuner's candidate-point construction
    (``analysis/autotune.py``) — the validity oracle that refuses an invalid
    knob combination is therefore exactly the validation a real run hits.

    ``model.size`` applies FIRST (a zoo lookup replaces the whole model
    section), so ``model.*`` overrides — wherever they appear — land on top
    of the zoo entry instead of being clobbered by it.

    All overrides for one section apply in a SINGLE ``replace`` so only the
    final combination is validated — applying ``serving.prefill_chunk=8``
    and ``serving.page_size=8`` one field at a time would refuse the valid
    pair whenever the intermediate state (new chunk against the old page
    size) happens to be invalid."""
    overrides = dict(overrides)
    if "model.size" in overrides:
        cfg = dataclasses.replace(
            cfg, model=model_config(str(overrides.pop("model.size")))
        )
    by_section: dict[str, dict[str, Any]] = {}
    for dotted, value in overrides.items():
        section_name, _, field = dotted.partition(".")
        section = getattr(cfg, section_name, None)
        if section is None or not field or not hasattr(section, field):
            raise ValueError(f"unknown config field {dotted!r}")
        by_section.setdefault(section_name, {})[field] = value
    for section_name, fields in by_section.items():
        cfg = dataclasses.replace(
            cfg,
            **{
                section_name: dataclasses.replace(
                    getattr(cfg, section_name), **fields
                )
            },
        )
    return cfg


def flatten_config(cfg: Config) -> dict[str, Any]:
    """Flatten for metric loggers (reference ``src/utils/configs.py:7-17``)."""
    out = {}
    for section in dataclasses.fields(cfg):
        val = getattr(cfg, section.name)
        for f in dataclasses.fields(val):
            out[f"{section.name}.{f.name}"] = getattr(val, f.name)
    return out
