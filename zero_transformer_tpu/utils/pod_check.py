"""Cluster health check: verify every device/host still participates in
collectives.

Modern-API re-think of the reference's manual smoke script (reference
``src/utils/pod_test.py:1-34``: global + local ``pmap(psum)``, with the
documented failure mode of hung processes needing ``pkill``). Here:

- the global check is a jitted ``psum`` under ``shard_map`` over a 1-D mesh of
  every device — the same ICI/DCN all-reduce a training step issues;
- the local check sums over this process's devices only;
- both verify the *value* (device count), so a silently dropped participant
  is caught, and a wall-clock timeout turns a hang into a diagnosis instead
  of a mystery (``pod_check(timeout)`` runs the collective in a worker thread).

Usage: ``python -m zero_transformer_tpu.utils.pod_check [--timeout 60]``.
"""
from __future__ import annotations

import argparse
import concurrent.futures
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map


def _allreduce_count(devices) -> float:
    """psum of ones over a 1-D mesh of ``devices`` — returns the device count
    as seen by the collective (must equal ``len(devices)``)."""
    mesh = Mesh(np.asarray(devices), ("all",))

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P("all"), out_specs=P(), check_vma=False
    )
    def count(x):
        return jax.lax.psum(jnp.sum(x), "all")

    ones = jax.device_put(
        jnp.ones((len(devices),), jnp.float32),
        jax.sharding.NamedSharding(mesh, P("all")),
    )
    return float(count(ones))


def pod_check(timeout: float = 60.0, verbose: bool = True) -> bool:
    """Run global + local collective checks. Returns True when healthy."""

    def run() -> tuple[float, float]:
        global_count = _allreduce_count(jax.devices())
        local_count = _allreduce_count(jax.local_devices())
        return global_count, local_count

    with concurrent.futures.ThreadPoolExecutor(max_workers=1) as pool:
        fut = pool.submit(run)
        try:
            global_count, local_count = fut.result(timeout=timeout)
        except concurrent.futures.TimeoutError:
            if verbose:
                print(
                    f"UNHEALTHY: collective did not complete within {timeout:.0f}s "
                    "— a host or device is hung (the reference's documented "
                    "remedy: kill stray processes on every host and restart, "
                    "pod_test.py:1-6)"
                )
            return False

    ok = global_count == jax.device_count() and local_count == jax.local_device_count()
    if verbose:
        state = "healthy" if ok else "UNHEALTHY"
        print(
            f"{state}: global allreduce saw {global_count:.0f}/{jax.device_count()} "
            f"devices; local saw {local_count:.0f}/{jax.local_device_count()} "
            f"(process {jax.process_index()}/{jax.process_count()})"
        )
    return ok


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="zero_transformer_tpu.utils.pod_check")
    p.add_argument("--timeout", type=float, default=60.0)
    args = p.parse_args(argv)
    raise SystemExit(0 if pod_check(args.timeout) else 1)


if __name__ == "__main__":
    main()
