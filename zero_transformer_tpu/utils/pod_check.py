"""Cluster health check: verify every device/host still participates in
collectives.

Modern-API re-think of the reference's manual smoke script (reference
``src/utils/pod_test.py:1-34``: global + local ``pmap(psum)``, with the
documented failure mode of hung processes needing ``pkill``). Here:

- the global check is a jitted ``psum`` under ``shard_map`` over a 1-D mesh of
  every device — the same ICI/DCN all-reduce a training step issues;
- the local check sums over this process's devices only;
- both verify the *value* (device count), so a silently dropped participant
  is caught, and a wall-clock timeout turns a hang into a diagnosis instead
  of a mystery (``pod_check(timeout)`` runs the collective in a worker thread).

Usage: ``python -m zero_transformer_tpu.utils.pod_check [--timeout 60]``.
"""
from __future__ import annotations

import argparse
import functools
import os
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from zero_transformer_tpu.utils.jax_compat import shard_map


def _allreduce_count(devices) -> float:
    """psum of ones over a 1-D mesh of ``devices`` — returns the device count
    as seen by the collective (must equal ``len(devices)``)."""
    mesh = Mesh(np.asarray(devices), ("all",))

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P("all"), out_specs=P(), check_vma=False
    )
    def count(x):
        return jax.lax.psum(jnp.sum(x), "all")

    ones = jax.device_put(
        jnp.ones((len(devices),), jnp.float32),
        jax.sharding.NamedSharding(mesh, P("all")),
    )
    return float(count(ones))


def allreduce_bandwidth(
    mib: float = 64.0,
    reps: int = 5,
    devices=None,
    verbose: bool = True,
    timeout: float = 300.0,
) -> dict:
    """Time a training-shaped psum (f32, ``mib`` MiB per device) over every
    device and report achieved algorithmic bandwidth.

    The number a slow pod run needs first: whether the gradient all-reduce
    is getting ICI-class or DCN-class throughput. Algorithmic bandwidth =
    buffer bytes / wall time per all-reduce (the ring-transfer bytes are
    2(n-1)/n of that, reported too). One device short-circuits in HBM, so
    the single-chip figure is a sanity ceiling, not an interconnect number.

    Runs under the same hang-to-diagnosis guard as ``pod_check``: a link
    that passes the few-bytes health psum but wedges on a real-sized
    transfer returns ``{"error": "timeout..."}`` instead of hanging.
    """
    import time

    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    mesh = Mesh(np.asarray(devices), ("all",))
    per_dev = int(mib * (1 << 20) // 4)
    result: dict = {}

    def run() -> None:
        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("all"), out_specs=P("all"),
            check_vma=False,
        )
        def reduce(x):
            return jax.lax.psum(x, "all")

        sharding = jax.sharding.NamedSharding(mesh, P("all"))
        # build the buffer already sharded — an eager jnp.ones would
        # materialize the full n x per_dev global array on one device
        # first, which OOMs exactly the large pods this diagnoses
        x = jax.jit(
            lambda: jnp.ones((n * per_dev,), jnp.float32),
            out_shardings=sharding,
        )()
        reduced = jax.jit(reduce)
        ssum = jax.jit(jnp.sum)  # ONE warmed barrier fn, reused in the
        np.asarray(ssum(reduced(x)))  # timed window (cold jit in the window
        t0 = time.perf_counter()  # would deflate the reported bandwidth)
        for _ in range(reps):
            out = reduced(x)
        np.asarray(ssum(out))  # sync barrier (scalar fetch)
        dt = (time.perf_counter() - t0) / reps

        bytes_per_dev = per_dev * 4
        algo_gbs = bytes_per_dev / dt / 1e9
        ring_gbs = algo_gbs * (2 * (n - 1) / n) if n > 1 else algo_gbs
        result.update(
            devices=n,
            buffer_mib_per_device=round(bytes_per_dev / (1 << 20), 1),
            seconds_per_allreduce=round(dt, 6),
            algo_bandwidth_GBps=round(algo_gbs, 2),
            ring_transfer_GBps=round(ring_gbs, 2),
        )

    def guarded() -> None:
        try:
            run()
        except Exception as e:  # reported distinctly from a timeout below
            result["raised"] = e

    worker = threading.Thread(target=guarded, daemon=True)
    worker.start()
    worker.join(timeout)
    if "raised" in result:
        msg = f"bandwidth measurement raised: {result['raised']!r}"
        if verbose:
            print(f"UNHEALTHY: {msg}")
        return {"error": msg}
    if worker.is_alive() or "devices" not in result:
        msg = (
            f"timeout: {mib} MiB allreduce did not complete within "
            f"{timeout:.0f}s — the health psum passed but a real-sized "
            "transfer wedged (suspect one marginal link)"
        )
        if verbose:
            print(f"UNHEALTHY: {msg}")
        return {"error": msg}
    if verbose:
        print(
            f"allreduce {result['buffer_mib_per_device']} MiB/device over "
            f"{n} devices: {result['seconds_per_allreduce']*1e3:.2f} ms -> "
            f"{result['algo_bandwidth_GBps']:.1f} GB/s algorithmic"
            + (f" ({result['ring_transfer_GBps']:.1f} GB/s ring transfer)"
               if n > 1 else " (single device: HBM sanity ceiling)")
        )
    return result


def pod_check(timeout: float = 60.0, verbose: bool = True) -> bool:
    """Run global + local collective checks. Returns True when healthy."""

    result: dict = {}

    def run() -> None:
        try:
            result["global"] = _allreduce_count(jax.devices())
            result["local"] = _allreduce_count(jax.local_devices())
        except Exception as e:  # reported distinctly from a timeout below
            result["error"] = e

    # A hung collective cannot be cancelled from Python: the worker must be a
    # daemon thread so it never blocks process exit (a ThreadPoolExecutor's
    # __exit__ would join it forever — the exact hang this check diagnoses).
    worker = threading.Thread(target=run, daemon=True)
    worker.start()
    worker.join(timeout)
    if "error" in result:
        if verbose:
            print(f"UNHEALTHY: collective raised: {result['error']!r}")
        return False
    if worker.is_alive() or "local" not in result:
        if verbose:
            print(
                f"UNHEALTHY: collective did not complete within {timeout:.0f}s "
                "— a host or device is hung (the reference's documented "
                "remedy: kill stray processes on every host and restart, "
                "pod_test.py:1-6)"
            )
        return False
    global_count, local_count = result["global"], result["local"]

    ok = global_count == jax.device_count() and local_count == jax.local_device_count()
    if verbose:
        state = "healthy" if ok else "UNHEALTHY"
        print(
            f"{state}: global allreduce saw {global_count:.0f}/{jax.device_count()} "
            f"devices; local saw {local_count:.0f}/{jax.local_device_count()} "
            f"(process {jax.process_index()}/{jax.process_count()})"
        )
    return ok


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="zero_transformer_tpu.utils.pod_check")
    p.add_argument("--timeout", type=float, default=60.0)
    p.add_argument("--bandwidth", type=float, default=0.0, metavar="MiB",
                   help="after the health check, time a MiB-per-device psum "
                        "and report achieved all-reduce bandwidth (the "
                        "ICI-vs-DCN diagnosis for a slow pod run); shares "
                        "--timeout with the health leg")
    args = p.parse_args(argv)
    healthy = pod_check(args.timeout)
    if healthy and args.bandwidth > 0:
        if "error" in allreduce_bandwidth(
            mib=args.bandwidth, timeout=args.timeout
        ):
            healthy = False  # wedged mid-transfer: exit through the same
            # hard-exit path (the daemon worker still holds the collective)
    if not healthy:
        # The daemon worker may still hold the hung collective; a normal exit
        # would wait on runtime teardown. Flush and hard-exit with the
        # diagnosis already printed.
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(1)
    raise SystemExit(0)


if __name__ == "__main__":
    main()
