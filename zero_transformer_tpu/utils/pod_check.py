"""Cluster health check: verify every device/host still participates in
collectives.

Modern-API re-think of the reference's manual smoke script (reference
``src/utils/pod_test.py:1-34``: global + local ``pmap(psum)``, with the
documented failure mode of hung processes needing ``pkill``). Here:

- the global check is a jitted ``psum`` under ``shard_map`` over a 1-D mesh of
  every device — the same ICI/DCN all-reduce a training step issues;
- the local check sums over this process's devices only;
- both verify the *value* (device count), so a silently dropped participant
  is caught, and a wall-clock timeout turns a hang into a diagnosis instead
  of a mystery (``pod_check(timeout)`` runs the collective in a worker thread).

Usage: ``python -m zero_transformer_tpu.utils.pod_check [--timeout 60]``.
"""
from __future__ import annotations

import argparse
import functools
import os
import sys
import threading

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P
from jax import shard_map


def _allreduce_count(devices) -> float:
    """psum of ones over a 1-D mesh of ``devices`` — returns the device count
    as seen by the collective (must equal ``len(devices)``)."""
    mesh = Mesh(np.asarray(devices), ("all",))

    @functools.partial(
        shard_map, mesh=mesh, in_specs=P("all"), out_specs=P(), check_vma=False
    )
    def count(x):
        return jax.lax.psum(jnp.sum(x), "all")

    ones = jax.device_put(
        jnp.ones((len(devices),), jnp.float32),
        jax.sharding.NamedSharding(mesh, P("all")),
    )
    return float(count(ones))


def pod_check(timeout: float = 60.0, verbose: bool = True) -> bool:
    """Run global + local collective checks. Returns True when healthy."""

    result: dict = {}

    def run() -> None:
        try:
            result["global"] = _allreduce_count(jax.devices())
            result["local"] = _allreduce_count(jax.local_devices())
        except Exception as e:  # reported distinctly from a timeout below
            result["error"] = e

    # A hung collective cannot be cancelled from Python: the worker must be a
    # daemon thread so it never blocks process exit (a ThreadPoolExecutor's
    # __exit__ would join it forever — the exact hang this check diagnoses).
    worker = threading.Thread(target=run, daemon=True)
    worker.start()
    worker.join(timeout)
    if "error" in result:
        if verbose:
            print(f"UNHEALTHY: collective raised: {result['error']!r}")
        return False
    if worker.is_alive() or "local" not in result:
        if verbose:
            print(
                f"UNHEALTHY: collective did not complete within {timeout:.0f}s "
                "— a host or device is hung (the reference's documented "
                "remedy: kill stray processes on every host and restart, "
                "pod_test.py:1-6)"
            )
        return False
    global_count, local_count = result["global"], result["local"]

    ok = global_count == jax.device_count() and local_count == jax.local_device_count()
    if verbose:
        state = "healthy" if ok else "UNHEALTHY"
        print(
            f"{state}: global allreduce saw {global_count:.0f}/{jax.device_count()} "
            f"devices; local saw {local_count:.0f}/{jax.local_device_count()} "
            f"(process {jax.process_index()}/{jax.process_count()})"
        )
    return ok


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="zero_transformer_tpu.utils.pod_check")
    p.add_argument("--timeout", type=float, default=60.0)
    args = p.parse_args(argv)
    healthy = pod_check(args.timeout)
    if not healthy:
        # The daemon worker may still hold the hung collective; a normal exit
        # would wait on runtime teardown. Flush and hard-exit with the
        # diagnosis already printed.
        sys.stdout.flush()
        sys.stderr.flush()
        os._exit(1)
    raise SystemExit(0)


if __name__ == "__main__":
    main()
