"""Load repo scripts as modules by file location — one implementation.

The ``scripts/`` directory is not a package (its files are CLIs loaded by
path from tests, guards, and the ``--tuned`` surfaces); every consumer
used to hand-roll the ``spec_from_file_location`` boilerplate. Any future
fix to the loading pattern (sys.modules registration, error handling for
a missing scripts dir) now lands once, here.
"""
from __future__ import annotations

import importlib.util
from pathlib import Path

# the repo root this package is installed/checked out under
REPO_ROOT = Path(__file__).resolve().parent.parent.parent


def load_module(name: str, path):
    """Exec the file at ``path`` as module ``name`` and return it."""
    spec = importlib.util.spec_from_file_location(name, path)
    if spec is None or spec.loader is None:
        raise ImportError(f"cannot load {name} from {path}")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def load_script(filename: str):
    """A module from ``<repo>/scripts/<filename>`` (e.g. the shared
    ``bench_common.py`` provenance gate both --tuned surfaces use)."""
    return load_module(filename.rsplit(".", 1)[0],
                       REPO_ROOT / "scripts" / filename)
