"""Model surgery: depth-wise warm-start extension and layer-layout conversion.

Re-implements the reference's Gopher §G.3.3 scale-up path (reference
``src/utils/extend_params.py:12-49``: duplicate each of N trained blocks into
k·N consecutive blocks — mapping {i: [2i, 2i+1]} for doubling — and copy
embeddings / final LN unchanged), used there to warm-start 760M from 580M and
1.1B from 760M (reference ``logs/760.md:5-10``).

Two layouts are supported because the models compile either way:
- **stacked** (``scan_layers=True``): block params are [n_layers, ...] leaves
  under ``blocks`` — extension is an ``np.repeat`` on axis 0;
- **per-block** (``scan_layers=False``): ``block_0`` … ``block_{N-1}``
  subtrees — extension copies subtrees.

``stack_blocks`` / ``unstack_blocks`` convert between them so checkpoints
trained one way restore into models compiled the other way.
"""
from __future__ import annotations

from typing import Any, Dict

# numpy, not jax.numpy, for the array ops: surgery is a host-side tool
# (export CLI, warm-start load path) and must never trigger accelerator
# backend init. jax is imported for tree utilities only (host-side).
import jax
import numpy as np

_BLOCK_PREFIX = "block_"
_STACKED_KEY = "blocks"


def is_stacked(params: Dict[str, Any]) -> bool:
    return _STACKED_KEY in params


def _block_keys(params: Dict[str, Any]) -> list:
    keys = sorted(
        (k for k in params if k.startswith(_BLOCK_PREFIX)),
        key=lambda k: int(k[len(_BLOCK_PREFIX) :]),
    )
    if not keys:
        raise ValueError("no block_<i> subtrees found (already stacked?)")
    return keys


def stack_blocks(params: Dict[str, Any]) -> Dict[str, Any]:
    """per-block layout → stacked [n_layers, ...] layout."""
    if is_stacked(params):
        return params
    keys = _block_keys(params)
    blocks = [params[k] for k in keys]
    stacked = jax.tree.map(lambda *leaves: np.stack(leaves, axis=0), *blocks)
    out = {k: v for k, v in params.items() if not k.startswith(_BLOCK_PREFIX)}
    out[_STACKED_KEY] = stacked
    return out


def unstack_blocks(params: Dict[str, Any]) -> Dict[str, Any]:
    """stacked layout → per-block layout."""
    if not is_stacked(params):
        return params
    stacked = params[_STACKED_KEY]
    n = jax.tree.leaves(stacked)[0].shape[0]
    out = {k: v for k, v in params.items() if k != _STACKED_KEY}
    for i in range(n):
        out[f"{_BLOCK_PREFIX}{i}"] = jax.tree.map(lambda x: x[i], stacked)
    return out


def num_layers(params: Dict[str, Any]) -> int:
    if is_stacked(params):
        return jax.tree.leaves(params[_STACKED_KEY])[0].shape[0]
    return len(_block_keys(params))


def extend_depth(params: Dict[str, Any], n_new: int) -> Dict[str, Any]:
    """Depth-wise warm start: N trained blocks → n_new = k·N blocks.

    Block i of the donor becomes blocks [k·i, k·i+1, …, k·i+k-1] of the new
    model (the reference's ``create_mapping`` {i: [2i, 2i+1]} generalized to
    any integer factor, reference ``extend_params.py:46-49``); all non-block
    params (wte, wpe, final LN) are copied unchanged (``extend_params.py:20-26``).
    Preserves the input layout (stacked stays stacked).
    """
    n_old = num_layers(params)
    if n_new % n_old:
        raise ValueError(
            f"new depth {n_new} must be an integer multiple of donor depth {n_old}"
        )
    factor = n_new // n_old
    if factor == 1:
        return params
    if is_stacked(params):
        out = dict(params)
        out[_STACKED_KEY] = jax.tree.map(
            lambda x: np.repeat(x, factor, axis=0), params[_STACKED_KEY]
        )
        return out
    out = {k: v for k, v in params.items() if not k.startswith(_BLOCK_PREFIX)}
    for i, key in enumerate(_block_keys(params)):
        for j in range(factor):
            out[f"{_BLOCK_PREFIX}{factor * i + j}"] = jax.tree.map(
                lambda x: x, params[key]
            )
    return out


def upcycle_moe(
    params: Dict[str, Any], n_experts: int, router_scale: float = 0.02
) -> Dict[str, Any]:
    """Sparse upcycling: dense checkpoint → MoE warm start.

    Every block's dense MLP weights are replicated into all ``n_experts``
    expert slots (each expert starts as an exact copy, so the upcycled model
    computes the same function as the donor up to router mixing), and a
    small random router is added. This is the Sparse Upcycling recipe
    (Komatsuzaki et al. 2023) — the MoE analogue of the reference's
    depth-extension warm start (reference ``extend_params.py``). Beyond the
    reference, which has no MoE at all.

    Expects/returns the stacked layout (``scan_layers=True``; convert with
    ``stack_blocks`` first). The output matches ``Transformer`` with
    ``n_experts=n_experts``: ``blocks/moe/{router, wi, wo[, gate]}``.
    """
    if not is_stacked(params):
        raise ValueError("upcycle_moe expects the stacked layout (stack_blocks)")
    if "mlp" not in params[_STACKED_KEY]:
        raise ValueError("donor has no dense MLP to upcycle (already MoE?)")
    blocks = dict(params[_STACKED_KEY])
    mlp = blocks.pop("mlp")

    # numpy throughout: surgery is a host-side tool (export CLI) and must
    # not trigger accelerator backend init
    def expertize(kernel):  # [L, d, f] -> [L, E, d, f]
        return np.repeat(np.asarray(kernel)[:, None], n_experts, axis=1)

    moe = {name: expertize(mlp[name]["kernel"]) for name in mlp}
    wi = moe["wi"]
    L, _, d, _ = wi.shape
    rng = np.random.default_rng(0)
    moe["router"] = (
        rng.standard_normal((L, d, n_experts)).astype(np.float32) * router_scale
    )
    blocks["moe"] = moe
    out = dict(params)
    out[_STACKED_KEY] = blocks
    return out
