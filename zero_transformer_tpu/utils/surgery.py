"""Model surgery: depth-wise warm-start extension and layer-layout conversion.

Re-implements the reference's Gopher §G.3.3 scale-up path (reference
``src/utils/extend_params.py:12-49``: duplicate each of N trained blocks into
k·N consecutive blocks — mapping {i: [2i, 2i+1]} for doubling — and copy
embeddings / final LN unchanged), used there to warm-start 760M from 580M and
1.1B from 760M (reference ``logs/760.md:5-10``).

Two layouts are supported because the models compile either way:
- **stacked** (``scan_layers=True``): block params are [n_layers, ...] leaves
  under ``blocks`` — extension is a ``jnp.repeat`` on axis 0;
- **per-block** (``scan_layers=False``): ``block_0`` … ``block_{N-1}``
  subtrees — extension copies subtrees.

``stack_blocks`` / ``unstack_blocks`` convert between them so checkpoints
trained one way restore into models compiled the other way.
"""
from __future__ import annotations

from typing import Any, Dict

import jax
import jax.numpy as jnp

_BLOCK_PREFIX = "block_"
_STACKED_KEY = "blocks"


def is_stacked(params: Dict[str, Any]) -> bool:
    return _STACKED_KEY in params


def _block_keys(params: Dict[str, Any]) -> list:
    keys = sorted(
        (k for k in params if k.startswith(_BLOCK_PREFIX)),
        key=lambda k: int(k[len(_BLOCK_PREFIX) :]),
    )
    if not keys:
        raise ValueError("no block_<i> subtrees found (already stacked?)")
    return keys


def stack_blocks(params: Dict[str, Any]) -> Dict[str, Any]:
    """per-block layout → stacked [n_layers, ...] layout."""
    if is_stacked(params):
        return params
    keys = _block_keys(params)
    blocks = [params[k] for k in keys]
    stacked = jax.tree.map(lambda *leaves: jnp.stack(leaves, axis=0), *blocks)
    out = {k: v for k, v in params.items() if not k.startswith(_BLOCK_PREFIX)}
    out[_STACKED_KEY] = stacked
    return out


def unstack_blocks(params: Dict[str, Any]) -> Dict[str, Any]:
    """stacked layout → per-block layout."""
    if not is_stacked(params):
        return params
    stacked = params[_STACKED_KEY]
    n = jax.tree.leaves(stacked)[0].shape[0]
    out = {k: v for k, v in params.items() if k != _STACKED_KEY}
    for i in range(n):
        out[f"{_BLOCK_PREFIX}{i}"] = jax.tree.map(lambda x: x[i], stacked)
    return out


def num_layers(params: Dict[str, Any]) -> int:
    if is_stacked(params):
        return jax.tree.leaves(params[_STACKED_KEY])[0].shape[0]
    return len(_block_keys(params))


def extend_depth(params: Dict[str, Any], n_new: int) -> Dict[str, Any]:
    """Depth-wise warm start: N trained blocks → n_new = k·N blocks.

    Block i of the donor becomes blocks [k·i, k·i+1, …, k·i+k-1] of the new
    model (the reference's ``create_mapping`` {i: [2i, 2i+1]} generalized to
    any integer factor, reference ``extend_params.py:46-49``); all non-block
    params (wte, wpe, final LN) are copied unchanged (``extend_params.py:20-26``).
    Preserves the input layout (stacked stays stacked).
    """
    n_old = num_layers(params)
    if n_new % n_old:
        raise ValueError(
            f"new depth {n_new} must be an integer multiple of donor depth {n_old}"
        )
    factor = n_new // n_old
    if factor == 1:
        return params
    if is_stacked(params):
        out = dict(params)
        out[_STACKED_KEY] = jax.tree.map(
            lambda x: jnp.repeat(x, factor, axis=0), params[_STACKED_KEY]
        )
        return out
    out = {k: v for k, v in params.items() if not k.startswith(_BLOCK_PREFIX)}
    for i, key in enumerate(_block_keys(params)):
        for j in range(factor):
            out[f"{_BLOCK_PREFIX}{factor * i + j}"] = jax.tree.map(
                lambda x: x, params[key]
            )
    return out
