"""Path-scheme helpers shared by checkpointing and metrics (dependency-free:
importable without orbax/jax so host-side tools can use it)."""
from __future__ import annotations

from pathlib import Path


def is_remote_path(path: "str | Path") -> bool:
    """True for scheme-ful storage URLs (``gs://``, ``s3://``, ...) — the
    reference's deployment mode writes checkpoints straight to GCS
    (reference ``main_zero.py:58-93``, ``gs://bucket/...`` paths). Local
    filesystem paths (absolute, relative, ``~``) are False."""
    return "://" in str(path)
