"""wandb credential distribution for multi-host pods.

The reference ships a one-shot CLI that logs every TPU host into wandb
before a pod run (reference ``login.py:20-22``). TPU-native equivalent,
import-gated (wandb optional in this tree):

- ``python -m zero_transformer_tpu.utils.wandb_login --key $KEY`` logs THIS
  host in (writes the credential via ``wandb.login``; falls back to a
  ~/.netrc entry when wandb isn't importable, which wandb reads on first
  use).
- ``--broadcast NAME --zone Z`` prints the one gcloud command that replays
  the login on every worker of a TPU pod slice — credential distribution
  without this package needing cluster-ssh machinery of its own.

The key is resolved from ``--key``, then ``$WANDB_API_KEY``, then
``--key-file``. Nothing is ever echoed back; the key only lands in the
local credential store.
"""
from __future__ import annotations

import argparse
import os
import stat
import sys

_NETRC_HOST = "api.wandb.ai"


def _resolve_key(args) -> str:
    if args.key:
        return args.key
    if os.environ.get("WANDB_API_KEY"):
        return os.environ["WANDB_API_KEY"]
    if args.key_file:
        with open(args.key_file) as f:
            return f.read().strip()
    raise SystemExit(
        "no API key: pass --key, set WANDB_API_KEY, or pass --key-file"
    )


def _netrc_login(key: str) -> str:
    """Write the machine entry wandb's client reads — the no-import path."""
    path = os.path.expanduser("~/.netrc")
    lines = []
    if os.path.exists(path):
        with open(path) as f:
            content = f.read().splitlines()
        skip = False
        for line in content:
            head = line.strip().split(" ", 1)[0]
            # a new netrc entry starts at machine/default/macdef — any of
            # them ends the skipped wandb block (dropping only OUR entry)
            if head in ("machine", "default", "macdef"):
                skip = head == "machine" and _NETRC_HOST in line
            if not skip:
                lines.append(line)
    lines += [f"machine {_NETRC_HOST}", "  login user", f"  password {key}"]
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    os.chmod(path, stat.S_IRUSR | stat.S_IWUSR)
    return path


def login(key: str) -> str:
    """Log this host in; returns a human-readable description of what stuck."""
    try:
        import wandb

        wandb.login(key=key, relogin=True)
        return "wandb.login ok"
    except ImportError:
        return f"wandb not installed; wrote {_netrc_login(key)}"


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--key", default=None, help="wandb API key")
    p.add_argument("--key-file", default=None, help="file containing the key")
    p.add_argument(
        "--broadcast",
        default=None,
        metavar="TPU_NAME",
        help="print the gcloud command that runs this login on all pod workers",
    )
    p.add_argument("--zone", default=None, help="GCE zone for --broadcast")
    args = p.parse_args(argv)

    if args.broadcast:
        # resolve NOW so --key/--key-file work too (not just an exported
        # env var); the printed command necessarily carries the key — same
        # trust model as typing it into gcloud yourself
        key = _resolve_key(args)
        zone = f" --zone={args.zone}" if args.zone else ""
        print(
            f"gcloud compute tpus tpu-vm ssh {args.broadcast}{zone} --worker=all "
            f'--command="python -m zero_transformer_tpu.utils.wandb_login '
            f'--key {key}"'
        )
        return
    print(login(_resolve_key(args)), file=sys.stderr)


if __name__ == "__main__":
    main()
