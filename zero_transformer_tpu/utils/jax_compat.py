"""Version tolerance for the handful of new-jax APIs this repo leans on.

The codebase targets the modern ambient-mesh jax surface (``jax.shard_map``,
``jax.set_mesh``, ``jax.sharding.use_abstract_mesh``); some deployment images
pin an older jax (0.4.x) where those names either live elsewhere
(``jax.experimental.shard_map``) or do not exist at all (the ambient-mesh
machinery). Import sites go through this module so one place owns the
translation:

- ``shard_map``: the new keyword surface (``axis_names`` = the MANUAL axes,
  ``check_vma``) translated to the experimental API's complement form
  (``auto`` = the axes left automatic, ``check_rep``) when needed;
- ``set_mesh``: falls back to the legacy ``with mesh:`` context — on old jax
  that is what resolves bare-PartitionSpec ``with_sharding_constraint`` calls;
- ``use_abstract_mesh`` / ``clear_abstract_mesh``: no-ops on old jax, where
  there is no ambient abstract mesh to leak into flax's param boxing;
- ``get_abstract_mesh``: returns None on old jax, which callers treat as
  "no ambient mesh" (``parallel.sharding.constrain_activation`` no-ops).

Nothing here changes behavior on a modern jax: every symbol resolves to the
real API when it exists.
"""
from __future__ import annotations

import contextlib

import jax

HAS_AMBIENT_MESH = hasattr(jax, "set_mesh") and hasattr(
    jax.sharding, "use_abstract_mesh"
)

if hasattr(jax, "shard_map"):
    shard_map = jax.shard_map
else:
    from jax.experimental.shard_map import shard_map as _exp_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, axis_names=None,
                  check_vma=None, **kwargs):
        """New-surface ``jax.shard_map`` on the experimental implementation.

        ``axis_names`` (manual axes) becomes ``auto`` (its complement);
        ``check_vma`` maps onto ``check_rep``.
        """
        if axis_names is not None:
            kwargs["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
        if check_vma is not None:
            kwargs["check_rep"] = bool(check_vma)
        return _exp_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
        )


if hasattr(jax, "set_mesh"):
    set_mesh = jax.set_mesh
else:

    @contextlib.contextmanager
    def set_mesh(mesh):
        # legacy ambient mesh: the Mesh context manager is what pre-ambient
        # jax used to resolve unqualified sharding constraints
        with mesh:
            yield mesh


if HAS_AMBIENT_MESH:
    use_abstract_mesh = jax.sharding.use_abstract_mesh

    def clear_abstract_mesh():
        """Context clearing the ambient mesh (see ``inference.generate``:
        flax boxing must not read logical axis names as mesh axes)."""
        from jax.sharding import AbstractMesh

        return jax.sharding.use_abstract_mesh(AbstractMesh((), ()))

    get_abstract_mesh = jax.sharding.get_abstract_mesh
else:

    @contextlib.contextmanager
    def use_abstract_mesh(mesh):
        yield mesh

    @contextlib.contextmanager
    def clear_abstract_mesh():
        # old jax has no abstract mesh, but the hazard this guards against
        # (flax boxing reading LOGICAL axis names as mesh axes during an
        # eval_shape init) exists all the same under the legacy ``with mesh:``
        # context that our ``set_mesh`` fallback enters — clear the legacy
        # thread-resources mesh for the duration instead
        from jax._src import mesh as _mesh_lib

        prev = _mesh_lib.thread_resources.env
        _mesh_lib.thread_resources.env = _mesh_lib.EMPTY_ENV
        try:
            yield
        finally:
            _mesh_lib.thread_resources.env = prev

    def get_abstract_mesh():
        return None


def ensure_donatable(tree):
    """Copy every leaf into an XLA-runtime-owned buffer (eager add-0).

    ``jax.device_put`` from host numpy and orbax restores can hand back
    arrays whose buffers the runtime does NOT own (zero-copy views of host
    memory). The train step donates its input state, and on jax 0.4.37's
    CPU backend donating such a foreign buffer lets XLA recycle memory it
    never owned — the state silently turns to garbage within a step or two
    and glibc aborts with heap corruption. An eager add-0 per leaf runs a
    real XLA computation, so every output buffer is freshly allocated and
    runtime-owned (shardings are preserved: eager ops follow their committed
    operands). Call this on ANY state that flows into a donating jit from
    outside one: checkpoint restores, host-RAM rollback snapshots, warm-init
    imports.
    """
    import jax.numpy as jnp

    return jax.tree.map(lambda x: jnp.add(x, jnp.zeros((), x.dtype)), tree)
