"""Compatibility facade over ``zero_transformer_tpu.obs`` (PR 7).

This module used to own MetricsLogger / StepTimer / MFU / HBM helpers; they
now live in ``obs/logging.py`` as part of the unified observability layer
(spans, Prometheus metrics, flight recorder, profiling — see
docs/OBSERVABILITY.md). Every pre-PR7 import path keeps working through the
re-exports below; new code should import from ``zero_transformer_tpu.obs``.
"""
from zero_transformer_tpu.obs.logging import (  # noqa: F401
    TPU_PEAK_FLOPS,
    MetricsLogger,
    StepTimer,
    device_peak_flops,
    hbm_device_stats,
    hbm_used_gb,
    mfu,
    model_flops_per_token,
    profile,
)

__all__ = [
    "TPU_PEAK_FLOPS",
    "MetricsLogger",
    "StepTimer",
    "device_peak_flops",
    "hbm_device_stats",
    "hbm_used_gb",
    "mfu",
    "model_flops_per_token",
    "profile",
]
