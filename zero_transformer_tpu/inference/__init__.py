"""In-tree TPU inference: KV-cache decode + sampling (replaces the
reference's CUDA/PyTorch side-car, reference ``torch_compatability/`` +
``app.py``)."""
from zero_transformer_tpu.inference.speculative import (
    generate_speculative,
    ngram_propose,
)
from zero_transformer_tpu.inference.generate import (
    decode_model,
    generate,
    generate_tokens,
    init_cache,
    prefill,
    serve_mesh,
    shard_for_inference,
    stream_tokens,
)
from zero_transformer_tpu.inference.sampling import (
    SamplingConfig,
    apply_repetition_penalty,
    process_logits,
    sample_token,
    top_k_filter,
    top_p_filter,
)

__all__ = [
    "SamplingConfig",
    "apply_repetition_penalty",
    "decode_model",
    "generate",
    "generate_speculative",
    "generate_tokens",
    "init_cache",
    "ngram_propose",
    "prefill",
    "process_logits",
    "sample_token",
    "serve_mesh",
    "shard_for_inference",
    "stream_tokens",
    "top_k_filter",
    "top_p_filter",
]
