"""Jittable logit processors and token sampling.

TPU-native replacement for the reference's per-token Python/torch logit
processing (reference ``app.py:97-142``: repetition penalty, top-k, nucleus
top-p, greedy = top-1). Everything here is shape-static and traceable so the
whole decode step — model, processors, sampling — compiles into one XLA
program; the reference instead re-ran Python string/ops per generated token
(``app.py:69-94``).

Processor semantics match the reference:
- repetition penalty divides positive / multiplies negative logits of tokens
  generated so far (``app.py:102-107``), tracked as a [B, vocab] presence mask
  instead of a Python list;
- top-k keeps the k best logits (``app.py:111-115``);
- top-p keeps the smallest prefix of the sorted distribution whose cumulative
  probability exceeds p, always retaining the top token (``app.py:119-142``).
"""
from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e10


@dataclasses.dataclass(frozen=True)
class SamplingConfig:
    """Static sampling hyperparameters (baked into the compiled decode step)."""

    temperature: float = 1.0
    top_k: int = 0  # 0 = disabled
    top_p: float = 0.0  # 0 or 1 = disabled
    repetition_penalty: float = 1.0  # 1 = disabled
    greedy: bool = False
    # "exact" = lax.top_k (full [B, V] sort per decode step); "approx" =
    # lax.approx_max_k, the TPU-native partial-reduce top-k (PEAK-k): much
    # cheaper on the 50k-entry vocab axis, at the cost of an APPROXIMATE
    # cutoff — the kept set can be slightly wider than k when the recall
    # target misses a true top-k entry (never narrower than the true top-k
    # entries it did find). Semantics knob, so it is opt-in.
    top_k_impl: str = "exact"  # "exact" | "approx"

    def __post_init__(self):
        if self.temperature <= 0:
            raise ValueError("temperature must be > 0")
        if not 0.0 <= self.top_p <= 1.0:
            raise ValueError("top_p must be in [0, 1]")
        if self.top_k < 0:
            raise ValueError("top_k must be >= 0")
        if self.top_k_impl not in ("exact", "approx"):
            raise ValueError(f"invalid top_k_impl {self.top_k_impl!r}")


def apply_repetition_penalty(
    logits: jax.Array, generated_mask: jax.Array, penalty: float
) -> jax.Array:
    """Penalize tokens already generated. logits [B, V]; mask [B, V] bool."""
    if penalty == 1.0:
        return logits
    penalized = jnp.where(logits > 0, logits / penalty, logits * penalty)
    return jnp.where(generated_mask, penalized, logits)


def top_k_filter(logits: jax.Array, k: int, impl: str = "exact") -> jax.Array:
    """Keep the k largest logits per row; mask the rest to NEG_INF.

    impl="approx" thresholds at the minimum of ``lax.approx_max_k``'s
    result instead of the exact k-th value: on TPU that replaces the full
    vocab sort with the hardware partial-reduce (designed for exactly this
    op). The approximate threshold is <= the exact one, so the kept set is
    a superset of the approx-found true top entries and can be slightly
    wider than k — a strictly softer filter, never a harder one."""
    if k <= 0 or k >= logits.shape[-1]:
        return logits
    if impl == "approx":
        kth = jax.lax.approx_max_k(logits, k)[0][..., -1:]
    else:
        kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, NEG_INF, logits)


def top_p_filter(logits: jax.Array, p: float) -> jax.Array:
    """Nucleus filtering: keep the smallest prefix with cumulative prob > p.

    p == 1.0 is the conventional "disabled" value (keeps everything)."""
    if p <= 0.0 or p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    cum = jnp.cumsum(jax.nn.softmax(sorted_logits, axis=-1), axis=-1)
    # a token is dropped when the cumulative mass *before* it already exceeds
    # p (reference shifts the removal mask right by one, app.py:133-135)
    exceeded = cum > p
    drop_sorted = jnp.concatenate(
        [jnp.zeros_like(exceeded[..., :1]), exceeded[..., :-1]], axis=-1
    )
    # threshold = smallest kept logit
    threshold = jnp.min(
        jnp.where(drop_sorted, jnp.inf, sorted_logits), axis=-1, keepdims=True
    )
    return jnp.where(logits < threshold, NEG_INF, logits)


def process_logits(
    logits: jax.Array,
    cfg: SamplingConfig,
    generated_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Temperature → repetition penalty → top-k → top-p (reference order,
    ``app.py:97-108`` then ``generate_text`` wiring ``app.py:159-175``)."""
    logits = logits.astype(jnp.float32) / cfg.temperature
    if generated_mask is not None:
        logits = apply_repetition_penalty(
            logits, generated_mask, cfg.repetition_penalty
        )
    logits = top_k_filter(logits, cfg.top_k, cfg.top_k_impl)
    logits = top_p_filter(logits, cfg.top_p)
    return logits


def sample_token(
    rng: jax.Array,
    logits: jax.Array,
    cfg: SamplingConfig,
    generated_mask: Optional[jax.Array] = None,
) -> jax.Array:
    """Sample (or argmax) next tokens. logits [B, V] → [B] int32."""
    logits = process_logits(logits, cfg, generated_mask)
    if cfg.greedy:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return jax.random.categorical(rng, logits, axis=-1).astype(jnp.int32)
