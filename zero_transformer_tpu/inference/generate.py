"""KV-cached autoregressive generation, fully under jit.

In-tree JAX replacement for the reference's CUDA-only inference stack
(reference ``torch_compatability/GPT2.py:354-445`` ``generate``/KV cache and
``app.py:42-94`` streaming loop). Design differences, TPU-first:

- ONE compiled program for prefill and one for the whole decode loop
  (``lax.while_loop`` with a fixed-shape cache and early exit when every
  sequence hits EOS) — the reference re-enters Python per token;
- the KV cache is preallocated [B, cache_len] (model's ``decode=True``
  variant), so shapes are static and XLA never re-tiles — the reference's
  torch path instead rebuilds its ALiBi mask whenever the context grows
  (``GPT2.py:191-235``);
- batch generation is native: [B, T] prompts in, [B, max_new_tokens] out,
  per-row EOS masking; the reference generates one sequence at a time.
"""
from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from zero_transformer_tpu.config import ModelConfig
from zero_transformer_tpu.inference.sampling import SamplingConfig, sample_token
from zero_transformer_tpu.models.gpt import Transformer


def decode_model(cfg: ModelConfig, cache_len: int, kv_pages=None) -> Transformer:
    """The KV-cache variant of the model (same params as the training one).

    ``kv_pages=(n_pages, page_size)`` builds the PAGED cache variant for
    the serving engine: K/V in a global page pool addressed through
    per-row block tables (``models.gpt.Attention``). ``page_size`` must
    divide ``cache_len``."""
    if kv_pages is not None:
        n_pages, page = kv_pages
        if page < 1 or n_pages < 2:
            raise ValueError(
                f"kv_pages needs page_size >= 1 and n_pages >= 2 (one trash "
                f"page + one real page), got {kv_pages}"
            )
        if cache_len % page:
            raise ValueError(
                f"page_size ({page}) must divide cache_len ({cache_len})"
            )
        kv_pages = (int(n_pages), int(page))
    return Transformer(cfg, decode=True, cache_len=cache_len, kv_pages=kv_pages)


def serve_mesh(tensor: int):
    """Pure tensor-parallel mesh over the first ``tensor`` devices — the
    serving layout. The decode batch stays whole on every chip; params and
    KV cache shard over heads/feature dims, so a model bigger than one
    chip's HBM (the gap between the llama3_8b plan test and anything
    runnable, round-3 VERDICT missing #5) serves across chips."""
    from zero_transformer_tpu.config import MeshConfig
    from zero_transformer_tpu.parallel.mesh import make_mesh

    return make_mesh(
        MeshConfig(data=1, tensor=tensor), devices=jax.devices()[:tensor]
    )


def shard_for_inference(model: Transformer, params: Any, mesh) -> Any:
    """Place a param tree into its tensor-parallel serving layout.

    Logical axes come from an abstract init (``eval_shape`` — nothing
    materializes), so this works for BOTH fresh boxed trees and plain trees
    restored from a checkpoint / reference msgpack import. zero_stage=0:
    serving has no optimizer state to shard and no data axis."""
    from zero_transformer_tpu.parallel import sharding as shd
    from zero_transformer_tpu.utils.jax_compat import clear_abstract_mesh

    # clear any ambient mesh for the abstract init (same hazard as
    # init_cache below: flax boxing would read logical names as mesh axes)
    with clear_abstract_mesh():
        abstract = jax.eval_shape(
            lambda r: model.init(r, jnp.zeros((1, 8), jnp.int32)),
            jax.random.PRNGKey(0),
        )["params"]
    shardings = shd.param_sharding(
        mesh, shd.unbox(abstract), shd.logical_specs(abstract), zero_stage=0
    )
    from zero_transformer_tpu.utils.jax_compat import ensure_donatable

    # restored/imported param trees are host numpy; device_put of host
    # memory can be zero-copy — force runtime ownership once at placement
    # so no downstream consumer can donate an unowned buffer
    return ensure_donatable(jax.device_put(shd.unbox(params), shardings))


def init_cache(model: Transformer, batch: int, rng=None, mesh=None) -> Any:
    """Allocate the zeroed cache collection for a [batch, cache_len] run.

    Shapes come from ``eval_shape`` (no parameter materialization — a fresh
    full ``model.init`` here would transiently double peak HBM on large
    models); the cache contents are genuinely zeros + zero indices, which is
    exactly what a fresh init produces.

    With ``mesh``, K/V buffers (and int8 scales), shaped [..., KVH, D] with
    per-layer [B, T] leading dims (plus a layer axis under the scanned
    stack), are laid out sharded over the tensor axis on the KV-heads dim —
    committed up front so the decode loop's cache carry never round-trips
    through a GSPMD-guessed layout."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    # shape derivation runs with the AMBIENT mesh cleared: under
    # jax.set_mesh, flax's with_partitioning boxing would interpret the
    # params' LOGICAL axis names ('vocab', 'embed', ...) as mesh axes and
    # fail NamedSharding validation — the logical->mesh translation is this
    # repo's sharding module's job, not flax's
    from zero_transformer_tpu.utils.jax_compat import clear_abstract_mesh

    with clear_abstract_mesh():
        shapes = jax.eval_shape(
            lambda r: model.init(r, jnp.zeros((batch, 1), jnp.int32)), rng
        )["cache"]
    if mesh is None:
        return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)
    from jax.sharding import NamedSharding, PartitionSpec as P

    from zero_transformer_tpu.parallel.mesh import TENSOR_AXIS

    tp = mesh.shape[TENSOR_AXIS]
    # KV buffers end [..., KVH, D] and their int8 scales [..., KVH, 1]
    # (leading dims: [B, T] per layer, plus a layer axis up front under the
    # scanned stack) — KVH is dim -2 in EVERY layout; indexing it from the
    # front silently sharded the cache's sequence dim on scanned models.
    # Keyed by LEAF NAME, not shape-sniffing — a future cache entry with a
    # different layout must not be silently mis-sharded.
    kv_leaves = {"cached_key", "cached_value", "key_scale", "value_scale"}
    if tp > 1:
        kvh = {s.shape[-2] for p, s in jax.tree_util.tree_leaves_with_path(shapes)
               if str(p[-1].key if hasattr(p[-1], "key") else p[-1]) in kv_leaves}
        bad = {h for h in kvh if h % tp != 0}
        if bad:
            # the params ARE tensor-sharded in this configuration, so a
            # replicated cache silently forfeits the HBM win the mesh was
            # requested for — make the GQA/tensor mismatch visible
            import warnings

            warnings.warn(
                f"KV cache stays REPLICATED: kv head count(s) {sorted(bad)} "
                f"not divisible by tensor={tp}; each chip holds the full "
                "cache while params are sharded. Pick tensor dividing the "
                "KV-head count (GQA) to shard the cache.",
                stacklevel=2,
            )

    def place(path, s):
        leaf = str(path[-1].key if hasattr(path[-1], "key") else path[-1])
        spec = P()
        if leaf in kv_leaves and tp > 1 and s.shape[-2] % tp == 0:
            spec = P(*([None] * (s.ndim - 2)), TENSOR_AXIS, None)
        return jax.device_put(
            jnp.zeros(s.shape, s.dtype), NamedSharding(mesh, spec)
        )

    from zero_transformer_tpu.utils.jax_compat import ensure_donatable

    # the cache is DONATED by prefill/decode_step/the engine's fused step;
    # device_put output must be runtime-owned before the first donating
    # dispatch (jax 0.4.37 zero-copy class — jax_compat.ensure_donatable).
    # Leaf-by-leaf add-0, so the transient peak is one extra leaf, not 2x
    # the cache.
    return ensure_donatable(jax.tree_util.tree_map_with_path(place, shapes))


def _in_mesh(mesh, fn, *args, **kwargs):
    """Call ``fn`` under ``jax.set_mesh(mesh)`` (no-op when mesh is None)."""
    if mesh is None:
        return fn(*args, **kwargs)
    from zero_transformer_tpu.utils.jax_compat import set_mesh

    with set_mesh(mesh):
        return fn(*args, **kwargs)


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(3,))
def prefill(
    model: Transformer, params: Any, prompt: jax.Array, cache: Any
) -> Tuple[jax.Array, Any]:
    """Run the prompt through the model, filling the cache.

    Returns (last-position logits [B, V], cache)."""
    logits, vars_out = model.apply(
        {"params": params, "cache": cache}, prompt, mutable=["cache"]
    )
    return logits[:, -1, :].astype(jnp.float32), vars_out["cache"]


def generate(
    model: Transformer,
    params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    rng: jax.Array,
    sampling: SamplingConfig = SamplingConfig(),
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
    mesh=None,
) -> jax.Array:
    """Generate ``max_new_tokens`` continuations for a [B, T] prompt.

    Returns [B, max_new_tokens] int32. Rows that hit ``eos_token_id`` are
    padded with ``pad_token_id`` afterwards; the loop exits early once every
    row is done (the reference's EOS handling, ``app.py:79-92``, single-row).

    ``mesh`` (from ``serve_mesh``) runs the decode tensor-parallel: pass
    params through ``shard_for_inference`` first; prefill and the decode
    loop then trace under the ambient mesh so activation constraints
    (heads/mlp over tensor) apply.
    """

    def run():
        last_logits, cache, gen_mask = _start_decode(
            model, params, prompt, max_new_tokens, mesh
        )
        return _decode_loop(
            model,
            max_new_tokens,
            sampling,
            -1 if eos_token_id is None else int(eos_token_id),
            int(pad_token_id),
            params,
            last_logits,
            cache,
            gen_mask,
            rng,
        )

    if mesh is not None:
        from zero_transformer_tpu.utils.jax_compat import set_mesh

        with set_mesh(mesh):
            return run()
    return run()


def _start_decode(
    model: Transformer,
    params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    mesh=None,
):
    """Shared guards + prefill for ``generate`` and ``stream_tokens`` (one
    source of truth — the two entry points must never diverge on bounds)."""
    cache_len = model.cache_len or model.cfg.max_seq_len
    B, T = prompt.shape
    # the final sampled token is never fed back, so cache holds T+max_new-1
    if T + max_new_tokens - 1 > cache_len:
        raise ValueError(
            f"prompt ({T}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"cache_len ({cache_len})"
        )
    if model.cfg.position == "learned" and T + max_new_tokens > model.cfg.max_seq_len:
        # the wpe table cannot extrapolate; traced decode positions past it
        # would silently clamp to the last row (XLA gather semantics)
        raise ValueError(
            f"prompt ({T}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_seq_len ({model.cfg.max_seq_len}) and learned positions "
            "cannot extrapolate (use position='alibi' or 'rope')"
        )
    cache = init_cache(model, B, mesh=mesh)
    last_logits, cache = prefill(model, params, prompt, cache)
    # presence mask of *generated* tokens for the repetition penalty
    # (reference penalizes generated tokens only, app.py:75,85-88)
    gen_mask = jnp.zeros((B, last_logits.shape[-1]), jnp.bool_)
    return last_logits, cache, gen_mask


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def _decode_loop(
    model: Transformer,
    max_new_tokens: int,
    sampling: SamplingConfig,
    eos_token_id: int,
    pad_token_id: int,
    params: Any,
    last_logits: jax.Array,
    cache: Any,
    gen_mask: jax.Array,
    rng: jax.Array,
):
    B = last_logits.shape[0]
    out = jnp.full((B, max_new_tokens), pad_token_id, jnp.int32)
    done = jnp.zeros((B,), jnp.bool_)

    def cond(carry):
        step, _, _, _, done, _, _ = carry
        return (step < max_new_tokens) & ~jnp.all(done)

    def body(carry):
        step, logits, cache, gen_mask, done, out, rng = carry
        rng, sub = jax.random.split(rng)
        token = sample_token(sub, logits, sampling, gen_mask)
        is_eos = token == eos_token_id
        emitted = jnp.where(done, pad_token_id, token)
        out = jax.lax.dynamic_update_slice(out, emitted[:, None], (0, step))
        newly = jax.nn.one_hot(token, gen_mask.shape[1], dtype=jnp.bool_)
        gen_mask = gen_mask | (newly & ~done[:, None])
        done = done | is_eos

        def forward(cache):
            next_logits, vars_out = model.apply(
                {"params": params, "cache": cache}, token[:, None], mutable=["cache"]
            )
            return next_logits[:, -1, :].astype(jnp.float32), vars_out["cache"]

        # the last emitted token is never fed back — skip its forward
        logits, cache = jax.lax.cond(
            (step + 1 < max_new_tokens) & ~jnp.all(done),
            forward,
            lambda cache: (logits, cache),
            cache,
        )
        return (step + 1, logits, cache, gen_mask, done, out, rng)

    carry = (0, last_logits, cache, gen_mask, done, out, rng)
    _, _, _, _, _, out, _ = jax.lax.while_loop(cond, body, carry)
    return out


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(3,))
def _stream_sample(sampling, rng, logits, gen_mask):
    token = sample_token(rng, logits, sampling, gen_mask)
    newly = jax.nn.one_hot(token, gen_mask.shape[1], dtype=jnp.bool_)
    return token, gen_mask | newly


@functools.partial(jax.jit, static_argnums=(0, 1), donate_argnums=(4, 5, 6))
def _stream_step(model, sampling, params, token, cache, gen_mask, rng):
    """Fused per-token stream step: forward the PREVIOUS token through the
    cache, then process + sample from the fresh logits — attention →
    logits → sample in ONE dispatch. The pre-kernel-lane stream paid two
    dispatches per token (a standalone sample jit plus the cached
    forward); the fused form halves the per-token dispatch count while
    emitting the IDENTICAL token chain (same rng split order)."""
    logits, vars_out = model.apply(
        {"params": params, "cache": cache}, token[:, None], mutable=["cache"]
    )
    logits = logits[:, -1, :].astype(jnp.float32)
    rng, sub = jax.random.split(rng)
    token = sample_token(sub, logits, sampling, gen_mask)
    newly = jax.nn.one_hot(token, gen_mask.shape[1], dtype=jnp.bool_)
    return token, vars_out["cache"], gen_mask | newly, rng


def stream_tokens(
    model: Transformer,
    params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    rng: jax.Array,
    sampling: SamplingConfig = SamplingConfig(),
    eos_token_id: Optional[int] = None,
    mesh=None,
):
    """Yield tokens one step at a time (a [B] int32 array per yield).

    The per-token host round trip the reference's UI loop paid for every
    request (reference ``app.py:69-94``) — here an explicit OPT-IN for
    interactive streaming; use ``generate`` (single compiled while_loop) for
    throughput. Each step is a jitted sample + a jitted cached forward
    (``prefill`` on the [B, 1] token — same compiled path; the FINAL token's
    forward is skipped, matching ``generate``); rows that hit
    ``eos_token_id`` stop the stream when ALL rows are done (callers doing
    single-row streaming just break on their own EOS).

    Since the kernel lane (PR 11) each token past the first costs ONE
    dispatch (``_stream_step``: forward + sample fused); the first token
    samples from the prefill logits. The final token's forward is still
    skipped and the rng split chain is unchanged, so the emitted tokens are
    bit-identical to the pre-fusion stream and to ``generate``.
    """
    # the mesh context is scoped per CALL, never across a yield: a generator
    # suspended inside a `with jax.set_mesh(...)` would leak the ambient mesh
    # into the caller's context, and the ambient mesh keys the jit cache, so
    # it must be identically present on every invocation
    logits, cache, gen_mask = _in_mesh(
        mesh, _start_decode, model, params, prompt, max_new_tokens, mesh
    )
    B = prompt.shape[0]
    done = jnp.zeros((B,), jnp.bool_)
    rng, sub = jax.random.split(rng)
    token, gen_mask = _stream_sample(sampling, sub, logits, gen_mask)
    for step in range(max_new_tokens):
        yield token
        if eos_token_id is not None:
            done = done | (token == eos_token_id)
            if bool(jnp.all(done)):
                return
        if step + 1 < max_new_tokens:  # the last token is never fed back
            token, cache, gen_mask, rng = _in_mesh(
                mesh, _stream_step, model, sampling, params, token, cache,
                gen_mask, rng,
            )


def generate_tokens(
    cfg: ModelConfig,
    params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    rng: Optional[jax.Array] = None,
    cache_len: Optional[int] = None,
    **kwargs,
) -> jax.Array:
    """Convenience wrapper: build the decode model and generate."""
    if prompt.ndim == 1:
        prompt = prompt[None, :]
    total = prompt.shape[1] + max_new_tokens
    cache_len = cache_len or max(cfg.max_seq_len, total)
    model = decode_model(cfg, cache_len)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    return generate(model, params, prompt, max_new_tokens, rng, **kwargs)
