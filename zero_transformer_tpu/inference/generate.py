"""KV-cached autoregressive generation, fully under jit.

In-tree JAX replacement for the reference's CUDA-only inference stack
(reference ``torch_compatability/GPT2.py:354-445`` ``generate``/KV cache and
``app.py:42-94`` streaming loop). Design differences, TPU-first:

- ONE compiled program for prefill and one for the whole decode loop
  (``lax.while_loop`` with a fixed-shape cache and early exit when every
  sequence hits EOS) — the reference re-enters Python per token;
- the KV cache is preallocated [B, cache_len] (model's ``decode=True``
  variant), so shapes are static and XLA never re-tiles — the reference's
  torch path instead rebuilds its ALiBi mask whenever the context grows
  (``GPT2.py:191-235``);
- batch generation is native: [B, T] prompts in, [B, max_new_tokens] out,
  per-row EOS masking; the reference generates one sequence at a time.
"""
from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from zero_transformer_tpu.config import ModelConfig
from zero_transformer_tpu.inference.sampling import SamplingConfig, sample_token
from zero_transformer_tpu.models.gpt import Transformer


def decode_model(cfg: ModelConfig, cache_len: int) -> Transformer:
    """The KV-cache variant of the model (same params as the training one)."""
    return Transformer(cfg, decode=True, cache_len=cache_len)


def init_cache(model: Transformer, batch: int, rng=None) -> Any:
    """Allocate the zeroed cache collection for a [batch, cache_len] run.

    Shapes come from ``eval_shape`` (no parameter materialization — a fresh
    full ``model.init`` here would transiently double peak HBM on large
    models); the cache contents are genuinely zeros + zero indices, which is
    exactly what a fresh init produces."""
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    shapes = jax.eval_shape(
        lambda r: model.init(r, jnp.zeros((batch, 1), jnp.int32)), rng
    )["cache"]
    return jax.tree.map(lambda s: jnp.zeros(s.shape, s.dtype), shapes)


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(3,))
def prefill(
    model: Transformer, params: Any, prompt: jax.Array, cache: Any
) -> Tuple[jax.Array, Any]:
    """Run the prompt through the model, filling the cache.

    Returns (last-position logits [B, V], cache)."""
    logits, vars_out = model.apply(
        {"params": params, "cache": cache}, prompt, mutable=["cache"]
    )
    return logits[:, -1, :].astype(jnp.float32), vars_out["cache"]


def generate(
    model: Transformer,
    params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    rng: jax.Array,
    sampling: SamplingConfig = SamplingConfig(),
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
) -> jax.Array:
    """Generate ``max_new_tokens`` continuations for a [B, T] prompt.

    Returns [B, max_new_tokens] int32. Rows that hit ``eos_token_id`` are
    padded with ``pad_token_id`` afterwards; the loop exits early once every
    row is done (the reference's EOS handling, ``app.py:79-92``, single-row).
    """
    last_logits, cache, gen_mask = _start_decode(
        model, params, prompt, max_new_tokens
    )
    return _decode_loop(
        model,
        max_new_tokens,
        sampling,
        -1 if eos_token_id is None else int(eos_token_id),
        int(pad_token_id),
        params,
        last_logits,
        cache,
        gen_mask,
        rng,
    )


def _start_decode(model: Transformer, params: Any, prompt: jax.Array, max_new_tokens: int):
    """Shared guards + prefill for ``generate`` and ``stream_tokens`` (one
    source of truth — the two entry points must never diverge on bounds)."""
    cache_len = model.cache_len or model.cfg.max_seq_len
    B, T = prompt.shape
    # the final sampled token is never fed back, so cache holds T+max_new-1
    if T + max_new_tokens - 1 > cache_len:
        raise ValueError(
            f"prompt ({T}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"cache_len ({cache_len})"
        )
    if model.cfg.position == "learned" and T + max_new_tokens > model.cfg.max_seq_len:
        # the wpe table cannot extrapolate; traced decode positions past it
        # would silently clamp to the last row (XLA gather semantics)
        raise ValueError(
            f"prompt ({T}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_seq_len ({model.cfg.max_seq_len}) and learned positions "
            "cannot extrapolate (use position='alibi' or 'rope')"
        )
    cache = init_cache(model, B)
    last_logits, cache = prefill(model, params, prompt, cache)
    # presence mask of *generated* tokens for the repetition penalty
    # (reference penalizes generated tokens only, app.py:75,85-88)
    gen_mask = jnp.zeros((B, last_logits.shape[-1]), jnp.bool_)
    return last_logits, cache, gen_mask


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4))
def _decode_loop(
    model: Transformer,
    max_new_tokens: int,
    sampling: SamplingConfig,
    eos_token_id: int,
    pad_token_id: int,
    params: Any,
    last_logits: jax.Array,
    cache: Any,
    gen_mask: jax.Array,
    rng: jax.Array,
):
    B = last_logits.shape[0]
    out = jnp.full((B, max_new_tokens), pad_token_id, jnp.int32)
    done = jnp.zeros((B,), jnp.bool_)

    def cond(carry):
        step, _, _, _, done, _, _ = carry
        return (step < max_new_tokens) & ~jnp.all(done)

    def body(carry):
        step, logits, cache, gen_mask, done, out, rng = carry
        rng, sub = jax.random.split(rng)
        token = sample_token(sub, logits, sampling, gen_mask)
        is_eos = token == eos_token_id
        emitted = jnp.where(done, pad_token_id, token)
        out = jax.lax.dynamic_update_slice(out, emitted[:, None], (0, step))
        newly = jax.nn.one_hot(token, gen_mask.shape[1], dtype=jnp.bool_)
        gen_mask = gen_mask | (newly & ~done[:, None])
        done = done | is_eos

        def forward(cache):
            next_logits, vars_out = model.apply(
                {"params": params, "cache": cache}, token[:, None], mutable=["cache"]
            )
            return next_logits[:, -1, :].astype(jnp.float32), vars_out["cache"]

        # the last emitted token is never fed back — skip its forward
        logits, cache = jax.lax.cond(
            (step + 1 < max_new_tokens) & ~jnp.all(done),
            forward,
            lambda cache: (logits, cache),
            cache,
        )
        return (step + 1, logits, cache, gen_mask, done, out, rng)

    carry = (0, last_logits, cache, gen_mask, done, out, rng)
    _, _, _, _, _, out, _ = jax.lax.while_loop(cond, body, carry)
    return out


@functools.partial(jax.jit, static_argnums=(0,), donate_argnums=(3,))
def _stream_sample(sampling, rng, logits, gen_mask):
    token = sample_token(rng, logits, sampling, gen_mask)
    newly = jax.nn.one_hot(token, gen_mask.shape[1], dtype=jnp.bool_)
    return token, gen_mask | newly


def stream_tokens(
    model: Transformer,
    params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    rng: jax.Array,
    sampling: SamplingConfig = SamplingConfig(),
    eos_token_id: Optional[int] = None,
):
    """Yield tokens one step at a time (a [B] int32 array per yield).

    The per-token host round trip the reference's UI loop paid for every
    request (reference ``app.py:69-94``) — here an explicit OPT-IN for
    interactive streaming; use ``generate`` (single compiled while_loop) for
    throughput. Each step is a jitted sample + a jitted cached forward
    (``prefill`` on the [B, 1] token — same compiled path; the FINAL token's
    forward is skipped, matching ``generate``); rows that hit
    ``eos_token_id`` stop the stream when ALL rows are done (callers doing
    single-row streaming just break on their own EOS).
    """
    logits, cache, gen_mask = _start_decode(model, params, prompt, max_new_tokens)
    B = prompt.shape[0]
    done = jnp.zeros((B,), jnp.bool_)
    for step in range(max_new_tokens):
        rng, sub = jax.random.split(rng)
        token, gen_mask = _stream_sample(sampling, sub, logits, gen_mask)
        yield token
        if eos_token_id is not None:
            done = done | (token == eos_token_id)
            if bool(jnp.all(done)):
                return
        if step + 1 < max_new_tokens:  # the last token is never fed back
            logits, cache = prefill(model, params, token[:, None], cache)


def generate_tokens(
    cfg: ModelConfig,
    params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    rng: Optional[jax.Array] = None,
    cache_len: Optional[int] = None,
    **kwargs,
) -> jax.Array:
    """Convenience wrapper: build the decode model and generate."""
    if prompt.ndim == 1:
        prompt = prompt[None, :]
    total = prompt.shape[1] + max_new_tokens
    cache_len = cache_len or max(cfg.max_seq_len, total)
    model = decode_model(cfg, cache_len)
    rng = rng if rng is not None else jax.random.PRNGKey(0)
    return generate(model, params, prompt, max_new_tokens, rng, **kwargs)
