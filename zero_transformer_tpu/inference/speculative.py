"""Self-speculative decoding via prompt lookup (no draft model).

Decode on TPU is HBM-bandwidth-bound: a 1-token step and a (K+1)-token step
read the same weight bytes, so verifying K drafted tokens in ONE cached
forward multiplies throughput by the acceptance length. The drafts come from
the sequence's own history — the K tokens that followed the most recent
earlier occurrence of the current bigram ("prompt lookup decoding") — which
is free and surprisingly accurate on the repetitive text that dominates
summarization/extraction/code serving. The reference has nothing comparable
(its decode is a per-token Python loop, ``app.py:69-94``).

Exactness contract: greedy speculative output is IDENTICAL to greedy
one-token-at-a-time decode — acceptance keeps a drafted token only when it
equals the model's own argmax given the verified prefix, so the emitted
sequence is the plain greedy sequence by construction (tested).

Mechanics (one ``lax.while_loop``, all shapes static):
- carry the confirmed history ``hist`` and the newest confirmed-but-uncached
  token ``c0``;
- draft = the K tokens after the latest earlier occurrence of
  ``(hist[cur-1], c0)``;
- one cached forward on ``[c0, draft…]`` writes K+1 cache slots at offset
  ``cur`` and yields argmaxes ``y``; the accepted prefix is the run of
  ``draft[j] == y[j]``;
- emit accepted drafts + the correction token ``y[n_acc]``, rewind the cache
  index to ``cur + n_acc + 1`` (stale slots beyond it are masked by the
  validity mask and overwritten by the next iteration's writes).

Batch 1 only: per-row acceptance lengths would need per-row cache offsets,
which the fixed-shape cache does not support — and batch-1 latency is
exactly where speculation matters (the serve REPL case).
"""
from __future__ import annotations

import functools
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp

from zero_transformer_tpu.inference.generate import init_cache, prefill
from zero_transformer_tpu.models.gpt import Transformer


def ngram_propose(history, k: int, n: int = 2, skip: int = 1,
                  window: int = 512):
    """Host-side prompt-lookup draft for the SERVING tick: the ``k`` tokens
    that followed the most recent earlier occurrence of ``history``'s final
    ``n``-gram.

    ``skip=1`` offsets the continuation by one token: the serving engine
    samples this tick's first token IN-GRAPH (it is not known when the host
    drafts), so the draft bets the matched continuation's first token IS
    that sample and proposes what follows it. A wrong bet just verifies to
    zero accepted drafts — correctness never depends on draft quality.
    Falls back to zeros (guaranteed-cheap garbage) when history is short or
    no earlier match exists. Pure host lists, run per slot per tick between
    device dispatches — the scan is bounded to the trailing ``window``
    positions so a long-context slot cannot put O(cache_len) of Python on
    the decode hot path (recent history carries the repetition signal
    anyway; a production draft model plugs in via the engine's
    ``draft_fn``).
    """
    if k < 1:
        return []
    hist = [int(t) for t in history]
    H = len(hist)
    best: list = []
    if H > n:
        key = hist[H - n :]
        # most recent earlier occurrence with a FULL k-token continuation
        # (the very latest matches sit so close to the end that their
        # continuation is mostly off-history — on a repetition loop that
        # would propose nothing); fall back to the longest partial one
        floor = max(-1, H - n - 1 - window)
        for start in range(H - n - 1, floor, -1):
            if hist[start : start + n] == key:
                out = hist[start + n + skip : start + n + skip + k]
                if len(out) == k:
                    return out
                if len(out) > len(best):
                    best = out
    return best + [0] * (k - len(best))


def _set_cache_index(cache: Any, value: jax.Array) -> Any:
    """Overwrite every ``cache_index`` leaf (scalar per layer; [L] when the
    layer stack is scanned) with ``value`` — the cache rewind primitive."""

    def one(path, leaf):
        if any(getattr(k, "key", None) == "cache_index" for k in path):
            return jnp.full(leaf.shape, value, leaf.dtype)
        return leaf

    return jax.tree_util.tree_map_with_path(one, cache)


@functools.partial(jax.jit, static_argnums=(0, 1, 2, 3, 4, 5))
def _spec_loop(
    model: Transformer,
    max_new: int,
    K: int,
    eos_token_id: int,
    pad_token_id: int,
    penalty: float,  # repetition penalty (1.0 = off; emulated in acceptance;
    # static — it selects the vectorized vs sequential acceptance branch)
    temperature: jax.Array,  # traced f32 scalar (a serving knob: every value
    # sharing one executable). Mirrored bit-exactly from the plain path: FP
    # division can collapse two near-equal logits into a tie and flip the
    # argmax, so "temperature never changes the argmax" holds in real
    # arithmetic but not in float32 — we apply the SAME transform instead
    # of relying on the claim (x / 1.0 == x exactly, so the default is free)
    params: Any,
    hist0: jax.Array,  # [hist_len] int32: prompt then zeros
    t0: jax.Array,  # scalar: prompt length
    c0_init: jax.Array,  # scalar: first greedy token (already emitted)
    gen_mask0: jax.Array,  # [V] bool: generated-token presence (c0 set)
    cache: Any,
):
    hist_len = hist0.shape[0]
    V = gen_mask0.shape[0]
    out_len = max_new + K + 1  # slack for the fixed-size block writes
    out0 = jnp.full((out_len,), pad_token_id, jnp.int32)
    out0 = out0.at[0].set(c0_init)
    hist0 = jax.lax.dynamic_update_slice(hist0, c0_init[None], (t0,))
    done0 = (eos_token_id >= 0) & (c0_init == eos_token_id)

    def cond(carry):
        _, _, _, _, _, out_pos, done, _, _ = carry
        return (out_pos < max_new) & ~done

    def body(carry):
        c0, hist, cur, cache, out, out_pos, done, n_fwd, gen_mask = carry
        # ---- draft: K tokens after the latest earlier (prev, c0) bigram
        prev = hist[cur - 1]
        pos = jnp.arange(hist_len - 1)
        match = (hist[:-1] == prev) & (hist[1:] == c0) & (pos < cur - 1)
        has_match = jnp.any(match)
        p = jnp.argmax(jnp.where(match, pos, -1))
        start = jnp.where(has_match, p + 2, 0).astype(jnp.int32)
        draft = jax.lax.dynamic_slice(hist, (start,), (K,))

        # ---- one cached forward over [c0, draft...]; KV written at cur
        x_in = jnp.concatenate([c0[None], draft])[None]  # [1, K+1]
        logits, vars_out = model.apply(
            {"params": params, "cache": cache}, x_in, mutable=["cache"]
        )
        cache = vars_out["cache"]
        # same cast-then-divide order as sampling.process_logits
        logits32 = logits[0].astype(jnp.float32) / temperature  # [K+1, V]

        # ---- accepted prefix + correction token
        if penalty == 1.0:
            # pure argmax: acceptance is vectorizable
            y = jnp.argmax(logits32, axis=-1).astype(jnp.int32)
            ok = (draft == y[:K]).astype(jnp.int32)
            n_acc = jnp.sum(jnp.cumprod(ok))
            j = jnp.arange(K + 1)
            block = jnp.where(j == n_acc, y[n_acc], jnp.concatenate([draft, y[-1:]]))
        else:
            # the repetition penalty makes position j's argmax depend on the
            # tokens accepted before it, so acceptance walks the block
            # sequentially with the evolving generated-token mask — exactly
            # the trajectory the plain loop's sample_token takes (temperature
            # is already divided into logits32 above; top-k/top-p only mask
            # non-argmax entries, so they are exactly argmax-neutral)
            draft_ext = jnp.concatenate([draft, jnp.full((1,), -1, jnp.int32)])
            is_last = jnp.arange(K + 1) == K

            from zero_transformer_tpu.inference.sampling import (
                apply_repetition_penalty,
            )

            def acc_step(c, inp):
                mask, accepting, n_acc, corr = c
                row, d_j, last = inp
                # the canonical penalty transform (sampling.py) — the
                # exact-greedy contract requires bit-identical semantics
                pl = apply_repetition_penalty(row, mask, penalty)
                yj = jnp.argmax(pl).astype(jnp.int32)
                take = accepting & ~last & (d_j == yj)
                new_tok = jnp.where(take, d_j, yj)
                mask = jnp.where(
                    accepting, mask | (jnp.arange(V) == new_tok), mask
                )
                corr = jnp.where(accepting & ~take, yj, corr)
                n_acc = n_acc + jnp.where(take, 1, 0)
                return (mask, take, n_acc, corr), None

            (gen_mask, _, n_acc, corr), _ = jax.lax.scan(
                acc_step,
                (gen_mask, jnp.asarray(True), jnp.asarray(0, jnp.int32),
                 jnp.asarray(0, jnp.int32)),
                (logits32, draft_ext, is_last),
            )
            j = jnp.arange(K + 1)
            block = jnp.where(j == n_acc, corr, jnp.concatenate([draft, corr[None]]))
        n_emit = n_acc + 1
        if eos_token_id >= 0:
            hit = (block == eos_token_id) & (j < n_emit)
            first = jnp.argmax(hit)  # first True (0 if none — gated by any)
            n_emit = jnp.where(jnp.any(hit), first + 1, n_emit)
            done = done | jnp.any(hit)

        # ---- commit: out, hist, cache index rewind
        out = jax.lax.dynamic_update_slice(out, block, (out_pos,))
        hist = jax.lax.dynamic_update_slice(hist, block, (cur + 1,))
        cache = _set_cache_index(cache, (cur + n_acc + 1).astype(jnp.int32))
        out_pos = out_pos + n_emit
        done = done | (out_pos >= max_new)
        return (
            block[n_emit - 1], hist, cur + n_emit, cache, out, out_pos, done,
            n_fwd + 1, gen_mask,
        )

    carry = (
        c0_init.astype(jnp.int32), hist0, t0.astype(jnp.int32), cache, out0,
        jnp.asarray(1, jnp.int32), done0, jnp.asarray(0, jnp.int32), gen_mask0,
    )
    c0, hist, cur, cache, out, out_pos, done, n_fwd, _ = jax.lax.while_loop(
        cond, body, carry
    )
    valid = jnp.arange(out_len) < out_pos
    out = jnp.where(valid, out, pad_token_id)[:max_new]
    # rows past an early EOS are pad (mirror generate()'s contract)
    if eos_token_id >= 0:
        hit = out == eos_token_id
        after = jnp.cumsum(hit) - hit.astype(jnp.int32) > 0
        out = jnp.where(after, pad_token_id, out)
    return out[None, :], n_fwd, jnp.minimum(out_pos, max_new)


def generate_speculative(
    model: Transformer,
    params: Any,
    prompt: jax.Array,
    max_new_tokens: int,
    *,
    draft_len: int = 8,
    eos_token_id: Optional[int] = None,
    pad_token_id: int = 0,
    repetition_penalty: float = 1.0,
    temperature: float = 1.0,
    return_stats: bool = False,
) -> jax.Array | Tuple[jax.Array, dict]:
    """Greedy prompt-lookup speculative decode. prompt [1, T] int32.

    Returns [1, max_new_tokens] int32 — identical to
    ``generate(..., SamplingConfig(greedy=True, repetition_penalty=p,
    temperature=t))`` by construction, in fewer model forwards on
    self-similar text. The penalty AND the temperature division are applied
    inside the acceptance walk with the same transforms the plain loop's
    ``sample_token`` uses (FP division can flip an argmax on a collapsed
    tie, so bit-exactness requires mirroring it rather than arguing it
    away); top-k/top-p only mask non-argmax entries and need no emulation.
    ``return_stats`` adds ``{"forwards": n, "tokens_per_forward": ...}``.
    """
    B, T0 = prompt.shape
    if B != 1:
        raise ValueError("speculative decoding supports batch=1 (serve latency path)")
    K = int(draft_len)
    if K < 1:
        raise ValueError("draft_len must be >= 1")
    if not temperature > 0:
        # mirror SamplingConfig.__post_init__: a direct API call with
        # temperature<=0 must fail loudly, not emit inf/NaN-logit garbage
        raise ValueError(f"temperature must be > 0, got {temperature}")
    if not repetition_penalty > 0:
        raise ValueError(
            f"repetition_penalty must be > 0, got {repetition_penalty}"
        )
    cache_len = model.cache_len or model.cfg.max_seq_len
    # worst case writes K+1 slots starting at T0 + max_new - 1
    if T0 + max_new_tokens + K > cache_len:
        raise ValueError(
            f"prompt ({T0}) + max_new_tokens ({max_new_tokens}) + draft_len "
            f"({K}) exceeds cache_len ({cache_len})"
        )
    if model.cfg.position == "learned" and T0 + max_new_tokens > model.cfg.max_seq_len:
        # same guard as generate(): the wpe table cannot extrapolate and the
        # gather would silently clamp — breaking the exact-greedy contract
        raise ValueError(
            f"prompt ({T0}) + max_new_tokens ({max_new_tokens}) exceeds "
            f"max_seq_len ({model.cfg.max_seq_len}) and learned positions "
            "cannot extrapolate (use position='alibi' or 'rope')"
        )
    cache = init_cache(model, 1)
    last_logits, cache = prefill(model, params, prompt, cache)
    # first token: nothing generated yet, so the penalty mask is empty and
    # the temperature-scaled argmax matches the plain loop's first sample
    c0 = jnp.argmax(
        last_logits[0].astype(jnp.float32) / float(temperature)
    ).astype(jnp.int32)
    V = last_logits.shape[-1]
    gen_mask0 = jnp.arange(V) == c0

    hist_len = T0 + max_new_tokens + K + 2
    hist = jnp.zeros((hist_len,), jnp.int32)
    hist = jax.lax.dynamic_update_slice(hist, prompt[0], (0,))
    out, n_fwd, n_emitted = _spec_loop(
        model, int(max_new_tokens), K,
        -1 if eos_token_id is None else int(eos_token_id), int(pad_token_id),
        float(repetition_penalty),
        jnp.asarray(float(temperature), jnp.float32),
        params, hist, jnp.asarray(T0, jnp.int32), c0, gen_mask0, cache,
    )
    if return_stats:
        stats = {
            "forwards": int(n_fwd) + 1,  # + prefill's last-position logits
            "tokens_per_forward": int(n_emitted) / (int(n_fwd) + 1),
        }
        return out, stats
    return out
