"""Flight recorder: a bounded ring of recent tick/step summaries and events,
dumped to the run directory when something goes wrong.

The post-mortem problem it solves: a breaker-open, anomaly halt, watchdog
abort, checkpoint quarantine, or drain happens at 3am with verbose logging
OFF, and the JSONL metrics timeline only has the last log-frequency-aligned
sample. The recorder keeps the last N ticks of context in RAM at all times
(appending a small dict per tick — no IO on the hot path) and serializes the
whole window atomically when an escalation fires, spans included.

Dumps are best-effort by design: a full disk or read-only run directory must
degrade to a logged warning, never take the serving/training loop down with
it (the recorder exists FOR failure windows).
"""
from __future__ import annotations

import json
import logging
import time
from collections import deque
from pathlib import Path
from typing import Any, Dict, List, Optional

log = logging.getLogger("zero_transformer_tpu")


class FlightRecorder:
    """Ring of tick summaries + events with crash-dump serialization.

    ``directory=None`` keeps recording (tests and facades can read the ring)
    but turns ``dump()`` into a counted no-op — dumping needs a run dir.
    """

    def __init__(
        self,
        directory: Optional[str] = None,
        capacity: int = 256,
        tracer=None,
        clock=time.monotonic,
        span_tail: int = 2000,
        max_dumps: int = 64,
    ):
        self.directory = str(directory) if directory else None
        self.tracer = tracer
        self.clock = clock
        self.span_tail = span_tail
        # dump-directory rotation: breaker flaps / repeated ejections / SLO
        # burns each write a dump, and a long-lived replica must not grow
        # flightrec/ without bound — past max_dumps the OLDEST dump this
        # recorder wrote is deleted (the newest always survives)
        self.max_dumps = max(1, int(max_dumps))
        self._ticks: deque = deque(maxlen=capacity)
        self._events: deque = deque(maxlen=capacity)
        self._n_dumps = 0
        self.dumps: List[str] = []  # paths written, oldest first

    # ------------------------------------------------------------- recording

    def tick(self, summary: Dict[str, Any]) -> None:
        """One scheduler-tick / train-step summary (small dict; the caller
        owns the keys — ``tick``/``step`` index at minimum)."""
        self._ticks.append((self.clock(), summary))

    def event(self, name: str, **fields: Any) -> None:
        self._events.append((self.clock(), name, fields))

    # --------------------------------------------------------------- reading

    def ticks(self) -> List[tuple]:
        return list(self._ticks)

    def events(self) -> List[tuple]:
        return list(self._events)

    # ---------------------------------------------------------------- dumps

    def dump(self, reason: str, extra: Optional[Dict[str, Any]] = None
             ) -> Optional[str]:
        """Serialize the ring (ticks, events, span tail) to
        ``<directory>/flightrec/<NNN>_<reason>.json``. Returns the path, or
        None when no directory is configured or the write failed."""
        self._n_dumps += 1
        if self.directory is None:
            return None
        doc = {
            "reason": reason,
            # graftlint: allow[wall-clock-in-span-path] reason=deliberately wall-clock — a post-mortem dump is correlated with external logs by unix time; span timestamps ride clock_now (monotonic) on the next line
            "written_at_unix": time.time(),
            "clock_now": self.clock(),
            "extra": extra or {},
            "ticks": [
                {"t": t, **summary} for t, summary in self._ticks
            ],
            "events": [
                {"t": t, "event": name, **fields}
                for t, name, fields in self._events
            ],
        }
        if self.tracer is not None:
            doc["spans"] = [
                {"track": s[1], "name": s[2], "t0": s[3], "t1": s[4],
                 "attrs": s[5]}
                for s in self.tracer.spans()[-self.span_tail:]
            ]
            doc["spans_dropped"] = self.tracer.dropped
        try:
            out_dir = Path(self.directory) / "flightrec"
            out_dir.mkdir(parents=True, exist_ok=True)
            safe = "".join(c if c.isalnum() or c in "-_" else "_" for c in reason)
            path = out_dir / f"{self._n_dumps:03d}_{safe}.json"
            path.write_text(json.dumps(doc, default=str, indent=1) + "\n")
        except Exception:
            log.exception("flight recorder: dump for %r failed (continuing)", reason)
            return None
        self.dumps.append(str(path))
        while len(self.dumps) > self.max_dumps:
            oldest = self.dumps.pop(0)
            try:
                Path(oldest).unlink()
            except OSError:
                pass  # already gone / permissions: rotation is best-effort
        log.warning("flight recorder: dumped %d ticks / %d events to %s "
                    "(reason: %s)", len(doc["ticks"]), len(doc["events"]),
                    path, reason)
        return str(path)
