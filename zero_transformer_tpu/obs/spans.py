"""Span tracing: a low-overhead, ring-buffered timeline of named intervals.

The serving request lifecycle (admit → queue → prefill chunks → decode
ticks → detok → finish/shed/expire) and the training step loop (data fetch,
dispatch, device sync, checkpoint save, replica audit) both record into one
``Tracer``. Design constraints, in order:

- **hot-path cost**: recording a span is ONE ``deque.append`` of a fixed
  7-tuple — no string formatting, no dict merging, no IO. The ring is
  bounded (``capacity``), so a long-lived server holds the most recent
  window and the overflow is *counted*, never silently unbounded.
- **clock**: timestamps are caller-supplied floats on ONE monotonic clock
  (the engine's ``now()`` / ``time.monotonic``). Spans recorded at finish
  time from timestamps captured earlier are first-class — the request
  lifecycle is emitted as one batch when the request reaches a terminal
  state, so the hot emit path allocates nothing per token.
- **export**: ``chrome_trace()`` renders Perfetto/Chrome ``traceEvents``
  JSON (complete "X" events, one ``tid`` per track); ``write_jsonl``
  appends newly finished spans to a ``spans.jsonl`` beside
  ``metrics.jsonl`` (incremental — safe to call at every log point).

Tracks are correlation keys: ``"engine"`` / ``"train"`` for the scheduler
timelines, the request id for per-request span trees. A request's span tree
is well-nested by construction: the root span is ``[submitted, finished]``
and every phase span is a sub-interval of it.
"""
from __future__ import annotations

import contextlib
import itertools
import json
import logging
import threading
import time
from collections import deque
from pathlib import Path
from typing import Any, Dict, List, Optional, Tuple

log = logging.getLogger("zero_transformer_tpu")

# span record layout (fixed tuple, index-addressed):
# (seq, track, name, t0_s, t1_s, attrs_or_None)
SEQ, TRACK, NAME, T0, T1, ATTRS = range(6)


class Tracer:
    """Bounded span ring. Thread-safe: ``deque.append`` is atomic under the
    GIL and readers snapshot with ``list(ring)``; no lock on the hot path."""

    def __init__(
        self,
        enabled: bool = True,
        capacity: int = 8192,
        clock=time.monotonic,
    ):
        self.enabled = enabled
        self.clock = clock
        self._ring: deque = deque(maxlen=capacity)
        self._seq = itertools.count()
        self._added = 0
        self._capacity = capacity
        # warn ONCE at first overflow: the drop count is exported on
        # /metrics (obs_spans_dropped), but an operator reading logs must
        # also learn that trace truncation started — silently losing the
        # head of every trace is the failure mode this flag makes loud
        self._overflow_warned = False
        # JSONL cursor: seq of the last span already flushed to disk
        self._flushed_seq = -1

    def __len__(self) -> int:
        return len(self._ring)

    @property
    def dropped(self) -> int:
        """Spans pushed out of the ring by overflow (bounded-buffer honesty:
        a trace that silently lost its head must say so)."""
        return max(0, self._added - len(self._ring))

    # ------------------------------------------------------------- recording

    # graftlint: hot-path
    def add(
        self,
        name: str,
        track: str,
        t0: float,
        t1: float,
        attrs: Optional[Dict[str, Any]] = None,
    ) -> None:
        """Record a finished span [t0, t1] (seconds on this tracer's clock)."""
        if not self.enabled:
            return
        self._ring.append((next(self._seq), track, name, t0, t1, attrs))
        self._added += 1
        if self._added > self._capacity and not self._overflow_warned:
            self._overflow_warned = True
            log.warning(
                "tracer: span ring overflowed (capacity %d) — oldest spans "
                "are being dropped; obs_spans_dropped counts them on "
                "/metrics", self._capacity,
            )

    def instant(self, name: str, track: str, t: Optional[float] = None,
                attrs: Optional[Dict[str, Any]] = None) -> None:
        """Zero-duration marker event (renders as a thin slice)."""
        if not self.enabled:
            return
        ts = self.clock() if t is None else t
        self.add(name, track, ts, ts, attrs)

    @contextlib.contextmanager
    def span(self, name: str, track: str = "main", **attrs):
        """Context-manager form for host-side phases. The span is recorded
        even when the body raises — a fault's timeline is the one that
        matters most."""
        if not self.enabled:
            yield
            return
        t0 = self.clock()
        try:
            yield
        finally:
            self.add(name, track, t0, self.clock(), attrs or None)

    # --------------------------------------------------------------- reading

    def spans(self) -> List[tuple]:
        """Snapshot of the current ring, oldest first."""
        return list(self._ring)

    def by_track(self, track: str) -> List[tuple]:
        return [s for s in self._ring if s[TRACK] == track]

    def track_dicts(self, track: Optional[str] = None,
                    tail: Optional[int] = None) -> List[Dict[str, Any]]:
        """Spans as JSON-ready dicts (the /admin/spans wire shape and the
        stitching input): one track's spans, or the whole ring tail."""
        spans = self.by_track(track) if track is not None else self.spans()
        if tail is not None:
            spans = spans[-tail:]
        return [span_dict(s) for s in spans]

    # --------------------------------------------------------------- export

    def chrome_trace(self, tail: Optional[int] = None) -> Dict[str, Any]:
        """Perfetto/Chrome ``traceEvents`` document (complete events).

        ``ts``/``dur`` are microseconds; each track gets its own ``tid``
        plus a ``thread_name`` metadata event so Perfetto labels the rows.
        """
        spans = self.spans()
        if tail is not None:
            spans = spans[-tail:]
        tids: Dict[str, int] = {}
        events: List[dict] = []
        for s in spans:
            tid = tids.get(s[TRACK])
            if tid is None:
                tid = tids[s[TRACK]] = len(tids) + 1
            ev = {
                "ph": "X",
                "name": s[NAME],
                "cat": s[TRACK],
                "ts": s[T0] * 1e6,
                "dur": max(0.0, (s[T1] - s[T0]) * 1e6),
                "pid": 0,
                "tid": tid,
            }
            if s[ATTRS]:
                ev["args"] = s[ATTRS]
            events.append(ev)
        meta = [
            {
                "ph": "M",
                "name": "thread_name",
                "pid": 0,
                "tid": tid,
                "args": {"name": track},
            }
            for track, tid in tids.items()
        ]
        return {
            "traceEvents": meta + events,
            "displayTimeUnit": "ms",
            "otherData": {"dropped_spans": self.dropped},
        }

    def write_chrome_trace(self, path, tail: Optional[int] = None) -> str:
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(json.dumps(self.chrome_trace(tail=tail)) + "\n")
        return str(path)

    def write_jsonl(self, path) -> int:
        """Append spans not yet flushed (incremental: call at log points).
        Returns the number of spans written."""
        fresh = [s for s in self.spans() if s[SEQ] > self._flushed_seq]
        if not fresh:
            return 0
        path = Path(path)
        path.parent.mkdir(parents=True, exist_ok=True)
        with open(path, "a") as f:
            for s in fresh:
                f.write(json.dumps({
                    "track": s[TRACK],
                    "name": s[NAME],
                    "t0": s[T0],
                    "t1": s[T1],
                    "dur_ms": round((s[T1] - s[T0]) * 1e3, 6),
                    "attrs": s[ATTRS],
                }) + "\n")
        self._flushed_seq = fresh[-1][SEQ]
        return len(fresh)


def span_dict(s: tuple) -> Dict[str, Any]:
    """One ring record as the cross-process wire/stitch shape."""
    return {
        "track": s[TRACK], "name": s[NAME], "t0": s[T0], "t1": s[T1],
        "attrs": s[ATTRS],
    }


def span_tree(spans: List[tuple], track: str) -> Dict[str, Any]:
    """Assemble one track's spans into {root, children} where root is the
    span named ``request`` (the full lifetime) — the shape the span-parity
    tests assert on. Returns {} when the track has no root."""
    mine = [s for s in spans if s[TRACK] == track]
    root = next((s for s in mine if s[NAME] == "request"), None)
    if root is None:
        return {}
    children = [s for s in mine if s is not root]
    return {"root": root, "children": children}


def coverage_fraction(tree: Dict[str, Any]) -> float:
    """Fraction of the root span's wall time covered by the union of its
    child spans (the >=95% acceptance bar). Children are clamped into the
    root interval and overlaps merged, so the result is in [0, 1]."""
    root = tree.get("root")
    if root is None:
        return 0.0
    r0, r1 = root[T0], root[T1]
    if r1 <= r0:
        return 1.0  # zero-length lifetime (e.g. rejected at submit)
    ivs = sorted(
        (max(r0, s[T0]), min(r1, s[T1])) for s in tree["children"]
    )
    covered = 0.0
    cur0 = cur1 = None
    for a, b in ivs:
        if b < a:
            continue
        if cur0 is None:
            cur0, cur1 = a, b
        elif a <= cur1:
            cur1 = max(cur1, b)
        else:
            covered += cur1 - cur0
            cur0, cur1 = a, b
    if cur0 is not None:
        covered += cur1 - cur0
    return covered / (r1 - r0)
