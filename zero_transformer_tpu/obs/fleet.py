"""Fleet-level observability: cross-process trace stitching, /metrics
aggregation, and the per-request cost ledger (PR 15).

PR 7's ``obs/`` layer is strictly per-process: each router/replica process
holds its own span ring, its own Prometheus registry, its own flight
recorder. A disaggregated request (router -> prefill replica -> page ship ->
decode replica -> attach) therefore leaves four disjoint span files and four
``/metrics`` endpoints, and nothing answers "where did this request's
latency go" at the level the control decisions (routing, autoscaling,
tuning) are made. This module is the stitch layer:

- **trace stitching**: every process already keys a request's spans on the
  propagated ``X-Request-Id``; the router pulls each replica's span tail
  (``GET /admin/spans?request_id=``), maps the remote monotonic clocks onto
  its own via the per-replica offset estimated from probe round-trips, and
  merges everything into ONE Perfetto document with one ``pid`` per process
  — ``verify_stitched`` then checks the merged tree programmatically
  (wall-latency coverage, orphan spans, hop ordering);
- **metrics aggregation**: ``parse_exposition`` reads the replica's
  ``text/plain; version=0.0.4`` scrape and ``FleetAggregator`` folds the
  per-replica families into fleet rollups (counters/histograms summed,
  gauges summed or maxed per ``MAX_GAUGES``) with per-role and per-replica
  labels, rendered as ``fleet_*`` families on the router's own /metrics;
- **cost ledger**: the schema for the per-request resource ledger the
  engine accumulates on its tick thread and the router completes with
  fleet-side fields, plus ``TenantLedger`` — the bounded per-tenant rollup
  the router exposes so capacity decisions stop being guesses.

Everything here is pure stdlib + pure functions where possible; sockets and
threads stay in ``serving/router.py``.
"""
from __future__ import annotations

import json
import logging
import re
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

log = logging.getLogger("zero_transformer_tpu")

# ------------------------------------------------------------- cost ledger

# accumulated on the ENGINE's tick thread (plain-int increments; the dict
# rides the request handle and ships with the page span on migration, so
# the counts stay cumulative across replicas)
ENGINE_LEDGER_KEYS = (
    "prefill_chunks",     # chunk-prefill dispatches this request paid for
    "decode_ticks",       # decode ticks a slot was held
    "tokens_out",         # tokens emitted to the client
    "draft_tokens",       # speculative drafts proposed for this request
    "accepted_tokens",    # drafts the verify step accepted
    "pages_held_ticks",   # sum over ticks of KV pages held (0 on slab)
    "migrations",         # times the stream's pages crossed processes
    "queue_ms",           # submit -> slot admission
    "prefill_ms",         # admission -> K/V installed
    "decode_ms",          # installed -> terminal state
)

# added by the ROUTER when it builds the terminal event (fleet-side facts a
# replica cannot know)
ROUTER_LEDGER_KEYS = (
    "replicas_crossed",        # distinct replicas that served a hop
    "failovers",               # hops lost to failures
    "attach_hops",             # zero-recompute attach hops followed
    "resume_replayed_tokens",  # tokens re-sent as prompt by the recompute fallback
    "tokens_relayed",          # tokens the router relayed to the client
    "relay_ms",                # client-observed wall time at the router
)

LEDGER_KEYS = ENGINE_LEDGER_KEYS + ROUTER_LEDGER_KEYS

# the schema-pinned payload contracts (tests/test_serve_bench.py): a
# terminal event's ledger and a /slo response must carry at least these
FLEET_OBS_REQUIRED_KEYS = {
    "ledger": set(LEDGER_KEYS),
    "slo": {"objectives", "verdict", "evaluated", "window_clipped"},
}


def new_engine_ledger() -> Dict[str, float]:
    return {k: 0 for k in ENGINE_LEDGER_KEYS}


def complete_ledger(
    engine_ledger: Optional[Dict[str, Any]],
    **router_fields: Any,
) -> Dict[str, Any]:
    """The terminal event's ledger: the engine's cumulative counters (zeros
    when a hop died before its done event could deliver them) plus the
    router-side fields. Every LEDGER_KEYS key is always present."""
    out: Dict[str, Any] = {k: 0 for k in LEDGER_KEYS}
    if isinstance(engine_ledger, dict):
        for k in ENGINE_LEDGER_KEYS:
            try:
                out[k] = round(float(engine_ledger.get(k, 0)), 3)
            except (TypeError, ValueError):
                out[k] = 0
    for k in ROUTER_LEDGER_KEYS:
        if k in router_fields:
            out[k] = router_fields[k]
    return out


class TenantLedger:
    """Bounded per-tenant rollup of completed-request ledgers. The router
    records every terminal event's ledger under its tenant key (the
    ``X-Tenant-Key`` header / ``tenant`` body field, ``anon`` otherwise);
    a capacity question ("who is burning the pages?") becomes one scrape.
    LRU-bounded so a tenant-id cardinality attack cannot balloon the
    router."""

    def __init__(self, capacity: int = 1024, on_evict=None):
        from collections import OrderedDict

        self.capacity = max(1, int(capacity))
        # true LRU: record() refreshes recency, so a key-churn flood
        # evicts idle one-off tenants, never the continuously active one
        self._totals: "OrderedDict[str, Dict[str, float]]" = OrderedDict()
        self._lock = threading.Lock()
        # eviction is billing-data loss — never silent: count it and let
        # the owner (the router) turn each drop into a flight event
        self.on_evict = on_evict
        self.evictions = 0

    def record(self, tenant: str, ledger: Dict[str, Any]) -> None:
        tenant = str(tenant or "anon")[:64]
        evicted: Optional[str] = None
        with self._lock:
            row = self._totals.get(tenant)
            if row is None:
                if len(self._totals) >= self.capacity:
                    evicted, _ = self._totals.popitem(last=False)  # idle LRU
                    self.evictions += 1
                row = self._totals[tenant] = {k: 0.0 for k in LEDGER_KEYS}
                row["requests"] = 0.0
            self._totals.move_to_end(tenant)
            row["requests"] += 1
            for k in LEDGER_KEYS:
                try:
                    row[k] += float(ledger.get(k, 0) or 0)
                except (TypeError, ValueError):
                    pass
        if evicted is not None and self.on_evict is not None:
            try:  # outside the lock: the callback may re-enter snapshot()
                self.on_evict(evicted)
            except Exception:
                log.exception("tenant ledger on_evict callback failed")

    def snapshot(self) -> Dict[str, Dict[str, float]]:
        with self._lock:
            return {t: dict(row) for t, row in self._totals.items()}

    def totals(self) -> Dict[str, float]:
        """Fleet-wide aggregate across every tenant (the BENCH artifact's
        ``ledger`` block)."""
        agg = {k: 0.0 for k in LEDGER_KEYS}
        agg["requests"] = 0.0
        for row in self.snapshot().values():
            for k, v in row.items():
                agg[k] = agg.get(k, 0.0) + v
        return agg

    def samples(self, key: str) -> List[Tuple[Dict[str, str], float]]:
        """``[({"tenant": t}, value)]`` rows for a labeled gauge_func."""
        return [
            ({"tenant": t}, row.get(key, 0.0))
            for t, row in sorted(self.snapshot().items())
        ]


# ------------------------------------------- Prometheus exposition parsing

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(.*)\})?"
    r" (NaN|[+-]Inf|[-+]?[0-9]*\.?[0-9]+(?:[eE][-+]?[0-9]+)?)$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def parse_exposition(text: str) -> Dict[str, Dict[str, Any]]:
    """Parse a ``text/plain; version=0.0.4`` scrape into
    ``{family: {"type": t, "help": h, "samples": [(labels, value)]}}``.

    Histogram sub-series (``_bucket``/``_sum``/``_count``) fold under their
    base family name so one entry carries the whole histogram."""
    fams: Dict[str, Dict[str, Any]] = {}
    types: Dict[str, str] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3]
            elif len(parts) >= 4 and parts[1] == "HELP":
                fams.setdefault(
                    parts[2], {"type": "untyped", "help": "", "samples": []}
                )["help"] = parts[3]
            continue
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, labels_raw, value_raw = m.groups()
        base = name
        sub = ""
        for suffix in ("_bucket", "_sum", "_count"):
            stem = name[: -len(suffix)] if name.endswith(suffix) else None
            if stem and types.get(stem) == "histogram":
                base, sub = stem, suffix[1:]
                break
        fam = fams.setdefault(
            base, {"type": "untyped", "help": "", "samples": []}
        )
        labels = (
            {k: v.replace('\\"', '"').replace("\\\\", "\\")
             for k, v in _LABEL_RE.findall(labels_raw)}
            if labels_raw else {}
        )
        if sub:
            labels["__sub__"] = sub
        try:
            value = float(value_raw)
        except ValueError:
            continue
        fam["samples"].append((labels, value))
    for base, t in types.items():
        if base in fams:
            fams[base]["type"] = t
    return fams


# gauges where the honest fleet rollup is the MAX, not the sum (a fleet's
# uptime is its oldest replica, its breaker state is "any open", its ITL
# estimate is the slowest replica a request could land on)
MAX_GAUGES = frozenset({
    "serve_uptime_seconds",
    "serve_breaker_open",
    "serve_itl_ewma_seconds",
    "serve_page_pool_util",
    "hbm_used_gigabytes_max",
    "obs_spans_dropped",
    "serve_trace_spans_dropped",
})


class FleetAggregator:
    """Fold per-replica /metrics scrapes into fleet rollups.

    ``update(replica, role, text)`` stores one replica's latest parsed
    scrape; ``render()`` emits every family as ``fleet_<name>`` with
    per-role series (labels ``{role}``, values folded across the role's
    replicas) AND per-replica series (labels ``{replica, role}``) for
    scalar families, so one scrape of the router sees the whole fleet and
    the per-role sums are pin-testable against the per-replica scrapes
    they fold. Aggregation semantics: counters and histogram
    buckets/sums/counts are SUMMED; gauges are summed except the
    ``MAX_GAUGES`` set, which are MAXED (docs/OBSERVABILITY.md)."""

    def __init__(self):
        self._lock = threading.Lock()
        # replica id -> (role, families)
        self._scrapes: Dict[str, Tuple[str, Dict[str, Dict[str, Any]]]] = {}

    def update(self, replica: str, role: str, text: str) -> None:
        fams = parse_exposition(text)
        with self._lock:
            self._scrapes[replica] = (str(role or "mixed"), fams)

    def drop(self, replica: str) -> None:
        with self._lock:
            self._scrapes.pop(replica, None)

    def replicas(self) -> List[str]:
        with self._lock:
            return list(self._scrapes)

    def _snapshot(self):
        with self._lock:
            return dict(self._scrapes)

    @staticmethod
    def _fold(name: str, mtype: str, values: Sequence[float]) -> float:
        if mtype == "gauge" and name in MAX_GAUGES:
            return max(values) if values else 0.0
        return sum(values)

    def merged(self) -> Dict[str, Dict[str, Any]]:
        """``{family: {"type", "by_role": {(role, labelkey): value},
        "by_replica": {(replica, role, labelkey): value}}}`` where labelkey
        is the family's own labels (``le``, ``device``...) as a sorted
        tuple. The render path and the SLO sources both read this."""
        scrapes = self._snapshot()
        out: Dict[str, Dict[str, Any]] = {}
        for replica, (role, fams) in scrapes.items():
            for name, fam in fams.items():
                entry = out.setdefault(name, {
                    "type": fam["type"], "help": fam["help"],
                    "by_role": {}, "by_replica": {},
                })
                for labels, value in fam["samples"]:
                    key = tuple(sorted(labels.items()))
                    entry["by_role"].setdefault((role, key), []).append(value)
                    entry["by_replica"][(replica, role, key)] = value
        for name, entry in out.items():
            entry["by_role"] = {
                k: self._fold(name, entry["type"], vs)
                for k, vs in entry["by_role"].items()
            }
        return out

    def merged_histogram(self, name: str) -> Optional[Dict[str, Any]]:
        """The fleet-wide histogram for ``name`` (buckets summed across
        replicas): ``{"buckets": [(le, cumulative)], "count", "sum"}`` —
        the SLO engine's latency-objective source. None when no replica
        exported it yet."""
        entry = self.merged().get(name)
        if entry is None or entry["type"] != "histogram":
            return None
        buckets: Dict[str, float] = {}
        count = 0.0
        total = 0.0
        for (_, key), value in entry["by_role"].items():
            labels = dict(key)
            sub = labels.get("__sub__")
            if sub == "bucket":
                le = labels.get("le", "+Inf")
                buckets[le] = buckets.get(le, 0.0) + value
            elif sub == "count":
                count += value
            elif sub == "sum":
                total += value

        def le_key(le: str) -> float:
            return float("inf") if le == "+Inf" else float(le)

        return {
            "buckets": sorted(buckets.items(), key=lambda kv: le_key(kv[0])),
            "count": count,
            "sum": total,
        }

    def good_total_below(self, name: str, threshold: float) -> Optional[Tuple[float, float]]:
        """(good, total) cumulative event counts for a latency objective.

        A cumulative histogram can only be evaluated AT a bucket bound, so
        the threshold rounds UP to the smallest finite bound >= it (a
        2.0 s objective over 1.0/2.5 buckets grades at 2.5 s). Rounding
        down instead would damn every observation in the straddling bucket
        — including ones under the threshold. Declare thresholds on bucket
        bounds (obs.metrics.LATENCY_BUCKETS) for exact grading."""
        hist = self.merged_histogram(name)
        if hist is None or not hist["buckets"]:
            return None
        good = 0.0
        for le, cum in hist["buckets"]:
            bound = float("inf") if le == "+Inf" else float(le)
            if bound != float("inf"):
                good = cum
            if bound >= threshold:
                break
        return good, hist["count"]

    def render(self) -> str:
        """``fleet_*`` exposition text, appended to the router's own
        registry render by the /metrics handler."""
        merged = self.merged()
        lines: List[str] = []
        for name in sorted(merged):
            entry = merged[name]
            mtype = entry["type"] if entry["type"] != "untyped" else "gauge"
            out_name = f"fleet_{name}"
            lines.append(
                f"# HELP {out_name} fleet rollup of {name} "
                f"(per-role + per-replica)"
            )
            lines.append(f"# TYPE {out_name} {mtype}")
            scalar = mtype != "histogram"
            for (role, key), value in sorted(entry["by_role"].items()):
                labels = dict(key)
                sub = labels.pop("__sub__", None)
                labels["role"] = role
                lines.append(self._line(out_name, sub, labels, value))
            if scalar:
                for (replica, role, key), value in sorted(
                    entry["by_replica"].items()
                ):
                    labels = dict(key)
                    sub = labels.pop("__sub__", None)
                    labels["replica"] = replica
                    labels["role"] = role
                    lines.append(self._line(out_name, sub, labels, value))
        return ("\n".join(lines) + "\n") if lines else ""

    @staticmethod
    def _line(name: str, sub: Optional[str], labels: Dict[str, str],
              value: float) -> str:
        if sub:
            name = f"{name}_{sub}"
        inner = ",".join(
            f'{k}="{v}"' for k, v in sorted(labels.items())
        )
        if value == int(value) and abs(value) < 1e15:
            rendered = str(int(value))
        else:
            rendered = format(value, ".10g")
        return f"{name}{{{inner}}} {rendered}" if inner else f"{name} {rendered}"


# ------------------------------------------------------------- clock offset


def estimate_clock_offset(
    remote_clock: float, t0: float, t1: float,
    prev: Optional[Tuple[float, float]] = None,
    max_age_s: float = 30.0,
    now: Optional[float] = None,
) -> Tuple[float, float, float]:
    """One probe round-trip's clock-offset estimate, NTP-style: the remote
    read ``remote_clock`` happened somewhere inside [t0, t1] on the local
    clock, best guess the midpoint, so ``offset = remote - (t0+t1)/2`` with
    uncertainty rtt/2. Keeps the previous estimate when it came from a
    tighter round trip (smaller rtt = smaller error bar), unless it has
    aged out (clocks drift). Returns ``(offset_s, rtt_s, at)``."""
    rtt = max(0.0, t1 - t0)
    offset = remote_clock - (t0 + t1) / 2.0
    at = t1 if now is None else now
    if prev is not None:
        prev_offset, prev_rtt, prev_at = prev[0], prev[1], (
            prev[2] if len(prev) > 2 else 0.0
        )
        if rtt > prev_rtt and (at - prev_at) <= max_age_s:
            return prev_offset, prev_rtt, prev_at
    return offset, rtt, at


# ----------------------------------------------------------- trace stitching


def stitch_spans(groups: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-process span groups into ONE Perfetto/Chrome-trace doc.

    ``groups``: ``[{"process": label, "offset_s": off, "spans": [span
    dicts with track/name/t0/t1/attrs]}]`` — ``offset_s`` is the group's
    clock minus the reference clock (``t_ref = t - offset_s``), 0.0 for
    the reference process (the router). One ``pid`` per group, one ``tid``
    per (group, track); timestamps land on the shared reference clock so
    hop ordering is readable straight off the timeline."""
    events: List[dict] = []
    meta: List[dict] = []
    for pid, group in enumerate(groups):
        off = float(group.get("offset_s", 0.0) or 0.0)
        meta.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": str(group.get("process", f"proc{pid}"))},
        })
        tids: Dict[str, int] = {}
        for s in group.get("spans", []):
            track = str(s.get("track", "main"))
            tid = tids.get(track)
            if tid is None:
                tid = tids[track] = len(tids) + 1
                meta.append({
                    "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
                    "args": {"name": track},
                })
            t0 = float(s["t0"]) - off
            t1 = float(s["t1"]) - off
            ev = {
                "ph": "X",
                "name": str(s.get("name", "span")),
                "cat": track,
                "ts": t0 * 1e6,
                "dur": max(0.0, (t1 - t0) * 1e6),
                "pid": pid,
                "tid": tid,
            }
            if s.get("attrs"):
                ev["args"] = s["attrs"]
            events.append(ev)
    return {
        "traceEvents": meta + events,
        "displayTimeUnit": "ms",
        "otherData": {"stitched_processes": len(groups)},
    }


def _merged_coverage(root: Tuple[float, float],
                     children: Sequence[Tuple[float, float]]) -> float:
    r0, r1 = root
    if r1 <= r0:
        return 1.0
    ivs = sorted((max(r0, a), min(r1, b)) for a, b in children)
    covered = 0.0
    cur0 = cur1 = None
    for a, b in ivs:
        if b < a:
            continue
        if cur0 is None:
            cur0, cur1 = a, b
        elif a <= cur1:
            cur1 = max(cur1, b)
        else:
            covered += cur1 - cur0
            cur0, cur1 = a, b
    if cur0 is not None:
        covered += cur1 - cur0
    return covered / (r1 - r0)


def verify_stitched(
    doc: Dict[str, Any], request_id: str, slack_s: float = 0.05,
) -> Dict[str, Any]:
    """Programmatic check of one request's merged trace — the acceptance
    bar, executable: the root is the router's ``route`` span on the
    request's track; every other span of that track (relay hops, each
    replica's request tree) must (a) sit inside the root ± ``slack_s``
    (anything outside is an ORPHAN — a stitching or clock-offset bug), (b)
    union-cover >= 95% of the root's wall time, and (c) where spans carry a
    propagated ``hop`` attr, start in hop order after clock correction.

    Returns ``{"coverage", "orphans", "hops_ordered", "spans", "wall_s"}``.
    """
    xs = [
        e for e in doc.get("traceEvents", [])
        if e.get("ph") == "X" and e.get("cat") == request_id
    ]
    root = next((e for e in xs if e["name"] == "route"), None)
    if root is None:
        return {"coverage": 0.0, "orphans": 0, "hops_ordered": False,
                "spans": len(xs), "wall_s": 0.0}
    r0 = root["ts"] / 1e6
    r1 = r0 + root["dur"] / 1e6
    children = []
    orphans = 0
    hops: List[Tuple[int, float]] = []
    for e in xs:
        if e is root:
            continue
        t0 = e["ts"] / 1e6
        t1 = t0 + e["dur"] / 1e6
        if t0 < r0 - slack_s or t1 > r1 + slack_s:
            orphans += 1
            continue
        children.append((t0, t1))
        hop = (e.get("args") or {}).get("hop")
        if hop is not None:
            try:
                hops.append((int(hop), t0))
            except (TypeError, ValueError):
                pass
    hops.sort(key=lambda h: h[0])
    hops_ordered = all(
        b[1] >= a[1] - slack_s for a, b in zip(hops, hops[1:])
    )
    return {
        "coverage": round(_merged_coverage((r0, r1), children), 4),
        "orphans": orphans,
        "hops_ordered": hops_ordered,
        "spans": len(xs),
        "wall_s": round(r1 - r0, 6),
    }


def detect_stragglers(
    groups: Sequence[Dict[str, Any]],
    span_name: str = "compute",
    factor: float = 3.0,
    min_spans: int = 4,
    window: int = 8,
) -> Dict[str, Dict[str, Any]]:
    """Fleet-relative straggler detection over per-process span groups —
    the SAME shape ``stitch_spans`` takes, so the training coordinator
    feeds the identical data structure to the trace stitcher and the
    straggler detector (one observability plane, two consumers).

    Per process: the mean duration of its most recent ``window`` spans
    named ``span_name``. Fleet baseline: the MEDIAN of those means (robust
    to the straggler itself — a mean-of-means baseline would be dragged
    toward the slow worker and mask it). A process is a straggler when its
    mean exceeds ``factor`` x the fleet median and it has produced at
    least ``min_spans`` samples (cold starts and compile steps must not
    trip it). Returns ``{process: {"mean_s", "n", "ratio", "straggler"}}``.
    """
    means: Dict[str, Tuple[float, int]] = {}
    for g in groups:
        durs = [
            float(s["t1"]) - float(s["t0"])
            for s in g.get("spans", [])
            if s.get("name") == span_name
        ][-window:]
        if durs:
            means[str(g.get("process"))] = (sum(durs) / len(durs), len(durs))
    if not means:
        return {}
    ordered = sorted(m for m, _ in means.values())
    mid = len(ordered) // 2
    median = (
        ordered[mid]
        if len(ordered) % 2
        else (ordered[mid - 1] + ordered[mid]) / 2.0
    )
    out: Dict[str, Dict[str, Any]] = {}
    for proc, (mean, n) in means.items():
        ratio = mean / median if median > 0 else 1.0
        out[proc] = {
            "mean_s": mean,
            "n": n,
            "ratio": ratio,
            "straggler": bool(
                n >= min_spans and len(means) >= 2 and ratio >= factor
            ),
        }
    return out


def request_ids_in(doc: Dict[str, Any]) -> List[str]:
    """Every request id with a ``route`` root in a merged doc (per-run
    verification sweeps these)."""
    return sorted({
        e.get("cat") for e in doc.get("traceEvents", [])
        if e.get("ph") == "X" and e.get("name") == "route" and e.get("cat")
    })


def write_trace(path, doc: Dict[str, Any]) -> str:
    from pathlib import Path

    p = Path(path)
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(json.dumps(doc) + "\n")
    return str(p)
