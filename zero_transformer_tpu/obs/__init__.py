"""Unified observability: spans, Prometheus metrics, flight recorder,
on-demand profiling (PR 7).

One subsystem threaded through serving (``serving/engine.py`` /
``server.py``), training (``training/trainer.py`` / ``train.py``), and the
resilience escalation seams:

- ``Tracer``: ring-buffered span API (monotonic clock, fixed-tuple records,
  no hot-path allocation beyond the record) instrumenting the serving
  request lifecycle end-to-end and the training step loop; exports
  Perfetto/Chrome-trace JSON and an incremental ``spans.jsonl``;
- ``Registry`` / ``Counter`` / ``Gauge`` / ``Histogram``: Prometheus text
  exposition (``/metrics`` content-negotiates it) backed by fixed-bucket
  histograms — a scrape is O(buckets) and never holds the tick lock;
- ``FlightRecorder``: bounded ring of recent tick summaries + events,
  dumped automatically on breaker-open, anomaly halt, watchdog abort,
  checkpoint quarantine, and drain;
- ``ProfileWindow``: ``POST /admin/profile`` / ``train.py
  --profile-window`` jax.profiler capture windows landing next to the
  flight-recorder dumps;
- ``logging``: MetricsLogger / StepTimer / MFU / per-device HBM stats
  (``utils.monitoring`` is the compatibility facade over it).

See docs/OBSERVABILITY.md for the span model, metric tables, and scrape
configuration.
"""
from zero_transformer_tpu.obs.exporter import MetricsExporter
from zero_transformer_tpu.obs.flight import FlightRecorder
from zero_transformer_tpu.obs.logging import (
    MetricsLogger,
    StepTimer,
    device_peak_flops,
    hbm_device_stats,
    hbm_used_gb,
    mfu,
    model_flops_per_token,
    profile,
)
from zero_transformer_tpu.obs.metrics import (
    LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
)
from zero_transformer_tpu.obs.profiling import ProfileWindow, parse_profile_window
from zero_transformer_tpu.obs.spans import (
    Tracer,
    coverage_fraction,
    span_tree,
)

__all__ = [
    "Counter",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "LATENCY_BUCKETS",
    "MetricsExporter",
    "MetricsLogger",
    "ProfileWindow",
    "Registry",
    "StepTimer",
    "Tracer",
    "coverage_fraction",
    "device_peak_flops",
    "hbm_device_stats",
    "hbm_used_gb",
    "mfu",
    "model_flops_per_token",
    "parse_profile_window",
    "profile",
    "span_tree",
]
