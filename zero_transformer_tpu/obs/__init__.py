"""Unified observability: spans, Prometheus metrics, flight recorder,
on-demand profiling (PR 7).

One subsystem threaded through serving (``serving/engine.py`` /
``server.py``), training (``training/trainer.py`` / ``train.py``), and the
resilience escalation seams:

- ``Tracer``: ring-buffered span API (monotonic clock, fixed-tuple records,
  no hot-path allocation beyond the record) instrumenting the serving
  request lifecycle end-to-end and the training step loop; exports
  Perfetto/Chrome-trace JSON and an incremental ``spans.jsonl``;
- ``Registry`` / ``Counter`` / ``Gauge`` / ``Histogram``: Prometheus text
  exposition (``/metrics`` content-negotiates it) backed by fixed-bucket
  histograms — a scrape is O(buckets) and never holds the tick lock;
- ``FlightRecorder``: bounded ring of recent tick summaries + events,
  dumped automatically on breaker-open, anomaly halt, watchdog abort,
  checkpoint quarantine, and drain;
- ``ProfileWindow``: ``POST /admin/profile`` / ``train.py
  --profile-window`` jax.profiler capture windows landing next to the
  flight-recorder dumps;
- ``logging``: MetricsLogger / StepTimer / MFU / per-device HBM stats
  (``utils.monitoring`` is the compatibility facade over it).

See docs/OBSERVABILITY.md for the span model, metric tables, and scrape
configuration.
"""
from zero_transformer_tpu.obs.exporter import MetricsExporter
from zero_transformer_tpu.obs.fleet import (
    ENGINE_LEDGER_KEYS,
    FLEET_OBS_REQUIRED_KEYS,
    LEDGER_KEYS,
    ROUTER_LEDGER_KEYS,
    FleetAggregator,
    TenantLedger,
    complete_ledger,
    estimate_clock_offset,
    new_engine_ledger,
    parse_exposition,
    request_ids_in,
    stitch_spans,
    verify_stitched,
)
from zero_transformer_tpu.obs.flight import FlightRecorder
from zero_transformer_tpu.obs.logging import (
    MetricsLogger,
    StepTimer,
    device_peak_flops,
    hbm_device_stats,
    hbm_used_gb,
    mfu,
    model_flops_per_token,
    profile,
)
from zero_transformer_tpu.obs.metrics import (
    LATENCY_BUCKETS,
    Counter,
    Gauge,
    Histogram,
    Registry,
)
from zero_transformer_tpu.obs.profiling import ProfileWindow, parse_profile_window
from zero_transformer_tpu.obs.slo import (
    Objective,
    SLOEngine,
    default_objectives,
    parse_slo_config,
)
from zero_transformer_tpu.obs.spans import (
    Tracer,
    coverage_fraction,
    span_dict,
    span_tree,
)

__all__ = [
    "Counter",
    "ENGINE_LEDGER_KEYS",
    "FLEET_OBS_REQUIRED_KEYS",
    "FleetAggregator",
    "FlightRecorder",
    "Gauge",
    "Histogram",
    "LATENCY_BUCKETS",
    "LEDGER_KEYS",
    "MetricsExporter",
    "MetricsLogger",
    "Objective",
    "ProfileWindow",
    "ROUTER_LEDGER_KEYS",
    "Registry",
    "SLOEngine",
    "StepTimer",
    "TenantLedger",
    "Tracer",
    "complete_ledger",
    "coverage_fraction",
    "default_objectives",
    "estimate_clock_offset",
    "new_engine_ledger",
    "parse_exposition",
    "parse_slo_config",
    "request_ids_in",
    "span_dict",
    "stitch_spans",
    "verify_stitched",
    "device_peak_flops",
    "hbm_device_stats",
    "hbm_used_gb",
    "mfu",
    "model_flops_per_token",
    "parse_profile_window",
    "profile",
    "span_tree",
]
