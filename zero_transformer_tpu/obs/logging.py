"""Metrics logging, step timing, MFU, HBM stats (formerly utils/monitoring).

Moved here when ``obs/`` unified observability (PR 7); ``utils.monitoring``
remains as a compatibility facade re-exporting everything below, so existing
imports keep working. The reference's observability was wandb-only
(reference ``main_zero.py:354-366,504-529,559-562``) with no profiling and
no MFU anywhere (SURVEY §5). Here:

- ``MetricsLogger`` fans out to console, a JSONL file, and wandb when the
  package is importable (this image has no wandb — it is import-gated);
- ``model_flops_per_token`` / ``mfu`` give the 6N + attention FLOPs estimate
  against per-chip peak;
- ``hbm_device_stats`` reports EVERY local device's HBM in use with max and
  mean rollups (the old ``hbm_used_gb`` read only device 0 — a skewed
  TP/PP shard or a leaking replica on device 3 was invisible);
- ``StepTimer`` measures wall-per-step with a sync-on-read design (value
  fetch, not ``block_until_ready`` — see bench.py note);
- ``profile`` context manager wraps ``jax.profiler`` trace capture.
"""
from __future__ import annotations

import contextlib
import json
import time
from pathlib import Path
from typing import Any, Dict, Optional

import jax

# bf16 peak FLOP/s per chip by TPU generation (public spec sheets)
TPU_PEAK_FLOPS = {
    "v3": 123e12 / 2,  # per chip (2 cores): 61.5 TF/core… v3 chip = 123 TF bf16
    "v4": 275e12,
    "v5e": 197e12,
    "v5p": 459e12,
    "v6e": 918e12,
}


def model_flops_per_token(
    n_params: int, n_layers: int, d_model: int, seq_len: int, backward: bool = True
) -> float:
    """FLOPs per trained token: 6N (fwd+bwd matmuls) + 12·L·d·T attention term
    (PaLM appendix-B style accounting)."""
    mult = 3.0 if backward else 1.0
    dense = 2.0 * n_params
    attn = 4.0 * n_layers * d_model * seq_len  # qk^T + av, causal halves the 2x
    return mult * (dense + attn)


def device_peak_flops() -> Optional[float]:
    kind = jax.devices()[0].device_kind.lower()
    for key, val in TPU_PEAK_FLOPS.items():
        if key in kind.replace(" ", "").replace("tpu", ""):
            return val
    if "v5lite" in kind.replace(" ", "") or "lite" in kind:
        return TPU_PEAK_FLOPS["v5e"]
    return None


def mfu(
    tokens_per_sec_per_chip: float,
    flops_per_token: float,
    peak_flops: Optional[float] = None,
) -> Optional[float]:
    peak = peak_flops if peak_flops is not None else device_peak_flops()
    if not peak:
        return None
    return tokens_per_sec_per_chip * flops_per_token / peak


def hbm_device_stats() -> Optional[Dict[str, Any]]:
    """Per-device HBM in use (GB) with max/mean rollups, or None where the
    backend exposes no memory stats (CPU). The per-device view is the one
    that catches a SKEWED fleet — one TP shard 2 GB heavier than its peers,
    or a leak on a single replica — which a device-0-only read hides."""
    per: list = []
    try:
        for d in jax.local_devices():
            stats = d.memory_stats()
            if not stats or "bytes_in_use" not in stats:
                return None
            per.append(stats["bytes_in_use"] / 1e9)
    except Exception:
        return None
    if not per:
        return None
    return {
        "per_device_gb": per,
        "max_gb": max(per),
        "mean_gb": sum(per) / len(per),
    }


def hbm_used_gb() -> Optional[float]:
    """Device-0 HBM in use, GB — the legacy single-device read, kept for
    compatibility; prefer ``hbm_device_stats`` (max/mean over ALL local
    devices). The observability hook the reference never had: its OOMs were
    discovered by crashing (reference ``logs/1B.md:7``)."""
    stats = hbm_device_stats()
    return stats["per_device_gb"][0] if stats else None


class MetricsLogger:
    """Console + JSONL + optional-wandb metrics sink."""

    def __init__(
        self,
        directory: Optional[str | Path] = None,
        use_wandb: bool = False,
        wandb_project: str = "zero-transformer-tpu",
        config: Optional[dict] = None,
        enabled: bool = True,
    ):
        self.enabled = enabled and jax.process_index() == 0
        self._file = None
        self._wandb = None
        if not self.enabled:
            return
        if directory is not None:
            from zero_transformer_tpu.utils.paths import is_remote_path

            if is_remote_path(directory):
                # remote run directory (gs:// etc.): object stores don't
                # support the append-mode JSONL sink; wandb carries remote
                # metrics, and the console line always prints.
                print(f"metrics: remote directory {directory}; JSONL sink disabled "
                      "(use wandb for remote metric history)", flush=True)
            else:
                path = Path(directory)
                path.mkdir(parents=True, exist_ok=True)
                self._file = open(path / "metrics.jsonl", "a", buffering=1)
        if use_wandb:
            try:
                import wandb

                self._wandb = wandb
                wandb.init(project=wandb_project, config=config or {})
            except ImportError:
                pass

    def log(self, metrics: Dict[str, Any], step: int, prefix: str = "") -> None:
        if not self.enabled:
            return
        clean = {
            (f"{prefix}/{k}" if prefix else k): (
                float(v) if hasattr(v, "item") or isinstance(v, (int, float)) else v
            )
            for k, v in metrics.items()
        }
        if self._file:
            self._file.write(json.dumps({"step": step, **clean}) + "\n")
        if self._wandb:
            self._wandb.log(clean, step=step)
        parts = " ".join(
            f"{k}={v:.4g}" if isinstance(v, float) else f"{k}={v}"
            for k, v in clean.items()
        )
        print(f"[step {step}] {parts}", flush=True)

    def event(self, name: str, step: int, **fields: Any) -> None:
        """One-off run event (anomaly rollback, supervisor restart, watchdog
        abort, skipped data shard) — lands in the same JSONL/wandb stream as
        the scalar metrics so a post-mortem reads ONE timeline, but tagged
        with ``event`` so dashboards can render it as an annotation instead
        of a curve."""
        if not self.enabled:
            return
        clean = {
            k: (float(v) if hasattr(v, "item") else v) for k, v in fields.items()
        }
        if self._file:
            self._file.write(
                json.dumps({"step": step, "event": name, **clean}) + "\n"
            )
        if self._wandb:
            self._wandb.log(
                {f"event/{name}/{k}": v for k, v in clean.items()}, step=step
            )
        parts = " ".join(f"{k}={v}" for k, v in clean.items())
        print(f"[step {step}] EVENT {name} {parts}", flush=True)

    def close(self) -> None:
        if self._file:
            self._file.close()
        if self._wandb:
            self._wandb.finish()


class StepTimer:
    """Rolling wall-clock per-step timer. Call ``tick()`` once per step after
    fetching a step output (the fetch is the device sync)."""

    def __init__(self, window: int = 50):
        self.window = window
        self._times: list[float] = []
        self._last: Optional[float] = None

    def tick(self) -> Optional[float]:
        now = time.perf_counter()
        dt = None
        if self._last is not None:
            dt = now - self._last
            self._times.append(dt)
            if len(self._times) > self.window:
                self._times.pop(0)
        self._last = now
        return dt

    def mean(self) -> Optional[float]:
        return sum(self._times) / len(self._times) if self._times else None


@contextlib.contextmanager
def profile(log_dir: str | Path, enabled: bool = True):
    """Capture a jax.profiler trace viewable in TensorBoard/XProf."""
    if not enabled:
        yield
        return
    jax.profiler.start_trace(str(log_dir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()
