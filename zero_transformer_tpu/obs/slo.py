"""SLO engine: declared objectives, multi-window burn rates, budget-driven
escalation (PR 15).

Automap's thesis applied to objectives (PAPERS.md 2112.02958): declare the
SLO ONCE — "TTFT p99 <= 2s for 99% of requests", "dropped_streams == 0" —
and derive the monitoring and the reactions instead of hand-wiring a
dashboard, an alert rule, and a scaling trigger that drift apart. The
engine is deliberately small and classical (the SRE-workbook multi-window
burn-rate alert):

- an **objective** says what fraction of events must be good
  (``target``) over what horizon; its error budget is ``1 - target``;
- a **source** is a callable returning cumulative ``(bad, total)`` event
  counts — latency objectives read the fleet-merged histogram's cumulative
  buckets (bad = observations above the threshold), availability reads the
  router's request counters, ``kind="zero"`` objectives (dropped_streams)
  treat ANY bad event as budget-gone;
- each evaluation appends a ``(t, bad, total)`` sample to a bounded ring
  and computes the **burn rate** over a short and a long window:
  ``(Δbad/Δtotal) / (1 - target)`` — 1.0 means "spending exactly the
  budget", ``fast_burn`` (default 14.4, the 1h/5m page threshold) means
  "the budget dies in hours, act now";
- a **fast burn** (both windows above the threshold — the long window
  de-flaps the short one) fires the registered callbacks ONCE per episode:
  the router wires these to the existing machinery (FlightRecorder dump
  with the fleet snapshot, an autoscaler up-signal, a loud log) rather
  than inventing an alerting stack.

Pure stdlib, no threads of its own: the owner calls ``evaluate()`` on its
own cadence (the router's obs loop) and reads ``snapshot()`` for the
``/slo`` endpoint and the ``slo_*`` gauges.
"""
from __future__ import annotations

import bisect
import dataclasses
import logging
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

log = logging.getLogger("zero_transformer_tpu")

# metric names an owner can bind without custom sources; anything else
# needs an explicit source callable (a typo'd objective must fail loudly
# at construction, not silently never burn)
KNOWN_METRICS = (
    "ttft_p99", "itl_p99", "availability", "dropped_streams",
)

OK, FAST_BURN, VIOLATED = "ok", "fast_burn", "violated"


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declared objective. ``threshold_s`` only applies to latency
    metrics (an event is good when its latency lands at or under it)."""

    name: str
    metric: str
    target: float = 0.99          # fraction of events that must be good
    threshold_s: float = 0.0      # latency bound (latency metrics only)
    short_window_s: float = 60.0
    long_window_s: float = 3600.0
    fast_burn: float = 14.4       # burn-rate threshold on BOTH windows
    kind: str = "ratio"           # "ratio" | "zero"
    qos_class: Optional[str] = None  # bind to one class's metric stream

    def __post_init__(self):
        if not (0.0 < self.target < 1.0) and self.kind != "zero":
            raise ValueError(
                f"objective {self.name!r}: target must be in (0, 1)"
            )
        if self.short_window_s <= 0 or self.long_window_s < self.short_window_s:
            raise ValueError(
                f"objective {self.name!r}: need 0 < short <= long window"
            )
        if self.fast_burn <= 0:
            raise ValueError(f"objective {self.name!r}: fast_burn must be > 0")


def parse_slo_config(spec: Sequence[Dict[str, Any]]) -> List[Objective]:
    """Objectives from a config list (e.g. ``configs/slo_default.json``).
    Also accepts the PR-18 dict shape ``{"objectives": [...], "qos": ...,
    "brownout": ...}`` — the qos/brownout blocks belong to their owners
    (``QosPolicy.from_config`` / the router) and are ignored here.
    Unknown keys are an error — a typo must not silently weaken an SLO."""
    if isinstance(spec, dict):
        spec = spec.get("objectives") or []
    out: List[Objective] = []
    allowed = {f.name for f in dataclasses.fields(Objective)}
    for raw in spec:
        unknown = set(raw) - allowed
        if unknown:
            raise ValueError(
                f"SLO objective {raw.get('name', '?')!r}: unknown keys "
                f"{sorted(unknown)} (allowed: {sorted(allowed)})"
            )
        if raw.get("metric") not in KNOWN_METRICS:
            raise ValueError(
                f"SLO objective {raw.get('name', '?')!r}: unknown metric "
                f"{raw.get('metric')!r} (known: {KNOWN_METRICS})"
            )
        out.append(Objective(**raw))
    return out


def default_objectives() -> List[Objective]:
    """The committed defaults (mirrors configs/slo_default.json): latency
    objectives sized for production serving, availability, and the
    zero-tolerance dropped-streams objective the chaos proofs pin.
    Latency thresholds sit ON LATENCY_BUCKETS bounds — the histogram can
    only grade at a bound, so an off-bound threshold silently grades at
    the next bound up."""
    return [
        Objective(name="ttft_p99", metric="ttft_p99", target=0.99,
                  threshold_s=2.5),
        Objective(name="itl_p99", metric="itl_p99", target=0.99,
                  threshold_s=0.25),
        Objective(name="availability", metric="availability", target=0.999),
        Objective(name="dropped_streams", metric="dropped_streams",
                  kind="zero", target=0.999999),
    ]


class _ObjectiveState:
    __slots__ = ("objective", "source", "ring", "state", "last_fired_at",
                 "burn_short", "burn_long", "budget_remaining", "bad",
                 "total", "window_clipped")

    def __init__(self, objective: Objective, source):
        self.objective = objective
        self.source = source
        # (t, bad, total) cumulative samples, oldest first, clipped to the
        # long window (+ slack so the window edge always has a sample)
        self.ring: deque = deque()
        self.state = OK
        self.last_fired_at: Optional[float] = None
        self.burn_short = 0.0
        self.burn_long = 0.0
        self.budget_remaining = 1.0
        self.bad = 0.0
        self.total = 0.0
        self.window_clipped = True  # less history than the long window


class SLOEngine:
    """Evaluate declared objectives over cumulative (bad, total) sources.

    ``add_objective(obj, source)`` binds one objective; ``evaluate(now)``
    samples every source, updates burn rates, and fires ``on_fast_burn``
    callbacks on the OK -> FAST_BURN edge (re-armed after one short window
    back under the threshold). ``snapshot()`` is the /slo payload."""

    def __init__(self, clock=time.monotonic):
        self.clock = clock
        self._objectives: Dict[str, _ObjectiveState] = {}
        self._callbacks: List[Callable[[Objective, Dict[str, Any]], None]] = []
        self._evaluations = 0
        # evaluate() runs on the owner's obs loop AND on direct callers
        # (tests, the loadgen's fleet-obs segment): the ring/window math
        # must never see a concurrent mutation. Callbacks fire OUTSIDE the
        # lock — they may legitimately read snapshot().
        self._lock = threading.Lock()

    def add_objective(
        self,
        objective: Objective,
        source: Callable[[], Optional[Tuple[float, float]]],
    ) -> None:
        if objective.name in self._objectives:
            raise ValueError(f"duplicate objective {objective.name!r}")
        self._objectives[objective.name] = _ObjectiveState(objective, source)

    def on_fast_burn(
        self, callback: Callable[[Objective, Dict[str, Any]], None]
    ) -> None:
        self._callbacks.append(callback)

    def __len__(self) -> int:
        return len(self._objectives)

    # ------------------------------------------------------------ evaluation

    @staticmethod
    def _window_delta(ring, now: float, window_s: float):
        """(Δbad, Δtotal, clipped): deltas vs the newest sample at or
        before ``now - window_s`` (the youngest sample OUTSIDE the window,
        so the delta covers at least the window). clipped=True when
        history is shorter than the window."""
        t_new, bad_new, total_new = ring[-1]
        cutoff = now - window_s
        times = [s[0] for s in ring]
        i = bisect.bisect_right(times, cutoff) - 1
        if i < 0:
            t0, bad0, total0 = ring[0]
            return bad_new - bad0, total_new - total0, True
        t0, bad0, total0 = ring[i]
        return bad_new - bad0, total_new - total0, False

    def evaluate(self, now: Optional[float] = None) -> Dict[str, Any]:
        t = self.clock() if now is None else now
        # sources run OUTSIDE the lock (they may take their owner's locks)
        samples: Dict[str, Optional[Tuple[float, float]]] = {}
        for name, st in self._objectives.items():
            try:
                samples[name] = st.source()
            except Exception:  # a broken source must not kill the obs loop
                log.exception("slo: source for %r failed", st.objective.name)
                samples[name] = None
        with self._lock:
            fired = self._evaluate_locked(t, samples)
        for obj, snap in fired:
            log.warning(
                "SLO FAST BURN: objective %r burning at %.1fx/%.1fx "
                "(short/long window) — budget_remaining %.3f",
                obj.name, snap["burn_rate_short"], snap["burn_rate_long"],
                snap["budget_remaining"],
            )
            for cb in self._callbacks:
                try:
                    cb(obj, snap)
                except Exception:
                    log.exception("slo: fast-burn callback failed")
        return self.snapshot()

    def _evaluate_locked(
        self, t: float, samples: Dict[str, Optional[Tuple[float, float]]],
    ) -> List[Tuple[Objective, Dict[str, Any]]]:
        self._evaluations += 1
        fired: List[Tuple[Objective, Dict[str, Any]]] = []
        for name, st in self._objectives.items():
            obj = st.objective
            sample = samples.get(name)
            if sample is None:
                continue
            bad, total = float(sample[0]), float(sample[1])
            st.bad, st.total = bad, total
            st.ring.append((t, bad, total))
            horizon = obj.long_window_s * 1.25
            while len(st.ring) > 2 and st.ring[0][0] < t - horizon:
                st.ring.popleft()
            budget = max(1e-9, 1.0 - obj.target)
            d_bad_s, d_total_s, _ = self._window_delta(
                st.ring, t, obj.short_window_s
            )
            d_bad_l, d_total_l, clipped = self._window_delta(
                st.ring, t, obj.long_window_s
            )
            st.window_clipped = clipped
            if obj.kind == "zero":
                # zero-tolerance: any bad event in the window IS the burn
                st.burn_short = float("inf") if d_bad_s > 0 else 0.0
                st.burn_long = float("inf") if d_bad_l > 0 else 0.0
                st.budget_remaining = 0.0 if bad > 0 else 1.0
            else:
                st.burn_short = (
                    (d_bad_s / d_total_s) / budget if d_total_s > 0 else 0.0
                )
                st.burn_long = (
                    (d_bad_l / d_total_l) / budget if d_total_l > 0 else 0.0
                )
                err_long = d_bad_l / d_total_l if d_total_l > 0 else 0.0
                st.budget_remaining = max(0.0, 1.0 - err_long / budget)
            burning = (
                st.burn_short >= obj.fast_burn
                and st.burn_long >= obj.fast_burn
            )
            if burning:
                was = st.state
                st.state = FAST_BURN
                rearmed = (
                    st.last_fired_at is None
                    or t - st.last_fired_at >= obj.short_window_s
                )
                if was != FAST_BURN and rearmed:
                    st.last_fired_at = t
                    fired.append((obj, self._objective_snapshot(st)))
            elif st.budget_remaining <= 0.0:
                st.state = VIOLATED
            else:
                st.state = OK
        return fired

    # -------------------------------------------------------------- reading

    @staticmethod
    def _objective_snapshot(st: _ObjectiveState) -> Dict[str, Any]:
        obj = st.objective

        def finite(v: float) -> float:
            return round(min(v, 1e9), 4)

        return {
            "metric": obj.metric,
            "kind": obj.kind,
            "qos_class": obj.qos_class,
            "target": obj.target,
            "threshold_s": obj.threshold_s,
            "state": st.state,
            "burn_rate_short": finite(st.burn_short),
            "burn_rate_long": finite(st.burn_long),
            "budget_remaining": round(st.budget_remaining, 4),
            "fast_burn_threshold": obj.fast_burn,
            "short_window_s": obj.short_window_s,
            "long_window_s": obj.long_window_s,
            "bad": st.bad,
            "total": st.total,
            "window_clipped": st.window_clipped,
        }

    def snapshot(self) -> Dict[str, Any]:
        """The /slo payload: per-objective burn rates + budget, and one
        fleet verdict — ``violated`` when any objective is fast-burning or
        out of budget (the bench guard's gate), ``ok`` otherwise."""
        with self._lock:
            objectives = {
                name: self._objective_snapshot(st)
                for name, st in self._objectives.items()
            }
        verdict = OK
        if any(o["state"] in (FAST_BURN, VIOLATED) for o in objectives.values()):
            verdict = VIOLATED
        return {
            "objectives": objectives,
            "verdict": verdict,
            "evaluated": self._evaluations,
            "window_clipped": any(
                o["window_clipped"] for o in objectives.values()
            ),
        }
