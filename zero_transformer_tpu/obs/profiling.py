"""On-demand ``jax.profiler`` capture windows.

Two surfaces share this module:

- serving: ``POST /admin/profile {"ticks": N}`` stages a capture that the
  engine's tick thread starts at its next ``step()`` and stops N ticks
  later (``ProfileWindow`` owns the start/stop bookkeeping; only the tick
  thread touches the profiler, so there is no cross-thread start/stop
  race);
- training: ``train.py --profile-window START:LEN`` captures the step
  window [START, START+LEN) — ``parse_profile_window`` is the flag parser.

Traces land under ``<run dir>/profiles/<name>`` next to the flight-recorder
dumps, viewable in TensorBoard/XProf or ``xprof``.
"""
from __future__ import annotations

import logging
import time
from pathlib import Path
from typing import Optional, Tuple

log = logging.getLogger("zero_transformer_tpu")


def parse_profile_window(spec: str) -> Tuple[int, int]:
    """``"START:LEN"`` -> (start_step, n_steps); both must be positive."""
    try:
        start_s, _, len_s = spec.partition(":")
        start, length = int(start_s), int(len_s)
    except ValueError:
        raise ValueError(
            f"--profile-window expects START:LEN (e.g. 100:20), got {spec!r}"
        ) from None
    if start < 1 or length < 1:
        raise ValueError(
            f"--profile-window START and LEN must be >= 1, got {spec!r}"
        )
    return start, length


class ProfileWindow:
    """Single-owner capture window: ``request(n)`` stages it (any thread),
    ``poll()`` starts/advances/stops it (the OWNING loop thread only).

    ``poll()`` is called once per tick/step, BEFORE the work: the first call
    after a request starts the trace, each later call burns one tick of the
    budget, and the call after the budget stops the trace — so a window of
    N covers exactly N full iterations of the owning loop.
    """

    def __init__(self, directory: Optional[str], prefix: str = "capture"):
        self.directory = str(directory) if directory else None
        self.prefix = prefix
        self._pending: Optional[Tuple[int, str]] = None
        self._active: Optional[list] = None  # [target_tick, path]
        # in-progress flag spanning the WHOLE capture lifetime (staged ->
        # start_trace -> window -> stop_trace): the first start_trace can
        # block the owning thread for hundreds of ms, and a second request
        # arriving inside that window must still conflict
        self._busy = False
        self.completed: list = []  # paths of finished captures

    @property
    def active(self) -> bool:
        return self._busy

    def request(self, ticks: int, name: Optional[str] = None) -> dict:
        """Stage a capture of the next ``ticks`` loop iterations. Raises
        RuntimeError when no directory is configured or a capture is
        already staged/running (jax.profiler is single-trace)."""
        if ticks < 1:
            raise ValueError("profile ticks must be >= 1")
        if self.directory is None:
            raise RuntimeError(
                "profiling is disabled: no observability directory "
                "configured (serve --obs-dir / --metrics-dir)"
            )
        if self._busy:
            raise RuntimeError("a profile capture is already in progress")
        self._busy = True
        # graftlint: allow[wall-clock-in-span-path] reason=deliberately wall-clock — the capture DIRECTORY name is a human-readable unix stamp; no span math touches it
        stamp = name or f"{self.prefix}_{int(time.time())}"
        path = str(Path(self.directory) / "profiles" / stamp)
        self._pending = (int(ticks), path)
        return {"path": path, "ticks": int(ticks)}

    def poll(self, tick: int) -> None:
        """Advance the window (owning thread only). ``tick`` is the loop's
        monotone WORK counter — the serving engine's busy-tick index, which
        does not advance on idle spins — so a window of N covers N ticks of
        real work: started here before tick T runs, stopped when the
        counter reaches T + N."""
        if self._active is not None and tick >= self._active[0]:
            self._stop()
        if self._pending is not None and self._active is None:
            ticks, path = self._pending
            self._pending = None
            try:
                import jax

                Path(path).mkdir(parents=True, exist_ok=True)
                jax.profiler.start_trace(path)
            except Exception:
                log.exception("profiler: start_trace failed (capture skipped)")
                self._busy = False
                return
            self._active = [tick + ticks, path]
            log.info("profiler: capturing %d ticks to %s", ticks, path)

    def abort(self) -> None:
        """Stop a live capture immediately (drain/abort paths): a dying
        engine must not leave the process-global profiler running."""
        self._pending = None
        if self._active is not None:
            self._stop()
        self._busy = False

    def _stop(self) -> None:
        path = self._active[1]
        self._active = None
        self._busy = False
        try:
            import jax

            jax.profiler.stop_trace()
        except Exception:
            log.exception("profiler: stop_trace failed")
            return
        self.completed.append(path)
        log.info("profiler: capture finished -> %s", path)
