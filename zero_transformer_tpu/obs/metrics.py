"""Prometheus-style metric types + text exposition.

Dependency-free implementations of the three types the serving and training
stacks need, with the scrape-cost property the old ``/metrics`` path lacked:

- ``Counter`` / ``Gauge``: one float behind a micro-lock;
- ``Histogram``: FIXED buckets chosen at construction — ``observe`` is one
  bisect + three adds, a quantile read is O(buckets) with linear
  interpolation inside the landing bucket (monotone in q), and exposition
  renders cumulative ``_bucket{le=...}`` lines the Prometheus way;
- ``counter_func`` / ``gauge_func``: callback-backed metrics that read an
  EXISTING host counter at scrape time (the engine's ``stats`` dict keeps
  its plain-int increments on the hot path; exposition pays the read, not
  the tick);
- ``Registry.render()``: the ``text/plain; version=0.0.4`` exposition
  format, conformance-tested in tests/test_obs.py.

This replaces the deque-percentile recompute the engine used to do under
its scheduler lock (the known cost flagged at serving/engine.py:829 pre-PR7):
a scrape no longer sorts 10k samples or touches the tick lock at all.
"""
from __future__ import annotations

import bisect
import math
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

# default latency buckets (seconds): 100us .. 60s, roughly x2.5 per step —
# wide enough for CPU-box integration runs and TPU production both
LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)


def _fmt(v) -> str:
    if isinstance(v, bool):
        return "1" if v else "0"
    if isinstance(v, int):
        return str(v)
    f = float(v)
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if math.isnan(f):
        return "NaN"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return format(f, ".10g")


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _labels_str(labels: Optional[Dict[str, Any]]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_escape_label(str(v))}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class Counter:
    """Monotonically increasing value; renders as ``<name>_total``."""

    def __init__(self, name: str, help: str):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def render(self) -> List[str]:
        n = self.name if self.name.endswith("_total") else self.name + "_total"
        return [
            f"# HELP {n} {_escape_help(self.help)}",
            f"# TYPE {n} counter",
            f"{n} {_fmt(self._value)}",
        ]


class Gauge:
    """Value that can go up and down."""

    def __init__(self, name: str, help: str):
        self.name = name
        self.help = help
        self._value = 0.0
        self._lock = threading.Lock()

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value

    def render(self) -> List[str]:
        return [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} gauge",
            f"{self.name} {_fmt(self._value)}",
        ]


class Histogram:
    """Fixed-bucket histogram: O(log buckets) observe, O(buckets) quantile.

    ``__len__`` is the observation count — the engine's legacy latency
    deques were measured by ``len()`` in tests and callers, and the
    histogram that replaced them keeps that contract.
    """

    def __init__(self, name: str, help: str,
                 buckets: Sequence[float] = LATENCY_BUCKETS):
        if not buckets or sorted(buckets) != list(buckets):
            raise ValueError("buckets must be a non-empty ascending sequence")
        self.name = name
        self.help = help
        self.buckets: Tuple[float, ...] = tuple(float(b) for b in buckets)
        # counts[i] = observations in (buckets[i-1], buckets[i]];
        # counts[-1] = overflow (> buckets[-1], the +Inf bucket)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1

    def __len__(self) -> int:
        return self._count

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def quantile(self, q: float) -> float:
        """Estimated q-quantile (q in [0, 1]) by linear interpolation inside
        the landing bucket. Returns 0.0 with no observations; the overflow
        bucket clamps to the top finite bound (a histogram cannot honestly
        extrapolate past its widest bucket)."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
        if total == 0:
            return 0.0
        target = q * total
        cum = 0.0
        for i, c in enumerate(counts):
            if c == 0:
                continue
            if cum + c >= target:
                lo = self.buckets[i - 1] if i > 0 else 0.0
                hi = self.buckets[i] if i < len(self.buckets) else self.buckets[-1]
                frac = (target - cum) / c
                return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
            cum += c
        return self.buckets[-1]

    def render(self) -> List[str]:
        with self._lock:
            counts = list(self._counts)
            total, s = self._count, self._sum
        lines = [
            f"# HELP {self.name} {_escape_help(self.help)}",
            f"# TYPE {self.name} histogram",
        ]
        cum = 0
        for bound, c in zip(self.buckets, counts):
            cum += c
            lines.append(
                f'{self.name}_bucket{{le="{_fmt(bound)}"}} {cum}'
            )
        lines.append(f'{self.name}_bucket{{le="+Inf"}} {total}')
        lines.append(f"{self.name}_sum {_fmt(s)}")
        lines.append(f"{self.name}_count {total}")
        return lines


class _FuncMetric:
    """Callback-backed counter/gauge: the callback returns a scalar, or a
    list of ``(labels_dict, value)`` pairs for labeled families (e.g. one
    ``hbm_used_gigabytes{device="N"}`` sample per local device)."""

    def __init__(self, name: str, help: str, mtype: str,
                 fn: Callable[[], Any]):
        self.name = name
        self.help = help
        self.mtype = mtype
        self.fn = fn

    def render(self) -> List[str]:
        n = self.name
        if self.mtype == "counter" and not n.endswith("_total"):
            n = n + "_total"
        lines = [
            f"# HELP {n} {_escape_help(self.help)}",
            f"# TYPE {n} {self.mtype}",
        ]
        try:
            out = self.fn()
        except Exception:
            # a scrape must never take the server down with it
            return lines
        if isinstance(out, list):
            for labels, value in out:
                lines.append(f"{n}{_labels_str(labels)} {_fmt(value)}")
        elif out is not None:
            lines.append(f"{n} {_fmt(out)}")
        return lines


class Registry:
    """Ordered collection of metrics with one ``render()`` to the
    ``text/plain; version=0.0.4`` exposition format.

    Get-or-create semantics: asking for an existing name returns the
    existing metric when the type matches (idempotent wiring), and raises
    when it does not (two meanings for one name is a scrape-side bug)."""

    CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

    def __init__(self):
        self._metrics: Dict[str, Any] = {}
        self._lock = threading.Lock()

    def _get_or_make(self, name: str, kind, factory):
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if not isinstance(existing, kind):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{type(existing).__name__}"
                    )
                return existing
            metric = factory()
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str) -> Counter:
        return self._get_or_make(name, Counter, lambda: Counter(name, help))

    def gauge(self, name: str, help: str) -> Gauge:
        return self._get_or_make(name, Gauge, lambda: Gauge(name, help))

    def histogram(self, name: str, help: str,
                  buckets: Sequence[float] = LATENCY_BUCKETS) -> Histogram:
        return self._get_or_make(
            name, Histogram, lambda: Histogram(name, help, buckets)
        )

    def _func(self, name: str, help: str, mtype: str, fn: Callable[[], Any]):
        metric = self._get_or_make(
            name, _FuncMetric, lambda: _FuncMetric(name, help, mtype, fn)
        )
        if metric.mtype != mtype:
            # both func flavors share _FuncMetric, so the class check alone
            # would silently hand a counter back to a gauge registration
            raise ValueError(
                f"metric {name!r} already registered as a {metric.mtype} func"
            )
        return metric

    def counter_func(self, name: str, help: str, fn: Callable[[], Any]):
        return self._func(name, help, "counter", fn)

    def gauge_func(self, name: str, help: str, fn: Callable[[], Any]):
        return self._func(name, help, "gauge", fn)

    def render(self) -> str:
        with self._lock:
            metrics = list(self._metrics.values())
        lines: List[str] = []
        for m in metrics:
            lines.extend(m.render())
        return "\n".join(lines) + "\n"
