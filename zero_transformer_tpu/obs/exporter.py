"""Standalone Prometheus /metrics exporter for non-serving processes.

The serving engine exposes its Registry through the API server's /metrics
route; TRAINING has no HTTP surface of its own, so its gauges (PR 8:
``train_bubble_frac``, ``train_exposed_comm_frac``, plus whatever later
PRs register) were previously reachable only through metrics.jsonl. This
is the missing scrape endpoint: a daemon-threaded stdlib HTTP server that
renders one Registry in the ``text/plain; version=0.0.4`` exposition
format. Zero hot-path cost — gauges are callback-backed and only read at
scrape time.

Usage (train.py ``--metrics-port``)::

    exporter = MetricsExporter(trainer.registry, port=9100)
    ...
    exporter.close()
"""
from __future__ import annotations

import logging
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from zero_transformer_tpu.obs.metrics import Registry

log = logging.getLogger("zero_transformer_tpu")


class MetricsExporter:
    """Serve ``registry.render()`` at GET /metrics on a daemon thread.

    ``port=0`` binds an ephemeral port (tests); the bound port is
    ``self.port``. Render errors return 500 rather than killing the
    serving thread — a broken gauge callback must not take the scrape
    endpoint (or the training loop) down with it."""

    def __init__(self, registry: Registry, port: int = 9100,
                 host: str = "0.0.0.0"):
        self.registry = registry

        exporter = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 (stdlib naming)
                if self.path.split("?")[0] != "/metrics":
                    self.send_error(404)
                    return
                try:
                    body = exporter.registry.render().encode()
                except Exception:  # noqa: BLE001 — see class docstring
                    log.exception("metrics exporter: render failed")
                    self.send_error(500)
                    return
                self.send_response(200)
                self.send_header("Content-Type", Registry.CONTENT_TYPE)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *args):  # scrapes are not run events
                pass

        self._server = ThreadingHTTPServer((host, port), Handler)
        self.port = self._server.server_address[1]
        self._thread = threading.Thread(
            target=self._server.serve_forever, name="metrics-exporter",
            daemon=True,
        )
        self._thread.start()
        log.info("metrics exporter: /metrics on %s:%d", host, self.port)

    def close(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        self._thread.join(timeout=5)
