"""Attention ops.

The XLA path below is the always-correct reference implementation: causal
multi-head/grouped-query attention with an additive bias (ALiBi) and a
float32 softmax — the dtype discipline the reference learned the hard way
(reference ``src/models/layers.py:167-173``; bug log ``logs/580.md:94-98``).

``dot_product_attention`` dispatches between this and the Pallas flash kernel
(``zero_transformer_tpu.ops.flash_attention``) which never materializes the
[T, T] score matrix the reference allocates in full (reference ``layers.py:159-173``).
"""
from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from zero_transformer_tpu.ops.positions import (
    NEG_INF,
    alibi_bias,
    alibi_slopes,
    causal_mask_bias,
)


def xla_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    alibi: bool = False,
    q_offset=0,
    segment_ids: Optional[jax.Array] = None,
    doc_ids: Optional[jax.Array] = None,
    softmax_scale: Optional[float] = None,
    slopes: Optional[jax.Array] = None,
) -> jax.Array:
    """Attention via explicit einsums, softmax in float32.

    Args:
      q: [B, Tq, H, D]
      k, v: [B, Tkv, KVH, D]; KVH must divide H (GQA).
      q_offset: position of q[0] within the full sequence (decode w/ KV cache).
        May be a traced scalar, or a traced [B] vector when every batch row
        sits at its own position (continuous-batching decode: one fused step
        over slots whose sequences have different lengths).
      slopes: optional [H] or [H, 1] f32 ALiBi slope override — for
        head-sharded callers (ulysses / TP local attention) whose local head
        0 is not global head 0.
      segment_ids: optional [B, Tkv] int mask; 0 = padding (masked out).
      doc_ids: optional [B, T] int document ids (Tq == Tkv); positions in
        DIFFERENT documents cannot attend to each other — the packed-sequence
        training mask.
    """
    B, Tq, H, D = q.shape
    _, Tkv, KVH, _ = k.shape
    G = H // KVH
    scale = softmax_scale if softmax_scale is not None else 1.0 / (D**0.5)

    qg = q.reshape(B, Tq, KVH, G, D)
    # scores in f32
    scores = jnp.einsum("btkgd,bskd->bkgts", qg, k, preferred_element_type=jnp.float32)
    scores = scores * jnp.float32(scale)

    per_row = getattr(q_offset, "ndim", 0) == 1
    if per_row:
        # per-row q positions: biases get a leading batch dim
        q_pos = q_offset[:, None] + jnp.arange(Tq, dtype=jnp.int32)[None, :]  # [B, Tq]
        kv_pos = jnp.arange(Tkv, dtype=jnp.int32)
        if alibi:
            s = (alibi_slopes(H) if slopes is None else slopes).reshape(H)
            dist = jnp.maximum(
                q_pos[:, :, None] - kv_pos[None, None, :], 0
            ).astype(jnp.float32)  # [B, Tq, Tkv]
            bias = -s[None, :, None, None] * dist[:, None]  # [B, H, Tq, Tkv]
            if causal:
                visible = kv_pos[None, None, :] <= q_pos[:, :, None]
                bias = bias + jnp.where(visible, 0.0, NEG_INF)[:, None]
            scores = scores + bias.reshape(B, KVH, G, Tq, Tkv)
        elif causal:
            visible = kv_pos[None, None, :] <= q_pos[:, :, None]  # [B, Tq, Tkv]
            scores = scores + jnp.where(visible, 0.0, NEG_INF)[:, None, None]
    elif alibi:
        bias = alibi_bias(H, Tq, Tkv, offset=q_offset, slopes=slopes)  # [H, Tq, Tkv]
        if causal:
            bias = bias + causal_mask_bias(Tq, Tkv, offset=q_offset)[None]
        scores = scores + bias.reshape(1, KVH, G, Tq, Tkv)
    elif causal:
        scores = scores + causal_mask_bias(Tq, Tkv, offset=q_offset)[None, None, None]
    if segment_ids is not None:
        pad = jnp.where(segment_ids[:, None, None, None, :] != 0, 0.0, NEG_INF)
        scores = scores + pad
    if doc_ids is not None:
        if Tq != Tkv:
            raise ValueError("doc_ids requires full-sequence shapes (Tq == Tkv)")
        same = doc_ids[:, :, None] == doc_ids[:, None, :]  # [B, Tq, Tkv]
        scores = scores + jnp.where(same, 0.0, NEG_INF)[:, None, None]

    weights = jax.nn.softmax(scores.astype(jnp.float32), axis=-1).astype(q.dtype)
    out = jnp.einsum("bkgts,bskd->btkgd", weights, v)
    return out.reshape(B, Tq, H, D)


def dot_product_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    alibi: bool = False,
    q_offset=0,
    segment_ids: Optional[jax.Array] = None,
    doc_ids: Optional[jax.Array] = None,
    impl: str = "auto",
) -> jax.Array:
    """Dispatching attention entry point used by the models.

    impl="auto" picks the Pallas flash kernel on TPU (or under
    ``ZT_PALLAS_INTERPRET=1`` interpret mode) for full-sequence causal
    training shapes — including document-masked packing — AND for the
    serving cache shapes (chunked prefill / spec-verify windows with a
    traced or per-row q_offset and a kv-validity segment mask), falling
    back to the XLA path everywhere else (single-token decode, CPU, odd
    shapes).
    """
    if impl in ("auto", "flash"):
        from zero_transformer_tpu.ops import flash_attention as fa

        if fa.supported(
            q, k, v, causal=causal, alibi=alibi, q_offset=q_offset,
            segment_ids=segment_ids, doc_ids=doc_ids,
        ):
            return fa.flash_attention(
                q, k, v, causal=causal, alibi=alibi, q_offset=q_offset,
                segment_ids=segment_ids, doc_ids=doc_ids,
            )
        if impl == "flash":
            # flash-or-raise contract: never silently hand an explicit
            # flash request the O(T^2) fallback
            raise NotImplementedError(
                f"flash attention unsupported for shapes q={q.shape} k={k.shape}"
            )
    return xla_attention(
        q,
        k,
        v,
        causal=causal,
        alibi=alibi,
        q_offset=q_offset,
        segment_ids=segment_ids,
        doc_ids=doc_ids,
    )
