"""Ulysses sequence parallelism: all-to-all context-parallel attention.

The reference has NO sequence/context parallelism (SURVEY §2 checklist; its
long-context story is ALiBi extrapolation plus *reducing* context to dodge
OOM, reference ``src/models/layers.py:80-101``, ``logs/1B.md:7``). This module
is the second of this framework's two context-parallel engines, alongside
``ops/ring_attention.py``:

- **ring**: K/V shards rotate with ``lax.ppermute`` (ICI neighbor exchange);
  per-chip memory stays at one KV shard; comm volume grows with the number of
  ring steps. Best at very long T where each fold is compute-heavy. Works at
  any head count.
- **ulysses** (this file): two ``lax.all_to_all`` reshards per attention call.
  Activations arrive sequence-sharded [B, T/n, H, D]; the first all-to-all
  re-shards them to head-sharded [B, T, H/n, D], each device runs ONE local
  flash-attention call over the FULL sequence for its head group, and the
  second all-to-all restores sequence sharding. Comm volume is O(T·d_model/n)
  per call regardless of T — cheaper than ring when the per-step folds are
  small — and the attention itself needs no cross-device softmax merging, so
  the flash kernel runs at exactly its single-chip efficiency.

The head dimension is the parallel resource: the ``sequence`` axis must divide
the (tensor-sharded) head counts, queries AND kv (GQA group boundaries always
align because H/KVH is preserved under the split). ALiBi slopes are sliced to
each device's global head range and handed to the shared attention wrappers
via their ``slopes`` override; packed-document ids are all-gathered (they are
[B, T] int — tiny) so the local mask is exact.

Composes with the same mesh axes as ring attention: batch over data/fsdp,
heads over ``tensor``, sequence over ``sequence``. Select per-model with
``ModelConfig.cp_impl = "ulysses"``.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from zero_transformer_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from zero_transformer_tpu.ops.attention import xla_attention
from zero_transformer_tpu.ops.positions import alibi_slopes
from zero_transformer_tpu.ops.ring_attention import (
    _axis_rank,
    _engine_ctx,
    _explicit_vjp_engine,
    _flash_local_ok,
    _specs,
    _validate_cp_shapes,
)
from zero_transformer_tpu.parallel.mesh import SEQUENCE_AXIS, TENSOR_AXIS


def _ulysses_body(
    q, k, v, ids, *, n, tp, H, causal, alibi, docs, scale, flash, interpret
):
    B, t, H_tp, D = q.shape
    if n > 1:
        # seq-sharded [B, T/n, h, D] → head-sharded [B, T, h/n, D]: local head
        # chunk j ships to sequence-rank j, time chunks concatenate in rank
        # order — device (tensor=r, sequence=s) ends up owning global heads
        # [r·H_tp + s·h_loc, r·H_tp + (s+1)·h_loc).
        q = jax.lax.all_to_all(q, SEQUENCE_AXIS, split_axis=2, concat_axis=1, tiled=True)
        k = jax.lax.all_to_all(k, SEQUENCE_AXIS, split_axis=2, concat_axis=1, tiled=True)
        v = jax.lax.all_to_all(v, SEQUENCE_AXIS, split_axis=2, concat_axis=1, tiled=True)
    ids_full = None
    if docs:
        ids_full = (
            jax.lax.all_gather(ids, SEQUENCE_AXIS, axis=1, tiled=True)
            if n > 1 else ids
        )

    H_loc = q.shape[2]
    slopes = None
    if alibi:
        h_off = _axis_rank(SEQUENCE_AXIS, n) * H_loc
        if tp > 1:
            h_off = h_off + _axis_rank(TENSOR_AXIS, tp) * H_tp
        slopes = jax.lax.dynamic_slice_in_dim(alibi_slopes(H), h_off, H_loc)
        slopes = slopes.reshape(H_loc, 1)

    if flash:
        from zero_transformer_tpu.ops.pallas.flash import flash_attention

        out = flash_attention(
            q, k, v, causal=causal, alibi=alibi, doc_ids=ids_full,
            softmax_scale=scale, slopes=slopes, interpret=interpret,
        )
    else:
        # NOT wrapped in jax.checkpoint: a checkpoint region inside this
        # shard_map body deadlocks the XLA:CPU collective rendezvous (the
        # rematerialized replay re-issues the surrounding collectives in a
        # divergent order across devices — observed hang at all-gather/
        # all-to-all, 8-device CPU mesh). Long-context memory is instead
        # governed by the model's per-block remat (cfg.remat), whose
        # checkpoint sits OUTSIDE the shard_map call and already discards
        # the [B, KVH, G, T, T] softmax residuals this fallback produces;
        # at long T use the flash engine anyway (this path is the
        # odd-shape/CPU fallback).
        out = xla_attention(
            q, k, v, causal=causal, alibi=alibi, softmax_scale=scale,
            doc_ids=ids_full, slopes=slopes,
        )

    if n > 1:
        # head-sharded back to seq-sharded: time chunk j returns to rank j,
        # head groups concatenate in rank order, restoring the original order.
        out = jax.lax.all_to_all(out, SEQUENCE_AXIS, split_axis=1, concat_axis=2, tiled=True)
    return out


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    alibi: bool = False,
    doc_ids: Optional[jax.Array] = None,
    softmax_scale: Optional[float] = None,
    impl: str = "auto",  # "auto" | "flash" | "xla"
    interpret: bool = False,  # run the Pallas engine interpreted (CPU tests)
) -> jax.Array:
    """Global-view Ulysses attention. q [B,T,H,D]; k,v [B,T,KVH,D].

    Requires the ``sequence`` axis size n to divide T and BOTH tensor-local
    head counts (H/tp and KVH/tp) — the head dimension is what Ulysses
    parallelizes over. Use ring attention when heads are too few.

    ``doc_ids`` [B, T] int: packed-sequence document mask, sharded over the
    sequence axis like q; all-gathered inside the body (ids are tiny).
    """
    B, T, H, D = q.shape
    _, S, KVH, _ = k.shape
    n = mesh.shape[SEQUENCE_AXIS]
    tp = mesh.shape[TENSOR_AXIS]
    _validate_cp_shapes("ulysses", T, S, n, tp, H, KVH)
    if (H // tp) % n or (KVH // tp) % n:
        raise ValueError(
            f"ulysses needs the sequence axis ({n}) to divide the tensor-local "
            f"head counts ({H // tp} query / {KVH // tp} kv); use cp_impl='ring' "
            f"for few-headed models"
        )
    scale = float(softmax_scale if softmax_scale is not None else 1.0 / (D**0.5))
    qkv_spec, _ = _specs(mesh, B, tp)
    ids_spec = P(qkv_spec[0], SEQUENCE_AXIS)
    # nested-context resolution (see ring_attention._engine_ctx): inside the
    # explicit ZeRO core the data/fsdp axes are already manual — drop them
    # from the specs and manualize only sequence(+tensor)
    mesh_arg, axes, (qkv_spec, ids_spec) = _engine_ctx(mesh, (qkv_spec, ids_spec))
    docs = doc_ids is not None

    # the local flash call sees the FULL sequence length T
    use_flash = impl in ("auto", "flash") and _flash_local_ok(T, D, q.dtype, interpret)
    if impl == "flash" and not use_flash:
        raise NotImplementedError(
            f"flash ulysses attention unsupported for T={T}, D={D}, dtype={q.dtype}"
        )

    ids = (
        doc_ids.astype(jnp.float32) if docs
        else jnp.zeros((B, T), jnp.float32)
    )
    # explicit recompute vjp shared with the XLA-fallback ring: jax's
    # transpose of a nested partial-manual shard_map mis-lowers, so the
    # backward re-differentiates the body inside a fresh shard_map
    body = functools.partial(
        _ulysses_body, n=n, tp=tp, H=H, causal=causal, alibi=alibi, docs=docs,
        scale=scale, flash=use_flash, interpret=interpret,
    )
    return _explicit_vjp_engine(
        body, mesh_arg, qkv_spec, ids_spec, axes, q, k, v, ids
    )
