from zero_transformer_tpu.ops.attention import dot_product_attention, xla_attention  # noqa: F401
from zero_transformer_tpu.ops.losses import (  # noqa: F401
    cross_entropy_loss,
    next_token_loss,
    token_log_likelihood,
)
from zero_transformer_tpu.ops.positions import alibi_bias, alibi_slopes, apply_rope  # noqa: F401
