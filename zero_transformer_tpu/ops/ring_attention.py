"""Ring attention: exact causal attention over a sequence-sharded mesh axis.

The reference has NO sequence/context parallelism — its long-context story is
ALiBi length extrapolation plus *reducing* context to dodge OOM (reference
``src/models/layers.py:80-101``, ``logs/1B.md:7``; SURVEY §2 checklist). This
module adds the TPU-native mechanism: activations stay sharded [B, T/n, H, D]
over the ``sequence`` mesh axis; K/V shards rotate around the ring with
``lax.ppermute`` (ICI neighbor exchange) while each device folds one KV shard
per step into an online-softmax merge. Attention is exact (same numerics as a
full all-gather) but peak memory per chip stays at one KV shard per in-flight
step and the transfers overlap with the block compute.

Two inner engines:

- **flash** (default on TPU): each ring step is one Pallas flash-attention
  call at the shard's global position offsets (``ops/pallas/flash.py
  flash_partial``), merged across steps by logsumexp weights; the backward is
  a ring of ``flash_grads`` calls against the GLOBAL lse (the flash identity
  p = exp(s - lse) makes per-shard backwards independent), with (dk, dv)
  accumulators riding the same ppermute ring home to their owners. HBM per
  step stays at flash-kernel level — no [t, t] score matrix ever exists.
- **xla** fallback (CPU tests, unsupported shapes): the same merge with plain
  einsums, rematerialized per step via ``jax.checkpoint``.

Global-view entry: ``ring_attention(q, k, v, mesh, ...)`` wraps the SPMD body
in ``shard_map`` with specs derived from the mesh (batch over data/fsdp axes,
sequence over ``sequence``, heads over ``tensor``), so it drops into a jitted
train step like any other op.
"""
from __future__ import annotations

import contextvars
import functools
import math
from typing import Optional

import jax
import jax.numpy as jnp
from zero_transformer_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, PartitionSpec as P

from zero_transformer_tpu.ops.positions import NEG_INF, alibi_slopes
from zero_transformer_tpu.parallel.mesh import (
    DATA_AXIS,
    FSDP_AXIS,
    SEQUENCE_AXIS,
    TENSOR_AXIS,
)

_INIT_M = -1e30


# -- shared helpers -----------------------------------------------------------


def _specs(mesh: Mesh, B: int, tp: int):
    batch_axes = tuple(a for a in (DATA_AXIS, FSDP_AXIS) if mesh.shape.get(a, 1) > 1)
    # keep only batch axes whose product divides B (small eval batches stay
    # replicated rather than erroring)
    while batch_axes and B % math.prod(mesh.shape[a] for a in batch_axes):
        batch_axes = batch_axes[:-1]
    head_axis = TENSOR_AXIS if tp > 1 else None
    qkv = P(batch_axes or None, SEQUENCE_AXIS, head_axis, None)
    lse = P(batch_axes or None, head_axis, SEQUENCE_AXIS, None)
    return qkv, lse


def _engine_ctx(mesh: Mesh, specs: tuple):
    """Resolve (mesh_arg, manual_axes, restricted_specs) for the engine's
    shard_maps so the context-parallel engines NEST inside the explicit
    ZeRO shard_map core (round-5: ZeRO-2/3 x sequence-parallel previously
    fell back to the GSPMD hint path, which compiled to ZERO
    reduce-scatters and weight-sized all-reduces — stage-1 traffic).

    Standalone (no ambient manual axes): unchanged full behavior — the
    engine manualizes every axis its specs mention (batch over data/fsdp,
    sequence, tensor), which the Pallas kernels require (GSPMD cannot
    auto-partition a pallas_call). Nested inside a partial-manual region:
    the axes already manual there (the ZeRO data/fsdp axes) are dropped
    from the specs — the batch dim arrives pre-sliced — and the engine
    manualizes only what remains; shard_map must then be handed the
    ambient ABSTRACT mesh, whose axis types record what is already manual
    (a concrete all-Auto mesh is rejected inside the region).
    """
    from zero_transformer_tpu.utils.jax_compat import get_abstract_mesh

    amesh = get_abstract_mesh()
    ctx_manual: set = set()
    mesh_arg = mesh
    if amesh is not None and amesh.axis_names and dict(amesh.shape) == dict(mesh.shape):
        ctx_manual = {
            name for name, t in zip(amesh.axis_names, amesh.axis_types)
            if t == jax.sharding.AxisType.Manual
        }
        if ctx_manual:
            mesh_arg = amesh
    mentioned: set = set()
    for s in specs:
        for e in s:
            if e is not None:
                mentioned |= set(e) if isinstance(e, tuple) else {e}
    axes = frozenset(mentioned - ctx_manual)

    def drop(spec: P) -> P:
        def keep(e):
            if e is None:
                return None
            kept = tuple(
                a for a in (e if isinstance(e, tuple) else (e,))
                if a not in ctx_manual
            )
            return kept if len(kept) > 1 else (kept[0] if kept else None)

        return P(*(keep(e) for e in spec))

    return mesh_arg, axes, tuple(drop(s) for s in specs)


# True while tracing an engine body that is NESTED inside another manual
# region (set by _engine_shard_map; read at trace time, so the chosen branch
# is baked per compiled program).
_NESTED_ENGINE = contextvars.ContextVar("zt_engine_nested", default=False)


def _axis_rank(name: str, size: int) -> jax.Array:
    """``jax.lax.axis_index``, except under NESTED partial-manual shard_map
    lowering: there, axis_index's Shardy lowering emits its own
    sdy.manual_computation binding EVERY manual axis, which is rejected
    ("operates on axis ... already bound by a parent" — upstream; plain
    collectives lower fine). The nested branch derives the rank from a tiny
    psum_scatter of an identical arange (device r's slice sums to size*r);
    the standalone hot path keeps the free axis_index."""
    if size == 1:
        return jnp.zeros((), jnp.int32)
    if not _NESTED_ENGINE.get():
        return jax.lax.axis_index(name)
    s = jax.lax.psum_scatter(
        jnp.arange(size, dtype=jnp.int32), name, scatter_dimension=0, tiled=True
    )
    return s[0] // size


def _engine_shard_map(fn, mesh, in_specs, out_specs, axes, operands):
    """ONE shard_map for an engine body, with the nested-context flag set
    while the body traces (see ``_axis_rank``). ``mesh`` carrying any
    Manual axis type marks the nested case."""
    nested = not isinstance(mesh, Mesh) and any(
        t == jax.sharding.AxisType.Manual for t in mesh.axis_types
    )
    token = _NESTED_ENGINE.set(nested)
    try:
        return shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            axis_names=axes, check_vma=False,
        )(*operands)
    finally:
        _NESTED_ENGINE.reset(token)


def _explicit_vjp_engine(body, mesh, qkv_spec, ids_spec, axes, q, k, v, ids):
    """Run ``body(q, k, v, ids)`` under one engine shard_map with an
    EXPLICIT recompute vjp: the backward differentiates the body INSIDE a
    fresh shard_map from the saved q/k/v/ids instead of letting jax
    transpose the forward shard_map — that transpose mis-lowers when the
    engine nests inside the explicit ZeRO core. Shared by the XLA-fallback
    ring and the Ulysses engine (the flash ring hand-rolls the same
    structure because its backward consumes the forward's lse)."""
    return _engine_vjp_call(q, k, v, ids, body, mesh, qkv_spec, ids_spec, axes)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _engine_vjp_call(q, k, v, ids, body, mesh, qkv_spec, ids_spec, axes):
    return _engine_shard_map(
        body, mesh, (qkv_spec,) * 3 + (ids_spec,), qkv_spec, axes,
        (q, k, v, ids),
    )


def _engine_vjp_fwd(q, k, v, ids, body, mesh, qkv_spec, ids_spec, axes):
    out = _engine_vjp_call(q, k, v, ids, body, mesh, qkv_spec, ids_spec, axes)
    return out, (q, k, v, ids)


def _engine_vjp_bwd(body, mesh, qkv_spec, ids_spec, axes, res, do):
    q, k, v, ids = res

    def bwd_body(q, k, v, ids, do):
        _, vjp = jax.vjp(lambda q, k, v: body(q, k, v, ids), q, k, v)
        return vjp(do)

    dq, dk, dv = _engine_shard_map(
        bwd_body, mesh, (qkv_spec,) * 3 + (ids_spec, qkv_spec), (qkv_spec,) * 3,
        axes, (q, k, v, ids, do),
    )
    return dq, dk, dv, jnp.zeros_like(ids)


_engine_vjp_call.defvjp(_engine_vjp_fwd, _engine_vjp_bwd)


def _local_slopes(H_global: int, H_local: int, tp: int, alibi: bool):
    """[H_local, 1] ALiBi slope table for this tensor-parallel shard (zeros
    when ALiBi is off — the kernels ignore it then)."""
    if not alibi:
        return jnp.zeros((H_local, 1), jnp.float32)
    all_slopes = alibi_slopes(H_global)
    if tp > 1:
        h_off = _axis_rank(TENSOR_AXIS, tp) * H_local
        return jax.lax.dynamic_slice_in_dim(all_slopes, h_off, H_local).reshape(
            H_local, 1
        )
    return all_slopes.reshape(H_local, 1)


def _rotate(x, axis_name: str, n: int):
    return jax.lax.ppermute(x, axis_name, [(j, (j + 1) % n) for j in range(n)])


def _validate_cp_shapes(kind: str, T: int, S: int, n: int, tp: int, H: int, KVH: int):
    """Shared entry guards for the context-parallel engines (ring / ulysses)."""
    if T != S:
        raise ValueError(f"{kind} attention requires q and kv sequence lengths equal")
    if T % n:
        raise ValueError(f"sequence length {T} not divisible by sequence axis {n}")
    if tp > 1 and (H % tp or KVH % tp):
        raise ValueError(f"heads ({H}, {KVH}) not divisible by tensor axis {tp}")
    if H % KVH:
        raise ValueError(f"query heads {H} not divisible by kv heads {KVH}")


# -- flash-backed ring (custom VJP) ------------------------------------------


def _ring_flash_fwd_body(q, k, v, ids, *, n, tp, H, causal, alibi, docs, scale, interpret):
    from zero_transformer_tpu.ops.pallas.flash import flash_partial

    B, t_q, H_l, D = q.shape
    my = _axis_rank(SEQUENCE_AXIS, n)
    q_off = my * t_q
    t_kv = k.shape[1]
    slopes = _local_slopes(H, H_l, tp, alibi)

    def fold(m, norm, acc, k_cur, v_cur, kid_cur, src):
        o_i, lse_i = flash_partial(
            q, k_cur, v_cur,
            causal=causal, alibi=alibi, softmax_scale=scale,
            q_offset=q_off, kv_offset=src * t_kv, slopes=slopes,
            q_ids=ids if docs else None, k_ids=kid_cur,
            interpret=interpret,
        )
        lse_i = lse_i[..., 0]  # [B, H_l, t_q]
        m_new = jnp.maximum(m, lse_i)
        w_prev = jnp.exp(m - m_new)
        w_i = jnp.exp(lse_i - m_new)
        norm_new = norm * w_prev + w_i
        wp = jnp.transpose(w_prev, (0, 2, 1))[..., None]  # [B, t_q, H_l, 1]
        wi = jnp.transpose(w_i, (0, 2, 1))[..., None]
        return m_new, norm_new, acc * wp + o_i * wi

    def step(carry, _):
        # ids ride the scan carry (and the ppermute ring) ONLY when packing:
        # the non-packed hot path pays zero extra collectives
        if docs:
            m, norm, acc, k_cur, v_cur, kid_cur, src = carry
        else:
            m, norm, acc, k_cur, v_cur, src = carry
            kid_cur = None
        m, norm, acc = fold(m, norm, acc, k_cur, v_cur, kid_cur, src)
        out = (
            m, norm, acc,
            _rotate(k_cur, SEQUENCE_AXIS, n), _rotate(v_cur, SEQUENCE_AXIS, n),
        )
        if docs:
            out += (_rotate(kid_cur, SEQUENCE_AXIS, n),)
        return out + ((src - 1) % n,), None

    m0 = jnp.full((B, H_l, t_q), _INIT_M, jnp.float32)
    n0 = jnp.zeros((B, H_l, t_q), jnp.float32)
    a0 = jnp.zeros((B, t_q, H_l, D), jnp.float32)
    init = (m0, n0, a0, k, v) + ((ids,) if docs else ()) + (my,)
    # n-1 rotated steps + a final fold without the (discarded) last rotation
    carry, _ = jax.lax.scan(step, init, None, length=n - 1)
    if docs:
        m, norm, acc, k_last, v_last, kid_last, src = carry
    else:
        m, norm, acc, k_last, v_last, src = carry
        kid_last = None
    m, norm, acc = fold(m, norm, acc, k_last, v_last, kid_last, src)
    norm_safe = jnp.where(norm == 0.0, 1.0, norm)
    out = acc / jnp.transpose(norm_safe, (0, 2, 1))[..., None]
    lse = (m + jnp.log(norm_safe))[..., None]  # [B, H_l, t_q, 1]
    return out.astype(q.dtype), lse


def _ring_flash_bwd_body(
    q, k, v, ids, o, lse, do, *, n, tp, H, causal, alibi, docs, scale, interpret
):
    from zero_transformer_tpu.ops.pallas.flash import flash_grads

    B, t_q, H_l, D = q.shape
    my = _axis_rank(SEQUENCE_AXIS, n)
    q_off = my * t_q
    t_kv = k.shape[1]
    slopes = _local_slopes(H, H_l, tp, alibi)
    # rowsum(do * o) is identical for every ring step — compute it once,
    # in the kernels' [B, H, T, 1] layout
    delta = jnp.swapaxes(
        jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1), 1, 2
    )[..., None]

    def grads_at(dq, dk_rot, dv_rot, k_cur, v_cur, kid_cur, src):
        dq_i, dk_i, dv_i = flash_grads(
            q, k_cur, v_cur, o, lse, do,
            causal=causal, alibi=alibi, softmax_scale=scale,
            q_offset=q_off, kv_offset=src * t_kv, slopes=slopes, delta=delta,
            q_ids=ids if docs else None, k_ids=kid_cur,
            interpret=interpret,
        )
        return dq + dq_i, dk_rot + dk_i, dv_rot + dv_i

    def step(carry, _):
        if docs:
            dq, dk_rot, dv_rot, k_cur, v_cur, kid_cur, src = carry
        else:
            dq, dk_rot, dv_rot, k_cur, v_cur, src = carry
            kid_cur = None
        dq, dk_rot, dv_rot = grads_at(dq, dk_rot, dv_rot, k_cur, v_cur, kid_cur, src)
        # (dk, dv) accumulators ride the ring WITH their kv shard; after the
        # final rotation they land back on the shard's owner
        out = (
            dq,
            _rotate(dk_rot, SEQUENCE_AXIS, n), _rotate(dv_rot, SEQUENCE_AXIS, n),
            _rotate(k_cur, SEQUENCE_AXIS, n), _rotate(v_cur, SEQUENCE_AXIS, n),
        )
        if docs:
            out += (_rotate(kid_cur, SEQUENCE_AXIS, n),)
        return out + ((src - 1) % n,), None

    dq0 = jnp.zeros(q.shape, jnp.float32)
    dkv0 = jnp.zeros(k.shape, jnp.float32)
    init = (dq0, dkv0, dkv0, k, v) + ((ids,) if docs else ()) + (my,)
    carry, _ = jax.lax.scan(step, init, None, length=n - 1)
    if docs:
        dq, dk, dv, k_last, v_last, kid_last, src = carry
    else:
        dq, dk, dv, k_last, v_last, src = carry
        kid_last = None
    # final step: fold the last shard, then rotate ONLY the grad accumulators
    # (the kv rotation would be discarded)
    dq, dk, dv = grads_at(dq, dk, dv, k_last, v_last, kid_last, src)
    dk = _rotate(dk, SEQUENCE_AXIS, n)
    dv = _rotate(dv, SEQUENCE_AXIS, n)
    return dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14))
def _ring_flash(
    q, k, v, ids, mesh, qkv_spec, lse_spec, ids_spec, n, tp, causal, alibi,
    scale, interpret, axes,
):
    out, _ = _ring_flash_fwd(
        q, k, v, ids, mesh, qkv_spec, lse_spec, ids_spec, n, tp, causal, alibi,
        scale, interpret, axes,
    )
    return out


def _ring_flash_fwd(
    q, k, v, ids, mesh, qkv_spec, lse_spec, ids_spec, n, tp, causal, alibi,
    scale, interpret, axes,
):
    H = q.shape[2]
    docs = ids is not None
    if not docs:  # dummy rides the ring; the static flag skips mask compute
        ids = jnp.zeros(q.shape[:2], jnp.float32)
    body = functools.partial(
        _ring_flash_fwd_body,
        n=n, tp=tp, H=H, causal=causal, alibi=alibi, docs=docs, scale=scale,
        interpret=interpret,
    )
    out, lse = _engine_shard_map(
        body, mesh, (qkv_spec, qkv_spec, qkv_spec, ids_spec),
        (qkv_spec, lse_spec), axes, (q, k, v, ids),
    )
    return out, (q, k, v, ids if docs else None, out, lse)


def _ring_flash_bwd(
    mesh, qkv_spec, lse_spec, ids_spec, n, tp, causal, alibi, scale, interpret,
    axes, res, do,
):
    q, k, v, ids, out, lse = res
    H = q.shape[2]
    docs = ids is not None
    d_ids = None if ids is None else jnp.zeros_like(ids)
    if not docs:
        ids = jnp.zeros(q.shape[:2], jnp.float32)
    body = functools.partial(
        _ring_flash_bwd_body,
        n=n, tp=tp, H=H, causal=causal, alibi=alibi, docs=docs, scale=scale,
        interpret=interpret,
    )
    dq, dk, dv = _engine_shard_map(
        body, mesh,
        (qkv_spec, qkv_spec, qkv_spec, ids_spec, qkv_spec, lse_spec, qkv_spec),
        (qkv_spec, qkv_spec, qkv_spec), axes, (q, k, v, ids, out, lse, do),
    )
    return dq, dk, dv, d_ids


_ring_flash.defvjp(_ring_flash_fwd, _ring_flash_bwd)


# -- XLA fallback ring (autodiff through the scan) ---------------------------


def _block_bias(slopes, q_off, kv_off, t_q: int, t_kv: int, causal: bool):
    """[H|1, t_q, t_kv] f32 bias; offsets may be traced scalars."""
    q_pos = q_off + jnp.arange(t_q, dtype=jnp.int32)
    kv_pos = kv_off + jnp.arange(t_kv, dtype=jnp.int32)
    dist = q_pos[:, None] - kv_pos[None, :]
    bias = jnp.zeros((1, t_q, t_kv), jnp.float32)
    if slopes is not None:
        bias = bias - slopes[:, None, None] * jnp.maximum(dist, 0).astype(jnp.float32)
    if causal:
        bias = bias + jnp.where(dist >= 0, 0.0, NEG_INF).astype(jnp.float32)
    return bias


def _ring_xla_body(q, k, v, ids, *, n, tp, H, causal, alibi, docs, scale):
    """Einsum inner engine: same merge math, full [t_q, t_kv] block per step
    (rematerialized in the backward via jax.checkpoint)."""
    B, t_q, H_l, D = q.shape
    _, t_kv, KVH, _ = k.shape
    G = H_l // KVH
    qg = q.reshape(B, t_q, KVH, G, D)
    my = _axis_rank(SEQUENCE_AXIS, n)
    q_off = my * t_q
    slopes = _local_slopes(H, H_l, tp, alibi)[:, 0] if alibi else None

    @jax.checkpoint
    def fold(m, l, acc, k_cur, v_cur, kid_cur, src):
        bias = _block_bias(slopes, q_off, src * t_kv, t_q, t_kv, causal)
        s = jnp.einsum(
            "btkgd,bskd->bkgts", qg, k_cur, preferred_element_type=jnp.float32
        )
        s = s * jnp.float32(scale)
        if bias.shape[0] == 1:
            s = s + bias[None, :, None]
        else:
            s = s + bias.reshape(1, KVH, G, t_q, t_kv)
        if docs:
            same = ids[:, :, None] == kid_cur[:, None, :]  # [B, t_q, t_kv]
            s = s + jnp.where(same, 0.0, NEG_INF)[:, None, None]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m_new[..., None])
        alpha = jnp.exp(m - m_new)
        l_new = alpha * l + jnp.sum(p, axis=-1)
        pv = jnp.einsum(
            "bkgts,bskd->btkgd", p, v_cur, preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc * alpha.transpose(0, 3, 1, 2)[..., None] + pv

    def step(carry, _):
        if docs:
            m, l, acc, k_cur, v_cur, kid_cur, src = carry
        else:
            m, l, acc, k_cur, v_cur, src = carry
            kid_cur = None
        m, l, acc = fold(m, l, acc, k_cur, v_cur, kid_cur, src)
        out = (
            m, l, acc,
            _rotate(k_cur, SEQUENCE_AXIS, n), _rotate(v_cur, SEQUENCE_AXIS, n),
        )
        if docs:
            out += (_rotate(kid_cur, SEQUENCE_AXIS, n),)
        return out + ((src - 1) % n,), None

    m0 = jnp.full((B, KVH, G, t_q), _INIT_M, jnp.float32)
    l0 = jnp.zeros((B, KVH, G, t_q), jnp.float32)
    a0 = jnp.zeros((B, t_q, KVH, G, D), jnp.float32)
    init = (m0, l0, a0, k, v) + ((ids,) if docs else ()) + (my,)
    # n-1 rotated steps + a final fold without the (discarded) last rotation
    carry, _ = jax.lax.scan(step, init, None, length=n - 1)
    if docs:
        m, l, acc, k_last, v_last, kid_last, src = carry
    else:
        m, l, acc, k_last, v_last, src = carry
        kid_last = None
    m, l, acc = fold(m, l, acc, k_last, v_last, kid_last, src)
    l_safe = jnp.where(l == 0.0, 1.0, l)
    out = acc / l_safe.transpose(0, 3, 1, 2)[..., None]
    return out.reshape(B, t_q, H_l, D).astype(q.dtype)


# -- public entry -------------------------------------------------------------


def _flash_local_ok(t_local: int, D: int, dtype, interpret: bool) -> bool:
    from zero_transformer_tpu.ops.pallas.flash import pick_block

    if pick_block(t_local, 512) is None:
        return False
    if D % 64 or D > 256:
        return False
    if dtype not in (jnp.bfloat16, jnp.float32):
        return False
    if not interpret and jax.default_backend() != "tpu":
        return False
    return True


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    causal: bool = True,
    alibi: bool = False,
    doc_ids: Optional[jax.Array] = None,
    softmax_scale: Optional[float] = None,
    impl: str = "auto",  # "auto" | "flash" | "xla"
    interpret: bool = False,  # run the Pallas engine interpreted (CPU tests)
) -> jax.Array:
    """Global-view ring attention. q [B,T,H,D]; k,v [B,T,KVH,D].

    T must divide by the ``sequence`` axis size; heads by the ``tensor`` axis
    size when that is >1. With sequence=1 this degrades to a single local
    fold (still correct, but use the flash/XLA paths instead).

    ``doc_ids`` [B, T] int: packed-sequence document mask — ids shard over
    the sequence axis with q, and each device's kv ids ride the ppermute
    ring with its kv shard, so cross-shard cross-document attention is
    masked exactly.
    """
    B, T, H, D = q.shape
    _, S, KVH, _ = k.shape
    n = mesh.shape[SEQUENCE_AXIS]
    tp = mesh.shape[TENSOR_AXIS]
    _validate_cp_shapes("ring", T, S, n, tp, H, KVH)
    scale = float(softmax_scale if softmax_scale is not None else 1.0 / (D**0.5))
    qkv_spec, lse_spec = _specs(mesh, B, tp)
    ids_spec = P(qkv_spec[0], SEQUENCE_AXIS)
    mesh_arg, axes, (qkv_spec, lse_spec, ids_spec) = _engine_ctx(
        mesh, (qkv_spec, lse_spec, ids_spec)
    )
    docs = doc_ids is not None
    ids = doc_ids.astype(jnp.float32) if docs else None

    use_flash = impl in ("auto", "flash") and _flash_local_ok(
        T // n, D, q.dtype, interpret
    )
    if impl == "flash" and not use_flash:
        raise NotImplementedError(
            f"flash ring attention unsupported for local shape "
            f"T/n={T // n}, D={D}, dtype={q.dtype}"
        )
    if use_flash:
        return _ring_flash(
            q, k, v, ids, mesh_arg, qkv_spec, lse_spec, ids_spec, n, tp, causal,
            alibi, scale, interpret, axes,
        )

    if not docs:
        ids = jnp.zeros((B, T), jnp.float32)
    body = functools.partial(
        _ring_xla_body, n=n, tp=tp, H=H, causal=causal, alibi=alibi, docs=docs,
        scale=scale,
    )
    return _explicit_vjp_engine(
        body, mesh_arg, qkv_spec, ids_spec, axes, q, k, v, ids
    )
