"""Flash attention dispatch gate for ``ops.attention.dot_product_attention``.

``supported`` decides whether the Pallas TPU kernel
(``zero_transformer_tpu.ops.pallas.flash``) handles the call; anything it
declines (decode steps with a query offset, padded batches via segment_ids,
CPU test runs, odd shapes) falls back to the XLA path, keeping one call site
for the hot op.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from zero_transformer_tpu.ops.pallas.flash import (
    DEFAULT_BLOCK_K,
    DEFAULT_BLOCK_Q,
    flash_attention as _pallas_flash,
    pick_block,
)


def supported(
    q, k, v, *, causal: bool, alibi: bool = False, q_offset=0,
    segment_ids=None, doc_ids=None,
) -> bool:
    # q_offset must be a static 0 (full-sequence training shapes): the kernel
    # has no offset plumbing, so a decode-style call must take the XLA path.
    if not (isinstance(q_offset, int) and q_offset == 0):
        return False
    if segment_ids is not None:
        return False
    if doc_ids is not None and q.shape[1] != k.shape[1]:
        return False  # document masking needs full self-attention shapes
    if jax.default_backend() != "tpu":
        return False
    B, T, H, D = q.shape
    _, S, KVH, _ = k.shape
    if H % KVH:
        return False
    if pick_block(T, DEFAULT_BLOCK_Q) is None or pick_block(S, DEFAULT_BLOCK_K) is None:
        return False
    if D % 64 or D > 256:
        return False  # lane-dim alignment for the MXU
    if q.dtype not in (jnp.bfloat16, jnp.float32):
        return False
    return True


def flash_attention(
    q, k, v, *, causal: bool = True, alibi: bool = False, doc_ids=None
) -> jax.Array:
    return _pallas_flash(q, k, v, causal=causal, alibi=alibi, doc_ids=doc_ids)
