"""Pallas TPU flash attention (placeholder gate — kernel lands in ops/pallas/).

Until the kernel is wired in, ``supported`` returns False so the dispatcher in
``ops.attention`` always takes the XLA path. This keeps a single call site for
the hot op while the Pallas implementation matures.
"""
from __future__ import annotations

import jax


def supported(q, k, v, *, causal: bool, alibi: bool = False, q_offset=0, segment_ids=None) -> bool:
    # q_offset must be a static 0 (full-sequence training shapes): the kernel
    # has no offset plumbing, so a decode-style call must take the XLA path.
    if not (isinstance(q_offset, int) and q_offset == 0):
        return False
    if segment_ids is not None:
        return False
    return False  # kernel not wired in yet


def flash_attention(q, k, v, *, causal: bool = True, alibi: bool = False) -> jax.Array:
    raise NotImplementedError("pallas flash attention not wired in yet")
