"""Flash attention dispatch gate for ``ops.attention.dot_product_attention``.

``supported`` decides whether the Pallas flash kernel
(``zero_transformer_tpu.ops.pallas.flash``) handles the call; anything it
declines falls back to the XLA path, keeping one call site for the hot op.

Since PR 11 the gate accepts the SERVING cache shapes it used to decline:
a traced scalar or per-row ``[B]`` ``q_offset`` (the engine's vector cache
index — chunked prefill windows, spec-verify blocks) and a ``[B, S]``
``segment_ids`` kv-validity mask both route to the forward-only
``flash_serving`` kernel entry. What still falls back to XLA, by design:

- single-token decode (T = 1 — no legal sublane block; the PAGED decode
  kernel owns that dispatch, ``ops.pallas.paged_attention``);
- non-TPU backends, unless ``ZT_PALLAS_INTERPRET=1`` opts into Pallas
  interpret mode (how this CPU image exercises the kernels' numerics);
- shapes without a sublane-aligned block decomposition, head widths the
  MXU lane layout cannot take, f16, packed doc masks on cache shapes.

The gate and the wrapper share ONE keyword surface — every kwarg
``supported`` inspects, ``flash_attention`` threads to the kernel (pinned
by test: the gate may never advertise a distinction it then drops).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from zero_transformer_tpu.ops.pallas.flash import (
    DEFAULT_BLOCK_K,
    DEFAULT_BLOCK_Q,
    flash_attention as _pallas_flash,
    flash_serving as _pallas_serving,
    pick_block,
)


def interpret_enabled() -> bool:
    """``ZT_PALLAS_INTERPRET=1``: run the Pallas kernels in interpret mode
    off-TPU (CPU parity tests / bench lanes). Trace-time read — set it
    before building the model or engine. ONE implementation shared with
    the paged gate (``ops.pallas.paged_attention.interpret_requested``)
    so the two kernels can never disagree about interpret mode."""
    from zero_transformer_tpu.ops.pallas.paged_attention import (
        interpret_requested,
    )

    return interpret_requested()


def _is_training_call(q_offset, segment_ids) -> bool:
    """Static-zero offset and no validity mask = the full-sequence
    self-attention shape the differentiable custom-VJP kernel serves."""
    return (
        isinstance(q_offset, int) and q_offset == 0 and segment_ids is None
    )


def supported(
    q, k, v, *, causal: bool, alibi: bool = False, q_offset=0,
    segment_ids=None, doc_ids=None,
) -> bool:
    B, T, H, D = q.shape
    _, S, KVH, _ = k.shape
    if H % KVH:
        return False
    on_tpu = jax.default_backend() == "tpu"
    if not on_tpu and not interpret_enabled():
        return False
    if q.dtype not in (jnp.bfloat16, jnp.float32) or k.dtype != q.dtype:
        return False
    if on_tpu and (D % 64 or D > 256):
        # Mosaic lane-dim constraint — interpret mode (the CPU parity
        # lane) has no tiling and accepts any structurally valid width
        return False
    if not _is_training_call(q_offset, segment_ids):
        # serving path: forward-only kernel with per-row offsets + validity
        if getattr(q_offset, "ndim", None) not in (0, 1) and not isinstance(
            q_offset, int
        ):
            return False
        if doc_ids is not None:
            return False  # cache shapes never carry packed-doc masks
        if segment_ids is not None and tuple(segment_ids.shape) != (B, S):
            return False
    if doc_ids is not None and T != S:
        return False  # document masking needs full self-attention shapes
    # alibi imposes no extra shape constraint (slopes interpolate for any
    # head count, and the per-row bias path covers vector offsets) — but it
    # IS threaded to the kernel below; the signature-parity test pins that
    bq = pick_block(T, DEFAULT_BLOCK_Q)
    bk = pick_block(S, DEFAULT_BLOCK_K)
    if bq is None or bk is None:
        return False
    if on_tpu:
        floor = 16 if q.dtype == jnp.bfloat16 else 8
        if bq % floor or bk % floor:
            return False
    return True


def flash_attention(
    q, k, v, *, causal: bool = True, alibi: bool = False, q_offset=0,
    segment_ids=None, doc_ids=None,
) -> jax.Array:
    """Kernel wrapper with EXACTLY the gate's keyword surface. Training
    shapes take the differentiable custom-VJP entry; serving shapes
    (traced/vector offsets, validity masks) take the forward-only entry."""
    interpret = jax.default_backend() != "tpu" and interpret_enabled()
    if _is_training_call(q_offset, segment_ids):
        return _pallas_flash(
            q, k, v, causal=causal, alibi=alibi, doc_ids=doc_ids,
            interpret=interpret,
        )
    return _pallas_serving(
        q, k, v, causal=causal, alibi=alibi, q_offset=q_offset,
        segment_ids=segment_ids, interpret=interpret,
    )
