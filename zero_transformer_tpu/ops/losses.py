"""Loss functions.

TPU-native cross entropy: integer labels + ``take_along_axis`` instead of the
reference's materialized one-hot matmul (reference ``src/utils/losses.py:9-23``
builds a [B*T, vocab] one-hot — 50304x the label memory). The log-softmax is
computed in float32 regardless of input dtype, preserving the reference's
bf16-safety guarantee (reference ``losses.py:22``; bug history ``logs/580.md:94-106``).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def cross_entropy_loss(
    logits: jax.Array,
    labels: jax.Array,
    ignore_index: Optional[int] = None,
    z_loss: float = 0.0,
) -> jax.Array:
    """Mean token-level cross entropy, computed in float32.

    Args:
      logits: [..., vocab] in any float dtype.
      labels: [...] int token ids.
      ignore_index: label value to mask out of the mean (e.g. padding).
      z_loss: coefficient for the PaLM-style log-Z regularizer (stabilizes
        logits in bf16 training; 0 disables).
    """
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    nll = lse - label_logits
    if z_loss:
        nll = nll + z_loss * jnp.square(lse)
    if ignore_index is not None:
        mask = (labels != ignore_index).astype(jnp.float32)
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def next_token_loss(
    logits: jax.Array,
    tokens: jax.Array,
    ignore_index: Optional[int] = None,
    z_loss: float = 0.0,
) -> jax.Array:
    """Shifted LM loss: predict tokens[t+1] from logits[t].

    Matches the reference's in-model shift (reference ``GPT.py:102-113``).

    Args:
      logits: [..., T, vocab].
      tokens: [..., T] int ids (same sequence that produced the logits).
    """
    return cross_entropy_loss(
        logits[..., :-1, :], tokens[..., 1:], ignore_index=ignore_index, z_loss=z_loss
    )


def chunked_next_token_loss(
    hidden: jax.Array,
    w_dv: jax.Array,
    tokens: jax.Array,
    chunk_size: int,
    ignore_index: Optional[int] = None,
    z_loss: float = 0.0,
) -> jax.Array:
    """``next_token_loss`` computed from pre-head hidden states WITHOUT ever
    materializing the [B, T, vocab] logits.

    At real scale the logits are the single largest activation in the step —
    1.3B/50k-vocab at 8x1024 tokens is 1.6 GB in f32, paid again in the
    backward — and they exist only to be reduced to one scalar. This chunks
    the SEQUENCE dim (batch stays whole, so data/batch sharding is
    untouched): a ``lax.scan`` projects ``chunk_size`` positions at a time
    onto the vocab, reduces them to (nll_sum, count), and discards the tile;
    ``jax.checkpoint`` on the tile makes the backward recompute it, so peak
    logits memory is [B, chunk_size, vocab] in BOTH directions. Same f32
    log-softmax discipline as ``cross_entropy_loss``.

    Args:
      hidden: [B, T, d] post-final-norm hidden states (model compute dtype).
      w_dv: [d, vocab] projection — the tied embedding TRANSPOSED, or the
        untied lm_head kernel as stored.
      tokens: [B, T] int ids (the same sequence that produced ``hidden``).
      chunk_size: positions projected per scan tick (tile T-dim).
      ignore_index / z_loss: as in ``cross_entropy_loss``.
    """
    B, T, D = hidden.shape
    h = hidden[:, :-1, :]
    tgt = tokens[:, 1:]
    n_pos = T - 1
    valid = (
        jnp.ones((B, n_pos), jnp.bool_)
        if ignore_index is None
        else tgt != ignore_index
    )
    tgt = jnp.where(valid, tgt, 0)  # keep the gather in-bounds for -1 labels
    pad = (-n_pos) % chunk_size
    if pad:  # explicit pad: a clamped dynamic_slice would misalign labels
        h = jnp.pad(h, ((0, 0), (0, pad), (0, 0)))
        tgt = jnp.pad(tgt, ((0, 0), (0, pad)))
        valid = jnp.pad(valid, ((0, 0), (0, pad)))
    n_chunks = (n_pos + pad) // chunk_size
    w = w_dv.astype(hidden.dtype)

    @jax.checkpoint
    def tile_stats(h_c, t_c, v_c):
        logits = (h_c @ w).astype(jnp.float32)  # [B, chunk, V] — the tile
        lse = jax.scipy.special.logsumexp(logits, axis=-1)
        lab = jnp.take_along_axis(logits, t_c[..., None], axis=-1)[..., 0]
        nll = lse - lab
        if z_loss:
            nll = nll + z_loss * jnp.square(lse)
        m = v_c.astype(jnp.float32)
        return jnp.sum(nll * m), jnp.sum(m)

    def body(carry, i):
        s, c = carry
        start = i * chunk_size
        ds, dc = tile_stats(
            jax.lax.dynamic_slice_in_dim(h, start, chunk_size, axis=1),
            jax.lax.dynamic_slice_in_dim(tgt, start, chunk_size, axis=1),
            jax.lax.dynamic_slice_in_dim(valid, start, chunk_size, axis=1),
        )
        return (s + ds, c + dc), None

    (s, c), _ = jax.lax.scan(
        body,
        (jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
        jnp.arange(n_chunks),
    )
    return s / jnp.maximum(c, 1.0)


def token_log_likelihood(logits: jax.Array, tokens: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-position log p(tokens[t+1] | tokens[<=t]) and greedy-match flags.

    Used by the eval harness (LAMBADA PPL/ACC — replaces the reference's
    GPU-side lm-eval-harness path, SURVEY §6).

    Returns:
      (logprobs [..., T-1], is_greedy [..., T-1] bool)
    """
    logits = logits[..., :-1, :].astype(jnp.float32)
    targets = tokens[..., 1:]
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
    is_greedy = jnp.argmax(logits, axis=-1) == targets
    return ll, is_greedy
