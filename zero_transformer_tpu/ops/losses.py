"""Loss functions.

TPU-native cross entropy: integer labels + ``take_along_axis`` instead of the
reference's materialized one-hot matmul (reference ``src/utils/losses.py:9-23``
builds a [B*T, vocab] one-hot — 50304x the label memory). The log-softmax is
computed in float32 regardless of input dtype, preserving the reference's
bf16-safety guarantee (reference ``losses.py:22``; bug history ``logs/580.md:94-106``).
"""
from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp


def cross_entropy_loss(
    logits: jax.Array,
    labels: jax.Array,
    ignore_index: Optional[int] = None,
    z_loss: float = 0.0,
) -> jax.Array:
    """Mean token-level cross entropy, computed in float32.

    Args:
      logits: [..., vocab] in any float dtype.
      labels: [...] int token ids.
      ignore_index: label value to mask out of the mean (e.g. padding).
      z_loss: coefficient for the PaLM-style log-Z regularizer (stabilizes
        logits in bf16 training; 0 disables).
    """
    logits = logits.astype(jnp.float32)
    lse = jax.scipy.special.logsumexp(logits, axis=-1)
    label_logits = jnp.take_along_axis(logits, labels[..., None], axis=-1)[..., 0]
    nll = lse - label_logits
    if z_loss:
        nll = nll + z_loss * jnp.square(lse)
    if ignore_index is not None:
        mask = (labels != ignore_index).astype(jnp.float32)
        return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)
    return jnp.mean(nll)


def next_token_loss(
    logits: jax.Array,
    tokens: jax.Array,
    ignore_index: Optional[int] = None,
    z_loss: float = 0.0,
) -> jax.Array:
    """Shifted LM loss: predict tokens[t+1] from logits[t].

    Matches the reference's in-model shift (reference ``GPT.py:102-113``).

    Args:
      logits: [..., T, vocab].
      tokens: [..., T] int ids (same sequence that produced the logits).
    """
    return cross_entropy_loss(
        logits[..., :-1, :], tokens[..., 1:], ignore_index=ignore_index, z_loss=z_loss
    )


def token_log_likelihood(logits: jax.Array, tokens: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Per-position log p(tokens[t+1] | tokens[<=t]) and greedy-match flags.

    Used by the eval harness (LAMBADA PPL/ACC — replaces the reference's
    GPU-side lm-eval-harness path, SURVEY §6).

    Returns:
      (logprobs [..., T-1], is_greedy [..., T-1] bool)
    """
    logits = logits[..., :-1, :].astype(jnp.float32)
    targets = tokens[..., 1:]
    logprobs = jax.nn.log_softmax(logits, axis=-1)
    ll = jnp.take_along_axis(logprobs, targets[..., None], axis=-1)[..., 0]
    is_greedy = jnp.argmax(logits, axis=-1) == targets
    return ll, is_greedy
