"""Position encodings: ALiBi slopes/bias and RoPE.

ALiBi math mirrors the reference's capability (reference ``src/models/layers.py:17-44``:
geometric slope schedule with the non-power-of-2 interpolation from the ALiBi
paper) but is re-derived here in closed form and built lazily under jit for the
trace-time sequence length — this is what gives train-short/test-long
extrapolation (reference ``logs/580.md:30``).
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

NEG_INF = -1e10  # additive mask value; large but finite so f32 softmax is exact


@functools.lru_cache(maxsize=None)
def alibi_slopes_list(n_heads: int) -> tuple:
    """ALiBi head slopes: geometric sequence starting at 2^(-8/n) for
    power-of-two n, with the published interpolation otherwise."""

    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start**i) for i in range(n)]

    if math.log2(n_heads).is_integer():
        return tuple(pow2_slopes(n_heads))
    closest = 2 ** math.floor(math.log2(n_heads))
    extra = pow2_slopes(2 * closest)[0::2][: n_heads - closest]
    return tuple(pow2_slopes(closest) + extra)


def alibi_slopes(n_heads: int, dtype=jnp.float32) -> jax.Array:
    return jnp.asarray(alibi_slopes_list(n_heads), dtype=dtype)


def alibi_bias(
    n_heads: int, q_len: int, kv_len: int, offset: int = 0, dtype=jnp.float32,
    slopes=None,
) -> jax.Array:
    """[n_heads, q_len, kv_len] additive attention bias: -slope * distance.

    ``offset`` positions the query block within the full sequence — used for
    single-query decode with a KV cache, where q position = offset (the
    capability the reference's Flax side lacks and its torch side rebuilds
    dynamically, reference ``torch_compatability/GPT2.py:191-235``).
    ``slopes`` ([n_heads] or [n_heads, 1]) overrides the slope table for
    head-sharded callers whose local head 0 is not global head 0.
    """
    q_pos = jnp.arange(q_len, dtype=jnp.int32) + offset
    kv_pos = jnp.arange(kv_len, dtype=jnp.int32)
    # distance to the key, clamped at 0 (future keys are masked separately)
    dist = jnp.maximum(q_pos[:, None] - kv_pos[None, :], 0).astype(dtype)
    if slopes is None:
        slopes = alibi_slopes(n_heads, dtype)
    slopes = slopes.reshape(n_heads).astype(dtype)
    return -slopes[:, None, None] * dist[None, :, :]


def causal_mask_bias(q_len: int, kv_len: int, offset: int = 0, dtype=jnp.float32) -> jax.Array:
    """[q_len, kv_len] additive causal mask (0 where visible, NEG_INF where not)."""
    q_pos = jnp.arange(q_len, dtype=jnp.int32) + offset
    kv_pos = jnp.arange(kv_len, dtype=jnp.int32)
    visible = kv_pos[None, :] <= q_pos[:, None]
    return jnp.where(visible, 0.0, NEG_INF).astype(dtype)


def rope_frequencies(head_dim: int, theta: float = 10000.0) -> jax.Array:
    """Inverse frequencies for rotary embeddings, [head_dim // 2] float32."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    return 1.0 / (theta**exponent)


def apply_rope(
    x: jax.Array, positions: jax.Array, theta: float = 10000.0
) -> jax.Array:
    """Rotate [..., T, n_heads, head_dim] by position. ``positions`` is [T] or
    broadcastable to x's batch+time dims; rotation math runs in float32."""
    head_dim = x.shape[-1]
    freqs = rope_frequencies(head_dim, theta)  # [hd/2]
    angles = positions[..., None].astype(jnp.float32) * freqs  # [..., T, hd/2]
    # insert head axis
    angles = angles[..., None, :]
    sin, cos = jnp.sin(angles), jnp.cos(angles)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    rotated = jnp.concatenate([x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return rotated.astype(x.dtype)
