"""Paged-attention decode kernel: block tables read INSIDE the kernel grid.

The serving engine's paged KV cache (``serving/slots.py``, PR 6) stores K/V
in a global page pool ``[n_pages, page, KVH, D]`` addressed through per-row
int32 block tables. Until this kernel, every decode/spec-verify dispatch
first materialized a gather-to-slab view — ``jnp.take(pool, table)`` builds
a fresh ``[B, cache_len, KVH, D]`` copy of every live row's K/V per token —
and then ran the slab attention over it. That gather is pure HBM traffic
the math never needed: attention only has to *read* each page once.

This kernel walks the block table inside the Pallas grid instead: grid
``(B, KVH, n_blocks)``, with the page axis resolved per grid step through a
scalar-prefetched table (``PrefetchScalarGridSpec``) so the BlockSpec index
map fetches ``pool[table[b, j]]`` directly — the pipelined HBM→VMEM copy IS
the page walk, and no slab view ever exists. int8 KV pages dequantize
in-register (per-page scale blocks ride the same index map) on their way
into the VMEM K/V scratch.

Bit-exactness contract: the kernel computes, per (row, kv-head), the exact
op sequence of the gather path (``jnp.take`` + ``ops.attention.xla_attention``
per-row branch) — same dot shapes per contraction, same f32 bias add order,
same ``jax.nn.softmax`` reduction, same output-dot dtypes — so its output is
bit-identical to the gather path on the same backend (pinned by
``tests/test_paged_kernel.py`` across page sizes, ragged tables, trash-page
rows, and int8 scales). Swapping the read path can therefore never change a
served token.

VMEM note: the whole row's K/V lands in a ``[cache_len, D]`` scratch pair
per (row, head) — at D=128 bf16 that is 0.5 MB per 1k cache positions, so
decode contexts to ~8k fit comfortably; past that, a production variant
would switch to an online-softmax page walk (and forfeit the bitwise
contract vs the full-softmax slab path).
"""
from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu imports on CPU builds too; guard anyway
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from zero_transformer_tpu.ops.positions import NEG_INF, alibi_slopes

# decode window ceiling: 1 (plain decode) .. 1 + draft_k (spec verify).
# Larger query windows belong to the flash kernel's chunked-prefill path.
MAX_DECODE_T = 8


def interpret_requested() -> bool:
    """True when ``ZT_PALLAS_INTERPRET=1``: run the Pallas kernels in
    interpret mode off-TPU so their numerics are exercised on this CPU
    image (tests, bench parity lanes). Read at TRACE time — flip it before
    building the engine/model, not mid-run."""
    return os.environ.get("ZT_PALLAS_INTERPRET", "") == "1"


def supported(
    impl: str,
    *,
    T: int,
    D: int,
    page_size: int,
    dtype,
    interpret: bool = False,
) -> bool:
    """Gate: does the paged kernel handle this decode dispatch?

    ONE function consulted by both the model's paged read path
    (``models/gpt.py``) and the engine's dispatch-site bookkeeping, so
    "supported" and "will actually run" can never disagree. ``impl`` is
    ``cfg.attention_impl``; ``xla`` always declines (the gather path is the
    reference), ``auto``/``flash`` accept on TPU or under interpret mode.
    """
    if impl not in ("auto", "flash"):
        return False
    on_tpu = jax.default_backend() == "tpu"
    if not (on_tpu or interpret or interpret_requested()):
        return False
    if T < 1 or T > MAX_DECODE_T:
        return False  # decode/spec-verify windows only
    if on_tpu:
        # Mosaic lowering constraints — interpret mode (the CPU parity
        # lane) has no tiling and accepts any structurally valid shape
        if D % 64 or D > 256:
            return False  # lane-dim alignment for the MXU
        if page_size % 8:
            return False  # sublane-aligned page copies into the K/V scratch
    if dtype not in (jnp.bfloat16, jnp.float32):
        return False
    return True


def _kernel(
    # scalar-prefetch refs
    table_ref, offs_ref,
    # operands
    slope_ref, q_ref, k_ref, v_ref, *args,
    T: int, H: int, KVH: int, page: int, n_blocks: int, scale: float,
    causal: bool, alibi: bool, int8: bool,
):
    """One row's attention over its paged K/V, ALL heads per grid step.

    Grid (B, n_blocks): step j copies page ``table[b, j]``'s block —
    already pipelined into VMEM by the index map — into the K/V scratch at
    its logical position (dequantized when int8); the final step runs the
    full-softmax attention with the gather path's exact einsum subscripts.
    Keeping the kv-head axis INSIDE the contraction (a batch dim of the
    einsum, not a grid dim) is load-bearing for the bitwise contract: XLA
    lowers a per-head 2-D dot through a different gemm path than the
    reference's batched einsum, and the two differ by ulps at M=1."""
    # arg order: remaining inputs (int8 scale blocks), the output ref,
    # then the scratch buffers
    G = H // KVH
    if int8:
        ks_ref, vs_ref = args[0], args[1]
        o_scr, k_scr, v_scr = args[2], args[3], args[4]
    else:
        o_scr, k_scr, v_scr = args[0], args[1], args[2]
    b, j = pl.program_id(0), pl.program_id(1)
    S = n_blocks * page

    kb = k_ref[0]  # [page, KVH, D]
    vb = v_ref[0]
    if int8:
        # exact mirror of the gather path's dequant:
        # (int8 -> f32) * f32 scale -> compute dtype, elementwise
        kb = (kb.astype(jnp.float32) * ks_ref[0]).astype(k_scr.dtype)
        vb = (vb.astype(jnp.float32) * vs_ref[0]).astype(v_scr.dtype)
    k_scr[pl.ds(j * page, page), :, :] = kb.astype(k_scr.dtype)
    v_scr[pl.ds(j * page, page), :, :] = vb.astype(v_scr.dtype)

    @pl.when(j == n_blocks - 1)
    def _compute():
        off = offs_ref[b]
        qg = q_ref[0]  # [T, KVH, G, D]
        # scores einsum with the REFERENCE's subscripts (kvh stays a batch
        # dim), in f32, THEN the scalar scale multiply — xla_attention's
        # exact order
        s = jnp.einsum(
            "tkgd,skd->kgts", qg, k_scr[:],
            preferred_element_type=jnp.float32,
        )
        s = s * jnp.float32(scale)  # [KVH, G, T, S]
        q_pos = off + jax.lax.broadcasted_iota(jnp.int32, (T, S), 0)
        kv_pos = jax.lax.broadcasted_iota(jnp.int32, (T, S), 1)
        if alibi:
            # xla per-row branch: bias = -slope*dist (+ causal NEG_INF
            # folded into the SAME bias tensor), ONE add onto the scores
            dist = jnp.maximum(q_pos - kv_pos, 0).astype(jnp.float32)  # [T, S]
            sl = jnp.stack(
                [slope_ref[i, 0] for i in range(H)]
            ).reshape(KVH, G)
            bias = -sl[:, :, None, None] * dist[None, None, :, :]
            if causal:
                visible = kv_pos <= q_pos
                bias = bias + jnp.where(visible, 0.0, NEG_INF)[None, None, :, :]
            s = s + bias
        elif causal:
            visible = kv_pos <= q_pos
            s = s + jnp.where(visible, 0.0, NEG_INF)[None, None, :, :]
        # validity pad is its own SECOND add, exactly like the xla path's
        # segment_ids term (order matters for the bitwise contract)
        valid = kv_pos[:1, :] < off + T  # [1, S]
        s = s + jnp.where(valid, 0.0, NEG_INF)[None, None, :, :]
        w = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(k_scr.dtype)
        out = jnp.einsum("kgts,skd->tkgd", w, v_scr[:])
        o_scr[0] = out.astype(o_scr.dtype)


# graftlint: hot-path
def paged_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    block_table: jax.Array,
    q_offset: jax.Array,
    *,
    causal: bool,
    alibi: bool = False,
    softmax_scale: Optional[float] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    slopes: Optional[jax.Array] = None,
    interpret: bool = False,
) -> jax.Array:
    """Decode attention straight off the page pool. q ``[B, T, H, D]``
    (T = 1 decode, 1+K spec verify; RoPE already applied, overflow rows
    already NaN-poisoned by the caller); ``k_pool``/``v_pool``
    ``[n_pages, page, KVH, D]`` (int8 with ``k_scale``/``v_scale``
    ``[n_pages, page, KVH, 1]`` f32, or the compute dtype); ``block_table``
    ``[B, n_blocks]`` int32 (zeros = the serving layer's trash page);
    ``q_offset`` ``[B]`` (or scalar) — row r's query block starts at
    position ``q_offset[r]``, and positions ``>= q_offset[r] + T`` are
    masked invalid, the gather path's ``kv_valid``.

    Forward-only (the decode path never differentiates). Output is
    bit-identical to gather-to-slab + ``xla_attention`` on the same
    backend — see the module docstring for why that holds by construction.
    """
    B, T, H, D = q.shape
    n_pages, page, KVH, _ = k_pool.shape
    if H % KVH:
        raise ValueError(f"query heads {H} not divisible by kv heads {KVH}")
    G = H // KVH
    _, n_blocks = block_table.shape
    S = n_blocks * page
    int8 = k_pool.dtype == jnp.int8
    if int8 and (k_scale is None or v_scale is None):
        raise ValueError("int8 pools need k_scale/v_scale pools")
    scale = softmax_scale if softmax_scale is not None else 1.0 / (D**0.5)
    dtype = q.dtype

    offs = jnp.broadcast_to(jnp.asarray(q_offset, jnp.int32).reshape(-1), (B,))
    if slopes is None:
        slopes = alibi_slopes(H) if alibi else jnp.zeros((H,), jnp.float32)
    slopes = slopes.reshape(H, 1).astype(jnp.float32)
    q5 = q.reshape(B, T, KVH, G, D)

    # index maps receive the scalar-prefetch refs (table, offsets) last;
    # the page axis of every pool operand resolves through the table — the
    # pipelined block fetch IS the page walk
    qo_spec = pl.BlockSpec((1, T, KVH, G, D), lambda b, j, tbl, off: (b, 0, 0, 0, 0))
    kv_spec = pl.BlockSpec((1, page, KVH, D), lambda b, j, tbl, off: (tbl[b, j], 0, 0, 0))
    sc_spec = pl.BlockSpec((1, page, KVH, 1), lambda b, j, tbl, off: (tbl[b, j], 0, 0, 0))
    smem = pl.BlockSpec(memory_space=pltpu.SMEM if pltpu else None)
    in_specs = [smem, qo_spec, kv_spec, kv_spec]
    operands = [slopes, q5, k_pool, v_pool]
    if int8:
        in_specs += [sc_spec, sc_spec]
        operands += [k_scale, v_scale]

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, n_blocks),
        in_specs=in_specs,
        out_specs=qo_spec,
        scratch_shapes=[
            pltpu.VMEM((S, KVH, D), dtype),
            pltpu.VMEM((S, KVH, D), dtype),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _kernel, T=T, H=H, KVH=KVH, page=page, n_blocks=n_blocks,
            scale=float(scale), causal=causal, alibi=alibi, int8=int8,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, T, KVH, G, D), dtype),
        interpret=interpret or (jax.default_backend() != "tpu" and interpret_requested()),
    )(block_table.astype(jnp.int32), offs, *operands)
    return out.reshape(B, T, H, D)
