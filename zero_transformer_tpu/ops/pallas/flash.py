"""Flash attention as Pallas TPU kernels (forward + backward).

Blockwise online-softmax attention that never materializes the [T, T] score
matrix the reference allocates in full (reference ``src/models/layers.py:159-173``).
Supports causal masking, ALiBi bias (reference ``layers.py:17-44``),
grouped-query attention, and **global position offsets** so the same kernels
serve ring attention (``ops/ring_attention.py``), where each device's q / kv
shard starts at a different absolute position. Softmax statistics are carried
in float32 — the dtype discipline the reference adopted after its bf16-softmax
quality bug (reference ``logs/580.md:94-98``).

Kernels run on a [B, H, T, D] layout (Mosaic requires the blocked time axis in
the sublane position); the public wrappers transpose from the model's
[B, T, H, D] at the boundary — XLA fuses these transposes into neighboring
ops. The grid walks (batch, head, q-block, k-block) with the online-softmax
state (m, l, acc) carried in VMEM scratch across the innermost k-block
dimension; causally-skipped blocks are predicated off with ``pl.when``. The
backward pass is two more kernels over the same tiling: one carrying dq across
k-blocks, one carrying (dk, dv) across q-blocks, both recomputing
p = exp(s - lse) from the forward's saved logsumexp.

Three entry points:
- ``flash_attention``      — differentiable, self-contained (custom VJP);
- ``flash_partial``        — forward returning (out, lse); building block for
                             cross-device softmax merges (ring attention);
- ``flash_grads``          — backward given a (possibly *global*) lse/out.
"""
from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard anyway
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from zero_transformer_tpu.ops.positions import NEG_INF, alibi_slopes

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
_INIT_M = -1e30  # below any finite score; never produced by real inputs


def pick_block(n: int, prefer: int, floor: int = 8) -> Optional[int]:
    """Largest sublane-aligned block <= prefer dividing n (>= ``floor``,
    multiple of 8 — the f32 sublane tile), or None if none exists.

    Shared by the wrappers and the dispatch gate (``ops.flash_attention``) so
    "supported" and "will actually run" can never disagree. The floor used
    to be 128 (MXU-efficiency conservatism); serving shapes — chunked
    prefill windows of 64, small test caches — are legal Mosaic blocks down
    to the 8-sublane tile, and the gate applies a dtype-aware floor (16 for
    bf16) on top."""
    b = min(prefer, n)
    while b >= floor:
        if n % b == 0 and b % 8 == 0:
            return b
        b //= 2
    return None


def _bias_block(slope, q_pos0, k_pos0, block_q: int, block_k: int, alibi, causal):
    """f32 additive bias for one score block whose first q/k global positions
    are ``q_pos0`` / ``k_pos0`` (traced scalars under ring attention).

    Matches ``ops.positions.alibi_bias`` / ``causal_mask_bias`` exactly
    (distance clamped at 0, mask additive NEG_INF) so the kernels are
    numerically interchangeable with the XLA path."""
    q_pos = q_pos0 + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    k_pos = k_pos0 + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    dist = q_pos - k_pos
    bias = jnp.zeros((block_q, block_k), jnp.float32)
    if alibi:
        bias = bias - slope * jnp.maximum(dist, 0).astype(jnp.float32)
    if causal:
        bias = bias + jnp.where(dist >= 0, 0.0, NEG_INF).astype(jnp.float32)
    return bias


def _scores(
    slope, offs_ref, b, q_ref, k_ref, qid_ref, kid_ref, seg_ref, scale,
    alibi, causal, docs, segs, i, j
):
    """[block_q, block_k] f32 score block shared by all three kernels.

    ``docs`` (static) adds the packed-sequence document mask: positions with
    different ids (float32-encoded ints, exact ==) cannot attend. ``segs``
    (static) adds the serving path's kv validity mask: segment id 0 =
    padding / not-yet-written cache positions, masked out. Offsets are
    PER-ROW (``offs_ref`` is [2, B]; ``b`` the batch grid index) so the
    continuous-batching engine's vector cache index — every slot at its own
    position — rides the same kernels."""
    q = q_ref[0, 0, :, :]
    k = k_ref[0, 0, :, :]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    q_pos0 = offs_ref[0, b] + i * q.shape[0]
    k_pos0 = offs_ref[1, b] + j * k.shape[0]
    s = s * scale + _bias_block(
        slope, q_pos0, k_pos0, q.shape[0], k.shape[0], alibi, causal
    )
    if docs:
        same = qid_ref[0, 0, :][:, None] == kid_ref[0, 0, :][None, :]
        s = s + jnp.where(same, 0.0, NEG_INF).astype(jnp.float32)
    if segs:
        s = s + jnp.where(
            seg_ref[0, 0, :][None, :] != 0.0, 0.0, NEG_INF
        ).astype(jnp.float32)
    return s


def _run_predicate(offs_ref, b, i, j, block_q: int, block_k: int, causal: bool):
    """Does block (i, j) contain any causally-visible entry for row b?"""
    if not causal:
        return True
    first_k = offs_ref[1, b] + j * block_k
    last_q = offs_ref[0, b] + i * block_q + block_q - 1
    return first_k <= last_q


def _fwd_kernel(
    slope_ref, offs_ref, *args,
    scale: float, causal: bool, alibi: bool, docs: bool, segs: bool, n_k: int,
):
    # id/segment operands exist ONLY when their masking is on: per-grid-step
    # VMEM copies measurably slow the un-masked path (~2x at T=1024 on v5e)
    rest = list(args)
    qid_ref, kid_ref = (rest[0], rest[1]) if docs else (None, None)
    rest = rest[2:] if docs else rest
    seg_ref = rest[0] if segs else None
    rest = rest[1:] if segs else rest
    q_ref, k_ref, v_ref, o_ref, lse_ref, m_scr, l_scr, acc_scr = rest
    b, i, j = pl.program_id(0), pl.program_id(2), pl.program_id(3)
    slope = slope_ref[pl.program_id(1), 0]
    block_q, block_k = q_ref.shape[2], k_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _INIT_M)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    @pl.when(_run_predicate(offs_ref, b, i, j, block_q, block_k, causal))
    def _compute():
        s = _scores(
            slope, offs_ref, b, q_ref, k_ref, qid_ref, kid_ref, seg_ref,
            scale, alibi, causal, docs, segs, i, j,
        )
        v = v_ref[0, 0, :, :]
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:, :1] = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        m_scr[:, :1] = m_new
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    # the grid's k dimension is innermost-sequential: the final j visit for
    # this (b, h, i) is always j == n_k-1, even when it was causally skipped
    @pl.when(j == n_k - 1)
    def _write():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0, :, :] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0, :, :] = (m_scr[:, :1] + jnp.log(l_safe)).astype(jnp.float32)


def _dq_kernel(
    slope_ref, offs_ref, *args,
    scale: float, causal: bool, alibi: bool, docs: bool, n_k: int,
):
    qid_ref, kid_ref = (args[0], args[1]) if docs else (None, None)
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
     dq_scr) = args[2 if docs else 0:]
    b, i, j = pl.program_id(0), pl.program_id(2), pl.program_id(3)
    slope = slope_ref[pl.program_id(1), 0]
    block_q, block_k = q_ref.shape[2], k_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    @pl.when(_run_predicate(offs_ref, b, i, j, block_q, block_k, causal))
    def _compute():
        s = _scores(
            slope, offs_ref, b, q_ref, k_ref, qid_ref, kid_ref, None,
            scale, alibi, causal, docs, False, i, j,
        )
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        p = jnp.exp(s - lse_ref[0, 0, :, :])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0, 0, :, :])
        dq_scr[:] += scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(j == n_k - 1)
    def _write():
        dq_ref[0, 0, :, :] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(
    slope_ref, offs_ref, *args,
    scale: float, causal: bool, alibi: bool, docs: bool, n_q: int,
):
    qid_ref, kid_ref = (args[0], args[1]) if docs else (None, None)
    (q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
     dk_scr, dv_scr) = args[2 if docs else 0:]
    # grid: (B, H, n_k, n_q) — j is the k-block, inner index i walks q-blocks
    b, j, i = pl.program_id(0), pl.program_id(2), pl.program_id(3)
    slope = slope_ref[pl.program_id(1), 0]
    block_q, block_k = q_ref.shape[2], k_ref.shape[2]

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    @pl.when(_run_predicate(offs_ref, b, i, j, block_q, block_k, causal))
    def _compute():
        s = _scores(
            slope, offs_ref, b, q_ref, k_ref, qid_ref, kid_ref, None,
            scale, alibi, causal, docs, False, i, j,
        )
        q = q_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        p = jnp.exp(s - lse_ref[0, 0, :, :])  # [bq, bk]
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0, 0, :, :])
        dk_scr[:] += scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(i == n_q - 1)
    def _write():
        dk_ref[0, 0, :, :] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_scr[:].astype(dv_ref.dtype)


def _slopes_arg(n_heads: int, alibi: bool) -> jax.Array:
    if alibi:
        return alibi_slopes(n_heads).reshape(n_heads, 1)
    return jnp.zeros((n_heads, 1), jnp.float32)


def _offsets_arg(q_offset, kv_offset, B: int) -> jax.Array:
    """[2, B] int32 (q row 0, kv row 1): scalars broadcast, [B] vectors pass
    through — the per-row form the serving engine's vector cache index
    needs (every slot's query block at its own position)."""
    qo = jnp.broadcast_to(jnp.asarray(q_offset, jnp.int32).reshape(-1), (B,))
    ko = jnp.broadcast_to(jnp.asarray(kv_offset, jnp.int32).reshape(-1), (B,))
    return jnp.stack([qo, ko])


def _smem_spec():
    return pl.BlockSpec(memory_space=pltpu.SMEM if pltpu else None)


def _ids_args(q_ids, k_ids, B, T, S):
    """[B, 1, T]/[B, 1, S] f32 id arrays — built only when document masking
    is on (the operands and their per-grid-step VMEM copies cost ~2x at
    T=1024 when present but unused).

    The singleton middle axis is load-bearing: Mosaic requires the last two
    block dims to be (div 8, div 128) or equal to the array dims. A [B, T]
    layout with (1, block) blocks violates the sublane rule on real TPUs
    (interpret mode does not enforce it); [B, 1, T] with (1, 1, block)
    blocks is legal (1 == array dim, block >= 128)."""
    qi = q_ids.astype(jnp.float32).reshape(B, 1, T)
    ki = k_ids.astype(jnp.float32).reshape(B, 1, S)
    return qi, ki


def _fwd(q, k, v, causal, alibi, scale, block_q, block_k, interpret,
         q_offset=0, kv_offset=0, slopes=None, out_dtype=None,
         q_ids=None, k_ids=None, segment_ids=None):
    # [B, T, H, D] → [B, H, T, D]: Mosaic needs the blocked time axis in the
    # sublane position
    docs = q_ids is not None
    segs = segment_ids is not None
    q, k, v = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    B, H, T, D = q.shape
    _, KVH, S, _ = k.shape
    G = H // KVH
    n_q, n_k = T // block_q, S // block_k
    id_args = _ids_args(q_ids, k_ids, B, T, S) if docs else ()
    seg_args = (
        (segment_ids.astype(jnp.float32).reshape(B, 1, S),) if segs else ()
    )

    if slopes is None:
        slopes = _slopes_arg(H, alibi)
    q_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h // G, j, 0))
    qid_spec = pl.BlockSpec((1, 1, block_q), lambda b, h, i, j: (b, 0, i))
    kid_spec = pl.BlockSpec((1, 1, block_k), lambda b, h, i, j: (b, 0, j))
    id_specs = [qid_spec, kid_spec] if docs else []
    seg_specs = [kid_spec] if segs else []
    o, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, causal=causal, alibi=alibi, docs=docs,
            segs=segs, n_k=n_k,
        ),
        grid=(B, H, n_q, n_k),
        in_specs=[_smem_spec(), _smem_spec(), *id_specs, *seg_specs,
                  q_spec, kv_spec, kv_spec],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, out_dtype or q.dtype),
            jax.ShapeDtypeStruct((B, H, T, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # m (col 0 used)
            pltpu.VMEM((block_q, 128), jnp.float32),  # l
            pltpu.VMEM((block_q, D), jnp.float32),  # acc
        ],
        interpret=interpret,
    )(slopes, _offsets_arg(q_offset, kv_offset, B), *id_args, *seg_args, q, k, v)
    return jnp.swapaxes(o, 1, 2), lse


def _bwd(q, k, v, o, lse, do, causal, alibi, scale, block_q, block_k, interpret,
         q_offset=0, kv_offset=0, slopes=None, grad_dtype=None, delta=None,
         q_ids=None, k_ids=None):
    docs = q_ids is not None
    q, k, v, o, do = (jnp.swapaxes(x, 1, 2) for x in (q, k, v, o, do))
    B, H, T, D = q.shape
    _, KVH, S, _ = k.shape
    G = H // KVH
    n_q, n_k = T // block_q, S // block_k
    id_args = _ids_args(q_ids, k_ids, B, T, S) if docs else ()

    if delta is None:  # rowsum(do * o) — loop-invariant for ring callers
        delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)[..., None]

    if slopes is None:
        slopes = _slopes_arg(H, alibi)
    offs = _offsets_arg(q_offset, kv_offset, B)
    q_spec_iq = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    kv_spec_iq = pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h // G, j, 0))
    row_spec_iq = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0))

    qid_spec_iq = pl.BlockSpec((1, 1, block_q), lambda b, h, i, j: (b, 0, i))
    kid_spec_iq = pl.BlockSpec((1, 1, block_k), lambda b, h, i, j: (b, 0, j))
    id_specs_iq = [qid_spec_iq, kid_spec_iq] if docs else []
    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal, alibi=alibi, docs=docs,
            n_k=n_k,
        ),
        grid=(B, H, n_q, n_k),
        in_specs=[_smem_spec(), _smem_spec(), *id_specs_iq,
                  q_spec_iq, kv_spec_iq, kv_spec_iq,
                  q_spec_iq, row_spec_iq, row_spec_iq],
        out_specs=q_spec_iq,
        out_shape=jax.ShapeDtypeStruct(q.shape, grad_dtype or q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(slopes, offs, *id_args, q, k, v, do, lse, delta)

    # k-block-major grid; q walked innermost. dk/dv computed per *query* head
    # ([B, H, S, D]) then group-summed to KVH for GQA.
    q_spec_jq = pl.BlockSpec((1, 1, block_q, D), lambda b, h, j, i: (b, h, i, 0))
    kv_spec_jq = pl.BlockSpec((1, 1, block_k, D), lambda b, h, j, i: (b, h // G, j, 0))
    kv_out_jq = pl.BlockSpec((1, 1, block_k, D), lambda b, h, j, i: (b, h, j, 0))
    row_spec_jq = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, j, i: (b, h, i, 0))
    qid_spec_jq = pl.BlockSpec((1, 1, block_q), lambda b, h, j, i: (b, 0, i))
    kid_spec_jq = pl.BlockSpec((1, 1, block_k), lambda b, h, j, i: (b, 0, j))
    id_specs_jq = [qid_spec_jq, kid_spec_jq] if docs else []
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, alibi=alibi, docs=docs,
            n_q=n_q,
        ),
        grid=(B, H, n_k, n_q),
        in_specs=[_smem_spec(), _smem_spec(), *id_specs_jq,
                  q_spec_jq, kv_spec_jq, kv_spec_jq,
                  q_spec_jq, row_spec_jq, row_spec_jq],
        out_specs=[kv_out_jq, kv_out_jq],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), grad_dtype or k.dtype),
            jax.ShapeDtypeStruct((B, H, S, D), grad_dtype or v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(slopes, offs, *id_args, q, k, v, do, lse, delta)

    dq = jnp.swapaxes(dq, 1, 2)
    dk = jnp.swapaxes(dk, 1, 2)  # [B, S, H, D]
    dv = jnp.swapaxes(dv, 1, 2)
    if G > 1:
        dk = dk.reshape(B, S, KVH, G, D).sum(axis=3).astype(grad_dtype or k.dtype)
        dv = dv.reshape(B, S, KVH, G, D).sum(axis=3).astype(grad_dtype or v.dtype)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(5, 6, 7, 8, 9, 10))
def _flash(q, k, v, doc_ids, slopes, causal, alibi, scale, block_q, block_k, interpret):
    # doc_ids: [B, T] float32 (or None) — f32 so its zero cotangent below is
    # a plain zeros_like rather than float0 plumbing. slopes: [H, 1] f32 (or
    # None) overriding the ALiBi table for head-sharded callers (ulysses/TP).
    o, _ = _fwd(q, k, v, causal, alibi, scale, block_q, block_k, interpret,
                slopes=slopes, q_ids=doc_ids, k_ids=doc_ids)
    return o


def _flash_fwd(q, k, v, doc_ids, slopes, causal, alibi, scale, block_q, block_k, interpret):
    o, lse = _fwd(q, k, v, causal, alibi, scale, block_q, block_k, interpret,
                  slopes=slopes, q_ids=doc_ids, k_ids=doc_ids)
    return o, (q, k, v, doc_ids, slopes, o, lse)


def _flash_bwd(causal, alibi, scale, block_q, block_k, interpret, res, do):
    q, k, v, doc_ids, slopes, o, lse = res
    dq, dk, dv = _bwd(
        q, k, v, o, lse, do, causal, alibi, scale, block_q, block_k, interpret,
        slopes=slopes, q_ids=doc_ids, k_ids=doc_ids,
    )
    d_ids = None if doc_ids is None else jnp.zeros_like(doc_ids)
    d_slopes = None if slopes is None else jnp.zeros_like(slopes)
    return dq, dk, dv, d_ids, d_slopes


_flash.defvjp(_flash_fwd, _flash_bwd)


def _resolve_blocks(T, S, block, block_q, block_k):
    block_q = block_q or block or pick_block(T, DEFAULT_BLOCK_Q) or DEFAULT_BLOCK_Q
    block_k = block_k or block or pick_block(S, DEFAULT_BLOCK_K) or DEFAULT_BLOCK_K
    block_q, block_k = min(block_q, T), min(block_k, S)
    if T % block_q or S % block_k:
        raise ValueError(
            f"seq lengths ({T}, {S}) not divisible by blocks ({block_q}, {block_k})"
        )
    return block_q, block_k


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    alibi: bool = False,
    doc_ids: Optional[jax.Array] = None,
    softmax_scale: Optional[float] = None,
    slopes: Optional[jax.Array] = None,
    block: Optional[int] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: bool = False,
) -> jax.Array:
    """Differentiable flash attention. q [B,T,H,D]; k,v [B,S,KVH,D].

    ``doc_ids`` [B, T] int: packed-sequence document mask (requires T == S;
    different ids cannot attend to each other). ``slopes`` [H, 1] f32
    overrides the ALiBi slope table — for head-sharded callers (ulysses / TP
    local attention) whose local head 0 is not global head 0. Slopes are
    treated as a CONSTANT of the kernel (stop_gradient applied): unlike the
    XLA path, the custom VJP does not propagate slope gradients — do not use
    this entry point with learnable slopes."""
    B, T, H, D = q.shape
    _, S, KVH, _ = k.shape
    if H % KVH:
        raise ValueError(f"query heads {H} not divisible by kv heads {KVH}")
    if doc_ids is not None and T != S:
        raise ValueError("doc_ids requires full-sequence shapes (T == S)")
    if slopes is not None:
        slopes = jax.lax.stop_gradient(slopes)
    block_q, block_k = _resolve_blocks(T, S, block, block_q, block_k)
    scale = softmax_scale if softmax_scale is not None else 1.0 / (D**0.5)
    ids = None if doc_ids is None else doc_ids.astype(jnp.float32)
    return _flash(
        q, k, v, ids, slopes, causal, alibi, float(scale), block_q, block_k,
        interpret,
    )


# graftlint: hot-path
def flash_serving(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    alibi: bool = False,
    q_offset=0,
    segment_ids: Optional[jax.Array] = None,
    softmax_scale: Optional[float] = None,
    slopes: Optional[jax.Array] = None,
    block: Optional[int] = None,
    interpret: bool = False,
) -> jax.Array:
    """Forward-only flash attention for the serving cache shapes the
    differentiable entry point cannot express:

    - ``q_offset`` scalar or PER-ROW ``[B]`` (traced): the query block of
      row r starts at global position ``q_offset[r]`` — the engine's
      chunked prefill window / spec-verify block over a vector cache index;
    - ``segment_ids`` ``[B, S]``: kv validity (0 = not-yet-written cache
      positions past each row's fill cursor, masked out exactly like the
      XLA path's pad mask).

    Decode never differentiates, so this skips the custom-VJP plumbing and
    the lse output. Numerics: same online-softmax kernel as training flash,
    pinned few-ulp against ``ops.attention.xla_attention`` (tests)."""
    B, T, H, D = q.shape
    _, S, KVH, _ = k.shape
    if H % KVH:
        raise ValueError(f"query heads {H} not divisible by kv heads {KVH}")
    if segment_ids is not None and tuple(segment_ids.shape) != (B, S):
        raise ValueError(
            f"segment_ids must be [B, S] = {(B, S)}, got {segment_ids.shape}"
        )
    if slopes is not None:
        slopes = jax.lax.stop_gradient(slopes).reshape(-1, 1).astype(jnp.float32)
    block_q, block_k = _resolve_blocks(T, S, block, None, None)
    scale = softmax_scale if softmax_scale is not None else 1.0 / (D**0.5)
    o, _ = _fwd(
        q, k, v, causal, alibi, float(scale), block_q, block_k, interpret,
        q_offset=q_offset, kv_offset=0, slopes=slopes,
        segment_ids=segment_ids,
    )
    return o


def flash_partial(
    q, k, v, *, causal, alibi, softmax_scale, q_offset, kv_offset,
    slopes=None, q_ids=None, k_ids=None,
    block: Optional[int] = None, interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Forward-only: (out [B,T,H,D], lse [B,H,T,1]) at global offsets.

    ``out`` is normalized by the LOCAL softmax sum; merge across kv shards
    with the lse (ring attention does this). ``slopes`` overrides the ALiBi
    slope table for head-sharded (TP) calls; ``q_ids``/``k_ids`` are this
    shard's document ids (ring packing — the kv ids rotate with the kv
    shard). NOT differentiable — pair with ``flash_grads`` under a custom
    VJP.
    """
    B, T, H, D = q.shape
    _, S, KVH, _ = k.shape
    block_q, block_k = _resolve_blocks(T, S, block, None, None)
    scale = softmax_scale if softmax_scale is not None else 1.0 / (D**0.5)
    return _fwd(
        q, k, v, causal, alibi, float(scale), block_q, block_k, interpret,
        q_offset=q_offset, kv_offset=kv_offset, slopes=slopes,
        out_dtype=jnp.float32,  # merged (and rounded once) by the caller
        q_ids=q_ids, k_ids=k_ids,
    )


def flash_grads(
    q, k, v, o, lse, do, *, causal, alibi, softmax_scale, q_offset, kv_offset,
    slopes=None, delta=None, q_ids=None, k_ids=None,
    block: Optional[int] = None, interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """(dq, dk, dv) given the GLOBAL (out, lse) of the merged softmax —
    the flash backward identity p = exp(s - lse_global) makes per-shard
    backward passes independent (ring attention sums them)."""
    B, T, H, D = q.shape
    _, S, KVH, _ = k.shape
    block_q, block_k = _resolve_blocks(T, S, block, None, None)
    scale = softmax_scale if softmax_scale is not None else 1.0 / (D**0.5)
    return _bwd(
        q, k, v, o, lse, do, causal, alibi, float(scale), block_q, block_k,
        interpret, q_offset=q_offset, kv_offset=kv_offset, slopes=slopes,
        grad_dtype=jnp.float32,  # summed across ring steps by the caller
        delta=delta, q_ids=q_ids, k_ids=k_ids,
    )
