"""Flash attention as Pallas TPU kernels (forward + backward).

Blockwise online-softmax attention that never materializes the [T, T] score
matrix the reference allocates in full (reference ``src/models/layers.py:159-173``).
Supports causal masking, ALiBi bias (reference ``layers.py:17-44``), and
grouped-query attention; softmax statistics are carried in float32 — the dtype
discipline the reference adopted after its bf16-softmax quality bug
(reference ``logs/580.md:94-98``).

Kernels run on a [B, H, T, D] layout (Mosaic requires the blocked time axis in
the sublane position); the public wrapper transposes from the model's
[B, T, H, D] at the boundary — XLA fuses these transposes into neighboring
ops. The grid walks (batch, head, q-block, k-block) with the online-softmax
state (m, l, acc) carried in VMEM scratch across the innermost k-block
dimension; causally-skipped blocks are predicated off with ``pl.when``. The
backward pass is two more kernels over the same tiling: one carrying dq across
k-blocks, one carrying (dk, dv) across q-blocks, both recomputing
p = exp(s - lse) from the forward's saved logsumexp.
"""
from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:  # pltpu is importable on CPU builds too; guard anyway
    from jax.experimental.pallas import tpu as pltpu
except ImportError:  # pragma: no cover
    pltpu = None

from zero_transformer_tpu.ops.positions import NEG_INF, alibi_slopes

DEFAULT_BLOCK_Q = 512
DEFAULT_BLOCK_K = 512
_INIT_M = -1e30  # below any finite score; never produced by real inputs


def _bias_block(
    slope, i, j, block_q: int, block_k: int, alibi: bool, causal: bool
):
    """f32 additive bias for score block (i, j): ALiBi distance + causal mask.

    Matches ``ops.positions.alibi_bias`` / ``causal_mask_bias`` exactly
    (distance clamped at 0, mask additive NEG_INF) so the kernel is
    numerically interchangeable with the XLA path.
    """
    q_pos = i * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    k_pos = j * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    bias = jnp.zeros((block_q, block_k), jnp.float32)
    if alibi:
        dist = jnp.maximum(q_pos - k_pos, 0).astype(jnp.float32)
        bias = bias - slope * dist
    if causal:
        bias = bias + jnp.where(k_pos <= q_pos, 0.0, NEG_INF).astype(jnp.float32)
    return bias


def _scores(slope, q_ref, k_ref, scale, alibi, causal, i, j):
    """[block_q, block_k] f32 score block shared by all three kernels."""
    q = q_ref[0, 0, :, :]
    k = k_ref[0, 0, :, :]
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    )
    return s * scale + _bias_block(
        slope, i, j, q.shape[0], k.shape[0], alibi, causal
    )


def _fwd_kernel(
    slope_ref, q_ref, k_ref, v_ref, o_ref, lse_ref,
    m_scr, l_scr, acc_scr,
    *, scale: float, causal: bool, alibi: bool, n_k: int,
):
    i, j = pl.program_id(2), pl.program_id(3)
    slope = slope_ref[pl.program_id(1), 0]
    block_q, block_k = q_ref.shape[2], k_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, _INIT_M)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # causal: block (i, j) contributes iff some k_pos <= some q_pos
    run = (j * block_k <= i * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _compute():
        s = _scores(slope, q_ref, k_ref, scale, alibi, causal, i, j)
        v = v_ref[0, 0, :, :]
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=1, keepdims=True))
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_prev - m_new)
        l_scr[:, :1] = alpha * l_scr[:, :1] + jnp.sum(p, axis=1, keepdims=True)
        m_scr[:, :1] = m_new
        acc_scr[:] = acc_scr[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    # i is a traced grid index: compute the last contributing j dynamically.
    last = (
        jnp.minimum(((i + 1) * block_q - 1) // block_k, n_k - 1)
        if causal
        else n_k - 1
    )

    @pl.when(j == last)
    def _write():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0, :, :] = (acc_scr[:] / l_safe).astype(o_ref.dtype)
        lse_ref[0, 0, :, :] = (m_scr[:, :1] + jnp.log(l_safe)).astype(jnp.float32)


def _dq_kernel(
    slope_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
    dq_scr,
    *, scale: float, causal: bool, alibi: bool, n_k: int,
):
    i, j = pl.program_id(2), pl.program_id(3)
    slope = slope_ref[pl.program_id(1), 0]
    block_q, block_k = q_ref.shape[2], k_ref.shape[2]

    @pl.when(j == 0)
    def _init():
        dq_scr[:] = jnp.zeros_like(dq_scr)

    run = (j * block_k <= i * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _compute():
        s = _scores(slope, q_ref, k_ref, scale, alibi, causal, i, j)
        k = k_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        p = jnp.exp(s - lse_ref[0, 0, :, :])
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0, 0, :, :])
        dq_scr[:] += scale * jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    last = (
        jnp.minimum(((i + 1) * block_q - 1) // block_k, n_k - 1)
        if causal
        else n_k - 1
    )

    @pl.when(j == last)
    def _write():
        dq_ref[0, 0, :, :] = dq_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(
    slope_ref, q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dk_ref, dv_ref,
    dk_scr, dv_scr,
    *, scale: float, causal: bool, alibi: bool, n_q: int,
):
    # grid: (B, H, n_k, n_q) — j is the k-block, inner index i walks q-blocks
    j, i = pl.program_id(2), pl.program_id(3)
    slope = slope_ref[pl.program_id(1), 0]
    block_q, block_k = q_ref.shape[2], k_ref.shape[2]

    @pl.when(i == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    run = (j * block_k <= i * block_q + block_q - 1) if causal else True

    @pl.when(run)
    def _compute():
        s = _scores(slope, q_ref, k_ref, scale, alibi, causal, i, j)
        q = q_ref[0, 0, :, :]
        v = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :].astype(jnp.float32)
        p = jnp.exp(s - lse_ref[0, 0, :, :])  # [bq, bk]
        dv_scr[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )
        ds = p * (dp - delta_ref[0, 0, :, :])
        dk_scr[:] += scale * jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    @pl.when(i == n_q - 1)
    def _write():
        dk_ref[0, 0, :, :] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0, :, :] = dv_scr[:].astype(dv_ref.dtype)


def pick_block(n: int, prefer: int) -> Optional[int]:
    """Largest block <= prefer (>=128) dividing n, or None if none exists.

    Shared by the wrapper and the dispatch gate (``ops.flash_attention``) so
    "supported" and "will actually run" can never disagree."""
    b = min(prefer, n)
    while b >= 128:
        if n % b == 0:
            return b
        b //= 2
    return None


def _slopes_arg(n_heads: int, alibi: bool) -> jax.Array:
    if alibi:
        return alibi_slopes(n_heads).reshape(n_heads, 1)
    return jnp.zeros((n_heads, 1), jnp.float32)


def _fwd(q, k, v, causal, alibi, scale, block_q, block_k, interpret):
    # [B, T, H, D] → [B, H, T, D]: Mosaic needs the blocked time axis in the
    # sublane position
    q, k, v = (jnp.swapaxes(x, 1, 2) for x in (q, k, v))
    B, H, T, D = q.shape
    _, KVH, S, _ = k.shape
    G = H // KVH
    n_q, n_k = T // block_q, S // block_k

    slope_spec = pl.BlockSpec(memory_space=pltpu.SMEM if pltpu else None)
    q_spec = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    kv_spec = pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h // G, j, 0))
    o, lse = pl.pallas_call(
        functools.partial(
            _fwd_kernel, scale=scale, causal=causal, alibi=alibi, n_k=n_k
        ),
        grid=(B, H, n_q, n_k),
        in_specs=[slope_spec, q_spec, kv_spec, kv_spec],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((B, H, T, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 128), jnp.float32),  # m (col 0 used)
            pltpu.VMEM((block_q, 128), jnp.float32),  # l
            pltpu.VMEM((block_q, D), jnp.float32),  # acc
        ],
        interpret=interpret,
    )(_slopes_arg(H, alibi), q, k, v)
    return jnp.swapaxes(o, 1, 2), lse


def _bwd(q, k, v, o, lse, do, causal, alibi, scale, block_q, block_k, interpret):
    q, k, v, o, do = (jnp.swapaxes(x, 1, 2) for x in (q, k, v, o, do))
    B, H, T, D = q.shape
    _, KVH, S, _ = k.shape
    G = H // KVH
    n_q, n_k = T // block_q, S // block_k

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)[..., None]  # [B,H,T,1]

    slope_spec = pl.BlockSpec(memory_space=pltpu.SMEM if pltpu else None)
    q_spec_iq = pl.BlockSpec((1, 1, block_q, D), lambda b, h, i, j: (b, h, i, 0))
    kv_spec_iq = pl.BlockSpec((1, 1, block_k, D), lambda b, h, i, j: (b, h // G, j, 0))
    row_spec_iq = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, i, j: (b, h, i, 0))

    dq = pl.pallas_call(
        functools.partial(
            _dq_kernel, scale=scale, causal=causal, alibi=alibi, n_k=n_k
        ),
        grid=(B, H, n_q, n_k),
        in_specs=[slope_spec, q_spec_iq, kv_spec_iq, kv_spec_iq, q_spec_iq, row_spec_iq, row_spec_iq],
        out_specs=q_spec_iq,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, D), jnp.float32)],
        interpret=interpret,
    )(_slopes_arg(H, alibi), q, k, v, do, lse, delta)

    # k-block-major grid; q walked innermost. dk/dv computed per *query* head
    # ([B, H, S, D]) then group-summed to KVH for GQA.
    q_spec_jq = pl.BlockSpec((1, 1, block_q, D), lambda b, h, j, i: (b, h, i, 0))
    kv_spec_jq = pl.BlockSpec((1, 1, block_k, D), lambda b, h, j, i: (b, h // G, j, 0))
    kv_out_jq = pl.BlockSpec((1, 1, block_k, D), lambda b, h, j, i: (b, h, j, 0))
    row_spec_jq = pl.BlockSpec((1, 1, block_q, 1), lambda b, h, j, i: (b, h, i, 0))
    dk, dv = pl.pallas_call(
        functools.partial(
            _dkv_kernel, scale=scale, causal=causal, alibi=alibi, n_q=n_q
        ),
        grid=(B, H, n_k, n_q),
        in_specs=[slope_spec, q_spec_jq, kv_spec_jq, kv_spec_jq, q_spec_jq, row_spec_jq, row_spec_jq],
        out_specs=[kv_out_jq, kv_out_jq],
        out_shape=[
            jax.ShapeDtypeStruct((B, H, S, D), k.dtype),
            jax.ShapeDtypeStruct((B, H, S, D), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, D), jnp.float32),
            pltpu.VMEM((block_k, D), jnp.float32),
        ],
        interpret=interpret,
    )(_slopes_arg(H, alibi), q, k, v, do, lse, delta)

    dq = jnp.swapaxes(dq, 1, 2)
    dk = jnp.swapaxes(dk, 1, 2)  # [B, S, H, D]
    dv = jnp.swapaxes(dv, 1, 2)
    if G > 1:
        dk = dk.reshape(B, S, KVH, G, D).sum(axis=3).astype(k.dtype)
        dv = dv.reshape(B, S, KVH, G, D).sum(axis=3).astype(v.dtype)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def _flash(q, k, v, causal, alibi, scale, block_q, block_k, interpret):
    o, _ = _fwd(q, k, v, causal, alibi, scale, block_q, block_k, interpret)
    return o


def _flash_fwd(q, k, v, causal, alibi, scale, block_q, block_k, interpret):
    o, lse = _fwd(q, k, v, causal, alibi, scale, block_q, block_k, interpret)
    return o, (q, k, v, o, lse)


def _flash_bwd(causal, alibi, scale, block_q, block_k, interpret, res, do):
    q, k, v, o, lse = res
    return _bwd(q, k, v, o, lse, do, causal, alibi, scale, block_q, block_k, interpret)


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    alibi: bool = False,
    softmax_scale: Optional[float] = None,
    block: Optional[int] = None,
    block_q: Optional[int] = None,
    block_k: Optional[int] = None,
    interpret: bool = False,
) -> jax.Array:
    """Differentiable flash attention. q [B,T,H,D]; k,v [B,S,KVH,D]."""
    B, T, H, D = q.shape
    _, S, KVH, _ = k.shape
    if H % KVH:
        raise ValueError(f"query heads {H} not divisible by kv heads {KVH}")
    block_q = block_q or block or pick_block(T, DEFAULT_BLOCK_Q) or DEFAULT_BLOCK_Q
    block_k = block_k or block or pick_block(S, DEFAULT_BLOCK_K) or DEFAULT_BLOCK_K
    block_q, block_k = min(block_q, T), min(block_k, S)
    if T % block_q or S % block_k:
        raise ValueError(
            f"seq lengths ({T}, {S}) not divisible by blocks ({block_q}, {block_k})"
        )
    scale = softmax_scale if softmax_scale is not None else 1.0 / (D**0.5)
    return _flash(q, k, v, causal, alibi, float(scale), block_q, block_k, interpret)
