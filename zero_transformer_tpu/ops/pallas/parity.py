"""Interpret-mode parity report for the Pallas kernel lane.

ONE implementation of the correctness half of the per-op kernel A/B,
shared by ``scripts/train_step_bench.py`` (the ``interpret_parity`` block
of BENCH_step.json) and ``bench.py``'s flash child (its off-TPU output) —
the two artifacts must never assert different parity contracts
(tolerances, shapes, the jit-boundary rule) for the same kernels.

Cases:
- ``flash_train_fwd_bwd`` — the differentiable training kernel, forward
  and gradients, few-ulp vs ``xla_attention``;
- ``flash_serving_offsets_mask`` — the serving entry (per-row offsets +
  kv-validity mask), few-ulp;
- ``paged_decode_vs_gather`` — the paged decode kernel, BITWISE vs the
  gather-to-slab path it replaces. Both sides run under jit with the
  gather INSIDE the reference program: the engine's fused step computes
  take + attention in one compiled program, and that is the program the
  bitwise contract is defined against (different jit boundaries fuse
  differently).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

FWD_TOL = 3e-5
BWD_TOL = 3e-4


def interpret_parity_report() -> dict:
    """Run all three parity cases in Pallas interpret mode on THIS backend
    and return the labeled report (no timing — timed kernel numbers are
    TPU-only by the repo's provenance discipline)."""
    from zero_transformer_tpu.ops.attention import xla_attention
    from zero_transformer_tpu.ops.pallas.flash import (
        flash_attention, flash_serving,
    )
    from zero_transformer_tpu.ops.pallas.paged_attention import paged_attention

    cases = []
    # training shape, fwd + grads, few-ulp bar
    B, T, H, D = 2, 128, 4, 64
    q, k, v = (
        jax.random.normal(jax.random.PRNGKey(i), (B, T, H, D), jnp.float32)
        for i in range(3)
    )
    ref = xla_attention(q, k, v, causal=True, alibi=True)
    out = flash_attention(q, k, v, causal=True, alibi=True, block=64,
                          interpret=True)
    fwd_diff = float(jnp.max(jnp.abs(ref - out)))
    g = jax.random.normal(jax.random.PRNGKey(9), (B, T, H, D))
    ref_g = jax.grad(lambda q: jnp.sum(
        xla_attention(q, k, v, causal=True, alibi=True) * g))(q)
    out_g = jax.grad(lambda q: jnp.sum(flash_attention(
        q, k, v, causal=True, alibi=True, block=64, interpret=True) * g))(q)
    bwd_diff = float(jnp.max(jnp.abs(ref_g - out_g)))
    cases.append({
        "case": "flash_train_fwd_bwd", "shape": [B, T, H, D],
        "max_abs_diff_fwd": fwd_diff, "max_abs_diff_bwd": bwd_diff,
        "ok": fwd_diff < FWD_TOL and bwd_diff < BWD_TOL,
    })

    # serving shape: per-row offsets + kv-validity mask
    L = 192
    offs = jnp.asarray([0, 40], jnp.int32)
    kl = jax.random.normal(jax.random.PRNGKey(3), (B, L, H, D), jnp.float32)
    vl = jax.random.normal(jax.random.PRNGKey(4), (B, L, H, D), jnp.float32)
    qc = q[:, :64]
    seg = (jnp.arange(L)[None, :] < (offs[:, None] + 64)).astype(jnp.int32)
    ref = xla_attention(qc, kl, vl, causal=True, alibi=True, q_offset=offs,
                        segment_ids=seg)
    out = flash_serving(qc, kl, vl, causal=True, alibi=True, q_offset=offs,
                        segment_ids=seg, interpret=True)
    sdiff = float(jnp.max(jnp.abs(ref - out)))
    cases.append({
        "case": "flash_serving_offsets_mask", "shape": [B, 64, H, D],
        "max_abs_diff_fwd": sdiff, "ok": sdiff < FWD_TOL,
    })

    # paged decode kernel: BITWISE vs the gather-to-slab path it replaces
    page, n_blocks = 16, 4
    n_pages = 12
    S = page * n_blocks
    kp = jax.random.normal(jax.random.PRNGKey(5), (n_pages, page, H, D), jnp.float32)
    vp = jax.random.normal(jax.random.PRNGKey(6), (n_pages, page, H, D), jnp.float32)
    table = jax.random.randint(
        jax.random.PRNGKey(7), (B, n_blocks), 1, n_pages, jnp.int32
    )
    doff = jnp.asarray([17, 42], jnp.int32)

    def _gather_ref(q, kp, vp, tbl, o):
        gk = jnp.take(kp, tbl, axis=0).reshape(B, S, H, D)
        gv = jnp.take(vp, tbl, axis=0).reshape(B, S, H, D)
        s = (jnp.arange(S)[None, :] < (o[:, None] + 1)).astype(jnp.int32)
        return xla_attention(q, gk, gv, causal=False, alibi=True,
                             q_offset=o, segment_ids=s)

    ref = jax.jit(_gather_ref)(q[:, :1], kp, vp, table, doff)
    out = jax.jit(lambda q, kp, vp, t, o: paged_attention(
        q, kp, vp, t, o, causal=False, alibi=True, interpret=True,
    ))(q[:, :1], kp, vp, table, doff)
    bitwise = bool(np.array_equal(np.asarray(ref), np.asarray(out)))
    cases.append({
        "case": "paged_decode_vs_gather", "shape": [B, 1, H, D],
        "page_size": page, "bitwise": bitwise, "ok": bitwise,
    })

    return {
        "provenance": "interpret_mode_parity",
        "platform": jax.default_backend(),
        "cases": cases,
        "ok": all(c["ok"] for c in cases),
    }
