"""Text-generation CLI / demo server on TPU.

Replaces the reference's CUDA-only Gradio app (reference ``app.py``: hard
``torch.cuda.is_available()`` gate at :23-24, per-token Python sampling loop
at :69-94) with the in-tree jitted decode path. Runs as:

  python -m zero_transformer_tpu.serve --model 1_3b --params params.msgpack \\
      [--tokenizer <hf name or local path>] [--prompt "..."] [--ui]

- with ``--prompt``: one-shot generation to stdout;
- without: an interactive REPL;
- with ``--server``: the continuous-batching HTTP server (slot-based KV
  cache + request scheduler + SSE streaming — ``zero_transformer_tpu.serving``);
- with ``--ui``: the same controls in a Gradio web UI when gradio is
  importable (it is not baked into this image — the CLI is the primary
  surface; the reference made the UI the only surface).

The sampling controls mirror the reference UI (``app.py:199-259``):
temperature, top-k, top-p, repetition penalty, max tokens, greedy toggle.
"""
from __future__ import annotations

import argparse
import sys
from typing import Any, Optional

import jax
import jax.numpy as jnp


class ByteTokenizer:
    """UTF-8 byte-level tokenizer: token id = byte value (vocab 256).

    ``--tokenizer bytes``: a zero-dependency, zero-download fallback so the
    serve surface works on air-gapped machines and with byte-vocab models
    (the ``test`` zoo entry). No EOS — generation runs to max_new_tokens."""

    eos_token_id = None

    def encode(self, text: str):
        return list(text.encode("utf-8"))

    def decode(self, toks, **kwargs) -> str:
        return bytes(t for t in toks if 0 <= t < 256).decode("utf-8", errors="replace")


def _load_tokenizer(name_or_path: str):
    """GPT-NeoX tokenizer by default (what the reference trained with,
    reference ``app.py:27``). Must resolve locally — this environment has no
    egress, so pass a local path when the HF cache is cold, or ``bytes`` for
    the built-in byte-level fallback."""
    if name_or_path == "bytes":
        return ByteTokenizer()
    from transformers import AutoTokenizer

    return AutoTokenizer.from_pretrained(name_or_path)


class TextGenerator:
    """Tokenizer + params + compiled decode loop behind one ``__call__``."""

    def __init__(self, cfg, params: Any, tokenizer, cache_len: Optional[int] = None,
                 speculative: int = 0, tensor: int = 1,
                 top_k_impl: str = "exact"):
        from zero_transformer_tpu.inference import decode_model

        self.cfg = cfg
        # server-level execution knob, not a per-request sampling semantic:
        # "approx" swaps the per-step vocab sort for lax.approx_max_k (TPU
        # partial-reduce; kept set can be slightly wider than k)
        self.top_k_impl = top_k_impl
        self.tokenizer = tokenizer
        self.cache_len = cache_len or cfg.max_seq_len
        self.model = decode_model(cfg, self.cache_len)
        # tensor>1: shard params/cache over a pure-TP mesh so models larger
        # than one chip's HBM serve (llama3_8b on 4-8 chips); outputs match
        # single-chip decode (tested argmax-identical)
        self.mesh = None
        if tensor > 1:
            from zero_transformer_tpu.inference import serve_mesh, shard_for_inference

            self.mesh = serve_mesh(tensor)
            params = shard_for_inference(self.model, params, self.mesh)
            if speculative:
                print(
                    "serve: --speculative is single-chip only and is "
                    "DISABLED under --tensor>1 (requests take the plain "
                    "decode path)",
                    flush=True,
                )
                speculative = 0
        self.params = params
        # draft length for prompt-lookup speculative decoding (greedy one-shot
        # generation only; 0 = off)
        self.speculative = speculative

    def _decode(self, toks) -> str:
        """Detokenize through the shared pinned decode (no
        clean_up_tokenization_spaces) so the one-shot path, the REPL stream,
        and the SSE server can never diverge on detok behavior."""
        from zero_transformer_tpu.serving.detok import decode_tokens

        return decode_tokens(self.tokenizer, toks)

    def __call__(
        self,
        prompt: str,
        max_new_tokens: int = 128,
        temperature: float = 0.8,
        top_k: int = 0,
        top_p: float = 0.9,
        repetition_penalty: float = 1.1,
        greedy: bool = False,
        seed: int = 0,
    ) -> str:
        from zero_transformer_tpu.inference import generate

        ids, sampling, eos = self._prepare(
            prompt, max_new_tokens, temperature, top_k, top_p,
            repetition_penalty, greedy,
        )
        # draft scratch must fit the cache (prompt + new + K); shrink K to
        # whatever fits rather than erroring at the budget edge. every greedy
        # configuration routes through speculation: top-k/top-p are exactly
        # argmax-neutral, and the temperature division + repetition penalty
        # are mirrored bit-exactly inside the acceptance walk.
        spec_k = min(self.speculative, self.cache_len - len(ids) - max_new_tokens)
        # speculation is single-chip only for now: its draft/verify loop does
        # not take a mesh (TP serving goes through the plain path)
        if spec_k > 0 and greedy and self.mesh is None:
            from zero_transformer_tpu.inference import generate_speculative

            out = generate_speculative(
                self.model, self.params, jnp.asarray([ids], jnp.int32),
                max_new_tokens, draft_len=spec_k,
                eos_token_id=eos, pad_token_id=eos if eos is not None else 0,
                repetition_penalty=repetition_penalty,
                temperature=temperature,
            )
            toks = [t for t in out[0].tolist() if t != eos]
            return self._decode(toks)
        out = generate(
            self.model,
            self.params,
            jnp.asarray([ids], jnp.int32),
            max_new_tokens,
            jax.random.PRNGKey(seed),
            sampling,
            eos_token_id=eos,
            # pad finished rows with EOS so stripping EOS below also strips
            # padding, whatever the tokenizer's ids are
            pad_token_id=eos if eos is not None else 0,
            mesh=self.mesh,
        )
        toks = [t for t in out[0].tolist() if t != eos]
        return self._decode(toks)

    def _prepare(
        self, prompt, max_new_tokens, temperature, top_k, top_p,
        repetition_penalty, greedy,
    ):
        """Shared encode/truncate/sampling preamble for __call__ and stream
        (one source of truth: the two paths must never diverge)."""
        from zero_transformer_tpu.inference import SamplingConfig

        ids = self.tokenizer.encode(prompt.strip())
        budget = self.cache_len - max_new_tokens
        if budget < 1:
            raise ValueError("max_new_tokens leaves no room for the prompt")
        ids = ids[-budget:]  # keep the tail (reference app.py:61-64)
        sampling = SamplingConfig(
            temperature=temperature, top_k=top_k, top_p=top_p,
            repetition_penalty=repetition_penalty, greedy=greedy,
            top_k_impl=self.top_k_impl,
        )
        return ids, sampling, self.tokenizer.eos_token_id

    def stream(
        self,
        prompt: str,
        max_new_tokens: int = 128,
        temperature: float = 0.8,
        top_k: int = 0,
        top_p: float = 0.9,
        repetition_penalty: float = 1.1,
        greedy: bool = False,
        seed: int = 0,
    ):
        """Yield decoded text increments as tokens generate (the reference
        UI's streaming behavior, ``app.py:42-94``, on the jitted step)."""
        from zero_transformer_tpu.inference import stream_tokens

        from zero_transformer_tpu.serving.detok import StreamDecoder

        ids, sampling, eos = self._prepare(
            prompt, max_new_tokens, temperature, top_k, top_p,
            repetition_penalty, greedy,
        )
        # committed-prefix decoding via the shared StreamDecoder (HF
        # TextStreamer pattern): only the UNCOMMITTED tail is re-decoded
        # each step — O(n) total, not O(n^2) — and output is held back while
        # the tail is an incomplete byte sequence (byte-level BPE chars can
        # span tokens; decode -> U+FFFD). One implementation with the SSE
        # server's stream path, so the two surfaces cannot diverge.
        decoder = StreamDecoder(self.tokenizer)
        for token in stream_tokens(
            self.model, self.params, jnp.asarray([ids], jnp.int32),
            max_new_tokens, jax.random.PRNGKey(seed), sampling,
            eos_token_id=eos, mesh=self.mesh,
        ):
            t = int(token[0])
            if eos is not None and t == eos:
                break
            piece = decoder.push(t)
            if piece is not None:
                yield piece
        tail = decoder.flush()  # a genuinely incomplete tail at stream end
        if tail is not None:
            yield tail


def _has_quantized_leaves(tree) -> bool:
    """True when the tree already carries int8-serving leaves
    (``kernel_q``/``embedding_q`` — the layout ``models/quant.py`` emits)."""
    if not isinstance(tree, dict):
        return False
    return any(
        k in ("kernel_q", "embedding_q") or _has_quantized_leaves(v)
        for k, v in tree.items()
    )


# the ServingConfig knobs the autotuner searches (scripts/autotune.py):
# argparse leaves them at a None sentinel so explicit flags are
# distinguishable from "use the default"
_TUNED_KNOBS = (
    "kv_layout", "prefill_chunk", "page_size", "page_pool_tokens", "draft_k",
)


def _resolve_tuned_args(args):
    """Resolve the autotuner-covered serving knobs in priority order:
    explicit CLI flag > TUNE_serve.json winner (``--tuned``, gated) >
    ServingConfig hand default. A tuned artifact whose platform/model do
    not match THIS run is refused with a loud message and the hand
    defaults stand — tuning is per (model, hardware, workload), never
    portable by assumption."""
    from zero_transformer_tpu.config import ServingConfig
    from zero_transformer_tpu.utils.modload import load_script

    defaults = ServingConfig()
    tuned: dict = {}
    if args.tuned:
        bc = load_script("bench_common.py")
        artifact, reasons = bc.load_tuned(
            args.tuned, platform=bc.platform_block(), model=args.model,
            target="serve",
        )
        if artifact is None:
            print(
                f"serve: --tuned {args.tuned} REFUSED "
                f"({'; '.join(reasons)}); falling back to hand defaults",
                flush=True,
            )
        else:
            tuned = dict((artifact.get("winner") or {}).get("knobs") or {})
            if tuned.get("draft_k") and args.repetition_penalty != 1.0:
                # _server would disable speculation later with its generic
                # flag-conflict message — the headline tuned knob must be
                # dropped HERE instead, before the "applying tuned
                # defaults" banner, with the artifact-aware remedy
                print(
                    f"serve: tuned draft_k={tuned['draft_k']} DROPPED: "
                    f"--repetition-penalty {args.repetition_penalty} is "
                    "incompatible with speculative verify; pass "
                    "--repetition-penalty 1.0 to serve the tuned winner "
                    "(the artifact's workload was measured without the "
                    "penalty)",
                    flush=True,
                )
                tuned.pop("draft_k")
            print(
                f"serve: --tuned {args.tuned}: autotuned defaults {tuned} "
                f"(tuned on {artifact.get('platform')}, workload "
                f"{artifact.get('workload_hash')}, "
                f"{artifact.get('value')}x vs hand defaults)",
                flush=True,
            )
    for name in _TUNED_KNOBS:
        if getattr(args, name) is None:
            setattr(args, name, tuned.get(name, getattr(defaults, name)))
    if args.no_fused_tail is None:
        args.no_fused_tail = not tuned.get("fused_tail", defaults.fused_tail)
    return args


def _build_generator(args) -> TextGenerator:
    from zero_transformer_tpu.checkpoint import import_params_msgpack
    from zero_transformer_tpu.config import model_config

    cfg = model_config(
        args.model, compute_dtype=args.dtype, dropout=0.0,
        kv_cache_dtype=args.kv_cache_dtype, param_quant=args.quantize,
        attention_impl=args.attention_impl,
    )
    params = import_params_msgpack(args.params)
    if args.quantize != "int8" and _has_quantized_leaves(params):
        # caught here, at import time: letting this through used to surface
        # as an opaque flax param-structure mismatch deep in apply()
        raise SystemExit(
            f"{args.params} is already int8-quantized (kernel_q/embedding_q "
            "leaves found); pass --quantize int8 to serve it"
        )
    if args.quantize == "int8":
        from zero_transformer_tpu.models.quant import quantize_params

        # quantize on HOST numpy first: deviceing the full-precision tree
        # before shrinking it would put the ~2x bytes on the chip at peak —
        # the exact OOM the flag exists to avoid on 8B-class models
        # (a pre-quantized artifact passes through unchanged and is
        # validated against the quant model's structure)
        params = quantize_params(params, cfg)
    params = jax.tree.map(jnp.asarray, params)
    tokenizer = _load_tokenizer(args.tokenizer)
    # graftlint: allow[donation-safety] reason=params are never donated — generate/engine donate cache+logits+masks+rngs by argnum, params excluded; the TP path additionally seals inside shard_for_inference
    return TextGenerator(
        cfg, params, tokenizer, cache_len=args.cache_len,
        speculative=args.speculative, tensor=args.tensor,
        top_k_impl="approx" if args.approx_top_k else "exact",
    )


def _reload_loader(gen: "TextGenerator", args):
    """Zero-arg loader for hot weight reload (SIGHUP / POST /admin/reload):
    re-runs the STARTUP param path — msgpack import, optional int8
    quantization, TP sharding under the serving mesh — so a swapped tree is
    prepared exactly like the one it replaces. Runs in the reload thread,
    never the tick thread; ``reload_params`` validates before the swap."""

    def load(path: str = args.params):
        from zero_transformer_tpu.checkpoint import import_params_msgpack

        params = import_params_msgpack(path)
        if args.quantize == "int8" and not _has_quantized_leaves(params):
            from zero_transformer_tpu.models.quant import quantize_params

            params = quantize_params(params, gen.cfg)
        if gen.mesh is not None:
            from zero_transformer_tpu.inference import shard_for_inference

            return shard_for_inference(gen.model, params, gen.mesh)
        return jax.tree.map(jnp.asarray, params)

    # graftlint: allow[donation-safety] reason=the closure's product is consumed only by engine.reload_params, which applies ensure_donatable before the tick-boundary swap
    return load


def _server(gen: TextGenerator, args) -> None:
    """Continuous-batching server mode: N KV-cache slots, bounded admission
    queue, SSE token streaming (POST /generate, GET /healthz, GET /metrics).
    Sampling controls come from the CLI and are ENGINE-level (baked into the
    fused decode step); requests vary prompt/budget/seed/deadline.

    Hot-path defaults (docs/SERVING.md): prompts prefill CHUNKED
    (--prefill-chunk tokens per tick, interleaved with decode so long
    prompts never stall active streams) with a chunk-aligned prefix cache
    (--prefix-cache) that lets repeated system prompts skip straight to
    their first novel chunk.

    Resilience wiring: /healthz answers 503 until the engine is READY and
    while it drains; SIGTERM closes admission and finishes in-flight
    generations up to --drain-deadline before exiting 0; SIGHUP (or
    POST /admin/reload) hot-swaps a new checkpoint between decode ticks
    without dropping a slot."""
    from zero_transformer_tpu.inference import SamplingConfig
    from zero_transformer_tpu.serving import ServingEngine, run_server
    from zero_transformer_tpu.utils.monitoring import MetricsLogger

    sampling = SamplingConfig(
        temperature=args.temperature, top_k=args.top_k, top_p=args.top_p,
        repetition_penalty=args.repetition_penalty, greedy=args.greedy,
        top_k_impl=gen.top_k_impl,
    )
    kv_layout = args.kv_layout
    if kv_layout == "paged" and args.prefill_chunk == 0:
        print(
            "serve: --prefill-chunk 0 (legacy one-shot prefill) has no "
            "block-table path; falling back to --kv-layout slab",
            flush=True,
        )
        kv_layout = "slab"
    draft_k = args.draft_k
    if draft_k and args.repetition_penalty != 1.0:
        print(
            "serve: --draft-k requires --repetition-penalty 1.0 (the batched "
            "verify step cannot emulate the in-block penalty); speculation "
            "DISABLED for this run",
            flush=True,
        )
        draft_k = 0
    if draft_k and args.no_fused_tail:
        print(
            "serve: --no-fused-tail (the fused-tail A/B control) covers the "
            "plain decode path only; speculation DISABLED for this run",
            flush=True,
        )
        draft_k = 0
    engine = ServingEngine(
        gen.cfg,
        gen.params,
        n_slots=args.slots,
        cache_len=gen.cache_len,
        sampling=sampling,
        eos_token_id=gen.tokenizer.eos_token_id,
        max_queue=args.max_queue,
        mesh=gen.mesh,
        metrics=MetricsLogger(directory=args.metrics_dir),
        metrics_interval=args.metrics_interval,
        prefill_chunk=args.prefill_chunk,
        prefix_cache_chunks=args.prefix_cache if args.prefill_chunk else 0,
        max_prefill_buckets=args.max_prefill_buckets,
        kv_layout=kv_layout,
        page_size=args.page_size,
        page_pool_tokens=args.page_pool_tokens,
        draft_k=draft_k,
        fused_tail=not args.no_fused_tail,
        role=args.role,
        obs_dir=args.obs_dir or args.metrics_dir,
        trace=not args.no_trace,
    )
    run_server(
        engine, gen.tokenizer, host=args.host, port=args.port,
        reload_source=_reload_loader(gen, args),
        drain_deadline_s=args.drain_deadline,
        admin_token=args.admin_token,
    )


def _repl(gen: TextGenerator, args) -> None:
    print("zero_transformer_tpu generation REPL — empty line to exit")
    while True:
        try:
            prompt = input(">>> ")
        except EOFError:
            return
        if not prompt.strip():
            return
        # stream tokens as they decode (reference app.py behavior)
        for piece in gen.stream(
            prompt,
            max_new_tokens=args.max_new_tokens,
            temperature=args.temperature,
            top_k=args.top_k,
            top_p=args.top_p,
            repetition_penalty=args.repetition_penalty,
            greedy=args.greedy,
        ):
            print(piece, end="", flush=True)
        print()


def _ui(gen: TextGenerator) -> None:
    try:
        import gradio as gr
    except ImportError:
        raise SystemExit(
            "gradio is not installed in this environment; use the CLI/REPL "
            "surface instead (the reference's UI dependency made serving "
            "CUDA+gradio-only, app.py:192-261)"
        )
    # mirror of the reference's controls (app.py:199-259)
    demo = gr.Interface(
        fn=lambda prompt, steps, temp, tk, tp, rp, greedy: gen(
            prompt,
            max_new_tokens=int(steps),
            temperature=temp,
            top_k=int(tk),
            top_p=tp,
            repetition_penalty=rp,
            greedy=greedy,
        ),
        inputs=[
            gr.Textbox(label="Prompt"),
            gr.Slider(1, 512, value=128, label="Max new tokens"),
            gr.Slider(0.1, 2.0, value=0.8, label="Temperature"),
            gr.Slider(0, 100, value=0, label="Top-k (0 = off)"),
            gr.Slider(0.0, 0.99, value=0.9, label="Top-p (0 = off)"),
            gr.Slider(1.0, 2.0, value=1.1, label="Repetition penalty"),
            gr.Checkbox(label="Greedy"),
        ],
        outputs=gr.Textbox(label="Completion"),
    )
    demo.launch()


def main(argv=None) -> None:
    p = argparse.ArgumentParser(prog="zero_transformer_tpu.serve", description=__doc__)
    p.add_argument("--model", required=True, help="model zoo name (configs/models.yaml)")
    p.add_argument("--params", required=True, help="params msgpack (see export)")
    p.add_argument("--tokenizer", default="EleutherAI/gpt-neox-20b")
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--quantize", default="none", choices=("none", "int8"),
                   help="weight-only int8 serving: kernels + token table "
                        "stored int8 with per-channel scales — halves the "
                        "weight HBM reads decode is bound by, and fits "
                        "8B-class models on one 16 GB chip")
    p.add_argument("--attention-impl", default="auto",
                   choices=("auto", "xla", "flash"),
                   help="attention dispatch: 'auto' (default) runs the "
                        "Pallas kernels — flash for prefill/verify windows, "
                        "the paged-attention kernel for block-table decode — "
                        "wherever the gate accepts (TPU, or interpret mode "
                        "under ZT_PALLAS_INTERPRET=1), XLA elsewhere; 'xla' "
                        "forces the reference path; 'flash' is flash-or-"
                        "raise (never silently O(T^2))")
    p.add_argument("--no-fused-tail", action="store_true", default=None,
                   help="A/B CONTROL: run sampling as its own dispatch "
                        "after the forward instead of inside the single "
                        "jitted decode program (byte-identical output; "
                        "exists so the bench can price the fused tail — "
                        "disables --draft-k)")
    p.add_argument("--kv-cache-dtype", default="auto", choices=("auto", "int8"),
                   help="int8 halves KV-cache HBM traffic (doubles servable "
                        "context) at slight quantization cost")
    p.add_argument("--cache-len", type=int, default=None)
    p.add_argument("--tensor", type=int, default=1, metavar="N",
                   help="tensor-parallel serving over the first N chips "
                        "(params + KV cache shard over heads/features; "
                        "serves models larger than one chip's HBM)")
    p.add_argument("--speculative", type=int, default=0, metavar="K",
                   help="prompt-lookup speculative decoding with K-token "
                        "drafts (greedy one-shot generation; exact same "
                        "output — incl. under the repetition penalty — in "
                        "fewer model forwards)")
    p.add_argument("--prompt", default=None, help="one-shot generation")
    p.add_argument("--max-new-tokens", type=int, default=128)
    p.add_argument("--temperature", type=float, default=0.8)
    p.add_argument("--top-k", type=int, default=0)
    p.add_argument("--approx-top-k", action="store_true",
                   help="use the TPU partial-reduce (lax.approx_max_k) for "
                        "the top-k cutoff instead of the exact vocab sort; "
                        "the kept set can be slightly wider than k")
    p.add_argument("--top-p", type=float, default=0.9)
    p.add_argument("--repetition-penalty", type=float, default=1.1)
    p.add_argument("--greedy", action="store_true")
    p.add_argument("--ui", action="store_true", help="launch the Gradio UI")
    p.add_argument("--server", action="store_true",
                   help="continuous-batching HTTP server: slot-based KV "
                        "cache, bounded admission queue, SSE streaming "
                        "(POST /generate, GET /healthz, GET /metrics)")
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--port", type=int, default=8000)
    from zero_transformer_tpu.config import ServingConfig

    serving_defaults = ServingConfig()
    p.add_argument("--slots", type=int, default=serving_defaults.slots,
                   help="concurrent decode slots (KV-cache rows); queued "
                        "requests admit as slots free up")
    p.add_argument("--max-queue", type=int, default=serving_defaults.max_queue,
                   help="admission-queue depth; beyond it /generate "
                        "returns 429 (backpressure)")
    p.add_argument("--tuned", nargs="?", const="TUNE_serve.json",
                   default=None, metavar="TUNE_JSON",
                   help="load autotuned serving defaults from a "
                        "scripts/autotune.py artifact (default: "
                        "TUNE_serve.json). Applied only when the artifact's "
                        "platform/model match this run — a mismatch is "
                        "refused with a loud warning and the hand defaults "
                        "stand; explicit flags always win over tuned values")
    # the autotuner-covered knobs default to None (sentinel): resolution is
    # explicit flag > TUNE_serve.json winner (--tuned, gated) > the
    # ServingConfig hand default — see _resolve_tuned_args
    p.add_argument("--prefill-chunk", type=int,
                   default=None,
                   help="prefill this many prompt tokens per scheduler tick, "
                        "written directly into the slot KV cache and "
                        "interleaved with decode — a long prompt no longer "
                        "stalls every active stream for its full prefill "
                        "(0 = legacy one-shot bucketed prefill; default "
                        f"{serving_defaults.prefill_chunk})")
    p.add_argument("--prefix-cache", type=int,
                   default=serving_defaults.prefix_cache_chunks,
                   metavar="CHUNKS",
                   help="capacity of the chunk-aligned token-prefix K/V "
                        "LRU: repeated system prompts skip straight to "
                        "their first novel chunk (0 = off; requires "
                        "--prefill-chunk > 0; flushed on hot reload)")
    p.add_argument("--kv-layout", default=None,
                   choices=("slab", "paged"),
                   help="KV cache layout: 'paged' (default) = block-table "
                        "page pool (PagedAttention) — HBM scales with ACTUAL "
                        "sequence lengths, not slots x cache_len, and prefix "
                        "hits are page-refcount bumps; 'slab' = the classic "
                        "fixed [slots, cache_len] rows")
    p.add_argument("--page-size", type=int,
                   default=None,
                   help="tokens per KV page (paged layout); must divide "
                        "--prefill-chunk and the cache length (default "
                        f"{serving_defaults.page_size})")
    p.add_argument("--page-pool-tokens", type=int,
                   default=None,
                   help="total page-pool capacity in token positions "
                        "(0 = the slab-equivalent slots x cache_len); at a "
                        "fixed budget, more concurrent streams fit whenever "
                        "real sequences run shorter than cache_len")
    p.add_argument("--draft-k", type=int, default=None,
                   help="speculative serving: verify K prompt-lookup draft "
                        "tokens per slot per tick in one batched forward "
                        "(greedy = bit-identical output, sampling = exact "
                        "rejection rule; needs --repetition-penalty 1.0; "
                        f"0 = off; default {serving_defaults.draft_k})")
    p.add_argument("--role", default=serving_defaults.role,
                   choices=("mixed", "prefill", "decode"),
                   help="disaggregated fleet role: 'prefill' runs only "
                        "chunked prefill and ships finished KV pages to the "
                        "decode replica each request names (prefill_to); "
                        "'decode' serves imported streams plus the "
                        "recompute fallback; 'mixed' (default) is the "
                        "classic standalone replica. Non-mixed roles "
                        "require --kv-layout paged")
    p.add_argument("--max-prefill-buckets", type=int,
                   default=serving_defaults.max_prefill_buckets,
                   help="cap on distinct compiled one-shot prefill buckets "
                        "(legacy --prefill-chunk 0 path): past it, new "
                        "prompt lengths round up to an existing bucket "
                        "instead of compiling another program")
    p.add_argument("--metrics-dir", default=None,
                   help="JSONL sink for serving metrics (TTFT/ITL "
                        "percentiles, tokens/s, occupancy)")
    p.add_argument("--obs-dir", default=None,
                   help="observability run directory: flight-recorder dumps "
                        "(breaker-open/drain post-mortems), on-demand "
                        "profiler captures (POST /admin/profile), and span "
                        "trace exports land here (defaults to --metrics-dir; "
                        "unset disables dumps/profiling, not recording)")
    p.add_argument("--no-trace", action="store_true",
                   help="disable span tracing (the bounded ring costs <2%% "
                        "decode tok/s — BENCH_serve.json obs_overhead is "
                        "the measured number); /metrics histograms stay on")
    p.add_argument("--metrics-interval", type=int, default=200,
                   help="log serving metrics every N scheduler ticks")
    p.add_argument("--admin-token", default=None,
                   help="bearer token for /admin/* from non-loopback peers "
                        "(loopback is always allowed; without a token, "
                        "remote admin requests get 403 — weight swapping "
                        "must not be open to any peer that can reach a "
                        "--host 0.0.0.0 port)")
    p.add_argument("--drain-deadline", type=float,
                   default=serving_defaults.drain_deadline_s,
                   help="graceful-drain budget on SIGTERM/shutdown: "
                        "admission closes immediately (503 + Retry-After), "
                        "in-flight generations get this many seconds to "
                        "finish, then are force-finished and the process "
                        "exits 0")
    args = _resolve_tuned_args(p.parse_args(argv))

    gen = _build_generator(args)
    if args.server:
        _server(gen, args)
    elif args.ui:
        _ui(gen)
    elif args.prompt is not None:
        sys.stdout.write(
            gen(
                args.prompt,
                max_new_tokens=args.max_new_tokens,
                temperature=args.temperature,
                top_k=args.top_k,
                top_p=args.top_p,
                repetition_penalty=args.repetition_penalty,
                greedy=args.greedy,
            )
            + "\n"
        )
    else:
        _repl(gen, args)


if __name__ == "__main__":
    main()
