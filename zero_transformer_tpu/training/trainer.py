"""Trainer: the orchestrator the reference keeps inline in ``main_zero.py``.

One object wires config → mesh → model → optimizer → sharding plan → fused
train step → data → checkpoints → metrics, with the reference's semantics
(eval every N steps, checkpoint keep=K, resume = restore + rng fold + loader
fast-forward, warm-init from another run's params) but none of its per-step
resharding churn: state lives permanently in its ZeRO sharding and the hot
loop is ONE jitted call per step (vs the reference's four dispatches,
``main_zero.py:495-500``).
"""
from __future__ import annotations

import dataclasses
import logging
import signal
import threading
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from zero_transformer_tpu import checkpoint as ckpt_lib
from zero_transformer_tpu.config import Config
from zero_transformer_tpu.data import DataLoader, device_put_batch, make_loader
from zero_transformer_tpu.models.gpt import Transformer
from zero_transformer_tpu.parallel.mesh import make_mesh
from zero_transformer_tpu.parallel.zero import (
    TrainState,
    init_train_state,
    make_eval_step,
    make_plan,
    make_train_step,
)
from zero_transformer_tpu.training.optimizer import make_optimizer, make_schedule
from zero_transformer_tpu.obs import FlightRecorder, Tracer
from zero_transformer_tpu.utils import monitoring
from zero_transformer_tpu.utils.jax_compat import ensure_donatable

log = logging.getLogger("zero_transformer_tpu")


def _exposed_comm_from_artifact(
    path: str, overlap_comm: bool
) -> Optional[float]:
    """Read the measured exposed-comm fraction for the ACTIVE overlap arm
    from a BENCH_step.json (scripts/train_step_bench.py). Returns None —
    the gauge stays unregistered — on a missing/unreadable artifact or one
    from a different backend than this process (a CPU-box measurement must
    not masquerade as this TPU's decomposition)."""
    import json

    import jax as _jax

    try:
        with open(path) as f:
            art = json.load(f)
    except (OSError, ValueError):
        log.warning("step_bench_artifact %s unreadable; exposed_comm_frac "
                    "gauge disabled", path)
        return None
    # (platform, device_kind) is the comparability key — the same rule the
    # bench guard applies; a v4 measurement must not export as a v5e run's
    # decomposition any more than a CPU one may
    hw = (_jax.default_backend(), _jax.devices()[0].device_kind)
    art_hw = (art.get("platform"), art.get("device_kind"))
    if art_hw != hw:
        log.warning(
            "step_bench_artifact %s measured on %r but this run is on %r; "
            "exposed_comm_frac gauge disabled (re-run "
            "scripts/train_step_bench.py here)",
            path, art_hw, hw,
        )
        return None
    arm = art.get("overlap_on" if overlap_comm else "overlap_off") or {}
    frac = arm.get("exposed_comm_frac")
    return float(frac) if frac is not None else None


def remap_loader_state(
    meta: Optional[dict],
    batch_size: int,
    train_context: int,
    accum_steps: int = 1,
) -> Optional[dict]:
    """Map a saved loader position onto the CURRENT run's batch geometry.

    The loader position is stored in GLOBAL optimizer steps
    (``steps_consumed``; each consumes ``batch_size * accum_steps``
    sequences of ``train_context`` tokens), so a topology change alone
    (different device/host count) needs NO remap: every process assembles
    the same global batch and the global-token trajectory continues exactly
    where it left off. When the geometry changed — ``batch_size``,
    ``train_context``, or ``gradient_accumulation_steps`` (the canonical
    elastic move is halving the devices and doubling accum to preserve the
    global batch) — the position is remapped by TOKEN count, rounding DOWN
    to the previous whole-step boundary: up to one optimizer step's tokens
    are replayed, never skipped (the batch-boundary semantics documented in
    docs/RESILIENCE.md and pinned in tests/test_elastic.py)."""
    loader_state = (meta or {}).get("loader")
    if not loader_state:
        return None
    sched = (meta or {}).get("schedule") or {}
    old_bs = int(sched.get("batch_size", batch_size))
    old_ctx = int(sched.get("train_context", train_context))
    old_accum = int(sched.get("accum_steps", accum_steps))
    if (old_bs, old_ctx, old_accum) == (batch_size, train_context, accum_steps):
        return loader_state
    steps = int(loader_state.get("steps_consumed", 0))
    tokens = steps * old_bs * old_accum * old_ctx
    new_steps, replayed = divmod(
        tokens, batch_size * accum_steps * train_context
    )
    if replayed:
        log.warning(
            "loader remap: batch geometry changed (%d seq x %d accum x %d "
            "tok -> %d x %d x %d); resuming at optimizer step %d replays "
            "%d tokens (position rounds DOWN to a step boundary — replay, "
            "never skip)",
            old_bs, old_accum, old_ctx, batch_size, accum_steps,
            train_context, new_steps, replayed,
        )
    return {"steps_consumed": int(new_steps)}


@dataclasses.dataclass(frozen=True)
class TrainingBuild:
    """Mesh → model → optimizer → plan → compiled-step builders for a config.

    The data-free, side-effect-free half of Trainer construction, factored
    out so the ``--memory-analysis`` surface (and tests) can build the real
    train step without touching loaders or checkpoint directories."""

    mesh: Any
    model: Transformer
    schedule: Any
    tx: Any
    plan: Any
    train_step: Any
    eval_step: Any
    sample_shape: tuple


def build_training(cfg: Config, mesh=None) -> TrainingBuild:
    if cfg.model.param_quant != "none":
        raise ValueError(
            "param_quant is an inference-only configuration (serve "
            "--quantize); training runs on full-precision params"
        )
    mesh = mesh if mesh is not None else make_mesh(cfg.mesh)
    opt = dataclasses.replace(cfg.optimizer, total_steps=cfg.training.total_steps)
    # an active sequence axis routes attention through the ring-attention
    # context-parallel path (ops/ring_attention.py)
    from zero_transformer_tpu.parallel.mesh import SEQUENCE_AXIS

    seq_parallel = mesh.shape[SEQUENCE_AXIS] > 1
    model = Transformer(cfg.model, mesh=mesh if seq_parallel else None)
    schedule = make_schedule(opt)
    tx = make_optimizer(opt, schedule)

    sample_shape = (cfg.training.batch_size, cfg.training.train_context)
    plan = make_plan(
        model, tx, mesh, sample_shape, cfg.mesh.zero_stage,
        pp_schedule=cfg.mesh.pp_schedule,
    )
    train_step = make_train_step(
        model,
        tx,
        mesh,
        plan,
        cfg.mesh.zero_stage,
        schedule,
        # lets the explicit ZeRO-2/3 core rebuild the optimizer with a
        # shard-aware grad-clip norm (same opt-state structure)
        tx_factory=lambda norm_fn, zc=None: make_optimizer(
            opt, schedule, norm_fn, zero_collectives=zc
        ),
        pp_schedule=cfg.mesh.pp_schedule,
        grad_accum_dtype=cfg.training.grad_accum_dtype,
        pp_interleave=cfg.mesh.pp_interleave,
        overlap_comm=cfg.mesh.overlap_comm,
    )
    eval_step = make_eval_step(model, mesh, plan)
    return TrainingBuild(
        mesh=mesh, model=model, schedule=schedule, tx=tx, plan=plan,
        train_step=train_step, eval_step=eval_step, sample_shape=sample_shape,
    )


def _schedule_memory(
    cfg: Config, b: "TrainingBuild", abstract, accum: int
) -> Dict[str, Any]:
    """Analytic, schedule-aware memory itemization for ``memory_analysis``.

    Estimates (clearly labeled — the compiled ``temp_bytes`` is the ground
    truth when the backend reports it): per-microbatch activation bytes are
    one residual-stream tensor [batch, T, d_model] at compute dtype; the
    pipeline stash formulas count what each engine's wavefront keeps live
    (GPipe/interleaved: the differentiated tick scan saves its carry once
    per tick; 1F1B: the hand-managed 2P-slot input ring)."""
    from zero_transformer_tpu.analysis.memory import pp_stash_ticks
    from zero_transformer_tpu.config import resolve_dtype
    from zero_transformer_tpu.parallel.pipeline import bubble_fraction

    mc = cfg.mesh
    P_ = mc.pipe
    V = mc.pp_interleave
    out: Dict[str, Any] = {
        "pp_schedule": mc.pp_schedule,
        "pp_interleave": V,
        "overlap_comm": mc.overlap_comm,
        "bubble_frac": round(bubble_fraction(mc.pp_schedule, P_, accum, V), 5),
    }
    act = (
        cfg.training.batch_size
        * cfg.training.train_context
        * cfg.model.d_model
        * jnp.dtype(resolve_dtype(cfg.model.compute_dtype)).itemsize
    )
    out["microbatch_activation_bytes"] = act
    if P_ > 1:
        # ONE formula table with the analytic pruner (analysis/memory.py)
        stash_ticks = pp_stash_ticks(mc.pp_schedule, accum, P_, V)
        out["pp_activation_stash_bytes_est"] = stash_ticks * act
        if mc.pp_schedule == "interleaved":
            # interleaved stores the block stack pipe-replicated (see
            # sharding.plan_rules): P-1 extra copies vs the contiguous shard
            blocks_bytes = sum(
                leaf.size * jnp.dtype(leaf.dtype).itemsize
                for leaf in jax.tree.leaves(abstract.params["blocks"])
            )
            out["pp_block_replication_extra_bytes"] = (P_ - 1) * (
                blocks_bytes // P_
            )
    if mc.overlap_comm:
        from zero_transformer_tpu.parallel.overlap import bucket_summary

        out["overlap_buckets"] = bucket_summary(b.plan, b.mesh, abstract.params)
    return out


def memory_analysis(cfg: Config, accum: Optional[int] = None) -> Dict[str, Any]:
    """AOT-compile the train step for ``cfg`` and report the compiled memory
    picture — no state is materialized and nothing executes. The tool behind
    sizing runs for a 16 GB chip (see docs/DESIGN.md "The 16 GB budget"):
    the same HBM accounting the AOT compiler enforces when it rejects a
    config, exposed BEFORE a multi-minute failed launch.

    Compiled sizes (argument/output/temp/alias/peak) are PER DEVICE —
    exactly what must fit one chip's HBM; the ``*_global`` keys are the
    logical whole-tree sizes. Backends without ``memory_analysis`` support
    fall back to the shape-derived global totals with ``"exact": False``.

    The ``schedule`` block keeps the estimate honest per training schedule:
    the pipeline engines stash activations across the wavefront (O(M) ticks
    for GPipe, the 2P-slot ring for 1F1B, O(V*M) ticks for interleaved —
    which ALSO stores the block stack pipe-replicated), and ``overlap_comm``
    keeps up to two gathered layer buckets live while the scan runs; all of
    that is inside the compiled ``temp_bytes`` when exact, and itemized
    analytically here so a CPU sizing pass still sees it."""
    b = build_training(cfg)
    abstract = ckpt_lib.abstract_state(b.model, b.tx, b.plan, b.sample_shape)
    accum = accum or cfg.training.gradient_accumulation_steps
    batch = jax.ShapeDtypeStruct((accum, *b.sample_shape), jnp.int32)
    rng = jax.eval_shape(lambda: jax.random.PRNGKey(0))
    compiled = b.train_step.lower(abstract, batch, rng).compile()

    def _tree_bytes(tree) -> int:
        return sum(
            leaf.size * jnp.dtype(leaf.dtype).itemsize
            for leaf in jax.tree.leaves(tree)
        )

    # GLOBAL logical sizes; the compiled numbers below are PER DEVICE (a
    # ZeRO-sharded opt state divides across the mesh, so on n devices
    # alias/argument bytes are roughly params + sharded-state/n each)
    out = {
        "state_bytes_global": _tree_bytes(abstract),
        "batch_bytes_global": _tree_bytes(batch),
        "n_devices": len(b.mesh.devices.ravel()),
        "tokens_per_step": accum * b.sample_shape[0] * b.sample_shape[1],
        "schedule": _schedule_memory(cfg, b, abstract, max(accum, 1)),
    }
    # the compile-free analytic itemization (analysis/memory.py) rides
    # along so one report carries both the compiled ground truth and the
    # numbers the autotuner's pruner would see for this point
    from zero_transformer_tpu.analysis.memory import analytic_memory

    out["analytic"] = analytic_memory(cfg, accum=accum)
    try:
        ma = compiled.memory_analysis()
        out.update(
            exact=True,
            argument_bytes=ma.argument_size_in_bytes,
            output_bytes=ma.output_size_in_bytes,
            temp_bytes=ma.temp_size_in_bytes,
            alias_bytes=ma.alias_size_in_bytes,
            generated_code_bytes=ma.generated_code_size_in_bytes,
            # donated state aliases in place, so the live peak is roughly
            # arguments (incl. state) + temps − aliased output
            peak_estimate_bytes=(
                ma.argument_size_in_bytes
                + ma.temp_size_in_bytes
                + ma.output_size_in_bytes
                - ma.alias_size_in_bytes
            ),
        )
    except Exception as e:  # backend without memory_analysis (CPU)
        out.update(exact=False, unavailable_reason=f"{type(e).__name__}: {e}")
    return out


class Trainer:
    def __init__(
        self,
        cfg: Config,
        mesh=None,
        train_loader: Optional[DataLoader] = None,
        val_loader: Optional[DataLoader] = None,
        use_wandb: bool = False,
        chaos=None,
    ):
        self.cfg = cfg
        build = build_training(cfg, mesh=mesh)
        self.mesh = build.mesh
        self.zero_stage = cfg.mesh.zero_stage
        self.model = build.model
        self.schedule = build.schedule
        self.tx = build.tx
        self.sample_shape = build.sample_shape
        self.plan = build.plan
        self.train_step = build.train_step
        self.eval_step = build.eval_step
        self.batch_sharding = NamedSharding(
            self.mesh, P(None, *self.plan.batch.spec)
        )

        self.train_loader = train_loader or make_loader(cfg)
        # lazy: a run with evaluation disabled must not require validation data
        self._val_loader = val_loader

        self.ckpt = ckpt_lib.CheckpointManager(
            cfg.checkpoint.directory,
            keep=cfg.checkpoint.keep,
            save_frequency=cfg.checkpoint.save_frequency,
            async_save=cfg.checkpoint.async_save,
            integrity=cfg.checkpoint.integrity,
        )
        # fail fast on a bad checkpoint destination (wrong bucket, perms)
        # before any compute is spent — the manager is otherwise lazy
        self.ckpt.ensure_ready()
        # chaos injection (tests/test_resilience.py): wrap the fault seams —
        # step function, loader, checkpoint manager — before anything
        # compiles against them. None in production runs.
        self._chaos = chaos
        if chaos is not None:
            self.train_step = chaos.wrap_train_step(self.train_step)
            self.train_loader = chaos.wrap_loader(self.train_loader)
            self.ckpt = chaos.wrap_checkpoint(self.ckpt)
        # anomaly-guard wrap cache, keyed on the identity of the step
        # function it wrapped (tests monkeypatch self.train_step; the guard
        # must wrap whatever is current at train() time, once)
        self._guard_cache: Optional[tuple] = None
        # supervisor-facing run status
        self.preempted = False
        self.last_step: Optional[int] = None
        self.resilience_report: Dict[str, Any] = {}
        # filled by a verified resume (quarantine/fallback counters)
        self._restore_report: Optional[ckpt_lib.RestoreReport] = None
        from zero_transformer_tpu.config import flatten_config

        self.metrics = monitoring.MetricsLogger(
            directory=cfg.checkpoint.directory,
            use_wandb=use_wandb,
            # full flattened run config at init (reference main_zero.py:354-366)
            config=flatten_config(cfg),
        )
        # observability (obs/): the step loop records per-phase spans (data
        # fetch, dispatch, device sync, checkpoint save, replica audit) into
        # a bounded tracer, and a flight recorder keeps the last N step
        # summaries + events for the post-mortem dump fired on anomaly
        # halt, watchdog abort, and checkpoint quarantine. Both export to
        # the run directory beside metrics.jsonl (local dirs only — object
        # stores have no append/dump semantics here).
        from zero_transformer_tpu.utils.paths import is_remote_path

        obs_dir = (
            cfg.checkpoint.directory
            if cfg.checkpoint.directory
            and not is_remote_path(cfg.checkpoint.directory)
            and jax.process_index() == 0
            else None
        )
        self.tracer = Tracer(capacity=16384)
        self.flight = FlightRecorder(directory=obs_dir, tracer=self.tracer)
        # step-time decomposition gauges (PR 8): bubble_frac is ANALYTIC —
        # exact for the configured schedule (pipeline.bubble_fraction, the
        # same formula the bench and memory_analysis use); exposed_comm_frac
        # is a MEASUREMENT and only reported when the operator points
        # training.step_bench_artifact at a BENCH_step.json measured for
        # this platform (scripts/train_step_bench.py). Scrape them from
        # /metrics via train.py --metrics-port (obs.MetricsExporter).
        from zero_transformer_tpu.obs import Registry
        from zero_transformer_tpu.parallel.pipeline import bubble_fraction

        self.registry = Registry()
        self._bubble_frac = bubble_fraction(
            cfg.mesh.pp_schedule,
            cfg.mesh.pipe,
            max(cfg.training.gradient_accumulation_steps, 1),
            cfg.mesh.pp_interleave,
        )
        self._exposed_comm_frac: Optional[float] = None
        if cfg.training.step_bench_artifact:
            self._exposed_comm_frac = _exposed_comm_from_artifact(
                cfg.training.step_bench_artifact, cfg.mesh.overlap_comm
            )
        self.registry.gauge_func(
            "train_bubble_frac",
            "analytic pipeline-bubble fraction of the configured schedule",
            lambda: self._bubble_frac,
        )
        if self._exposed_comm_frac is not None:
            self.registry.gauge_func(
                "train_exposed_comm_frac",
                "measured exposed-communication fraction of step time "
                "(from training.step_bench_artifact)",
                lambda: self._exposed_comm_frac,
            )
        self.rng = jax.random.PRNGKey(cfg.training.seed)
        # validation window pin: source state captured at first evaluate(),
        # restored before every later one, so eval always scores the SAME
        # data window and loss curves are comparable step-to-step
        self._val_window: Optional[dict] = None
        self.flops_per_token = monitoring.model_flops_per_token(
            cfg.model.num_params,
            cfg.model.n_layers,
            cfg.model.d_model,
            cfg.training.train_context,
        )
        self.state: Optional[TrainState] = None
        # compile-family sanitizer (analysis/runtime.py): the train step is
        # ONE program for the whole run — batch geometry, rng layout and
        # carry structure are fixed at build time. A second distinct
        # signature here means a shape leaked into the step loop (the
        # "training got slow" recompile class; strict mode raises in tests)
        from zero_transformer_tpu.analysis.runtime import bounded_dispatch

        self.dispatch_site = bounded_dispatch("trainer.step", 1)

    @property
    def val_loader(self) -> DataLoader:
        if self._val_loader is None:
            self._val_loader = make_loader(self.cfg, validation=True)
        return self._val_loader

    # -- state lifecycle ----------------------------------------------------

    def abstract_state(self) -> TrainState:
        return ckpt_lib.abstract_state(
            self.model, self.tx, self.plan, self.sample_shape
        )

    def _save_meta(self) -> dict:
        """Per-save JSON metadata: loader position + the topology and batch
        geometry the checkpoint was written under (what elastic resume
        validates and remaps against)."""
        from zero_transformer_tpu.parallel import sharding as shd

        return {
            "loader": self.train_loader.state(),
            "topology": shd.topology_summary(
                self.mesh, self.zero_stage, self.cfg.mesh.pp_schedule
            ),
            "schedule": {
                "batch_size": self.cfg.training.batch_size,
                "train_context": self.cfg.training.train_context,
                "accum_steps": max(
                    self.cfg.training.gradient_accumulation_steps, 1
                ),
            },
        }

    def _check_restore_meta(self, meta: dict) -> None:
        """Pre-restore elastic-topology validation (raises ValueError — fatal
        to the supervisor — on genuinely incompatible topologies, BEFORE any
        array IO or pjit compilation touches the checkpoint)."""
        from zero_transformer_tpu.parallel import sharding as shd

        notes = shd.check_elastic_compat(
            (meta or {}).get("topology"),
            self.mesh,
            self.zero_stage,
            self.cfg.training.batch_size,
            pp_schedule=self.cfg.mesh.pp_schedule,
        )
        for note in notes:
            log.warning("elastic resume: %s", note)

    def init_state(self) -> TrainState:
        """Fresh init, or resume / warm-init per the checkpoint config."""
        ck = self.cfg.checkpoint
        if ck.resume and self.ckpt.latest_step() is not None:
            # verified restore: digest-checks every leaf against the step's
            # integrity manifest, quarantines corrupt step dirs, falls back
            # to the newest verified older step, and validates/reshards
            # across topology changes (elastic ZeRO resume)
            state, meta, report = self.ckpt.restore_verified(
                self.abstract_state(),
                check_meta=self._check_restore_meta,
                on_event=self._restore_event,
            )
            self._restore_report = report
            # donation seam: restore_verified seals its output through
            # ensure_donatable at the source (checkpoint.py), so the state
            # is already runtime-owned when the donating train step sees it
            step = int(state.step)
            loader_state = remap_loader_state(
                meta,
                self.cfg.training.batch_size,
                self.cfg.training.train_context,
                max(self.cfg.training.gradient_accumulation_steps, 1),
            )
            if loader_state:
                self.train_loader.restore(loader_state)
            else:
                self.train_loader.skip(step)
            log.info(
                "resumed from step %d (verified in %.1f ms; %d quarantined, "
                "fell back %d step(s))",
                step, report.verify_ms, len(report.quarantined),
                report.fallback_steps,
            )
        else:
            if ck.resume:
                incomplete = self.ckpt.incomplete_steps()
                if incomplete:
                    # --resume with step dirs on disk but none COMPLETE:
                    # almost always a crash mid-first-save (fresh init is
                    # correct and save() will quarantine the leftovers), but
                    # if these were real checkpoints whose commit markers a
                    # backup tool dropped, the operator must know progress
                    # is being discarded — say so loudly, in metrics too
                    log.error(
                        "--resume: step dir(s) %s exist under %s but none "
                        "pass the completeness check (no commit markers) — "
                        "starting FRESH from step 0. If these are real "
                        "checkpoints, restore their _CHECKPOINT_METADATA/"
                        "state/_METADATA files and rerun",
                        incomplete, self.cfg.checkpoint.directory,
                    )
                    self.metrics.event(
                        "resume_found_only_incomplete_steps", 0,
                        steps=str(incomplete),
                    )
            state = init_train_state(
                self.model, self.tx, self.rng, self.mesh, self.sample_shape, self.plan
            )
            if ck.warm_init and ck.warm_init_msgpack:
                # donation seam sealed inside _warm_params_from_msgpack
                params = self._warm_params_from_msgpack(ck.warm_init_msgpack)
                state = TrainState(
                    step=state.step, params=params, opt_state=state.opt_state
                )
                log.info("warm-initialized params from %s", ck.warm_init_msgpack)
            elif ck.warm_init and ck.warm_init_dir:
                donor = ckpt_lib.CheckpointManager(ck.warm_init_dir, keep=1)
                abstract = self.abstract_state()
                # donation seam sealed inside restore_params (checkpoint.py)
                params = donor.restore_params(abstract.params)
                state = TrainState(
                    step=state.step, params=params, opt_state=state.opt_state
                )
                log.info("warm-initialized params from %s", ck.warm_init_dir)
        self.state = state
        return state

    def _restore_event(self, name: str, step: int, **fields) -> None:
        """Restore-path events -> metrics timeline AND flight recorder; a
        quarantined checkpoint additionally dumps the recorder window (the
        post-mortem for WHY a step dir failed its digest belongs next to
        the quarantined artifact — docs/RESILIENCE.md)."""
        self.metrics.event(name, step, **fields)
        self.flight.event(name, step=step, **fields)
        if name == "ckpt_quarantined":
            self.flight.dump("quarantine", extra={"step": step, **fields})

    def _warm_params_from_msgpack(self, path: str):
        """Load donor params, auto-extend depth / convert layer layout to this
        model, and place into the plan's shardings (the reference's scale-up
        warm start, reference ``main_zero.py:268-289`` + ``extend_params.py``)."""
        from zero_transformer_tpu.utils import surgery

        donor = ckpt_lib.import_params_msgpack(path)
        if surgery.num_layers(donor) != self.cfg.model.n_layers:
            donor = surgery.extend_depth(donor, self.cfg.model.n_layers)
        if self.cfg.model.n_experts > 0:
            # dense donor → MoE model: sparse upcycling. Runs BEFORE the
            # layout conversion (upcycle_moe needs the stacked layout, and
            # a scan_layers=False model would otherwise unstack first and
            # skip this branch entirely).
            stacked = surgery.stack_blocks(donor)
            if "mlp" in stacked.get("blocks", {}):
                donor = surgery.upcycle_moe(stacked, self.cfg.model.n_experts)
                log.info(
                    "upcycled dense donor to %d experts", self.cfg.model.n_experts
                )
        if surgery.is_stacked(donor) != self.cfg.model.scan_layers:
            donor = (
                surgery.stack_blocks(donor)
                if self.cfg.model.scan_layers
                else surgery.unstack_blocks(donor)
            )
        abstract = self.abstract_state().params
        donor_struct = jax.tree.structure(donor)
        if donor_struct != jax.tree.structure(abstract):
            raise ValueError(
                f"warm-init donor structure does not match model "
                f"{self.cfg.model.name!r} after surgery: {path}"
            )
        for (kp, d), (_, t) in zip(
            jax.tree_util.tree_flatten_with_path(donor)[0],
            jax.tree_util.tree_flatten_with_path(abstract)[0],
        ):
            if tuple(d.shape) != tuple(t.shape):
                name = "/".join(str(getattr(k, "key", k)) for k in kp)
                raise ValueError(
                    f"warm-init donor {path} has {name} shaped {tuple(d.shape)} "
                    f"but model {self.cfg.model.name!r} expects {tuple(t.shape)}"
                )
        # runtime-owned buffers: device_put of host msgpack leaves can be
        # zero-copy, and this tree flows into the donating train step
        return ensure_donatable(
            jax.tree.map(
                lambda leaf, tgt: jax.device_put(
                    jnp.asarray(leaf, tgt.dtype), tgt.sharding
                ),
                donor,
                abstract,
            )
        )

    # -- loops --------------------------------------------------------------

    def evaluate(self, state: Optional[TrainState] = None) -> Dict[str, float]:
        state = state if state is not None else self.state
        max_steps = self.cfg.training.maximum_evaluation_steps
        # Pin the validation window: without this every evaluate() consumes
        # the NEXT max_steps batches of a continuing stream, so each eval
        # scores different data and the loss curve is incomparable across
        # steps (round-2 verdict, "validation drift").
        if self._val_window is None:
            self._val_window = self.val_loader.source.state()
        else:
            self.val_loader.source.restore(self._val_window)
        total, n = 0.0, 0
        it = iter(self.val_loader)
        for _ in range(max_steps):
            local = next(it)[0]  # [local_batch, seq]
            batch = device_put_batch(local, self.plan.batch)
            total += float(self.eval_step(state.params, batch))
            n += 1
        loss = total / max(n, 1)
        return {"loss": loss, "perplexity": float(jnp.exp(jnp.minimum(loss, 20.0)))}

    def _install_preemption_handler(self):
        """SIGTERM → finish the current step, force-save, exit the train loop
        cleanly (preemption handling the reference lacks — its only recovery
        was rerunning with --resume, reference ``main_zero.py:48-52``).
        Returns (flag, restore_fn); no-op off the main thread."""
        flag = threading.Event()
        if threading.current_thread() is not threading.main_thread():
            return flag, lambda: None
        previous = signal.getsignal(signal.SIGTERM)

        def handler(signum, frame):
            log.warning("SIGTERM: will checkpoint and stop after this step")
            flag.set()

        signal.signal(signal.SIGTERM, handler)
        return flag, lambda: signal.signal(signal.SIGTERM, previous)

    # -- resilience plumbing ------------------------------------------------

    def _guarded_step(self):
        """(guard, wrapped_step) for the CURRENT ``self.train_step`` — cached
        so repeated ``train()`` calls reuse the compiled wrapper, but rebuilt
        if the step function was swapped (tests monkeypatch it)."""
        from zero_transformer_tpu.resilience.anomaly import AnomalyGuard

        cache = self._guard_cache
        if cache is None or cache[0] is not self.train_step:
            guard = AnomalyGuard(
                self.cfg.resilience, self.mesh, self.plan, self.batch_sharding
            )
            self._guard_cache = (
                self.train_step, guard, guard.wrap(self.train_step)
            )
        return self._guard_cache[1], self._guard_cache[2]

    def _hang_force_save(self):
        """Watchdog ``on_hang`` hook: best-effort checkpoint of the last
        COMPLETED step's state, from the watchdog thread, so the supervisor's
        restart resumes at the hang point instead of the last periodic save.
        (With a host-side hang the device state is intact; with a wedged
        device this save itself may hang — it runs after the stack dump, and
        the abort does not depend on it.)"""
        live = getattr(self, "_live", None)
        if live is None:
            return
        step, state = live
        try:
            self.ckpt.save(step, state, meta=self._save_meta(), force=True)
            self.ckpt.wait()
            log.warning("watchdog: force-saved checkpoint at step %d", step)
        except Exception:
            log.exception("watchdog: force-save failed (restart will use the "
                          "last periodic checkpoint)")

    def _data_fault_payload(self) -> Dict[str, float]:
        """Loader fault counters (skipped shards/members, retries) for the
        metrics stream — a pod run must SHOW the data it silently skipped."""
        counters = getattr(self.train_loader, "fault_counters", None)
        if counters is None:
            return {}
        return {f"data_{k}": float(v) for k, v in counters().items() if v}

    # graftlint: hot-path
    def train(self, max_steps: Optional[int] = None) -> TrainState:
        cfg = self.cfg.training
        res = self.cfg.resilience
        state = self.state if self.state is not None else self.init_state()
        # graftlint: allow[host-sync-in-hot-path] reason=once at run start before the loop, not per step — the resume step must be known to size the loop
        start = int(state.step)
        end = min(cfg.total_steps, start + max_steps) if max_steps else cfg.total_steps
        timer = monitoring.StepTimer()
        it = iter(self.train_loader)
        n_chips = max(jax.device_count(), 1)
        tokens_per_step = cfg.batch_size * cfg.train_context * max(
            cfg.gradient_accumulation_steps, 1
        )
        preempted, restore_handler = self._install_preemption_handler()
        profile_dir = cfg.profile_dir or f"{self.cfg.checkpoint.directory}/profile"
        # trace window: start_trace fires at loop top when the COMPLETED
        # step counter equals profile_trigger, so the traced steps are
        # [trigger+1, trigger+profile_steps]. The legacy default
        # (profile_start=0) keeps its historical trigger of start+1
        # (skip the compile step); --profile-window START:LEN pins the
        # absolute window [START, START+LEN) -> trigger START-1
        # (obs/profiling.py parses the flag)
        profile_trigger = (
            cfg.profile_start - 1 if cfg.profile_start else start + 1
        )
        profile_stop = (
            profile_trigger + cfg.profile_steps if cfg.profile_steps else None
        )
        if profile_stop and profile_trigger < start:
            log.warning(
                "profiler: window [%d, %d) is already behind resume step %d; "
                "no capture this run", cfg.profile_start,
                cfg.profile_start + cfg.profile_steps, start,
            )
        profiling = False
        tr = self.tracer

        # anomaly guard: in-graph detect-and-drop with a device-resident
        # carry; the host reads it only at log points (no per-step sync)
        guard = carry = None
        step_fn = self.train_step
        if res.anomaly_detection:
            guard, step_fn = self._guarded_step()
            carry = guard.init_carry()
        anom_seen = 0
        audit_seen = 0
        rollbacks = 0
        snapshot = None
        last_snap_step = start
        if guard is not None and res.anomaly_response == "rollback":
            from zero_transformer_tpu.resilience.anomaly import HostSnapshot

            snapshot = HostSnapshot()
            snapshot.capture(state)  # rollback target exists from step one
        watchdog = None
        if res.watchdog_timeout_s > 0:
            from zero_transformer_tpu.resilience.watchdog import Watchdog

            # armed AFTER the first step completes: step one legitimately
            # blocks for the whole XLA compile, which would need its own
            # (huge) deadline — the heartbeat contract is for steady state
            watchdog = Watchdog(
                res.watchdog_timeout_s, on_hang=self._hang_force_save
            )
        self.preempted = False
        self.last_step = start
        self.resilience_report = {"anomalies": 0, "rollbacks": 0,
                                  "watchdog_fired": False,
                                  "replica_audit_failures": 0}
        if self._restore_report is not None:
            # a verified resume's quarantine/fallback work is part of this
            # run's resilience story — surface it alongside the counters
            self.resilience_report["ckpt_quarantined"] = len(
                self._restore_report.quarantined
            )
            self.resilience_report["restore_fallback_steps"] = (
                self._restore_report.fallback_steps
            )

        step = start
        tick_step = start  # step at which the timing window last restarted
        try:
            while step < end:
                if profile_stop and not profiling and step == profile_trigger:
                    jax.profiler.start_trace(profile_dir)
                    profiling = True
                    log.info("profiler: tracing %d steps to %s", cfg.profile_steps, profile_dir)
                t_fetch = tr.clock()
                local = next(it)
                batch = device_put_batch(local, self.batch_sharding)
                t_disp = tr.clock()
                if tr.enabled:
                    tr.add("data_fetch", "train", t_fetch, t_disp,
                           {"step": step + 1})
                # observe only the axes that can vary mid-run (batch
                # geometry, rng layout, guard carry) — state shapes are
                # fixed at build time and threaded through step_fn, and
                # describing the whole param tree would cost O(params)
                # per step for no added detection
                if guard is not None:
                    self.dispatch_site.observe(batch, self.rng, carry)
                    state, metrics, carry = step_fn(state, batch, self.rng, carry)
                else:
                    self.dispatch_site.observe(batch, self.rng)
                    state, metrics = step_fn(state, batch, self.rng)
                if tr.enabled:
                    # dispatch, not compute: jax returns futures — the
                    # device milliseconds show up in device_sync at the
                    # next log point (and in a --profile-window capture)
                    tr.add("dispatch", "train", t_disp, tr.clock(),
                           {"step": step + 1})
                step += 1
                self.last_step = step
                self._live = (step, state)
                if watchdog is not None:
                    if step == start + 1:
                        watchdog.start()
                    watchdog.beat()
                if profiling and step >= profile_stop:
                    # graftlint: allow[host-sync-in-hot-path] reason=profile-window close only — the trace must not stop before the steps it captured finish on device; never reached in steady state
                    jax.block_until_ready(metrics["loss"])
                    jax.profiler.stop_trace()
                    profiling = False

                paused = False
                if step % cfg.log_frequency == 0 or step == end:
                    t_sync = tr.clock()
                    # graftlint: allow[host-sync-in-hot-path] reason=THE designed log-point sync (every log_frequency steps, not per step) — the device_sync span right below measures exactly this wait
                    loss = float(metrics["loss"])  # device sync point
                    if tr.enabled:
                        # host-blocked time waiting on the device: the gap
                        # between dispatch rate and compute rate
                        tr.add("device_sync", "train", t_sync, tr.clock(),
                               {"step": step})
                    if (
                        cfg.halt_on_nan
                        and not jnp.isfinite(loss)
                        and (guard is None or res.anomaly_response == "halt")
                    ):
                        # Without the guard this state is post-divergence (the
                        # NaN update already landed) — deliberately NOT saved,
                        # or it would bury the last GOOD checkpoint. With the
                        # guard the update was dropped in-graph, but 'halt'
                        # still means halt: surface it, don't train through.
                        good = self.ckpt.latest_step()
                        poisoned = (
                            "update was dropped in-graph (params still clean)"
                            if guard is not None
                            else "NOT checkpointed (state is already poisoned)"
                        )
                        self.flight.dump(
                            "anomaly_halt",
                            extra={"step": step, "loss": repr(loss),
                                   "cause": "halt_on_nan"},
                        )
                        raise RuntimeError(
                            f"non-finite loss {loss} at step {step}; {poisoned} "
                            f"— resume from step {good} and rerun with "
                            f"--debug-nans to find the source op"
                        )
                    dt = timer.tick()
                    payload = {
                        "loss": loss,
                        "perplexity": float(jnp.exp(jnp.minimum(jnp.float32(loss), 20.0))),
                        # graftlint: allow[host-sync-in-hot-path] reason=rides the log-point sync paid by loss above — the step's metrics materialized together; no extra device wait
                        "grad_norm": float(metrics["grad_norm"]),
                        "learning_rate": float(metrics.get("learning_rate", 0.0)),
                        "tokens_seen": float(step) * tokens_per_step,
                        "seq_len": cfg.train_context,
                    }
                    if dt and step > tick_step:
                        per_step = dt / (step - tick_step)
                        tok_s = tokens_per_step / per_step
                        payload["tokens_per_sec"] = tok_s
                        payload["step_time_s"] = per_step
                        util = monitoring.mfu(tok_s / n_chips, self.flops_per_token)
                        if util is not None:
                            payload["mfu"] = util
                        # step-time decomposition (PR 8): analytic bubble +
                        # bench-measured exposed comm, as metric keys and as
                        # estimate spans subdividing this logging window —
                        # the same fractions the train_bubble_frac /
                        # train_exposed_comm_frac gauges export on /metrics
                        if self._bubble_frac > 0:
                            payload["bubble_frac"] = self._bubble_frac
                        if self._exposed_comm_frac is not None:
                            payload["exposed_comm_frac"] = (
                                self._exposed_comm_frac
                            )
                        if tr.enabled:
                            comm = self._exposed_comm_frac or 0.0
                            bub = self._bubble_frac
                            t_phase = tr.clock() - dt
                            for name, frac in (
                                ("grads_compute", max(0.0, 1.0 - comm - bub)),
                                ("comm_exposed", comm),
                                ("bubble_wait", bub),
                            ):
                                if frac > 0:
                                    tr.add(
                                        name, "train", t_phase,
                                        t_phase + dt * frac,
                                        {"step": step, "estimate": True},
                                    )
                                    t_phase += dt * frac
                    hbm = monitoring.hbm_device_stats()
                    if hbm is not None:
                        # max across local devices (the OOM-relevant number;
                        # the old device-0-only read hid a skewed shard),
                        # mean alongside once there is more than one device
                        payload["hbm_gb"] = hbm["max_gb"]
                        if len(hbm["per_device_gb"]) > 1:
                            payload["hbm_gb_mean"] = hbm["mean_gb"]
                    payload.update(self._data_fault_payload())
                    if self.ckpt.last_digest_ms:
                        # digest time of the most recent manifest-carrying
                        # save tick (the <5% overhead budget, observable)
                        payload["ckpt_verify_ms"] = self.ckpt.last_digest_ms
                    if self._restore_report is not None and (
                        self._restore_report.quarantined
                    ):
                        payload["ckpt_quarantined"] = len(
                            self._restore_report.quarantined
                        )
                        payload["restore_fallback_steps"] = (
                            self._restore_report.fallback_steps
                        )
                    if guard is not None:
                        stats = guard.read(carry)  # host sync — log points only
                        new_anoms = stats.count - anom_seen
                        if new_anoms > 0:
                            # run-level total survives carry resets (rollback)
                            self.resilience_report["anomalies"] += new_anoms
                        if self.resilience_report["anomalies"]:
                            payload["anomalies_total"] = (
                                self.resilience_report["anomalies"]
                            )
                            payload["anomaly_streak"] = stats.streak
                        new_audit = stats.audit_failures - audit_seen
                        if new_audit > 0:
                            self.resilience_report["replica_audit_failures"] += (
                                new_audit
                            )
                        if self.resilience_report["replica_audit_failures"]:
                            payload["replica_audit_failures"] = (
                                self.resilience_report["replica_audit_failures"]
                            )
                    self.metrics.log(payload, step, prefix="train")
                    # flight ring + incremental span log, at log points only
                    # (the hot loop appends fixed records; IO lands here)
                    self.flight.tick({
                        "step": step, "loss": loss,
                        "grad_norm": payload["grad_norm"],
                        "anomalies": self.resilience_report["anomalies"],
                        "rollbacks": rollbacks,
                        "audit_failures": self.resilience_report[
                            "replica_audit_failures"
                        ],
                    })
                    if self.flight.directory:
                        tr.write_jsonl(
                            f"{self.flight.directory}/spans.jsonl"
                        )
                    tick_step = step
                    if guard is not None:
                        t_audit = tr.clock()
                        state, carry, rolled = self._handle_replica_divergence(
                            new_audit, state, carry, guard, snapshot,
                            rollbacks, step,
                        )
                        if rolled:
                            # audit rollback reset the carry; both counters
                            # restart from zero at the next read
                            anom_seen = 0
                            audit_seen = 0
                        else:
                            audit_seen = stats.audit_failures
                            state, carry, rolled = self._handle_anomalies(
                                stats, new_anoms, state, carry, guard, snapshot,
                                rollbacks, step,
                            )
                            anom_seen = 0 if rolled else stats.count
                            if rolled:
                                audit_seen = 0
                        if rolled:
                            rollbacks += 1
                            self.resilience_report["rollbacks"] = rollbacks
                            paused = True  # exclude rollback time from timing
                        # mirror a known-good state to host RAM on schedule.
                        # With the replica audit active, "known-good" also
                        # requires a CLEAN audit to have run since the last
                        # capture: otherwise a desync that happened between
                        # audits could be captured and later re-replicated
                        # by the "heal" rollback, baking the corruption into
                        # every replica. (Residual window: corruption in the
                        # <= audit_frequency steps since the last clean
                        # audit can still slip in — the audit bounds it.)
                        audit_vouched = (
                            getattr(guard, "_audit", None) is None
                            or (
                                new_audit == 0
                                and step // res.audit_frequency
                                > last_snap_step // res.audit_frequency
                            )
                        )
                        if (
                            snapshot is not None
                            and stats.streak == 0
                            and not rolled
                            and audit_vouched
                            and step - last_snap_step >= res.snapshot_frequency
                        ):
                            snapshot.capture(state)
                            last_snap_step = step
                        if tr.enabled:
                            # guard-carry read + divergence/anomaly
                            # escalation + snapshot refresh, as one phase
                            tr.add("replica_audit", "train", t_audit,
                                   tr.clock(), {"step": step,
                                                "rolled": rolled})

                if cfg.evaluation_frequency and step % cfg.evaluation_frequency == 0:
                    with tr.span("evaluate", "train", step=step):
                        self.metrics.log(
                            self.evaluate(state), step, prefix="validation"
                        )
                    paused = True

                t_save = tr.clock()
                if self.ckpt.save(step, state, meta=self._save_meta()):
                    if tr.enabled:
                        tr.add("checkpoint_save", "train", t_save, tr.clock(),
                               {"step": step})
                    paused = True
                if paused:
                    # exclude eval/checkpoint wall time from the throughput window
                    timer.tick()
                    tick_step = step

                if self._chaos is not None:
                    self._chaos.on_step(step)
                    # replica_perturb chaos: desync one DP replica's copy of
                    # the (logically replicated) state — the SDC the audit
                    # exists to catch. No-op without such a fault.
                    state = self._chaos.perturb_state(step, state)
                if preempted.is_set():
                    log.warning("preemption: saving at step %d and stopping", step)
                    self.metrics.event("preemption", step)
                    self.preempted = True
                    break
        except KeyboardInterrupt:
            if watchdog is not None and watchdog.fired:
                from zero_transformer_tpu.resilience import HangError

                self.resilience_report["watchdog_fired"] = True
                self.metrics.event(
                    "watchdog_abort", step, timeout_s=res.watchdog_timeout_s
                )
                self.flight.dump(
                    "watchdog_abort",
                    extra={"step": step,
                           "timeout_s": res.watchdog_timeout_s},
                )
                raise HangError(
                    f"train loop produced no step for more than "
                    f"{res.watchdog_timeout_s}s (hung around step {step}); "
                    f"stacks dumped, checkpoint force-saved — restartable"
                ) from None
            raise
        finally:
            if profiling:
                jax.profiler.stop_trace()
            if watchdog is not None:
                watchdog.stop()
            restore_handler()
        # drain any in-flight async save BEFORE the latest_step comparison:
        # latest_step() now checks ON-DISK commit markers, and a step whose
        # background commit hasn't landed yet would read as absent — the
        # redundant force-save would then raise StepAlreadyExistsError
        self.ckpt.wait()
        if self.ckpt.latest_step() != step:
            self.ckpt.save(step, state, meta=self._save_meta(), force=True)
        self.ckpt.wait()
        self.state = state
        return state

    def _rollback_to_snapshot(self, state, guard, snapshot):
        """Restore params/opt-state from the host-RAM snapshot, KEEPING the
        current step counter (the loader and LR schedule move forward — the
        offending window is never replayed), with a fresh guard carry. The
        snapshot's ``restore()`` routes through ``ensure_donatable`` (the
        re-placed buffers enter the donating train step) and its
        ``device_put`` re-replicates ONE host copy onto every device —
        which is also what makes rollback heal a replica desync."""
        restored = snapshot.restore()
        state = TrainState(
            step=state.step,
            params=restored.params,
            opt_state=restored.opt_state,
        )
        return state, guard.init_carry()

    def _handle_replica_divergence(
        self, new, state, carry, guard, snapshot, rollbacks, step
    ):
        """Escalation when the cross-replica audit tripped since the last
        log point. A desynced replica cannot be skipped past (every
        subsequent step forks further) — the options are HEAL by re-placing
        identical copies from the host snapshot (``anomaly_response:
        rollback``; a ``device_put`` from one host buffer re-replicates
        bit-identical state on every device) or HALT so the operator swaps
        the suspect host. Returns (state, carry, did_rollback)."""
        if new <= 0:
            return state, carry, False
        res = self.cfg.resilience
        good = self.ckpt.latest_step()
        log.error(
            "replica audit: %d failed agreement check(s) by step %d — one "
            "DP replica's state no longer matches its peers (silent data "
            "corruption)", new, step,
        )
        self.metrics.event(
            "replica_divergence", step, new_failures=new,
            total=self.resilience_report["replica_audit_failures"],
        )
        from zero_transformer_tpu.resilience import AnomalyHalt

        if (
            res.anomaly_response == "rollback"
            and snapshot is not None
            and snapshot.captured
            and rollbacks < res.max_rollbacks
        ):
            state, carry = self._rollback_to_snapshot(state, guard, snapshot)
            log.warning(
                "replica divergence HEALED by rollback %d/%d: host snapshot "
                "of step %d re-replicated identical copies at step %d",
                rollbacks + 1, res.max_rollbacks, snapshot.step, step,
            )
            self.metrics.event(
                "replica_heal_rollback", step,
                to_step=snapshot.step, rollback=rollbacks + 1,
            )
            return state, carry, True
        self.flight.dump(
            "anomaly_halt",
            extra={"step": step, "cause": "replica_divergence",
                   "new_failures": new},
        )
        raise AnomalyHalt(
            f"cross-replica divergence at step {step} (audited every "
            f"{res.audit_frequency} steps): a DP replica's replicated state "
            f"differs bit-for-bit from its peers — silent data corruption "
            f"on one host/device. Resume from step {good} (restore "
            f"re-replicates identical copies); if it recurs, rotate out the "
            f"suspect host"
        )

    def _handle_anomalies(
        self, stats, new, state, carry, guard, snapshot, rollbacks, step
    ):
        """Host-side escalation from the guard carry, at a log point.

        The in-graph guard already DROPPED every flagged update (skip_batch
        is the floor, not a choice); what remains is whether to keep going,
        roll back, or stop. Returns (state, carry, did_rollback)."""
        res = self.cfg.resilience
        if new <= 0:
            return state, carry, False
        good = self.ckpt.latest_step()
        log.warning(
            "anomaly guard: %d flagged step(s) since last check "
            "(streak %d, total %d) — updates dropped in-graph",
            new, stats.streak, stats.count,
        )
        from zero_transformer_tpu.resilience import AnomalyHalt

        if res.anomaly_response == "halt":
            self.flight.dump(
                "anomaly_halt",
                extra={"step": step, "cause": "policy_halt", "new": new,
                       "streak": stats.streak},
            )
            raise AnomalyHalt(
                f"anomaly policy 'halt': {new} flagged step(s) by step {step} "
                f"(non-finite loss/grad or spike; streak {stats.streak}). "
                f"Updates were dropped in-graph; resume from step {good} "
                f"after inspecting the data window / lowering the LR"
            )
        if (
            res.anomaly_response == "rollback"
            and stats.streak >= res.rollback_after
            and snapshot is not None
            and snapshot.captured
        ):
            if rollbacks >= res.max_rollbacks:
                self.flight.dump(
                    "anomaly_halt",
                    extra={"step": step, "cause": "rollback_budget",
                           "streak": stats.streak},
                )
                raise AnomalyHalt(
                    f"rollback budget exhausted ({res.max_rollbacks}) with the "
                    f"anomaly streak still at {stats.streak} at step {step} — "
                    f"this divergence is persistent; resume from step {good} "
                    f"with a changed config"
                )
            state, carry = self._rollback_to_snapshot(state, guard, snapshot)
            log.warning(
                "anomaly rollback %d/%d: restored host snapshot of step %d "
                "at step %d (loader continues forward)",
                rollbacks + 1, res.max_rollbacks, snapshot.step, step,
            )
            self.metrics.event(
                "anomaly_rollback", step,
                to_step=snapshot.step, streak=stats.streak,
                rollback=rollbacks + 1,
            )
            return state, carry, True
        if stats.streak >= res.max_consecutive_anomalies:
            self.flight.dump(
                "anomaly_halt",
                extra={"step": step, "cause": "consecutive_anomalies",
                       "streak": stats.streak},
            )
            raise AnomalyHalt(
                f"{stats.streak} consecutive anomalous steps at step {step}: "
                f"every update is being dropped — no training progress is "
                f"possible; resume from step {good} with a changed config"
            )
        return state, carry, False

    def close(self) -> None:
        if self.flight.directory:
            # Perfetto trace + remaining spans beside metrics.jsonl — the
            # per-phase step timeline survives the process
            try:
                self.tracer.write_chrome_trace(
                    f"{self.flight.directory}/trace_train.json"
                )
                self.tracer.write_jsonl(f"{self.flight.directory}/spans.jsonl")
            except Exception:
                log.exception("obs: trace export failed (run results intact)")
        self.ckpt.close()
        self.metrics.close()
