"""Optimizer + LR schedule factory.

Reference equivalent: ``main_zero.py:142-173`` (AdamW chain with clip-by-global
-norm and a weight-decay mask) and ``:207-213`` (warmup-cosine schedule with a
hardcoded decay horizon). Here every knob is config, and the weight-decay mask
is *path-based* (decay kernels/embeddings, skip norm scales and positional
embeddings) instead of ndim-based — the reference's ``ndim != 1`` test
(``main_zero.py:155-158``) breaks under scan-stacked layers where norm scales
are [n_layers, d].
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import flax.traverse_util as traverse_util
import jax
import jax.numpy as jnp
import optax

from zero_transformer_tpu.config import OptimizerConfig

# optax renamed safe_int32_increment -> safe_increment; accept either so the
# pinned-older-optax images keep working
_safe_increment = getattr(optax, "safe_increment", None) or optax.safe_int32_increment


def make_schedule(cfg: OptimizerConfig) -> optax.Schedule:
    if cfg.schedule == "constant":
        return optax.constant_schedule(cfg.peak_learning_rate)
    decay_steps = cfg.decay_steps if cfg.decay_steps is not None else (
        cfg.total_steps - cfg.warmup_steps
    )
    if cfg.schedule == "warmup_linear":
        return optax.join_schedules(
            [
                optax.linear_schedule(0.0, cfg.peak_learning_rate, cfg.warmup_steps),
                optax.linear_schedule(cfg.peak_learning_rate, cfg.end_learning_rate, decay_steps),
            ],
            [cfg.warmup_steps],
        )
    if cfg.schedule == "warmup_cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=cfg.peak_learning_rate,
            warmup_steps=cfg.warmup_steps,
            # reference hardcodes decay_steps=143000 (main_zero.py:211)
            decay_steps=cfg.warmup_steps + decay_steps,
            end_value=cfg.end_learning_rate,
        )
    raise ValueError(f"unknown schedule {cfg.schedule!r}")


def weight_decay_mask(params: Any) -> Any:
    """True (decay) for kernels and the token embedding; False for norm scales,
    biases, and positional embeddings."""
    flat = traverse_util.flatten_dict(params, sep="/")

    def decay(path: str) -> bool:
        if "wpe" in path:
            return False
        leaf = path.rsplit("/", 1)[-1]
        return leaf in ("kernel", "embedding")

    return traverse_util.unflatten_dict(
        {tuple(k.split("/")): decay(k) for k in flat}, sep=None
    )


def _lr_coupled_decay(
    schedule, weight_decay: float
) -> optax.GradientTransformation:
    """AdamW-style decoupled weight decay (update -= lr·wd·p) appended AFTER
    an optimizer whose own update doesn't include it. Needed for adafactor:
    optax applies ``weight_decay_rate`` un-scaled by the learning rate, so a
    0.1 AdamW-style value would shrink params 10% per step and collapse
    training."""

    def init(params):
        del params
        return optax.ScaleByScheduleState(count=jnp.zeros((), jnp.int32))

    def update(updates, state, params):
        if params is None:
            raise ValueError("weight decay needs params")
        lr = schedule(state.count)
        mask = weight_decay_mask(params)
        updates = jax.tree.map(
            lambda u, p, m: u - lr * weight_decay * p if m else u,
            updates,
            params,
            mask,
        )
        return updates, optax.ScaleByScheduleState(count=state.count + 1)

    return optax.GradientTransformation(init, update)


def _clip_by_norm_fn(max_norm: float, norm_fn: Callable) -> optax.GradientTransformation:
    """``optax.clip_by_global_norm`` with a pluggable norm — needed inside a
    shard_map region, where ``optax.global_norm`` would see only this device's
    gradient SHARDS (the true norm needs a psum across the ZeRO axis). Same
    ``EmptyState`` as optax's clip, so the optimizer-state pytree structure —
    and therefore checkpoints — are identical between the GSPMD and
    explicit-collective train steps."""

    def init(params):
        del params
        return optax.EmptyState()

    def update(updates, state, params=None):
        del params
        norm = norm_fn(updates)
        # optax semantics: scale by max_norm/norm only when norm exceeds it
        scale = jnp.minimum(1.0, max_norm / (norm + 1e-16))
        return jax.tree.map(lambda u: u * scale, updates), state

    return optax.GradientTransformation(init, update)


def _factored_dims(shape: tuple, min_dim_size_to_factor: int = 128):
    """The two largest axes when factoring applies, else None — byte-for-byte
    the rule optax's ``scale_by_factored_rms`` uses (``_src/factorized.py``),
    applied here to the FULL (unsharded) shape so shard boundaries can never
    flip the factoring decision."""
    import numpy as np

    if len(shape) < 2:
        return None
    order = np.argsort(shape)
    if shape[order[-2]] < min_dim_size_to_factor:
        return None
    return int(order[-2]), int(order[-1])


def _sharded_factored_rms(
    zc,
    decay_rate: float = 0.8,
    min_dim_size_to_factor: int = 128,
    epsilon: float = 1e-30,
) -> optax.GradientTransformation:
    """``optax.scale_by_factored_rms`` re-derived for gradient SHARDS inside
    the explicit ZeRO shard_map core (round-4 VERDICT weak #6: adafactor x
    ZeRO>=2 was rejected outright, blocking factored-stats training at the
    very scale that needs both).

    The factored statistics are stored FULL-SIZE and replicated — optax's
    exact ``FactoredState`` structure, so plans/checkpoints are identical to
    the unsharded path — because they are the tiny O(d+f) part; what's
    sharded is the WORK: each device reduces g^2 over its own gradient shard
    and the cross-shard halves of the means ride one psum (reduction over
    the scattered dim) or one small all-gather (reduction over another dim)
    on the ZeRO axis. The per-shard update then slices the replicated
    row/col factors back down, so no full-size gradient tensor ever
    materializes (the non-factored fallback all-gathers g^2, but factoring
    covers every >=128x128 kernel — the fallback leaves are norm-scale
    sized). Math matches ``optax.scale_by_factored_rms`` exactly up to
    reduction order.
    """
    from optax import FactoredState

    def init(params):  # mirror optax's init (runs on FULL params)
        def one(p):
            dims = _factored_dims(tuple(p.shape), min_dim_size_to_factor)
            if dims is not None:
                d1, d0 = dims
                vr = [s for i, s in enumerate(p.shape) if i != d0]
                vc = [s for i, s in enumerate(p.shape) if i != d1]
                return (
                    jnp.zeros(vr, p.dtype), jnp.zeros(vc, p.dtype),
                    jnp.zeros((1,), p.dtype),
                )
            return (
                jnp.zeros((1,), p.dtype), jnp.zeros((1,), p.dtype),
                jnp.zeros(p.shape, p.dtype),
            )

        trees = jax.tree.map(one, params)
        return FactoredState(
            count=jnp.zeros([], jnp.int32),
            v_row=jax.tree.map(lambda t: t[0], trees, is_leaf=lambda x: isinstance(x, tuple)),
            v_col=jax.tree.map(lambda t: t[1], trees, is_leaf=lambda x: isinstance(x, tuple)),
            v=jax.tree.map(lambda t: t[2], trees, is_leaf=lambda x: isinstance(x, tuple)),
        )

    def update(grads, state, params):
        if params is None:
            raise ValueError("sharded adafactor needs params")
        t = jnp.asarray(state.count + 1, jnp.float32)
        decay_t = 1.0 - t ** (-decay_rate)

        def shard_slice(f, sdim, local):
            """Slice a replicated factor down to this device's shard along
            ``sdim`` (no-op when the factor broadcasts there)."""
            if sdim < 0 or f.shape[sdim] == 1 or f.shape[sdim] == local:
                return f
            n = f.shape[sdim] // zc.zsize
            return jax.lax.dynamic_slice_in_dim(
                f, zc.dev_index() * n, n, axis=sdim
            )

        def full_mean(x, axis_, sdim, full_axis_size):
            """mean over ``axis_`` of the FULL tensor, from its shard."""
            if axis_ == sdim:  # reducing across the scatter dim: psum of sums
                return jax.lax.psum(jnp.sum(x, axis=axis_), zc.axis) / full_axis_size
            m = jnp.mean(x, axis=axis_)
            if sdim >= 0:  # result still sliced along the (shifted) scatter dim
                adj = sdim - 1 if sdim > axis_ else sdim
                m = jax.lax.all_gather(m, zc.axis, axis=adj, tiled=True)
            return m

        def one(g, v_row, v_col, v, p, sdim):
            dtype = p.dtype
            full_shape = list(g.shape)
            if sdim >= 0:
                full_shape[sdim] *= zc.zsize
            dims = _factored_dims(tuple(full_shape), min_dim_size_to_factor)
            gsq = (g.conj() * g).real + epsilon
            if dims is not None:
                d1, d0 = dims
                new_v_row = (
                    decay_t * v_row
                    + (1.0 - decay_t) * full_mean(gsq, d0, sdim, full_shape[d0])
                ).astype(dtype)
                new_v_col = (
                    decay_t * v_col
                    + (1.0 - decay_t) * full_mean(gsq, d1, sdim, full_shape[d1])
                ).astype(dtype)
                reduced_d1 = d1 - 1 if d1 > d0 else d1
                row_col_mean = jnp.mean(new_v_row, axis=reduced_d1, keepdims=True)
                row_factor = (new_v_row / row_col_mean) ** -0.5
                col_factor = new_v_col ** -0.5
                u = (
                    g
                    * shard_slice(jnp.expand_dims(row_factor, d0), sdim, g.shape[sdim] if sdim >= 0 else -1)
                    * shard_slice(jnp.expand_dims(col_factor, d1), sdim, g.shape[sdim] if sdim >= 0 else -1)
                )
                return u, new_v_row, new_v_col, v
            # Non-factored leaf. The v STORAGE layout follows
            # opt_state_sharding's structural matching, all-or-nothing per
            # state tree: when NO param in the tree factors, FactoredState.v
            # is exactly param-shaped, matches the param treedef+shapes, and
            # is ZeRO-SCATTERED like the params — the elementwise update
            # then runs straight on the shards, no collective at all. When
            # >=1 param factors, the (1,)-marker leaves break the match and
            # the whole v tree is REPLICATED full-size — update the full
            # buffer from an all-gathered g^2 (these leaves are norm-scale
            # sized). v.shape distinguishes the two (shard != full whenever
            # the leaf is actually scattered).
            if sdim < 0 or v.shape == g.shape:  # full-vs-full or shard-vs-shard
                new_v = (decay_t * v + (1.0 - decay_t) * gsq).astype(dtype)
                return g * new_v ** -0.5, v_row, v_col, new_v
            gsq = jax.lax.all_gather(gsq, zc.axis, axis=sdim, tiled=True)
            new_v = (decay_t * v + (1.0 - decay_t) * gsq).astype(dtype)
            u = g * shard_slice(new_v, sdim, g.shape[sdim]) ** -0.5
            return u, v_row, v_col, new_v

        out = jax.tree.map(
            one, grads, state.v_row, state.v_col, state.v, params, zc.sdims
        )
        pick = lambda i: jax.tree.map(  # noqa: E731
            lambda t: t[i], out, is_leaf=lambda x: isinstance(x, tuple)
        )
        new_state = FactoredState(
            count=_safe_increment(state.count),
            v_row=pick(1), v_col=pick(2), v=pick(3),
        )
        return pick(0), new_state

    return optax.GradientTransformation(init, update)


def _sharded_param_block_rms(zc, min_scale: float = 1e-3) -> optax.GradientTransformation:
    """``optax.scale_by_param_block_rms`` over param SHARDS: the per-leaf RMS
    needs the cross-shard sum of squares (one scalar psum per leaf)."""

    def update(updates, state, params):
        if params is None:
            raise ValueError("param block rms needs params")

        def one(u, p, sdim):
            sq = jnp.sum((p.conj() * p).real)
            n = p.size
            if sdim >= 0:
                sq = jax.lax.psum(sq, zc.axis)
                n = n * zc.zsize
            return u * jnp.maximum(jnp.sqrt(sq / n), min_scale)

        return jax.tree.map(one, updates, params, zc.sdims), state

    return optax.GradientTransformation(lambda params: optax.EmptyState(), update)


def make_optimizer(
    cfg: OptimizerConfig,
    schedule=None,
    global_norm_fn: Optional[Callable] = None,
    zero_collectives=None,
) -> optax.GradientTransformation:
    """Optimizer chain: clip → {adamw | adafactor | lion}.

    ``global_norm_fn`` swaps the grad-clip norm computation (used by the
    explicit-collective ZeRO step, which runs the update on gradient
    shards); state structure is unchanged either way. Adafactor keeps
    factored second moments (O(d+f) per [d,f] kernel instead of O(d·f)) —
    the classic TPU choice when even ZeRO-sharded Adam moments don't fit;
    lion keeps a single momentum buffer.

    ``zero_collectives`` (a ``zero.ZeroCollectives``) makes adafactor
    compose with the explicit ZeRO-2/3 shard_map core: the factored-rms and
    param-scale transforms are swapped for shard-aware versions whose
    cross-shard reductions ride the ZeRO axis, with the SAME state
    structure as the plain chain (plans and checkpoints are
    interchangeable). Without it, plain adafactor on sharded gradients
    would shape-error at trace time — the pre-round-5 reason the Trainer
    rejected adafactor at stage >= 2.
    """
    schedule = schedule or make_schedule(cfg)
    clip = (
        _clip_by_norm_fn(cfg.grad_clip, global_norm_fn)
        if global_norm_fn is not None
        else optax.clip_by_global_norm(cfg.grad_clip)
    )
    if cfg.optimizer == "adafactor":
        if zero_collectives is not None:
            inner = optax.chain(
                # mirrors optax.adafactor's internal chain (clipping off,
                # momentum off) member-for-member so the state structure —
                # and therefore checkpoints — match the unsharded path
                _sharded_factored_rms(zero_collectives),
                optax.scale_by_learning_rate(schedule, flip_sign=False),
                _sharded_param_block_rms(zero_collectives),
                optax.scale(-1),
            )
        else:
            inner = optax.adafactor(
                learning_rate=schedule,
                # external clip + schedule: disable adafactor's own update
                # clipping so cfg.grad_clip is the single clipping knob
                clipping_threshold=None,
            )
        return optax.chain(
            clip,
            inner,
            # decay OUTSIDE adafactor: optax's weight_decay_rate is applied
            # un-scaled by lr (p -= wd*p per step would collapse training
            # at AdamW-style wd=0.1)
            _lr_coupled_decay(schedule, cfg.weight_decay),
        )
    if cfg.optimizer == "lion":
        inner = optax.lion(
            learning_rate=schedule,
            b1=cfg.b1,
            b2=cfg.b2,
            weight_decay=cfg.weight_decay,
            mask=weight_decay_mask,
        )
    else:
        inner = optax.adamw(
            learning_rate=schedule,
            b1=cfg.b1,
            b2=cfg.b2,
            eps=cfg.eps,
            weight_decay=cfg.weight_decay,
            mask=weight_decay_mask,
        )
    return optax.chain(clip, inner)
