"""Optimizer + LR schedule factory.

Reference equivalent: ``main_zero.py:142-173`` (AdamW chain with clip-by-global
-norm and a weight-decay mask) and ``:207-213`` (warmup-cosine schedule with a
hardcoded decay horizon). Here every knob is config, and the weight-decay mask
is *path-based* (decay kernels/embeddings, skip norm scales and positional
embeddings) instead of ndim-based — the reference's ``ndim != 1`` test
(``main_zero.py:155-158``) breaks under scan-stacked layers where norm scales
are [n_layers, d].
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import flax.traverse_util as traverse_util
import jax
import jax.numpy as jnp
import optax

from zero_transformer_tpu.config import OptimizerConfig


def make_schedule(cfg: OptimizerConfig) -> optax.Schedule:
    if cfg.schedule == "constant":
        return optax.constant_schedule(cfg.peak_learning_rate)
    decay_steps = cfg.decay_steps if cfg.decay_steps is not None else (
        cfg.total_steps - cfg.warmup_steps
    )
    if cfg.schedule == "warmup_linear":
        return optax.join_schedules(
            [
                optax.linear_schedule(0.0, cfg.peak_learning_rate, cfg.warmup_steps),
                optax.linear_schedule(cfg.peak_learning_rate, cfg.end_learning_rate, decay_steps),
            ],
            [cfg.warmup_steps],
        )
    if cfg.schedule == "warmup_cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=cfg.peak_learning_rate,
            warmup_steps=cfg.warmup_steps,
            # reference hardcodes decay_steps=143000 (main_zero.py:211)
            decay_steps=cfg.warmup_steps + decay_steps,
            end_value=cfg.end_learning_rate,
        )
    raise ValueError(f"unknown schedule {cfg.schedule!r}")


def weight_decay_mask(params: Any) -> Any:
    """True (decay) for kernels and the token embedding; False for norm scales,
    biases, and positional embeddings."""
    flat = traverse_util.flatten_dict(params, sep="/")

    def decay(path: str) -> bool:
        if "wpe" in path:
            return False
        leaf = path.rsplit("/", 1)[-1]
        return leaf in ("kernel", "embedding")

    return traverse_util.unflatten_dict(
        {tuple(k.split("/")): decay(k) for k in flat}, sep=None
    )


def _clip_by_norm_fn(max_norm: float, norm_fn: Callable) -> optax.GradientTransformation:
    """``optax.clip_by_global_norm`` with a pluggable norm — needed inside a
    shard_map region, where ``optax.global_norm`` would see only this device's
    gradient SHARDS (the true norm needs a psum across the ZeRO axis). Same
    ``EmptyState`` as optax's clip, so the optimizer-state pytree structure —
    and therefore checkpoints — are identical between the GSPMD and
    explicit-collective train steps."""

    def init(params):
        del params
        return optax.EmptyState()

    def update(updates, state, params=None):
        del params
        norm = norm_fn(updates)
        # optax semantics: scale by max_norm/norm only when norm exceeds it
        scale = jnp.minimum(1.0, max_norm / (norm + 1e-16))
        return jax.tree.map(lambda u: u * scale, updates), state

    return optax.GradientTransformation(init, update)


def make_optimizer(
    cfg: OptimizerConfig,
    schedule=None,
    global_norm_fn: Optional[Callable] = None,
) -> optax.GradientTransformation:
    """AdamW chain. ``global_norm_fn`` swaps the grad-clip norm computation
    (used by the explicit-collective ZeRO step, which runs the update on
    gradient shards); state structure is unchanged either way."""
    schedule = schedule or make_schedule(cfg)
    clip = (
        _clip_by_norm_fn(cfg.grad_clip, global_norm_fn)
        if global_norm_fn is not None
        else optax.clip_by_global_norm(cfg.grad_clip)
    )
    return optax.chain(
        clip,
        optax.adamw(
            learning_rate=schedule,
            b1=cfg.b1,
            b2=cfg.b2,
            eps=cfg.eps,
            weight_decay=cfg.weight_decay,
            mask=weight_decay_mask,
        ),
    )
