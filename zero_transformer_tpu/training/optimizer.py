"""Optimizer + LR schedule factory.

Reference equivalent: ``main_zero.py:142-173`` (AdamW chain with clip-by-global
-norm and a weight-decay mask) and ``:207-213`` (warmup-cosine schedule with a
hardcoded decay horizon). Here every knob is config, and the weight-decay mask
is *path-based* (decay kernels/embeddings, skip norm scales and positional
embeddings) instead of ndim-based — the reference's ``ndim != 1`` test
(``main_zero.py:155-158``) breaks under scan-stacked layers where norm scales
are [n_layers, d].
"""
from __future__ import annotations

from typing import Any, Callable, Optional

import flax.traverse_util as traverse_util
import jax
import jax.numpy as jnp
import optax

from zero_transformer_tpu.config import OptimizerConfig


def make_schedule(cfg: OptimizerConfig) -> optax.Schedule:
    if cfg.schedule == "constant":
        return optax.constant_schedule(cfg.peak_learning_rate)
    decay_steps = cfg.decay_steps if cfg.decay_steps is not None else (
        cfg.total_steps - cfg.warmup_steps
    )
    if cfg.schedule == "warmup_linear":
        return optax.join_schedules(
            [
                optax.linear_schedule(0.0, cfg.peak_learning_rate, cfg.warmup_steps),
                optax.linear_schedule(cfg.peak_learning_rate, cfg.end_learning_rate, decay_steps),
            ],
            [cfg.warmup_steps],
        )
    if cfg.schedule == "warmup_cosine":
        return optax.warmup_cosine_decay_schedule(
            init_value=0.0,
            peak_value=cfg.peak_learning_rate,
            warmup_steps=cfg.warmup_steps,
            # reference hardcodes decay_steps=143000 (main_zero.py:211)
            decay_steps=cfg.warmup_steps + decay_steps,
            end_value=cfg.end_learning_rate,
        )
    raise ValueError(f"unknown schedule {cfg.schedule!r}")


def weight_decay_mask(params: Any) -> Any:
    """True (decay) for kernels and the token embedding; False for norm scales,
    biases, and positional embeddings."""
    flat = traverse_util.flatten_dict(params, sep="/")

    def decay(path: str) -> bool:
        if "wpe" in path:
            return False
        leaf = path.rsplit("/", 1)[-1]
        return leaf in ("kernel", "embedding")

    return traverse_util.unflatten_dict(
        {tuple(k.split("/")): decay(k) for k in flat}, sep=None
    )


def _lr_coupled_decay(
    schedule, weight_decay: float
) -> optax.GradientTransformation:
    """AdamW-style decoupled weight decay (update -= lr·wd·p) appended AFTER
    an optimizer whose own update doesn't include it. Needed for adafactor:
    optax applies ``weight_decay_rate`` un-scaled by the learning rate, so a
    0.1 AdamW-style value would shrink params 10% per step and collapse
    training."""

    def init(params):
        del params
        return optax.ScaleByScheduleState(count=jnp.zeros((), jnp.int32))

    def update(updates, state, params):
        if params is None:
            raise ValueError("weight decay needs params")
        lr = schedule(state.count)
        mask = weight_decay_mask(params)
        updates = jax.tree.map(
            lambda u, p, m: u - lr * weight_decay * p if m else u,
            updates,
            params,
            mask,
        )
        return updates, optax.ScaleByScheduleState(count=state.count + 1)

    return optax.GradientTransformation(init, update)


def _clip_by_norm_fn(max_norm: float, norm_fn: Callable) -> optax.GradientTransformation:
    """``optax.clip_by_global_norm`` with a pluggable norm — needed inside a
    shard_map region, where ``optax.global_norm`` would see only this device's
    gradient SHARDS (the true norm needs a psum across the ZeRO axis). Same
    ``EmptyState`` as optax's clip, so the optimizer-state pytree structure —
    and therefore checkpoints — are identical between the GSPMD and
    explicit-collective train steps."""

    def init(params):
        del params
        return optax.EmptyState()

    def update(updates, state, params=None):
        del params
        norm = norm_fn(updates)
        # optax semantics: scale by max_norm/norm only when norm exceeds it
        scale = jnp.minimum(1.0, max_norm / (norm + 1e-16))
        return jax.tree.map(lambda u: u * scale, updates), state

    return optax.GradientTransformation(init, update)


def make_optimizer(
    cfg: OptimizerConfig,
    schedule=None,
    global_norm_fn: Optional[Callable] = None,
) -> optax.GradientTransformation:
    """Optimizer chain: clip → {adamw | adafactor | lion}.

    ``global_norm_fn`` swaps the grad-clip norm computation (used by the
    explicit-collective ZeRO step, which runs the update on gradient
    shards); state structure is unchanged either way. Adafactor keeps
    factored second moments (O(d+f) per [d,f] kernel instead of O(d·f)) —
    the classic TPU choice when even ZeRO-sharded Adam moments don't fit;
    lion keeps a single momentum buffer.

    Adafactor does NOT compose with the explicit ZeRO-2/3 shard_map core:
    its factored row/col statistics are replicated by the sharding plan
    while gradients arrive reduce-scattered, which shape-errors at trace
    time for any factored (>=128-dim) kernel. ``Trainer`` rejects the
    combination up front; use stage <= 1 — adafactor's whole point is
    removing the optimizer-memory pressure that higher stages exist to
    shard.
    """
    schedule = schedule or make_schedule(cfg)
    clip = (
        _clip_by_norm_fn(cfg.grad_clip, global_norm_fn)
        if global_norm_fn is not None
        else optax.clip_by_global_norm(cfg.grad_clip)
    )
    if cfg.optimizer == "adafactor":
        return optax.chain(
            clip,
            optax.adafactor(
                learning_rate=schedule,
                # external clip + schedule: disable adafactor's own update
                # clipping so cfg.grad_clip is the single clipping knob
                clipping_threshold=None,
            ),
            # decay OUTSIDE adafactor: optax's weight_decay_rate is applied
            # un-scaled by lr (p -= wd*p per step would collapse training
            # at AdamW-style wd=0.1)
            _lr_coupled_decay(schedule, cfg.weight_decay),
        )
    if cfg.optimizer == "lion":
        inner = optax.lion(
            learning_rate=schedule,
            b1=cfg.b1,
            b2=cfg.b2,
            weight_decay=cfg.weight_decay,
            mask=weight_decay_mask,
        )
    else:
        inner = optax.adamw(
            learning_rate=schedule,
            b1=cfg.b1,
            b2=cfg.b2,
            eps=cfg.eps,
            weight_decay=cfg.weight_decay,
            mask=weight_decay_mask,
        )
    return optax.chain(clip, inner)
