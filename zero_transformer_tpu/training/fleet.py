"""MPMD training fleet: supervised multi-process training with heartbeats,
elastic re-layout, and bounded-replay recovery.

The serving half of this repo already runs as a fleet — replica registry,
probe/eject state machine, cordon, migration, chaos proofs. This module
lifts that control plane to TRAINING (ROADMAP item 4; MPMD pipelining,
arXiv:2412.14374): the run is N supervised worker processes plus one
coordinator, and worker death is an event the control plane absorbs, not a
run-ending exception.

Design, in one breath:

- **Logical shards decouple layout from worker count.** The global batch is
  a fixed set of ``n_shards`` per-step micro-batches, generated
  counter-style from ``(seed, step, shard)`` — the stub for the data plane
  a real DCN/loader feeds. Workers own disjoint shard subsets; an elastic
  re-layout only REASSIGNS shards, never changes what any shard contains.
- **The fold is the collective.** Workers push per-shard grads to the
  coordinator, which left-folds them in ascending shard-id order (fp
  addition is not associative — fixed bracketing is what makes the fold
  bitwise-deterministic regardless of which worker computed which shard or
  in what order contributions arrived). This is the stub transport seam: a
  real deployment swaps the HTTP push/fold for DCN all-reduce with the same
  reduction order contract (GSPMD determinism, arXiv:2105.04663).
- **State is bitwise-replicated, so peers ARE the checkpoint.** Every
  worker applies the identical folded update, so params/optimizer state
  stay byte-identical across the fleet. A worker that dies between
  snapshots restarts checkpoint-free from any live peer's state; disk
  snapshots (orbax ``CheckpointManager`` — PR 5's verified-restore and
  loader-remap machinery) are only needed when the WHOLE fleet dies, and
  then replay is bounded by the snapshot interval.
- **Heartbeats ride the serving registry.** ``FleetRegistry`` adapts the
  push model (workers heartbeat) onto ``serving.router.ReplicaRegistry``'s
  pull-shaped probe state machine: a received heartbeat is a successful
  probe; a sweeper converts heartbeat silence into failed probes, so the
  same breaker/eject/backoff logic that decides replica death decides
  worker death. Straggler detection consumes the PR 15 obs plane's
  stitched span groups (``obs.fleet.detect_stragglers``).

Coordinator-side code performs NO jax computation — the fold is plain
numpy on received bytes, so the control plane keeps running when a
worker's backend is wedged and never compiles anything. ``FleetWorker``
touches jax lazily, inside its own methods only.
"""
from __future__ import annotations

import base64
import dataclasses
import json
import logging
import threading
import time
import urllib.error
import urllib.request
from collections import OrderedDict
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple
from urllib.parse import parse_qs, urlsplit

import numpy as np

from zero_transformer_tpu.obs.fleet import (
    detect_stragglers,
    estimate_clock_offset,
    stitch_spans,
    write_trace,
)
from zero_transformer_tpu.serving.router import READY, ReplicaRegistry

log = logging.getLogger("zero_transformer_tpu")

# folded results kept for laggards catching up after a blackhole/rejoin; a
# worker further behind than this re-bootstraps full state from a peer
FOLD_CACHE_STEPS = 8

# BENCH_fleet_train.json schema (pinned by tests/test_fleet_train.py)
FLEET_BENCH_REQUIRED_KEYS = (
    "metric",
    "workers",
    "n_shards",
    "steps",
    "relayouts",
    "replayed_steps",
    "replayed_shards",
    "relayout_downtime_s",
    "snapshot_every",
    "chaos",
    "bitwise_rejoin",
    "loss_first",
    "loss_last",
    "platform",
)


# ------------------------------------------------------------------- layout


def assign_shards(workers: Sequence[str], n_shards: int) -> Dict[str, Tuple[int, ...]]:
    """Deterministic round-robin shard assignment over SORTED worker ids.

    Sorting makes the layout a pure function of the live set — every
    relayout with the same survivors produces the same assignment, so a
    flapping worker cannot make the layout (and with it the fold-barrier
    membership) wander."""
    ws = sorted(workers)
    if not ws:
        return {}
    out: Dict[str, List[int]] = {w: [] for w in ws}
    for s in range(n_shards):
        out[ws[s % len(ws)]].append(s)
    return {w: tuple(v) for w, v in out.items()}


def shard_batch(
    seed: int, step: int, shard: int, per_shard: int, seq_len: int, vocab: int
) -> np.ndarray:
    """Counter-based deterministic micro-batch for ``(step, shard)``.

    Keyed on the logical shard, NOT the worker: after a re-layout the new
    owner regenerates byte-identical data, which is what makes replay a
    pure recompute instead of a data-loss event. (Stub for the real
    loader's sharded tar streams, which are position-addressable the same
    way — see ``remap_loader_state``.)"""
    rng = np.random.default_rng([int(seed), int(step), int(shard)])
    return rng.integers(0, vocab, size=(per_shard, seq_len), dtype=np.int32)


# ------------------------------------------------------- leaf (de)serialization


def encode_leaves(leaves: Sequence[np.ndarray]) -> Dict[str, Any]:
    """JSON-safe encoding of a flat leaf list (b64 raw bytes + dtype/shape).

    Raw ``tobytes`` round-trips bit-exactly — the wire format must not be
    where the bitwise-rejoin claim dies."""
    arrs = [np.ascontiguousarray(a) for a in leaves]
    return {
        "shapes": [list(a.shape) for a in arrs],
        "dtypes": [str(a.dtype) for a in arrs],
        "data": [base64.b64encode(a.tobytes()).decode("ascii") for a in arrs],
    }


def decode_leaves(doc: Dict[str, Any]) -> List[np.ndarray]:
    out = []
    for shape, dtype, data in zip(doc["shapes"], doc["dtypes"], doc["data"]):
        raw = base64.b64decode(data)
        out.append(
            np.frombuffer(raw, dtype=np.dtype(dtype)).reshape(shape).copy()
        )
    return out


def fold_shard_leaves(
    contribs: Dict[int, List[np.ndarray]]
) -> List[np.ndarray]:
    """Left-fold per-shard leaf lists in ASCENDING shard-id order.

    Floating-point addition is not associative: the fixed fold order (and
    fixed bracketing — one running accumulator) is the entire determinism
    contract. Any worker may have produced any contribution; the folded
    bytes are identical regardless."""
    shards = sorted(contribs)
    acc = [np.array(l, copy=True) for l in contribs[shards[0]]]
    for s in shards[1:]:
        leaves = contribs[s]
        if len(leaves) != len(acc):
            raise ValueError(
                f"shard {s} contributed {len(leaves)} leaves, expected {len(acc)}"
            )
        for i, l in enumerate(leaves):
            acc[i] = acc[i] + l
    return acc


def scale_leaves(leaves: Sequence[np.ndarray], n: int) -> List[np.ndarray]:
    """Mean-scale a folded sum by ``1/n`` — done ONCE, coordinator-side, so
    every worker receives identical bytes (a per-worker divide would be a
    second place for bit drift to enter)."""
    s = np.float32(1.0 / n)
    return [(l * s).astype(l.dtype) for l in leaves]


def fold_losses(loss_by_shard: Dict[int, float], n_shards: int) -> float:
    acc = np.float32(0.0)
    for s in sorted(loss_by_shard):
        acc = np.float32(acc + np.float32(loss_by_shard[s]))
    return float(np.float32(acc * np.float32(1.0 / n_shards)))


# ----------------------------------------------------------- fleet registry


class FleetRegistry:
    """Training-side facade over serving's ``ReplicaRegistry``.

    Serving PULLS health (the router probes); training PUSHES it (workers
    heartbeat). The adaptation: a received heartbeat is folded in as a
    successful probe, and :meth:`sweep` converts heartbeat SILENCE into
    synthetic failed probes — so the exact same breaker / eject-threshold /
    cordon state machine that decides replica death decides worker death,
    and its edge cases (stale-cordon resurrection, late data from a removed
    member) are shared, tested once, and fixed once."""

    def __init__(
        self,
        clock: Callable[[], float] = time.monotonic,
        hb_timeout_s: float = 0.75,
        eject_threshold: int = 3,
    ):
        # ReplicaRegistry refuses to start empty (a router with no replicas
        # is a config error); the fleet legitimately starts empty and fills
        # on /join — bootstrap with a placeholder and drop it.
        self._reg = ReplicaRegistry(
            ["fleet-bootstrap"], clock=clock, eject_threshold=eject_threshold
        )
        self._reg.remove(next(iter(self._reg.replicas)))
        self.clock = clock
        self.hb_timeout_s = hb_timeout_s
        self._rid: Dict[str, str] = {}  # wid -> registry rid
        self._last_hb: Dict[str, float] = {}

    def _wid_of(self, rid: str) -> Optional[str]:
        for w, r in self._rid.items():
            if r == rid:
                return w
        return None

    def register(self, wid: str) -> str:
        """Register (or RE-register) a worker.

        ``replace=True`` is load-bearing: a worker that was SIGKILLed and
        respawned under the same identity must get a fresh row — inheriting
        the dead predecessor's cordon/breaker/ejection state would keep the
        new process out of rotation forever (the stale-cordon resurrection
        bug, pinned in tests/test_router.py)."""
        rid = self._reg.add(wid, replace=True)
        self._rid[wid] = rid
        self._last_hb[wid] = self.clock()
        self._reg.observe_probe(rid, ok=True, body={"state": READY})
        return rid

    def heartbeat(self, wid: str, body: Optional[dict] = None) -> bool:
        """Fold one heartbeat in. Returns False for an unknown/removed
        worker: a LATE heartbeat from a removed member is dropped, never
        re-added — re-admission goes through :meth:`register` only."""
        rid = self._rid.get(wid)
        if rid is None or rid not in self._reg.replicas:
            return False
        self._last_hb[wid] = self.clock()
        b = {"state": READY}
        b.update(body or {})
        self._reg.observe_probe(rid, ok=True, body=b)
        return True

    def sweep(self, now: Optional[float] = None) -> List[Tuple[str, str]]:
        """Convert heartbeat silence into failed probes; returns lifecycle
        events as ``(event, wid)`` — ``("ejected", wid)`` is worker loss."""
        t = self.clock() if now is None else now
        events: List[Tuple[str, str]] = []
        for wid, rid in list(self._rid.items()):
            if rid not in self._reg.replicas:
                continue
            if t - self._last_hb.get(wid, 0.0) > self.hb_timeout_s:
                for ev, _ in self._reg.observe_probe(rid, ok=False):
                    events.append((ev, wid))
        return events

    def live(self) -> List[str]:
        return sorted(
            wid
            for wid, rid in self._rid.items()
            if rid in self._reg.replicas and self._reg.replicas[rid].routable
        )

    def is_live(self, wid: str) -> bool:
        rid = self._rid.get(wid)
        if rid is None or rid not in self._reg.replicas:
            return False
        return self._reg.replicas[rid].routable

    def cordon(self, wid: str) -> None:
        rid = self._rid.get(wid)
        if rid is not None:
            self._reg.cordon(rid)

    def remove(self, wid: str) -> None:
        rid = self._rid.pop(wid, None)
        self._last_hb.pop(wid, None)
        if rid is not None:
            self._reg.remove(rid)

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        snap = self._reg.snapshot()
        return {
            wid: snap[rid] for wid, rid in self._rid.items() if rid in snap
        }


# ------------------------------------------------------------- coordinator


@dataclasses.dataclass
class RelayoutRecord:
    """One elastic re-layout: why, who, and what the recovery cost."""

    epoch: int
    reason: str
    lost: Tuple[str, ...]
    workers: Tuple[str, ...]
    step: int  # in-flight global step when the layout changed
    replayed_steps: int
    replayed_shards: int
    t_detect: float
    t_resume: Optional[float] = None

    @property
    def downtime_s(self) -> float:
        if self.t_resume is None:
            return float("nan")
        return max(0.0, self.t_resume - self.t_detect)

    def to_dict(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        # None, not NaN: NaN is not strict JSON and breaks downstream parsers
        d["downtime_s"] = None if self.t_resume is None else self.downtime_s
        return d


class FleetCoordinator:
    """The training control plane: registry + fold barrier + layout epochs.

    Pure logic + threading (no sockets — :class:`CoordinatorServer` wraps
    it in HTTP): workers ``join``, ``heartbeat``, and ``submit`` per-shard
    grads; the coordinator folds when all shards of the in-flight step have
    arrived and releases the folded update to every blocked submitter. A
    layout EPOCH versions the assignment: any submit carrying a stale epoch
    is bounced with the new layout instead of being folded, which is how
    survivors learn mid-step that a re-layout happened and which shards
    they now owe."""

    def __init__(
        self,
        *,
        n_shards: int = 4,
        per_shard_batch: int = 2,
        seq_len: int = 16,
        vocab: int = 64,
        seed: int = 0,
        total_steps: Optional[int] = None,
        snapshot_every: int = 5,
        min_workers: int = 1,
        lr: float = 1e-3,
        model: Optional[Dict[str, int]] = None,
        ckpt_dir: Optional[str] = None,
        hb_timeout_s: float = 0.75,
        eject_threshold: int = 3,
        straggler_factor: float = 3.0,
        straggler_min_spans: int = 4,
        shed_stragglers: bool = True,
        clock: Callable[[], float] = time.monotonic,
    ):
        self.n_shards = int(n_shards)
        self.per_shard_batch = int(per_shard_batch)
        self.seq_len = int(seq_len)
        self.vocab = int(vocab)
        self.seed = int(seed)
        self.total_steps = total_steps
        self.snapshot_every = int(snapshot_every)
        self.min_workers = int(min_workers)
        self.lr = float(lr)
        self.model = dict(model or {"d_model": 32, "n_heads": 2, "n_layers": 2})
        self.ckpt_dir = ckpt_dir
        self.clock = clock
        self.straggler_factor = float(straggler_factor)
        self.straggler_min_spans = int(straggler_min_spans)
        self.shed_stragglers = bool(shed_stragglers)

        self.registry = FleetRegistry(
            clock=clock, hb_timeout_s=hb_timeout_s,
            eject_threshold=eject_threshold,
        )
        self.cv = threading.Condition()
        self.epoch = 0
        self.assignment: Dict[str, Tuple[int, ...]] = {}
        self.committed = -1  # last step whose fold was released
        self.contribs: Dict[int, List[np.ndarray]] = {}
        self.loss_by_shard: Dict[int, float] = {}
        self.folds: "OrderedDict[int, Dict[str, Any]]" = OrderedDict()
        self.loss_history: List[Tuple[int, float]] = []
        self.relayouts: List[RelayoutRecord] = []
        self.events: List[Dict[str, Any]] = []
        self.stopping = False
        self.done = threading.Event()
        # obs plane: coordinator fold spans + drained worker spans/offsets
        self.spans: List[Dict[str, Any]] = []
        self.worker_spans: Dict[str, List[Dict[str, Any]]] = {}
        self.worker_offsets: Dict[str, float] = {}
        self.worker_meta: Dict[str, Dict[str, Any]] = {}
        self._snapshot_step: Optional[int] = None
        self._fold_open_t: Optional[float] = None
        self._last_release_t: Optional[float] = None
        # peer-bootstrap plumbing: newest uploaded full state + who waits
        self._state_cache: Optional[Tuple[int, Dict[str, Any]]] = None
        self._bootstrap_waiters = 0
        self._stragglers: Dict[str, float] = {}

    # -- membership ---------------------------------------------------------

    def join(
        self, wid: str, offset_s: float = 0.0, version: Optional[int] = None
    ) -> Dict[str, Any]:
        """Admit (or re-admit) a worker; returns layout + run config + how
        to bootstrap state (``init`` | ``peer`` | ``snapshot``)."""
        with self.cv:
            if self.stopping:
                # a (re)join after the run finished: admit nothing, assign
                # nothing — the worker follows the fold line, sees stop, exits
                return {
                    "epoch": self.epoch,
                    "assignment": {},
                    "committed": self.committed,
                    "bootstrap": "none",
                    "stop": True,
                    "cfg": {
                        "n_shards": self.n_shards,
                        "per_shard_batch": self.per_shard_batch,
                        "seq_len": self.seq_len,
                        "vocab": self.vocab,
                        "seed": self.seed,
                        "snapshot_every": self.snapshot_every,
                        "lr": self.lr,
                        "model": self.model,
                        "total_steps": self.total_steps,
                    },
                }
            others = [w for w in self.registry.live() if w != wid]
            rewound = 0
            if version is not None and not others and version <= self.committed:
                # the whole fleet died and this worker restored a snapshot:
                # rewind the fold line to its restore point. Replay from
                # there is bounded by the snapshot interval — and because
                # shards are counter-addressed, it re-produces the exact
                # trajectory rather than an approximation of it.
                rewound = self.committed + 1 - version
                log.warning(
                    "fleet: rewinding committed %d -> %d for snapshot resume "
                    "of %s (replaying %d step(s))",
                    self.committed, version - 1, wid, rewound,
                )
                self.committed = version - 1
                self.contribs.clear()
                self.loss_by_shard.clear()
                self.folds.clear()
                self.loss_history = [
                    e for e in self.loss_history if e[0] < version
                ]
            self.registry.register(wid)
            self.worker_offsets[wid] = float(offset_s)
            self.worker_spans.setdefault(wid, [])
            boot = "init"
            if self.committed >= 0 or version is not None:
                boot = "peer" if others else ("snapshot" if version is None else "none")
            self._relayout(
                reason=("rewind:" if rewound else "join:") + wid,
                lost=(),
                replayed_steps=rewound,
            )
            self.events.append(
                {"t": self.clock(), "event": "join", "wid": wid, "boot": boot}
            )
            return {
                "epoch": self.epoch,
                "assignment": {w: list(s) for w, s in self.assignment.items()},
                "committed": self.committed,
                "bootstrap": boot,
                "cfg": {
                    "n_shards": self.n_shards,
                    "per_shard_batch": self.per_shard_batch,
                    "seq_len": self.seq_len,
                    "vocab": self.vocab,
                    "seed": self.seed,
                    "snapshot_every": self.snapshot_every,
                    "lr": self.lr,
                    "model": self.model,
                    "total_steps": self.total_steps,
                },
            }

    def _relayout(
        self,
        reason: str,
        lost: Tuple[str, ...],
        replayed_steps: Optional[int] = None,
        assignment: Optional[Dict[str, Tuple[int, ...]]] = None,
    ) -> None:
        """Bump the layout epoch and reassign shards over the live set.

        Must be called with ``self.cv`` held. Partial contributions for the
        in-flight step are KEPT: a shard's grads are identical whoever
        computed them, so only the shards the lost worker never delivered
        are replayed — the replay bill is the partial step, not the step."""
        self.epoch += 1
        live = self.registry.live()
        started = self.committed >= 0
        if not started and len(live) < self.min_workers:
            self.assignment = {}  # start gate: hold the first fold
        elif assignment is not None:
            self.assignment = assignment
        else:
            self.assignment = assign_shards(live, self.n_shards)
        s_cur = self.committed + 1
        missing = self.n_shards - len(self.contribs)
        if replayed_steps is None:
            replayed_steps = 1 if (lost and missing) else 0
        self.relayouts.append(
            RelayoutRecord(
                epoch=self.epoch,
                reason=reason,
                lost=tuple(lost),
                workers=tuple(live),
                step=s_cur,
                replayed_steps=int(replayed_steps),
                replayed_shards=missing if lost else 0,
                t_detect=self.clock(),
            )
        )
        if self.assignment and self._last_release_t is None:
            # the start gate just opened: this is when workers can begin
            # computing, so it anchors the first global step's trace window
            self._last_release_t = self.clock()
        log.warning(
            "fleet: relayout epoch=%d (%s) workers=%s assignment=%s",
            self.epoch, reason, live, self.assignment,
        )
        self.cv.notify_all()

    def _relayout_reply(self) -> Dict[str, Any]:
        return {
            "relayout": True,
            "epoch": self.epoch,
            "assignment": {w: list(s) for w, s in self.assignment.items()},
            "committed": self.committed,
        }

    # -- health plane -------------------------------------------------------

    def heartbeat(self, wid: str, body: Dict[str, Any]) -> Optional[Dict[str, Any]]:
        """One worker heartbeat. Returns directives, or None when the
        worker is unknown/removed (HTTP 410 — it must re-join)."""
        with self.cv:
            ok = self.registry.heartbeat(
                wid, {"clock_monotonic": body.get("clock")}
            )
            if not ok:
                self.events.append(
                    {"t": self.clock(), "event": "late_heartbeat_dropped",
                     "wid": wid}
                )
                return None
            self.worker_meta[wid] = {
                "step": body.get("step"),
                "version": body.get("version"),
                "snapshot_step": body.get("snapshot_step"),
                "loader": body.get("loader"),
            }
            if body.get("offset_s") is not None:
                self.worker_offsets[wid] = float(body["offset_s"])
            if body.get("snapshot_step") is not None:
                s = int(body["snapshot_step"])
                self._snapshot_step = max(self._snapshot_step or 0, s)
            spans = body.get("spans") or []
            if spans:
                buf = self.worker_spans.setdefault(wid, [])
                buf.extend(spans)
                del buf[:-400]
            directives: Dict[str, Any] = {}
            if (
                self._bootstrap_waiters > 0
                and self.registry.is_live(wid)
                and (self._state_cache is None
                     or self._state_cache[0] < self.committed + 1)
                and body.get("version") == self.committed + 1
            ):
                directives["upload_state"] = self.committed + 1
            if self.stopping:
                directives["stop"] = True
            return directives

    def sweep(self) -> List[Tuple[str, str]]:
        """Heartbeat-silence sweep; drives loss-triggered re-layouts and
        straggler detection. Called periodically by the server loop (or
        directly by tests with a fake clock)."""
        with self.cv:
            events = self.registry.sweep()
            lost = [wid for ev, wid in events if ev == "ejected"]
            for wid in lost:
                self.registry.cordon(wid)  # out of layout until re-register
                self.events.append(
                    {"t": self.clock(), "event": "worker_lost", "wid": wid}
                )
            if lost and not self.stopping:
                # post-stop exits are workers leaving on cue, not failures:
                # re-layouting for them would fabricate relayout records
                self._relayout(
                    reason="lost:" + ",".join(lost), lost=tuple(lost)
                )
            self._check_stragglers()
            return events

    def _check_stragglers(self) -> None:
        """Fleet-relative straggler detection over the stitched span groups
        the PR 15 obs plane defines (must hold ``self.cv``)."""
        groups = [
            {
                "process": wid,
                "offset_s": self.worker_offsets.get(wid, 0.0),
                "spans": list(self.worker_spans.get(wid, ())),
            }
            for wid in self.registry.live()
        ]
        report = detect_stragglers(
            groups,
            span_name="compute",
            factor=self.straggler_factor,
            min_spans=self.straggler_min_spans,
        )
        for wid, info in report.items():
            if not info["straggler"] or wid in self._stragglers:
                continue
            self._stragglers[wid] = info["ratio"]
            self.events.append(
                {"t": self.clock(), "event": "straggler_detected",
                 "wid": wid, "ratio": round(info["ratio"], 3)}
            )
            log.warning(
                "fleet: straggler %s (%.1fx fleet median)", wid, info["ratio"]
            )
            if self.shed_stragglers and len(self.assignment.get(wid, ())) > 1:
                self._shed_shard(wid, report)

    def _shed_shard(self, slow: str, report: Dict[str, Dict[str, Any]]) -> None:
        """Load-driven re-layout: move ONE shard off a straggler onto the
        fastest worker. Trajectory-invariant by construction (shards are
        the data, workers are just where they compute)."""
        fast = min(
            (w for w in self.assignment if w != slow),
            key=lambda w: report.get(w, {}).get("mean_s", float("inf")),
            default=None,
        )
        if fast is None:
            return
        new = {w: list(s) for w, s in self.assignment.items()}
        moved = new[slow].pop()
        new[fast].append(moved)
        self._relayout(
            reason=f"shed:{slow}->{fast}",
            lost=(),
            replayed_steps=0,
            assignment={w: tuple(sorted(s)) for w, s in new.items()},
        )

    # -- fold barrier -------------------------------------------------------

    def submit(
        self,
        wid: str,
        epoch: int,
        step: int,
        shard_docs: Dict[str, Dict[str, Any]],
        losses: Dict[str, float],
        timeout: float = 10.0,
    ) -> Dict[str, Any]:
        """Fold-barrier entry: accept per-shard grads, block until the fold
        for ``step`` releases (or the epoch moves / the run stops)."""
        deadline = self.clock() + timeout
        with self.cv:
            if not self.registry.is_live(wid):
                return {"gone": True}
            if (
                not self.stopping
                and epoch == self.epoch
                and step == self.committed + 1
            ):
                now = self.clock()
                if self._fold_open_t is None:
                    self._fold_open_t = now
                for sid_s, doc in shard_docs.items():
                    sid = int(sid_s)
                    if 0 <= sid < self.n_shards and sid not in self.contribs:
                        self.contribs[sid] = decode_leaves(doc)
                        self.loss_by_shard[sid] = float(losses[sid_s])
                if len(self.contribs) == self.n_shards and self.assignment:
                    self._complete_fold()
            while True:
                if not self.registry.is_live(wid):
                    return {"gone": True}
                if step <= self.committed:
                    # fold-before-stop: the LAST fold of the run both commits
                    # and sets stopping — workers must still receive it, or
                    # the final optimizer step exists only on the coordinator
                    fold = self.folds.get(step)
                    if fold is not None:
                        return {"ok": True, "step": step, **fold}
                    return {"stale": True, "committed": self.committed}
                if self.stopping:
                    return {"stop": True, "committed": self.committed}
                if epoch != self.epoch:
                    return self._relayout_reply()
                if self.clock() >= deadline:
                    return {"retry": True}
                self.cv.wait(timeout=0.05)

    def _complete_fold(self) -> None:
        """All shards in: fold in shard order, release, commit (cv held)."""
        s = self.committed + 1
        # the step's trace root spans the whole global step: from the
        # previous release (when workers could start computing this step)
        # to this release — worker compute/post/apply spans nest inside it
        t0 = self._last_release_t
        if t0 is None:
            t0 = self._fold_open_t if self._fold_open_t is not None else self.clock()
        folded = fold_shard_leaves(self.contribs)
        scaled = scale_leaves(folded, self.n_shards)
        loss = fold_losses(self.loss_by_shard, self.n_shards)
        self.folds[s] = {"grads": encode_leaves(scaled), "loss": loss}
        while len(self.folds) > FOLD_CACHE_STEPS:
            self.folds.popitem(last=False)
        self.committed = s
        self.loss_history.append((s, loss))
        self.contribs = {}
        self.loss_by_shard = {}
        t1 = self.clock()
        self._fold_open_t = None
        self._last_release_t = t1
        self.spans.append(
            {"track": f"step-{s}", "name": "route", "t0": t0, "t1": t1,
             "attrs": {"step": s, "loss": loss}}
        )
        del self.spans[:-600]
        for rec in self.relayouts:
            if rec.t_resume is None:
                rec.t_resume = t1
        if self.total_steps is not None and s >= self.total_steps - 1:
            self.stopping = True
            self.done.set()
        self.cv.notify_all()

    def get_fold(self, step: int, timeout: float = 10.0) -> Dict[str, Any]:
        """Catch-up path for shardless/lagging workers: the fold for
        ``step``, long-polling while it is still in flight. ``evicted``
        means the worker is too far behind the cache — re-bootstrap."""
        deadline = self.clock() + timeout
        with self.cv:
            while True:
                if step <= self.committed:
                    fold = self.folds.get(step)
                    if fold is None:
                        return {"evicted": True, "committed": self.committed}
                    return {"ok": True, "step": step, **fold}
                if self.stopping:
                    return {"stop": True, "committed": self.committed}
                if self.clock() >= deadline:
                    return {"pending": True, "committed": self.committed}
                self.cv.wait(timeout=0.05)

    # -- peer state bootstrap ----------------------------------------------

    def put_state(self, wid: str, version: int, state: Dict[str, Any]) -> bool:
        with self.cv:
            if self._state_cache is None or version >= self._state_cache[0]:
                self._state_cache = (int(version), state)
                self.events.append(
                    {"t": self.clock(), "event": "state_uploaded",
                     "wid": wid, "version": int(version)}
                )
                self.cv.notify_all()
                return True
            return False

    def get_bootstrap(self, timeout: float = 10.0) -> Dict[str, Any]:
        """Long-poll a peer state upload at the current fold line. The
        requesting worker then catches up through the fold cache if the
        line moved while it was downloading."""
        deadline = self.clock() + timeout
        with self.cv:
            self._bootstrap_waiters += 1
            try:
                while True:
                    if self.committed < 0 and self._state_cache is None:
                        return {"kind": "init"}
                    cache = self._state_cache
                    if cache is not None and cache[0] >= self.committed + 1 - (
                        FOLD_CACHE_STEPS - 1
                    ):
                        return {
                            "kind": "peer",
                            "version": cache[0],
                            "state": cache[1],
                        }
                    if self.clock() >= deadline:
                        return {"pending": True}
                    self.cv.wait(timeout=0.05)
            finally:
                self._bootstrap_waiters -= 1

    # -- observability ------------------------------------------------------

    def trace_groups(self, step: Optional[int] = None) -> List[Dict[str, Any]]:
        """Span groups in ``obs.fleet.stitch_spans`` shape — coordinator as
        the reference clock (offset 0), workers shifted by their reported
        offsets. ``step`` filters to one global step's track."""
        def keep(s):
            return step is None or s.get("track") == f"step-{step}"

        with self.cv:
            groups = [
                {
                    "process": "coordinator",
                    "offset_s": 0.0,
                    "spans": [s for s in self.spans if keep(s)],
                }
            ]
            for wid in sorted(self.worker_spans):
                groups.append(
                    {
                        "process": wid,
                        "offset_s": self.worker_offsets.get(wid, 0.0),
                        "spans": [
                            s for s in self.worker_spans[wid] if keep(s)
                        ],
                    }
                )
            return groups

    def trace_doc(self, step: Optional[int] = None) -> Dict[str, Any]:
        return stitch_spans(self.trace_groups(step))

    def status(self) -> Dict[str, Any]:
        with self.cv:
            return {
                "epoch": self.epoch,
                "committed": self.committed,
                "stopping": self.stopping,
                "assignment": {w: list(s) for w, s in self.assignment.items()},
                "workers": self.registry.snapshot(),
                "worker_meta": dict(self.worker_meta),
                "loss_history": [[s, l] for s, l in self.loss_history],
                "relayouts": [r.to_dict() for r in self.relayouts],
                "events": list(self.events),
                "stragglers": dict(self._stragglers),
                "snapshot_step": self._snapshot_step,
            }

    def bench(self, chaos: Sequence[str] = (), bitwise_rejoin: Optional[bool] = None) -> Dict[str, Any]:
        """The BENCH_fleet_train.json document (schema:
        ``FLEET_BENCH_REQUIRED_KEYS``)."""
        with self.cv:
            loss_rl = [
                r for r in self.relayouts if r.lost or "rewind" in r.reason
            ]
            downtime = sum(
                r.downtime_s for r in loss_rl if r.t_resume is not None
            )
            return {
                "metric": "fleet_train_relayout",
                "workers": len(self.registry.snapshot()),
                "n_shards": self.n_shards,
                "steps": self.committed + 1,
                "relayouts": [r.to_dict() for r in self.relayouts],
                "replayed_steps": sum(r.replayed_steps for r in loss_rl),
                "replayed_shards": sum(r.replayed_shards for r in loss_rl),
                "relayout_downtime_s": round(downtime, 6),
                "snapshot_every": self.snapshot_every,
                "chaos": list(chaos),
                "bitwise_rejoin": bitwise_rejoin,
                "loss_first": self.loss_history[0][1] if self.loss_history else None,
                "loss_last": self.loss_history[-1][1] if self.loss_history else None,
                "platform": "cpu",
            }

    def stop(self) -> None:
        with self.cv:
            self.stopping = True
            self.done.set()
            self.cv.notify_all()


# ----------------------------------------------------------- HTTP control plane


class _CoordinatorHandler(BaseHTTPRequestHandler):
    coord: FleetCoordinator  # set by CoordinatorServer
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # stdlib default spams stderr
        log.debug("fleet-http: " + fmt, *args)

    def _json(self, code: int, obj: Dict[str, Any]) -> None:
        body = json.dumps(obj).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _body(self) -> Dict[str, Any]:
        n = int(self.headers.get("Content-Length", 0) or 0)
        if n <= 0:
            return {}
        return json.loads(self.rfile.read(n).decode())

    def do_GET(self):  # noqa: N802 — stdlib handler API
        parts = urlsplit(self.path)
        q = {k: v[0] for k, v in parse_qs(parts.query).items()}
        if parts.path == "/clock":
            self._json(200, {"clock_monotonic": time.monotonic()})
        elif parts.path == "/status":
            self._json(200, self.coord.status())
        elif parts.path == "/fold":
            # short server-side long-poll: a pending reply doubles as the
            # shardless worker's cue to refresh its layout
            self._json(
                200, self.coord.get_fold(int(q.get("step", -1)), timeout=1.0)
            )
        elif parts.path == "/bootstrap":
            self._json(200, self.coord.get_bootstrap())
        elif parts.path == "/trace":
            step = int(q["step"]) if "step" in q else None
            self._json(200, self.coord.trace_doc(step))
        else:
            self._json(404, {"error": f"unknown path {parts.path}"})

    def do_POST(self):  # noqa: N802 — stdlib handler API
        path = urlsplit(self.path).path
        body = self._body()
        if path == "/join":
            self._json(
                200,
                self.coord.join(
                    str(body["wid"]),
                    offset_s=float(body.get("offset_s", 0.0)),
                    version=(
                        int(body["version"]) if body.get("version") is not None
                        else None
                    ),
                ),
            )
        elif path == "/heartbeat":
            directives = self.coord.heartbeat(str(body["wid"]), body)
            if directives is None:
                self._json(410, {"gone": True})
            else:
                self._json(200, {"directives": directives})
        elif path == "/grads":
            out = self.coord.submit(
                str(body["wid"]),
                int(body["epoch"]),
                int(body["step"]),
                body.get("shards", {}),
                body.get("losses", {}),
            )
            self._json(410 if out.get("gone") else 200, out)
        elif path == "/state":
            ok = self.coord.put_state(
                str(body["wid"]), int(body["version"]), body["state"]
            )
            self._json(200, {"accepted": ok})
        elif path == "/stop":
            self.coord.stop()
            self._json(200, {"stopping": True})
        else:
            self._json(404, {"error": f"unknown path {path}"})


class CoordinatorServer:
    """HTTP wrapper + heartbeat-sweeper thread around a FleetCoordinator."""

    def __init__(
        self,
        coord: FleetCoordinator,
        host: str = "127.0.0.1",
        port: int = 0,
        sweep_interval_s: float = 0.15,
    ):
        self.coord = coord
        handler = type(
            "_BoundHandler", (_CoordinatorHandler,), {"coord": coord}
        )
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.httpd.daemon_threads = True
        self.port = self.httpd.server_address[1]
        self.url = f"http://{host}:{self.port}"
        self.sweep_interval_s = sweep_interval_s
        self._stop = threading.Event()
        self._threads = [
            threading.Thread(target=self.httpd.serve_forever, daemon=True),
            threading.Thread(target=self._sweep_loop, daemon=True),
        ]

    def start(self) -> "CoordinatorServer":
        for t in self._threads:
            t.start()
        return self

    def _sweep_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.coord.sweep()
            except Exception:
                # the sweeper must outlive any one bad sweep: losing it
                # silently would disable death detection for the whole run
                log.exception("fleet: sweep failed (continuing)")
            self._stop.wait(self.sweep_interval_s)

    def close(self) -> None:
        self._stop.set()
        self.coord.stop()
        self.httpd.shutdown()
        self.httpd.server_close()

    def __enter__(self) -> "CoordinatorServer":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()


# --------------------------------------------------------------- HTTP client


def http_json(
    base: str,
    path: str,
    payload: Optional[Dict[str, Any]] = None,
    timeout: float = 30.0,
) -> Tuple[int, Dict[str, Any]]:
    """One JSON request to the coordinator. Returns ``(status, body)`` —
    HTTP errors with JSON bodies (409/410 protocol replies) are DATA here,
    not exceptions; transport errors raise for the caller's retry loop."""
    url = base.rstrip("/") + path
    data = None if payload is None else json.dumps(payload).encode()
    req = urllib.request.Request(
        url,
        data=data,
        headers={"Content-Type": "application/json"},
        method="POST" if data is not None else "GET",
    )
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, json.loads(resp.read().decode() or "{}")
    except urllib.error.HTTPError as e:
        raw = e.read().decode() if e.fp else "{}"
        try:
            return e.code, json.loads(raw or "{}")
        except json.JSONDecodeError:
            return e.code, {"error": raw}


def estimate_offset_to(base: str, timeout: float = 5.0) -> float:
    """This process's clock offset relative to the coordinator (worker
    clock minus coordinator clock), NTP-style from one ``/clock`` round
    trip — the group ``offset_s`` the PR 15 stitcher expects."""
    t0 = time.monotonic()
    _, body = http_json(base, "/clock", timeout=timeout)
    t1 = time.monotonic()
    coord_minus_us, _, _ = estimate_clock_offset(
        float(body["clock_monotonic"]), t0, t1
    )
    return -coord_minus_us


# -------------------------------------------------------------- fleet worker


class FleetWorker:
    """One DP worker process: compute owned shards, push grads, apply the
    released fold, heartbeat, snapshot when designated saver.

    jax is imported lazily (coordinator-side imports of this module stay
    backend-free). All state-mutating jax calls live on the main thread;
    the heartbeat thread only reads the published numpy copy of the state
    (peer-bootstrap uploads must not race the step loop)."""

    def __init__(
        self,
        base_url: str,
        wid: str,
        ckpt_dir: Optional[str] = None,
        resume: bool = False,
        chaos=None,
        hb_interval_s: float = 0.2,
        print_losses: bool = True,
    ):
        self.base = base_url
        self.wid = wid
        self.ckpt_dir = ckpt_dir
        self.resume = resume
        self.chaos = chaos
        self.hb_interval_s = hb_interval_s
        self.print_losses = print_losses
        self.version = 0  # state version = next global step to compute
        self.epoch = 0
        self.assignment: Dict[str, List[int]] = {}
        self.cfg: Dict[str, Any] = {}
        self.offset_s = 0.0
        self.snapshot_step: Optional[int] = None
        self._lock = threading.Lock()
        self._spans: List[Dict[str, Any]] = []
        self._pub: Optional[Tuple[int, Dict[str, Any]]] = None
        self._stop = threading.Event()
        self._shard_cache: Dict[Tuple[int, int], Tuple[float, Dict[str, Any]]] = {}
        self._ckpt = None
        self._losses: List[Tuple[int, float]] = []

    # -- jax-side construction ---------------------------------------------

    def _build(self) -> None:
        import jax
        import optax

        from zero_transformer_tpu.config import ModelConfig
        from zero_transformer_tpu.models.gpt import Transformer

        c = self.cfg
        mc = ModelConfig(
            vocab_size=c["vocab"],
            d_model=c["model"]["d_model"],
            n_heads=c["model"]["n_heads"],
            n_layers=c["model"]["n_layers"],
            max_seq_len=c["seq_len"],
            dropout=0.0,
        )
        model = Transformer(cfg=mc)
        sample = np.zeros((c["per_shard_batch"], c["seq_len"]), np.int32)
        params = model.init(jax.random.PRNGKey(c["seed"]), sample)["params"]
        tx = optax.adam(c["lr"])
        opt_state = tx.init(params)

        def loss_fn(p, batch):
            _, loss = model.apply({"params": p}, batch, labels=batch)
            return loss

        self._grad_fn = jax.jit(jax.value_and_grad(loss_fn))

        def apply_fn(p, o, g):
            updates, o2 = tx.update(g, o, p)
            return optax.apply_updates(p, updates), o2

        self._apply_fn = jax.jit(apply_fn)
        self._jax = jax
        self._tx = tx
        self.params = params
        self.opt_state = opt_state
        _, self._params_def = jax.tree_util.tree_flatten(params)
        _, self._opt_def = jax.tree_util.tree_flatten(opt_state)
        self._publish()

    def _param_leaves(self) -> List[np.ndarray]:
        return [np.asarray(l) for l in self._jax.tree_util.tree_leaves(self.params)]

    def _publish(self) -> None:
        """Numpy snapshot of (version, params, opt) for the heartbeat
        thread to serve on an ``upload_state`` directive."""
        doc = {
            "params": encode_leaves(self._param_leaves()),
            "opt": encode_leaves(
                [np.asarray(l) for l in self._jax.tree_util.tree_leaves(self.opt_state)]
            ),
        }
        with self._lock:
            self._pub = (self.version, doc)

    def _adopt_state(self, version: int, doc: Dict[str, Any]) -> None:
        self.params = self._jax.tree_util.tree_unflatten(
            self._params_def, decode_leaves(doc["params"])
        )
        self.opt_state = self._jax.tree_util.tree_unflatten(
            self._opt_def, decode_leaves(doc["opt"])
        )
        self.version = int(version)
        self._publish()

    # -- snapshots (PR 5 machinery) ----------------------------------------

    def _ckpt_mgr(self):
        if self._ckpt is None:
            from zero_transformer_tpu.checkpoint import CheckpointManager

            self._ckpt = CheckpointManager(
                self.ckpt_dir,
                save_frequency=max(1, int(self.cfg.get("snapshot_every", 5))),
                async_save=False,
            )
        return self._ckpt

    def _save_snapshot(self) -> None:
        import jax.numpy as jnp

        from zero_transformer_tpu.parallel.zero import TrainState

        c = self.cfg
        state = TrainState(
            step=jnp.asarray(self.version, jnp.int32),
            params=self.params,
            opt_state=self.opt_state,
        )
        meta = {
            "loader": {"steps_consumed": self.version},
            "schedule": {
                "batch_size": c["n_shards"] * c["per_shard_batch"],
                "train_context": c["seq_len"],
                "accum_steps": 1,
            },
            "fleet": {"wid": self.wid, "epoch": self.epoch,
                      "n_shards": c["n_shards"]},
        }
        if self._ckpt_mgr().save(self.version, state, meta=meta, force=True):
            self._ckpt_mgr().wait()
            self.snapshot_step = self.version
            log.info("fleet[%s]: snapshot at step %d", self.wid, self.version)

    def restore_snapshot(self) -> Optional[int]:
        """Verified restore (digest manifest; PR 5) + loader-position remap
        through the trainer's elastic-resume seam. Returns the restored
        version, or None when the directory holds no usable snapshot."""
        import jax.numpy as jnp

        from zero_transformer_tpu.parallel.zero import TrainState
        from zero_transformer_tpu.training.trainer import remap_loader_state

        mgr = self._ckpt_mgr()
        if mgr.latest_step() is None:
            return None
        template = TrainState(
            step=jnp.asarray(0, jnp.int32),
            params=self.params,
            opt_state=self.opt_state,
        )
        state, meta, _report = mgr.restore_verified(template)
        c = self.cfg
        loader = remap_loader_state(
            meta,
            batch_size=c["n_shards"] * c["per_shard_batch"],
            train_context=c["seq_len"],
            accum_steps=1,
        )
        version = int(
            (loader or {}).get("steps_consumed", int(np.asarray(state.step)))
        )
        self.params = state.params
        self.opt_state = state.opt_state
        self.version = version
        self.snapshot_step = version
        self._publish()
        return version

    # -- wire helpers -------------------------------------------------------

    def _span(self, name: str, t0: float, t1: float, **attrs) -> None:
        attrs.setdefault("wid", self.wid)
        with self._lock:
            self._spans.append(
                {"track": f"step-{self.version}", "name": name,
                 "t0": t0, "t1": t1, "attrs": attrs}
            )
            del self._spans[:-200]

    def _heartbeat_once(self) -> None:
        if self.chaos is not None and self.chaos.drop_heartbeat(self.version):
            return
        with self._lock:
            spans, self._spans = self._spans, []
            pub = self._pub
        body = {
            "wid": self.wid,
            "step": self.version,
            "version": self.version,
            "snapshot_step": self.snapshot_step,
            "loader": {"steps_consumed": self.version},
            "clock": time.monotonic(),
            "offset_s": self.offset_s,
            "spans": spans,
        }
        try:
            status, out = http_json(
                self.base, "/heartbeat", body, timeout=5.0
            )
        except (OSError, urllib.error.URLError) as e:
            log.warning("fleet[%s]: heartbeat failed: %s", self.wid, e)
            return
        if status == 410:
            return  # declared dead; the main loop will hit gone and rejoin
        directives = out.get("directives") or {}
        want = directives.get("upload_state")
        if want is not None and pub is not None and pub[0] == int(want):
            http_json(
                self.base, "/state",
                {"wid": self.wid, "version": pub[0], "state": pub[1]},
                timeout=10.0,
            )
        if directives.get("stop"):
            self._stop.set()

    def _hb_loop(self) -> None:
        while not self._stop.is_set():
            try:
                self._heartbeat_once()
            except Exception:
                # losing the heartbeat thread IS worker death to the fleet:
                # log and keep beating rather than silently going dark
                log.exception("fleet[%s]: heartbeat loop error", self.wid)
            self._stop.wait(self.hb_interval_s)

    # -- lifecycle ----------------------------------------------------------

    def _join(self, version: Optional[int] = None) -> Dict[str, Any]:
        self.offset_s = estimate_offset_to(self.base)
        _, out = http_json(
            self.base, "/join",
            {"wid": self.wid, "offset_s": self.offset_s, "version": version},
        )
        self.epoch = int(out["epoch"])
        self.assignment = out["assignment"]
        self.cfg = out["cfg"]
        return out

    def _bootstrap_peer(self) -> None:
        while not self._stop.is_set():
            _, out = http_json(self.base, "/bootstrap", timeout=30.0)
            if out.get("kind") == "peer":
                self._adopt_state(out["version"], out["state"])
                log.info(
                    "fleet[%s]: peer bootstrap at version %d",
                    self.wid, self.version,
                )
                return
            if out.get("kind") == "init":
                return
            time.sleep(0.05)

    def _catch_up_or_rebootstrap(self, committed: int) -> None:
        """Apply cached folds from our version up to the fold line; if the
        cache no longer reaches back far enough, take a fresh peer state."""
        while self.version <= committed and not self._stop.is_set():
            _, out = http_json(
                self.base, f"/fold?step={self.version}", timeout=30.0
            )
            if out.get("ok"):
                self._apply_fold(out)
            elif out.get("evicted"):
                self._bootstrap_peer()
                return
            elif out.get("stop"):
                self._stop.set()
                return
            else:  # pending
                time.sleep(0.02)

    def _apply_fold(self, fold: Dict[str, Any]) -> None:
        t0 = time.monotonic()
        grads = self._jax.tree_util.tree_unflatten(
            self._params_def, decode_leaves(fold["grads"])
        )
        self.params, self.opt_state = self._apply_fn(
            self.params, self.opt_state, grads
        )
        self._jax.block_until_ready(self.params)
        step = self.version
        self._losses.append((step, float(fold["loss"])))
        if self.print_losses:
            print(f"LOSS step={step} {float(fold['loss']):.6f}", flush=True)
        self.version += 1
        self._span("apply", t0, time.monotonic(), step=step)
        self._publish()
        self._shard_cache = {
            k: v for k, v in self._shard_cache.items() if k[0] >= self.version
        }
        c = self.cfg
        if (
            self.ckpt_dir
            and self.version % max(1, int(c["snapshot_every"])) == 0
            and self.wid == min(self.assignment or {self.wid: ()})
        ):
            self._save_snapshot()
        if self.chaos is not None:
            self.chaos.on_step(self.version)

    def _compute_shard(self, step: int, sid: int) -> Tuple[float, Dict[str, Any]]:
        key = (step, sid)
        if key in self._shard_cache:
            return self._shard_cache[key]
        c = self.cfg
        t0 = time.monotonic()
        if self.chaos is not None:
            delay = self.chaos.compute_delay(step)
            if delay > 0:
                time.sleep(delay)
        batch = shard_batch(
            c["seed"], step, sid, c["per_shard_batch"], c["seq_len"], c["vocab"]
        )
        loss, grads = self._grad_fn(self.params, batch)
        leaves = [np.asarray(l) for l in self._jax.tree_util.tree_leaves(grads)]
        out = (float(np.float32(loss)), encode_leaves(leaves))
        self._shard_cache[key] = out
        self._span("compute", t0, time.monotonic(), shard=sid, step=step)
        return out

    def run(self) -> int:
        """Join, bootstrap, train until the coordinator stops the run.
        Returns the number of optimizer steps this process applied."""
        out = self._join()
        if out.get("stop"):
            return 0  # run already over; nothing to bootstrap or compute
        # heartbeat BEFORE the jax build: compiling the model takes longer
        # than the death timeout, and a worker mid-compile is slow, not dead
        hb = threading.Thread(target=self._hb_loop, daemon=True)
        hb.start()
        self._build()
        applied_from = self.version
        if out["bootstrap"] == "snapshot" or (self.resume and self.ckpt_dir):
            restored = self.restore_snapshot() if self.ckpt_dir else None
            if restored is not None:
                # re-join carrying the restored version: the coordinator
                # rewinds the fold line to it when we are the sole survivor
                out = self._join(version=restored)
                applied_from = self.version
        if out["bootstrap"] == "peer":
            self._bootstrap_peer()
            applied_from = self.version
        try:
            self._run_loop()
        finally:
            self._stop.set()
            hb.join(timeout=2.0)
            try:
                # final span flush: spans ride heartbeats, and a clean exit
                # lands within one hb interval of the last steps — without
                # this the trace tail of the run is coordinator-only
                self._heartbeat_once()
            except Exception:
                log.exception("fleet[%s]: final span flush failed", self.wid)
            if self._ckpt is not None:
                self._ckpt.close()
        return self.version - applied_from

    def _run_loop(self) -> None:
        while not self._stop.is_set():
            mine = [int(s) for s in self.assignment.get(self.wid, [])]
            step = self.version
            if not mine:
                # shardless (fleet larger than shard count, or start gate):
                # follow the fold line
                _, out = http_json(
                    self.base, f"/fold?step={step}", timeout=30.0
                )
                if out.get("ok"):
                    self._apply_fold(out)
                elif out.get("stop"):
                    self._stop.set()
                elif out.get("evicted"):
                    self._bootstrap_peer()
                else:
                    self._refresh_layout()
                continue
            shards: Dict[str, Any] = {}
            losses: Dict[str, float] = {}
            for sid in mine:
                loss, doc = self._compute_shard(step, sid)
                shards[str(sid)] = doc
                losses[str(sid)] = loss
            t0 = time.monotonic()
            try:
                status, out = http_json(
                    self.base, "/grads",
                    {
                        "wid": self.wid,
                        "epoch": self.epoch,
                        "step": step,
                        "shards": shards,
                        "losses": losses,
                    },
                    timeout=30.0,
                )
            except (OSError, urllib.error.URLError) as e:
                log.warning("fleet[%s]: grads post failed: %s", self.wid, e)
                time.sleep(0.1)
                continue
            self._span("post", t0, time.monotonic(), step=step)
            if status == 410 or out.get("gone"):
                self._rejoin()
            elif out.get("relayout"):
                self.epoch = int(out["epoch"])
                self.assignment = out["assignment"]
            elif out.get("stop"):
                self._stop.set()
            elif out.get("stale"):
                self._catch_up_or_rebootstrap(int(out["committed"]))
            elif out.get("ok"):
                self._apply_fold(out)
            # retry: loop again (cached shards make the re-post cheap)

    def _refresh_layout(self) -> None:
        _, status = http_json(self.base, "/status", timeout=10.0)
        self.epoch = int(status["epoch"])
        self.assignment = {
            w: list(s) for w, s in status["assignment"].items()
        }

    def _rejoin(self) -> None:
        """Declared dead (heartbeat blackhole / SIGSTOP resume): re-register
        under the same id — the registry gives us a FRESH row — then close
        any fold gap that opened while we were out."""
        log.warning(
            "fleet[%s]: declared dead by coordinator, rejoining", self.wid
        )
        out = self._join()
        if out.get("stop"):
            self._stop.set()
            return
        if int(out["committed"]) >= self.version:
            self._catch_up_or_rebootstrap(int(out["committed"]))
