"""Evaluation tasks: LAMBADA-style last-word prediction, document perplexity,
bits-per-byte.

Computes on TPU, in-tree, the metrics the reference could only get by
exporting to PyTorch + lm-eval-harness on a GPU (reference ``README.md:53-57``
LAMBADA PPL/ACC table; ``logs/1B.md:25-29`` Pile bits-per-byte). Inputs are
token sequences — tokenization happens upstream (``serve.py`` /
``data.sources``) so the harness has no tokenizer or network dependency.
"""
from __future__ import annotations

import math
from typing import Any, Iterable, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from zero_transformer_tpu.evalharness.scoring import loglikelihoods, score_batch
from zero_transformer_tpu.models.gpt import Transformer


def lambada(
    model: Transformer,
    params: Any,
    examples: Iterable[Tuple[Sequence[int], Sequence[int]]],
    seq_len: int,
    batch_size: int = 8,
) -> dict:
    """LAMBADA-style eval: (context, last-word tokens) pairs.

    Returns ``{"ppl", "acc", "examples"}`` — perplexity over the target word
    tokens and greedy-prediction accuracy, the two numbers the reference
    reports per model (reference ``README.md:53-57``).
    """
    results = loglikelihoods(model, params, examples, seq_len, batch_size)
    if not results:
        return {"ppl": float("nan"), "acc": float("nan"), "examples": 0}
    total_lp = sum(r["logprob"] for r in results)
    total_tok = sum(r["tokens"] for r in results)
    acc = sum(r["greedy_match"] for r in results) / len(results)
    return {
        "ppl": math.exp(-total_lp / max(total_tok, 1)),
        "acc": acc,
        "examples": len(results),
    }


def perplexity(
    model: Transformer,
    params: Any,
    tokens: Sequence[int],
    seq_len: int,
    batch_size: int = 8,
    num_bytes: Optional[int] = None,
) -> dict:
    """Token-stream perplexity over [seq_len] windows with one token of
    overlap (stride ``seq_len - 1``), so every token except the stream's very
    first is predicted exactly once — the rolling-loglikelihood convention
    lm-eval-harness uses, whose numbers the reference publishes.

    With ``num_bytes`` (the UTF-8 length of the source text) also reports
    bits-per-byte: nll_total / (ln2 * bytes) — the Pile metric the reference
    reports (reference ``logs/1B.md:25-29``, ``logs/760.md:66-70``). Only the
    first token of the whole stream is unscored (it has no context), matching
    the harness convention.
    """
    tokens = np.asarray(tokens, np.int32)
    if len(tokens) < 2:
        raise ValueError(f"need at least 2 tokens, got {len(tokens)}")
    stride = seq_len - 1
    n_windows = (len(tokens) - 2) // stride + 1
    # pad the tail once so every window is a strided view; the pad is masked
    padded = np.zeros(n_windows * stride + 1, np.int32)
    padded[: len(tokens)] = tokens
    windows = np.lib.stride_tricks.sliding_window_view(padded, seq_len)[::stride]
    # all windows share the mask pattern [0,1,1,...] except the last, where
    # positions past the real tail are off
    window_masks = np.zeros((n_windows, seq_len), np.int32)
    window_masks[:, 1:] = 1
    tail = len(tokens) - (n_windows - 1) * stride  # real length of last window
    window_masks[-1, tail:] = 0

    total_nll, total_tok = 0.0, 0
    for start in range(0, n_windows, batch_size):
        chunk = windows[start : start + batch_size]
        mask = window_masks[start : start + batch_size]
        n_real = len(chunk)
        pad_n = batch_size - n_real
        if pad_n:
            chunk = np.concatenate([chunk, np.zeros((pad_n, seq_len), np.int32)])
            mask = np.concatenate([mask, np.zeros((pad_n, seq_len), np.int32)])
        res = score_batch(model, params, jnp.asarray(chunk), jnp.asarray(mask))
        total_nll += -float(jnp.sum(res["logprob"][:n_real]))
        total_tok += int(jnp.sum(res["tokens"][:n_real]))

    out = {
        "nll": total_nll,
        "tokens": total_tok,
        "ppl": math.exp(total_nll / max(total_tok, 1)),
    }
    if num_bytes:
        out["bits_per_byte"] = total_nll / (math.log(2) * num_bytes)
    return out
