"""Evaluation tasks: LAMBADA-style last-word prediction, multiple-choice
accuracy (PIQA / Winogrande / HellaSwag-style), document perplexity,
bits-per-byte.

Computes on TPU, in-tree, the metrics the reference could only get by
exporting to PyTorch + lm-eval-harness on a GPU (reference ``README.md:53-57``
LAMBADA PPL/ACC table; ``logs/1B.md:25-29`` Pile bits-per-byte). Inputs are
token sequences — tokenization happens upstream (``serve.py`` /
``data.sources``) so the harness has no tokenizer or network dependency.
"""
from __future__ import annotations

import math
from typing import Any, Iterable, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from zero_transformer_tpu.evalharness.scoring import loglikelihoods, score_batch
from zero_transformer_tpu.models.gpt import Transformer


def lambada(
    model: Transformer,
    params: Any,
    examples: Iterable[Tuple[Sequence[int], Sequence[int]]],
    seq_len: int,
    batch_size: int = 8,
) -> dict:
    """LAMBADA-style eval: (context, last-word tokens) pairs.

    Returns ``{"ppl", "acc", "examples"}`` — perplexity over the target word
    tokens and greedy-prediction accuracy, the two numbers the reference
    reports per model (reference ``README.md:53-57``).
    """
    results = loglikelihoods(model, params, examples, seq_len, batch_size)
    if not results:
        return {"ppl": float("nan"), "acc": float("nan"), "examples": 0}
    total_lp = sum(r["logprob"] for r in results)
    total_tok = sum(r["tokens"] for r in results)
    acc = sum(r["greedy_match"] for r in results) / len(results)
    return {
        "ppl": math.exp(-total_lp / max(total_tok, 1)),
        "acc": acc,
        "examples": len(results),
    }


def choice_accuracy(
    model: Transformer,
    params: Any,
    examples: Iterable[tuple],
    seq_len: int,
    batch_size: int = 8,
) -> dict:
    """Multiple-choice task driver — the scoring convention behind the
    reference's published PIQA / Winogrande / HellaSwag-norm table
    (reference ``README.md:53-57``, produced there via lm-eval-harness on an
    exported PyTorch model; here it runs in-tree on TPU).

    Each example is ``(context_tokens, choices, gold_index)`` or
    ``(context_tokens, choices, gold_index, choice_byte_lens)`` where
    ``choices`` is a list of per-choice continuation token lists and
    ``choice_byte_lens`` the UTF-8 byte length of each choice's surface
    string. Every choice is scored as sum log P(choice | context); the
    prediction is the argmax choice under two criteria:

    - ``acc``       — raw summed loglikelihood (PIQA/Winogrande convention);
    - ``acc_norm``  — loglikelihood / byte length (the "HellaSwag-norm"
      length normalization). Falls back to token-count normalization when
      byte lengths aren't provided (reported as ``norm="tokens"``).
    """
    examples = list(examples)
    flat: List[Tuple[Sequence[int], Sequence[int]]] = []
    spans: List[Tuple[int, int]] = []  # [start, end) into flat per example
    for ex in examples:
        ctx, choices = ex[0], ex[1]
        if not choices:
            raise ValueError("example has no choices")
        spans.append((len(flat), len(flat) + len(choices)))
        flat.extend((ctx, cont) for cont in choices)
    # one normalization per run: mixing logprob/byte with logprob/token
    # across examples would make acc_norm a meaningless hybrid
    has_bytes = [len(ex) > 3 and ex[3] is not None for ex in examples]
    if any(has_bytes) and not all(has_bytes):
        raise ValueError(
            "choice_byte_lens must be provided for all examples or none "
            f"(got {sum(has_bytes)}/{len(examples)})"
        )
    used_bytes = bool(examples) and all(has_bytes)
    scored = loglikelihoods(model, params, flat, seq_len, batch_size)

    n_correct, n_correct_norm = 0, 0
    for ex, (start, end) in zip(examples, spans):
        gold = int(ex[2])
        lps = [scored[i]["logprob"] for i in range(start, end)]
        if used_bytes:
            byte_lens = ex[3]
        else:
            byte_lens = [max(scored[i]["tokens"], 1) for i in range(start, end)]
        if len(byte_lens) != len(lps):
            raise ValueError("choice_byte_lens length mismatch")
        n_correct += int(int(np.argmax(lps)) == gold)
        normed = [lp / max(b, 1) for lp, b in zip(lps, byte_lens)]
        n_correct_norm += int(int(np.argmax(normed)) == gold)
    n = max(len(examples), 1)
    return {
        "acc": n_correct / n,
        "acc_norm": n_correct_norm / n,
        "norm": "bytes" if used_bytes else "tokens",
        "examples": len(examples),
    }


def perplexity(
    model: Transformer,
    params: Any,
    tokens: Sequence[int],
    seq_len: int,
    batch_size: int = 8,
    num_bytes: Optional[int] = None,
) -> dict:
    """Token-stream perplexity over [seq_len] windows with one token of
    overlap (stride ``seq_len - 1``), so every token except the stream's very
    first is predicted exactly once — the rolling-loglikelihood convention
    lm-eval-harness uses, whose numbers the reference publishes.

    With ``num_bytes`` (the UTF-8 length of the source text) also reports
    bits-per-byte: nll_total / (ln2 * bytes) — the Pile metric the reference
    reports (reference ``logs/1B.md:25-29``, ``logs/760.md:66-70``). Only the
    first token of the whole stream is unscored (it has no context), matching
    the harness convention.
    """
    tokens = np.asarray(tokens, np.int32)
    if len(tokens) < 2:
        raise ValueError(f"need at least 2 tokens, got {len(tokens)}")
    stride = seq_len - 1
    n_windows = (len(tokens) - 2) // stride + 1
    # pad the tail once so every window is a strided view; the pad is masked
    padded = np.zeros(n_windows * stride + 1, np.int32)
    padded[: len(tokens)] = tokens
    windows = np.lib.stride_tricks.sliding_window_view(padded, seq_len)[::stride]
    # all windows share the mask pattern [0,1,1,...] except the last, where
    # positions past the real tail are off
    window_masks = np.zeros((n_windows, seq_len), np.int32)
    window_masks[:, 1:] = 1
    tail = len(tokens) - (n_windows - 1) * stride  # real length of last window
    window_masks[-1, tail:] = 0

    total_nll, total_tok = 0.0, 0
    for start in range(0, n_windows, batch_size):
        chunk = windows[start : start + batch_size]
        mask = window_masks[start : start + batch_size]
        n_real = len(chunk)
        pad_n = batch_size - n_real
        if pad_n:
            chunk = np.concatenate([chunk, np.zeros((pad_n, seq_len), np.int32)])
            mask = np.concatenate([mask, np.zeros((pad_n, seq_len), np.int32)])
        res = score_batch(model, params, jnp.asarray(chunk), jnp.asarray(mask))
        total_nll += -float(jnp.sum(res["logprob"][:n_real]))
        total_tok += int(jnp.sum(res["tokens"][:n_real]))

    out = {
        "nll": total_nll,
        "tokens": total_tok,
        "ppl": math.exp(total_nll / max(total_tok, 1)),
    }
    if num_bytes:
        out["bits_per_byte"] = total_nll / (math.log(2) * num_bytes)
    return out
