"""In-tree TPU eval harness (replaces the reference's export-to-PyTorch +
GPU lm-eval-harness loop, reference ``torch_compatability/`` + ``README.md:53-57``)."""
from zero_transformer_tpu.evalharness.scoring import loglikelihoods, score_batch
from zero_transformer_tpu.evalharness.tasks import (
    choice_accuracy,
    lambada,
    perplexity,
)

__all__ = [
    "choice_accuracy",
    "lambada",
    "loglikelihoods",
    "perplexity",
    "score_batch",
]
