"""Eval CLI: run the in-tree tasks on a checkpoint from the command line.

The reference's published numbers required exporting to PyTorch and running
lm-eval-harness on a CUDA GPU (reference ``README.md:53-57``,
``torch_compatability/``); this runs the same measurements on TPU in one
command::

  python -m zero_transformer_tpu.evalharness --model 1_3b --params p.msgpack \\
      --task lambada --data lambada.jsonl --seq-len 1024

Data is pre-tokenized JSONL (no tokenizer or network dependency):

- ``lambada``:  {"context": [ids], "target": [ids]}            per line
- ``choice``:   {"context": [ids], "choices": [[ids], ...],
                 "gold": i, "choice_bytes": [n, ...]?}          per line
- ``ppl``/``bpb``: one object {"tokens": [ids], "num_bytes": n?}
  (or a raw ``.bin``/``.u16`` uint16 token file; pass --num-bytes for bpb)

Pass ``--tokenizer <hf name/path>`` to instead accept text fields
("context"/"target"/"choices" as strings), tokenized on the fly.
"""
from __future__ import annotations

import argparse
import itertools
import json
from pathlib import Path

import numpy as np


def _read_jsonl(path: str):
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                yield json.loads(line)


def _tok(tokenizer, x, prefix_space: bool = False):
    if tokenizer is None:
        return list(x)
    if isinstance(x, str):
        # no BOS/EOS injection: continuations are scored token-for-token,
        # so a tokenizer-added special token would be scored as if it were
        # part of the target text
        return tokenizer.encode(
            (" " + x) if prefix_space else x, add_special_tokens=False
        )
    return list(x)


def main(argv=None) -> None:
    p = argparse.ArgumentParser(description="TPU in-tree eval harness")
    p.add_argument("--model", required=True, help="model zoo name")
    p.add_argument("--params", required=True, help="params msgpack (see export)")
    p.add_argument(
        "--task", required=True, choices=["lambada", "choice", "ppl", "bpb"]
    )
    p.add_argument("--data", required=True, help="JSONL / token file (see docstring)")
    p.add_argument("--seq-len", type=int, default=1024)
    p.add_argument("--batch-size", type=int, default=8)
    p.add_argument("--dtype", default="bfloat16")
    p.add_argument("--num-bytes", type=int, default=None, help="UTF-8 bytes for bpb")
    p.add_argument("--limit", type=int, default=None, help="cap example count")
    p.add_argument("--tokenizer", default=None, help="HF tokenizer for text JSONL")
    p.add_argument("--quantize", default="none", choices=("none", "int8"),
                   help="score the weight-only int8 serving path (the same "
                        "conversion serve --quantize runs) — measures what "
                        "the quantization costs in eval quality")
    args = p.parse_args(argv)

    from zero_transformer_tpu.checkpoint import import_params_msgpack
    from zero_transformer_tpu.config import model_config
    from zero_transformer_tpu.evalharness import (
        choice_accuracy,
        lambada,
        perplexity,
    )
    from zero_transformer_tpu.models import Transformer

    cfg = model_config(
        args.model, compute_dtype=args.dtype, dropout=0.0,
        param_quant=args.quantize,
    )
    params = import_params_msgpack(args.params)
    if args.quantize == "int8":
        from zero_transformer_tpu.models.quant import quantize_params

        params = quantize_params(params)  # host-side, before device placement
    model = Transformer(cfg)
    tokenizer = None
    if args.tokenizer:
        from transformers import AutoTokenizer

        tokenizer = AutoTokenizer.from_pretrained(args.tokenizer)

    def rows():
        # bound the read AND the tokenization to what will be scored
        return itertools.islice(_read_jsonl(args.data), args.limit)

    if args.task in ("ppl", "bpb"):
        if args.data.endswith((".bin", ".u16")):
            tokens = np.fromfile(args.data, dtype=np.uint16).astype(np.int32)
            num_bytes = args.num_bytes
        else:
            obj = json.loads(Path(args.data).read_text())
            tokens = np.asarray(_tok(tokenizer, obj["tokens"]), np.int32)
            num_bytes = obj.get("num_bytes", args.num_bytes)
        if args.task == "bpb":
            if not num_bytes:
                raise SystemExit(
                    "--task bpb needs the source byte count: pass --num-bytes "
                    "or a num_bytes field in the data file"
                )
            if args.limit and args.limit < len(tokens):
                raise SystemExit(
                    "--limit with --task bpb would divide a truncated nll by "
                    "the full document's bytes; truncate the data file instead"
                )
        if args.limit:
            tokens = tokens[: args.limit]
        out = perplexity(
            model, params, tokens, args.seq_len, args.batch_size, num_bytes
        )
    elif args.task == "lambada":
        examples = [
            (_tok(tokenizer, r["context"]), _tok(tokenizer, r["target"], True))
            for r in rows()
        ]
        out = lambada(model, params, examples, args.seq_len, args.batch_size)
    else:  # choice
        examples = []
        for r in rows():
            choices = [_tok(tokenizer, c, True) for c in r["choices"]]
            byte_lens = r.get("choice_bytes")
            if byte_lens is None and all(
                isinstance(c, str) for c in r["choices"]
            ):
                # lm-eval convention: UTF-8 length of the continuation as
                # scored, including its leading space. Token-list choices
                # without explicit byte lengths fall through to
                # choice_accuracy's token-count normalization.
                byte_lens = [len((" " + c).encode()) for c in r["choices"]]
            examples.append(
                (_tok(tokenizer, r["context"]), choices, int(r["gold"]), byte_lens)
            )
        out = choice_accuracy(
            model, params, examples, args.seq_len, args.batch_size
        )

    print(json.dumps({"task": args.task, "model": args.model, **out}))


if __name__ == "__main__":
    main()
