from zero_transformer_tpu.evalharness.cli import main

if __name__ == "__main__":
    main()
