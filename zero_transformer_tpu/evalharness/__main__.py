from zero_transformer_tpu.evalharness.cli import main

main()
