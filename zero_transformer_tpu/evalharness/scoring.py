"""Jitted log-likelihood scoring primitives.

The reference exports checkpoints to PyTorch and runs lm-eval-harness on a
CUDA GPU to get its LAMBADA / PIQA / Pile numbers (reference ``README.md:53-57``,
``torch_compatability/GPT2.py:358`` keeps a cache-less ``generate`` purely for
harness compatibility). Here the same measurements run in-tree on TPU:
fixed-shape batched scoring under one jit, no export step, no torch.

Conventions (lm-eval-harness "loglikelihood" semantics):
- an example is (context tokens, continuation tokens);
- score = sum of log P(continuation_t | context, continuation_<t);
- "greedy match" = every continuation token is the argmax — the accuracy
  criterion for LAMBADA.
"""
from __future__ import annotations

import functools
from typing import Any, Iterable, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from zero_transformer_tpu.models.gpt import Transformer


@functools.partial(jax.jit, static_argnums=(0,))
def score_batch(
    model: Transformer,
    params: Any,
    tokens: jax.Array,
    target_mask: jax.Array,
) -> dict:
    """Score target positions of a [B, T] batch.

    ``target_mask`` [B, T] marks positions whose tokens are *predicted*
    (i.e. the continuation); position t is predicted from logits at t-1.
    Returns per-example sum logprob, token count, and whether every target
    token was the argmax. Softmax runs in float32 (the dtype discipline of
    reference ``src/utils/losses.py:22``).
    """
    logits = model.apply({"params": params}, tokens)  # [B, T, V]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    # logits at t predict token at t+1
    pred_logp = logp[:, :-1, :]
    targets = tokens[:, 1:]
    mask = target_mask[:, 1:].astype(jnp.float32)
    tok_logp = jnp.take_along_axis(pred_logp, targets[..., None], axis=-1)[..., 0]
    greedy = (jnp.argmax(pred_logp, axis=-1) == targets).astype(jnp.float32)
    return {
        "logprob": jnp.sum(tok_logp * mask, axis=-1),
        "tokens": jnp.sum(mask, axis=-1),
        "greedy_match": jnp.all(jnp.where(mask > 0, greedy, 1.0) > 0, axis=-1),
    }


def _pad_batch(
    examples: Sequence[Tuple[Sequence[int], Sequence[int]]],
    seq_len: int,
    batch: int,
) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Right-pad (context, continuation) pairs to [batch, seq_len].

    Left-truncates long contexts (keeps the continuation intact) — the
    sliding-window convention lm-eval-harness uses for fixed-ctx models.
    """
    tokens = np.zeros((batch, seq_len), np.int32)
    mask = np.zeros((batch, seq_len), np.int32)
    valid = np.zeros((batch,), np.int32)
    for i, (ctx, cont) in enumerate(examples):
        ctx, cont = list(ctx), list(cont)
        if not cont:
            raise ValueError("empty continuation")
        if len(cont) >= seq_len:
            raise ValueError(f"continuation ({len(cont)}) must be < seq_len ({seq_len})")
        keep_ctx = min(len(ctx), seq_len - len(cont))
        if keep_ctx < 1:
            raise ValueError("need at least one context token")
        row = ctx[len(ctx) - keep_ctx :] + cont
        tokens[i, : len(row)] = row
        mask[i, keep_ctx : len(row)] = 1
        valid[i] = 1
    return tokens, mask, valid


def loglikelihoods(
    model: Transformer,
    params: Any,
    examples: Iterable[Tuple[Sequence[int], Sequence[int]]],
    seq_len: int,
    batch_size: int = 8,
) -> List[dict]:
    """Score every (context, continuation) pair; returns one dict per example
    with ``logprob``, ``tokens``, ``greedy_match``."""
    examples = list(examples)
    out: List[dict] = []
    for start in range(0, len(examples), batch_size):
        chunk = examples[start : start + batch_size]
        pad_n = batch_size - len(chunk)
        padded = chunk + [([0], [0])] * pad_n  # dummy rows, dropped below
        tokens, mask, _ = _pad_batch(padded, seq_len, batch_size)
        res = score_batch(model, params, jnp.asarray(tokens), jnp.asarray(mask))
        for i in range(len(chunk)):
            out.append(
                {
                    "logprob": float(res["logprob"][i]),
                    "tokens": int(res["tokens"][i]),
                    "greedy_match": bool(res["greedy_match"][i]),
                }
            )
    return out
