"""zero_transformer_tpu — a TPU-native LLM pretraining + inference framework.

A ground-up re-design of the capabilities of fattorib/ZeRO-transformer for the
unified jax.Array era: NamedSharding ZeRO-1/2/3 on a device Mesh, a single
fused jit train step, Pallas flash attention, ring-attention context
parallelism, Orbax async checkpointing, and an in-tree JAX inference and eval
path (no CUDA/PyTorch anywhere).
"""

__version__ = "0.1.0"

from zero_transformer_tpu.config import (  # noqa: F401
    CheckpointConfig,
    Config,
    DataConfig,
    MeshConfig,
    ModelConfig,
    OptimizerConfig,
    TrainingConfig,
    load_config,
    model_config,
)
from zero_transformer_tpu.models import Transformer, model_getter  # noqa: F401
