"""Automap-style system autotuner core: a declared knob space, analytic
pre-pruning with auditable reasons, and successive-halving measured search.

Automap (arXiv 2112.02958) showed that search over partitioning/placement
decisions with a cheap cost model recovers expert-tuned performance
automatically; PartIR (arXiv 2401.11202) showed the value of keeping the
strategy space declarative and checkable. This repo already has every
ingredient they had to build — deterministic bench harnesses as the cost
model (``scripts/train_step_bench.py``, ``scripts/serve_loadgen.py``),
config validation + ``analysis.spec_check`` as the validity oracle, and
bitwise parity suites as the correctness gate. This module is the pure
search logic; ``scripts/autotune.py`` wires the measured trials and emits
the committed ``TUNE_<target>.json`` artifacts that ``train.py --tuned``
and ``serve.py --tuned`` load as defaults.

Design rules:

- **knobs are registered, not hardwired**: a new knob joins the search by
  declaring its name, domain, the dotted ``Config`` field it drives, and
  which bench grades it — nothing else;
- **every pruned point records its reason**: the search trace is auditable
  end to end (``enumerated == len(pruned) + len(survivors)``);
- **the validity oracle is the real one**: candidate points are
  constructed through ``config.apply_dotted_overrides``, so the exact
  ``ValueError`` a real run would raise is what prunes an invalid point —
  no measured trial ever runs an invalid config (``spec_check`` fires
  inside ``make_plan`` before any train trial compiles);
- **deterministic mechanics**: enumeration order, prune order, and the
  successive-halving promote rule (stable sort, index tie-break) are pure
  functions of (space, seed, workload) — re-running reproduces the same
  trace structure, and the driver re-runs the whole search to certify the
  same winner.

No device work and no timing in this module.
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

TUNE_SCHEMA_VERSION = 1

# the committed-artifact contract, mirrored by tests/test_autotune.py the
# way tests/test_serve_bench.py pins BENCH_serve.json
TUNE_REQUIRED_KEYS = {
    "metric", "target", "value", "unit", "model", "platform",
    "workload", "workload_hash", "seed", "provenance",
    "space", "pruning", "search", "winner", "baseline", "improvement",
    "determinism", "measured_at_utc", "schema_version",
}


@dataclasses.dataclass(frozen=True)
class Knob:
    """One searchable knob: its domain, the dotted ``Config`` field it
    drives, and which bench grades it."""

    name: str
    values: Tuple[Any, ...]
    field: str  # dotted Config field, e.g. "mesh.overlap_comm"
    subsystem: str  # "train" | "serve"
    bench: str  # "BENCH_step" | "BENCH_serve"
    doc: str = ""

    def __post_init__(self):
        if not self.values:
            raise ValueError(f"knob {self.name!r} has an empty domain")
        if len(set(map(repr, self.values))) != len(self.values):
            raise ValueError(f"knob {self.name!r} has duplicate domain values")
        if "." not in self.field:
            raise ValueError(
                f"knob {self.name!r}: field {self.field!r} must be a dotted "
                "Config path (section.field)"
            )


class KnobSpace:
    """Ordered knob registry; enumeration is the cartesian product in
    registration order (deterministic, so the trace is reproducible)."""

    def __init__(self, target: str):
        if target not in ("train", "serve"):
            raise ValueError(f"invalid target {target!r}")
        self.target = target
        self._knobs: Dict[str, Knob] = {}

    def register(self, knob: Knob) -> "KnobSpace":
        if knob.name in self._knobs:
            raise ValueError(f"knob {knob.name!r} already registered")
        self._knobs[knob.name] = knob
        return self

    @property
    def knobs(self) -> List[Knob]:
        return list(self._knobs.values())

    def __getitem__(self, name: str) -> Knob:
        return self._knobs[name]

    @property
    def size(self) -> int:
        return math.prod(len(k.values) for k in self._knobs.values())

    def points(self) -> List[Dict[str, Any]]:
        """Every point of the space, deterministic order (last-registered
        knob varies fastest)."""
        out: List[Dict[str, Any]] = [{}]
        for knob in self._knobs.values():
            out = [{**p, knob.name: v} for p in out for v in knob.values]
        return out

    def overrides(self, point: Dict[str, Any]) -> Dict[str, Any]:
        """Dotted-field overrides for one point (the form
        ``config.apply_dotted_overrides`` and ``train.py --set`` take)."""
        return {self._knobs[name].field: value for name, value in point.items()}

    def describe(self) -> Dict[str, Any]:
        """Artifact-embeddable description of the registered space."""
        return {
            k.name: {
                "values": list(k.values),
                "field": k.field,
                "bench": k.bench,
                "doc": k.doc,
            }
            for k in self._knobs.values()
        }


def train_space() -> KnobSpace:
    """The training knob space (graded by BENCH_step): comm overlap, ZeRO
    stage, pipeline schedule family, microbatch count, remat."""
    s = KnobSpace("train")
    s.register(Knob("overlap_comm", (False, True), "mesh.overlap_comm",
                    "train", "BENCH_step",
                    "layer-bucketed in-scan ZeRO collectives vs serial"))
    s.register(Knob("zero_stage", (0, 1, 2, 3), "mesh.zero_stage",
                    "train", "BENCH_step",
                    "0=DP, 1=opt shard, 2=+grad scatter, 3=+param shard"))
    s.register(Knob("pipe", (1, 2), "mesh.pipe", "train", "BENCH_step",
                    "pipeline stages"))
    s.register(Knob("pp_schedule", ("gpipe", "1f1b", "interleaved"),
                    "mesh.pp_schedule", "train", "BENCH_step",
                    "pipeline wavefront schedule"))
    s.register(Knob("pp_interleave", (1, 2), "mesh.pp_interleave",
                    "train", "BENCH_step",
                    "virtual stages per rank (interleaved only)"))
    s.register(Knob("accum", (1, 2, 4),
                    "training.gradient_accumulation_steps",
                    "train", "BENCH_step",
                    "microbatch count splitting the workload's FIXED "
                    "global batch (same tokens per optimizer step in "
                    "every arm — a pure perf knob)"))
    s.register(Knob("remat", (False, True), "model.remat",
                    "train", "BENCH_step", "checkpoint each block"))
    s.register(Knob("remat_policy", ("none", "dots"), "model.remat_policy",
                    "train", "BENCH_step", "what the block checkpoint saves"))
    return s


def serve_space() -> KnobSpace:
    """The serving knob space (graded by BENCH_serve): KV layout/paging,
    chunked prefill, speculation, fused sampling tail."""
    s = KnobSpace("serve")
    s.register(Knob("kv_layout", ("paged", "slab"), "serving.kv_layout",
                    "serve", "BENCH_serve",
                    "block-table page pool vs fixed slab rows"))
    s.register(Knob("prefill_chunk", (0, 8, 16), "serving.prefill_chunk",
                    "serve", "BENCH_serve",
                    "prompt tokens prefilled per tick (0 = one-shot)"))
    s.register(Knob("page_size", (4, 8, 16), "serving.page_size",
                    "serve", "BENCH_serve", "tokens per KV page"))
    s.register(Knob("page_pool_tokens", (0, 192),
                    "serving.page_pool_tokens", "serve", "BENCH_serve",
                    "page-pool capacity (0 = slab-equivalent)"))
    s.register(Knob("draft_k", (0, 4), "serving.draft_k",
                    "serve", "BENCH_serve",
                    "speculative draft length per tick (0 = off)"))
    s.register(Knob("fused_tail", (True, False), "serving.fused_tail",
                    "serve", "BENCH_serve",
                    "sampling inside the single jitted decode program"))
    return s


@dataclasses.dataclass(frozen=True)
class PrunedPoint:
    index: int
    knobs: Dict[str, Any]
    rule: str
    reason: str


Validator = Tuple[str, Callable[[Dict[str, Any]], Optional[str]]]


def config_validator(space: KnobSpace, base_cfg) -> Validator:
    """The validity oracle: construct the candidate ``Config`` through the
    SAME dotted-override path ``train.py --set`` uses; the dataclass
    ``__post_init__`` refusal text becomes the prune reason verbatim."""
    from zero_transformer_tpu.config import apply_dotted_overrides

    def check(point: Dict[str, Any]) -> Optional[str]:
        try:
            apply_dotted_overrides(base_cfg, space.overrides(point))
        except ValueError as e:
            return str(e)
        return None

    return ("config_validation", check)


def train_redundancy_validator() -> Validator:
    """Dedup rules: points whose differing knob is inert compile the exact
    same program as a canonical sibling — measuring both would double-count
    the same arm (recorded, never silent)."""

    def check(point: Dict[str, Any]) -> Optional[str]:
        if not point.get("remat") and point.get("remat_policy", "none") != "none":
            return (
                "redundant: remat_policy is inert with remat=False "
                "(identical program to remat_policy='none')"
            )
        if point.get("pipe", 1) == 1 and point.get("pp_interleave", 1) != 1:
            # config validation already rejects schedule mismatches; this
            # catches the inert-interleave-on-gpipe duplicates
            return "redundant: pp_interleave is inert without a pipe axis"
        return None

    return ("redundancy", check)


def train_memory_validator(
    space: KnobSpace, base_cfg, budget_bytes: int, n_devices: int
) -> Validator:
    """Analytic HBM pre-prune: the ``analysis.memory`` stash/bubble/gather
    formulas against a per-device budget — the cheap cost model that keeps
    config points the AOT compiler would reject out of the measured set."""
    from zero_transformer_tpu.analysis.memory import analytic_memory
    from zero_transformer_tpu.config import apply_dotted_overrides

    def check(point: Dict[str, Any]) -> Optional[str]:
        try:
            cfg = apply_dotted_overrides(base_cfg, space.overrides(point))
        except ValueError:
            return None  # config_validation owns invalid points
        est = analytic_memory(cfg, n_devices=n_devices)
        if est["peak_bytes_est"] > budget_bytes:
            return (
                f"analytic peak {est['peak_bytes_est']} B exceeds the "
                f"{budget_bytes} B budget (state "
                f"{est['per_device_state_bytes_est']} B + stash/buffers)"
            )
        return None

    return ("memory_budget", check)


def serve_redundancy_validator() -> Validator:
    def check(point: Dict[str, Any]) -> Optional[str]:
        if point.get("kv_layout") == "slab":
            if point.get("page_size", 4) != 4 or point.get("page_pool_tokens", 0):
                return (
                    "redundant: page_size/page_pool_tokens are inert with "
                    "kv_layout='slab' (identical engine to the canonical "
                    "page_size=4, page_pool_tokens=0 sibling)"
                )
        return None

    return ("redundancy", check)


def serve_feasibility_validator(cache_len: int) -> Validator:
    """Workload-level analytic rules config validation cannot see (it has
    no cache_len): page divisibility of the cache and minimum pool size to
    hold one worst-case stream (admission would wedge, not error)."""

    def check(point: Dict[str, Any]) -> Optional[str]:
        if point.get("kv_layout") != "paged":
            return None
        ps = point.get("page_size", 4)
        if cache_len % ps:
            return (
                f"page_size={ps} does not divide cache_len={cache_len} "
                "(ragged final page; engine refuses)"
            )
        pool = point.get("page_pool_tokens", 0)
        if pool and pool < cache_len + ps:
            return (
                f"page_pool_tokens={pool} cannot hold one worst-case "
                f"stream (cache_len={cache_len}); admission would wait "
                "forever"
            )
        return None

    return ("workload_feasibility", check)


def prune_points(
    points: Sequence[Dict[str, Any]], validators: Sequence[Validator]
) -> Tuple[List[Tuple[int, Dict[str, Any]]], List[PrunedPoint]]:
    """Run every point through the validators in order; the first refusal
    prunes it with (rule, reason) recorded. Returns (survivors, pruned)
    with ``len(survivors) + len(pruned) == len(points)``."""
    survivors: List[Tuple[int, Dict[str, Any]]] = []
    pruned: List[PrunedPoint] = []
    for i, point in enumerate(points):
        for rule, check in validators:
            reason = check(point)
            if reason is not None:
                pruned.append(PrunedPoint(i, dict(point), rule, reason))
                break
        else:
            survivors.append((i, dict(point)))
    return survivors, pruned


def successive_halving(
    arms: Sequence[int],
    measure: Callable[[int, Any, int], Dict[str, Any]],
    budgets: Sequence[Any],
    keep_frac: float = 0.5,
    tie_frac: float = 0.0,
    log: Optional[Callable[[str], None]] = None,
) -> Tuple[int, List[Dict[str, Any]]]:
    """Successive halving over arm ids: cheap short trials gate expensive
    long ones. ``measure(arm_id, budget, rung)`` returns ``{"ok": bool,
    "score": float (lower is better), "metrics": {...}, "error": str?}``.
    Failed arms score ``inf`` and are never promoted. Promotion is a
    stable sort with arm-id tie-break, so identical scores reproduce the
    same trace.

    ``tie_frac``: relative noise floor for the FINAL winner — every arm
    whose last-rung score lands within ``tie_frac`` of the best magnitude
    is a statistical tie with the best, and the winner is the lowest arm
    index among them. Two arms that are really equivalent (e.g. two remat
    policies compiling to near-identical programs, or adjacent ZeRO
    stages on a comm-free box) swap raw order between reruns on noise;
    under this rule both reruns see the same tie set and pick the same
    arm. Promotion rungs rank raw (near-tied arms are simply both
    promoted). 0 = raw winner. Returns (winner_arm_id, rung_trace)."""
    if not arms:
        raise ValueError("successive_halving: no arms survived pruning")
    alive = list(arms)
    rungs: List[Dict[str, Any]] = []
    for rung_i, budget in enumerate(budgets):
        trials = []
        for arm in alive:
            r = measure(arm, budget, rung_i)
            score = r.get("score", float("inf")) if r.get("ok") else float("inf")
            trial = {
                "arm": arm,
                "ok": bool(r.get("ok")),
                "score": None if score == float("inf") else score,
                "metrics": r.get("metrics", {}),
            }
            if r.get("error"):
                trial["error"] = str(r["error"])[:300]
            trials.append(trial)
            if log:
                log(
                    f"rung {rung_i} budget={budget} arm={arm} "
                    f"score={trials[-1]['score']} ok={trials[-1]['ok']}"
                )
        ranked = sorted(
            trials,
            key=lambda t: (
                t["score"] if t["score"] is not None else float("inf"),
                t["arm"],
            ),
        )
        ok_trials = [t for t in ranked if t["ok"]]
        if not ok_trials:
            raise RuntimeError(
                f"successive_halving: every arm failed at rung {rung_i} "
                f"(budget {budget})"
            )
        ok_arms = [t["arm"] for t in ok_trials]
        last = rung_i == len(budgets) - 1
        if last:
            best = ok_trials[0]["score"]
            threshold = best + tie_frac * abs(best)
            tied = [t["arm"] for t in ok_trials if t["score"] <= threshold]
            promoted = [min(tied)]
        else:
            # tie-aware promotion (Hoeffding-race style): an arm within
            # tie_frac of the cut boundary promotes too — membership of
            # the next rung must never be decided by a noise-width margin,
            # or two certification passes diverge on WHICH arms the final
            # tie set even contains
            keep = max(1, math.ceil(len(ok_arms) * keep_frac))
            cutoff = ok_trials[keep - 1]["score"]
            boundary = cutoff + tie_frac * abs(ok_trials[0]["score"])
            promoted = [t["arm"] for t in ok_trials if t["score"] <= boundary]
        rungs.append({
            "rung": rung_i,
            "budget": budget,
            "trials": trials,
            "promoted": promoted,
        })
        alive = promoted
    return alive[0], rungs


def winner_overrides(artifact: Dict[str, Any]) -> Dict[str, Any]:
    """Dotted ``Config`` overrides of a TUNE artifact's winner — what
    ``train.py --tuned`` / ``serve.py --tuned`` apply as defaults. Reads
    the winner's pre-mapped overrides when present, else derives them from
    the embedded space description (knob -> field)."""
    winner = artifact.get("winner") or {}
    if winner.get("overrides"):
        return dict(winner["overrides"])
    space = artifact.get("space") or {}
    out = {}
    for name, value in (winner.get("knobs") or {}).items():
        desc = space.get(name)
        if not desc or "field" not in desc:
            raise ValueError(
                f"TUNE artifact winner knob {name!r} has no field mapping "
                "in the embedded space description"
            )
        out[desc["field"]] = value
    return out


def canonical_json(obj: Any) -> str:
    return json.dumps(obj, sort_keys=True, separators=(",", ":"))


def workload_hash(spec: Dict[str, Any]) -> str:
    """Stable short hash of a workload spec: byte-identical replay across
    arms and runs is part of the artifact's claim, so the hash rides in
    every BENCH/TUNE artifact the spec produced."""
    return hashlib.sha256(canonical_json(spec).encode()).hexdigest()[:16]


def trace_fingerprint(
    target: str,
    model: str,
    wl_hash: str,
    seed: int,
    space_desc: Dict[str, Any],
    pruned: Sequence[PrunedPoint],
    survivors: Sequence[Tuple[int, Dict[str, Any]]],
    budgets: Sequence[Any],
) -> str:
    """Hash of the DETERMINISTIC search structure (enumeration, pruning
    reasons, survivor set, rung budgets) — measured timings excluded. Two
    runs with the same (seed, space, workload) must produce the same
    fingerprint; the driver separately certifies the same winner."""
    payload = {
        "target": target,
        "model": model,
        "workload_hash": wl_hash,
        "seed": seed,
        "space": space_desc,
        "pruned": [
            {"index": p.index, "rule": p.rule, "reason": p.reason}
            for p in pruned
        ],
        "survivors": [i for i, _ in survivors],
        "budgets": list(budgets),
    }
    return hashlib.sha256(canonical_json(payload).encode()).hexdigest()[:16]
