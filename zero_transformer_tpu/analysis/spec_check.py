"""Sharding-spec consistency checker: validate specs against the mesh
BEFORE anything compiles.

The first machine-checked piece of ROADMAP item 1's spec algebra (GSPMD
2105.04663 / PartIR 2401.11202: specs are *checked or derived*, never
hand-trusted). ``parallel.zero.make_plan`` calls ``check_plan`` on every
plan it builds, so a bad rule table or a hand-edited spec fails at plan
time with a precise message instead of surfacing deep inside pjit as an
unrelated sharding error at first dispatch.

Checks, per spec (a ``PartitionSpec`` or the spec of a ``NamedSharding``):

- every axis it names is a declared axis of the mesh;
- no axis shards two different dims of one tensor (XLA rejects this late
  and cryptically);
- with the leaf's shape available: each sharded dim is divisible by the
  product of its axes' sizes (the ZeRO-axis-on-an-indivisible-dim class —
  ``sharding._add_zero_axis`` guarantees this by construction, so a
  violation means a hand-seeded or corrupted plan).

Pure tree walks — no device work, no compilation.
"""
from __future__ import annotations

import math
from typing import Any, Collection, List, Optional

import jax


class SpecError(ValueError):
    """One or more sharding specs disagree with the mesh. ``errors`` holds
    every individual message (the exception text joins them)."""

    def __init__(self, errors: List[str]):
        self.errors = list(errors)
        super().__init__(
            f"{len(self.errors)} sharding-spec inconsistencies:\n  "
            + "\n  ".join(self.errors)
        )


def _spec_of(leaf) -> Optional[tuple]:
    """PartitionSpec entries of a NamedSharding / PartitionSpec leaf."""
    spec = getattr(leaf, "spec", None)  # NamedSharding
    if spec is None and type(leaf).__name__ == "PartitionSpec":
        spec = leaf
    if spec is None:
        return None
    return tuple(spec)


def _axes_of(entry) -> tuple:
    if entry is None:
        return ()
    return tuple(entry) if isinstance(entry, tuple) else (entry,)


def check_entry_spec(
    spec,
    mesh,
    where: str,
    shape: Optional[tuple] = None,
    allow_uneven: Collection[str] = (),
) -> List[str]:
    """Errors for one spec (optionally against a concrete leaf shape).

    ``allow_uneven``: axes permitted to shard a dim unevenly — GSPMD pads
    ragged shards, so raggedness is a *component* limitation, not a spec
    inconsistency, and it arises from honest inputs (an imported 50257
    vocab over ``tensor=2``, a 3-layer stack over ``pipe=2``; components
    that cannot pad own their refusal, e.g. the pipeline's "divisible"
    error in ``make_train_step``). ``make_plan`` keeps ONLY the ZeRO axes
    strict: ``sharding._add_zero_axis`` skips indivisible dims by
    construction, so a ragged ZeRO dim means a hand-seeded or corrupted
    plan."""
    entries = _spec_of(spec)
    if entries is None:
        return []
    errors: List[str] = []
    declared = set(mesh.axis_names)
    seen: dict = {}
    for dim, entry in enumerate(entries):
        for axis in _axes_of(entry):
            if axis not in declared:
                errors.append(
                    f"{where}: dim {dim} names axis {axis!r} which is not "
                    f"a mesh axis (declared: {sorted(declared)})"
                )
                continue
            if axis in seen:
                errors.append(
                    f"{where}: axis {axis!r} shards both dim {seen[axis]} "
                    f"and dim {dim} — an axis may shard at most one dim"
                )
            seen[axis] = dim
    if shape is not None:
        if len(entries) > len(shape):
            errors.append(
                f"{where}: spec has {len(entries)} entries for a rank-"
                f"{len(shape)} leaf"
            )
        for dim, entry in enumerate(entries[: len(shape)]):
            axes = [a for a in _axes_of(entry) if a in declared]
            # a dim is exempt only when EVERY axis on it is allowed-uneven;
            # mixing in one strict (ZeRO) axis re-arms the check for the
            # full world — _add_zero_axis only ever adds the ZeRO axis when
            # the whole product divides, so raggedness on a mixed dim still
            # means a hand-seeded or corrupted spec
            if not axes or all(a in allow_uneven for a in axes):
                continue
            world = math.prod(int(mesh.shape[a]) for a in axes)
            if world > 1 and shape[dim] % world:
                errors.append(
                    f"{where}: dim {dim} of size {shape[dim]} is not "
                    f"divisible by {'x'.join(axes)}={world} — the shard "
                    "would be ragged (the ZeRO-axis-on-indivisible-dim "
                    "class; sharding._add_zero_axis skips such dims, so "
                    "this spec was hand-seeded or corrupted)"
                )
    return errors


def check_tree(
    tree: Any,
    mesh,
    where: str,
    shapes: Any = None,
    allow_uneven: Collection[str] = (),
) -> List[str]:
    """Errors for every NamedSharding/PartitionSpec leaf of ``tree``.
    ``shapes``: matching pytree of shaped leaves (e.g. from eval_shape) to
    enable divisibility checks."""
    errors: List[str] = []
    is_spec = lambda x: _spec_of(x) is not None  # noqa: E731
    leaves = jax.tree_util.tree_leaves_with_path(tree, is_leaf=is_spec)
    shape_leaves = None
    if shapes is not None:
        shape_leaves = jax.tree_util.tree_leaves(shapes)
        if len(shape_leaves) != len(leaves):
            shape_leaves = None  # structure mismatch: shape checks off
    for i, (path, leaf) in enumerate(leaves):
        if _spec_of(leaf) is None:
            continue
        shape = None
        if shape_leaves is not None:
            shape = tuple(getattr(shape_leaves[i], "shape", ()) or ())
            shape = shape or None
        errors += check_entry_spec(
            leaf,
            mesh,
            f"{where}{jax.tree_util.keystr(path)}",
            shape=shape,
            allow_uneven=allow_uneven,
        )
    return errors


def check_plan(
    plan, mesh, abstract_state: Any = None, allow_uneven: Collection[str] = ()
) -> None:
    """Validate a ``parallel.zero.ShardingPlan`` against ``mesh``; raises
    ``SpecError`` listing every inconsistency. ``abstract_state``: matching
    abstract TrainState (eval_shape output) to enable divisibility checks
    on the state specs. ``allow_uneven``: see ``check_entry_spec``."""
    errors: List[str] = []
    errors += check_tree(
        plan.state, mesh, "state", shapes=abstract_state,
        allow_uneven=allow_uneven,
    )
    errors += check_tree(
        plan.zero,
        mesh,
        "zero",
        shapes=getattr(abstract_state, "params", None),
        allow_uneven=allow_uneven,
    )
    errors += check_tree(plan.batch, mesh, "batch")
    if errors:
        raise SpecError(errors)
