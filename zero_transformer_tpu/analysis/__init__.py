"""graftlint: machine-checked enforcement of this repo's hard-won invariants.

Nine PRs accumulated a set of rules that existed only as reviewer folklore:

- donated buffers must pass through ``jax_compat.ensure_donatable`` (the
  jax 0.4.37 zero-copy heap-corruption class fixed in PR 2 and re-fixed in
  PR 5's multihost worker);
- hot loops must not host-sync (PR 2/5's "zero per-step host sync", PR 4/7's
  per-tick dispatch discipline);
- every dispatch site must have a BOUNDED compile family (PR 4/6/8's
  fixed-shape discipline);
- span/trace timestamps ride one monotonic clock (PR 7);
- sharding specs must agree with the mesh they target (ROADMAP item 1, in
  the spirit of GSPMD/PartIR: specs are checked, not hand-trusted).

Each of these has already caused a real bug. This package machine-checks
them in three layers:

- ``static_rules``: a single-pass AST analyzer (pure stdlib — no jax
  import) with repo-specific rules, suppressible only via
  ``# graftlint: allow[rule] reason=...`` comments whose reasons are
  audited (``scripts/graftlint.py --audit``);
- ``spec_check``: a sharding-spec consistency checker that validates every
  ``PartitionSpec`` in a ``ShardingPlan`` against the declared mesh axes
  BEFORE anything compiles (wired into ``parallel.zero.make_plan``);
- ``runtime``: compile-family sanitizers — labeled dispatch sites
  (``bounded_dispatch(name, max_entries)``) count distinct jit cache
  signatures and fail tests when a site exceeds its declared bound.

See docs/ANALYSIS.md for the rule catalog and suppression policy.
"""
from zero_transformer_tpu.analysis.static_rules import (  # noqa: F401
    ALL_RULES,
    Finding,
    analyze_source,
    analyze_file,
    analyze_paths,
    iter_python_files,
)
from zero_transformer_tpu.analysis.runtime import (  # noqa: F401
    CompileFamilyExceeded,
    DispatchSite,
    all_sites,
    bounded_dispatch,
    set_strict,
)

__all__ = [
    "ALL_RULES",
    "Finding",
    "analyze_source",
    "analyze_file",
    "analyze_paths",
    "iter_python_files",
    "CompileFamilyExceeded",
    "DispatchSite",
    "all_sites",
    "bounded_dispatch",
    "set_strict",
]
