"""Analytic memory model: machine-readable stash/bubble/gather-buffer
numbers per config point, with NO compilation and NO device work.

``trainer.memory_analysis`` AOT-compiles the real train step — the ground
truth, but minutes per point and impossible for a backend that cannot
execute the config (this image's jax cannot run the pipe engine).
``analytic_memory`` is the cheap twin the autotuner's pruner calls per
candidate point (``analysis/autotune.py``): pure arithmetic over the
config — parameter/optimizer/gradient tree bytes under the ZeRO stage,
the pipeline activation-stash formulas, the interleaved block-replication
tax, the overlapped-ZeRO gather-buffer residency, and the analytic bubble
fraction. Every number is an ESTIMATE (``"exact": False``) sharing one
formula table with the trainer's ``memory_analysis`` schedule block
(``pp_stash_ticks`` below), so the two surfaces cannot drift.

CLI (the machine-readable surface — a dict, not a pretty-printer):

    python -m zero_transformer_tpu.analysis.memory --cfg configs/train_test.yaml \
        [--set mesh.zero_stage=3 ...] [--accum N] [--devices N] [--json]
"""
from __future__ import annotations

from typing import Any, Dict, Optional

# Optimizer-state tree size as a multiple of the f32 master-param tree.
# adamw: mu + nu; lion: momentum only; adafactor: factored second moments —
# O(rows + cols) per matrix, a few percent of the param bytes at real
# d_model (labeled estimate; the compiled memory_analysis is ground truth).
OPT_TREE_FACTOR = {"adamw": 2.0, "lion": 1.0, "adafactor": 0.05}


def pp_stash_ticks(schedule: str, accum: int, pipe: int, interleave: int) -> int:
    """Activation-stash depth (in microbatch ticks) of each pipeline
    engine's wavefront — the ONE formula table shared by
    ``trainer.memory_analysis`` and the autotuner's pruner. GPipe /
    interleaved: the differentiated tick scan saves its carry once per
    tick; 1F1B: the hand-managed 2P-slot input ring."""
    return {
        "gpipe": accum + pipe - 1,
        "1f1b": 2 * pipe,
        "interleaved": interleave * accum + pipe - 1,
    }[schedule]


def _dtype_bytes(name: str) -> int:
    import jax.numpy as jnp

    from zero_transformer_tpu.config import resolve_dtype

    return jnp.dtype(resolve_dtype(name)).itemsize


def analytic_memory(
    cfg, accum: Optional[int] = None, n_devices: Optional[int] = None
) -> Dict[str, Any]:
    """Analytic per-device memory itemization for one config point.

    ``n_devices``: size of the ZeRO/data axis the state shards over
    (default: ``mesh.data`` when pinned, else the runtime device count
    divided by the model axes). Returns plain ints/floats — the pruner
    compares ``peak_bytes_est`` against an HBM budget and records the
    losing terms in the prune reason."""
    from zero_transformer_tpu.parallel.pipeline import bubble_fraction

    m, mc, t = cfg.model, cfg.mesh, cfg.training
    accum = accum or t.gradient_accumulation_steps
    accum = max(accum, 1)
    model_axes = mc.fsdp * mc.expert * mc.tensor * mc.pipe * mc.sequence
    if n_devices is None:
        if mc.data > 0:
            n_devices = mc.data
        else:
            import jax

            n_devices = max(1, jax.device_count() // max(1, model_axes))
    zero_div = max(1, n_devices)

    param_b = _dtype_bytes(m.param_dtype)
    compute_b = _dtype_bytes(m.compute_dtype)
    accum_b = _dtype_bytes(t.grad_accum_dtype)
    n_params = m.num_params
    params_bytes = n_params * param_b
    embed_params = m.vocab_size * m.d_model * (1 if m.tie_embeddings else 2)
    layer_params = max(1, (n_params - embed_params) // max(1, m.n_layers))

    stage = mc.zero_stage
    per_dev_params = params_bytes // (zero_div if stage >= 3 else 1)
    per_dev_opt = int(
        params_bytes
        * OPT_TREE_FACTOR[cfg.optimizer.optimizer]
        // (zero_div if stage >= 1 else 1)
    )
    per_dev_grads = params_bytes // (zero_div if stage >= 2 else 1)
    # the running accumulation buffer only exists when accumulating
    per_dev_accum = n_params * accum_b if accum > 1 else 0

    act = t.batch_size * t.train_context * m.d_model * compute_b
    batch_bytes = accum * t.batch_size * t.train_context * 4  # int32 tokens

    out: Dict[str, Any] = {
        "exact": False,
        "provenance": "analytic",
        "zero_stage": stage,
        "n_devices": zero_div,
        "accum": accum,
        "optimizer": cfg.optimizer.optimizer,
        "params_bytes_global": params_bytes,
        "per_device_params_bytes": per_dev_params,
        "per_device_opt_state_bytes": per_dev_opt,
        "per_device_grad_bytes": per_dev_grads,
        "grad_accum_buffer_bytes": per_dev_accum,
        "microbatch_activation_bytes": act,
        "batch_bytes": batch_bytes,
        "pp_schedule": mc.pp_schedule,
        "pp_interleave": mc.pp_interleave,
        "overlap_comm": mc.overlap_comm,
        "remat": m.remat,
        "remat_policy": m.remat_policy,
        "bubble_frac": round(
            bubble_fraction(mc.pp_schedule, mc.pipe, accum, mc.pp_interleave), 5
        ),
    }

    stash = act  # the live residual of the current microbatch
    if mc.pipe > 1:
        ticks = pp_stash_ticks(mc.pp_schedule, accum, mc.pipe, mc.pp_interleave)
        out["pp_activation_stash_ticks"] = ticks
        out["pp_activation_stash_bytes_est"] = ticks * act
        stash = ticks * act
        if mc.pp_schedule == "interleaved":
            # interleaved stores the block stack pipe-replicated
            # (sharding.plan_rules): P-1 extra copies vs the contiguous shard
            blocks_bytes = layer_params * m.n_layers * param_b
            out["pp_block_replication_extra_bytes"] = (mc.pipe - 1) * (
                blocks_bytes // mc.pipe
            )
            stash += out["pp_block_replication_extra_bytes"]
    gather_buf = 0
    if mc.overlap_comm and stage >= 1:
        # the bucketed in-scan placement keeps up to two gathered layer
        # buckets live while the layer scan runs (parallel/overlap.py)
        gather_buf = 2 * layer_params * param_b
        out["overlap_gather_buffer_bytes_est"] = gather_buf

    out["per_device_state_bytes_est"] = (
        per_dev_params + per_dev_opt + per_dev_grads + per_dev_accum
    )
    out["peak_bytes_est"] = (
        out["per_device_state_bytes_est"] + stash + gather_buf + batch_bytes
    )
    return out


def main(argv=None) -> None:
    import argparse
    import json

    from zero_transformer_tpu.config import (
        apply_dotted_overrides,
        load_config,
    )

    p = argparse.ArgumentParser(
        description="analytic per-config-point memory itemization (no "
        "compile, no device work; trainer.memory_analysis is the compiled "
        "ground truth)"
    )
    p.add_argument("--cfg", default="configs/train_test.yaml")
    p.add_argument("--set", nargs="*", action="extend", default=None,
                   metavar="KEY=VALUE")
    p.add_argument("--accum", type=int, default=None)
    p.add_argument("--devices", type=int, default=None,
                   help="ZeRO/data axis size (default: mesh.data, else the "
                        "runtime device count over the model axes)")
    p.add_argument("--json", action="store_true",
                   help="one-line JSON to stdout (the machine-readable "
                        "surface; default is one key per line)")
    args = p.parse_args(argv)

    import ast

    overrides = {}
    for pair in args.set or []:
        key, _, raw = pair.partition("=")
        try:
            overrides[key] = ast.literal_eval(raw)
        except (ValueError, SyntaxError):
            overrides[key] = raw
    cfg = apply_dotted_overrides(load_config(args.cfg), overrides)
    report = analytic_memory(cfg, accum=args.accum, n_devices=args.devices)
    if args.json:
        print(json.dumps(report))
    else:
        for k in report:
            print(f"{k} = {report[k]}")


if __name__ == "__main__":
    main()
