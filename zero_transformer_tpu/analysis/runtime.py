"""Runtime compile-family sanitizer: bounded, labeled dispatch sites.

The repo's fixed-shape discipline (PR 4/6/8) says every jit dispatch site
has a BOUNDED family of cache signatures: the engine's fused decode step is
ONE program whatever the occupancy, the chunk prefill is ONE [S, C] program
whatever the prompt mix, the trainer step is ONE program for the whole run.
A regression (a shape that varies per request, a static arg that varies per
tick) silently multiplies compiles and looks like "serving got slow".

``bounded_dispatch(name, max_entries)`` creates a labeled site. The caller
``observe()``s the argument tuple right before each dispatch; the site
abstracts the args the same way jit's cache key does for the purposes we
care about — array leaves become (shape, dtype), hashable scalars keep
their value (static args select executables by value), opaque objects
collapse to their type — and counts DISTINCT signatures. Exceeding
``max_entries``:

- in strict mode (tests: ``set_strict(True)``, or env
  ``GRAFTLINT_DISPATCH=strict``): raises ``CompileFamilyExceeded`` listing
  every signature the site has seen, so the offending axis of variation is
  readable straight from the failure;
- otherwise: increments ``site.violations`` and warns ONCE per site —
  production serving must not die on an observability check.

No jax import: array leaves are duck-typed on ``.shape``/``.dtype``, so the
module stays importable from the stdlib-only lint path.
"""
from __future__ import annotations

import dataclasses
import os
import threading
import warnings
import weakref
from typing import Any, Dict, List, Optional, Tuple

_registry_lock = threading.Lock()
_registry: "weakref.WeakSet[DispatchSite]" = weakref.WeakSet()
_strict: Optional[bool] = None


def set_strict(value: Optional[bool]) -> None:
    """Force strict mode on/off process-wide (None: defer to the
    GRAFTLINT_DISPATCH env var). Tests flip this on so a family overflow
    fails the suite instead of warning."""
    global _strict
    _strict = value


def _is_strict() -> bool:
    if _strict is not None:
        return _strict
    return os.environ.get("GRAFTLINT_DISPATCH", "") == "strict"


class CompileFamilyExceeded(RuntimeError):
    """A labeled dispatch site saw more distinct jit signatures than its
    declared bound — some argument axis varies per call that should be
    fixed-shape (or the bound is honestly wrong and must be raised WITH the
    reasoning in the call site's comment)."""

    def __init__(self, site: "DispatchSite", fresh: Tuple):
        self.site = site
        self.fresh = fresh
        lines = [
            f"dispatch site {site.name!r} exceeded its compile-family bound: "
            f"{len(site.signatures)} distinct signatures > max_entries="
            f"{site.max_entries}. Signatures seen (count x):"
        ]
        for sig, n in site.signatures.items():
            marker = "  -> NEW: " if sig == fresh else "     "
            lines.append(f"{marker}{n}x {sig}")
        super().__init__("\n".join(lines))


def _describe(x: Any, depth: int = 0) -> Any:
    """Abstract one argument into a hashable signature component, the way
    jit's cache key would distinguish it: arrays by (shape, dtype) — their
    VALUES never select an executable — scalars/strings by value (static
    args select by value), containers structurally, opaque objects by type
    (a rebuilt-but-identical model object must not look like a new
    signature)."""
    if depth > 6:
        return "..."
    shape = getattr(x, "shape", None)
    dtype = getattr(x, "dtype", None)
    if shape is not None and dtype is not None:
        return ("arr", tuple(shape), str(dtype))
    if x is None or isinstance(x, (bool, int, float, str, bytes)):
        return x
    if isinstance(x, (tuple, list)):
        return (type(x).__name__,) + tuple(_describe(e, depth + 1) for e in x)
    if isinstance(x, dict) or (
        not isinstance(x, type) and callable(getattr(x, "items", None))
    ):
        # dicts AND dict-like mappings (flax FrozenDict) — leaf shapes in
        # these ARE jit's cache key
        try:
            items = sorted(x.items())
        except TypeError:
            items = list(x.items())
        return ("dict",) + tuple(
            (str(k), _describe(v, depth + 1)) for k, v in items
        )
    if dataclasses.is_dataclass(x) and not isinstance(x, type):
        # registered pytree containers (flax.struct dataclasses like
        # TrainState) — collapsing these to their type would blind the
        # site to the very shapes that select the executable
        return (type(x).__name__,) + tuple(
            (f.name, _describe(getattr(x, f.name), depth + 1))
            for f in dataclasses.fields(x)
        )
    return ("obj", type(x).__name__)


class DispatchSite:
    """One labeled jit dispatch site with a declared signature bound.

    Thread-safe; cheap on the hot path (one tuple build + dict lookup; the
    describe walk touches only arg metadata, never array bytes)."""

    def __init__(self, name: str, max_entries: int):
        if max_entries < 1:
            raise ValueError("max_entries must be >= 1")
        self.name = name
        self.max_entries = int(max_entries)
        self.signatures: Dict[Tuple, int] = {}
        self.violations = 0
        self._warned = False
        self._lock = threading.Lock()
        with _registry_lock:
            _registry.add(self)

    def observe(self, *args: Any, **kwargs: Any) -> None:
        """Record the signature of one dispatch. Call with the arguments
        the jitted callable is about to receive; engine-lifetime-constant
        trees (the model object, the params tree) may be omitted so the
        per-call describe walk stays O(varying args), not O(param count)."""
        sig = _describe(args) + (
            _describe(tuple(sorted(kwargs.items(), key=lambda kv: kv[0])))
            if kwargs
            else ()
        )
        with self._lock:
            count = self.signatures.get(sig)
            self.signatures[sig] = (count or 0) + 1
            if count is None and len(self.signatures) > self.max_entries:
                self.violations += 1
                if _is_strict():
                    raise CompileFamilyExceeded(self, sig)
                if not self._warned:
                    self._warned = True
                    warnings.warn(
                        f"graftlint: dispatch site {self.name!r} exceeded "
                        f"its compile-family bound ({len(self.signatures)} > "
                        f"{self.max_entries}) — shapes/statics vary per call "
                        "at a site declared fixed-shape",
                        stacklevel=2,
                    )

    def wrap(self, fn):
        """Return ``fn`` instrumented with this site (convenience for
        callables invoked directly rather than through ``_in_mesh``)."""

        def wrapped(*args, **kwargs):
            self.observe(*args, **kwargs)
            return fn(*args, **kwargs)

        wrapped.__wrapped__ = fn
        wrapped.dispatch_site = self
        return wrapped

    @property
    def distinct(self) -> int:
        return len(self.signatures)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "name": self.name,
                "max_entries": self.max_entries,
                "distinct": len(self.signatures),
                "calls": sum(self.signatures.values()),
                "violations": self.violations,
            }

    def reset(self) -> None:
        with self._lock:
            self.signatures.clear()
            self.violations = 0
            self._warned = False


def bounded_dispatch(name: str, max_entries: int) -> DispatchSite:
    """Create and register a labeled dispatch site (one per engine/trainer
    INSTANCE: the bound is about one logical site not churning compiles,
    and test processes legitimately build many differently-shaped
    engines)."""
    return DispatchSite(name, max_entries)


def all_sites() -> List[DispatchSite]:
    """Live sites, for test assertions and /metrics exports."""
    with _registry_lock:
        return sorted(_registry, key=lambda s: s.name)
