"""Single-pass AST rules enforcing the repo's hard-won invariants.

Pure stdlib (``ast`` + ``tokenize``) — importable and runnable without jax,
so the lint lane costs seconds, not a backend init. Loaded directly by file
path from ``scripts/graftlint.py`` to keep even the package ``__init__``
chain (which pulls flax/jax) out of the lint process.

Rules (catalog + rationale in docs/ANALYSIS.md):

- ``donation-safety``: values that flow from ``jax.device_put`` / orbax
  restores into a DONATED argument position — or out of a function as a
  return value callers may donate — without passing through
  ``jax_compat.ensure_donatable``. On jax 0.4.37 CPU a donated zero-copy
  host view lets XLA recycle memory it never owned (glibc heap corruption;
  PR 2's bug class, re-fixed in PR 5).
- ``host-sync-in-hot-path``: ``.item()``, ``jax.device_get``,
  ``block_until_ready``, ``np.asarray`` of device values, and
  ``float()/int()/bool()`` of device values inside functions marked
  ``# graftlint: hot-path`` (engine tick, train loop, span append).
- ``wall-clock-in-span-path``: ``time.time()`` anywhere in scanned code —
  span/trace timestamps must ride ONE monotonic clock; genuinely-wall-clock
  uses carry an audited suppression.
- ``broad-except-in-supervised-seam``: bare / ``Exception`` /
  ``BaseException`` handlers inside functions marked
  ``# graftlint: supervised-seam`` that neither re-raise nor hand the
  exception to a fault classifier — they would swallow the supervisor's
  retryable-vs-fatal classification.
- ``lock-held-device-sync``: blocking device ops (the host-sync set) inside
  any ``with ...lock...:`` body — a device sync under the engine lock
  stalls every submit/scrape for the sync's duration.
- ``swallowed-except-in-control-plane``: in resilience / fleet
  control-plane files (``resilience/``, ``training/fleet``,
  ``serving/router``, the coordinator/worker/router scripts), any bare
  ``except:``, and any ``except Exception/BaseException:`` whose body is
  only ``pass``/``...``/``continue``. The control plane's whole job is
  turning failures into decisions; a swallowed exception there converts a
  worker death or probe failure into silence — the one failure mode the
  fleet cannot recover from, because it never learns anything happened.
- ``sharding-spec``: ``PartitionSpec``/``P`` literals naming axes that are
  not declared mesh axes, or repeating an axis within one spec (the static
  half of ``analysis.spec_check``).

Suppression: ``# graftlint: allow[rule] reason=...`` on the offending line
or the line directly above. A missing/empty reason is itself a finding
(``suppression-missing-reason``), as is an allow that matched nothing
(``unused-suppression``) — the audit trail stays honest.

Markers:

- ``# graftlint: hot-path`` on/above a ``def``: the function (and its
  nested functions) is a no-host-sync region;
- ``# graftlint: supervised-seam`` on/above a ``def``: broad excepts inside
  must classify, not swallow;
- ``# graftlint: donates[i,j,...]`` on an assignment or ``def``: declares
  the bound callable as donating those positional argument indices (for
  jitted callables whose ``donate_argnums`` the analyzer cannot see through
  an indirection).
"""
from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

ALL_RULES = (
    "donation-safety",
    "host-sync-in-hot-path",
    "wall-clock-in-span-path",
    "broad-except-in-supervised-seam",
    "lock-held-device-sync",
    "sharding-spec",
    "swallowed-except-in-control-plane",
)

# path fragments that put a file in scope for the control-plane except rule:
# the resilience layer and the fleet control planes (training coordinator +
# serving router), where a swallowed exception silently disables recovery
CONTROL_PLANE_PATH_PARTS = (
    "resilience/",
    "training/fleet",
    "serving/router",
    "scripts/train_coordinator",
    "scripts/train_fleet_worker",
    "scripts/serve_router",
)
# meta-rules guard the audit trail itself and are NOT suppressible
META_RULES = ("suppression-missing-reason", "unused-suppression", "parse-error")

# declared mesh axes (parallel/mesh.py is the source of truth; the CLI
# re-derives this set from its AST so a renamed axis cannot silently stale
# the linter — see refresh_mesh_axes)
MESH_AXES: Set[str] = {"data", "fsdp", "expert", "tensor", "sequence", "pipe"}

# taint sources: calls whose result may be a zero-copy host view the XLA
# runtime does not own (device_put from host numpy; orbax/msgpack restores).
# checkpoint.CheckpointManager.restore/restore_verified/restore_params are
# NOT here: they seal through ensure_donatable at the source (pinned by
# tests/test_graftlint.py::test_checkpoint_restores_are_sealed) — raw orbax
# ``.restore`` calls remain tainted.
_TAINT_LAST = {
    "device_put",
    "restore",
    "partial_restore",
    "import_params_msgpack",
    "from_bytes",
}
# calls that launder taint: the result is a freshly allocated runtime-owned
# buffer whatever went in
_CLEANER_LAST = {"ensure_donatable"}

# known donating entry points that per-module analysis cannot see through
# (jitted elsewhere / behind an attribute swap): last path segment ->
# donated positional indices. Extend in-source with # graftlint: donates[..]
KNOWN_DONATING: Dict[str, Tuple[int, ...]] = {
    "train_step": (0, 3),
    "step_fn": (0, 3),
    "prefill": (3,),
}

_SYNC_NP = {"np.asarray", "np.array", "numpy.asarray", "numpy.array"}

_DIRECTIVE_RE = re.compile(r"graftlint:\s*(.*)$")
_ALLOW_RE = re.compile(r"allow\[([^\]]*)\]\s*(?:reason=(.*))?$")
_DONATES_RE = re.compile(r"donates\[([^\]]*)\]")


@dataclasses.dataclass
class Finding:
    rule: str
    path: str
    line: int
    col: int
    message: str
    suppressed: bool = False
    reason: str = ""

    def format(self) -> str:
        tag = f" [suppressed: {self.reason}]" if self.suppressed else ""
        return f"{self.path}:{self.line}:{self.col}: {self.rule}: {self.message}{tag}"


@dataclasses.dataclass
class _Suppression:
    line: int
    rules: Tuple[str, ...]
    reason: str
    used: bool = False


def _dotted(node: ast.AST) -> str:
    """Dotted source name of a Name/Attribute chain ('' when dynamic)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def _last(name: str) -> str:
    return name.rsplit(".", 1)[-1]


def _root_name(node: ast.AST) -> Optional[str]:
    """Leftmost Name of a Name/Attribute/Subscript/Call chain."""
    while isinstance(node, (ast.Attribute, ast.Subscript, ast.Call, ast.Starred)):
        node = (
            node.func
            if isinstance(node, ast.Call)
            else getattr(node, "value", None)
        )
        if node is None:
            return None
    return node.id if isinstance(node, ast.Name) else None


class _Module:
    """One parsed file: AST + comments resolved into suppressions/markers."""

    def __init__(self, path: str, src: str):
        self.path = path
        self.src = src
        self.tree = ast.parse(src, filename=path)
        self.suppressions: Dict[int, _Suppression] = {}
        self.hot_lines: Set[int] = set()
        self.seam_lines: Set[int] = set()
        self.donates_lines: Dict[int, Tuple[int, ...]] = {}
        self.meta_findings: List[Finding] = []
        self._scan_comments()
        self.hot_funcs = self._mark_funcs(self.hot_lines)
        self.seam_funcs = self._mark_funcs(self.seam_lines)
        self.donating = dict(KNOWN_DONATING)
        self._collect_donating()

    # -- comments ----------------------------------------------------------

    def _scan_comments(self) -> None:
        try:
            tokens = tokenize.generate_tokens(io.StringIO(self.src).readline)
            comments = [
                (t.start[0], t.string) for t in tokens if t.type == tokenize.COMMENT
            ]
        except (tokenize.TokenError, IndentationError):  # pragma: no cover
            comments = []
        for line, text in comments:
            m = _DIRECTIVE_RE.search(text)
            if not m:
                continue
            body = m.group(1).strip()
            if body == "hot-path":
                self.hot_lines.add(line)
            elif body == "supervised-seam":
                self.seam_lines.add(line)
            elif body.startswith("donates["):
                dm = _DONATES_RE.match(body)
                if dm:
                    try:
                        idx = tuple(
                            int(p) for p in dm.group(1).split(",") if p.strip()
                        )
                    except ValueError:
                        idx = ()
                    self.donates_lines[line] = idx
            elif body.startswith("allow["):
                am = _ALLOW_RE.match(body)
                if am is None:
                    continue
                rules = tuple(
                    r.strip() for r in am.group(1).split(",") if r.strip()
                )
                reason = (am.group(2) or "").strip()
                self.suppressions[line] = _Suppression(line, rules, reason)
                if not reason:
                    self.meta_findings.append(
                        Finding(
                            "suppression-missing-reason",
                            self.path,
                            line,
                            0,
                            f"allow[{','.join(rules)}] without a reason= — "
                            "every suppression must say WHY the invariant "
                            "does not apply here",
                        )
                    )

    def _mark_funcs(self, lines: Set[int]) -> List[ast.AST]:
        """Resolve marker comment lines to the function defs they annotate:
        the marker sits on the ``def`` line itself or up to 2 lines above
        (decorators included)."""
        out = []
        for node in ast.walk(self.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            first = min(
                [node.lineno] + [d.lineno for d in node.decorator_list]
            )
            if any(ln in lines for ln in range(first - 2, node.lineno + 1)):
                out.append(node)
        return out

    def _collect_donating(self) -> None:
        """Find donating callables: jit/pjit calls with a literal
        ``donate_argnums`` bound to a name, defs decorated with one, and
        explicit ``# graftlint: donates[...]`` markers."""
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                target = _last(_dotted(node.targets[0]))
                if not target:
                    continue
                idx = self._donate_argnums(node.value)
                if idx is None:
                    idx = self._marker_for(node.lineno)
                if idx:
                    self.donating[target] = idx
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                idx: Optional[Tuple[int, ...]] = None
                for dec in node.decorator_list:
                    idx = idx or self._donate_argnums(dec)
                first = min(
                    [node.lineno] + [d.lineno for d in node.decorator_list]
                )
                if idx is None:
                    for ln in range(first - 2, node.lineno + 1):
                        if ln in self.donates_lines:
                            idx = self.donates_lines[ln]
                            break
                if idx:
                    self.donating[node.name] = idx

    def _marker_for(self, lineno: int) -> Optional[Tuple[int, ...]]:
        for ln in (lineno, lineno - 1):
            if ln in self.donates_lines:
                return self.donates_lines[ln]
        return None

    @staticmethod
    def _donate_argnums(node: ast.AST) -> Optional[Tuple[int, ...]]:
        """Literal donate_argnums of a jit/pjit/partial(jit, ...) call."""
        if not isinstance(node, ast.Call):
            return None
        name = _last(_dotted(node.func))
        if name == "partial":
            inner = node.args[0] if node.args else None
            if inner is None or _last(_dotted(inner)) not in ("jit", "pjit"):
                return None
        elif name not in ("jit", "pjit"):
            return None
        for kw in node.keywords:
            if kw.arg in ("donate_argnums", "donate_argnames"):
                try:
                    val = ast.literal_eval(kw.value)
                except (ValueError, SyntaxError):
                    return ()
                if isinstance(val, int):
                    return (val,)
                if isinstance(val, (tuple, list)):
                    return tuple(v for v in val if isinstance(v, int))
                return ()
        return None

    # -- suppression application ------------------------------------------

    def suppress(self, finding: Finding) -> Finding:
        for ln in (finding.line, finding.line - 1):
            sup = self.suppressions.get(ln)
            if sup and finding.rule in sup.rules and sup.reason:
                sup.used = True
                finding.suppressed = True
                finding.reason = sup.reason
                return finding
        return finding


# ---------------------------------------------------------------------------
# scope helpers


def _functions(tree: ast.AST) -> List[ast.AST]:
    return [
        n
        for n in ast.walk(tree)
        if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
    ]


def _in_any(node_lines: Tuple[int, int], funcs: Iterable[ast.AST]) -> bool:
    lo, hi = node_lines
    for f in funcs:
        if f.lineno <= lo and (f.end_lineno or f.lineno) >= hi:
            return True
    return False


def _host_names(func: ast.AST) -> Set[str]:
    """Names bound (anywhere in ``func``) to values that are host-side by
    construction: ``jax.device_get`` results (tuple unpacks included),
    ``.tolist()``, numpy constructors, literals, ``len``/``sorted``/...
    Order-insensitive — good enough for flag/no-flag decisions."""
    host: Set[str] = set()
    HOST_CALLS = {
        "device_get",
        "tolist",
        "len",
        "sorted",
        "list",
        "dict",
        "range",
        "int",
        "float",
        "bool",
        "str",
        "min",
        "max",
        "sum",
        "enumerate",
        "zip",
        "monotonic",
        "now",
        "time",
        "perf_counter",
    }
    for node in ast.walk(func):
        if not isinstance(node, ast.Assign):
            continue
        val = node.value
        is_host = False
        if isinstance(val, ast.Call):
            name = _dotted(val.func)
            last = _last(name)
            is_host = last in HOST_CALLS or name.startswith(("np.", "numpy."))
        elif isinstance(val, ast.Constant):
            is_host = True
        if not is_host:
            continue
        for target in node.targets:
            if isinstance(target, ast.Name):
                host.add(target.id)
            elif isinstance(target, (ast.Tuple, ast.List)):
                for el in target.elts:
                    if isinstance(el, ast.Name):
                        host.add(el.id)
    return host


def _sync_calls(
    body: Iterable[ast.AST], host: Set[str]
) -> List[Tuple[ast.Call, str]]:
    """Device-synchronizing calls in ``body``: (node, description)."""
    out: List[Tuple[ast.Call, str]] = []
    for node in body:
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            name = _dotted(sub.func)
            last = _last(name)
            if last == "item" and isinstance(sub.func, ast.Attribute):
                out.append((sub, ".item() forces a device->host sync"))
            elif last == "block_until_ready":
                out.append((sub, "block_until_ready() blocks on the device"))
            elif name in ("jax.device_get", "device_get"):
                out.append((sub, "jax.device_get forces a device->host sync"))
            elif name in _SYNC_NP and sub.args:
                root = _root_name(sub.args[0])
                if root is None or root not in host:
                    out.append(
                        (sub, f"{name}() of a possibly-device value copies "
                              "through host")
                    )
            elif (
                isinstance(sub.func, ast.Name)
                and sub.func.id in ("float", "int", "bool")
                and len(sub.args) == 1
                and isinstance(sub.args[0], (ast.Subscript, ast.Attribute))
            ):
                root = _root_name(sub.args[0])
                if root is not None and root not in host and root != "self":
                    out.append(
                        (sub, f"{sub.func.id}() of {_dotted(sub.args[0]) or root}"
                              " syncs if it holds a device array")
                    )
    return out


# ---------------------------------------------------------------------------
# rules


def _rule_wall_clock(mod: _Module) -> List[Finding]:
    out = []
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Call) and _dotted(node.func) == "time.time":
            out.append(
                Finding(
                    "wall-clock-in-span-path",
                    mod.path,
                    node.lineno,
                    node.col_offset,
                    "time.time() is not monotonic — span/trace timestamps "
                    "must use time.monotonic() (suppress only for genuinely "
                    "wall-clock metadata)",
                )
            )
    return out


def _rule_host_sync(mod: _Module) -> List[Finding]:
    out = []
    for func in mod.hot_funcs:
        host = _host_names(func)
        for call, why in _sync_calls([func], host):
            out.append(
                Finding(
                    "host-sync-in-hot-path",
                    mod.path,
                    call.lineno,
                    call.col_offset,
                    f"{why} inside hot path {func.name!r} — hot loops must "
                    "not host-sync (keep the one designed sync point, "
                    "suppressed with a reason)",
                )
            )
    return out


def _rule_lock_sync(mod: _Module) -> List[Finding]:
    out = []
    funcs = _functions(mod.tree)
    for node in ast.walk(mod.tree):
        if not isinstance(node, (ast.With, ast.AsyncWith)):
            continue
        lockish = any(
            "lock" in _dotted(item.context_expr).lower()
            or (
                isinstance(item.context_expr, ast.Call)
                and "lock" in _dotted(item.context_expr.func).lower()
            )
            for item in node.items
        )
        if not lockish:
            continue
        # host-name context of the smallest enclosing function
        enclosing = [
            f
            for f in funcs
            if f.lineno <= node.lineno
            and (f.end_lineno or f.lineno) >= (node.end_lineno or node.lineno)
        ]
        host = (
            _host_names(min(enclosing, key=lambda f: (f.end_lineno or 0) - f.lineno))
            if enclosing
            else set()
        )
        for call, why in _sync_calls(node.body, host):
            out.append(
                Finding(
                    "lock-held-device-sync",
                    mod.path,
                    call.lineno,
                    call.col_offset,
                    f"{why} while holding a lock — device syncs under the "
                    "engine lock stall every submit/scrape for their "
                    "duration",
                )
            )
    return out


def _rule_broad_except(mod: _Module) -> List[Finding]:
    CLASSIFIERS = re.compile(
        r"(classify|fault|escalate|_abort|_fail|_finish|retryable)", re.I
    )
    out = []
    for func in mod.seam_funcs:
        for node in ast.walk(func):
            if not isinstance(node, ast.ExceptHandler):
                continue
            broad = node.type is None or _last(_dotted(node.type)) in (
                "Exception",
                "BaseException",
            )
            if not broad:
                continue
            handled = False
            for sub in ast.walk(node):
                if isinstance(sub, ast.Raise):
                    handled = True
                    break
                if isinstance(sub, ast.Call) and CLASSIFIERS.search(
                    _dotted(sub.func)
                ):
                    handled = True
                    break
            if not handled:
                out.append(
                    Finding(
                        "broad-except-in-supervised-seam",
                        mod.path,
                        node.lineno,
                        node.col_offset,
                        f"broad except in supervised seam {func.name!r} "
                        "neither re-raises nor classifies — it would swallow "
                        "the supervisor's retryable-vs-fatal decision",
                    )
                )
    return out


def _rule_control_plane_except(mod: _Module) -> List[Finding]:
    """Bare ``except:`` / swallow-only broad excepts in control-plane files.

    Unlike ``broad-except-in-supervised-seam`` (opt-in via marker, requires
    classification), this rule is PATH-scoped and catches the two shapes
    that are never right in a control plane: catching everything with no
    type at all, and catching ``Exception``/``BaseException`` only to
    discard it. A broad except that logs, re-raises, or acts is fine here —
    control loops legitimately outlive individual failures, but they must
    OBSERVE them."""
    norm = mod.path.replace("\\", "/")
    if not any(part in norm for part in CONTROL_PLANE_PATH_PARTS):
        return []
    out = []
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            out.append(
                Finding(
                    "swallowed-except-in-control-plane",
                    mod.path,
                    node.lineno,
                    node.col_offset,
                    "bare 'except:' in a resilience/fleet control-plane "
                    "path — it catches SystemExit/KeyboardInterrupt too, "
                    "and hides which failures the handler was written for",
                )
            )
            continue
        if _last(_dotted(node.type)) not in ("Exception", "BaseException"):
            continue
        swallow = all(
            isinstance(stmt, (ast.Pass, ast.Continue))
            or (
                isinstance(stmt, ast.Expr)
                and isinstance(stmt.value, ast.Constant)
                and stmt.value.value is Ellipsis
            )
            for stmt in node.body
        )
        if swallow:
            out.append(
                Finding(
                    "swallowed-except-in-control-plane",
                    mod.path,
                    node.lineno,
                    node.col_offset,
                    "'except "
                    f"{_last(_dotted(node.type))}: pass' in a control-plane "
                    "path swallows the failure the control plane exists to "
                    "react to — log it, classify it, or re-raise it",
                )
            )
    return out


def _local_mesh_axes(mod: _Module) -> Set[str]:
    """Axis names a module declares on its OWN ``Mesh(...)`` constructions
    (probe/test meshes, e.g. pod_check's 1-D ``("all",)`` mesh) — legal for
    that module's specs in addition to the repo's declared axes."""
    axes: Set[str] = set()
    for node in ast.walk(mod.tree):
        if not (
            isinstance(node, ast.Call) and _last(_dotted(node.func)) == "Mesh"
        ):
            continue
        candidates = list(node.args) + [
            kw.value for kw in node.keywords if kw.arg == "axis_names"
        ]
        for arg in candidates:
            if isinstance(arg, (ast.Tuple, ast.List)):
                for el in arg.elts:
                    if isinstance(el, ast.Constant) and isinstance(
                        el.value, str
                    ):
                        axes.add(el.value)
            elif isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                axes.add(arg.value)
    return axes


def _rule_sharding_spec(mod: _Module, axes: Set[str]) -> List[Finding]:
    out = []
    axes = set(axes) | _local_mesh_axes(mod)
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        if _last(_dotted(node.func)) not in ("P", "PartitionSpec"):
            continue
        seen: Dict[str, int] = {}
        literals: List[Tuple[str, ast.AST]] = []
        for arg in node.args:
            if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                literals.append((arg.value, arg))
            elif isinstance(arg, (ast.Tuple, ast.List)):
                for el in arg.elts:
                    if isinstance(el, ast.Constant) and isinstance(
                        el.value, str
                    ):
                        literals.append((el.value, el))
        for name, where in literals:
            if name not in axes:
                out.append(
                    Finding(
                        "sharding-spec",
                        mod.path,
                        where.lineno,
                        where.col_offset,
                        f"PartitionSpec names axis {name!r} which is not a "
                        f"declared mesh axis {sorted(axes)}",
                    )
                )
            count = seen.get(name, 0) + 1
            seen[name] = count
            if count == 2:
                out.append(
                    Finding(
                        "sharding-spec",
                        mod.path,
                        where.lineno,
                        where.col_offset,
                        f"PartitionSpec uses axis {name!r} twice — an axis "
                        "may shard at most one dim of a tensor",
                    )
                )
    return out


class _TaintScope:
    """Per-function donation-safety walk (statement order respected)."""

    def __init__(self, mod: _Module, func: ast.AST, findings: List[Finding]):
        self.mod = mod
        self.func = func
        self.findings = findings
        self.tainted: Set[str] = set()
        # nested defs whose returns are tainted: their NAME becomes a taint
        # source in the enclosing scope (the encloser may still apply the
        # ensure_donatable seam around e.g. a tree_map over the callback)
        self.tainted_funcs: Set[str] = set()
        self._nesting = 0

    # -- expression classification ----------------------------------------

    def _expr_taints(self, node: ast.AST) -> bool:
        """Does evaluating ``node`` produce a possibly-runtime-unowned
        buffer? Cleaner calls launder everything beneath them."""
        if isinstance(node, ast.Call):
            name = _last(_dotted(node.func))
            if name in _CLEANER_LAST:
                return False
            if name in ("float", "int", "bool", "str", "len", "repr"):
                return False  # host scalars carry no buffer to donate
            if name in _TAINT_LAST:
                return True
            # a call propagates taint from its arguments (tree.map etc.)
            return any(
                self._expr_taints(a)
                for a in list(node.args)
                + [kw.value for kw in node.keywords]
            )
        if isinstance(node, ast.Name):
            return node.id in self.tainted or node.id in self.tainted_funcs
        if isinstance(node, ast.Attribute):
            return _dotted(node) in self.tainted
        if isinstance(node, (ast.Tuple, ast.List)):
            return any(self._expr_taints(e) for e in node.elts)
        if isinstance(node, (ast.Subscript, ast.Starred)):
            return self._expr_taints(node.value)
        if isinstance(node, ast.IfExp):
            return self._expr_taints(node.body) or self._expr_taints(node.orelse)
        if isinstance(node, ast.Lambda):
            return self._expr_taints(node.body)
        return False

    # -- statement walk (source order: taint/clean must sequence) ----------

    def run(self) -> None:
        self._stmts(self.func.body)

    def _scan_calls(self, node: ast.AST) -> None:
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call):
                self._call(sub)

    def _stmts(self, body: List[ast.stmt]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                # nested def: same taint scope (closures see outer names).
                # Its tainted returns don't fire directly — they mark the
                # function NAME tainted, and findings arise where the
                # encloser lets the product escape unsealed.
                outer, self.func = self.func, stmt
                self._nesting += 1
                self._stmts(stmt.body)
                self._nesting -= 1
                self.func = outer
                continue
            if isinstance(stmt, (ast.If, ast.While)):
                self._scan_calls(stmt.test)
                self._stmts(stmt.body)
                self._stmts(stmt.orelse)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                self._scan_calls(stmt.iter)
                self._stmts(stmt.body)
                self._stmts(stmt.orelse)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    self._scan_calls(item.context_expr)
                self._stmts(stmt.body)
            elif isinstance(stmt, ast.Try):
                self._stmts(stmt.body)
                for h in stmt.handlers:
                    self._stmts(h.body)
                self._stmts(stmt.orelse)
                self._stmts(stmt.finalbody)
            else:
                self._scan_calls(stmt)
                if isinstance(stmt, ast.Assign):
                    self._assign(stmt.targets, stmt.value)
                elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
                    self._assign([stmt.target], stmt.value)
                elif isinstance(stmt, ast.AugAssign):
                    if self._expr_taints(stmt.value):
                        name = _dotted(stmt.target)
                        if name:
                            self.tainted.add(name)
                elif isinstance(stmt, ast.Return) and stmt.value is not None:
                    self._return(stmt)

    def _assign(self, targets: List[ast.AST], value: ast.AST) -> None:
        taints = self._expr_taints(value)
        for target in targets:
            names = (
                [e for e in target.elts]
                if isinstance(target, (ast.Tuple, ast.List))
                else [target]
            )
            for n in names:
                name = _dotted(n) if isinstance(n, (ast.Name, ast.Attribute)) else ""
                if not name:
                    continue
                if taints:
                    self.tainted.add(name)
                else:
                    self.tainted.discard(name)

    def _return(self, stmt: ast.Return) -> None:
        if not self._expr_taints(stmt.value):
            return
        if self._nesting > 0:
            self.tainted_funcs.add(self.func.name)
            return
        self.findings.append(
            Finding(
                "donation-safety",
                self.mod.path,
                stmt.lineno,
                stmt.col_offset,
                f"{self.func.name!r} returns buffers that flow from "
                "device_put/checkpoint restore — a caller that donates "
                "them corrupts the heap on jax 0.4.37; route through "
                "jax_compat.ensure_donatable (or suppress with the "
                "reason the result is never donated)",
            )
        )

    def _call(self, call: ast.Call) -> None:
        name = _dotted(call.func)
        last = _last(name)
        args = list(call.args)
        if last == "_in_mesh" and len(args) >= 2:
            # _in_mesh(mesh, fn, *real_args): the callee is args[1]
            last = _last(_dotted(args[1]))
            args = args[2:]
        donated = self.mod.donating.get(last)
        if not donated:
            return
        for i in donated:
            if i < len(args) and self._expr_taints(args[i]):
                src = _dotted(args[i]) or ast.dump(args[i])[:40]
                self.findings.append(
                    Finding(
                        "donation-safety",
                        self.mod.path,
                        call.lineno,
                        call.col_offset,
                        f"argument {i} ({src}) of donating call {last!r} "
                        "flows from device_put/checkpoint restore without "
                        "an ensure_donatable seam — donated zero-copy host "
                        "views corrupt the heap on jax 0.4.37",
                    )
                )


def _rule_donation(mod: _Module) -> List[Finding]:
    findings: List[Finding] = []
    funcs = _functions(mod.tree)
    # nested functions are walked by their own scope only (ast.walk of the
    # outer function includes the inner one's statements; dedupe by running
    # outermost scopes and letting name-taint stay function-local)
    tops = [
        f
        for f in funcs
        if not _in_any(
            (f.lineno, f.end_lineno or f.lineno),
            [g for g in funcs if g is not f],
        )
    ]
    for func in tops:
        _TaintScope(mod, func, findings).run()
    return findings


# ---------------------------------------------------------------------------
# driver


def refresh_mesh_axes(repo_root: Path) -> Set[str]:
    """Re-derive the declared axis-name set from parallel/mesh.py (AST only
    — no import): every ``X_AXIS = "name"`` module constant. Falls back to
    the built-in set when the file is missing/unreadable."""
    mesh_py = Path(repo_root) / "zero_transformer_tpu" / "parallel" / "mesh.py"
    try:
        tree = ast.parse(mesh_py.read_text())
    except (OSError, SyntaxError):
        return set(MESH_AXES)
    axes = set()
    for node in tree.body:
        if (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and node.targets[0].id.endswith("_AXIS")
            and isinstance(node.value, ast.Constant)
            and isinstance(node.value.value, str)
        ):
            axes.add(node.value.value)
    return axes or set(MESH_AXES)


def analyze_source(
    src: str,
    path: str = "<string>",
    rules: Optional[Sequence[str]] = None,
    mesh_axes: Optional[Set[str]] = None,
) -> List[Finding]:
    """Run the selected rules over one source string. Suppressions applied;
    meta-findings (bad/unused suppressions) appended unsuppressed."""
    want = set(rules or ALL_RULES)
    try:
        mod = _Module(path, src)
    except SyntaxError as exc:
        return [
            Finding(
                "parse-error", path, exc.lineno or 0, exc.offset or 0, str(exc)
            )
        ]
    findings: List[Finding] = []
    if "wall-clock-in-span-path" in want:
        findings += _rule_wall_clock(mod)
    if "host-sync-in-hot-path" in want:
        findings += _rule_host_sync(mod)
    if "lock-held-device-sync" in want:
        findings += _rule_lock_sync(mod)
    if "broad-except-in-supervised-seam" in want:
        findings += _rule_broad_except(mod)
    if "swallowed-except-in-control-plane" in want:
        findings += _rule_control_plane_except(mod)
    if "sharding-spec" in want:
        findings += _rule_sharding_spec(mod, mesh_axes or MESH_AXES)
    if "donation-safety" in want:
        findings += _rule_donation(mod)
    findings = [mod.suppress(f) for f in findings]
    findings += mod.meta_findings
    for sup in mod.suppressions.values():
        # only judge a suppression against rules that actually RAN: a
        # single-rule invocation must not call other rules' allows stale
        known = [r for r in sup.rules if r in ALL_RULES and r in want]
        unknown = [r for r in sup.rules if r not in ALL_RULES]
        for r in unknown:
            findings.append(
                Finding(
                    "unused-suppression",
                    path,
                    sup.line,
                    0,
                    f"allow[{r}]: unknown rule name (known: "
                    f"{', '.join(ALL_RULES)})",
                )
            )
        if known and sup.reason and not sup.used:
            findings.append(
                Finding(
                    "unused-suppression",
                    path,
                    sup.line,
                    0,
                    f"allow[{','.join(known)}] matched no finding — remove "
                    "the stale suppression (the invariant it excused is "
                    "gone or moved)",
                )
            )
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def analyze_file(
    path, rules=None, mesh_axes: Optional[Set[str]] = None
) -> List[Finding]:
    p = Path(path)
    return analyze_source(
        p.read_text(), str(p), rules=rules, mesh_axes=mesh_axes
    )


def iter_python_files(paths: Sequence) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        p = Path(p)
        if p.is_dir():
            out += sorted(
                f
                for f in p.rglob("*.py")
                if "__pycache__" not in f.parts
            )
        elif p.suffix == ".py":
            out.append(p)
    return out


def analyze_paths(
    paths: Sequence,
    rules=None,
    mesh_axes: Optional[Set[str]] = None,
) -> List[Finding]:
    findings: List[Finding] = []
    for f in iter_python_files(paths):
        findings += analyze_file(f, rules=rules, mesh_axes=mesh_axes)
    return findings
