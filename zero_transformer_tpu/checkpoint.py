"""Checkpointing: Orbax async, sharded-native save/restore.

Replaces the reference's checkpoint stack (reference ``main_zero.py:58-139``)
wholesale:

- the reference gathers the ZeRO-sharded optimizer state to host 0 with
  ``process_allgather`` before every save (``main_zero.py:554-557``) and saves
  synchronous msgpack; here each host writes only its own shards, and the save
  is async (the TODO at ``main_zero.py:62,78``);
- the reference hand-rebuilds the optax state tuple on restore, hardcoding the
  chain structure (``main_zero.py:105-139``); here restore targets the
  *abstract* state from ``jax.eval_shape`` so any optimizer chain round-trips
  unchanged, already laid out in its target NamedSharding (no post-restore
  resharding pjit, cf. ``main_zero.py:443-445``);
- params and optimizer state are one atomic step directory (the reference's
  split ``params_``/``optimizer_`` prefixes could desync);
- dataloader position and config are saved alongside as JSON metadata.

``export_params_msgpack`` keeps the reference's msgpack params format as an
export shim (consumed by its ``torch_compatability/extract_msgpack.py:28-47``).
"""
from __future__ import annotations

import dataclasses
import json
import logging
import time
from pathlib import Path
from typing import Any, Callable, List, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from zero_transformer_tpu.parallel.zero import TrainState
from zero_transformer_tpu.utils.jax_compat import ensure_donatable


from zero_transformer_tpu.utils.paths import is_remote_path  # noqa: F401 (re-export)

log = logging.getLogger("zero_transformer_tpu")


class CheckpointCorruptError(RuntimeError):
    """A step directory failed integrity verification (truncated files,
    digest mismatch, unreadable metadata). Raised internally and handled by
    ``CheckpointManager.restore_verified`` (quarantine + fallback); it only
    escapes when NO verified step remains."""


def _leaf_paths(tree) -> List[str]:
    return [
        jax.tree_util.keystr(kp)
        for kp, _ in jax.tree_util.tree_flatten_with_path(tree)[0]
    ]


@jax.jit
def _tree_checksums(leaves):
    from zero_transformer_tpu.resilience.detect import leaf_checksum

    return [leaf_checksum(l) for l in leaves]


def _np_checksum(x) -> int:
    """Host-side counterpart of ``detect.leaf_checksum`` — identical math
    (uint32 wrap-sum of the raw bits; numpy's ``sum(dtype=uint32)`` wraps
    exactly like XLA's), so both digest paths produce the same value.
    64-bit elements view as uint32 PAIRS, matching the jit path's word
    split (a 64->32 narrowing would hide high-word bit flips)."""
    a = np.asarray(x)
    if a.dtype == np.bool_:
        a = a.astype(np.uint8)
    width = min(a.dtype.itemsize, 4)
    u = a.reshape(-1).view(f"u{width}")
    return int(np.sum(u, dtype=np.uint32))


def tree_digests(tree) -> dict[str, int]:
    """Per-leaf content digests keyed by keypath: exact uint32 wrap-sums of
    the raw bits (``resilience.detect.leaf_checksum``). The digest of a
    logical array is independent of dtype layout, sharding, or device count
    (wrap-add is commutative and exact) — the property that lets a manifest
    written under one topology verify a restore onto another.

    Two equivalent paths: on a single-process CPU backend the leaves are
    digested from zero-copy host views on a small thread pool (numpy sum
    runs at memory bandwidth and releases the GIL; XLA's CPU "devices"
    share the same cores, so the on-device path is no faster there).
    Everywhere else — accelerators, multihost — ONE jit call digests on
    device; sharded leaves reduce via the collectives GSPMD inserts, so on
    a pod every host gets the same replicated scalars."""
    paths = _leaf_paths(tree)
    leaves = jax.tree.leaves(tree)
    host_path = (
        jax.process_count() == 1
        and jax.default_backend() == "cpu"
        and all(
            getattr(leaf, "is_fully_addressable", True) for leaf in leaves
        )
    )
    if host_path:
        import os
        from concurrent.futures import ThreadPoolExecutor

        jax.block_until_ready(leaves)
        workers = max(2, min(4, os.cpu_count() or 2))
        with ThreadPoolExecutor(max_workers=workers) as pool:
            vals = list(pool.map(_np_checksum, leaves))
    else:
        vals = jax.device_get(_tree_checksums(tuple(leaves)))
    return {p: int(v) for p, v in zip(paths, vals)}


def build_manifest(state) -> dict:
    """Integrity manifest for one step: per-leaf digest + shape + dtype.

    Shape/dtype make structural mismatch (a checkpoint from a DIFFERENT
    model/optimizer) distinguishable from corruption — the former is a
    fatal config error, the latter is quarantined."""
    digests = tree_digests(state)
    leaves = {
        p: {
            "sum": digests[p],
            "shape": list(leaf.shape),
            "dtype": str(jax.numpy.dtype(leaf.dtype)),
        }
        for p, leaf in zip(
            _leaf_paths(state), jax.tree.leaves(state)
        )
    }
    return {"version": 1, "algo": "u32sum", "leaves": leaves}


def manifest_mismatch(manifest: dict, target) -> Optional[str]:
    """Structural diff between a saved manifest and a restore target's
    abstract tree — None when they describe the same model/optimizer."""
    saved = manifest.get("leaves", {})
    tgt = {
        p: leaf
        for p, leaf in zip(_leaf_paths(target), jax.tree.leaves(target))
    }
    missing = sorted(set(saved) - set(tgt))
    unexpected = sorted(set(tgt) - set(saved))
    if missing or unexpected:
        return (
            f"leaf sets differ (checkpoint-only: {missing[:3]}, "
            f"target-only: {unexpected[:3]})"
        )
    for p, info in saved.items():
        if tuple(info["shape"]) != tuple(tgt[p].shape):
            return (
                f"{p} shaped {tuple(info['shape'])} in the checkpoint but "
                f"{tuple(tgt[p].shape)} in the model"
            )
        if str(info["dtype"]) != str(jax.numpy.dtype(tgt[p].dtype)):
            return (
                f"{p} is {info['dtype']} in the checkpoint but "
                f"{jax.numpy.dtype(tgt[p].dtype)} in the model"
            )
    return None


# clearly-transient storage/network fingerprints: a restore failure that
# matches these is RE-RAISED (the supervisor retries with the step dir
# intact) instead of quarantining a healthy checkpoint over a network blip.
# Deliberately narrower than supervisor._RETRYABLE_PATTERNS: "data_loss"
# style codes ARE corruption and must quarantine.
_TRANSIENT_PATTERNS = (
    "unavailable",
    "deadline_exceeded",
    "timed out",
    "timeout",
    "connection",
    "socket",
    "broken pipe",
    "reset by peer",
    "aborted",
    "eof occurred",
    "temporarily",
    "transient",
    "too many requests",
    "service unavailable",
    "resource_exhausted",
    "memoryerror",
    "unable to allocate",
    "out of memory",
)


def _looks_transient(exc: BaseException) -> bool:
    msg = f"{type(exc).__name__}: {exc}".lower()
    return any(pat in msg for pat in _TRANSIENT_PATTERNS)


@dataclasses.dataclass
class RestoreReport:
    """What ``restore_verified`` had to do to produce a trustworthy state."""

    step: Optional[int] = None  # the step that finally verified
    requested_step: Optional[int] = None  # newest candidate at entry
    quarantined: List[int] = dataclasses.field(default_factory=list)
    verify_ms: float = 0.0  # digest re-computation time at restore

    @property
    def fallback_steps(self) -> int:
        """How far behind the newest candidate the verified restore landed."""
        if self.step is None or self.requested_step is None:
            return 0
        return int(self.requested_step - self.step)


def resolve_ckpt_path(directory: str | Path):
    """Local paths become absolute ``pathlib.Path``; remote URLs become
    ``etils.epath.Path`` UNTOUCHED (``Path("gs://b").absolute()`` would mangle
    the URL into ``/current/dir/gs:/b`` — the round-3 bug)."""
    if is_remote_path(directory):
        from etils import epath

        return epath.Path(str(directory))
    return Path(directory).absolute()


def abstract_state(model, tx, plan, sample_input_shape) -> TrainState:
    """TrainState of ShapeDtypeStructs carrying target shardings — the restore
    target (and the structure any restore is validated against)."""
    import jax.numpy as jnp

    from zero_transformer_tpu.parallel.sharding import unbox

    def _init(rng):
        variables = model.init(rng, jnp.zeros(sample_input_shape, jnp.int32))
        params = unbox(variables["params"])
        return TrainState(
            step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params)
        )

    abstract = jax.eval_shape(_init, jax.random.PRNGKey(0))
    return jax.tree.map(
        lambda leaf, shd: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=shd),
        abstract,
        plan.state,
    )


class CheckpointManager:
    """Thin wrapper over ``orbax.checkpoint.CheckpointManager``.

    Layout: ``{directory}/{step}/state`` (sharded arrays) + ``.../meta`` (JSON:
    dataloader position, anything picklable-as-json).
    """

    def __init__(
        self,
        directory: str | Path,
        keep: int = 5,
        save_frequency: int = 1000,
        async_save: bool = True,
        integrity: bool = True,
    ):
        self.directory = resolve_ckpt_path(directory)
        self.save_frequency = save_frequency
        self._keep = keep
        self._async_save = async_save
        # integrity manifests: every save also writes a per-leaf content-
        # digest item; restore_verified() re-digests the restored leaves
        # against it and quarantines mismatching step dirs
        self.integrity = integrity
        # digest time of the most recent save tick (the <5% budget is
        # measured against this; surfaced as train/ckpt_verify_ms)
        self.last_digest_ms: float = 0.0
        # The orbax manager is built LAZILY: its constructor touches storage
        # (creates the root directory), which for a gs:// path would need
        # bucket access just to instantiate. Path resolution/formatting must
        # work storage-free (and is unit-tested that way).
        self._mgr_inst: Optional[ocp.CheckpointManager] = None

    @property
    def _mgr(self) -> ocp.CheckpointManager:
        if self._mgr_inst is None:
            # interval gating is done here with a modulo (reference cadence:
            # save at step % frequency == 0) — orbax's save_interval_steps
            # instead anchors the cadence at the first saved step.
            self._mgr_inst = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=self._keep,
                    enable_async_checkpointing=self._async_save,
                ),
            )
        return self._mgr_inst

    def step_path(self, step: int):
        """Formatted path of one step's checkpoint directory (storage-free)."""
        return self.directory / ocp.step.standard_name_format().build_name(step)

    def ensure_ready(self) -> None:
        """Force the first storage touch NOW (creates the root directory).
        Call at job startup so a misconfigured directory — bad bucket name,
        missing credentials — fails before hours of training, not at the
        first interval save (laziness exists for storage-free construction,
        not to defer validation)."""
        self._mgr

    def check_for_errors(self) -> None:
        """Surface a FAILED async save now instead of at ``wait()``/``close()``.

        Orbax's async commit thread parks its exception until someone joins
        it — historically that was hours later at run teardown, long after a
        dead bucket stopped persisting anything (every "checkpoint" since
        silently lost). Polled on every ``save()`` tick so a broken
        destination kills the run within one save interval. Storage-free:
        a manager that never saved has nothing to poll. Older orbax without
        ``check_for_errors`` degrades to the historical at-exit behavior."""
        if self._mgr_inst is None:
            return
        check = getattr(self._mgr_inst, "check_for_errors", None)
        if check is None:
            # older orbax: the AsyncCheckpointer underneath holds the thread
            inner = getattr(self._mgr_inst, "_checkpointer", None)
            check = getattr(inner, "check_for_errors", None)
        if check is not None:
            check()

    def save(
        self,
        step: int,
        state: TrainState,
        meta: Optional[dict] = None,
        force: bool = False,
    ) -> bool:
        """Save if ``step`` falls on the save interval (or ``force``)."""
        # a previous async save that died must fail THIS run promptly, not
        # hours later when wait()/close() finally joins the commit thread
        self.check_for_errors()
        if not force and (step == 0 or step % self.save_frequency != 0):
            return False
        # a PARTIAL dir for this step (crash mid-save on an object store —
        # no atomic rename) would make orbax's save raise
        # StepAlreadyExistsError, crash-looping a resumed run every time it
        # re-reaches this step; move the garbage aside first
        try:
            in_the_way = self.step_path(step).exists() and not self._step_complete(step)
        except OSError:
            in_the_way = False
        if in_the_way:
            self.quarantine(
                step, "incomplete step dir (crash mid-save) in the way of a new save"
            )
        items = {
            "state": ocp.args.StandardSave(state),
            "meta": ocp.args.JsonSave(meta or {}),
        }
        if self.integrity:
            # digest from the live device state BEFORE orbax serializes it:
            # one bandwidth-bound read (collective-reduced on pods, so every
            # host sees the same replicated scalars and process 0's JSON
            # write covers the whole tree). Restore re-digests and compares
            # — any storage-introduced change, torn write, or bit flip
            # between here and the future restore fails verification.
            t0 = time.perf_counter()
            manifest = build_manifest(state)
            self.last_digest_ms = (time.perf_counter() - t0) * 1e3
            items["manifest"] = ocp.args.JsonSave(manifest)
        return self._mgr.save(step, args=ocp.args.Composite(**items), force=force)

    def restore(
        self, target: TrainState, step: Optional[int] = None
    ) -> tuple[TrainState, dict]:
        """Restore into ``target``'s shapes/dtypes/shardings (from
        ``abstract_state``). Returns (state, meta)."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {self.directory}")
        out = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(target),
                meta=ocp.args.JsonRestore(),
            ),
        )
        # seal the donation seam AT THE SOURCE: orbax can hand back
        # zero-copy host views, and consumers (trainer, multihost workers)
        # feed restored state straight into donating steps — PR 5 re-fixed
        # exactly this in a consumer that had missed its own seam
        # graftlint: allow[donation-safety] reason=state element is sealed through ensure_donatable on this line; meta is restored JSON (host dict, no donatable buffers)
        return ensure_donatable(out["state"]), out["meta"]

    # -- trustworthy restore -------------------------------------------------

    def _reset_mgr(self) -> None:
        """Drop the lazy orbax manager so the next access re-reads storage
        (it caches step metadata; a quarantine rename invalidates that)."""
        if self._mgr_inst is None:
            return
        try:
            self._mgr_inst.close()
        except Exception:
            log.exception("checkpoint: manager close during reset (ignored)")
        self._mgr_inst = None

    def quarantine(self, step: int, reason: str) -> Optional[str]:
        """Take ``step`` out of the restore-candidate set, preserved for
        post-mortem: rename the dir to ``<step>.quarantined`` where the
        storage supports it, else (object stores — gs:// prefixes cannot be
        renamed) drop a ``_QUARANTINED`` tombstone file inside it, which
        ``_step_complete`` treats as incomplete. Returns the quarantined
        path (None when the dir vanished — another pod process got there
        first; the rename/tombstone is the commit point, first-wins)."""
        try:
            path = ocp.step.find_step_path(
                self.directory, ocp.step.standard_name_format(), step=step
            )
        except (ValueError, FileNotFoundError):
            path = self.step_path(step)
        dest = path.parent / f"{path.name}.quarantined"
        n = 0
        while dest.exists():
            n += 1
            dest = path.parent / f"{path.name}.quarantined.{n}"
        try:
            path.rename(dest)
        except (FileNotFoundError, NotADirectoryError) as e:
            # the dir vanished: another pod process quarantined it first
            log.warning(
                "checkpoint: step %d already quarantined elsewhere (%s)", step, e
            )
            self._reset_mgr()
            return None
        except OSError as rename_err:
            # object stores (and read-only mounts) reject directory renames;
            # fall back to an in-place tombstone that _step_complete honors
            try:
                (path / "_QUARANTINED").write_text(str(reason)[:500])
            except OSError as e:
                # even the tombstone failed — the caller's seen-step guard
                # turns this into a hard error instead of re-restoring the
                # same corrupt step forever
                log.error(
                    "checkpoint: could not quarantine step %d (rename: %s; "
                    "tombstone: %s) — the corrupt dir remains a restore "
                    "candidate", step, rename_err, e,
                )
                self._reset_mgr()
                return None
            log.error(
                "checkpoint: step %d QUARANTINED in place via tombstone "
                "(%s; dir rename unsupported: %s)", step, reason, rename_err,
            )
            self._reset_mgr()
            return str(path)
        log.error(
            "checkpoint: step %d QUARANTINED -> %s (%s)", step, dest, reason
        )
        self._reset_mgr()
        return str(dest)

    def restore_verified(
        self,
        target: TrainState,
        check_meta: Optional[Callable[[dict], None]] = None,
        on_event: Optional[Callable] = None,
    ) -> tuple[TrainState, dict, RestoreReport]:
        """Restore the newest step that passes integrity verification.

        Per candidate (newest first): read ``meta`` + ``manifest`` (cheap
        JSON); reject a manifest that describes a DIFFERENT model/optimizer
        with a precise ``ValueError`` (that is a config error, not
        corruption — quarantining it would discard a good checkpoint); run
        ``check_meta`` (the trainer's elastic-topology validation — raises
        before any array IO or compilation); restore the state; re-digest the
        restored leaves against the manifest. Any read failure or digest
        mismatch QUARANTINES the step dir and falls back to the next older
        candidate — so a supervised restart never crash-loops on the same
        bad artifact. Raises ``FileNotFoundError`` when no verified step
        remains (fatal to the supervisor: retrying cannot mint a good
        checkpoint).

        ``on_event(name, step, **fields)`` mirrors ``MetricsLogger.event``.
        Returns ``(state, meta, RestoreReport)``.
        """
        report = RestoreReport()
        report.requested_step = self.latest_step()
        seen: set = set()
        while True:
            step = self.latest_step()
            if step is not None and step in seen:
                # quarantine failed to remove the dir (read-only storage?):
                # without this guard the loop would re-restore and re-fail
                # the same corrupt step forever
                raise RuntimeError(
                    f"checkpoint step {step} under {self.directory} failed "
                    f"verification but could not be quarantined (rename "
                    f"failed — read-only storage or missing permissions?); "
                    f"move the step dir aside manually and rerun"
                )
            if step is None:
                raise FileNotFoundError(
                    f"no verified checkpoint under {self.directory} "
                    f"({len(report.quarantined)} step(s) quarantined this "
                    f"restore: {report.quarantined}; inspect the "
                    f"*.quarantined dirs or point --resume elsewhere)"
                )

            seen.add(step)

            def _bad(reason: str) -> None:
                dest = self.quarantine(step, reason)
                report.quarantined.append(step)
                if on_event is not None:
                    on_event(
                        "ckpt_quarantined", step,
                        reason=str(reason)[:200], path=dest or "",
                    )

            step_dir = self.step_path(step)
            manifest = None
            try:
                items = {"meta": ocp.args.JsonRestore()}
                if (step_dir / "manifest").exists():
                    items["manifest"] = ocp.args.JsonRestore()
                pre = self._mgr.restore(step, args=ocp.args.Composite(**items))
                meta = pre["meta"] or {}
                manifest = pre["manifest"] if "manifest" in items else None
            except Exception as e:
                if _looks_transient(e):
                    raise  # network blip, not corruption: retry, dir intact
                _bad(f"unreadable step metadata: {type(e).__name__}: {e}")
                continue
            if manifest is not None:
                mismatch = manifest_mismatch(manifest, target)
                if mismatch is not None:
                    raise ValueError(
                        f"checkpoint step {step} under {self.directory} was "
                        f"saved for a different model/optimizer: {mismatch}. "
                        f"This is a config mismatch, not corruption — fix the "
                        f"config (or warm-init instead of resuming)"
                    )
            if check_meta is not None:
                check_meta(meta)  # ValueError here is fatal by design
            try:
                out = self._mgr.restore(
                    step, args=ocp.args.Composite(state=ocp.args.StandardRestore(target))
                )
                state = out["state"]
            except Exception as e:
                if _looks_transient(e):
                    raise  # network blip, not corruption: retry, dir intact
                if manifest is None:
                    # pre-manifest checkpoint: without the structural check
                    # above, a restore failure may be a CONFIG mismatch
                    # (wrong model), not corruption — quarantining would
                    # mangle a healthy directory. Preserve the old restore()
                    # behavior: raise with the dir intact.
                    raise
                _bad(f"state restore failed: {type(e).__name__}: {e}")
                continue
            if manifest is not None and self.integrity:
                t0 = time.perf_counter()
                fresh = tree_digests(state)
                report.verify_ms += (time.perf_counter() - t0) * 1e3
                bad_leaves = [
                    p for p, info in manifest["leaves"].items()
                    if int(info["sum"]) != fresh.get(p)
                ]
                if bad_leaves:
                    _bad(
                        f"digest mismatch on {len(bad_leaves)} leaf/leaves "
                        f"(e.g. {bad_leaves[:3]}) — silent data corruption"
                    )
                    continue
            elif manifest is None:
                log.warning(
                    "checkpoint: step %d predates integrity manifests — "
                    "restored UNVERIFIED", step,
                )
            report.step = step
            if report.fallback_steps:
                log.warning(
                    "checkpoint: restore fell back %d step(s) (step %s -> %s) "
                    "past %d quarantined dir(s)",
                    report.fallback_steps, report.requested_step, step,
                    len(report.quarantined),
                )
                if on_event is not None:
                    on_event(
                        "restore_fallback", step,
                        from_step=report.requested_step,
                        fallback_steps=report.fallback_steps,
                        quarantined=len(report.quarantined),
                    )
            # runtime-owned buffers before ANY consumer can donate them
            # (the digest ran on the restored values above; add-0 preserves
            # them bitwise and their shardings)
            return ensure_donatable(state), meta, report

    def restore_params(self, abstract_params: Any, step: Optional[int] = None) -> Any:
        """Params-only restore — the ``warm_init`` path for scale-up surgery
        (reference ``main_zero.py:268-289``)."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {self.directory}")
        # The manager registered "state" with the Standard handler, which needs
        # the FULL tree on restore; a warm init must not need to know the donor
        # run's optimizer structure. Read the step's state item directly with a
        # PyTree partial restore instead, resolving the step path through orbax
        # so any step naming scheme works.
        step_dir = ocp.step.find_step_path(
            self.directory, ocp.step.standard_name_format(), step=step
        )
        state_dir = step_dir / "state"
        if not state_dir.exists():
            raise FileNotFoundError(f"step {step} has no 'state' item in {step_dir}")
        target = {"params": abstract_params}
        ckptr = ocp.PyTreeCheckpointer()
        # newer orbax spells subtree restore `partial_restore=True`; older
        # releases use the documented `transforms={}` idiom for the same thing
        import inspect

        if "partial_restore" in inspect.signature(ocp.args.PyTreeRestore).parameters:
            partial_kwargs = {"partial_restore": True}
        else:
            partial_kwargs = {"transforms": {}}
        try:
            out = ckptr.restore(
                state_dir,
                args=ocp.args.PyTreeRestore(
                    item=target,
                    restore_args=jax.tree.map(
                        lambda l: ocp.ArrayRestoreArgs(
                            sharding=l.sharding, global_shape=l.shape, dtype=l.dtype
                        ),
                        target,
                    ),
                    **partial_kwargs,
                ),
            )
        finally:
            ckptr.close()
        return ensure_donatable(out["params"])

    def _step_complete(self, step: int) -> bool:
        """True when ``step``'s directory is a COMMITTED checkpoint.

        A crash mid-async-save can leave a partial step directory (on object
        stores there is no atomic rename; locally, a hand-interrupted copy or
        a half-written restore from backup looks the same). Orbax's own
        ``latest_step`` trusts the directory listing — which made the newest
        *partial* dir the resume target. Completeness here means: orbax
        finalized it (tmp-name / commit-marker check), the manager-level
        ``_CHECKPOINT_METADATA`` (written at commit) exists, and the
        ``state`` item directory exists with its metadata file."""
        d = self.step_path(step)
        try:
            if not ocp.step.is_checkpoint_finalized(d):
                return False
        except (OSError, ValueError):
            return False
        if (d / "_QUARANTINED").exists():
            # tombstone-quarantined in place (object stores can't rename
            # directories): never a restore candidate again
            return False
        state_dir = d / "state"
        return (
            (d / "_CHECKPOINT_METADATA").exists()
            and state_dir.exists()
            and (state_dir / "_METADATA").exists()
        )

    def latest_step(self) -> Optional[int]:
        """Newest COMPLETE step (partial/uncommitted dirs are skipped — they
        exist after a crash mid-async-save and must never be the resume
        target)."""
        for step in sorted(self._mgr.all_steps(), reverse=True):
            if self._step_complete(step):
                return step
        return None

    def all_steps(self):
        return sorted(s for s in self._mgr.all_steps() if self._step_complete(s))

    def incomplete_steps(self) -> list:
        """Step dirs present in the listing that fail the completeness check
        (crash mid-save leftovers — or, pathologically, checkpoints whose
        commit markers a backup tool dropped). Lets a resume distinguish
        'nothing to resume' from 'steps exist but none are trustworthy'."""
        return sorted(
            s for s in self._mgr.all_steps() if not self._step_complete(s)
        )

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        if self._mgr_inst is None:
            return  # never touched storage; nothing to flush
        self._mgr.wait_until_finished()
        self._mgr.close()


def export_params_msgpack(params: Any, path: str | Path) -> Path:
    """Export gathered params as flax msgpack — the reference's interchange
    format (its converter reads exactly this, ``torch_compatability/
    extract_msgpack.py:54-62``)."""
    from flax.serialization import msgpack_serialize

    path = Path(path)
    host_params = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), params)
    path.write_bytes(msgpack_serialize(host_params))
    return path


def import_params_msgpack(path: str | Path) -> Any:
    """Load a msgpack params tree (reference checkpoints import path)."""
    from flax.serialization import msgpack_restore

    return msgpack_restore(Path(path).read_bytes())


def save_config_json(directory: str | Path, flat_config: dict) -> None:
    path = resolve_ckpt_path(directory)
    path.mkdir(parents=True, exist_ok=True)  # epath: no-op dir on GCS
    (path / "config.json").write_text(json.dumps(flat_config, indent=2, default=str))
