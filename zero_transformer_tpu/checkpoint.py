"""Checkpointing: Orbax async, sharded-native save/restore.

Replaces the reference's checkpoint stack (reference ``main_zero.py:58-139``)
wholesale:

- the reference gathers the ZeRO-sharded optimizer state to host 0 with
  ``process_allgather`` before every save (``main_zero.py:554-557``) and saves
  synchronous msgpack; here each host writes only its own shards, and the save
  is async (the TODO at ``main_zero.py:62,78``);
- the reference hand-rebuilds the optax state tuple on restore, hardcoding the
  chain structure (``main_zero.py:105-139``); here restore targets the
  *abstract* state from ``jax.eval_shape`` so any optimizer chain round-trips
  unchanged, already laid out in its target NamedSharding (no post-restore
  resharding pjit, cf. ``main_zero.py:443-445``);
- params and optimizer state are one atomic step directory (the reference's
  split ``params_``/``optimizer_`` prefixes could desync);
- dataloader position and config are saved alongside as JSON metadata.

``export_params_msgpack`` keeps the reference's msgpack params format as an
export shim (consumed by its ``torch_compatability/extract_msgpack.py:28-47``).
"""
from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np
import orbax.checkpoint as ocp

from zero_transformer_tpu.parallel.zero import TrainState


from zero_transformer_tpu.utils.paths import is_remote_path  # noqa: F401 (re-export)


def resolve_ckpt_path(directory: str | Path):
    """Local paths become absolute ``pathlib.Path``; remote URLs become
    ``etils.epath.Path`` UNTOUCHED (``Path("gs://b").absolute()`` would mangle
    the URL into ``/current/dir/gs:/b`` — the round-3 bug)."""
    if is_remote_path(directory):
        from etils import epath

        return epath.Path(str(directory))
    return Path(directory).absolute()


def abstract_state(model, tx, plan, sample_input_shape) -> TrainState:
    """TrainState of ShapeDtypeStructs carrying target shardings — the restore
    target (and the structure any restore is validated against)."""
    import jax.numpy as jnp

    from zero_transformer_tpu.parallel.sharding import unbox

    def _init(rng):
        variables = model.init(rng, jnp.zeros(sample_input_shape, jnp.int32))
        params = unbox(variables["params"])
        return TrainState(
            step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params)
        )

    abstract = jax.eval_shape(_init, jax.random.PRNGKey(0))
    return jax.tree.map(
        lambda leaf, shd: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype, sharding=shd),
        abstract,
        plan.state,
    )


class CheckpointManager:
    """Thin wrapper over ``orbax.checkpoint.CheckpointManager``.

    Layout: ``{directory}/{step}/state`` (sharded arrays) + ``.../meta`` (JSON:
    dataloader position, anything picklable-as-json).
    """

    def __init__(
        self,
        directory: str | Path,
        keep: int = 5,
        save_frequency: int = 1000,
        async_save: bool = True,
    ):
        self.directory = resolve_ckpt_path(directory)
        self.save_frequency = save_frequency
        self._keep = keep
        self._async_save = async_save
        # The orbax manager is built LAZILY: its constructor touches storage
        # (creates the root directory), which for a gs:// path would need
        # bucket access just to instantiate. Path resolution/formatting must
        # work storage-free (and is unit-tested that way).
        self._mgr_inst: Optional[ocp.CheckpointManager] = None

    @property
    def _mgr(self) -> ocp.CheckpointManager:
        if self._mgr_inst is None:
            # interval gating is done here with a modulo (reference cadence:
            # save at step % frequency == 0) — orbax's save_interval_steps
            # instead anchors the cadence at the first saved step.
            self._mgr_inst = ocp.CheckpointManager(
                self.directory,
                options=ocp.CheckpointManagerOptions(
                    max_to_keep=self._keep,
                    enable_async_checkpointing=self._async_save,
                ),
            )
        return self._mgr_inst

    def step_path(self, step: int):
        """Formatted path of one step's checkpoint directory (storage-free)."""
        return self.directory / ocp.step.standard_name_format().build_name(step)

    def ensure_ready(self) -> None:
        """Force the first storage touch NOW (creates the root directory).
        Call at job startup so a misconfigured directory — bad bucket name,
        missing credentials — fails before hours of training, not at the
        first interval save (laziness exists for storage-free construction,
        not to defer validation)."""
        self._mgr

    def check_for_errors(self) -> None:
        """Surface a FAILED async save now instead of at ``wait()``/``close()``.

        Orbax's async commit thread parks its exception until someone joins
        it — historically that was hours later at run teardown, long after a
        dead bucket stopped persisting anything (every "checkpoint" since
        silently lost). Polled on every ``save()`` tick so a broken
        destination kills the run within one save interval. Storage-free:
        a manager that never saved has nothing to poll. Older orbax without
        ``check_for_errors`` degrades to the historical at-exit behavior."""
        if self._mgr_inst is None:
            return
        check = getattr(self._mgr_inst, "check_for_errors", None)
        if check is None:
            # older orbax: the AsyncCheckpointer underneath holds the thread
            inner = getattr(self._mgr_inst, "_checkpointer", None)
            check = getattr(inner, "check_for_errors", None)
        if check is not None:
            check()

    def save(
        self,
        step: int,
        state: TrainState,
        meta: Optional[dict] = None,
        force: bool = False,
    ) -> bool:
        """Save if ``step`` falls on the save interval (or ``force``)."""
        # a previous async save that died must fail THIS run promptly, not
        # hours later when wait()/close() finally joins the commit thread
        self.check_for_errors()
        if not force and (step == 0 or step % self.save_frequency != 0):
            return False
        return self._mgr.save(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardSave(state),
                meta=ocp.args.JsonSave(meta or {}),
            ),
            force=force,
        )

    def restore(
        self, target: TrainState, step: Optional[int] = None
    ) -> tuple[TrainState, dict]:
        """Restore into ``target``'s shapes/dtypes/shardings (from
        ``abstract_state``). Returns (state, meta)."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {self.directory}")
        out = self._mgr.restore(
            step,
            args=ocp.args.Composite(
                state=ocp.args.StandardRestore(target),
                meta=ocp.args.JsonRestore(),
            ),
        )
        return out["state"], out["meta"]

    def restore_params(self, abstract_params: Any, step: Optional[int] = None) -> Any:
        """Params-only restore — the ``warm_init`` path for scale-up surgery
        (reference ``main_zero.py:268-289``)."""
        step = step if step is not None else self.latest_step()
        if step is None:
            raise FileNotFoundError(f"no checkpoint found under {self.directory}")
        # The manager registered "state" with the Standard handler, which needs
        # the FULL tree on restore; a warm init must not need to know the donor
        # run's optimizer structure. Read the step's state item directly with a
        # PyTree partial restore instead, resolving the step path through orbax
        # so any step naming scheme works.
        step_dir = ocp.step.find_step_path(
            self.directory, ocp.step.standard_name_format(), step=step
        )
        state_dir = step_dir / "state"
        if not state_dir.exists():
            raise FileNotFoundError(f"step {step} has no 'state' item in {step_dir}")
        target = {"params": abstract_params}
        ckptr = ocp.PyTreeCheckpointer()
        # newer orbax spells subtree restore `partial_restore=True`; older
        # releases use the documented `transforms={}` idiom for the same thing
        import inspect

        if "partial_restore" in inspect.signature(ocp.args.PyTreeRestore).parameters:
            partial_kwargs = {"partial_restore": True}
        else:
            partial_kwargs = {"transforms": {}}
        try:
            out = ckptr.restore(
                state_dir,
                args=ocp.args.PyTreeRestore(
                    item=target,
                    restore_args=jax.tree.map(
                        lambda l: ocp.ArrayRestoreArgs(
                            sharding=l.sharding, global_shape=l.shape, dtype=l.dtype
                        ),
                        target,
                    ),
                    **partial_kwargs,
                ),
            )
        finally:
            ckptr.close()
        return out["params"]

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def all_steps(self):
        return sorted(self._mgr.all_steps())

    def wait(self) -> None:
        self._mgr.wait_until_finished()

    def close(self) -> None:
        if self._mgr_inst is None:
            return  # never touched storage; nothing to flush
        self._mgr.wait_until_finished()
        self._mgr.close()


def export_params_msgpack(params: Any, path: str | Path) -> Path:
    """Export gathered params as flax msgpack — the reference's interchange
    format (its converter reads exactly this, ``torch_compatability/
    extract_msgpack.py:54-62``)."""
    from flax.serialization import msgpack_serialize

    path = Path(path)
    host_params = jax.tree.map(lambda x: np.asarray(jax.device_get(x)), params)
    path.write_bytes(msgpack_serialize(host_params))
    return path


def import_params_msgpack(path: str | Path) -> Any:
    """Load a msgpack params tree (reference checkpoints import path)."""
    from flax.serialization import msgpack_restore

    return msgpack_restore(Path(path).read_bytes())


def save_config_json(directory: str | Path, flat_config: dict) -> None:
    path = resolve_ckpt_path(directory)
    path.mkdir(parents=True, exist_ok=True)  # epath: no-op dir on GCS
    (path / "config.json").write_text(json.dumps(flat_config, indent=2, default=str))
