from zero_transformer_tpu.parallel.mesh import (  # noqa: F401
    AXES,
    DATA_AXIS,
    FSDP_AXIS,
    SEQUENCE_AXIS,
    TENSOR_AXIS,
    make_mesh,
    single_device_mesh,
)
from zero_transformer_tpu.parallel.zero import (  # noqa: F401
    ShardingPlan,
    TrainState,
    init_train_state,
    make_eval_step,
    make_plan,
    make_train_step,
)
