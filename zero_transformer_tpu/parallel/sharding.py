"""Sharding spec derivation: logical axes → NamedSharding, plus ZeRO layouts.

Replaces the reference's regex rule engine (reference
``src/partitioning/partition.py:28-111``: path-regex → PartitionSpec, with a
runtime assert that every param matched) with two composable, *total* passes:

1. **Tensor-parallel specs** from the logical axis names each param was
   annotated with in the model (``nn.with_partitioning``) via a rules table —
   the idiomatic flax ``logical_to_mesh`` design.
2. **ZeRO sharding** (stages 1-3) derived from *shapes*: for each tensor,
   shard the largest not-yet-sharded dimension divisible by the ZeRO axis
   size. This is what the reference's regex table effectively encodes by hand
   (``partition.py:49-87``), but it cannot miss a param and extends to any
   model family unchanged.

Optimizer-state specs clone each param's spec onto same-shaped leaves and
replicate the rest — the reference's ``create_opt_spec`` (``partition.py:114-140``)
without the optax-internals coupling.
"""
from __future__ import annotations

import math
from typing import Any, Optional

import flax.linen as nn
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zero_transformer_tpu.parallel.mesh import (
    DATA_AXIS,
    EXPERT_AXIS,
    FSDP_AXIS,
    PIPE_AXIS,
    SEQUENCE_AXIS,
    TENSOR_AXIS,
    zero_axes,
)

# logical axis name -> mesh axis (None = replicated). Megatron layout:
# qkv/mlp-in sharded on the output feature axis, out-proj/mlp-out on input;
# MoE expert stacks shard over the expert axis (EP); the stacked layer dim
# shards over the pipe axis (each pipeline stage owns n_layers/pipe layers).
LOGICAL_RULES: dict[str, Optional[str]] = {
    "vocab": TENSOR_AXIS,
    "qheads": TENSOR_AXIS,
    "kvheads": TENSOR_AXIS,
    "mlp": TENSOR_AXIS,
    "expert": EXPERT_AXIS,
    "embed": None,
    "layers": PIPE_AXIS,
}

# every axis name the repo may legally put in a rule table. An axis absent
# from a given mesh is fine (it drops to replication — small meshes declare
# a subset), but an axis outside this universe is a typo that would
# silently replicate a param the author meant to shard.
KNOWN_AXES: frozenset = frozenset(
    (DATA_AXIS, FSDP_AXIS, EXPERT_AXIS, TENSOR_AXIS, SEQUENCE_AXIS, PIPE_AXIS)
)


def validate_rules(rules: dict) -> None:
    """Reject rule tables naming axes outside the repo's declared universe.

    ``_tp_axes`` intentionally drops axes the target mesh does not carry
    (``mesh.shape.get(axis, 1)``), which is correct for a small mesh but
    turns a typo'd axis name into a silent no-shard. This is the
    hand-trusted gap ROADMAP item 1 closes: specs are checked, not trusted.
    """
    bad = {
        name: axis
        for name, axis in rules.items()
        if axis is not None and axis not in KNOWN_AXES
    }
    if bad:
        raise ValueError(
            "sharding rule table names unknown mesh axes "
            f"{sorted(set(bad.values()))} (for logical dims {sorted(bad)}); "
            f"declared axes are {sorted(KNOWN_AXES)} — a typo here silently "
            "replicates the param instead of sharding it"
        )


def logical_specs(boxed_params) -> Any:
    """Pytree of PartitionSpec(logical axis names) from nn.Partitioned boxes."""
    return nn.get_partition_spec(boxed_params)


def unbox(boxed_params) -> Any:
    return nn.meta.unbox(boxed_params)


def _tp_axes(logical: P, mesh: Mesh, rules: Optional[dict] = None) -> tuple:
    """Map one param's logical spec to mesh axes via LOGICAL_RULES (or an
    override table — the interleaved pipeline schedule drops the
    layers→pipe rule, see ``plan_rules``)."""
    rules = LOGICAL_RULES if rules is None else rules
    out = []
    for name in logical:
        if name is None:
            out.append(None)
            continue
        axis = rules.get(name)
        if axis is not None and mesh.shape.get(axis, 1) > 1:
            out.append(axis)
        else:
            out.append(None)
    return tuple(out)


def plan_rules(pp_schedule: str = "gpipe") -> dict:
    """Logical-rule table for a pipeline schedule.

    gpipe/1f1b shard the stacked layer dim over ``pipe`` (each rank owns a
    CONTIGUOUS block of layers — its stage). The interleaved schedule runs
    virtual stage v of rank r on layers ``[(v*P + r)*Lc, ...)`` — a
    round-robin assignment a contiguous PartitionSpec shard cannot express
    (Megatron stores those layers rank-locally by construction). Rather
    than permute weights across ranks every step, interleaved stores the
    block stack pipe-REPLICATED and each rank slices its virtual chunks
    locally: layer grads come back as disjoint per-rank partials summed by
    the pipe psum the engine already runs for wte/ln_f/head. The trade —
    pipe-degree × block-param memory, same as plain DP — is reported by
    ``trainer.memory_analysis`` per schedule.
    """
    if pp_schedule == "interleaved":
        return {**LOGICAL_RULES, "layers": None}
    return LOGICAL_RULES


def _add_zero_axis(shape: tuple, tp: tuple, mesh: Mesh, axes: tuple[str, ...]) -> tuple:
    """Shard the largest unsharded dim divisible by the ZeRO world size."""
    size = math.prod(mesh.shape[a] for a in axes)
    if size <= 1:
        return tp
    tp = tuple(tp) + (None,) * (len(shape) - len(tp))
    best, best_dim = -1, None
    for i, (d, t) in enumerate(zip(shape, tp)):
        if t is not None:
            continue
        # remaining dim must divide by zero size (after any TP split on other dims)
        if d % size == 0 and d > best:
            best, best_dim = d, i
    if best_dim is None:
        return tp  # too small / indivisible: stays replicated (never an error)
    out = list(tp)
    out[best_dim] = axes if len(axes) > 1 else axes[0]
    return tuple(out)


def param_sharding(
    mesh: Mesh,
    abstract_params: Any,
    logical: Any,
    zero_stage: int = 1,
    rules: Optional[dict] = None,
) -> Any:
    """NamedSharding pytree for the *stored* master params.

    Stage 0-2: TP axes only (params replicated over data/fsdp between steps —
    reference behavior, ``main_zero.py:455,500``). Stage 3: + ZeRO axis (FSDP).
    """
    validate_rules(LOGICAL_RULES if rules is None else rules)
    zaxes = zero_axes(mesh)

    def one(leaf, spec):
        tp = _tp_axes(spec, mesh, rules)
        if zero_stage >= 3:
            tp = _add_zero_axis(leaf.shape, tp, mesh, zaxes)
        return NamedSharding(mesh, P(*tp))

    return jax.tree.map(one, abstract_params, logical)


def zero_sharding(
    mesh: Mesh, abstract_params: Any, logical: Any, rules: Optional[dict] = None
) -> Any:
    """Fully ZeRO-sharded specs (TP + ZeRO axis) — the layout for optimizer
    state (stage≥1), gradient reduce-scatter targets (stage≥2), and stage-3
    params. Counterpart of reference ``set_partitions_zero`` (``partition.py:90-111``)."""
    validate_rules(LOGICAL_RULES if rules is None else rules)
    zaxes = zero_axes(mesh)

    def one(leaf, spec):
        tp = _tp_axes(spec, mesh, rules)
        tp = _add_zero_axis(leaf.shape, tp, mesh, zaxes)
        return NamedSharding(mesh, P(*tp))

    return jax.tree.map(one, abstract_params, logical)


def opt_state_sharding(
    mesh: Mesh, abstract_opt_state: Any, abstract_params: Any, param_zero_specs: Any
) -> Any:
    """Clone each param's ZeRO spec onto param-structured optimizer subtrees.

    Works on ``jax.eval_shape(tx.init, params)`` output. The opt state is
    walked top-down: any subtree whose treedef equals the param treedef (Adam
    mu/nu, Adafactor rows, …) is substituted with the param specs leaf-for-leaf;
    everything else (counts, masked sentinels) is replicated. Structural
    matching — not shape matching — so two distinct params that happen to share
    a shape can never steal each other's (possibly transposed) spec.
    (Reference: ``create_opt_spec``, ``partition.py:114-140``.)
    """
    pstruct = jax.tree.structure(abstract_params)
    pshapes = [p.shape for p in jax.tree.leaves(abstract_params)]
    replicated = NamedSharding(mesh, P())

    def is_param_tree(x) -> bool:
        return jax.tree.structure(x) == pstruct and [
            l.shape for l in jax.tree.leaves(x)
        ] == pshapes

    leaves, treedef = jax.tree_util.tree_flatten(abstract_opt_state, is_leaf=is_param_tree)
    return jax.tree_util.tree_unflatten(
        treedef,
        [
            jax.tree.map(lambda _, s: s, leaf, param_zero_specs)
            if is_param_tree(leaf)
            else replicated
            for leaf in leaves
        ],
    )


def topology_summary(
    mesh: Mesh, zero_stage: int, pp_schedule: str = "gpipe"
) -> dict:
    """JSON-serializable description of the topology a checkpoint was saved
    under — written into every step's ``meta`` so elastic resume can compare
    the saved world against the one it is restoring onto (and refuse, or
    log the reshard, BEFORE any array IO or compilation). ``pp_schedule``
    matters because it changes the STORED layout of the block stack
    (interleaved stores it pipe-replicated) — a schedule change is elastic
    (orbax reshards natively, same logical tree) but must be visible in the
    resume log."""
    import jax

    return {
        "mesh": {a: int(s) for a, s in mesh.shape.items()},
        "devices": int(mesh.devices.size),
        "processes": int(jax.process_count()),
        "zero_stage": int(zero_stage),
        "pp_schedule": str(pp_schedule),
    }


def check_elastic_compat(
    saved: Optional[dict],
    mesh: Mesh,
    zero_stage: int,
    global_batch: int,
    pp_schedule: str = "gpipe",
) -> list[str]:
    """Validate resuming onto ``mesh`` from a checkpoint saved under
    ``saved`` (a ``topology_summary``; None for pre-manifest checkpoints).

    Raises ``ValueError`` — fatal to the supervisor, a restart cannot fix a
    config — with a precise, actionable message for topologies that are
    GENUINELY incompatible (the failure would otherwise surface deep inside
    pjit as an unrelated sharding error). Everything else is elastic:
    orbax restores sharded-native into the NEW mesh's shardings, and
    ``make_plan`` already rebuilt the ZeRO partition spec for the new device
    count. Returns human-readable notes describing what changed (logged by
    the trainer so a resized resume is visible in the run log)."""
    dp = math.prod(
        mesh.shape.get(a, 1) for a in zero_axes(mesh)
    )
    if global_batch % dp:
        raise ValueError(
            f"elastic resume: global batch_size {global_batch} is not "
            f"divisible by the new data-parallel world of {dp} "
            f"(mesh {dict(mesh.shape)}). Resuming onto this topology would "
            f"fail inside pjit at the first step — pick a mesh whose "
            f"data*fsdp divides the batch, or adjust training.batch_size"
        )
    notes: list[str] = []
    if not saved:
        return notes
    new = topology_summary(mesh, zero_stage, pp_schedule)
    if saved.get("devices") != new["devices"]:
        notes.append(
            f"device count {saved.get('devices')} -> {new['devices']} "
            f"(ZeRO shard layout rebuilt for the new mesh; orbax reshards "
            f"the arrays natively on restore)"
        )
    if saved.get("mesh") != new["mesh"]:
        notes.append(f"mesh axes {saved.get('mesh')} -> {new['mesh']}")
    if saved.get("zero_stage") != new["zero_stage"]:
        notes.append(
            f"zero_stage {saved.get('zero_stage')} -> {new['zero_stage']} "
            f"(same state tree, different layout — restore reshards)"
        )
    if saved.get("processes") != new["processes"]:
        notes.append(
            f"process count {saved.get('processes')} -> {new['processes']}"
        )
    # pre-PR-8 checkpoints have no pp_schedule key; they were all saved
    # under the gpipe/1f1b CONTIGUOUS layer sharding, for which the stored
    # layout is identical — compare against that default
    old_sched = saved.get("pp_schedule", "gpipe")
    if old_sched != new["pp_schedule"]:
        relayout = "interleaved" in (old_sched, new["pp_schedule"])
        notes.append(
            f"pp_schedule {old_sched} -> {new['pp_schedule']}"
            + (
                " (same logical state tree; the block stack restores from "
                "pipe-sharded to pipe-replicated storage or back — orbax "
                "reshards natively, and the loader position is in global "
                "batches, so the token trajectory continues exactly)"
                if relayout
                else " (same stored layout — schedule change only)"
            )
        )
    return notes


def restrict_spec(spec: P, axes: set) -> P:
    """Keep only the entries of ``spec`` whose axes are all in ``axes``;
    everything else becomes None (auto/replicated).

    Used by the partial-manual shard_map cores (ZeRO and pipeline): specs
    handed to a partial-manual region may only mention its manual axes.
    Entries name axes as bare strings or tuples (batch specs use
    ``('data',)``), so comparison is by axis set.
    """

    def keep(e):
        if e is None:
            return None
        names = set(e) if isinstance(e, tuple) else {e}
        return e if names <= axes else None

    return P(*(keep(e) for e in spec))


def batch_sharding(mesh: Mesh) -> NamedSharding:
    """[batch, seq] input sharding: batch over data(+fsdp), seq over sequence."""
    batch_axes = tuple(
        a for a in (DATA_AXIS, FSDP_AXIS) if mesh.shape.get(a, 1) > 1
    ) or (DATA_AXIS,)
    seq_axis = SEQUENCE_AXIS if mesh.shape.get(SEQUENCE_AXIS, 1) > 1 else None
    return NamedSharding(mesh, P(batch_axes, seq_axis))


# Logical ACTIVATION axis name -> mesh axes (Megatron layout: the residual
# stream [batch, seq, embed] is batch/sequence-sharded and REPLICATED over
# tensor; the per-head attention intermediates and the MLP hidden shard their
# feature dim over tensor). Used by ``constrain_activation`` below — the
# activation-side counterpart of LOGICAL_RULES (which covers params).
ACTIVATION_RULES: dict[str, Any] = {
    "batch": (DATA_AXIS, FSDP_AXIS),
    "seq": SEQUENCE_AXIS,
    "heads": TENSOR_AXIS,
    "kvheads": TENSOR_AXIS,
    "mlp": TENSOR_AXIS,
    "embed": None,
    "head_dim": None,
}


def constrain_activation(x: jax.Array, *names: Optional[str]) -> jax.Array:
    """``with_sharding_constraint`` by logical activation-axis names.

    Resolves ``names`` (one per array dim, e.g. ``"batch", "seq", "mlp"``)
    against the AMBIENT abstract mesh (``jax.set_mesh`` — the train/eval
    steps in ``parallel.zero`` enter it around trace time), so model code
    needs no mesh plumbing. Total function, three no-op cases:

    - no ambient mesh (single-chip, unit tests, decode without a mesh);
    - every resolved axis has size 1 (e.g. tensor=1);
    - the resolved axes are MANUAL in the current scope (inside the explicit
      ZeRO shard_map core the data/fsdp axes are manual — constraining them
      is illegal and unnecessary; the tensor axis stays auto there and is
      still constrained).

    This is the Megatron "other half": without activation constraints GSPMD
    alone chooses TP activation layouts (round-3 VERDICT weak #3).
    """
    from zero_transformer_tpu.utils.jax_compat import get_abstract_mesh

    amesh = get_abstract_mesh()
    if amesh is None or not amesh.axis_names:
        return x
    auto = {
        n for n, t in zip(amesh.axis_names, amesh.axis_types)
        if t == jax.sharding.AxisType.Auto and amesh.shape[n] > 1
    }

    def resolve(name):
        axes = ACTIVATION_RULES.get(name) if name else None
        if axes is None:
            return None
        if isinstance(axes, tuple):
            kept = tuple(a for a in axes if a in auto)
            return kept if len(kept) > 1 else (kept[0] if kept else None)
        return axes if axes in auto else None

    spec = tuple(resolve(n) for n in names)
    if all(s is None for s in spec):
        return x
    return jax.lax.with_sharding_constraint(x, P(*spec))


def replicate_activation(x: jax.Array) -> jax.Array:
    """Constrain ``x`` to full replication over the ambient auto mesh.

    ``constrain_activation`` cannot express this (an all-``None`` spec is its
    no-op case); this is an explicit "materialize the whole tensor on every
    chip HERE" — used for the embedding-table view feeding the token gather,
    where one up-front all-gather beats the involuntary full
    rematerialization GSPMD otherwise inserts on the gather output. No-op
    without an ambient mesh."""
    from zero_transformer_tpu.utils.jax_compat import get_abstract_mesh

    amesh = get_abstract_mesh()
    if amesh is None or not amesh.axis_names:
        return x
    return jax.lax.with_sharding_constraint(x, P(*(None,) * x.ndim))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())
