"""Device mesh construction.

Replaces the reference's 1-D ``Mesh(jax.devices(), ("dp",))``
(reference ``main_zero.py:227-228``) with a named 6-axis mesh:

- ``data``: data parallelism (+ ZeRO sharding axis)
- ``fsdp``: parameter-shard axis for ZeRO-3/FSDP layouts
- ``expert``: expert parallelism (MoE layers; all-to-all dispatch)
- ``tensor``: Megatron tensor parallelism
- ``sequence``: ring-attention context parallelism
- ``pipe``: GPipe pipeline parallelism (layer stages; ppermute wavefront)

Axes of size 1 cost nothing; collectives lower onto ICI via GSPMD.
"""
from __future__ import annotations

import math
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh

from zero_transformer_tpu.config import MeshConfig

DATA_AXIS = "data"
FSDP_AXIS = "fsdp"
EXPERT_AXIS = "expert"
TENSOR_AXIS = "tensor"
SEQUENCE_AXIS = "sequence"
PIPE_AXIS = "pipe"
AXES = (PIPE_AXIS, DATA_AXIS, FSDP_AXIS, EXPERT_AXIS, TENSOR_AXIS, SEQUENCE_AXIS)


def make_mesh(
    cfg: Optional[MeshConfig] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build the mesh, inferring the ``data`` axis size when it is -1."""
    cfg = cfg or MeshConfig()
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    fixed = cfg.pipe * cfg.fsdp * cfg.expert * cfg.tensor * cfg.sequence
    if n % fixed:
        raise ValueError(
            f"{n} devices not divisible by pipe*fsdp*expert*tensor*sequence={fixed}"
        )
    data = cfg.data if cfg.data != -1 else n // fixed
    if data * fixed != n:
        raise ValueError(
            f"mesh {cfg.pipe}x{data}x{cfg.fsdp}x{cfg.expert}x{cfg.tensor}"
            f"x{cfg.sequence} != {n} devices"
        )
    # pipe leads: stage boundaries land on the slowest interconnect dimension
    shape = (cfg.pipe, data, cfg.fsdp, cfg.expert, cfg.tensor, cfg.sequence)
    if cfg.dcn_data > 1:
        return _hybrid_mesh(cfg, data, devices)
    try:
        # topology-aware placement: keeps collective-heavy axes on adjacent
        # ICI links on real TPU slices
        from jax.experimental import mesh_utils

        arr = mesh_utils.create_device_mesh(shape, devices=devices)
    except Exception:
        arr = np.asarray(devices).reshape(shape)
    return Mesh(arr, AXES)


def _hybrid_mesh(cfg: MeshConfig, data: int, devices) -> Mesh:
    """Multi-slice mesh: the data axis spans ``dcn_data`` DCN-connected
    groups; all model axes stay inside one ICI domain each (the
    scaling-book layout — only the per-step gradient reduction crosses the
    slow network). Uses TPU ``slice_index`` granules when the platform
    provides them, falling back to process granules (multi-host CPU, or
    single-slice-per-host topologies). Loud on any mismatch: a user who
    asked for a DCN layout must not silently get a DCN-crossing tensor
    axis instead."""
    from jax.experimental import mesh_utils

    if data % cfg.dcn_data:
        raise ValueError(
            f"data={data} not divisible by dcn_data={cfg.dcn_data}"
        )
    ici_shape = (
        cfg.pipe, data // cfg.dcn_data, cfg.fsdp, cfg.expert, cfg.tensor,
        cfg.sequence,
    )
    dcn_shape = (1, cfg.dcn_data, 1, 1, 1, 1)
    errs = []
    for process_is_granule in (False, True):
        try:
            arr = mesh_utils.create_hybrid_device_mesh(
                ici_shape, dcn_shape, devices=devices,
                process_is_granule=process_is_granule,
            )
            return Mesh(arr, AXES)
        except Exception as e:  # noqa: BLE001 — jax raises ValueError for
            # granule mismatches but NotImplementedError/AssertionError for
            # unplaceable per-granule topologies; all of them must reach the
            # combined loud error below, not escape raw mid-fallback
            errs.append(f"{type(e).__name__}: {e}")
    raise ValueError(
        f"cannot build hybrid mesh (ici={ici_shape}, dcn={dcn_shape}) over "
        f"{len(devices)} devices: {' | '.join(errs)}"
    )


def zero_axes(mesh: Mesh) -> tuple[str, ...]:
    """Mesh axes the ZeRO shard spans: data (and fsdp when present)."""
    axes = []
    if mesh.shape[DATA_AXIS] > 1:
        axes.append(DATA_AXIS)
    if mesh.shape[FSDP_AXIS] > 1:
        axes.append(FSDP_AXIS)
    return tuple(axes) or (DATA_AXIS,)


def single_device_mesh() -> Mesh:
    return make_mesh(MeshConfig(data=1), devices=jax.devices()[:1])
