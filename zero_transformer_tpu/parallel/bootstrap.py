"""Multi-host process bootstrap.

The reference relied on the implicit jax[tpu] runtime to bring up its v3-32
pods (reference ``main_zero.py:181-184`` just reads ``jax.device_count()``;
process striping at ``:377-387``). The modern explicit path is
``jax.distributed.initialize``, which wires the DCN coordination service so
``jax.process_count()/process_index()`` — and with them loader striping,
process-gated logging, and multi-process Orbax — are correct on any platform
(TPU pods, CPU multi-process tests, GPU clusters).

``maybe_initialize`` is idempotent and env-driven: it initializes only when
coordinator env vars are present (or the platform advertises cluster
autodetection), so single-process runs cost nothing and need no flags.
"""
from __future__ import annotations

import logging
import os
from typing import Optional

import jax

log = logging.getLogger("zero_transformer_tpu")

# env vars jax.distributed.initialize reads when called with no arguments
_COORD_VARS = ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS")


def maybe_initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Initialize jax.distributed when configured; returns True if initialized.

    Resolution order: explicit args → ``JAX_COORDINATOR_ADDRESS`` (+
    ``JAX_NUM_PROCESSES``/``JAX_PROCESS_ID``) → ``COORDINATOR_ADDRESS`` (+
    ``NUM_PROCESSES``/``PROCESS_ID``) → not distributed (no-op).
    Safe to call twice (second call is a no-op).
    """
    already = getattr(jax.distributed, "is_initialized", None)
    if callable(already) and already():
        return True

    if coordinator_address is None:
        for var in _COORD_VARS:
            if os.environ.get(var):
                coordinator_address = os.environ[var]
                prefix = var.removesuffix("COORDINATOR_ADDRESS")
                if num_processes is None and os.environ.get(f"{prefix}NUM_PROCESSES"):
                    num_processes = int(os.environ[f"{prefix}NUM_PROCESSES"])
                if process_id is None and os.environ.get(f"{prefix}PROCESS_ID"):
                    process_id = int(os.environ[f"{prefix}PROCESS_ID"])
                break
        else:
            return False

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    log.info(
        "jax.distributed initialized: process %d/%d via %s",
        jax.process_index(),
        jax.process_count(),
        coordinator_address,
    )
    return True
