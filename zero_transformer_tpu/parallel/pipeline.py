"""GPipe pipeline parallelism: layer stages over the ``pipe`` mesh axis.

Beyond the reference (SURVEY §2 checklist: PP = none). TPU-first design:

- the stacked ``[n_layers, ...]`` block params (``nn.scan`` layout) shard
  their layer dim over ``pipe`` (``sharding.LOGICAL_RULES["layers"]``), so
  each stage owns ``n_layers / pipe`` contiguous layers with NO parameter
  movement — stage locality falls out of the sharding;
- the microbatch wavefront is a ``lax.fori_loop`` of ``M + P - 1`` ticks
  under a PARTIAL-MANUAL ``shard_map`` (manual over ``pipe`` only):
  activations hop stages via ``ppermute`` (neighbor ICI traffic), while the
  data/tensor/expert axes stay auto so GSPMD still handles DP gradient
  reduction, Megatron TP, and MoE dispatch inside each stage;
- backward is plain ``jax.grad`` through the loop (``ppermute`` transposes
  to the reverse hop), giving the GPipe fill-drain schedule; per-block
  rematerialization (``cfg.remat``) bounds the stashed activations;
- every rank runs identical code; rank-dependent work (embed on the first
  stage, head + loss on the last) is selected with ``where`` masks — no
  divergent control flow, one compiled program (SPMD).

The bubble fraction is the textbook (P-1)/(M+P-1): gradient-accumulation
microbatches ARE the pipeline microbatches. ``pp_schedule="interleaved"``
(``core_interleaved``) shrinks it toward (P-1)/(V*M+P-1): each rank runs V
virtual stages of n_layers/(P*V) layers and every microbatch makes V laps
around the ring, so the fill/drain ramps are paid in stage units V× smaller
(arXiv:2412.14374's collectives-off-the-critical-path direction, on the
same stage_slot single-source stage forward as GPipe and 1F1B).
"""
from __future__ import annotations

import os
from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from zero_transformer_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zero_transformer_tpu.config import resolve_dtype
from zero_transformer_tpu.ops.losses import chunked_next_token_loss, next_token_loss
from zero_transformer_tpu.parallel.mesh import PIPE_AXIS
from zero_transformer_tpu.parallel.sharding import restrict_spec


def _pipe_part(spec: P) -> P:
    """Keep only the ``pipe`` entries of a spec (manual axis); every other
    axis stays auto under the partial-manual shard_map."""
    return restrict_spec(spec, {PIPE_AXIS})


def interleaved_slot(t, rank, n_stages: int, interleave: int, n_micro: int):
    """What (rank, tick) works on under the interleaved schedule — the ONE
    index arithmetic shared by ``core_interleaved`` (traced values) and the
    dataflow simulation test (concrete ints), so the schedule the tests
    prove is the schedule the engine runs.

    Items flow in groups of P microbatches through V chunk-laps: item
    j = t - rank decodes as (group, chunk v, lane) = (j // (V*P),
    (j % (V*P)) // P, j % P), microbatch = group*P + lane, global stage
    (the layer-chunk id) = v*P + rank. Returns
    ``(valid, mb, v, chunk, first, final)`` where ``first`` marks the
    embedding stage (rank 0, lap 0) and ``final`` the loss stage
    (last rank, last lap).
    """
    V, P_, M = interleave, n_stages, n_micro
    j = t - rank
    jc = jnp.clip(j, 0, V * M - 1)
    g, rem = jc // (V * P_), jc % (V * P_)
    v, lane = rem // P_, rem % P_
    mb = g * P_ + lane
    chunk = v * P_ + rank
    valid = (j >= 0) & (j < V * M)
    first = (rank == 0) & (v == 0)
    final = (rank == P_ - 1) & (v == V - 1)
    return valid, mb, v, chunk, first, final


def bubble_fraction(
    pp_schedule: str, n_stages: int, n_micro: int, interleave: int = 1
) -> float:
    """Idle fraction of the pipeline wavefront for a schedule — the ONE
    analytic formula shared by the trainer's ``train/bubble_frac`` gauge,
    ``memory_analysis``, and the step bench (they must never disagree).

    gpipe: (P-1)/(M+P-1) — fill + drain in full-stage units.
    1f1b: (2P-2)/(M+2P-2) — its unified fwd+bwd ticks pay both ramps
      (the schedule trades bubble for the O(P) stash, not the reverse).
    interleaved: (P-1)/(V*M+P-1) — V virtual stages per rank make the
      ramp units V× smaller.
    """
    if pp_schedule not in ("gpipe", "1f1b", "interleaved"):
        raise ValueError(
            f"pp_schedule must be 'gpipe', '1f1b', or 'interleaved', "
            f"got {pp_schedule!r}"
        )
    P_, M, V = n_stages, max(n_micro, 1), max(interleave, 1)
    if P_ <= 1:
        return 0.0
    if pp_schedule == "1f1b":
        return (2 * P_ - 2) / (M + 2 * P_ - 2)
    if pp_schedule == "interleaved":
        return (P_ - 1) / (V * M + P_ - 1)
    return (P_ - 1) / (M + P_ - 1)


def _has_pipe(spec: P) -> bool:
    """True when a param spec shards over the pipe axis (the stacked layer
    blocks); False for pipe-REPLICATED params (wte, final norm, head) whose
    gradients arrive as per-rank partials and need a pipe-psum."""
    return any(
        PIPE_AXIS in (e if isinstance(e, tuple) else (e,))
        for e in spec
        if e is not None
    )


def _pipe_sharded_map(plan) -> object:
    """Per-param bool tree: sharded over pipe? ONE derivation (from the
    stored-param specs) shared by every schedule and the ZeRO-2 core — the
    pipe entries are identical in plan.state.params and plan.zero, but a
    single source can't diverge."""
    return jax.tree.map(lambda ns: _has_pipe(ns.spec), plan.state.params)


def _psum_pipe_replicated(grads, pipe_sharded):
    """Sum the per-rank partial grads of pipe-REPLICATED params (rank 0 did
    the embedding work, the last rank the head); pipe-SHARDED layer grads
    are already rank-complete. The stage-0/1 GPipe path gets this sum for
    free from its shard_map transpose; every hand-differentiated path
    (1F1B, the ZeRO-2 core's GPipe closure) places it with this ONE helper."""
    return jax.tree.map(
        lambda g, hp: g if hp else jax.lax.psum(g, PIPE_AXIS),
        grads, pipe_sharded,
    )


def make_pp_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    plan,
    zero_stage: int = 1,
    schedule: Optional[Callable] = None,
    tx_factory: Optional[Callable] = None,
    pp_schedule: str = "gpipe",
    grad_accum_dtype: str = "float32",
    pp_interleave: int = 1,
) -> Callable:
    """Fused train step for meshes with an active ``pipe`` axis.

    Same signature/contract as ``zero.make_train_step``: ``(state, batch,
    rng) -> (state, metrics)`` with ``batch`` int32 [M, global_batch, seq]
    — the leading gradient-accumulation axis doubles as the pipeline
    microbatch axis, so M also sets the bubble fraction.

    ZeRO stages:

    - 0/1: the wavefront shard_map is manual over ``pipe`` ONLY; the data
      axis stays auto, so GSPMD lowers the DP gradient reduction and the
      (stage-1) sharded optimizer math from the plan's shardings.
    - 2: the whole step — pipe engine, ``psum_scatter`` gradient
      reduce-scatter, sharded optimizer update, ``all_gather`` of updated
      params — runs in ONE shard_map manual over ``pipe`` + the ZeRO axes,
      reusing ``zero.ZeroCollectives`` (the same hand-placed collective
      schedule as the non-pipe explicit core; round-3 VERDICT missing #4
      capped pipe at stage 1). BOTH schedules compose: the GPipe wavefront
      via value_and_grad, and 1F1B via its hand-placed per-tick vjp — the
      memory story that motivates 1F1B (O(P) stash) is exactly the
      large-model-on-small-HBM regime that also wants ZeRO-2 (round-4
      VERDICT weak #3).
    - 3 is rejected: data-sharded parameter storage would all-gather inside
      every wavefront tick.

    ``tx_factory(global_norm_fn)`` mirrors ``zero.make_train_step``: at
    stage 2 it rebuilds the optimizer with a shard+pipe-aware grad-clip
    norm (each pipe rank owns different layers AND each ZeRO shard owns a
    slice, so the true global norm needs psums over both).
    """
    from zero_transformer_tpu.models.gpt import (
        Block,
        _dense,
        _norm,
        doc_ids_from_tokens,
        mask_boundary_labels,
        resolve_remat_policy,
    )
    from zero_transformer_tpu.parallel.mesh import TENSOR_AXIS
    from zero_transformer_tpu.parallel.zero import TrainState, _accum_add, _accum_dtype

    cfg = model.cfg
    n_stages = mesh.shape[PIPE_AXIS]
    if pp_schedule not in ("gpipe", "1f1b", "interleaved"):
        # validate at the API boundary too (MeshConfig validates its own
        # field, but direct callers bypass it) — a typo must not silently
        # build the gpipe schedule while the user expects 1F1B's O(P) memory
        # or interleaved's smaller bubble
        raise ValueError(
            f"pp_schedule must be 'gpipe', '1f1b', or 'interleaved', "
            f"got {pp_schedule!r}"
        )
    acc_dt = _accum_dtype(grad_accum_dtype)
    if acc_dt != jnp.float32 and pp_schedule != "1f1b":
        raise NotImplementedError(
            "grad_accum_dtype=bfloat16 requires pp_schedule='1f1b' (its "
            "gradient accumulator is a hand-placed scan carry; GPipe's and "
            "interleaved's live inside jax's scan-VJP machinery, which "
            "follows the param dtype) — and 1F1B is the memory-starved "
            "regime the knob exists for"
        )
    interleave = pp_interleave if pp_schedule == "interleaved" else 1
    if pp_schedule == "interleaved" and pp_interleave < 2:
        raise ValueError(
            "pp_schedule='interleaved' needs pp_interleave >= 2 (1 virtual "
            "stage per rank is exactly gpipe — ask for that by name)"
        )
    if pp_schedule != "interleaved" and pp_interleave > 1:
        raise ValueError(
            f"pp_interleave={pp_interleave} only applies to "
            f"pp_schedule='interleaved'"
        )
    blocks_pipe_sharded = any(
        jax.tree.leaves(
            jax.tree.map(
                lambda ns: _has_pipe(ns.spec), plan.state.params["blocks"]
            )
        )
    )
    if pp_schedule == "interleaved" and blocks_pipe_sharded:
        raise ValueError(
            "interleaved schedule needs the block stack stored "
            "pipe-REPLICATED (virtual stage v of rank r runs layers "
            "[(v*P+r)*Lc, ...) — a round-robin set no contiguous pipe shard "
            "can hold); build the plan with make_plan(..., "
            "pp_schedule='interleaved')"
        )
    if pp_schedule != "interleaved" and not blocks_pipe_sharded:
        raise ValueError(
            f"plan stores the block stack pipe-replicated (an interleaved "
            f"plan) but pp_schedule={pp_schedule!r} expects contiguous "
            f"pipe-sharded stages; rebuild the plan with the matching "
            f"pp_schedule"
        )
    if zero_stage >= 3:
        raise NotImplementedError(
            "pipeline parallelism supports ZeRO stage 0-2; stage 3 (params "
            "stored data-sharded) would put a per-tick all-gather inside the "
            "wavefront — use fsdp without pipe for that regime"
        )
    if mesh.shape[TENSOR_AXIS] > 1 and os.environ.get("ZTPU_PIPE_TENSOR_PROBE") != "1":
        # XLA's SPMD partitioner CHECK-fails (spmd_partitioner_util.cc:495)
        # partitioning auto tensor-sharded ops inside a pipe-manual shard_map
        # region (jax 0.9.0; re-verified still crashing 2026-07-30 — an
        # upstream partitioner bug, not a logic error here). Fail loudly
        # instead of crashing the process. ZTPU_PIPE_TENSOR_PROBE=1 bypasses
        # the guard for re-probing on future jax upgrades (subprocess only:
        # the failure is a SIGABRT, not an exception).
        raise NotImplementedError(
            "pipe x tensor meshes currently crash XLA's SPMD partitioner; "
            "use pipe with data/fsdp/expert axes"
        )
    if not cfg.scan_layers:
        raise ValueError("pipeline parallelism requires scan_layers=True")
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by pipe={n_stages}"
        )
    if cfg.position == "learned":
        raise NotImplementedError(
            "pipeline parallelism supports alibi/rope positions"
        )
    packed = cfg.doc_sep_token is not None
    l_local = cfg.n_layers // n_stages
    dtype = resolve_dtype(cfg.compute_dtype)
    param_dtype = resolve_dtype(cfg.param_dtype)

    # the SAME module classes the plain Transformer is built from, applied
    # piecewise against param subtrees — no re-implemented math
    embed_mod = nn.Embed(
        num_embeddings=cfg.vocab_size,
        features=cfg.d_model,
        dtype=dtype,
        param_dtype=param_dtype,
    )
    norm_mod = _norm(cfg, dtype, "ln_f")
    head_mod = (
        None
        if cfg.tie_embeddings
        else _dense(cfg.vocab_size, ("embed", "vocab"), 0.02, dtype, param_dtype, "lm_head")
    )
    block_cls = Block
    if cfg.remat:
        # same per-block checkpointing (and policy) as the plain path —
        # resolve_remat_policy is the shared mapping, so a policy added in
        # models/gpt.py cannot silently degrade to None here — bounds the
        # activations stashed across the M+P-1 wavefront ticks
        block_cls = nn.remat(
            Block, prevent_cse=False, policy=resolve_remat_policy(cfg)
        )
    def _make_stage_mod(length):
        return nn.scan(
            block_cls,
            variable_axes={"params": 0},
            split_rngs={"params": True, "dropout": True},
            length=length,
            metadata_params={nn.PARTITION_NAME: "layers"},
        )(cfg, False, False, None, None)  # deterministic=False: train step

    stage_mod = _make_stage_mod(l_local)

    def stage_slot(p, blocks_p, smod, x, mb, batch, rng, first, fold):
        """THE per-rank stage forward — single source for every schedule
        (GPipe ticks, both 1F1B slots, the interleaved laps, and through
        them the ZeRO-2 core). Returns ``(h_out, (loss, aux))`` for
        microbatch ``mb`` given inbox activation ``x``; ``blocks_p`` is the
        stacked params this slot's layers run on (the rank's contiguous
        stage for GPipe/1F1B, one dynamically sliced virtual chunk for
        interleaved) applied through ``smod`` (an nn.scan of the matching
        length). Rank-dependent work is where-masked (embed feeds h_in only
        where ``first``; the head+loss value is only meaningful where the
        caller masks it for the final stage) — SPMD, one compiled body.
        ``fold`` keys the dropout rng (the global stage id: rank for
        contiguous schedules, v*P+rank for interleaved — identical at V=1).
        Every rank holds the full pipe-replicated batch, so packed-document
        ids are re-derived locally with the ONE shared rule
        (models/gpt.py doc_ids_from_tokens) instead of riding the hops."""
        M = batch.shape[0]
        tokens = batch[jnp.clip(mb, 0, M - 1)]
        emb = embed_mod.apply({"params": p["wte"]}, tokens)
        h_in = jnp.where(first, emb, x)
        mrng = jax.random.fold_in(jax.random.fold_in(rng, mb), fold)
        carry_in = (h_in.astype(dtype), jnp.zeros((), jnp.float32))
        if packed:
            carry_in = carry_in + (doc_ids_from_tokens(tokens, cfg.doc_sep_token),)
        (h_out, aux, *_), _ = smod.apply(
            {"params": blocks_p}, carry_in, rngs={"dropout": mrng}
        )
        h_norm = norm_mod.apply({"params": p["ln_f"]}, h_out)
        labels = tokens
        ignore = None
        if packed:
            labels = mask_boundary_labels(
                tokens, doc_ids_from_tokens(tokens, cfg.doc_sep_token)
            )
            ignore = -1
        if cfg.loss_chunk:
            # same chunked-CE path as the fused model: the [b, T, vocab]
            # logits tile never materializes on the last rank either
            w_dv = (
                jnp.asarray(p["wte"]["embedding"], dtype).T
                if cfg.tie_embeddings
                else jnp.asarray(p["lm_head"]["kernel"], dtype)
            )
            loss = chunked_next_token_loss(
                h_norm, w_dv, labels, cfg.loss_chunk, ignore_index=ignore
            )
        else:
            if cfg.tie_embeddings:
                logits = embed_mod.apply({"params": p["wte"]}, h_norm, method="attend")
            else:
                logits = head_mod.apply({"params": p["lm_head"]}, h_norm)
            loss = next_token_loss(logits, labels, ignore_index=ignore)
        return h_out, (loss, aux)

    def core(params, batch, rng, reduce=True):
        """GPipe wavefront loss. ``reduce=True`` returns the pipe-psum'd
        total (the stage-0/1 shard_map, whose ``out_specs=P()`` transpose
        handles replication correctly). ``reduce=False`` returns the
        rank-LOCAL (loss_sum + aux_sum)/M — REQUIRED when differentiating
        inside a pipe-manual region (the ZeRO-2 core): seeding cotangent 1
        on every rank of a psum-produced replicated loss makes the psum
        transpose sum P cotangents and scales every gradient by P. Adam +
        norm-clipping are scale-invariant, so trajectories still matched —
        the observable damage was grad_norm (and the clip threshold)
        off by exactly P. Cross-rank gradient flow still works without the
        psum: cotangents ride the ppermute transposes back through the
        scan."""
        rank = jax.lax.axis_index(PIPE_AXIS)
        M = batch.shape[0]
        n_ticks = M + n_stages - 1

        def tick(carry, t):
            outbox, loss_sum, aux_sum = carry
            # activations hop to the next stage; the wrap-around edge
            # (last -> first) always carries an inactive bubble slot
            inbox = jax.lax.ppermute(
                outbox,
                PIPE_AXIS,
                [(i, (i + 1) % n_stages) for i in range(n_stages)],
            )
            mb = t - rank  # microbatch this rank works on at tick t
            h_out, (loss_t, aux) = stage_slot(
                params, params["blocks"], stage_mod, inbox, mb, batch, rng,
                rank == 0, rank,
            )
            # only the last rank's loss counts, and there mb IS the
            # microbatch finishing at the tail (mb = t - (P-1) = mb_done)
            is_last = rank == n_stages - 1
            loss_sum = loss_sum + jnp.where(is_last & (mb >= 0), loss_t, 0.0)
            aux_sum = aux_sum + jnp.where((mb >= 0) & (mb < M), aux, 0.0)
            return (h_out, loss_sum, aux_sum), None

        # bubble payload; shape [b, T, d]
        h0 = jnp.zeros((batch.shape[1], batch.shape[2], cfg.d_model), dtype)
        # scan, not fori_loop: the wavefront must be reverse-differentiable
        # (grad through it produces the GPipe drain schedule)
        (_, loss_sum, aux_sum), _ = jax.lax.scan(
            tick,
            (h0.astype(dtype), jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
            jnp.arange(n_ticks),
        )
        local = loss_sum / M
        if cfg.n_experts > 0:
            local = local + aux_sum / M
        if not reduce:
            return local
        return jax.lax.psum(local, PIPE_AXIS)

    # ------------------------------------------------ 1F1B schedule (opt-in)
    # Unified fwd+bwd ticks with a stash-and-recompute backward: each rank
    # keeps only the INPUT activation of every in-flight microbatch (a ring
    # of S = 2P slots, O(P) — GPipe's grad-through-scan stashes O(M) carry
    # activations) and re-runs the stage forward inside jax.vjp on the
    # backward slot. Schedule: at tick t rank r forwards microbatch t - r
    # and backwards microbatch t - 2(P-1) + r, so the last rank's forward
    # and backward of the same microbatch share a tick (fwd -> loss -> seed
    # cotangent immediately — the 1F1B property). Total ticks M + 2P - 2.
    # Trade: ~one extra stage-forward per microbatch vs GPipe-with-remat
    # (the fwd slot's output cannot wait for the bwd slot's recompute), so
    # use it when accumulation depth M at the target context has outgrown
    # HBM, not as the default. See docs/DESIGN.md.
    def core_1f1b(params, batch, rng):
        rank = jax.lax.axis_index(PIPE_AXIS)
        is_last = rank == n_stages - 1
        M, b, T = batch.shape
        n_ticks = M + 2 * (n_stages - 1)
        S = 2 * n_stages  # ring slots; in-flight span is 2(P-1-r) < S

        def fwd_fn(p, x, mb):
            return stage_slot(
                p, p["blocks"], stage_mod, x, mb, batch, rng, rank == 0, rank
            )

        fwd_ring = [(i, (i + 1) % n_stages) for i in range(n_stages)]
        bwd_ring = [(i, (i - 1) % n_stages) for i in range(n_stages)]

        def tick(carry, t):
            out_f, out_b, stash, grads, loss_sum, aux_sum = carry
            inbox_f = jax.lax.ppermute(out_f, PIPE_AXIS, fwd_ring)
            inbox_b = jax.lax.ppermute(out_b, PIPE_AXIS, bwd_ring)
            mb_f = t - rank
            mb_b = t - 2 * (n_stages - 1) + rank
            b_valid = (mb_b >= 0) & (mb_b < M)

            # forward slot: emit y now, stash the INPUT for the bwd slot.
            # Out-of-range mb_f writes land in ring slots outside the
            # in-flight span (span < S), so they can never clobber a live one.
            y_f, _ = fwd_fn(params, inbox_f, mb_f)
            stash = jax.lax.dynamic_update_index_in_dim(
                stash, inbox_f.astype(dtype), jnp.mod(mb_f, S), 0
            )

            # backward slot: recompute the stage at the stashed input, seed
            # cotangents — upstream dx for interior ranks, d(loss)=1 on the
            # last rank (whose fwd of mb_b happened THIS tick, same slot)
            x_b = jax.lax.dynamic_index_in_dim(stash, jnp.mod(mb_b, S), 0, keepdims=False)
            (y_b, (loss_b, aux_b)), vjp = jax.vjp(
                lambda p, x: fwd_fn(p, x, mb_b), params, x_b
            )
            gy = jnp.where(is_last, 0.0, inbox_b).astype(y_b.dtype)
            gloss = jnp.where(is_last, 1.0, 0.0).astype(loss_b.dtype)
            gaux = jnp.asarray(1.0 if cfg.n_experts > 0 else 0.0, aux_b.dtype)
            dparams, dx = vjp((gy, (gloss, gaux)))
            grads = jax.tree.map(
                lambda a, g: _accum_add(a, jnp.where(b_valid, g, 0)),
                grads, dparams,
            )
            loss_sum = loss_sum + jnp.where(b_valid & is_last, loss_b, 0.0)
            aux_sum = aux_sum + jnp.where(b_valid, aux_b, 0.0)
            return (y_f.astype(dtype), dx.astype(dtype), stash, grads,
                    loss_sum, aux_sum), None

        zero_x = jnp.zeros((b, T, cfg.d_model), dtype)
        carry0 = (
            zero_x, zero_x,
            jnp.zeros((S, b, T, cfg.d_model), dtype),
            # the accumulator is acc_dt (f32 default — matching the fused
            # step's always-f32 buffer even for low-precision param dtypes;
            # bfloat16 halves the param-sized carry, the 1F1B memory story)
            jax.tree.map(lambda p: jnp.zeros(p.shape, acc_dt), params),
            jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32),
        )
        (_, _, _, grads, loss_sum, aux_sum), _ = jax.lax.scan(
            tick, carry0, jnp.arange(n_ticks)
        )
        loss = jax.lax.psum(loss_sum, PIPE_AXIS) / M
        if cfg.n_experts > 0:
            loss = loss + jax.lax.psum(aux_sum, PIPE_AXIS) / M
        grads = jax.tree.map(lambda g: g.astype(jnp.float32) / M, grads)
        grads = _psum_pipe_replicated(grads, _pipe_sharded_map(plan))
        return loss, grads

    # ---------------------------------------------- interleaved schedule
    # V virtual stages per rank: global stage s = v*P + r runs layers
    # [s*Lc, (s+1)*Lc) with Lc = L/(P*V); every microbatch makes V laps
    # around the ring, so the fill/drain ramps are paid in Lc-layer units —
    # bubble (P-1)/(V*M+P-1) vs GPipe's (P-1)/(M+P-1). Microbatches flow in
    # GROUPS OF P (Megatron's constraint, M % P == 0): item j of the tick
    # sequence decodes as (group g, chunk v, lane i) = (j // (V*P),
    # (j % (V*P)) // P, j % P), microbatch m = g*P + i — ordered so the
    # wrap-around hop (rank P-1 finishing chunk v of m) arrives at rank 0
    # EXACTLY when chunk v+1 of m starts: no activation stash, the inbox is
    # always the live input. The block stack is pipe-REPLICATED (see
    # make_plan's interleaved rules); each tick dynamic-slices its chunk,
    # and chunk grads come back as disjoint per-rank partials summed by the
    # pipe psum that already covers wte/ln_f/head. Memory trade vs GPipe:
    # P× block-param storage, and the grad-through-scan stash grows with
    # the tick count (V*M+P-1 vs M+P-1 carries) — this is interleaved
    # GPipe, aimed at the bubble-bound regime, not the HBM-bound one
    # (that's 1F1B's job). See docs/TRAINING.md.
    l_chunk = cfg.n_layers // (n_stages * interleave) if interleave > 1 else l_local
    if interleave > 1 and cfg.n_layers % (n_stages * interleave):
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by "
            f"pipe*pp_interleave={n_stages * interleave}"
        )
    chunk_mod = _make_stage_mod(l_chunk) if interleave > 1 else stage_mod

    def core_interleaved(params, batch, rng, reduce=True):
        """Interleaved wavefront loss; same contract as ``core`` (GPipe),
        including the rank-LOCAL ``reduce=False`` form the ZeRO-2 manual
        region needs (see the GPipe wavefront docstring for why local)."""
        rank = jax.lax.axis_index(PIPE_AXIS)
        V = interleave
        M = batch.shape[0]
        if M % n_stages:
            raise ValueError(
                f"interleaved schedule needs microbatches (accum steps) "
                f"divisible by pipe: M={M}, pipe={n_stages} — groups of P "
                f"keep the wrap-around hop just-in-time"
            )
        n_ticks = V * M + n_stages - 1

        def tick(carry, t):
            outbox, loss_sum, aux_sum = carry
            inbox = jax.lax.ppermute(
                outbox,
                PIPE_AXIS,
                [(i, (i + 1) % n_stages) for i in range(n_stages)],
            )
            valid, mb, v, chunk, first, is_final = interleaved_slot(
                t, rank, n_stages, V, M
            )
            blocks_p = jax.tree.map(
                lambda x: jax.lax.dynamic_slice_in_dim(
                    x, chunk * l_chunk, l_chunk, axis=0
                ),
                params["blocks"],
            )
            h_out, (loss_t, aux) = stage_slot(
                params, blocks_p, chunk_mod, inbox, mb, batch, rng, first,
                chunk,
            )
            loss_sum = loss_sum + jnp.where(valid & is_final, loss_t, 0.0)
            aux_sum = aux_sum + jnp.where(valid, aux, 0.0)
            return (h_out, loss_sum, aux_sum), None

        h0 = jnp.zeros((batch.shape[1], batch.shape[2], cfg.d_model), dtype)
        (_, loss_sum, aux_sum), _ = jax.lax.scan(
            tick,
            (h0.astype(dtype), jnp.zeros((), jnp.float32),
             jnp.zeros((), jnp.float32)),
            jnp.arange(n_ticks),
        )
        local = loss_sum / M
        if cfg.n_experts > 0:
            local = local + aux_sum / M
        if not reduce:
            return local
        return jax.lax.psum(local, PIPE_AXIS)

    wavefront = core_interleaved if interleave > 1 else core

    if zero_stage >= 2:
        # both schedules feed the explicit ZeRO-2 core through ONE contract:
        # (params, batch, rng) -> (pipe-psum'd loss, pipe-correct full local
        # grads). 1F1B already produces exactly that (hand-placed vjp per
        # tick); GPipe gets it from value_and_grad of the rank-LOCAL loss
        # (see the wavefront docstring for why local) + the pipe-psum of
        # the pipe-replicated params' partial grads.
        def gpipe_loss_and_grads(params, batch, rng):
            local_loss, grads = jax.value_and_grad(
                lambda p: wavefront(p, batch, rng, reduce=False)
            )(params)
            grads = _psum_pipe_replicated(grads, _pipe_sharded_map(plan))
            return jax.lax.psum(local_loss, PIPE_AXIS), grads

        loss_and_grads = core_1f1b if pp_schedule == "1f1b" else gpipe_loss_and_grads
        return _pp_zero2_step(loss_and_grads, tx, mesh, plan, schedule, tx_factory)

    param_specs = jax.tree.map(lambda ns: _pipe_part(ns.spec), plan.state.params)
    pp_loss = shard_map(
        wavefront,
        mesh=mesh,
        in_specs=(param_specs, P(), P()),
        out_specs=P(),
        axis_names=frozenset({PIPE_AXIS}),
        check_vma=False,
    )
    pp_grads_1f1b = shard_map(
        core_1f1b,
        mesh=mesh,
        in_specs=(param_specs, P(), P()),
        out_specs=(P(), param_specs),
        axis_names=frozenset({PIPE_AXIS}),
        check_vma=False,
    )

    def constrain_zero(tree):
        return jax.lax.with_sharding_constraint(tree, plan.zero)

    def train_step(state: TrainState, batch: jax.Array, rng: jax.Array):
        step_rng = jax.random.fold_in(rng, state.step)
        if pp_schedule == "1f1b":
            loss, grads = pp_grads_1f1b(state.params, batch, step_rng)
        else:
            loss, grads = jax.value_and_grad(
                lambda p: pp_loss(p, batch, step_rng)
            )(state.params)
        grad_norm = optax.global_norm(grads)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        if zero_stage >= 1:
            updates = constrain_zero(updates)
        new_params = optax.apply_updates(state.params, updates)
        new_params = jax.lax.with_sharding_constraint(
            new_params, plan.state.params
        )
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "tokens": jnp.asarray(batch.size, jnp.float32),
        }
        if schedule is not None:
            metrics["learning_rate"] = schedule(state.step)
        return (
            TrainState(step=state.step + 1, params=new_params, opt_state=new_opt),
            metrics,
        )

    batch_shard = NamedSharding(mesh, P(None, *plan.batch.spec))
    return jax.jit(
        train_step,
        in_shardings=(plan.state, batch_shard, NamedSharding(mesh, P())),
        out_shardings=(plan.state, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )


def _pp_zero2_step(
    loss_and_grads: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    plan,
    schedule: Optional[Callable],
    tx_factory: Optional[Callable],
) -> Callable:
    """Pipe × explicit ZeRO-2: one shard_map manual over pipe + ZeRO axes.

    ``loss_and_grads(params, batch, rng) -> (loss, grads)`` is the
    schedule-specific pipe engine (the GPipe tick-scan differentiated via
    value_and_grad, or the 1F1B hand-placed-vjp loop), returning the
    pipe-psum'd loss and pipe-correct FULL local gradients; here the
    gradient reduce-scatter, sharded optimizer math, and param all-gather
    are hand-placed around it via ``zero.ZeroCollectives`` instead of
    leaving DP reduction to GSPMD. Lifts round-3's "pipe caps at ZeRO-1"
    block; round 5 extends it to both schedules."""
    from zero_transformer_tpu.parallel.mesh import zero_axes
    from zero_transformer_tpu.parallel.zero import TrainState, ZeroCollectives

    zc = ZeroCollectives(mesh, plan)
    zaxes = zero_axes(mesh)
    manual = frozenset({PIPE_AXIS, *zaxes})

    pipe_sharded = _pipe_sharded_map(plan)

    def pp_shard_norm(tree):
        """Global grad norm, per-leaf: psum over data for ZeRO-scattered
        leaves, psum over pipe for pipe-sharded (per-stage layer) leaves;
        pipe-replicated leaves contribute once (identical on every rank)."""
        total = jnp.zeros((), jnp.float32)
        for g, d, hp in zip(
            jax.tree.leaves(tree),
            jax.tree.leaves(zc.sdims),
            jax.tree.leaves(pipe_sharded),
        ):
            s = jnp.sum(jnp.square(g.astype(jnp.float32)))
            if d >= 0:
                s = jax.lax.psum(s, zc.axis)
            if hp:
                s = jax.lax.psum(s, PIPE_AXIS)
            total = total + s
        return jnp.sqrt(total)

    from zero_transformer_tpu.parallel.zero import apply_tx_factory

    tx_inner = (
        apply_tx_factory(tx_factory, pp_shard_norm, zc)
        if tx_factory is not None
        else tx
    )
    probe_state = jax.eval_shape(  # structure-only: nothing materializes
        tx_inner.init, {"w": jax.ShapeDtypeStruct((4, 4), jnp.float32)}
    )
    if any(
        isinstance(s, optax.FactoredState)
        for s in jax.tree.leaves(
            probe_state, is_leaf=lambda x: isinstance(x, optax.FactoredState)
        )
    ):
        # The sharded factored stats are ZeRO-axis-aware but not PIPE-aware:
        # pipe-stacked leaves' stats are stage-local [L/P, ...] inside the
        # manual region while the plan stores them replicated at the global
        # shape — a trace-time shape clash (and fixing it needs pipe-sharded
        # opt-state specs for the stat trees). Reject with the reason rather
        # than dying in an internal shard_map assertion.
        raise NotImplementedError(
            "adafactor does not compose with pipeline x ZeRO>=2 (factored "
            "stats are not pipe-aware); use adamw/lion with pipe at stage 2, "
            "or adafactor with pipe at stage <= 1"
        )

    def core(state: TrainState, batch: jax.Array, rng: jax.Array):
        step_rng = jax.random.fold_in(rng, state.step)
        # distinct dropout per ZeRO shard; the wavefront folds in pipe rank
        step_rng = jax.random.fold_in(step_rng, zc.dev_index())

        full_params = state.params  # stage 2: stored full along ZeRO axes
        param_shards = zc.slice_local(full_params)

        # the pipe engine hands back the pipe-psum'd loss and pipe-correct
        # full grads (pipe-replicated params' partials already summed);
        # only the ZeRO reduction over data remains to place here
        pipe_loss, grads = loss_and_grads(full_params, batch, step_rng)
        loss = jax.lax.pmean(pipe_loss, zc.axis)
        grads = zc.reduce_grads(grads)

        grad_norm = pp_shard_norm(grads)
        updates, new_opt = tx_inner.update(grads, state.opt_state, param_shards)
        new_shards = optax.apply_updates(param_shards, updates)
        new_params = zc.gather_full(new_shards)
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "tokens": jnp.asarray(batch.size * zc.zsize, jnp.float32),
        }
        if schedule is not None:
            metrics["learning_rate"] = schedule(state.step)
        return (
            TrainState(step=state.step + 1, params=new_params, opt_state=new_opt),
            metrics,
        )

    def manual_part(spec: P) -> P:
        return restrict_spec(spec, set(manual))

    state_specs = TrainState(
        step=P(),
        params=jax.tree.map(lambda ns: manual_part(ns.spec), plan.state.params),
        opt_state=jax.tree.map(lambda ns: manual_part(ns.spec), plan.state.opt_state),
    )
    batch_spec = manual_part(P(None, *plan.batch.spec))
    metric_specs = {"loss": P(), "grad_norm": P(), "tokens": P()}
    if schedule is not None:
        metric_specs["learning_rate"] = P()

    mapped = shard_map(
        core,
        mesh=mesh,
        in_specs=(state_specs, batch_spec, P()),
        out_specs=(state_specs, metric_specs),
        axis_names=manual,
        check_vma=False,
    )
    return jax.jit(
        mapped,
        in_shardings=(
            plan.state,
            NamedSharding(mesh, batch_spec),
            NamedSharding(mesh, P()),
        ),
        out_shardings=(plan.state, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )
