"""GPipe pipeline parallelism: layer stages over the ``pipe`` mesh axis.

Beyond the reference (SURVEY §2 checklist: PP = none). TPU-first design:

- the stacked ``[n_layers, ...]`` block params (``nn.scan`` layout) shard
  their layer dim over ``pipe`` (``sharding.LOGICAL_RULES["layers"]``), so
  each stage owns ``n_layers / pipe`` contiguous layers with NO parameter
  movement — stage locality falls out of the sharding;
- the microbatch wavefront is a ``lax.fori_loop`` of ``M + P - 1`` ticks
  under a PARTIAL-MANUAL ``shard_map`` (manual over ``pipe`` only):
  activations hop stages via ``ppermute`` (neighbor ICI traffic), while the
  data/tensor/expert axes stay auto so GSPMD still handles DP gradient
  reduction, Megatron TP, and MoE dispatch inside each stage;
- backward is plain ``jax.grad`` through the loop (``ppermute`` transposes
  to the reverse hop), giving the GPipe fill-drain schedule; per-block
  rematerialization (``cfg.remat``) bounds the stashed activations;
- every rank runs identical code; rank-dependent work (embed on the first
  stage, head + loss on the last) is selected with ``where`` masks — no
  divergent control flow, one compiled program (SPMD).

The bubble fraction is the textbook (P-1)/(M+P-1): gradient-accumulation
microbatches ARE the pipeline microbatches.
"""
from __future__ import annotations

import os
from typing import Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zero_transformer_tpu.config import resolve_dtype
from zero_transformer_tpu.ops.losses import next_token_loss
from zero_transformer_tpu.parallel.mesh import PIPE_AXIS
from zero_transformer_tpu.parallel.sharding import restrict_spec


def _pipe_part(spec: P) -> P:
    """Keep only the ``pipe`` entries of a spec (manual axis); every other
    axis stays auto under the partial-manual shard_map."""
    return restrict_spec(spec, {PIPE_AXIS})


def make_pp_train_step(
    model,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    plan,
    zero_stage: int = 1,
    schedule: Optional[Callable] = None,
    tx_factory: Optional[Callable] = None,
) -> Callable:
    """Fused train step for meshes with an active ``pipe`` axis.

    Same signature/contract as ``zero.make_train_step``: ``(state, batch,
    rng) -> (state, metrics)`` with ``batch`` int32 [M, global_batch, seq]
    — the leading gradient-accumulation axis doubles as the pipeline
    microbatch axis, so M also sets the bubble fraction.

    ZeRO stages:

    - 0/1: the wavefront shard_map is manual over ``pipe`` ONLY; the data
      axis stays auto, so GSPMD lowers the DP gradient reduction and the
      (stage-1) sharded optimizer math from the plan's shardings.
    - 2: the whole step — wavefront, ``psum_scatter`` gradient
      reduce-scatter, sharded optimizer update, ``all_gather`` of updated
      params — runs in ONE shard_map manual over ``pipe`` + the ZeRO axes,
      reusing ``zero.ZeroCollectives`` (the same hand-placed collective
      schedule as the non-pipe explicit core; round-3 VERDICT missing #4
      capped pipe at stage 1).
    - 3 is rejected: data-sharded parameter storage would all-gather inside
      every wavefront tick.

    ``tx_factory(global_norm_fn)`` mirrors ``zero.make_train_step``: at
    stage 2 it rebuilds the optimizer with a shard+pipe-aware grad-clip
    norm (each pipe rank owns different layers AND each ZeRO shard owns a
    slice, so the true global norm needs psums over both).
    """
    from zero_transformer_tpu.models.gpt import (
        Block,
        _dense,
        _norm,
        doc_ids_from_tokens,
        mask_boundary_labels,
    )
    from zero_transformer_tpu.parallel.mesh import TENSOR_AXIS
    from zero_transformer_tpu.parallel.zero import TrainState

    cfg = model.cfg
    n_stages = mesh.shape[PIPE_AXIS]
    if zero_stage >= 3:
        raise NotImplementedError(
            "pipeline parallelism supports ZeRO stage 0-2; stage 3 (params "
            "stored data-sharded) would put a per-tick all-gather inside the "
            "wavefront — use fsdp without pipe for that regime"
        )
    if mesh.shape[TENSOR_AXIS] > 1 and os.environ.get("ZTPU_PIPE_TENSOR_PROBE") != "1":
        # XLA's SPMD partitioner CHECK-fails (spmd_partitioner_util.cc:495)
        # partitioning auto tensor-sharded ops inside a pipe-manual shard_map
        # region (jax 0.9.0; re-verified still crashing 2026-07-30 — an
        # upstream partitioner bug, not a logic error here). Fail loudly
        # instead of crashing the process. ZTPU_PIPE_TENSOR_PROBE=1 bypasses
        # the guard for re-probing on future jax upgrades (subprocess only:
        # the failure is a SIGABRT, not an exception).
        raise NotImplementedError(
            "pipe x tensor meshes currently crash XLA's SPMD partitioner; "
            "use pipe with data/fsdp/expert axes"
        )
    if not cfg.scan_layers:
        raise ValueError("pipeline parallelism requires scan_layers=True")
    if cfg.n_layers % n_stages:
        raise ValueError(
            f"n_layers={cfg.n_layers} not divisible by pipe={n_stages}"
        )
    if cfg.position == "learned":
        raise NotImplementedError(
            "pipeline parallelism supports alibi/rope positions"
        )
    packed = cfg.doc_sep_token is not None
    l_local = cfg.n_layers // n_stages
    dtype = resolve_dtype(cfg.compute_dtype)
    param_dtype = resolve_dtype(cfg.param_dtype)

    # the SAME module classes the plain Transformer is built from, applied
    # piecewise against param subtrees — no re-implemented math
    embed_mod = nn.Embed(
        num_embeddings=cfg.vocab_size,
        features=cfg.d_model,
        dtype=dtype,
        param_dtype=param_dtype,
    )
    norm_mod = _norm(cfg, dtype, "ln_f")
    head_mod = (
        None
        if cfg.tie_embeddings
        else _dense(cfg.vocab_size, ("embed", "vocab"), 0.02, dtype, param_dtype, "lm_head")
    )
    block_cls = Block
    if cfg.remat:
        # same per-block checkpointing (and policy) as the plain path
        # (models/gpt.py) — bounds the activations stashed across the
        # M+P-1 wavefront ticks
        policy = (
            jax.checkpoint_policies.dots_with_no_batch_dims_saveable
            if cfg.remat_policy == "dots"
            else None
        )
        block_cls = nn.remat(Block, prevent_cse=False, policy=policy)
    stage_mod = nn.scan(
        block_cls,
        variable_axes={"params": 0},
        split_rngs={"params": True, "dropout": True},
        length=l_local,
        metadata_params={nn.PARTITION_NAME: "layers"},
    )(cfg, False, False, None, None)  # deterministic=False: train step

    def core(params, batch, rng):
        rank = jax.lax.axis_index(PIPE_AXIS)
        M = batch.shape[0]
        n_ticks = M + n_stages - 1

        def embed_mb(i):
            x = batch[jnp.clip(i, 0, M - 1)]
            return embed_mod.apply({"params": params["wte"]}, x)

        def ids_mb(i):
            # every rank holds the full (pipe-replicated) batch, so the
            # packed-document ids need not ride the stage carry hops — each
            # rank derives them for whatever microbatch it is working on,
            # with the ONE shared rule (models/gpt.py doc_ids_from_tokens)
            x = batch[jnp.clip(i, 0, M - 1)]
            return doc_ids_from_tokens(x, cfg.doc_sep_token)

        def head_loss_mb(h, i):
            x = batch[jnp.clip(i, 0, M - 1)]
            h = norm_mod.apply({"params": params["ln_f"]}, h)
            if cfg.tie_embeddings:
                logits = embed_mod.apply(
                    {"params": params["wte"]}, h, method="attend"
                )
            else:
                logits = head_mod.apply({"params": params["lm_head"]}, h)
            if packed:
                labels = mask_boundary_labels(x, ids_mb(i))
                return next_token_loss(logits, labels, ignore_index=-1)
            return next_token_loss(logits, x)

        def tick(carry, t):
            outbox, loss_sum, aux_sum = carry
            # activations hop to the next stage; the wrap-around edge
            # (last -> first) always carries an inactive bubble slot
            inbox = jax.lax.ppermute(
                outbox,
                PIPE_AXIS,
                [(i, (i + 1) % n_stages) for i in range(n_stages)],
            )
            mb = t - rank  # microbatch this rank works on at tick t
            h_in = jnp.where(rank == 0, embed_mb(t), inbox)
            mrng = jax.random.fold_in(jax.random.fold_in(rng, mb), rank)
            carry_in = (h_in.astype(dtype), jnp.zeros((), jnp.float32))
            if packed:
                carry_in = carry_in + (ids_mb(mb),)
            (h_out, aux, *_), _ = stage_mod.apply(
                {"params": params["blocks"]},
                carry_in,
                rngs={"dropout": mrng},
            )
            mb_done = t - (n_stages - 1)  # microbatch finishing at the tail
            loss_t = head_loss_mb(h_out, mb_done)
            is_last = rank == n_stages - 1
            loss_sum = loss_sum + jnp.where(
                is_last & (mb_done >= 0), loss_t, 0.0
            )
            aux_sum = aux_sum + jnp.where((mb >= 0) & (mb < M), aux, 0.0)
            return (h_out, loss_sum, aux_sum), None

        h0 = embed_mb(0) * 0.0  # bubble payload; shape [b, T, d]
        # scan, not fori_loop: the wavefront must be reverse-differentiable
        # (grad through it produces the GPipe drain schedule)
        (_, loss_sum, aux_sum), _ = jax.lax.scan(
            tick,
            (h0.astype(dtype), jnp.zeros((), jnp.float32), jnp.zeros((), jnp.float32)),
            jnp.arange(n_ticks),
        )
        loss = jax.lax.psum(loss_sum, PIPE_AXIS) / M
        if cfg.n_experts > 0:
            loss = loss + jax.lax.psum(aux_sum, PIPE_AXIS) / M
        return loss

    if zero_stage >= 2:
        return _pp_zero2_step(core, tx, mesh, plan, schedule, tx_factory)

    param_specs = jax.tree.map(lambda ns: _pipe_part(ns.spec), plan.state.params)
    pp_loss = shard_map(
        core,
        mesh=mesh,
        in_specs=(param_specs, P(), P()),
        out_specs=P(),
        axis_names=frozenset({PIPE_AXIS}),
        check_vma=False,
    )

    def constrain_zero(tree):
        return jax.lax.with_sharding_constraint(tree, plan.zero)

    def train_step(state: TrainState, batch: jax.Array, rng: jax.Array):
        step_rng = jax.random.fold_in(rng, state.step)
        loss, grads = jax.value_and_grad(
            lambda p: pp_loss(p, batch, step_rng)
        )(state.params)
        grad_norm = optax.global_norm(grads)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        if zero_stage >= 1:
            updates = constrain_zero(updates)
        new_params = optax.apply_updates(state.params, updates)
        new_params = jax.lax.with_sharding_constraint(
            new_params, plan.state.params
        )
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "tokens": jnp.asarray(batch.size, jnp.float32),
        }
        if schedule is not None:
            metrics["learning_rate"] = schedule(state.step)
        return (
            TrainState(step=state.step + 1, params=new_params, opt_state=new_opt),
            metrics,
        )

    batch_shard = NamedSharding(mesh, P(None, *plan.batch.spec))
    return jax.jit(
        train_step,
        in_shardings=(plan.state, batch_shard, NamedSharding(mesh, P())),
        out_shardings=(plan.state, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )


def _pp_zero2_step(
    wavefront: Callable,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    plan,
    schedule: Optional[Callable],
    tx_factory: Optional[Callable],
) -> Callable:
    """Pipe × explicit ZeRO-2: one shard_map manual over pipe + ZeRO axes.

    ``wavefront(params, batch, rng) -> loss`` is the SAME GPipe tick-scan the
    stage-0/1 path uses (built in ``make_pp_train_step``); here the gradient
    reduce-scatter, sharded optimizer math, and param all-gather are
    hand-placed around it via ``zero.ZeroCollectives`` instead of leaving DP
    reduction to GSPMD. Lifts round-3's "pipe caps at ZeRO-1" block."""
    from zero_transformer_tpu.parallel.mesh import zero_axes
    from zero_transformer_tpu.parallel.zero import TrainState, ZeroCollectives

    zc = ZeroCollectives(mesh, plan)
    zaxes = zero_axes(mesh)
    manual = frozenset({PIPE_AXIS, *zaxes})

    def _has_pipe(spec: P) -> bool:
        return any(
            PIPE_AXIS in (e if isinstance(e, tuple) else (e,))
            for e in spec
            if e is not None
        )

    # True for params SHARDED over pipe (the stacked blocks); False for
    # pipe-REPLICATED ones (wte, final norm, untied head) whose gradients
    # arrive as per-rank partials — rank 0 does the embedding work, the last
    # rank the head — and must be pipe-psummed. The stage-0/1 path gets that
    # sum for free from the shard_map TRANSPOSE of its replicated in_specs;
    # with value_and_grad moved inside the manual region we place it by hand.
    pipe_sharded = jax.tree.map(lambda ns: _has_pipe(ns.spec), plan.zero)

    def pp_shard_norm(tree):
        """Global grad norm, per-leaf: psum over data for ZeRO-scattered
        leaves, psum over pipe for pipe-sharded (per-stage layer) leaves;
        pipe-replicated leaves contribute once (identical on every rank)."""
        total = jnp.zeros((), jnp.float32)
        for g, d, hp in zip(
            jax.tree.leaves(tree),
            jax.tree.leaves(zc.sdims),
            jax.tree.leaves(pipe_sharded),
        ):
            s = jnp.sum(jnp.square(g.astype(jnp.float32)))
            if d >= 0:
                s = jax.lax.psum(s, zc.axis)
            if hp:
                s = jax.lax.psum(s, PIPE_AXIS)
            total = total + s
        return jnp.sqrt(total)

    tx_inner = tx_factory(pp_shard_norm) if tx_factory is not None else tx

    def core(state: TrainState, batch: jax.Array, rng: jax.Array):
        step_rng = jax.random.fold_in(rng, state.step)
        # distinct dropout per ZeRO shard; the wavefront folds in pipe rank
        step_rng = jax.random.fold_in(step_rng, zc.dev_index())

        full_params = state.params  # stage 2: stored full along ZeRO axes
        param_shards = zc.slice_local(full_params)

        loss, grads = jax.value_and_grad(
            lambda p: wavefront(p, batch, step_rng)
        )(full_params)
        loss = jax.lax.pmean(loss, zc.axis)
        # pipe-replicated params: sum the per-rank partial grads (see
        # pipe_sharded above) BEFORE the ZeRO reduce-scatter over data
        grads = jax.tree.map(
            lambda g, hp: g if hp else jax.lax.psum(g, PIPE_AXIS),
            grads,
            pipe_sharded,
        )
        grads = zc.reduce_grads(grads)

        grad_norm = pp_shard_norm(grads)
        updates, new_opt = tx_inner.update(grads, state.opt_state, param_shards)
        new_shards = optax.apply_updates(param_shards, updates)
        new_params = zc.gather_full(new_shards)
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "tokens": jnp.asarray(batch.size * zc.zsize, jnp.float32),
        }
        if schedule is not None:
            metrics["learning_rate"] = schedule(state.step)
        return (
            TrainState(step=state.step + 1, params=new_params, opt_state=new_opt),
            metrics,
        )

    def manual_part(spec: P) -> P:
        return restrict_spec(spec, set(manual))

    state_specs = TrainState(
        step=P(),
        params=jax.tree.map(lambda ns: manual_part(ns.spec), plan.state.params),
        opt_state=jax.tree.map(lambda ns: manual_part(ns.spec), plan.state.opt_state),
    )
    batch_spec = manual_part(P(None, *plan.batch.spec))
    metric_specs = {"loss": P(), "grad_norm": P(), "tokens": P()}
    if schedule is not None:
        metric_specs["learning_rate"] = P()

    mapped = shard_map(
        core,
        mesh=mesh,
        in_specs=(state_specs, batch_spec, P()),
        out_specs=(state_specs, metric_specs),
        axis_names=manual,
        check_vma=False,
    )
    return jax.jit(
        mapped,
        in_shardings=(
            plan.state,
            NamedSharding(mesh, batch_spec),
            NamedSharding(mesh, P()),
        ),
        out_shardings=(plan.state, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )
