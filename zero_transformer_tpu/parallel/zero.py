"""ZeRO data-parallel training: one fused jit step on jax.Array shardings.

Replaces the reference's three-computation hot loop — xmap'd grad step, two
identity-pjit reshards, pjit'd optimizer update (reference ``main_zero.py:495-500``,
``src/partitioning/xmap_train_functions.py``) — with a SINGLE compiled step:

- batch sharded over the ``data`` axis → GSPMD lowers the gradient reduction
  to an ICI all-reduce (stage ≤1) or, with the in-scan sharding constraint,
  a reduce-scatter (stage 2), exactly the collective the reference got from
  ``lax.pmean`` inside xmap (``xmap_train_functions.py:83-84``);
- optimizer state lives permanently in its ZeRO NamedSharding (stage ≥1) —
  no replicated→sharded→replicated round trip per step;
- gradient accumulation is a ``lax.scan`` over a leading accum axis
  (reference used ``lax.fori_loop`` + dynamic_index, ``xmap_train_functions.py:62-81``),
  with the accumulator itself ZeRO-sharded at stage ≥2;
- buffers are donated: params/opt-state update in place in HBM.

Stages (cf. SURVEY §2 parallelism checklist):
  0: plain DP (everything replicated)
  1: optimizer state sharded          [reference's ceiling]
  2: + gradients reduce-scattered     [build target]
  3: + parameters stored sharded (FSDP); jit all-gathers weights per step
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import flax.linen as nn
import flax.struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zero_transformer_tpu.parallel import sharding as shd
from zero_transformer_tpu.parallel.mesh import DATA_AXIS


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any


@flax.struct.dataclass
class ShardingPlan:
    """All NamedShardings for one training setup."""

    state: Any = flax.struct.field(pytree_node=False)
    batch: Any = flax.struct.field(pytree_node=False)
    zero: Any = flax.struct.field(pytree_node=False)  # fully-sharded per-param specs
    logical: Any = flax.struct.field(pytree_node=False)  # PartitionSpec of logical names


def make_plan(
    model: nn.Module,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    sample_input_shape: tuple,
    zero_stage: int = 1,
) -> ShardingPlan:
    """Derive every sharding from abstract shapes — no real allocation."""

    def _init(rng):
        return model.init(rng, jnp.zeros(sample_input_shape, jnp.int32))

    boxed = jax.eval_shape(_init, jax.random.PRNGKey(0))["params"]
    logical = shd.logical_specs(boxed)
    abstract_params = shd.unbox(boxed)
    param_specs = shd.param_sharding(mesh, abstract_params, logical, zero_stage)
    zero_specs = shd.zero_sharding(mesh, abstract_params, logical)
    abstract_opt = jax.eval_shape(tx.init, abstract_params)
    opt_specs = shd.opt_state_sharding(
        mesh, abstract_opt, abstract_params, zero_specs if zero_stage >= 1 else param_specs
    )
    state_shardings = TrainState(
        step=NamedSharding(mesh, P()), params=param_specs, opt_state=opt_specs
    )
    return ShardingPlan(
        state=state_shardings,
        batch=shd.batch_sharding(mesh),
        zero=zero_specs,
        logical=logical,
    )


def init_train_state(
    model: nn.Module,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    mesh: Mesh,
    sample_input_shape: tuple,
    plan: ShardingPlan,
) -> TrainState:
    """Initialize params/opt-state directly into their target shardings (each
    device materializes only its shard — a 1.3B f32 init never exists fully
    replicated on any host)."""

    def _init(rng):
        variables = model.init(rng, jnp.zeros(sample_input_shape, jnp.int32))
        params = shd.unbox(variables["params"])
        return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params))

    return jax.jit(_init, out_shardings=plan.state)(rng)


def make_train_step(
    model: nn.Module,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    plan: ShardingPlan,
    zero_stage: int = 1,
    schedule: Optional[Callable] = None,
) -> Callable:
    """Build the fused jitted train step.

    Step signature: ``(state, batch, rng) -> (state, metrics)`` where
    ``batch`` is int32 [accum_steps, global_batch, seq_len] (accum may be 1).
    """

    def loss_fn(params, micro, rng):
        _, loss = model.apply(
            {"params": params}, micro, labels=micro, train=True, rngs={"dropout": rng}
        )
        return loss

    grad_fn = jax.value_and_grad(loss_fn)

    def constrain_zero(tree):
        return jax.lax.with_sharding_constraint(tree, plan.zero)

    def train_step(state: TrainState, batch: jax.Array, rng: jax.Array):
        accum = batch.shape[0]
        step_rng = jax.random.fold_in(rng, state.step)

        def micro_grads(i):
            mrng = jax.random.fold_in(step_rng, i)
            loss, grads = grad_fn(state.params, batch[i], mrng)
            if zero_stage >= 2:
                # reduce-scatter instead of all-reduce; sharded accumulator
                grads = constrain_zero(grads)
            return loss, grads

        if accum == 1:
            loss, grads = micro_grads(0)
        else:

            def body(carry, i):
                loss_sum, grads_sum = carry
                loss, grads = micro_grads(i)
                grads_sum = jax.tree.map(jnp.add, grads_sum, grads)
                return (loss_sum + loss, grads_sum), None

            zero_grads = jax.tree.map(
                lambda p: jnp.zeros(p.shape, jnp.float32), state.params
            )
            if zero_stage >= 2:
                zero_grads = constrain_zero(zero_grads)
            (loss, grads), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), zero_grads), jnp.arange(accum)
            )
            loss = loss / accum
            grads = jax.tree.map(lambda g: g / accum, grads)

        grad_norm = optax.global_norm(grads)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        if zero_stage >= 1:
            # ZeRO: optimizer math runs sharded; the all-gather happens once,
            # on the updates, at apply time (stage<3) or never (stage 3).
            updates = constrain_zero(updates)
        new_params = optax.apply_updates(state.params, updates)
        new_params = jax.lax.with_sharding_constraint(new_params, plan.state.params)
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "tokens": jnp.asarray(batch.size, jnp.float32),
        }
        if schedule is not None:
            metrics["learning_rate"] = schedule(state.step)
        new_state = TrainState(step=state.step + 1, params=new_params, opt_state=new_opt)
        return new_state, metrics

    batch_shard = NamedSharding(mesh, P(None, *plan.batch.spec))
    return jax.jit(
        train_step,
        in_shardings=(plan.state, batch_shard, NamedSharding(mesh, P())),
        out_shardings=(plan.state, NamedSharding(mesh, P())),
        donate_argnums=(0,),
    )


def make_eval_step(model: nn.Module, mesh: Mesh, plan: ShardingPlan) -> Callable:
    """Jitted eval: mean next-token loss over a [batch, seq] batch
    (reference ``xmap_train_functions.py:94-107``)."""

    def eval_step(params, batch):
        _, loss = model.apply({"params": params}, batch, labels=batch)
        return loss

    return jax.jit(
        eval_step,
        in_shardings=(plan.state.params, plan.batch),
        out_shardings=NamedSharding(mesh, P()),
    )
