"""ZeRO data-parallel training: one fused jit step on jax.Array shardings.

Replaces the reference's three-computation hot loop — xmap'd grad step, two
identity-pjit reshards, pjit'd optimizer update (reference ``main_zero.py:495-500``,
``src/partitioning/xmap_train_functions.py``) — with a SINGLE compiled step:

- batch sharded over the ``data`` axis → GSPMD lowers the gradient reduction
  to an ICI all-reduce (stage ≤1) or, with the in-scan sharding constraint,
  a reduce-scatter (stage 2), exactly the collective the reference got from
  ``lax.pmean`` inside xmap (``xmap_train_functions.py:83-84``);
- optimizer state lives permanently in its ZeRO NamedSharding (stage ≥1) —
  no replicated→sharded→replicated round trip per step;
- gradient accumulation is a ``lax.scan`` over a leading accum axis
  (reference used ``lax.fori_loop`` + dynamic_index, ``xmap_train_functions.py:62-81``),
  with the accumulator itself ZeRO-sharded at stage ≥2;
- buffers are donated: params/opt-state update in place in HBM.

Stages (cf. SURVEY §2 parallelism checklist):
  0: plain DP (everything replicated)
  1: optimizer state sharded          [reference's ceiling]
  2: + gradients reduce-scattered     [build target]
  3: + parameters stored sharded (FSDP); jit all-gathers weights per step
"""
from __future__ import annotations

import functools
import math
import os
from typing import Any, Callable, Optional

import flax.linen as nn
import flax.struct
import jax
import jax.numpy as jnp
import optax
from zero_transformer_tpu.utils.jax_compat import shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zero_transformer_tpu.parallel import sharding as shd
from zero_transformer_tpu.parallel.mesh import (
    SEQUENCE_AXIS,
    TENSOR_AXIS,
    zero_axes,
)


@flax.struct.dataclass
class TrainState:
    step: jax.Array
    params: Any
    opt_state: Any


@flax.struct.dataclass
class ShardingPlan:
    """All NamedShardings for one training setup."""

    state: Any = flax.struct.field(pytree_node=False)
    batch: Any = flax.struct.field(pytree_node=False)
    zero: Any = flax.struct.field(pytree_node=False)  # fully-sharded per-param specs
    logical: Any = flax.struct.field(pytree_node=False)  # PartitionSpec of logical names


def make_plan(
    model: nn.Module,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    sample_input_shape: tuple,
    zero_stage: int = 1,
    pp_schedule: str = "gpipe",
) -> ShardingPlan:
    """Derive every sharding from abstract shapes — no real allocation.

    ``pp_schedule`` selects the layer-stack storage rule: gpipe/1f1b shard
    the stacked layer dim contiguously over ``pipe``; interleaved stores it
    pipe-replicated (see ``sharding.plan_rules``). Meshes without a pipe
    axis are unaffected by either."""

    def _init(rng):
        return model.init(rng, jnp.zeros(sample_input_shape, jnp.int32))

    rules = shd.plan_rules(pp_schedule)
    boxed = jax.eval_shape(_init, jax.random.PRNGKey(0))["params"]
    logical = shd.logical_specs(boxed)
    abstract_params = shd.unbox(boxed)
    param_specs = shd.param_sharding(
        mesh, abstract_params, logical, zero_stage, rules=rules
    )
    zero_specs = shd.zero_sharding(mesh, abstract_params, logical, rules=rules)
    abstract_opt = jax.eval_shape(tx.init, abstract_params)
    opt_specs = shd.opt_state_sharding(
        mesh, abstract_opt, abstract_params, zero_specs if zero_stage >= 1 else param_specs
    )
    state_shardings = TrainState(
        step=NamedSharding(mesh, P()), params=param_specs, opt_state=opt_specs
    )
    plan = ShardingPlan(
        state=state_shardings,
        batch=shd.batch_sharding(mesh),
        zero=zero_specs,
        logical=logical,
    )
    # machine-check the plan against the mesh BEFORE anything compiles
    # (ROADMAP item 1: specs are checked, never hand-trusted) — a bad rule
    # table or hand-edited spec fails here with a precise message instead
    # of deep inside pjit at first dispatch. Divisibility is strict ONLY on
    # the ZeRO axes: _add_zero_axis skips indivisible dims by construction,
    # so raggedness there means a hand-seeded/corrupted plan. Every other
    # axis may shard unevenly from honest inputs (an imported 50257 vocab
    # over tensor=2, a 3-layer stack over pipe=2) — GSPMD pads those, and
    # components that cannot pad own their refusal (pipeline's "divisible"
    # error in make_train_step).
    from zero_transformer_tpu.analysis import spec_check

    abstract_state = TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32),
        params=abstract_params,
        opt_state=abstract_opt,
    )
    strict = set(zero_axes(mesh))
    spec_check.check_plan(
        plan,
        mesh,
        abstract_state=abstract_state,
        allow_uneven=tuple(a for a in mesh.axis_names if a not in strict),
    )
    return plan


def init_train_state(
    model: nn.Module,
    tx: optax.GradientTransformation,
    rng: jax.Array,
    mesh: Mesh,
    sample_input_shape: tuple,
    plan: ShardingPlan,
) -> TrainState:
    """Initialize params/opt-state directly into their target shardings (each
    device materializes only its shard — a 1.3B f32 init never exists fully
    replicated on any host)."""

    def _init(rng):
        variables = model.init(rng, jnp.zeros(sample_input_shape, jnp.int32))
        params = shd.unbox(variables["params"])
        return TrainState(step=jnp.zeros((), jnp.int32), params=params, opt_state=tx.init(params))

    return jax.jit(_init, out_shardings=plan.state)(rng)


def _accum_dtype(name: str):
    dt = jnp.dtype(name)
    if dt not in (jnp.dtype(jnp.float32), jnp.dtype(jnp.bfloat16)):
        raise ValueError(
            f"grad_accum_dtype must be float32 or bfloat16, got {name!r}"
        )
    return dt


def _accum_add(a, g):
    """Accumulate micro-step gradient ``g`` into buffer ``a``: add in the
    promoted dtype, round once into the accumulator dtype — a bfloat16
    accumulator rounds once per micro-step instead of once per operand, and
    an f32 accumulator is never downcast even when grads are low-precision
    (``model.param_dtype=bfloat16`` makes grads bf16; ``jnp.add`` promoted
    them before this helper existed and so does this)."""
    ct = jnp.promote_types(a.dtype, g.dtype)
    return (a.astype(ct) + g.astype(ct)).astype(a.dtype)


def _with_ambient_mesh(jitted, mesh: Mesh):
    """Run calls AND lowering of a jitted step under ``jax.set_mesh(mesh)``.

    The model's ``constrain_activation`` calls resolve logical PartitionSpecs
    against the ambient abstract mesh at TRACE time — which happens inside
    the first call (or an explicit ``.lower``), not at ``jax.jit`` wrap time.
    ``.lower`` is preserved because the HLO regression tests use it."""
    import functools

    from zero_transformer_tpu.utils.jax_compat import set_mesh

    @functools.wraps(jitted)
    def call(*args, **kwargs):
        with set_mesh(mesh):
            return jitted(*args, **kwargs)

    def lower(*args, **kwargs):
        with set_mesh(mesh):
            return jitted.lower(*args, **kwargs)

    call.lower = lower
    return call


def make_train_step(
    model: nn.Module,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    plan: ShardingPlan,
    zero_stage: int = 1,
    schedule: Optional[Callable] = None,
    tx_factory: Optional[Callable] = None,
    pp_schedule: str = "gpipe",
    grad_accum_dtype: str = "float32",
    pp_interleave: int = 1,
    overlap_comm: bool = False,
) -> Callable:
    """Build the fused jitted train step.

    Step signature: ``(state, batch, rng) -> (state, metrics)`` where
    ``batch`` is int32 [accum_steps, global_batch, seq_len] (accum may be 1).

    At stage >= 2 (any tensor-parallel degree, sequence = 1) the step is built
    around an EXPLICIT shard_map collective core — ``psum_scatter`` gradient
    reduce-scatter, sharded optimizer math, ``all_gather`` of updated params —
    so ZeRO-2/3 semantics are guaranteed by construction (and testable in the
    compiled HLO) rather than hoped for from GSPMD's all-reduce→reduce-scatter
    rewrite. The core is PARTIAL-MANUAL: only the ZeRO axes (data/fsdp) are
    manual shard_map axes; the tensor axis stays auto, so GSPMD still
    partitions the model math (Megatron TP) inside the body while the ZeRO
    collective schedule is hand-placed. (Verified need: at tensor=2 the
    constraint-hint path compiles to 0 reduce-scatters and 76 all-reduces —
    GSPMD legally satisfies the hints with all-reduce + slice.)
    ``tx_factory(global_norm_fn)`` rebuilds the optimizer with a shard-aware
    grad-clip norm for that core (see ``make_optimizer``); without it the
    core pre-clips using the provided ``tx`` (see
    ``_make_explicit_zero_step``). The sequence (context-parallel) axis
    composes: the ring/Ulysses engines nest their shard_maps inside the
    partial-manual core (``ops.ring_attention._engine_ctx`` — before round
    5 these meshes fell back to the GSPMD hint path, which compiled ZeRO-2
    to stage-1 traffic: zero reduce-scatters, weight-sized all-reduces).
    An active ``pipe`` axis routes to the GPipe wavefront step
    (``parallel.pipeline``).
    """
    from zero_transformer_tpu.parallel.mesh import PIPE_AXIS

    acc_dt = _accum_dtype(grad_accum_dtype)
    if mesh.shape[PIPE_AXIS] > 1:
        from zero_transformer_tpu.parallel.pipeline import make_pp_train_step

        if overlap_comm:
            raise ValueError(
                "overlap_comm does not apply to pipe meshes: the pipeline "
                "engine owns its own collective schedule (pp_schedule)"
            )
        # 1F1B accepts bfloat16 (its accumulator is a hand-placed scan
        # carry); GPipe rejects it there (accumulation lives in scan-VJP)
        return make_pp_train_step(
            model, tx, mesh, plan, zero_stage, schedule, tx_factory,
            pp_schedule=pp_schedule, grad_accum_dtype=grad_accum_dtype,
            pp_interleave=pp_interleave,
        )
    # sequence x tensor x explicit-core: XLA's SPMD partitioner CHECK-fails
    # (spmd_partitioner_util.cc:495 — the same upstream crash class as
    # pipe x tensor) partitioning the auto tensor axis around the nested CP
    # engine; those meshes keep the GSPMD constraint-hint path below.
    # ZTPU_SEQ_TENSOR_EXPLICIT_PROBE=1 re-probes on future jax upgrades
    # (subprocess only: the failure is a CHECK abort, not an exception).
    seq_tensor = (
        mesh.shape[SEQUENCE_AXIS] > 1 and mesh.shape[TENSOR_AXIS] > 1
        and os.environ.get("ZTPU_SEQ_TENSOR_EXPLICIT_PROBE") != "1"
    )
    if overlap_comm:
        from zero_transformer_tpu.parallel.overlap import make_overlap_zero_step

        if zero_stage < 1:
            raise ValueError(
                "overlap_comm requires zero_stage >= 1 (stage 0 has no ZeRO "
                "collective schedule to overlap)"
            )
        if seq_tensor:
            raise NotImplementedError(
                "overlap_comm on sequence x tensor meshes: those meshes "
                "cannot run an explicit shard_map core on this XLA (see the "
                "seq_tensor probe above) — drop overlap_comm or one axis"
            )
        return make_overlap_zero_step(
            model, tx, mesh, plan, zero_stage, schedule, tx_factory,
            grad_accum_dtype=grad_accum_dtype,
        )
    if zero_stage >= 2 and not seq_tensor:
        return _make_explicit_zero_step(
            model, tx, mesh, plan, zero_stage, schedule, tx_factory,
            grad_accum_dtype=grad_accum_dtype,
        )

    def loss_fn(params, micro, rng):
        _, loss = model.apply(
            {"params": params}, micro, labels=micro, train=True, rngs={"dropout": rng}
        )
        return loss

    grad_fn = jax.value_and_grad(loss_fn)

    def constrain_zero(tree):
        return jax.lax.with_sharding_constraint(tree, plan.zero)

    def train_step(state: TrainState, batch: jax.Array, rng: jax.Array):
        accum = batch.shape[0]
        step_rng = jax.random.fold_in(rng, state.step)

        def micro_grads(i):
            mrng = jax.random.fold_in(step_rng, i)
            loss, grads = grad_fn(state.params, batch[i], mrng)
            if zero_stage >= 2:
                # reduce-scatter instead of all-reduce; sharded accumulator
                grads = constrain_zero(grads)
            return loss, grads

        if accum == 1:
            loss, grads = micro_grads(0)
        else:

            def body(carry, i):
                loss_sum, grads_sum = carry
                loss, grads = micro_grads(i)
                grads_sum = jax.tree.map(_accum_add, grads_sum, grads)
                return (loss_sum + loss, grads_sum), None

            zero_grads = jax.tree.map(
                lambda p: jnp.zeros(p.shape, acc_dt), state.params
            )
            if zero_stage >= 2:
                zero_grads = constrain_zero(zero_grads)
            (loss, grads), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), zero_grads), jnp.arange(accum)
            )
            loss = loss / accum
            grads = jax.tree.map(
                lambda g: g.astype(jnp.float32) / accum, grads
            )

        grad_norm = optax.global_norm(grads)
        updates, new_opt = tx.update(grads, state.opt_state, state.params)
        if zero_stage >= 1:
            # ZeRO: optimizer math runs sharded; the all-gather happens once,
            # on the updates, at apply time (stage<3) or never (stage 3).
            updates = constrain_zero(updates)
        new_params = optax.apply_updates(state.params, updates)
        new_params = jax.lax.with_sharding_constraint(new_params, plan.state.params)
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "tokens": jnp.asarray(batch.size, jnp.float32),
        }
        if schedule is not None:
            metrics["learning_rate"] = schedule(state.step)
        new_state = TrainState(step=state.step + 1, params=new_params, opt_state=new_opt)
        return new_state, metrics

    batch_shard = NamedSharding(mesh, P(None, *plan.batch.spec))
    return _with_ambient_mesh(
        jax.jit(
            train_step,
            in_shardings=(plan.state, batch_shard, NamedSharding(mesh, P())),
            out_shardings=(plan.state, NamedSharding(mesh, P())),
            donate_argnums=(0,),
        ),
        mesh,
    )


def _zero_scatter_dim(spec: P, zaxes: tuple) -> int:
    """Index of the dim a ZeRO spec shards over the zero axes (-1: none).
    Mirrors ``sharding._add_zero_axis``'s entry encoding (axis name, or the
    axis tuple when the shard spans data+fsdp)."""
    entry = zaxes if len(zaxes) > 1 else zaxes[0]
    for i, e in enumerate(spec):
        if e == entry:
            return i
    return -1


def apply_tx_factory(tx_factory, norm_fn, zc):
    """Call ``tx_factory(norm_fn[, zc])``. The optional second argument hands
    the manual core's ``ZeroCollectives`` to optimizers that need shard-aware
    transforms beyond the clip norm (sharded adafactor); single-argument
    factories (the original contract) keep working unchanged."""
    import inspect

    try:
        n_pos = sum(
            1
            for p in inspect.signature(tx_factory).parameters.values()
            if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)
        )
    except (TypeError, ValueError):
        n_pos = 1
    return tx_factory(norm_fn, zc) if n_pos >= 2 else tx_factory(norm_fn)


class ZeroCollectives:
    """The hand-placed ZeRO collective schedule, reusable by any partial-
    manual core whose manual axes include the ZeRO (data/fsdp) axes — the
    explicit stage-2/3 step below AND the pipeline engine's stage-2 path
    (``parallel.pipeline``). All methods are trace-time helpers meant to be
    called INSIDE a shard_map body."""

    def __init__(self, mesh: Mesh, plan: ShardingPlan):
        self.zaxes = zero_axes(mesh)
        self.axis = self.zaxes if len(self.zaxes) > 1 else self.zaxes[0]
        self.zsize = math.prod(mesh.shape[a] for a in self.zaxes)
        self.mesh = mesh
        # -1 sentinel (None would vanish as an empty pytree)
        self.sdims = jax.tree.map(
            lambda ns: _zero_scatter_dim(ns.spec, self.zaxes), plan.zero
        )

    def dev_index(self):
        idx = jax.lax.axis_index(self.zaxes[0])
        for a in self.zaxes[1:]:
            idx = idx * self.mesh.shape[a] + jax.lax.axis_index(a)
        return idx

    def shard_norm(self, tree):
        """True global grad norm from shard-local pieces."""
        sq_scattered = jnp.zeros((), jnp.float32)
        sq_replicated = jnp.zeros((), jnp.float32)
        for g, d in zip(jax.tree.leaves(tree), jax.tree.leaves(self.sdims)):
            s = jnp.sum(jnp.square(g.astype(jnp.float32)))
            if d < 0:
                sq_replicated = sq_replicated + s
            else:
                sq_scattered = sq_scattered + s
        return jnp.sqrt(jax.lax.psum(sq_scattered, self.axis) + sq_replicated)

    def reduce_grads(self, grads):
        """Full local grads → ZeRO-sharded mean grads (literal
        reduce-scatter on the ICI ring; psum for indivisible leaves)."""

        def one(g, d):
            if d < 0:
                return jax.lax.psum(g, self.axis)
            return jax.lax.psum_scatter(
                g, self.axis, scatter_dimension=d, tiled=True
            )

        return jax.tree.map(
            lambda g: g / self.zsize, jax.tree.map(one, grads, self.sdims)
        )

    def gather_full(self, shards):
        def one(p, d):
            if d < 0:
                return p
            return jax.lax.all_gather(p, self.axis, axis=d, tiled=True)

        return jax.tree.map(one, shards, self.sdims)

    def slice_local(self, full):
        def one(p, d):
            if d < 0:
                return p
            size = p.shape[d] // self.zsize
            return jax.lax.dynamic_slice_in_dim(
                p, self.dev_index() * size, size, axis=d
            )

        return jax.tree.map(one, full, self.sdims)


def _make_explicit_zero_step(
    model: nn.Module,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    plan: ShardingPlan,
    zero_stage: int,
    schedule: Optional[Callable],
    tx_factory: Optional[Callable],
    grad_accum_dtype: str = "float32",
) -> Callable:
    """ZeRO-2/3 train step with hand-placed collectives under shard_map.

    Per microbatch: local grads → ``psum_scatter`` (a literal reduce-scatter
    on the ICI ring) → sharded accumulator. The optimizer update then runs on
    1/N-size shards, and the updated params are ``all_gather``ed back whole
    (stage 2) or stay sharded (stage 3, where the forward all-gathers them
    per step instead — FSDP). This is the collective schedule ZeRO-2 *means*;
    the GSPMD path merely hints it with sharding constraints, which XLA may
    legally satisfy with all-reduce + slice (VERDICT r1 weak #4). The
    reference never got past stage 1 (its grads leave the step fully
    replicated, ``xmap_train_functions.py:83-84``).

    Grad-clip: the true global norm needs a psum across the ZeRO axis
    (optax's clip would see one device's shards). ``tx_factory`` rebuilds the
    optimizer with that norm; without it the provided ``tx`` is used as-is
    and its clip under-measures large-grad steps (documented fallback for
    direct ``make_train_step`` callers that don't clip or don't care).
    """
    zc = ZeroCollectives(mesh, plan)
    zaxes, axis = zc.zaxes, zc.axis
    acc_dt = _accum_dtype(grad_accum_dtype)

    tx_inner = (
        apply_tx_factory(tx_factory, zc.shard_norm, zc)
        if tx_factory is not None
        else tx
    )

    def loss_fn(params, micro, rng):
        _, loss = model.apply(
            {"params": params}, micro, labels=micro, train=True, rngs={"dropout": rng}
        )
        return loss

    grad_fn = jax.value_and_grad(loss_fn)

    def core(state: TrainState, batch: jax.Array, rng: jax.Array):
        accum = batch.shape[0]
        step_rng = jax.random.fold_in(rng, state.step)
        # distinct dropout masks per DP shard (pmap-era fold-in semantics)
        step_rng = jax.random.fold_in(step_rng, zc.dev_index())

        if zero_stage >= 3:
            param_shards = state.params
            full_params = zc.gather_full(param_shards)  # FSDP per-step all-gather
        else:
            full_params = state.params
            param_shards = zc.slice_local(full_params)

        def micro(i):
            mrng = jax.random.fold_in(step_rng, i)
            loss, grads = grad_fn(full_params, batch[i], mrng)
            return jax.lax.pmean(loss, axis), zc.reduce_grads(grads)

        if accum == 1:
            loss, grads = micro(0)
        else:

            def body(carry, i):
                loss_sum, grads_sum = carry
                loss, grads = micro(i)
                return (loss_sum + loss, jax.tree.map(_accum_add, grads_sum, grads)), None

            zero_grads = jax.tree.map(
                lambda p: jnp.zeros(p.shape, acc_dt), param_shards
            )
            (loss, grads), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), zero_grads), jnp.arange(accum)
            )
            loss = loss / accum
            grads = jax.tree.map(
                lambda g: g.astype(jnp.float32) / accum, grads
            )

        grad_norm = zc.shard_norm(grads)
        updates, new_opt = tx_inner.update(grads, state.opt_state, param_shards)
        new_shards = optax.apply_updates(param_shards, updates)
        new_params = new_shards if zero_stage >= 3 else zc.gather_full(new_shards)
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "tokens": jnp.asarray(batch.size * zc.zsize, jnp.float32),
        }
        if schedule is not None:
            metrics["learning_rate"] = schedule(state.step)
        new_state = TrainState(
            step=state.step + 1, params=new_params, opt_state=new_opt
        )
        return new_state, metrics

    zset = set(zaxes)

    def manual_part(spec: P) -> P:
        # tensor/expert axes stay auto (GSPMD) under the partial-manual
        # shard_map; specs handed to it may only mention the ZeRO axes
        return shd.restrict_spec(spec, zset)

    state_specs = TrainState(
        step=P(),
        params=jax.tree.map(lambda ns: manual_part(ns.spec), plan.state.params),
        opt_state=jax.tree.map(
            lambda ns: manual_part(ns.spec), plan.state.opt_state
        ),
    )
    batch_spec = manual_part(P(None, *plan.batch.spec))
    metric_specs = {"loss": P(), "grad_norm": P(), "tokens": P()}
    if schedule is not None:
        metric_specs["learning_rate"] = P()

    mapped = shard_map(
        core,
        mesh=mesh,
        in_specs=(state_specs, batch_spec, P()),
        out_specs=(state_specs, metric_specs),
        axis_names=frozenset(zaxes),
        check_vma=False,
    )
    return _with_ambient_mesh(
        jax.jit(
            mapped,
            in_shardings=(plan.state, NamedSharding(mesh, batch_spec), NamedSharding(mesh, P())),
            out_shardings=(plan.state, NamedSharding(mesh, P())),
            donate_argnums=(0,),
        ),
        mesh,
    )


def make_replica_audit(mesh: Mesh, plan: ShardingPlan) -> Optional[Callable]:
    """Trace-time cross-replica agreement check over the ZeRO (data/fsdp)
    axes: ``audit(state) -> bool`` True when any DP replica's copy of the
    REPLICATED state leaves disagrees bit-for-bit with the others.

    Silent data corruption that desyncs one replica is invisible to GSPMD —
    XLA *assumes* replicated operands are identical, so a flipped bit on one
    device quietly forks that replica's trajectory until the loss curves
    split (arXiv:2004.13336's cross-replica sharding makes the redundant
    copies explicit; this is the cheap agreement check that redundancy
    affords). Mechanics: a ``shard_map`` over the zero axes lets each device
    checksum ITS OWN physical copy (``detect.leaf_checksum`` — exact uint32
    bit-sums, so healthy replicas agree exactly); a scalar ``all_gather``
    compares them. Only leaves replicated over the zero axes participate —
    ZeRO-sharded leaves have no redundant copy to compare (at stage >= 1
    that is the optimizer state, at stage 3 also the params; the audit then
    covers whatever replication remains, params at stage <= 2 being the
    expensive tree that matters). Cost: one bandwidth-bound read of the
    replicated leaves + one scalar all-gather — run every
    ``audit_frequency`` steps under ``lax.cond``, riding the anomaly-guard
    carry with NO extra host sync.

    Returns None when the mesh has no ZeRO-axis redundancy to audit
    (zero world of 1)."""
    zaxes = zero_axes(mesh)
    zsize = math.prod(mesh.shape[a] for a in zaxes)
    if zsize <= 1:
        return None
    zset = set(zaxes)
    specs = TrainState(
        step=P(),
        params=jax.tree.map(
            lambda ns: shd.restrict_spec(ns.spec, zset), plan.state.params
        ),
        opt_state=jax.tree.map(
            lambda ns: shd.restrict_spec(ns.spec, zset), plan.state.opt_state
        ),
    )

    def core(state: TrainState):
        from zero_transformer_tpu.resilience.detect import leaf_checksum

        total = jnp.zeros((), jnp.uint32)
        for leaf, spec in zip(jax.tree.leaves(state), jax.tree.leaves(specs)):
            if any(e is not None for e in spec):
                continue  # ZeRO-sharded: no redundant copy to compare
            total = total + leaf_checksum(leaf)
        gathered = jax.lax.all_gather(total, zaxes if len(zaxes) > 1 else zaxes[0])
        return (gathered.reshape(-1) != gathered.reshape(-1)[0]).any()

    return shard_map(
        core,
        mesh=mesh,
        in_specs=(specs,),
        out_specs=P(),
        axis_names=frozenset(zaxes),
        check_vma=False,
    )


def make_eval_step(model: nn.Module, mesh: Mesh, plan: ShardingPlan) -> Callable:
    """Jitted eval: mean next-token loss over a [batch, seq] batch
    (reference ``xmap_train_functions.py:94-107``)."""

    def eval_step(params, batch):
        _, loss = model.apply({"params": params}, batch, labels=batch)
        return loss

    return _with_ambient_mesh(
        jax.jit(
            eval_step,
            in_shardings=(plan.state.params, plan.batch),
            out_shardings=NamedSharding(mesh, P()),
        ),
        mesh,
    )
