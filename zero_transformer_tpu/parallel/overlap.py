"""Overlapped ZeRO communication: layer-granular collectives inside the scan.

The serial explicit core (``zero._make_explicit_zero_step``) brackets the
whole step with communication: one monolithic ``all_gather`` of every
parameter before the first forward flop (stage 3), backward to completion,
then one ``psum_scatter`` sweep over all gradients. Nothing overlaps —
the collectives sit squarely on the critical path (arXiv:2004.13336's
weight-update sharding and 2412.14374's async pipelines both exist to
remove exactly this exposed time).

This module rebuilds the step around **communication buckets derived from
the ShardingPlan** (``derive_buckets`` — never hand-listed):

- every parameter whose logical spec leads with ``"layers"`` (the stacked
  ``nn.scan`` block weights) forms one bucket PER LAYER, sliced along the
  stacked dim;
- everything else (wte, ln_f, lm_head, wpe) is the small ``dense`` bucket.

The forward is the same math as ``model.apply`` — the same ``Block`` /
``nn.Embed`` / norm modules applied piecewise, pinned bitwise in
``tests/test_overlap.py`` — but the layer loop is an explicit ``lax.scan``
whose body gathers ITS OWN layer's shard:

- forward: iteration ``l`` issues ``all_gather(bucket_l)`` with no data
  dependency on iteration ``l-1``'s compute, so XLA's latency-hiding
  scheduler / collective pipeliner can prefetch layer ``l+1``'s gather
  behind layer ``l``'s matmuls (the telescoping prefetch through the
  blocks' scan structure);
- backward: the gather's transpose IS ``psum_scatter``, so autodiff places
  one per-layer gradient reduce-scatter in the reverse scan exactly as
  each layer's backward retires — gradients arrive already ZeRO-sharded,
  no post-backward sweep;
- under ``cfg.remat`` the gather sits INSIDE the rematerialized body, so
  the backward re-gathers instead of saving gathered layers (the standard
  FSDP recompute economics; without remat XLA keeps the gathered values as
  residuals, same as the serial step keeps its monolithic gather).

``overlap=False`` builds the identical compute with the old serial
placement (bucket gathers hoisted before the scan, so the program orders
all communication ahead of all compute) — the bit-for-bit A/B arm.
Verified on this backend: overlap-on ≡ overlap-off ≡ the serial explicit
core, bitwise, including the optimizer trajectory.

Stage semantics: state LAYOUT follows the plan exactly as before (stage 1
params replicated / opt sharded, stage 2 + scattered grads, stage 3 params
stored sharded). At stage 1 the overlapped core's gradient traffic is the
reduce-scatter + all-gather pair (numerically the same mean as stage 1's
all-reduce, and no more bytes) — the bucketed-DDP overlap story.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from zero_transformer_tpu.config import resolve_dtype
from zero_transformer_tpu.ops.losses import chunked_next_token_loss, next_token_loss
from zero_transformer_tpu.parallel import sharding as shd
from zero_transformer_tpu.utils.jax_compat import shard_map


@dataclasses.dataclass(frozen=True)
class BucketPlan:
    """Communication buckets derived from a ShardingPlan (not hand-listed).

    ``block_sdims`` / ``dense_sdims`` carry each leaf's ZeRO scatter dim in
    its STORED shape (-1 = replicated over the zero axes, no collective).
    Stacked leaves with scatter dim 0 would be sharded over the layer dim
    itself — a layer's weights then live on one replica, so there is no
    per-layer bucket to overlap; they are gathered up front (``stack_sdims``)
    and ride the scan pre-gathered. ``*_bytes`` are full (gathered) sizes
    for the memory report and the step bench."""

    block_sdims: Any  # per-blocks-leaf scatter dim, -1 replicated/up-front
    stack_sdims: Any  # per-blocks-leaf dim-0 scatter (layer-dim sharded), -1 none
    dense_sdims: Any  # per-dense-leaf scatter dim
    n_layers: int
    n_buckets: int  # layer buckets + 1 dense bucket
    layer_bucket_bytes: int  # one layer's full params
    dense_bucket_bytes: int


def derive_buckets(plan, mesh: Mesh, abstract_params: Any) -> BucketPlan:
    """Split the param tree into layer-granular comm buckets, driven by the
    plan's logical specs (``"layers"``-stacked leaves) and ZeRO scatter
    dims — a model family change reshapes the buckets with no code here."""
    from zero_transformer_tpu.parallel.mesh import zero_axes
    from zero_transformer_tpu.parallel.zero import _zero_scatter_dim

    zaxes = zero_axes(mesh)
    stacked = jax.tree.map(
        lambda spec: len(spec) > 0 and spec[0] == "layers", plan.logical
    )
    sdims = jax.tree.map(
        lambda ns: _zero_scatter_dim(ns.spec, zaxes), plan.zero
    )

    blocks_stacked = stacked.get("blocks")
    if blocks_stacked is None or not all(jax.tree.leaves(blocks_stacked)):
        raise ValueError(
            "overlap_comm requires scan_layers=True (layer buckets are the "
            "stacked nn.scan block params; an unstacked model has none)"
        )
    for key, sub in stacked.items():
        if key != "blocks" and any(jax.tree.leaves(sub)):
            raise ValueError(
                f"layers-stacked params outside the blocks subtree ({key}); "
                f"the bucket derivation does not understand this model"
            )

    block_sdims = jax.tree.map(
        lambda d: d if d > 0 else -1, sdims["blocks"]
    )
    stack_sdims = jax.tree.map(
        lambda d: 0 if d == 0 else -1, sdims["blocks"]
    )
    dense_sdims = {k: v for k, v in sdims.items() if k != "blocks"}

    def _bytes(tree) -> int:
        return sum(
            leaf.size * jnp.dtype(leaf.dtype).itemsize
            for leaf in jax.tree.leaves(tree)
        )

    n_layers = jax.tree.leaves(abstract_params["blocks"])[0].shape[0]
    return BucketPlan(
        block_sdims=block_sdims,
        stack_sdims=stack_sdims,
        dense_sdims=dense_sdims,
        n_layers=int(n_layers),
        n_buckets=int(n_layers) + 1,
        layer_bucket_bytes=_bytes(abstract_params["blocks"]) // int(n_layers),
        dense_bucket_bytes=_bytes(
            {k: v for k, v in abstract_params.items() if k != "blocks"}
        ),
    )


def bucket_summary(plan, mesh: Mesh, abstract_params: Any) -> dict:
    """JSON-able bucket picture for ``trainer.memory_analysis`` and the
    step bench: how many buckets, how big, what a prefetch buffer costs."""
    b = derive_buckets(plan, mesh, abstract_params)
    return {
        "n_layer_buckets": b.n_layers,
        "layer_bucket_bytes": b.layer_bucket_bytes,
        "dense_bucket_bytes": b.dense_bucket_bytes,
        # during overlap, the gather of layer l+1 is in flight while layer
        # l computes: two gathered layer buckets live at once
        "overlap_gather_buffer_bytes": 2 * b.layer_bucket_bytes,
    }


def make_overlap_zero_step(
    model: nn.Module,
    tx: optax.GradientTransformation,
    mesh: Mesh,
    plan,
    zero_stage: int,
    schedule: Optional[Callable] = None,
    tx_factory: Optional[Callable] = None,
    grad_accum_dtype: str = "float32",
    overlap: bool = True,
) -> Callable:
    """Build the bucketed/overlapped ZeRO train step.

    Same contract as ``zero.make_train_step``: ``(state, batch, rng) ->
    (state, metrics)``, ``batch`` int32 [accum, global_batch, seq].
    ``overlap=False`` keeps the identical compute but hoists every bucket
    gather ahead of the layer scan — the serial-placement A/B arm, bitwise
    against both ``overlap=True`` and the legacy serial core.
    """
    from zero_transformer_tpu.models.gpt import (
        Block,
        _norm,
        doc_ids_from_tokens,
        mask_boundary_labels,
        resolve_remat_policy,
    )
    from zero_transformer_tpu.parallel.sharding import (
        constrain_activation,
        replicate_activation,
    )
    from zero_transformer_tpu.parallel.zero import (
        TrainState,
        ZeroCollectives,
        _accum_add,
        _accum_dtype,
        _with_ambient_mesh,
        apply_tx_factory,
    )

    cfg = model.cfg
    if not cfg.scan_layers:
        raise ValueError("overlap_comm requires scan_layers=True")
    if zero_stage < 1:
        raise ValueError("overlap_comm requires zero_stage >= 1")
    acc_dt = _accum_dtype(grad_accum_dtype)
    zc = ZeroCollectives(mesh, plan)
    zaxes, axis = zc.zaxes, zc.axis

    def _init(rng):
        return model.init(rng, jnp.zeros((1, 8), jnp.int32))

    abstract_params = shd.unbox(
        jax.eval_shape(_init, jax.random.PRNGKey(0))["params"]
    )
    buckets = derive_buckets(plan, mesh, abstract_params)

    tx_inner = (
        apply_tx_factory(tx_factory, zc.shard_norm, zc)
        if tx_factory is not None
        else tx
    )

    dtype = resolve_dtype(cfg.compute_dtype)
    param_dtype = resolve_dtype(cfg.param_dtype)
    packed = cfg.doc_sep_token is not None
    L = cfg.n_layers

    embed_mod = nn.Embed(
        num_embeddings=cfg.vocab_size,
        features=cfg.d_model,
        dtype=dtype,
        param_dtype=param_dtype,
    )
    norm_mod = _norm(cfg, dtype, "ln_f")
    wpe_mod = (
        nn.Embed(
            num_embeddings=cfg.max_seq_len,
            features=cfg.d_model,
            dtype=dtype,
            param_dtype=param_dtype,
        )
        if cfg.position == "learned"
        else None
    )
    block = Block(cfg, False, False, None, model.mesh)

    def _gather(x, d):
        if d < 0:
            return x
        return jax.lax.all_gather(x, axis, axis=d, tiled=True)

    def gather_layer(p_layer):
        """One layer bucket: shard slices → full layer params. Scatter dims
        were derived on the STACKED shapes; the scan slice dropped dim 0."""
        return jax.tree.map(
            lambda x, d: _gather(x, d - 1 if d > 0 else -1),
            p_layer, buckets.block_sdims,
        )

    def block_apply(p_layer, carry, mrng):
        return block.apply({"params": p_layer}, carry, rngs={"dropout": mrng})

    if cfg.remat:
        # the gather lives INSIDE the checkpointed region: backward
        # re-gathers the layer instead of saving a full gathered copy —
        # the FSDP recompute trade, same policy knob as the fused model
        block_remat = jax.checkpoint(
            lambda p_layer, carry, mrng: block_apply(
                gather_layer(p_layer), carry, mrng
            ),
            prevent_cse=False,
            policy=resolve_remat_policy(cfg),
        )

    def forward(params, blocks, tokens, mrng):
        """The fused model's forward, with the layer loop as an explicit
        scan over (possibly still-sharded) stacked block params. ``params``
        holds the dense bucket (full); ``blocks`` the stacked block leaves —
        sharded when ``overlap`` (gathered in-body), full otherwise. Bitwise
        against ``Transformer.__call__`` (pinned in tests/test_overlap.py);
        dropout draws differ from the fused path's flax scan rng split
        (same distribution — parity suites run dropout 0)."""
        table = replicate_activation(
            jnp.asarray(params["wte"]["embedding"], dtype)
        )
        h = jnp.take(table, tokens, axis=0)
        h = constrain_activation(h, "batch", "seq", "embed")
        if wpe_mod is not None:
            T = tokens.shape[1]
            if T > cfg.max_seq_len:
                raise ValueError(
                    f"sequence length {T} > max_seq_len {cfg.max_seq_len}: "
                    "learned positions cannot extrapolate"
                )
            h = h + wpe_mod.apply(
                {"params": params["wpe"]}, jnp.arange(T, dtype=jnp.int32)
            )
        if cfg.dropout > 0.0:
            h = nn.Dropout(cfg.dropout, deterministic=False).apply(
                {}, h, rngs={"dropout": jax.random.fold_in(mrng, L)}
            )

        aux = jnp.zeros((), jnp.float32)
        doc_ids = (
            doc_ids_from_tokens(tokens, cfg.doc_sep_token) if packed else None
        )
        carry = (h.astype(dtype), aux, doc_ids) if packed else (h.astype(dtype), aux)

        def body(carry, xs):
            p_layer, idx = xs
            lrng = jax.random.fold_in(mrng, idx)
            if cfg.remat:
                if not overlap:
                    # serial arm: gathers hoisted before the scan; remat
                    # only the block compute (matches the fused model)
                    carry, _ = jax.checkpoint(
                        block_apply, prevent_cse=False,
                        policy=resolve_remat_policy(cfg),
                    )(p_layer, carry, lrng)
                else:
                    carry, _ = block_remat(p_layer, carry, lrng)
            else:
                if overlap:
                    p_layer = gather_layer(p_layer)
                carry, _ = block_apply(p_layer, carry, lrng)
            return carry, None

        carry, _ = jax.lax.scan(
            body, carry, (blocks, jnp.arange(L, dtype=jnp.int32))
        )
        h, aux = carry[0], carry[1]
        h = norm_mod.apply({"params": params["ln_f"]}, h)

        labels = tokens
        ignore = None
        if packed:
            labels = mask_boundary_labels(labels, doc_ids)
            ignore = -1
        if cfg.loss_chunk:
            w_dv = (
                jnp.asarray(params["wte"]["embedding"], dtype).T
                if cfg.tie_embeddings
                else jnp.asarray(params["lm_head"]["kernel"], dtype)
            )
            loss = chunked_next_token_loss(
                h, w_dv, labels, cfg.loss_chunk, ignore_index=ignore
            )
        else:
            if cfg.tie_embeddings:
                logits = embed_mod.apply(
                    {"params": params["wte"]}, h, method="attend"
                )
            else:
                logits = (
                    h.astype(dtype)
                    @ jnp.asarray(params["lm_head"]["kernel"], dtype)
                )
            loss = next_token_loss(logits, labels, ignore_index=ignore)
        if cfg.n_experts > 0:
            loss = loss + aux
        return loss

    # leaves whose grads autodiff cannot reduce (no gather anywhere: not
    # per-layer bucketed, not layer-dim sharded / not ZeRO-scattered dense)
    needs_psum = {
        k: jax.tree.map(lambda d: d < 0, v)
        for k, v in buckets.dense_sdims.items()
    }
    needs_psum["blocks"] = jax.tree.map(
        lambda b, s: b < 0 and s < 0, buckets.block_sdims, buckets.stack_sdims
    )

    def core(state: TrainState, batch: jax.Array, rng: jax.Array):
        accum = batch.shape[0]
        step_rng = jax.random.fold_in(rng, state.step)
        step_rng = jax.random.fold_in(step_rng, zc.dev_index())

        # the step works on the SHARDED view regardless of storage: stage 3
        # stores shards; stage 1/2 store full and slice locally (free)
        param_shards = (
            state.params if zero_stage >= 3 else zc.slice_local(state.params)
        )

        def loss_fn(shards, tokens, mrng):
            dense = {k: v for k, v in shards.items() if k != "blocks"}
            dense_full = jax.tree.map(_gather, dense, buckets.dense_sdims)
            # leaves sharded over the LAYER dim itself have no per-layer
            # bucket — gathered up front either way
            blocks = jax.tree.map(
                _gather, shards["blocks"], buckets.stack_sdims
            )
            if not overlap:
                # serial placement: every bucket gather ahead of the scan
                blocks = jax.tree.map(_gather, blocks, buckets.block_sdims)
            return forward(dense_full, blocks, tokens, mrng)

        def micro(i):
            mrng = jax.random.fold_in(step_rng, i)
            loss, grads = jax.value_and_grad(loss_fn)(
                param_shards, batch[i], mrng
            )
            # the gather transpose psum_scatters SUMS over the zero axis for
            # every bucketed leaf — but leaves with NO scatter dim (nothing
            # divisible by the zero world; stored replicated, _gather a
            # no-op) get no collective from autodiff and must be psum'd
            # explicitly, exactly as the serial core's reduce_grads does for
            # its indivisible leaves. /zsize then makes both the mean.
            grads = jax.tree.map(
                lambda g, r: jax.lax.psum(g, axis) if r else g,
                grads, needs_psum,
            )
            grads = jax.tree.map(lambda g: g / zc.zsize, grads)
            return jax.lax.pmean(loss, axis), grads

        if accum == 1:
            loss, grads = micro(0)
        else:

            def body(carry, i):
                loss_sum, grads_sum = carry
                loss, grads = micro(i)
                return (
                    loss_sum + loss,
                    jax.tree.map(_accum_add, grads_sum, grads),
                ), None

            zero_grads = jax.tree.map(
                lambda p: jnp.zeros(p.shape, acc_dt), param_shards
            )
            (loss, grads), _ = jax.lax.scan(
                body, (jnp.zeros((), jnp.float32), zero_grads),
                jnp.arange(accum),
            )
            loss = loss / accum
            grads = jax.tree.map(lambda g: g.astype(jnp.float32) / accum, grads)

        grad_norm = zc.shard_norm(grads)
        updates, new_opt = tx_inner.update(grads, state.opt_state, param_shards)
        new_shards = optax.apply_updates(param_shards, updates)
        new_params = new_shards if zero_stage >= 3 else zc.gather_full(new_shards)
        metrics = {
            "loss": loss,
            "grad_norm": grad_norm,
            "tokens": jnp.asarray(batch.size * zc.zsize, jnp.float32),
        }
        if schedule is not None:
            metrics["learning_rate"] = schedule(state.step)
        return (
            TrainState(step=state.step + 1, params=new_params, opt_state=new_opt),
            metrics,
        )

    zset = set(zaxes)

    def manual_part(spec: P) -> P:
        return shd.restrict_spec(spec, zset)

    state_specs = TrainState(
        step=P(),
        params=jax.tree.map(lambda ns: manual_part(ns.spec), plan.state.params),
        opt_state=jax.tree.map(
            lambda ns: manual_part(ns.spec), plan.state.opt_state
        ),
    )
    batch_spec = manual_part(P(None, *plan.batch.spec))
    metric_specs = {"loss": P(), "grad_norm": P(), "tokens": P()}
    if schedule is not None:
        metric_specs["learning_rate"] = P()

    mapped = shard_map(
        core,
        mesh=mesh,
        in_specs=(state_specs, batch_spec, P()),
        out_specs=(state_specs, metric_specs),
        axis_names=frozenset(zaxes),
        check_vma=False,
    )
    return _with_ambient_mesh(
        jax.jit(
            mapped,
            in_shardings=(
                plan.state,
                NamedSharding(mesh, batch_spec),
                NamedSharding(mesh, P()),
            ),
            out_shardings=(plan.state, NamedSharding(mesh, P())),
            donate_argnums=(0,),
        ),
        mesh,
    )
