"""Token-row sources.

The reference streams GCS ``.tar.gz`` shards through webdataset into fixed
2048-token rows (reference ``main_zero.py:377-421``). Here a source is anything
iterable over 1-D int token rows of length ``max_context``, with optional
``seek(n)`` fast-forward (O(1) for the in-repo sources — the reference resumed
by *discarding* batches through islice, ``main_zero.py:470-471``) and
``state()/restore()`` for exact dataloader checkpointing.

In-tree sources:
- ``SyntheticSource`` — deterministic pseudo-random rows (tests, benchmarks).
- ``MemmapSource`` — a flat binary token file (np.memmap), the TPU-native
  high-throughput path: zero-copy reads, per-epoch row permutation.
- ``HFSource`` — HuggingFace ``datasets`` streaming (import-gated), for
  parity with the reference's web-scale streaming story without webdataset.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Iterator, Optional

import numpy as np


class TokenSource:
    """Iterable of 1-D int32 arrays of length ``max_context``."""

    max_context: int

    def __iter__(self) -> Iterator[np.ndarray]:
        raise NotImplementedError

    def seek(self, n_rows: int) -> None:
        """Fast-forward so iteration resumes ``n_rows`` in. O(1) when possible."""
        raise NotImplementedError

    def state(self) -> Dict[str, Any]:
        raise NotImplementedError

    def restore(self, state: Dict[str, Any]) -> None:
        raise NotImplementedError


class ReplayStreamSource(TokenSource):
    """Position tracking for sources that can only resume by replaying their
    stream from the head and discarding (webdataset-style tars, HF streaming).

    Subclasses implement ``_samples()`` — an infinite iterator over decoded
    rows from position 0. ``seek`` is O(n) (discard) but exact; repeated
    ``iter()`` calls CONTINUE the stream (replaying past skip + yielded rows)
    rather than restarting it, matching the indexable sources' contract.
    """

    def __init__(self):
        self._skip_rows = 0
        self._yielded = 0

    def _samples(self) -> Iterator[np.ndarray]:
        raise NotImplementedError

    def __iter__(self) -> Iterator[np.ndarray]:
        start = self._skip_rows + self._yielded
        skipped = 0
        for row in self._samples():
            if skipped < start:
                skipped += 1
                continue
            self._yielded += 1
            yield row

    def seek(self, n_rows: int) -> None:
        self._skip_rows += n_rows

    def state(self) -> Dict[str, Any]:
        return {"rows": self._yielded + self._skip_rows}

    def restore(self, state: Dict[str, Any]) -> None:
        self._skip_rows = int(state["rows"])
        self._yielded = 0


@dataclasses.dataclass
class SyntheticSource(TokenSource):
    """Deterministic random tokens; row ``i`` is a pure function of (seed, i)."""

    vocab_size: int
    max_context: int
    seed: int = 0
    _position: int = 0

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            i = self._position
            self._position += 1  # before yield: generator may never be resumed
            yield self._row(i)

    def _row(self, i: int) -> np.ndarray:
        rng = np.random.default_rng(np.random.SeedSequence([self.seed, i]))
        return rng.integers(0, self.vocab_size, self.max_context, dtype=np.int32)

    def seek(self, n_rows: int) -> None:
        self._position += n_rows

    def state(self) -> Dict[str, Any]:
        return {"position": self._position}

    def restore(self, state: Dict[str, Any]) -> None:
        self._position = int(state["position"])


class MemmapSource(TokenSource):
    """Rows from a flat binary token file, shuffled per epoch.

    The file is a contiguous token stream (uint16 for vocab < 65536 —
    GPT-NeoX's 50304 fits — or uint32); it is viewed as
    ``[n_rows, max_context]`` and row order is permuted each epoch with a
    seed derived from (shuffle_seed, epoch), so every process computes the
    same permutation without communication.
    """

    def __init__(
        self,
        path: str,
        max_context: int,
        dtype: str = "uint16",
        shuffle: bool = True,
        seed: int = 23,
    ):
        self.path = path
        self.max_context = max_context
        self.dtype = np.dtype(dtype)
        self.shuffle = shuffle
        self.seed = seed
        tokens = np.memmap(path, dtype=self.dtype, mode="r")
        self.n_rows = len(tokens) // max_context
        if self.n_rows == 0:
            raise ValueError(
                f"{path}: {len(tokens)} tokens < one row of {max_context}"
            )
        self._tokens = tokens[: self.n_rows * max_context].reshape(
            self.n_rows, max_context
        )
        self._epoch = 0
        self._row_in_epoch = 0
        self._perm: Optional[np.ndarray] = None
        self._perm_epoch = -1

    def _permutation(self) -> np.ndarray:
        if self._perm_epoch != self._epoch:
            if self.shuffle:
                rng = np.random.default_rng(
                    np.random.SeedSequence([self.seed, self._epoch])
                )
                self._perm = rng.permutation(self.n_rows)
            else:
                self._perm = np.arange(self.n_rows)
            self._perm_epoch = self._epoch
        return self._perm

    def __iter__(self) -> Iterator[np.ndarray]:
        while True:
            perm = self._permutation()
            idx = perm[self._row_in_epoch]
            row = np.asarray(self._tokens[idx], dtype=np.int32)
            self._row_in_epoch += 1
            if self._row_in_epoch >= self.n_rows:
                self._row_in_epoch = 0
                self._epoch += 1
            yield row

    def seek(self, n_rows: int) -> None:
        total = self._epoch * self.n_rows + self._row_in_epoch + n_rows
        self._epoch, self._row_in_epoch = divmod(total, self.n_rows)

    def state(self) -> Dict[str, Any]:
        return {"epoch": self._epoch, "row_in_epoch": self._row_in_epoch}

    def restore(self, state: Dict[str, Any]) -> None:
        self._epoch = int(state["epoch"])
        self._row_in_epoch = int(state["row_in_epoch"])


class HFSource(ReplayStreamSource):
    """Streaming rows from a HuggingFace dataset of pre-tokenized examples.

    Expects each example to carry ``field`` (default ``input_ids``) holding at
    least ``max_context`` token ids (extra ids are truncated — the reference's
    preprocess did the same, ``main_zero.py:368-373``). Positions are counted
    in YIELDED rows (length-filtered examples don't count), replayed
    deterministically by ``ReplayStreamSource``.
    """

    def __init__(
        self,
        name_or_path: str,
        max_context: int,
        split: str = "train",
        field: str = "input_ids",
        **load_kwargs,
    ):
        import datasets  # gated: heavy import

        super().__init__()
        self.max_context = max_context
        self.field = field
        self._ds = datasets.load_dataset(
            name_or_path, split=split, streaming=True, **load_kwargs
        )

    def _samples(self) -> Iterator[np.ndarray]:
        for ex in iter(self._ds):
            ids = np.asarray(ex[self.field], dtype=np.int32)
            if len(ids) < self.max_context:
                continue  # filtered examples don't count as rows
            yield ids[: self.max_context]


def write_memmap(tokens: np.ndarray, path: str, dtype: str = "uint16") -> str:
    """Write a flat token array as a MemmapSource binary (helper for tooling/tests)."""
    arr = np.asarray(tokens)
    info = np.iinfo(np.dtype(dtype))
    if arr.min() < info.min or arr.max() > info.max:
        raise ValueError(f"token ids out of range for {dtype}")
    arr.astype(dtype).tofile(path)
    return path
