"""Data pipeline: token sources + sharded batch loading (no torch anywhere)."""
from __future__ import annotations

from typing import Optional

from zero_transformer_tpu.config import Config
from zero_transformer_tpu.data.loader import DataLoader, device_put_batch  # noqa: F401
from zero_transformer_tpu.data.sources import (  # noqa: F401
    HFSource,
    MemmapSource,
    SyntheticSource,
    TokenSource,
    write_memmap,
)
from zero_transformer_tpu.data.tarshards import TarShardSource  # noqa: F401


def make_source(
    cfg: Config,
    validation: bool = False,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> TokenSource:
    """Build the TokenSource named by ``cfg.data.source``."""
    import jax

    data = cfg.data
    path = data.validation_path if validation else data.train_path
    if data.source == "synthetic":
        return SyntheticSource(
            vocab_size=cfg.model.vocab_size,
            max_context=data.max_context,
            seed=data.shuffle_seed + (1 if validation else 0),
        )
    if data.source == "memmap":
        return MemmapSource(
            path,
            max_context=data.max_context,
            shuffle=not validation,
            seed=data.shuffle_seed,
        )
    if data.source == "hf":
        return HFSource(path, max_context=data.max_context)
    if data.source == "tar":
        return TarShardSource(
            path,
            max_context=data.max_context,
            seed=data.shuffle_seed,
            shuffle_shards=not validation,
            strict=data.strict,
            process_index=(
                process_index if process_index is not None else jax.process_index()
            ),
            process_count=(
                process_count if process_count is not None else jax.process_count()
            ),
        )
    raise ValueError(f"unknown data source {cfg.data.source!r}")


def make_loader(
    cfg: Config,
    validation: bool = False,
    process_index: Optional[int] = None,
    process_count: Optional[int] = None,
) -> DataLoader:
    source = make_source(cfg, validation, process_index, process_count)
    return DataLoader(
        source,
        batch_size=cfg.training.batch_size,
        train_context=cfg.training.train_context,
        accum_steps=1 if validation else cfg.training.gradient_accumulation_steps,
        process_index=process_index,
        process_count=process_count,
        shuffle_buffer=0 if validation else (
            cfg.data.shuffle_buffer if cfg.data.source == "hf" else 0
        ),
        seed=cfg.data.shuffle_seed,
        # validation stays synchronous: Trainer.evaluate pins the source to a
        # fixed window via state()/restore(), which a read-ahead thread would
        # race; eval is rare and short so overlap buys nothing there
        prefetch=0 if validation else cfg.data.num_workers,
    )
